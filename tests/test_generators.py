"""Generator algebra semantics (single-threaded, driven by hand)."""

from jepsen_tpu.generators.core import (
    Clients,
    Ctx,
    Cycle,
    Delay,
    EachThread,
    FnGen,
    Mix,
    NemesisOnly,
    NemesisRoute,
    Once,
    OpGen,
    Pending,
    Phases,
    Sleep,
    TimeLimit,
)
from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType


def ctx(t=0, thread=0, n=2):
    return Ctx(time=t, thread=thread, process=thread, n_threads=n)


def test_once_emits_exactly_one():
    g = Once(OpGen(OpF.DRAIN))
    assert isinstance(g.next_for(ctx()), Op)
    assert g.next_for(ctx()) is None


def test_time_limit_cuts_off():
    g = TimeLimit(OpGen(OpF.DEQUEUE), 1.0)
    assert isinstance(g.next_for(ctx(t=0)), Op)
    assert g.next_for(ctx(t=int(2e9))) is None


def test_delay_rate_limits_globally():
    g = Delay(OpGen(OpF.DEQUEUE), 0.5)
    assert isinstance(g.next_for(ctx(t=0, thread=0)), Op)
    got = g.next_for(ctx(t=int(0.1e9), thread=1))
    assert isinstance(got, Pending) and got.wake == int(0.5e9)
    assert isinstance(g.next_for(ctx(t=int(0.6e9), thread=1)), Op)


def test_mix_draws_from_all(monkeypatch):
    a = FnGen(lambda c: Op.invoke(OpF.ENQUEUE, c.process, 1))
    b = FnGen(lambda c: Op.invoke(OpF.DEQUEUE, c.process))
    g = Mix([a, b], seed=4)
    fs = {g.next_for(ctx()).f for _ in range(50)}
    assert fs == {OpF.ENQUEUE, OpF.DEQUEUE}


def test_sleep_pends_then_exhausts():
    g = Sleep(1.0)
    got = g.next_for(ctx(t=int(0.5e9)))
    assert isinstance(got, Pending) and got.wake == int(1.5e9)
    assert g.next_for(ctx(t=int(1.6e9))) is None


def test_nemesis_route_waits_for_both_sides():
    g = NemesisOnly(Once(OpGen(OpF.STOP, OpType.INFO)))
    # client asks first: its side (Nothing) exhausts, but nemesis is alive
    got = g.next_for(ctx(thread=0))
    assert isinstance(got, Pending)
    # nemesis emits its op, then the generator is exhausted for everyone
    op = g.next_for(ctx(thread=NEMESIS_PROCESS))
    assert isinstance(op, Op) and op.f == OpF.STOP
    assert g.next_for(ctx(thread=NEMESIS_PROCESS)) is None
    assert g.next_for(ctx(thread=0)) is None


def test_each_thread_waits_for_all_threads():
    g = Clients(EachThread(lambda: Once(OpGen(OpF.DRAIN))))
    assert isinstance(g.next_for(ctx(thread=0, n=2)), Op)
    # thread 0 done, but thread 1 hasn't drained yet
    assert isinstance(g.next_for(ctx(thread=0, n=2)), Pending)
    assert isinstance(g.next_for(ctx(thread=NEMESIS_PROCESS, n=2)), Pending)
    assert isinstance(g.next_for(ctx(thread=1, n=2)), Op)
    assert g.next_for(ctx(thread=1, n=2)) is None
    assert g.next_for(ctx(thread=NEMESIS_PROCESS, n=2)) is None


def test_phases_advance_in_order():
    g = Phases(
        [
            Once(OpGen(OpF.ENQUEUE, value=1)),
            Once(OpGen(OpF.DEQUEUE)),
        ]
    )
    assert g.next_for(ctx()).f == OpF.ENQUEUE
    assert g.next_for(ctx()).f == OpF.DEQUEUE
    assert g.next_for(ctx()) is None


def test_cycle_repeats_factory():
    g = TimeLimit(Cycle(lambda: [Once(OpGen(OpF.START, OpType.INFO))]), 1.0)
    ops = []
    for _ in range(5):
        got = g.next_for(ctx(t=0))
        ops.append(got)
    assert all(isinstance(o, Op) and o.f == OpF.START for o in ops)
    assert g.next_for(ctx(t=int(2e9))) is None
