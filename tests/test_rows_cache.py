"""Packed-row store cache (history/rows.py, VERDICT r3 #3).

Row explosion is ~95% of replay wall clock and is a pure function of the
history file, so it is persisted as a hash-guarded ``rows.npz``.  These
tests pin the cache contract: identical matrices through hit and miss,
staleness on rewrite, record-time creation, and CLI parity.
"""

from __future__ import annotations

import numpy as np

from jepsen_tpu.history.ops import workload_of
from jepsen_tpu.history.rows import (
    _rows_for,
    cache_path_for,
    load_rows_cache,
    rows_with_cache,
    save_rows_cache,
)
from jepsen_tpu.history.store import Store, write_history_jsonl
from jepsen_tpu.history.synth import SynthSpec, synth_batch


def _write_history(tmp_path, n_ops=40, seed=0):
    h = synth_batch(1, SynthSpec(n_ops=n_ops, seed=seed))[0].ops
    p = tmp_path / "history.jsonl"
    write_history_jsonl(p, h)
    return p, h


def test_roundtrip_bitwise_identical(tmp_path):
    p, h = _write_history(tmp_path)
    rows = _rows_for(h)
    save_rows_cache(p, "queue", rows)
    got = load_rows_cache(p)
    assert got is not None
    workload, cached = got
    assert workload == "queue"
    assert cached.dtype == np.int32
    np.testing.assert_array_equal(cached, rows)


def test_stale_on_history_rewrite(tmp_path):
    p, h = _write_history(tmp_path)
    save_rows_cache(p, "queue", _rows_for(h))
    assert load_rows_cache(p) is not None
    # rewrite the history: the cache must be refused, not served stale
    h2 = synth_batch(1, SynthSpec(n_ops=44, seed=9))[0].ops
    write_history_jsonl(p, h2)
    assert load_rows_cache(p) is None


def test_missing_cache_is_none(tmp_path):
    p, _h = _write_history(tmp_path)
    assert load_rows_cache(p) is None


def test_corrupt_cache_is_none(tmp_path):
    p, h = _write_history(tmp_path)
    cache_path_for(p).write_bytes(b"not an npz")
    assert load_rows_cache(p) is None
    # and the load-through path recovers by re-exploding
    workload, rows, hit = rows_with_cache(p)
    assert not hit and workload == "queue" and rows.shape[1] == 8


def test_load_through_miss_then_hit(tmp_path):
    p, h = _write_history(tmp_path)
    w1, r1, hit1 = rows_with_cache(p)
    assert not hit1
    w2, r2, hit2 = rows_with_cache(p)
    assert hit2
    assert w1 == w2 == workload_of(h)
    np.testing.assert_array_equal(r1, r2)
    np.testing.assert_array_equal(r1, _rows_for(h))


def test_store_save_history_cuts_cache(tmp_path):
    store = Store(tmp_path)
    h = synth_batch(1, SynthSpec(n_ops=30))[0].ops
    d = store.run_dir("t")
    p = store.save_history(d, h)
    got = load_rows_cache(p)
    assert got is not None
    workload, rows = got
    assert workload == "queue"
    np.testing.assert_array_equal(rows, _rows_for(h))


def test_cli_bench_check_uses_cache(tmp_path, capsys):
    """End-to-end: synth a store, bench-check twice — the second run
    reports cache hits and produces the same invalid count."""
    from jepsen_tpu.cli.main import main

    rc = main(
        ["synth", "--count", "3", "--ops", "40", "--lost", "1",
         "--store", str(tmp_path / "s")]
    )
    assert rc == 0
    capsys.readouterr()

    args = ["bench-check", "--histories", str(tmp_path / "s")]
    assert main(args) == 0
    first = capsys.readouterr()
    # drop the store-level cache so this exercises the PER-FILE layer
    # (TestStoreCache covers the store-level hit separately)
    from jepsen_tpu.history.storecache import STORE_CACHE

    (tmp_path / "s" / STORE_CACHE).unlink()
    assert main(args) == 0
    second = capsys.readouterr()
    assert "(3 from the packed-row cache, 0 native-packed)" in second.err
    # identical verdict either way (timings differ, the counts must not)
    import json

    v1 = json.loads(first.out.strip().splitlines()[-1])
    v2 = json.loads(second.out.strip().splitlines()[-1])
    assert (v1["invalid"], v1["histories"]) == (
        v2["invalid"], v2["histories"],
    )


# ---------------------------------------------------------------------------
# Elle micro-op cell cache (history/storecache.py) — the packed substrate
# of the device-side edge inference, digest-keyed like rows.npz
# ---------------------------------------------------------------------------


class TestElleMopsCache:
    def _write_elle(self, tmp_path, seed=0, **kw):
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        sh = synth_elle_batch(
            1, ElleSynthSpec(n_txns=24, seed=seed), **kw
        )[0]
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, sh.ops)
        return p, sh.ops

    def test_roundtrip_bitwise_identical(self, tmp_path):
        from jepsen_tpu.checkers.elle import elle_mops_for
        from jepsen_tpu.history.storecache import (
            load_elle_mops_cache,
            save_elle_mops_cache,
        )

        p, h = self._write_elle(tmp_path, g1a=1)
        mat, meta = elle_mops_for(h)
        save_elle_mops_cache(p, mat, meta)
        got = load_elle_mops_cache(p)
        assert got is not None
        cmat, cmeta = got
        np.testing.assert_array_equal(cmat, mat)
        assert (cmeta.n_txns, cmeta.txn_index, cmeta.keys,
                cmeta.degenerate) == (
            meta.n_txns, meta.txn_index, meta.keys, meta.degenerate
        )

    def test_load_through_miss_then_hit(self, tmp_path):
        from jepsen_tpu.history.storecache import elle_mops_with_cache

        p, _h = self._write_elle(tmp_path)
        mat1, meta1, hit1 = elle_mops_with_cache(p)
        assert not hit1
        mat2, meta2, hit2 = elle_mops_with_cache(p)
        assert hit2
        np.testing.assert_array_equal(mat1, mat2)
        assert meta1.n_txns == meta2.n_txns

    def test_stale_on_history_rewrite(self, tmp_path):
        from jepsen_tpu.history.storecache import (
            elle_mops_with_cache,
            load_elle_mops_cache,
        )

        p, _h = self._write_elle(tmp_path)
        elle_mops_with_cache(p)
        assert load_elle_mops_cache(p) is not None
        _p, _h2 = self._write_elle(tmp_path, seed=7)  # rewrite in place
        assert load_elle_mops_cache(p) is None
        mat, meta, hit = elle_mops_with_cache(p)  # and re-cuts the cache
        assert not hit and meta.n_txns > 0

    def test_degenerate_flag_survives_the_cache(self, tmp_path):
        """A cached degenerate history must STAY degenerate: losing the
        flag would route it onto the device path with a wrong verdict."""
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
        from jepsen_tpu.history.storecache import elle_mops_with_cache

        mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
        h = reindex([mk([["append", 0, 1]]), mk([["append", 0, 1]])])
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, h)
        _, meta, hit = elle_mops_with_cache(p)
        assert not hit and meta.degenerate
        _, meta2, hit2 = elle_mops_with_cache(p)
        assert hit2 and meta2.degenerate

    def test_non_int_keys_are_not_cached(self, tmp_path):
        from jepsen_tpu.checkers.elle import elle_mops_for
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
        from jepsen_tpu.history.storecache import (
            elle_mops_cache_path,
            save_elle_mops_cache,
        )

        from jepsen_tpu.history.store import read_history

        mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
        h = reindex([mk([["append", "k", 1], ["r", "k", [1]]])])
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, h)
        mat, meta = elle_mops_for(read_history(p))
        assert meta.keys == ["k"]
        save_elle_mops_cache(p, mat, meta)
        assert not elle_mops_cache_path(p).exists()


# ---------------------------------------------------------------------------
# Store-level packed cache (history/storecache.py)
# ---------------------------------------------------------------------------


class TestStoreCache:
    def _mk_store(self, tmp_path, n=3):
        from jepsen_tpu.cli.main import main

        assert main(
            ["synth", "--count", str(n), "--ops", "40", "--lost", "1",
             "--store", str(tmp_path / "s")]
        ) == 0
        import glob

        return str(tmp_path / "s"), sorted(
            glob.glob(str(tmp_path / "s" / "synth" / "*" / "history.jsonl"))
        )

    def test_roundtrip_identical_columns(self, tmp_path):
        import jax.numpy as jnp

        from jepsen_tpu.history.encode import pack_histories
        from jepsen_tpu.history.storecache import (
            load_packed_store_cache,
            save_packed_store_cache,
        )
        from jepsen_tpu.history.store import read_history

        root, paths = self._mk_store(tmp_path)
        packed = pack_histories([read_history(p) for p in paths])
        save_packed_store_cache(root, paths, packed)
        got = load_packed_store_cache(root, paths)
        assert got is not None
        assert got.value_space == packed.value_space
        for name in ("index", "process", "type", "f", "value", "mask"):
            assert bool(
                jnp.array_equal(getattr(got, name), getattr(packed, name))
            ), name

    def test_stale_on_any_member_change(self, tmp_path):
        from jepsen_tpu.history.encode import pack_histories
        from jepsen_tpu.history.storecache import (
            load_packed_store_cache,
            save_packed_store_cache,
        )
        from jepsen_tpu.history.store import read_history, write_history_jsonl
        from jepsen_tpu.history.synth import SynthSpec, synth_batch

        root, paths = self._mk_store(tmp_path)
        packed = pack_histories([read_history(p) for p in paths])
        save_packed_store_cache(root, paths, packed)
        assert load_packed_store_cache(root, paths) is not None
        # rewrite one member → reject
        write_history_jsonl(
            paths[1], synth_batch(1, SynthSpec(n_ops=44, seed=7))[0].ops
        )
        assert load_packed_store_cache(root, paths) is None
        # different member set (drop one) → reject
        assert load_packed_store_cache(root, paths[:-1]) is None

    def test_missing_or_corrupt_is_none(self, tmp_path):
        from jepsen_tpu.history.storecache import (
            STORE_CACHE,
            load_packed_store_cache,
        )

        root, paths = self._mk_store(tmp_path)
        assert load_packed_store_cache(root, paths) is None
        (tmp_path / "s" / STORE_CACHE).write_bytes(b"junk")
        assert load_packed_store_cache(root, paths) is None

    def test_cli_second_run_hits_and_verdict_matches(self, tmp_path, capsys):
        import json

        from jepsen_tpu.cli.main import main

        root, _paths = self._mk_store(tmp_path)
        args = ["bench-check", "--histories", root]
        assert main(args) == 0
        first = capsys.readouterr()
        assert main(args) == 0
        second = capsys.readouterr()
        assert "store cache hit" in second.err
        v1 = json.loads(first.out.strip().splitlines()[-1])
        v2 = json.loads(second.out.strip().splitlines()[-1])
        assert (v1["invalid"], v1["histories"]) == (
            v2["invalid"], v2["histories"],
        )

    def test_mixed_store_is_not_cached(self, tmp_path, capsys):
        from jepsen_tpu.cli.main import main
        from jepsen_tpu.history.storecache import STORE_CACHE

        root, _paths = self._mk_store(tmp_path)
        assert main(
            ["synth", "--workload", "stream", "--count", "2", "--ops",
             "40", "--store", root]
        ) == 0
        capsys.readouterr()
        args = ["bench-check", "--histories", root, "--workload", "queue"]
        assert main(args) == 0
        # a subset pack must not be cached: ambiguous under auto
        assert not (tmp_path / "s" / STORE_CACHE).exists()
        assert main(args) == 0
        second = capsys.readouterr()
        assert "store cache hit" not in second.err
