"""Packed-row store cache (history/rows.py, VERDICT r3 #3).

Row explosion is ~95% of replay wall clock and is a pure function of the
history file, so it is persisted as a hash-guarded ``rows.npz``.  These
tests pin the cache contract: identical matrices through hit and miss,
staleness on rewrite, record-time creation, and CLI parity.
"""

from __future__ import annotations

import numpy as np

from jepsen_tpu.history.ops import workload_of
from jepsen_tpu.history.rows import (
    _rows_for,
    cache_path_for,
    load_rows_cache,
    rows_with_cache,
    save_rows_cache,
)
from jepsen_tpu.history.store import Store, write_history_jsonl
from jepsen_tpu.history.synth import SynthSpec, synth_batch


def _write_history(tmp_path, n_ops=40, seed=0):
    h = synth_batch(1, SynthSpec(n_ops=n_ops, seed=seed))[0].ops
    p = tmp_path / "history.jsonl"
    write_history_jsonl(p, h)
    return p, h


def test_roundtrip_bitwise_identical(tmp_path):
    p, h = _write_history(tmp_path)
    rows = _rows_for(h)
    save_rows_cache(p, "queue", rows)
    got = load_rows_cache(p)
    assert got is not None
    workload, cached = got
    assert workload == "queue"
    assert cached.dtype == np.int32
    np.testing.assert_array_equal(cached, rows)


def test_stale_on_history_rewrite(tmp_path):
    p, h = _write_history(tmp_path)
    save_rows_cache(p, "queue", _rows_for(h))
    assert load_rows_cache(p) is not None
    # rewrite the history: the cache must be refused, not served stale
    h2 = synth_batch(1, SynthSpec(n_ops=44, seed=9))[0].ops
    write_history_jsonl(p, h2)
    assert load_rows_cache(p) is None


def test_missing_cache_is_none(tmp_path):
    p, _h = _write_history(tmp_path)
    assert load_rows_cache(p) is None


def test_corrupt_cache_is_none(tmp_path):
    p, h = _write_history(tmp_path)
    cache_path_for(p).write_bytes(b"not an npz")
    assert load_rows_cache(p) is None
    # and the load-through path recovers by re-exploding
    workload, rows, hit = rows_with_cache(p)
    assert not hit and workload == "queue" and rows.shape[1] == 8


def test_load_through_miss_then_hit(tmp_path):
    p, h = _write_history(tmp_path)
    w1, r1, hit1 = rows_with_cache(p)
    assert not hit1
    w2, r2, hit2 = rows_with_cache(p)
    assert hit2
    assert w1 == w2 == workload_of(h)
    np.testing.assert_array_equal(r1, r2)
    np.testing.assert_array_equal(r1, _rows_for(h))


def test_store_save_history_cuts_cache(tmp_path):
    store = Store(tmp_path)
    h = synth_batch(1, SynthSpec(n_ops=30))[0].ops
    d = store.run_dir("t")
    p = store.save_history(d, h)
    got = load_rows_cache(p)
    assert got is not None
    workload, rows = got
    assert workload == "queue"
    np.testing.assert_array_equal(rows, _rows_for(h))


def test_cli_bench_check_uses_cache(tmp_path, capsys):
    """End-to-end: synth a store, bench-check twice — the second run
    reports cache hits and produces the same invalid count."""
    from jepsen_tpu.cli.main import main

    rc = main(
        ["synth", "--count", "3", "--ops", "40", "--lost", "1",
         "--store", str(tmp_path / "s")]
    )
    assert rc == 0
    capsys.readouterr()

    args = ["bench-check", "--histories", str(tmp_path / "s")]
    assert main(args) == 0
    first = capsys.readouterr()
    assert main(args) == 0
    second = capsys.readouterr()
    assert "(3 from the packed-row cache)" in second.err
    # identical verdict either way (timings differ, the counts must not)
    import json

    v1 = json.loads(first.out.strip().splitlines()[-1])
    v2 = json.loads(second.out.strip().splitlines()[-1])
    assert (v1["invalid"], v1["histories"]) == (
        v2["invalid"], v2["histories"],
    )
