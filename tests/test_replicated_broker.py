"""The mini broker in replicated (Raft) mode, driven end-to-end by the
native C++ AMQP driver over real TCP.

This is the SUT side of VERDICT r3 #2: a publish confirmed on ANY node is
quorum-committed and readable from EVERY node; an isolated leader stops
confirming; the majority keeps serving; heal converges; and the seeded
``confirm-before-quorum`` bug produces a confirmed-then-vanished write —
observable through the same AMQP surface the live suite uses.
"""

from __future__ import annotations

import socket
import subprocess
import time

import pytest

from jepsen_tpu.harness.broker import MiniAmqpBroker
from jepsen_tpu.harness.replication import ReplicatedBackend

FAST = dict(
    election_timeout=(0.15, 0.3),
    heartbeat_s=0.04,
    dead_owner_s=0.8,
    submit_timeout_s=2.0,
)


@pytest.fixture(scope="module")
def native_lib():
    import os

    native_dir = os.path.join(os.path.dirname(__file__), "..", "native")
    r = subprocess.run(
        ["make", "-C", native_dir], capture_output=True, text=True
    )
    if r.returncode != 0:
        pytest.skip(f"native build failed:\n{r.stderr}")
    from jepsen_tpu.client import native

    native.load_library().amqp_set_logging(0)
    return native


@pytest.fixture(autouse=True)
def _reset_driver(native_lib):
    native_lib.reset(drain_wait_ms=50)
    yield
    native_lib.reset(drain_wait_ms=50)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Cluster:
    def __init__(self, n=3, seed_bug=None, **overrides):
        names = [f"n{i}" for i in range(n)]
        peers = {nm: ("127.0.0.1", _free_port()) for nm in names}
        opts = {**FAST, **overrides}
        self.brokers: dict[str, MiniAmqpBroker] = {}
        for nm in names:
            backend = ReplicatedBackend(
                nm, peers, seed_bug=seed_bug, **opts
            )
            self.brokers[nm] = MiniAmqpBroker(
                port=0, replication=backend
            ).start()

    def leader(self, timeout=5.0) -> str:
        from _load import scaled

        deadline = time.monotonic() + scaled(timeout)
        while time.monotonic() < deadline:
            for nm, b in self.brokers.items():
                if b.replication.raft.is_leader():
                    return nm
            time.sleep(0.02)
        raise AssertionError("no leader")

    def followers(self) -> list[str]:
        lead = self.leader()
        return [nm for nm in self.brokers if nm != lead]

    def isolate(self, victim: str) -> None:
        for nm, b in self.brokers.items():
            if nm != victim:
                b.replication.raft.block(victim)
                self.brokers[victim].replication.raft.block(nm)

    def heal(self) -> None:
        for b in self.brokers.values():
            b.replication.raft.unblock_all()

    def stop(self) -> None:
        for b in self.brokers.values():
            b.stop()


@pytest.fixture
def cluster():
    c = _Cluster()
    try:
        yield c
    finally:
        c.stop()


def _driver(native_lib, broker, **kw):
    kw.setdefault("connect_retry_ms", 3000)
    return native_lib.NativeQueueDriver(
        ["127.0.0.1"], "127.0.0.1", port=broker.port, **kw
    )


def test_publish_on_one_node_read_from_another(native_lib, cluster):
    a, b = cluster.leader(), cluster.followers()[0]
    da = _driver(native_lib, cluster.brokers[a])
    db = _driver(native_lib, cluster.brokers[b])
    da.setup()
    db.setup()
    assert da.enqueue(41, 5.0) is True
    assert db.dequeue(5.0) == 41
    da.close()
    db.close()


def test_confirmed_on_follower_via_forwarding(native_lib, cluster):
    f = cluster.followers()[0]
    d = _driver(native_lib, cluster.brokers[f])
    d.setup()
    assert d.enqueue(7, 5.0) is True
    assert d.dequeue(5.0) == 7
    d.close()


def test_async_consumer_gets_cross_node_push(native_lib, cluster):
    a, b = cluster.leader(), cluster.followers()[0]
    consumer = _driver(
        native_lib, cluster.brokers[b], consumer_type="asynchronous"
    )
    consumer.setup()
    publisher = _driver(native_lib, cluster.brokers[a])
    publisher.setup()
    assert publisher.enqueue(13, 5.0) is True
    # the push rides the follower's apply→kick path, no local publish
    assert consumer.dequeue(5.0) == 13
    consumer.close()
    publisher.close()


def test_isolated_leader_stops_confirming(native_lib, cluster):
    from jepsen_tpu.client.protocol import DriverTimeout

    lead = cluster.leader()
    d = _driver(native_lib, cluster.brokers[lead])
    d.setup()
    assert d.enqueue(1, 5.0) is True
    cluster.isolate(lead)
    with pytest.raises(DriverTimeout):
        d.enqueue(2, 1.0)  # no quorum → no confirm → indeterminate
    d.close()


def test_majority_side_survives_and_heals(native_lib, cluster):
    lead = cluster.leader()
    maj = cluster.followers()
    cluster.isolate(lead)
    d = _driver(native_lib, cluster.brokers[maj[0]])
    d.setup()
    from _load import scaled

    # generous: on a loaded 1-core box elections can take several
    # rounds — and load-scaled on top (the round-4 flake class)
    deadline = time.monotonic() + scaled(12.0)
    ok = False
    while time.monotonic() < deadline and not ok:
        try:
            # per-attempt confirm window load-scaled too: the outer
            # deadline stretched under load while each try still gave
            # the quorum only 1.5s — the PR-11 tier-1 flake shape
            ok = d.enqueue(99, scaled(1.5))
        except Exception:
            time.sleep(0.1)
    assert ok, "majority side never elected a working leader"
    cluster.heal()
    # the healed ex-leader catches up and can serve the committed value
    d2 = _driver(native_lib, cluster.brokers[lead])
    d2.setup()
    deadline = time.monotonic() + scaled(12.0)
    got = None
    while time.monotonic() < deadline and got is None:
        try:
            got = d2.dequeue(scaled(1.5))
        except Exception:
            time.sleep(0.1)
    assert got == 99
    d.close()
    d2.close()


def test_leader_death_does_not_lose_confirmed_write(native_lib, cluster):
    lead = cluster.leader()
    d = _driver(native_lib, cluster.brokers[lead])
    d.setup()
    assert d.enqueue(55, 5.0) is True
    cluster.brokers[lead].stop()  # SIGKILL stand-in for the whole node
    other = next(nm for nm in cluster.brokers if nm != lead)
    d2 = _driver(native_lib, cluster.brokers[other])
    d2.setup()
    deadline = time.monotonic() + 8.0
    got = None
    while time.monotonic() < deadline and got is None:
        try:
            got = d2.dequeue(1.5)
        except Exception:
            # a quorum-less get now CLOSES the channel (it must not
            # answer empty — the r7 drain-loss fix); recover like the
            # suite's _guard does: best-effort reconnect, retry
            try:
                d2.reconnect()
            except Exception:  # noqa: BLE001 — retried
                pass
            time.sleep(0.1)
    assert got == 55
    d2.close()


def test_ttl_dead_letter_replicated(native_lib, cluster):
    from _load import scaled

    nm = cluster.followers()[0]
    d = _driver(native_lib, cluster.brokers[nm], dead_letter=True)
    d.setup()
    assert d.enqueue(3, 5.0) is True
    time.sleep(1.3)  # driver declares x-message-ttl=1000 in dead-letter mode
    # drain reads the dead-letter queue too; under load one pass can
    # come back short (no-quorum gets retried inside later passes), so
    # keep draining to a load-scaled deadline before failing
    drained = set(d.drain())
    deadline = time.monotonic() + scaled(6.0)
    while 3 not in drained and time.monotonic() < deadline:
        time.sleep(0.2)
        drained |= set(d.drain())
    assert 3 in drained
    d.close()


def test_seeded_bug_loses_confirmed_write_over_amqp(native_lib):
    """confirm-before-quorum, observed purely through AMQP: the isolated
    buggy leader confirms; after heal + truncation the value is gone."""
    from _load import scaled

    c = _Cluster(seed_bug="confirm-before-quorum")
    try:
        lead = c.leader()
        d = _driver(native_lib, c.brokers[lead])
        d.setup()
        c.isolate(lead)
        # the buggy confirm is local (no quorum) but the broker thread
        # still needs CPU under a loaded box — window load-scaled
        assert d.enqueue(666, scaled(5.0)) is True  # THE LIE
        maj = [nm for nm in c.brokers if nm != lead]
        # wait for the majority side to elect before driving it
        # (deadlines load-scaled: this one flaked under a concurrent
        # 30-min soak's analysis phase — the round-4 class)
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline and not any(
            c.brokers[nm].replication.raft.is_leader() for nm in maj
        ):
            time.sleep(0.05)
        dm = _driver(native_lib, c.brokers[maj[0]])
        dm.setup()
        deadline = time.monotonic() + scaled(5.0)
        ok = False
        while time.monotonic() < deadline and not ok:
            try:
                ok = dm.enqueue(1, scaled(1.5))
            except Exception:
                time.sleep(0.1)
        assert ok
        c.heal()
        time.sleep(scaled(1.0))  # truncation + catch-up
        # drain from the healed ex-leader: 666 must be gone (lost write)
        seen = []
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline:
            try:
                v = d.dequeue(1.0)
            except Exception:
                time.sleep(0.1)
                continue
            if v is None:
                break
            seen.append(v)
        assert 666 not in seen and 1 in seen
        d.close()
        dm.close()
    finally:
        c.stop()


# ---------------------------------------------------------------------------
# Linearizable stream reads (every family multi-node)
# ---------------------------------------------------------------------------


def _stream_driver(native_lib, broker):
    return native_lib.NativeStreamDriver(
        "127.0.0.1", port=broker.port, connect_retry_ms=3000
    )


def test_stream_append_on_one_node_read_from_lagging_other(
    native_lib, cluster
):
    """Read-your-append across nodes, with GENUINE lag induced: the
    follower is made to refuse AppendEntries (its local replica provably
    lacks the records) while its client-facing read still returns them,
    because the read commits through the log at the leader.  A local-
    snapshot regression fails this test deterministically.  Deadlines
    load-scaled (the PR-11 tier-1 flake trio: this read flaked beside
    a concurrent soak's analysis phase — the round-4 class)."""
    from _load import scaled

    a, b_node = cluster.leader(), cluster.followers()[0]
    wa = _stream_driver(native_lib, cluster.brokers[a])
    rb = _stream_driver(native_lib, cluster.brokers[b_node])
    wa.setup()
    rb.setup()

    raft_b = cluster.brokers[b_node].replication.raft

    def refuse(msg):
        # stay a quiet follower (reset timers, keep the leader hint) but
        # apply NOTHING — a lagging replica, not a partitioned one
        with raft_b.lock:
            raft_b._last_heartbeat = time.monotonic()
            raft_b._election_deadline = raft_b._fresh_deadline()
            raft_b.leader_hint = msg["from"]
        return {"term": raft_b.term, "ok": False, "have": len(raft_b.log)}

    raft_b.__dict__["_on_append_entries"] = refuse
    try:
        assert wa.append(7, scaled(5.0)) is True
        assert wa.append(9, scaled(5.0)) is True
        # the lag is real: b's local replica has neither record
        assert (
            cluster.brokers[b_node].replication.machine.stream_snapshot(
                "jepsen.stream"
            )
            == []
        )
        vals = [v for _off, v in rb.read_from(0, 100, scaled(3.0))]
        assert vals == [7, 9]  # ...yet b's served read is complete
    finally:
        # drop the instance shadow; the class method resumes, b catches up
        raft_b.__dict__.pop("_on_append_entries", None)
    wa.close()
    rb.close()


def test_minority_stream_read_fails_rather_than_stale(native_lib, cluster):
    """A node cut from quorum must NOT serve its local (possibly stale)
    stream state — and must not stay silent either (silence is
    indistinguishable from a committed empty log, which would read as
    data loss downstream): the broker closes the channel, so the
    client's read FAILS loudly."""
    lead = cluster.leader()
    d = _stream_driver(native_lib, cluster.brokers[lead])
    d.setup()
    assert d.append(1, 5.0) is True
    from _load import scaled

    cluster.isolate(lead)
    time.sleep(scaled(0.6))  # step-down
    # read timeout must outlast the broker's quorum wait (2s in FAST) so
    # the channel-close failure signal lands inside this read; a client
    # that gives up earlier records a timed-out/empty read, which is a
    # legal (empty-prefix) observation, never a stale snapshot — both
    # windows stretch with measured host load (the round-4 flake class)
    with pytest.raises(ConnectionError):
        d.read_from(0, 100, scaled(4.0))
    d.close()


def test_seeded_drop_unacked_on_close_loses_delivered_message(native_lib):
    """Second seeded bug class (the delivery/requeue plane): with
    drop-unacked-on-close, a dying connection's un-acked QoS-1 delivery
    is stranded instead of requeued — the drain provably misses it.
    Deterministic at the AMQP level: consume one message (the broker
    pushes the NEXT one un-acked), close, drain."""
    c = _Cluster(seed_bug="drop-unacked-on-close")
    try:
        lead = c.leader()
        b = c.brokers[lead]
        pub = _driver(native_lib, b)
        pub.setup()
        for v in (1, 2, 3):
            assert pub.enqueue(v, 5.0)
        cons = _driver(native_lib, b, consumer_type="asynchronous")
        cons.setup()
        assert cons.dequeue(5.0) == 1  # ack of 1 → broker pushes 2 un-acked
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            with b.replication.machine.lock:
                if b.replication.machine.inflight:
                    break
            time.sleep(0.05)
        with b.replication.machine.lock:
            assert b.replication.machine.inflight, "no un-acked push"
        cons.close()  # THE BUG: the un-acked delivery is not requeued
        time.sleep(0.8)
        drained = pub.drain()
        assert 3 in drained and 2 not in drained  # 2 is lost
        with b.replication.machine.lock:
            assert b.replication.machine.inflight  # stranded forever
    finally:
        c.stop()


def test_unacked_on_close_requeues_without_the_bug(native_lib, cluster):
    """The green twin: a correct cluster requeues the dying connection's
    un-acked delivery and the drain recovers every message."""
    lead = cluster.leader()
    b = cluster.brokers[lead]
    pub = _driver(native_lib, b)
    pub.setup()
    for v in (1, 2, 3):
        assert pub.enqueue(v, 5.0)
    cons = _driver(native_lib, b, consumer_type="asynchronous")
    cons.setup()
    assert cons.dequeue(5.0) == 1
    cons.close()
    drained = pub.drain()
    assert sorted(drained) == [2, 3]


def test_orphaned_inflight_requeued_after_lost_close_sweep(
    native_lib, cluster
):
    """Round-4 matrix find (config random-partition-halves, scaled):
    a consumer's connection died during a partition/election window, the
    close handler's one-shot ``requeue_owner`` submit timed out
    uncommitted — while the node itself stayed in the majority, so the
    leader's dead-NODE reaper never fired — and the delivered-but-unacked
    message sat inflight through the entire drain: depth 1 on every
    replica, ``total-queue`` lost.  The broker now runs a continuous
    orphan sweep: an inflight entry owned by a connection that no longer
    exists is re-proposed until it commits.

    The lost submit is injected (drop the close path's requeue_owner
    call once) so the orphan state the matrix reached through timing is
    reproduced deterministically on a healthy cluster; with the sweep
    disabled this test strands the entry forever and fails."""
    lead = cluster.leader()
    f = cluster.followers()[0]
    fb = cluster.brokers[f]

    pub = _driver(native_lib, cluster.brokers[lead])
    pub.setup()
    cons = _driver(native_lib, fb, consumer_type="asynchronous")
    cons.setup()
    assert pub.enqueue(55, 5.0) is True

    # the QoS-1 push lands on the consumer un-acked: wait for the
    # replicated inflight entry owned by f's connection
    deadline = time.monotonic() + 5.0
    prefix = f + "|"
    owners: set = set()
    while time.monotonic() < deadline:
        with fb.replication.machine.lock:
            owners = {
                o
                for o, _q, _m in fb.replication.machine.inflight.values()
            }
        if any(o.startswith(prefix) for o in owners):
            break
        time.sleep(0.02)
    else:
        raise AssertionError(f"no inflight entry owned by {f}: {owners}")

    # inject the lost close-time sweep: the serve thread's
    # requeue_owner vanishes exactly as a partition-window submit
    # timeout would, leaving the orphaned-inflight state behind
    import threading as _threading

    real = fb.replication.requeue_owner
    dropped = []
    fb.replication.requeue_owner = lambda owner: dropped.append(
        (_threading.current_thread().name, owner)
    )

    def _close_path_dropped():
        # the orphan-sweep thread may also hit the patch while it's in
        # place (its submits are dropped too — later unpatched ticks
        # re-propose, which is the feature under test); the injection is
        # only complete once the CLOSE handler's own call was swallowed
        return any(name != "orphan-sweep" for name, _ in dropped)

    try:
        cons.close()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not _close_path_dropped():
            time.sleep(0.02)
        assert _close_path_dropped(), (
            f"close path never tried the sweep: {dropped}"
        )
    finally:
        fb.replication.requeue_owner = real

    # the orphan sweep must re-propose: the message returns to the
    # READY queue and a fresh client can read it
    deadline = time.monotonic() + 8.0
    still = None
    while time.monotonic() < deadline:
        with fb.replication.machine.lock:
            still = [
                o
                for o, _q, _m in fb.replication.machine.inflight.values()
                if o.startswith(prefix)
            ]
        if not still:
            break
        time.sleep(0.05)
    assert not still, f"inflight entry stranded after lost sweep: {still}"
    assert pub.dequeue(5.0) == 55
    pub.close()


def test_departed_member_inflight_requeued_by_survivors(native_lib):
    """Round-5 burn-in find (10-min 5-node mixed soak, lost value 16943):
    a consumer held an un-acked delivery on a node that was then killed,
    FORGOTTEN (RemoveServer), and restarted OUTSIDE the cluster (its
    rejoin failed).  Nobody requeued the inflight entry: the departed
    node's own sweep cannot submit (no leader to forward to), and the
    leader's dead-NODE reaper only watches current members — the message
    sat inflight through the whole drain and total-queue flagged it
    lost.  Every member's orphan sweep now also re-proposes requeues for
    owners whose node has LEFT the config.

    dead_owner_s is huge here so the old dead-node reaper cannot mask
    the hole: with the departed-member sweep reverted, the entry
    strands forever and this test fails."""
    # a reaper that can never fire inside the test window
    c = _Cluster(dead_owner_s=60.0)
    try:
        lead = c.leader()
        victim = c.followers()[0]
        vb = c.brokers[victim]

        pub = _driver(native_lib, c.brokers[lead])
        pub.setup()
        cons = _driver(native_lib, vb, consumer_type="asynchronous")
        cons.setup()
        assert pub.enqueue(77, 5.0) is True

        # wait until the replicated inflight entry is owned by victim
        deadline = time.monotonic() + 5.0
        prefix = victim + "|"
        while time.monotonic() < deadline:
            with vb.replication.machine.lock:
                owners = {
                    o
                    for o, _q, _m in vb.replication.machine.inflight.values()
                }
            if any(o.startswith(prefix) for o in owners):
                break
            time.sleep(0.02)
        else:
            raise AssertionError(f"no inflight owned by {victim}: {owners}")

        # SIGKILL semantics: no close handlers, no goodbye requeue —
        # the victim's own machinery is gone for good (it "restarts
        # outside the cluster", unable to submit anything)
        vb.replication.requeue_owner = lambda owner: None
        vb.stop()

        # forget_cluster_node: the cluster genuinely shrinks to 2/2
        survivor = c.brokers[lead]
        assert survivor.replication.raft.request_forget(victim)

        # the survivors' departed-member sweep must re-ready the message
        deadline = time.monotonic() + 6.0
        got = None
        while time.monotonic() < deadline and got is None:
            got = pub.dequeue(1.0)
        assert got == 77, (
            f"departed member's inflight delivery never requeued "
            f"(got {got!r})"
        )
    finally:
        c.stop()


def test_fenced_lock_tokens_are_raft_commit_indices(native_lib, cluster):
    """Fenced grants across the replicated cluster carry the Raft log
    index of the grant commit — strictly increasing even across a
    dead-owner REVOCATION (the shape that double-grants unfenced: the
    reaped holder's token is superseded and its release is rejected).

    Acquire/release waits ride the ``scaled()`` deadline discipline:
    under full-suite scheduler pressure a fixed 5 s grant wait can
    expire on a healthy cluster (the round-4 load-flake class)."""
    from _load import scaled

    from jepsen_tpu.client.native import NativeMutexDriver

    a_node, b_node = cluster.leader(), cluster.followers()[0]
    a = NativeMutexDriver(
        "127.0.0.1", port=cluster.brokers[a_node].port, fenced=True,
        connect_retry_ms=3000,
    )
    b = NativeMutexDriver(
        "127.0.0.1", port=cluster.brokers[b_node].port, fenced=True,
        connect_retry_ms=3000,
    )
    a.setup()
    b.setup()
    t1 = a.acquire_fenced(scaled(5.0))
    assert t1 > 0
    # the token IS the replicated fence on the leader's machine
    lead = cluster.brokers[cluster.leader()].replication
    assert lead.machine.fences.get("jepsen.lock") == t1
    assert b.acquire_fenced(scaled(5.0)) == 0  # busy cluster-wide
    assert a.release_fenced(scaled(5.0)) == t1
    t2 = b.acquire_fenced(scaled(5.0))
    assert t2 > t1
    # revocation without the holder's consent: b's connection dies, the
    # close sweep requeues the grant through the log (fence advances)
    b.reconnect()
    t3 = a.acquire_fenced(scaled(8.0))
    assert t3 > t2
    assert b.release_fenced(scaled(5.0)) == 0  # revoked holder: not a release
    assert a.release_fenced(scaled(5.0)) == t3
    a.close()
    b.close()
