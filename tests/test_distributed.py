"""Multi-process checker plane: the real ``jax.distributed`` harness.

``parallel/distributed.py`` spawns N worker processes joined through
``jax.distributed`` (process 0 hosts the coordination service), assigns
every history file to exactly one worker by the deterministic
size-striped rule, runs per-process pipelines over each process's OWN
local devices, and merges the verdicts through the coordination
service's key-value store.  Computation never crosses the process
boundary — which is why this harness runs on the CPU backend, where XLA
has no cross-process programs (the pre-PR-5 version of this file tried
a global mesh over virtual CPU devices and failed since seed with
"Multiprocess computations aren't implemented on the CPU backend").

Parametrized over pod shapes: 2×4 (two processes, four virtual devices
each) and 4×2 (four processes, two devices each).  The verdicts are
differentially checked against the serial oracle on the same files.

PR 13 makes the failure contract ELASTIC by default (spool-directory
task protocol, survivor requeue, degraded provenance) with
``fail_fast=True`` preserving the PR-5 kill-everything contract
verbatim — both paths are pinned below.
"""

from __future__ import annotations

import json
import os

import pytest

from jepsen_tpu.history.store import _json_default, write_history_jsonl
from jepsen_tpu.history.synth import (
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_stream_batch,
)
from jepsen_tpu.parallel.distributed import (
    DistributedCheckError,
    assign_stripes,
    run_multiprocess_check,
)


def _norm(x):
    """JSON-normalize verdicts: the distributed merge round-trips JSON
    (numpy scalars become plain ints/bools), the serial oracle doesn't."""
    return json.loads(json.dumps(x, default=_json_default))


def _write(tmp_path, base, tag="h"):
    files = []
    for i, sh in enumerate(base):
        p = tmp_path / f"{tag}{i:03d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(p)
    return files


def test_assign_stripes_deterministic_and_balanced():
    sizes = [10, 500, 30, 400, 20, 300, 40, 200]
    stripes = assign_stripes(sizes, 3)
    # every index exactly once
    assert sorted(i for s in stripes for i in s) == list(range(8))
    # identical on recompute (the cross-process contract)
    assert stripes == assign_stripes(sizes, 3)
    # largest-first round-robin: the three biggest files land on three
    # DIFFERENT processes
    top3 = {1, 3, 5}
    assert {s[0] for s in stripes} == top3


@pytest.mark.parametrize(
    "n_procs,devices_per_proc,fail_fast",
    [(2, 4, True), (4, 2, False)],
    ids=["pod2x4-failfast", "pod4x2-elastic"],
)
def test_multiprocess_check_matches_serial(
    tmp_path, n_procs, devices_per_proc, fail_fast
):
    """Both launcher modes, differentially: the fail-fast
    jax.distributed KV merge and the elastic spool-task merge must
    produce identical verdicts to the serial oracle on a no-fault run."""
    base = synth_stream_batch(
        10, StreamSynthSpec(n_ops=30, seed=3), lost=1, duplicated=1
    )
    files = _write(tmp_path, base)
    results, info = run_multiprocess_check(
        "stream",
        files,
        n_procs,
        devices_per_proc=devices_per_proc,
        chunk=3,
        timeout_s=420,
        fail_fast=fail_fast,
    )
    assert info["n_procs"] == n_procs
    # together the workers covered the corpus exactly once; fail-fast
    # pins one shard per process (the deterministic stripes), elastic
    # allows a fast worker to STEAL a sibling's stripe before it spins
    # up (work conservation is the contract, not the ownership)
    per_proc = info["per_process"]
    if fail_fast:
        assert len(per_proc) == n_procs
    else:
        assert 1 <= len(per_proc) <= n_procs
    assert sum(p["checked"] for p in per_proc) == len(files)
    assert all(p["lanes"] >= 1 for p in per_proc)

    from jepsen_tpu.parallel.pipeline import check_sources

    serial, _ = check_sources("stream", files, chunk=3, serial=True)
    assert _norm(results) == _norm(serial)
    # the corpus carries seeded anomalies — the merged verdicts must
    # flag them (not just agree on all-green)
    assert any(r["stream"]["valid?"] is not True for r in results)


def test_multiprocess_queue_reduce_and_census(tmp_path):
    """2-process queue family in REDUCE mode: the merged two-scalar
    verdict matches the serial oracle's counts, launcher-dropped files
    are counted, and both sub-checkers fold into the combined valid."""
    base = synth_batch(8, SynthSpec(n_ops=40, seed=7), lost=1, duplicated=1)
    files = _write(tmp_path, base)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    verdict, info = run_multiprocess_check(
        "queue",
        files + [empty],
        2,
        devices_per_proc=2,
        chunk=3,
        mesh=True,
        reduce=True,
        timeout_s=420,
    )
    from jepsen_tpu.parallel.pipeline import check_sources

    serial, _ = check_sources("queue", files, chunk=3, serial=True)
    invalid = [
        not (
            r["queue"]["valid?"] is True and r["linear"]["valid?"] is True
        )
        for r in serial
    ]
    assert verdict["histories"] == len(files)
    assert verdict["invalid"] == sum(invalid)
    assert verdict["first_invalid"] == (
        invalid.index(True) if any(invalid) else -1
    )
    assert verdict["dropped"] == 1 and info["dropped"] == 1


def test_dead_worker_elastic_completes_on_survivors(tmp_path):
    """The crash contract, ELASTIC edition (PR 13, the default): worker
    1 of 3 is killed mid-run — right AFTER claiming its deterministic
    stripe, before publishing any verdict — and the run COMPLETES on
    the survivors: the dead worker's stripe requeues, the ``degraded``
    provenance names the dead worker and its requeued stripe, and the
    merged verdicts are identical to the serial oracle."""
    base = synth_stream_batch(9, StreamSynthSpec(n_ops=25, seed=5), lost=1)
    files = _write(tmp_path, base)
    os.environ["JEPSEN_TPU_DIST_DIE_PID"] = "1"
    try:
        results, info = run_multiprocess_check(
            "stream", files, 3, chunk=3, timeout_s=300
        )
    finally:
        del os.environ["JEPSEN_TPU_DIST_DIE_PID"]
    deg = info["degraded"]
    # the dead worker and its requeued stripes, machine-readable
    assert any(
        d["pid"] == 1 and d["rc"] == 42 for d in deg["dead_workers"]
    ), deg["dead_workers"]
    requeued = [r for r in deg["requeued_stripes"] if r["stripe"] == 1]
    assert requeued and requeued[0]["retries"] == 1
    assert requeued[0]["from_pid"] == 1
    assert requeued[0]["completed_by"] in (0, 2)
    assert requeued[0]["recovery_s"] >= 0
    assert deg["effective_procs"] == 2
    assert not deg["quarantined_stripes"]
    # verdict ≡ serial oracle on every history (nothing quarantined)
    from jepsen_tpu.parallel.pipeline import check_sources

    serial, _ = check_sources("stream", files, chunk=3, serial=True)
    assert _norm(results) == _norm(serial)
    assert any(r["stream"]["valid?"] is not True for r in results)


def test_dead_worker_fail_fast_aborts_with_no_partial_verdicts(tmp_path):
    """The old crash contract, preserved VERBATIM under --fail-fast: a
    worker killed mid-run (after joining the cluster, before publishing
    any verdict) aborts the whole run with DistributedCheckError — no
    merged verdicts, no partial results."""
    base = synth_stream_batch(6, StreamSynthSpec(n_ops=20, seed=5))
    files = _write(tmp_path, base)
    os.environ["JEPSEN_TPU_DIST_DIE_PID"] = "1"
    try:
        with pytest.raises(DistributedCheckError, match="worker 1"):
            run_multiprocess_check(
                "stream", files, 2, chunk=3, timeout_s=300,
                fail_fast=True,
            )
    finally:
        del os.environ["JEPSEN_TPU_DIST_DIE_PID"]
