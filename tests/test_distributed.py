"""Multi-process checker plane: the real ``jax.distributed`` harness.

``parallel/distributed.py`` spawns N worker processes joined through
``jax.distributed`` (process 0 hosts the coordination service), assigns
every history file to exactly one worker by the deterministic
size-striped rule, runs per-process pipelines over each process's OWN
local devices, and merges the verdicts through the coordination
service's key-value store.  Computation never crosses the process
boundary — which is why this harness runs on the CPU backend, where XLA
has no cross-process programs (the pre-PR-5 version of this file tried
a global mesh over virtual CPU devices and failed since seed with
"Multiprocess computations aren't implemented on the CPU backend").

Parametrized over pod shapes: 2×4 (two processes, four virtual devices
each) and 4×2 (four processes, two devices each).  The verdicts are
differentially checked against the serial oracle on the same files.

PR 13 makes the failure contract ELASTIC by default (spool-directory
task protocol, survivor requeue, degraded provenance) with
``fail_fast=True`` preserving the PR-5 kill-everything contract
verbatim — both paths are pinned below.
"""

from __future__ import annotations

import json
import os

import pytest

from jepsen_tpu.history.store import _json_default, write_history_jsonl
from jepsen_tpu.history.synth import (
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_stream_batch,
)
from jepsen_tpu.parallel.distributed import (
    DistributedCheckError,
    assign_stripes,
    run_multiprocess_check,
)


def _norm(x):
    """JSON-normalize verdicts: the distributed merge round-trips JSON
    (numpy scalars become plain ints/bools), the serial oracle doesn't."""
    return json.loads(json.dumps(x, default=_json_default))


def _write(tmp_path, base, tag="h"):
    files = []
    for i, sh in enumerate(base):
        p = tmp_path / f"{tag}{i:03d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(p)
    return files


def test_assign_stripes_deterministic_and_balanced():
    sizes = [10, 500, 30, 400, 20, 300, 40, 200]
    stripes = assign_stripes(sizes, 3)
    # every index exactly once
    assert sorted(i for s in stripes for i in s) == list(range(8))
    # identical on recompute (the cross-process contract)
    assert stripes == assign_stripes(sizes, 3)
    # largest-first round-robin: the three biggest files land on three
    # DIFFERENT processes
    top3 = {1, 3, 5}
    assert {s[0] for s in stripes} == top3


@pytest.mark.parametrize(
    "n_procs,devices_per_proc,fail_fast",
    [(2, 4, True), (4, 2, False)],
    ids=["pod2x4-failfast", "pod4x2-elastic"],
)
def test_multiprocess_check_matches_serial(
    tmp_path, n_procs, devices_per_proc, fail_fast
):
    """Both launcher modes, differentially: the fail-fast
    jax.distributed KV merge and the elastic spool-task merge must
    produce identical verdicts to the serial oracle on a no-fault run."""
    base = synth_stream_batch(
        10, StreamSynthSpec(n_ops=30, seed=3), lost=1, duplicated=1
    )
    files = _write(tmp_path, base)
    results, info = run_multiprocess_check(
        "stream",
        files,
        n_procs,
        devices_per_proc=devices_per_proc,
        chunk=3,
        timeout_s=420,
        fail_fast=fail_fast,
    )
    assert info["n_procs"] == n_procs
    # together the workers covered the corpus exactly once; fail-fast
    # pins one shard per process (the deterministic stripes), elastic
    # allows a fast worker to STEAL a sibling's stripe before it spins
    # up (work conservation is the contract, not the ownership)
    per_proc = info["per_process"]
    if fail_fast:
        assert len(per_proc) == n_procs
    else:
        assert 1 <= len(per_proc) <= n_procs
    assert sum(p["checked"] for p in per_proc) == len(files)
    assert all(p["lanes"] >= 1 for p in per_proc)

    from jepsen_tpu.parallel.pipeline import check_sources

    serial, _ = check_sources("stream", files, chunk=3, serial=True)
    assert _norm(results) == _norm(serial)
    # the corpus carries seeded anomalies — the merged verdicts must
    # flag them (not just agree on all-green)
    assert any(r["stream"]["valid?"] is not True for r in results)


def test_multiprocess_queue_reduce_and_census(tmp_path):
    """2-process queue family in REDUCE mode: the merged two-scalar
    verdict matches the serial oracle's counts, launcher-dropped files
    are counted, and both sub-checkers fold into the combined valid."""
    base = synth_batch(8, SynthSpec(n_ops=40, seed=7), lost=1, duplicated=1)
    files = _write(tmp_path, base)
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    verdict, info = run_multiprocess_check(
        "queue",
        files + [empty],
        2,
        devices_per_proc=2,
        chunk=3,
        mesh=True,
        reduce=True,
        timeout_s=420,
    )
    from jepsen_tpu.parallel.pipeline import check_sources

    serial, _ = check_sources("queue", files, chunk=3, serial=True)
    invalid = [
        not (
            r["queue"]["valid?"] is True and r["linear"]["valid?"] is True
        )
        for r in serial
    ]
    assert verdict["histories"] == len(files)
    assert verdict["invalid"] == sum(invalid)
    assert verdict["first_invalid"] == (
        invalid.index(True) if any(invalid) else -1
    )
    assert verdict["dropped"] == 1 and info["dropped"] == 1


def test_dead_worker_elastic_completes_on_survivors(tmp_path):
    """The crash contract, ELASTIC edition (PR 13, the default): worker
    1 of 3 is killed mid-run — right AFTER claiming its deterministic
    stripe, before publishing any verdict — and the run COMPLETES on
    the survivors: the dead worker's stripe requeues, the ``degraded``
    provenance names the dead worker and its requeued stripe, and the
    merged verdicts are identical to the serial oracle."""
    base = synth_stream_batch(9, StreamSynthSpec(n_ops=25, seed=5), lost=1)
    files = _write(tmp_path, base)
    os.environ["JEPSEN_TPU_DIST_DIE_PID"] = "1"
    try:
        results, info = run_multiprocess_check(
            "stream", files, 3, chunk=3, timeout_s=300
        )
    finally:
        del os.environ["JEPSEN_TPU_DIST_DIE_PID"]
    deg = info["degraded"]
    # the dead worker and its requeued stripes, machine-readable
    assert any(
        d["pid"] == 1 and d["rc"] == 42 for d in deg["dead_workers"]
    ), deg["dead_workers"]
    requeued = [r for r in deg["requeued_stripes"] if r["stripe"] == 1]
    assert requeued and requeued[0]["retries"] == 1
    assert requeued[0]["from_pid"] == 1
    assert requeued[0]["completed_by"] in (0, 2)
    assert requeued[0]["recovery_s"] >= 0
    assert deg["effective_procs"] == 2
    assert not deg["quarantined_stripes"]
    # verdict ≡ serial oracle on every history (nothing quarantined)
    from jepsen_tpu.parallel.pipeline import check_sources

    serial, _ = check_sources("stream", files, chunk=3, serial=True)
    assert _norm(results) == _norm(serial)
    assert any(r["stream"]["valid?"] is not True for r in results)


def test_dead_worker_fail_fast_aborts_with_no_partial_verdicts(tmp_path):
    """The old crash contract, preserved VERBATIM under --fail-fast: a
    worker killed mid-run (after joining the cluster, before publishing
    any verdict) aborts the whole run with DistributedCheckError — no
    merged verdicts, no partial results."""
    base = synth_stream_batch(6, StreamSynthSpec(n_ops=20, seed=5))
    files = _write(tmp_path, base)
    os.environ["JEPSEN_TPU_DIST_DIE_PID"] = "1"
    try:
        with pytest.raises(DistributedCheckError, match="worker 1"):
            run_multiprocess_check(
                "stream", files, 2, chunk=3, timeout_s=300,
                fail_fast=True,
            )
    finally:
        del os.environ["JEPSEN_TPU_DIST_DIE_PID"]


# ---------------------------------------------------------------------------
# ISSUE 18: the TRUE global mesh — N processes joined into ONE
# jax.distributed mesh running the SAME collective verdict program, with
# collectives (gloo on CPU) crossing the host boundary.  Each process
# stages its own input lane and feeds its local shard; the launcher's
# generation-elastic story covers worker death mid-collective.
# ---------------------------------------------------------------------------


def _queue_flags(serial):
    return [
        not (r["queue"]["valid?"] is True and r["linear"]["valid?"] is True)
        for r in serial
    ]


@pytest.mark.parametrize(
    "workload,n_procs,devices_per_proc,seq",
    [("queue", 2, 1, 1), ("elle", 2, 2, 2)],
    ids=["queue-2proc-lanes", "elle-2proc-seq2-packed-closure"],
)
def test_global_mesh_matches_serial_oracle(
    tmp_path, workload, n_procs, devices_per_proc, seq
):
    """The tentpole differential: the reduced verdict computed by TWO
    cooperating processes on one global mesh must equal the serial
    oracle.  The elle seq=2 case lowers the packed multi-chip closure
    with its plane axis split ACROSS the process boundary (all_gather /
    psum through gloo) — the composition the per-process harness could
    never express."""
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.parallel.pipeline import check_sources

    if workload == "queue":
        base = synth_batch(
            9, SynthSpec(n_ops=40, seed=7), lost=1, duplicated=1
        )
    else:
        base = synth_elle_batch(
            6, ElleSynthSpec(n_txns=24, seed=3), g2_cycle=1
        ) + synth_elle_batch(3, ElleSynthSpec(n_txns=24, seed=11))
    files = _write(tmp_path, base)
    serial, _ = check_sources(workload, files, chunk=4, serial=True)
    if workload == "queue":
        flags = _queue_flags(serial)
    else:
        flags = [r["elle"]["valid?"] is not True for r in serial]

    verdict, info = run_multiprocess_check(
        workload, files, n_procs,
        devices_per_proc=devices_per_proc, chunk=4, reduce=True,
        global_mesh=True, seq=seq, timeout_s=420,
    )
    assert verdict["histories"] == len(files)
    assert verdict["invalid"] == sum(flags)
    assert verdict["first_invalid"] == (
        flags.index(True) if any(flags) else -1
    )
    assert info["global_mesh"] is True
    deg = info["degraded"]
    assert deg["dead_workers"] == [] and deg["generations"] == 1
    assert deg["quarantined_histories"] == 0


def test_global_mesh_elle_degenerate_splice_at_lane_boundary(tmp_path):
    """A degenerate elle history (host-oracle fallback) placed EXACTLY
    at the lane boundary — the first index of lane 1's block, which is
    also a device-shard boundary of the global batch — must fold its
    host verdict into the collective reduction on the process that owns
    it, and the merged verdict must still equal the serial oracle."""
    from test_fuzz_elle_device import fuzz_history

    from jepsen_tpu.checkers.elle import elle_mops_for
    from jepsen_tpu.parallel.pipeline import check_sources

    class _SH:
        def __init__(self, ops):
            self.ops = ops

    pool = [fuzz_history(seed, n_txns=10) for seed in range(24)]
    degen = [ops for ops in pool if elle_mops_for(ops)[1].degenerate]
    live = [ops for ops in pool if not elle_mops_for(ops)[1].degenerate]
    assert degen and len(live) >= 5
    # 6 sources, chunk=8 → one chunk, 2 lanes of b_l=3: index 3 is the
    # first row of lane 1's block (the shard boundary)
    base = [_SH(o) for o in (live[:3] + [degen[0]] + live[3:5])]
    files = _write(tmp_path, base, tag="e")
    serial, _ = check_sources("elle", files, chunk=8, serial=True)
    flags = [r["elle"]["valid?"] is not True for r in serial]
    verdict, info = run_multiprocess_check(
        "elle", files, 2, devices_per_proc=1, chunk=8, reduce=True,
        global_mesh=True, timeout_s=420,
    )
    assert verdict["histories"] == len(files)
    assert verdict["invalid"] == sum(flags)
    assert verdict["first_invalid"] == (
        flags.index(True) if any(flags) else -1
    )


def test_global_mesh_dead_worker_generation_respawn(tmp_path):
    """Host death mid-run on the GLOBAL mesh: worker 1 of 2 dies, which
    wedges the survivor inside collectives — the launcher kills the
    generation, respawns a 1-process fleet on a fresh coordinator,
    skips the ledgered stripe, and the final verdict equals the
    no-fault oracle with the degradation named in the provenance."""
    from jepsen_tpu.parallel.pipeline import check_sources

    base = synth_batch(8, SynthSpec(n_ops=30, seed=5), lost=1)
    files = _write(tmp_path, base)
    serial, _ = check_sources("queue", files, chunk=4, serial=True)
    flags = _queue_flags(serial)
    os.environ["JEPSEN_TPU_DIST_DIE_PID"] = "1"
    try:
        verdict, info = run_multiprocess_check(
            "queue", files, 2, devices_per_proc=1, chunk=4, reduce=True,
            global_mesh=True, timeout_s=420,
        )
    finally:
        del os.environ["JEPSEN_TPU_DIST_DIE_PID"]
    deg = info["degraded"]
    assert deg["dead_workers"] == [1]
    assert deg["generations"] >= 2
    assert deg["final_procs"] == 1
    assert deg["requeued_stripes"] and not deg["quarantined_stripes"]
    assert deg["quarantined_histories"] == 0
    assert verdict["histories"] == len(files)
    assert verdict["invalid"] == sum(flags)
    assert verdict["first_invalid"] == (
        flags.index(True) if any(flags) else -1
    )


def test_global_mesh_rejects_bad_configs(tmp_path):
    """Loud validation: global-mesh mode requires the collective
    reduction, a workload with a wired collective program, and a seq
    axis that divides across the fleet."""
    base = synth_batch(4, SynthSpec(n_ops=20, seed=5))
    files = _write(tmp_path, base)
    with pytest.raises(ValueError, match="reduce"):
        run_multiprocess_check(
            "queue", files, 2, global_mesh=True, reduce=False
        )
    with pytest.raises(ValueError, match="workload"):
        run_multiprocess_check(
            "stream", files, 2, global_mesh=True, reduce=True
        )
    with pytest.raises(ValueError, match="multiple"):
        run_multiprocess_check(
            "queue", files, 2, global_mesh=True, reduce=True, seq=3
        )
    with pytest.raises(ValueError, match="seq"):
        run_multiprocess_check(
            "queue", files, 2, devices_per_proc=1, global_mesh=True,
            reduce=True, seq=4,
        )


def test_relative_source_paths_resolve_in_workers(tmp_path, monkeypatch):
    """Workers run with cwd=repo, so a caller's RELATIVE store paths
    (the CLI invoked from inside a store tree) must be anchored to the
    launcher's cwd before they enter the manifest — in both the
    elastic and global-mesh modes.  Pre-fix the elastic run silently
    quarantined everything to unknown and the global mesh crashed."""
    from jepsen_tpu.parallel.pipeline import check_sources

    base = synth_batch(4, SynthSpec(n_ops=30, seed=11), lost=1)
    files = _write(tmp_path, base)
    serial, _ = check_sources("queue", files, chunk=2, serial=True)
    flags = _queue_flags(serial)
    monkeypatch.chdir(tmp_path)
    rel = sorted(
        os.path.join(".", f) for f in os.listdir(".") if f.endswith(".jsonl")
    )
    for mode_kw in ({"mesh": True}, {"global_mesh": True}):
        verdict, info = run_multiprocess_check(
            "queue", rel, 2, chunk=2, reduce=True, timeout_s=300,
            **mode_kw,
        )
        assert verdict["histories"] == len(base)
        assert verdict.get("quarantined", 0) == 0
        assert verdict["invalid"] == flags.count(True), mode_kw
