"""Multi-process checker plane: ``init_multihost`` over localhost.

``jax.distributed`` joins N OS processes (each holding its share of
virtual CPU devices) into one 8-device runtime and the sharded
quorum-queue check runs pod-style over the global ``(hist, seq)`` mesh.
This is the DCN story of SURVEY.md §2.4 exercised for real — process 0
is the coordinator — with the verdict differentially checked against the
single-process CPU reference.  Parametrized over pod shapes: 2×4 (two
hosts) and 4×2 (four hosts, every mesh row crossing a process
boundary).
"""

import json
import socket
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_WORKER = r"""
import json, os, sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={sys.argv[3]}"
)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

port, pid, n_procs = sys.argv[1], int(sys.argv[2]), int(sys.argv[4])

from jepsen_tpu.parallel.distributed import (
    global_checker_mesh,
    init_multihost,
    is_coordinator,
)

init_multihost(f"localhost:{port}", num_processes=n_procs, process_id=pid)
assert jax.process_count() == n_procs, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert is_coordinator() == (pid == 0)

from jepsen_tpu.history.encode import pack_histories
from jepsen_tpu.history.synth import SynthSpec, synth_batch
from jepsen_tpu.parallel import shard_packed, sharded_total_queue

# identical data on both processes (same seed) -> consistent global array
shs = synth_batch(8, SynthSpec(n_ops=40, seed=7), lost=2)
packed = pack_histories([s.ops for s in shs], length=128)
mesh = global_checker_mesh(seq=2)
assert dict(mesh.shape) == {"hist": 4, "seq": 2}
sharded = shard_packed(packed, mesh)
tq = sharded_total_queue(sharded, mesh)

# every process sees the same global verdict via process_allgather
from jax.experimental import multihost_utils

valid = [
    bool(v) for v in multihost_utils.process_allgather(tq.valid, tiled=True)
]
lost = int((multihost_utils.process_allgather(tq.lost, tiled=True) > 0).sum())

# seq-parallel stream program pod-style: its phase combines and boundary
# ppermute now cross the process boundary (the DCN path for real pods)
from jepsen_tpu.checkers.stream_lin import pack_stream_histories
from jepsen_tpu.history.synth import StreamSynthSpec, synth_stream_batch
from jepsen_tpu.parallel import sharded_stream_lin

sshs = synth_stream_batch(4, StreamSynthSpec(n_ops=40, seed=3), lost=1)
sbatch = pack_stream_histories([s.ops for s in sshs])
st = sharded_stream_lin(sbatch, mesh)
svalid = [
    bool(v) for v in multihost_utils.process_allgather(st.valid, tiled=True)
]
print(
    json.dumps(
        {"pid": pid, "valid": valid, "lost": lost, "stream_valid": svalid}
    ),
    flush=True,
)
"""


import pytest


@pytest.mark.parametrize(
    "n_procs,devices_per_proc", [(2, 4), (4, 2)],
    ids=["pod2x4", "pod4x2"],
)
def test_init_multihost_sharded_check(n_procs, devices_per_proc):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = [
        subprocess.Popen(
            [
                sys.executable, "-c", _WORKER, str(port), str(pid),
                str(devices_per_proc), str(n_procs),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=REPO,
        )
        for pid in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # a failed/hung worker must not orphan its sibling (it would sit
        # inside jax.distributed.initialize holding the coordinator port)
        for p in procs:
            if p.poll() is None:
                p.kill()

    # every process computed the same global verdict
    for o in outs[1:]:
        assert o["valid"] == outs[0]["valid"]
        assert o["lost"] == outs[0]["lost"]
        assert o["stream_valid"] == outs[0]["stream_valid"]

    # stream differential (the lost append must be flagged pod-wide)
    from jepsen_tpu.checkers.stream_lin import check_stream_lin_cpu
    from jepsen_tpu.history.synth import StreamSynthSpec, synth_stream_batch

    sshs = synth_stream_batch(4, StreamSynthSpec(n_ops=40, seed=3), lost=1)
    sref = [check_stream_lin_cpu(s.ops)["valid?"] for s in sshs]
    assert outs[0]["stream_valid"] == sref
    assert not all(sref)

    # differential: single-process CPU reference on the same histories
    from jepsen_tpu.checkers.total_queue import check_total_queue_cpu
    from jepsen_tpu.history.synth import SynthSpec, synth_batch

    shs = synth_batch(8, SynthSpec(n_ops=40, seed=7), lost=2)
    ref = [check_total_queue_cpu(s.ops) for s in shs]
    assert outs[0]["valid"] == [r["valid?"] for r in ref]
    assert outs[0]["lost"] == sum(r["lost-count"] for r in ref)
