"""Fleet memory (ISSUE 19): prefix-resume ≡ from-zero differentially,
content-addressed section dedup, and the per-config baseline layer.

The prefix-checkpoint index (``history/prefix_index.py``) lets a
re-submitted history resume its segmented check from the deepest
published anchor whose ``(prefix_sha256, offset)`` matches the new
file's own bytes.  Everything here is differential: a fleet-resumed
check must reach the BYTE-IDENTICAL per-family verdict of a from-zero
check of the same file — including when the shared prefix already
refutes, and when the file diverges one op after the deepest anchor
(the resume must fall back to the shallower match, never serve a
stale carry).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from jepsen_tpu.checkers.segmented import segmented_check_file  # noqa: E402
from jepsen_tpu.history.prefix_index import (  # noqa: E402
    PrefixCheckpointIndex,
)
from jepsen_tpu.history.store import (  # noqa: E402
    _json_default,
    write_history_jsonl,
)
from jepsen_tpu.history.synth import (  # noqa: E402
    ElleSynthSpec,
    StreamSynthSpec,
    SynthSpec,
    synth_elle_history,
    synth_history,
    synth_stream_history,
)

SEG = 100

_FAMS = ("queue", "linear", "stream", "elle", "mutex", "valid?")


def norm(x):
    return json.loads(json.dumps(x, default=_json_default))


def verdicts(result):
    return {f: norm(result[f]) for f in _FAMS if f in result}


def write_corpus(workload, path, n=400, seed=5, **anomalies):
    if workload == "queue":
        sh = synth_history(SynthSpec(n_ops=n, seed=seed, **anomalies))
    elif workload == "stream":
        sh = synth_stream_history(
            StreamSynthSpec(n_ops=n, seed=seed, **anomalies)
        )
    else:
        sh = synth_elle_history(
            ElleSynthSpec(n_txns=max(40, n // 3), seed=seed, **anomalies)
        )
    path.parent.mkdir(parents=True, exist_ok=True)
    write_history_jsonl(path, sh.ops)
    return path


def check(path, idx=None, **kw):
    return segmented_check_file(
        path, segment_ops=SEG, device=False, prefix_index=idx, **kw
    )


# ---------------------------------------------------------------------------
# prefix-resume ≡ from-zero, per family
# ---------------------------------------------------------------------------


class TestPrefixResumeDifferential:
    @pytest.mark.parametrize("workload", ["queue", "stream", "elle"])
    def test_resubmitted_history_resumes_and_verdicts_match(
        self, tmp_path, workload
    ):
        hp = write_corpus(workload, tmp_path / "history.jsonl")
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        r_zero = check(hp)  # from-zero pin, no fleet involvement
        r_arm = check(hp, idx)  # publishes anchors
        assert "resumed_from_prefix" not in r_arm["segmented"]
        r_fleet = check(hp, idx)  # the re-submission
        prov = r_fleet["segmented"]["resumed_from_prefix"]
        assert prov is not None
        assert prov["offset"] > 0
        assert verdicts(r_fleet) == verdicts(r_zero) == verdicts(r_arm)

    @pytest.mark.parametrize(
        "workload,anomalies",
        [
            ("queue", {"lost": 1, "unexpected": 1}),
            ("stream", {"lost": 1, "divergent": 1}),
            ("elle", {"g1c_cycle": 1}),
        ],
    )
    def test_invalid_history_resumes_to_identical_refutation(
        self, tmp_path, workload, anomalies
    ):
        hp = write_corpus(
            workload, tmp_path / "history.jsonl", **anomalies
        )
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        r_zero = check(hp)
        assert r_zero["valid?"] is False
        check(hp, idx)
        r_fleet = check(hp, idx)
        assert r_fleet["segmented"]["resumed_from_prefix"] is not None
        assert verdicts(r_fleet) == verdicts(r_zero)

    def test_extension_resumes_from_parents_anchors(self, tmp_path):
        """A child history that extends a checked parent byte-for-byte
        resumes from the parent's deepest FULL-segment anchor."""
        parent = write_corpus("queue", tmp_path / "parent.jsonl", n=300)
        child = tmp_path / "child.jsonl"
        extra = synth_history(SynthSpec(n_ops=80, seed=77)).ops
        base = parent.read_bytes()
        with open(child, "wb") as fh:
            fh.write(base)
            for op in extra:
                fh.write((json.dumps(op.to_json()) + "\n").encode())
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        check(parent, idx)
        r_zero = check(child)
        r_fleet = check(child, idx)
        prov = r_fleet["segmented"]["resumed_from_prefix"]
        assert prov is not None
        # anchored strictly inside the shared parent bytes
        assert 0 < prov["offset"] <= len(base)
        assert verdicts(r_fleet) == verdicts(r_zero)

    def test_invalid_shared_prefix_still_refutes_extension(
        self, tmp_path
    ):
        """The carry must preserve refutation across a resume: a child
        extending an already-invalid parent prefix with healthy ops
        checks invalid, via the fleet anchor, with the identical
        verdict to from-zero."""
        parent = write_corpus(
            "queue", tmp_path / "parent.jsonl", n=300, unexpected=1
        )
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        r_parent = check(parent, idx)
        assert r_parent["valid?"] is False
        child = tmp_path / "child.jsonl"
        healthy_tail = synth_history(SynthSpec(n_ops=60, seed=31)).ops
        with open(child, "wb") as fh:
            fh.write(parent.read_bytes())
            for op in healthy_tail:
                fh.write(
                    (json.dumps(norm_op(op)) + "\n").encode()
                )
        r_zero = check(child)
        assert r_zero["valid?"] is False
        r_fleet = check(child, idx)
        assert r_fleet["segmented"]["resumed_from_prefix"] is not None
        assert verdicts(r_fleet) == verdicts(r_zero)

    def test_divergence_after_deepest_anchor_falls_back(self, tmp_path):
        """A file sharing the parent's bytes only up to segment j must
        resume from segment j's anchor, not the deeper ones published
        past the divergence point — and never serve a stale carry."""
        parent = write_corpus("queue", tmp_path / "parent.jsonl", n=400)
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        check(parent, idx)

        # find segment boundaries by line count: SEG lines per segment
        lines = parent.read_bytes().splitlines(keepends=True)
        shared = b"".join(lines[: 3 * SEG + 1])  # one op past seg 2
        child = tmp_path / "child.jsonl"
        tail = synth_history(SynthSpec(n_ops=150, seed=99)).ops
        with open(child, "wb") as fh:
            fh.write(shared)
            for op in tail:
                fh.write((json.dumps(norm_op(op)) + "\n").encode())
        r_zero = check(child)
        r_fleet = check(child, idx)
        prov = r_fleet["segmented"]["resumed_from_prefix"]
        assert prov is not None
        # deepest SERVABLE anchor is segment 2 (bytes diverge inside
        # segment 3): offset is exactly the 3*SEG-line boundary
        boundary = len(b"".join(lines[: 3 * SEG]))
        assert prov["offset"] == boundary
        assert prov["segment_idx"] == 2
        assert verdicts(r_fleet) == verdicts(r_zero)

    def test_divergent_byte_refuses_deeper_anchor_entirely(
        self, tmp_path
    ):
        """Mutating a byte INSIDE the deepest anchored prefix must
        unmatch that anchor (hash pass sees different bytes) and serve
        a shallower one — the served offset always hash-matches the
        new file's own bytes."""
        parent = write_corpus("queue", tmp_path / "parent.jsonl", n=400)
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        check(parent, idx)
        raw = bytearray(parent.read_bytes())
        lines = bytes(raw).splitlines(keepends=True)
        boundary2 = len(b"".join(lines[: 2 * SEG]))
        # flip a digit inside segment 2 (between anchors 1 and 2),
        # keeping JSON valid: find a "time" digit after boundary2
        child = tmp_path / "child.jsonl"
        mut = bytes(raw[:boundary2]) + b"".join(
            _bump_time(ln) if i == 0 else ln
            for i, ln in enumerate(lines[2 * SEG:])
        )
        child.write_bytes(mut)
        r_zero = check(child)
        r_fleet = check(child, idx)
        prov = r_fleet["segmented"]["resumed_from_prefix"]
        assert prov is not None
        assert prov["offset"] == boundary2
        assert prov["segment_idx"] == 1
        assert verdicts(r_fleet) == verdicts(r_zero)

    def test_local_checkpoint_wins_over_fleet_index(self, tmp_path):
        """resume=True with a valid local checkpoint must use it (it
        is at least as deep for the same source) — fleet provenance
        absent, classic ``resumed`` provenance present.  The dying
        child runs against a COLD index so its own publishes are the
        only anchors: local checkpoint and fleet anchor sit at the
        same depth and the local one must win."""
        import subprocess

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=400)
        idx_dir = tmp_path / "idx"
        idx = PrefixCheckpointIndex(idx_dir)
        code = (
            "import sys; sys.path.insert(0, sys.argv[1])\n"
            "from jepsen_tpu.checkers.segmented import "
            "segmented_check_file\n"
            f"segmented_check_file(sys.argv[2], segment_ops={SEG}, "
            f"device=False, prefix_index=sys.argv[3])\n"
        )
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            JEPSEN_TPU_SEG_DIE_AFTER="2",
        )
        p = subprocess.run(
            [sys.executable, "-c", code, str(REPO), str(hp),
             str(idx_dir)],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert p.returncode == 137, p.stderr[-500:]
        r = check(hp, idx, resume=True)
        assert r["segmented"]["resumed"] is True
        assert "resumed_from_prefix" not in r["segmented"]

    def test_contract_mismatch_never_served(self, tmp_path):
        """Anchors are contract-scoped: different opts or segment_ops
        must miss the index entirely."""
        hp = write_corpus("queue", tmp_path / "history.jsonl", n=400)
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        check(hp, idx)
        r_opts = segmented_check_file(
            hp, segment_ops=SEG, device=False, prefix_index=idx,
            opts={"delivery": "at-least-once"},
        )
        assert "resumed_from_prefix" not in r_opts["segmented"]
        r_seg = segmented_check_file(
            hp, segment_ops=50, device=False, prefix_index=idx,
        )
        assert "resumed_from_prefix" not in r_seg["segmented"]

    def test_torn_index_entry_falls_back_to_next_deepest(
        self, tmp_path
    ):
        """A torn fleet entry is refused loudly and the next-deepest
        valid anchor serves — provenance records the refusal."""
        hp = write_corpus("queue", tmp_path / "history.jsonl", n=400)
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        check(hp, idx)
        entries = sorted(
            (tmp_path / "idx").rglob("*.json"), key=lambda p: p.name
        )
        assert len(entries) >= 2
        deepest = entries[-1]
        deepest.write_bytes(deepest.read_bytes()[:40])  # tear it
        r_zero = check(hp)
        r_fleet = check(hp, idx)
        prov = r_fleet["segmented"]["resumed_from_prefix"]
        assert prov is not None
        assert prov.get("refused_deeper")
        assert verdicts(r_fleet) == verdicts(r_zero)

    def test_jtc_rows_substrate_resumes_by_row_prefix(self, tmp_path):
        """The queue family's zero-parse ``.jtc`` path uses row-prefix
        anchors: a re-check over the packed substrate resumes and
        reaches the identical verdict."""
        from jepsen_tpu.history.columnar import pack_jtc

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=400)
        assert pack_jtc(hp) is not None
        idx = PrefixCheckpointIndex(tmp_path / "idx")
        r_zero = check(hp)
        assert r_zero["segmented"]["substrate"] == "jtc"
        check(hp, idx)
        r_fleet = check(hp, idx)
        prov = r_fleet["segmented"]["resumed_from_prefix"]
        assert prov is not None
        assert prov["substrate"] == "jtc"
        assert verdicts(r_fleet) == verdicts(r_zero)


def norm_op(op):
    """An Op as its JSONL dict (the store's writer shape)."""
    return op.to_json()


def _bump_time(line: bytes) -> bytes:
    d = json.loads(line)
    d["time"] = int(d.get("time") or 0) + 1
    return json.dumps(d).encode() + b"\n"


# ---------------------------------------------------------------------------
# content-addressed sections: round-trip, dedup, GC refusal
# ---------------------------------------------------------------------------


class TestSectionStore:
    def _pack(self, path):
        from jepsen_tpu.history.columnar import jtc_path_for, pack_jtc

        assert pack_jtc(path) is not None
        return jtc_path_for(path)

    def test_publish_materialize_bit_exact(self, tmp_path):
        from jepsen_tpu.history.cas import SectionStore

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=300)
        jtc = self._pack(hp)
        original = jtc.read_bytes()
        cas = SectionStore(tmp_path / "cas")
        acc = cas.publish_jtc(jtc, ref="run0")
        assert acc["sections"] >= 1
        man = jtc.with_name(jtc.name + ".casman.json")
        assert man.is_file()
        jtc.unlink()  # dehydrate
        out = cas.materialize(man)
        assert hashlib.sha256(out.read_bytes()).hexdigest() == \
            hashlib.sha256(original).hexdigest()

    def test_content_key_from_manifest_matches_jtc(self, tmp_path):
        from jepsen_tpu.history.cas import SectionStore
        from jepsen_tpu.history.columnar import read_jtc

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=300)
        jtc = self._pack(hp)
        key = read_jtc(jtc)[0].content_key()
        cas = SectionStore(tmp_path / "cas")
        cas.publish_jtc(jtc, ref="run0")
        man = jtc.with_name(jtc.name + ".casman.json")
        assert cas.content_key_from_manifest(man) == key

    def test_shared_prefix_corpus_dedups(self, tmp_path):
        """Two substrates sharing a long byte prefix (parent + its
        extension) share chunk objects: honest ratio > 1."""
        from jepsen_tpu.history.cas import SectionStore, dedup_stats

        parent = write_corpus(
            "queue", tmp_path / "a" / "history.jsonl", n=9000
        )
        child_dir = tmp_path / "b"
        child_dir.mkdir()
        child = child_dir / "history.jsonl"
        with open(child, "wb") as fh:
            fh.write(parent.read_bytes())
            for op in synth_history(SynthSpec(n_ops=40, seed=2)).ops:
                fh.write((json.dumps(norm_op(op)) + "\n").encode())
        cas = SectionStore(tmp_path / "cas")
        for i, p in enumerate((parent, child)):
            cas.publish_jtc(self._pack(p), ref=f"run{i}")
        dd = dedup_stats(tmp_path, cas)
        assert dd["manifests"] == 2
        assert dd["ratio"] > 1.0
        assert dd["logical_bytes"] > dd["addressed_bytes"]
        assert dd["missing_objects"] == 0

    def test_unrelated_corpus_reports_honest_one(self, tmp_path):
        from jepsen_tpu.history.cas import SectionStore, dedup_stats

        a = write_corpus(
            "queue", tmp_path / "a" / "history.jsonl", n=200, seed=1
        )
        b = write_corpus(
            "queue", tmp_path / "b" / "history.jsonl", n=200, seed=2
        )
        cas = SectionStore(tmp_path / "cas")
        for i, p in enumerate((a, b)):
            cas.publish_jtc(self._pack(p), ref=f"run{i}")
        dd = dedup_stats(tmp_path, cas)
        assert dd["ratio"] == pytest.approx(1.0, abs=0.01)

    def test_gc_refuses_live_refs_even_forced(self, tmp_path):
        from jepsen_tpu.history.cas import SectionStore

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=300)
        cas = SectionStore(tmp_path / "cas")
        cas.publish_jtc(self._pack(hp), ref="live")
        live = cas.stats()["objects"]
        assert live > 0
        out = cas.gc(force=True)
        assert out["collected"] == 0
        assert out["refused_live"] == live
        assert cas.stats()["objects"] == live
        # dropping the ref releases them for a normal collect
        cas.drop_ref("live")
        out2 = cas.gc()
        assert out2["collected"] == live
        assert cas.stats()["objects"] == 0

    def test_store_gc_cli_reports_and_refuses(self, tmp_path):
        import subprocess

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=300)
        from jepsen_tpu.history.cas import SectionStore

        cas = SectionStore(tmp_path / "cas")
        cas.publish_jtc(self._pack(hp), ref="live")
        p = subprocess.run(
            [sys.executable, str(REPO / "tools" / "store_gc.py"),
             str(tmp_path), "--collect", "--force", "--verify"],
            capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )
        assert p.returncode == 0, p.stderr[-500:]
        out = json.loads(p.stdout)
        assert out["dedup"]["manifests"] == 1
        assert out["verify"]["ok"] is True
        assert out["gc"]["collected"] == 0
        assert out["gc"]["refused_live"] > 0


# ---------------------------------------------------------------------------
# shrink replay over the fleet index
# ---------------------------------------------------------------------------


class TestShrinkReplay:
    def test_shrink_window_finds_tail_cycle(self, tmp_path):
        from jepsen_tpu.fuzz.replay import shrink_window

        hp = tmp_path / "history.jsonl"
        h = synth_elle_history(
            ElleSynthSpec(n_txns=150, seed=7, g1c_cycle=1)
        )
        write_history_jsonl(hp, h.ops)
        n = sum(1 for _ in open(hp, "rb"))
        stats = shrink_window(
            hp, tmp_path / "work", workload="elle", segment_ops=50,
            opts={}, prefix_index=str(tmp_path / "idx"), confirm=2,
        )
        assert stats.n_ops == n
        # the g1c cycle sits at the tail: the minimal red prefix is
        # nearly the whole history, and bisection proved it
        assert stats.min_red_ops > n // 2
        assert stats.resumed_probes > 0
        assert all(
            p.red for p in stats.probes if p.n_ops >= stats.min_red_ops
        )

    def test_shrink_window_refuses_green(self, tmp_path):
        from jepsen_tpu.fuzz.replay import shrink_window

        hp = write_corpus("queue", tmp_path / "history.jsonl", n=200)
        with pytest.raises(ValueError):
            shrink_window(
                hp, tmp_path / "work", workload="queue",
                segment_ops=50, opts={},
            )


# ---------------------------------------------------------------------------
# baselines: seeded regression flags, flat series stays quiet
# ---------------------------------------------------------------------------


class TestBaselines:
    def _store(self, tmp_path, p50s, p99_mult=3.0):
        import shutil

        root = tmp_path / "store"
        if root.exists():
            shutil.rmtree(root)
        for i, p50 in enumerate(p50s):
            d = root / "camp" / f"run_{i:04d}"
            d.mkdir(parents=True)
            (d / "results.json").write_text(json.dumps({"valid?": True}))
            (d / "report.json").write_text(json.dumps({
                "run": d.name, "valid?": True, "ops": 10,
                "latency-ms": {"p50": p50, "p99": p50 * p99_mult},
            }))
        return root

    def test_seeded_regression_flags_loudly(self, tmp_path):
        from jepsen_tpu.obs.metrics import Registry
        from jepsen_tpu.report.baselines import collect_baselines
        from jepsen_tpu.report.index import build_store_index

        root = self._store(tmp_path, [4.0, 4.1, 3.9, 4.0, 14.0])
        reg = Registry()
        doc = collect_baselines(root, registry=reg)
        assert doc["n_flags"] >= 1
        assert any(
            f["flag"] == "regression"
            and "latency_p50_ms" in f["series"]
            for f in doc["flags"]
        )
        assert reg.value("fleet.regression_flags") >= 1
        idx = build_store_index(root, render_missing=False)
        html = idx.read_text()
        assert "REGRESSION" in html
        assert (root / "baselines.json").is_file()

    def test_flat_series_never_flags(self, tmp_path):
        from jepsen_tpu.report.baselines import collect_baselines

        root = self._store(tmp_path, [4.0, 4.0, 4.0, 4.0, 4.0])
        doc = collect_baselines(root, registry=False)
        assert doc["n_flags"] == 0

    def test_improvement_is_not_a_regression(self, tmp_path):
        from jepsen_tpu.report.baselines import collect_baselines

        root = self._store(tmp_path, [4.0, 4.1, 3.9, 4.0, 1.0])
        doc = collect_baselines(root, registry=False)
        assert doc["n_flags"] == 0
        assert any(
            v.get("flag") == "improvement"
            for v in doc["series"].values()
        )

    def test_short_series_never_baselines(self, tmp_path):
        from jepsen_tpu.report.baselines import collect_baselines

        root = self._store(tmp_path, [4.0, 40.0])
        doc = collect_baselines(root, registry=False)
        assert doc["n_flags"] == 0

    def test_valid_rate_flip_flags(self, tmp_path):
        """A config whose priors were unanimously valid flags loudly
        on the first invalid run."""
        from jepsen_tpu.report.baselines import collect_baselines

        root = self._store(tmp_path, [4.0, 4.0, 4.0, 4.0, 4.0])
        last = root / "camp" / "run_0004"
        (last / "report.json").write_text(json.dumps({
            "run": "run_0004", "valid?": False, "ops": 10,
            "latency-ms": {"p50": 4.0, "p99": 12.0},
        }))
        (last / "results.json").write_text(
            json.dumps({"valid?": False})
        )
        doc = collect_baselines(root, registry=False)
        assert any(
            f["flag"] == "regression" and "valid_rate" in f["series"]
            for f in doc["flags"]
        )


# ---------------------------------------------------------------------------
# the verdict cache seeds from CAS manifests (dehydrated runs)
# ---------------------------------------------------------------------------


class TestCasSeeding:
    def test_dehydrated_run_still_seeds_content_refs(self, tmp_path):
        from jepsen_tpu.history.cas import SectionStore
        from jepsen_tpu.history.columnar import (
            jtc_path_for,
            pack_jtc,
            read_jtc,
        )
        from jepsen_tpu.report.index import run_content_refs

        d = tmp_path / "run0"
        d.mkdir()
        hp = write_corpus("queue", d / "history.jsonl", n=200)
        (d / "results.json").write_text(json.dumps({"valid?": True}))
        assert pack_jtc(hp) is not None
        jtc = jtc_path_for(hp)
        key = read_jtc(jtc)[0].content_key()
        cas = SectionStore(tmp_path / "cas")
        cas.publish_jtc(jtc, ref="run0")
        # dehydrate: the .jtc AND the raw history leave disk
        jtc.unlink()
        hp.unlink()
        refs = list(run_content_refs(tmp_path))
        assert len(refs) == 1
        got_key, workload, _opts, verdict, rel = refs[0]
        assert got_key == key
        assert workload == "queue"
        assert verdict["valid?"] is True
        assert rel == "run0"

    def test_stale_manifest_never_seeds(self, tmp_path):
        from jepsen_tpu.history.cas import SectionStore
        from jepsen_tpu.history.columnar import jtc_path_for, pack_jtc
        from jepsen_tpu.report.index import run_content_refs

        d = tmp_path / "run0"
        d.mkdir()
        hp = write_corpus("queue", d / "history.jsonl", n=200)
        (d / "results.json").write_text(json.dumps({"valid?": True}))
        assert pack_jtc(hp) is not None
        jtc = jtc_path_for(hp)
        cas = SectionStore(tmp_path / "cas")
        cas.publish_jtc(jtc, ref="run0")
        jtc.unlink()
        # the source is REWRITTEN after dehydration: the manifest's
        # stamp no longer matches and the run must not seed
        write_corpus("queue", hp, n=220, seed=9)
        refs = list(run_content_refs(tmp_path))
        assert refs == []
