"""Dress rehearsal: ``test --db rabbitmq`` wiring over real OS processes.

The reference's integration bar is a real local cluster
(``docker/docker-compose.yml:24-35``); with no docker in this image, the
closest honest equivalent runs every *live* piece together — the real
runner, the C++ native clients over real TCP, ``RabbitMQDB``'s boot
choreography, and the nemesis — against mini-broker OS processes via
:class:`LocalProcTransport` (``harness/localcluster.py``), which maps the
SSH command stream onto process actions (spawn / SIGKILL / SIGSTOP /
quorum-loss partitions / admin depth queries).

Each piece is unit-tested elsewhere; these tests exist because round-2
review found they had never *executed together*.
"""

import tempfile

import pytest

from jepsen_tpu.control.db_rabbitmq import RabbitMQDB
from jepsen_tpu.control.runner import run_test
from jepsen_tpu.harness.localcluster import LocalProcTransport
from jepsen_tpu.suite import DEFAULT_OPTS, build_rabbitmq_test


# native_lib / _reset fixtures come from conftest.py


def _fast_db(t, nodes):
    return RabbitMQDB(
        t, nodes, primary_wait_s=0.2, secondary_wait_s=0.2,
        join_stagger_max_s=0.1,
    )


def test_full_queue_run_three_node_partition(_reset):
    """The flagship assembly: 3 REPLICATED broker processes (Raft quorum
    commit), 4 native clients, the partition nemesis cutting real
    node-to-node links (leader step-down / failover / heal catch-up
    underneath), drain across every host — valid verdict and queues
    drained to zero (the CI cross-check, ci/jepsen-test.sh:144-155).
    Triage-retried (tests/_live.py)."""
    from _live import run_live_with_triage

    state = {}

    def build():
        t = LocalProcTransport(n_nodes=3)
        nodes = t.nodes
        opts = {
            **DEFAULT_OPTS,
            "rate": 120.0,
            "time-limit": 3.0,
            "time-before-partition": 0.6,
            "partition-duration": 1.0,
            "recovery-sleep": 0.8,
            "publish-confirm-timeout": 1.5,
        }
        db = _fast_db(t, nodes)
        state["db"], state["nodes"] = db, nodes
        test = build_rabbitmq_test(
            opts=opts, nodes=nodes, transport=t, db=db,
            checker_backend="cpu", store_root=tempfile.mkdtemp(),
            workload="queue", concurrency=4,
        )
        return test, t

    def checks(run):
        q = run.results["queue"]
        assert q["attempt-count"] > 30
        # a partition actually fired: the nemesis completed a START op
        # whose value records the grudge map (node -> cut peers)
        from jepsen_tpu.history.ops import NEMESIS_PROCESS, OpF, OpType

        cuts = [
            op for op in run.history
            if op.process == NEMESIS_PROCESS
            and op.f == OpF.START
            and op.type == OpType.INFO
            and "127.0.0.1" in str(op.value)
        ]
        assert cuts, "nemesis never cut anything"
        # CI cross-check: every queue drained to zero on every node
        # (settled read: follower replicas apply the final acks with a
        # small lag — same reason the reference CI polls in a loop)
        for n in state["nodes"]:
            lengths = state["db"].queue_lengths_settled(n)
            assert all(v == 0 for v in lengths.values()), (n, lengths)

    run_live_with_triage(build, expect="valid", checks=checks)


def _leader_partition_build(seed_bug):
    """Builder for one replicated 3-node cluster with the
    leader-targeting partition (fresh per triage attempt)."""
    t = LocalProcTransport(n_nodes=3, seed_bug=seed_bug)
    nodes = t.nodes
    opts = {
        **DEFAULT_OPTS,
        "rate": 120.0,
        "time-limit": 5.0,
        "time-before-partition": 0.8,
        "partition-duration": 1.5,
        "recovery-sleep": 1.0,
        "publish-confirm-timeout": 2.5,
        "network-partition": "partition-leader",
    }
    test = build_rabbitmq_test(
        opts=opts, nodes=nodes, transport=t, db=_fast_db(t, nodes),
        checker_backend="cpu", store_root=tempfile.mkdtemp(),
        workload="queue", concurrency=4,
    )
    return test, t


def test_partition_leader_green_without_bug(_reset):
    """Isolating the Raft leader repeatedly is survivable by a correct
    replicated cluster: step-down, majority failover, heal catch-up —
    valid verdict, nothing lost.  Triage-retried (tests/_live.py)."""
    from _live import run_live_with_triage

    def checks(run):
        assert run.results["queue"]["lost-count"] == 0

    run_live_with_triage(
        lambda: _leader_partition_build(None), expect="valid",
        checks=checks,
    )


def test_seeded_confirm_before_quorum_caught_end_to_end(_reset):
    """VERDICT r3 #2's red-run proof: every node runs the
    confirm-before-quorum bug (publish acknowledged on leader-local
    append); isolating the leader then healing truncates its confirmed
    tail, and total-queue must flag the acknowledged writes as LOST —
    through the full live assembly (runner, native TCP clients, nemesis,
    drain, checker).  Triage-retried: flake retries never launder the
    red — a genuinely-green attempt is itself the retryable anomaly."""
    from _live import run_live_with_triage

    def checks(run):
        assert run.results["queue"]["lost-count"] > 0, run.results["queue"]

    run_live_with_triage(
        lambda: _leader_partition_build("confirm-before-quorum"),
        expect="invalid",
        checks=checks,
    )


def test_full_stream_run_single_node(_reset):
    """The stream family through the same live assembly on a single
    non-replicated node (the fast smoke path; the replicated 3-node
    variant with a partition is below): native stream client over real
    TCP, offset-proof full read, stream checker verdict."""
    t = LocalProcTransport(n_nodes=1)
    try:
        nodes = t.nodes
        opts = {
            **DEFAULT_OPTS,
            "rate": 80.0,
            "time-limit": 3.0,
            "time-before-partition": 30.0,  # no partition on 1 node
            "partition-duration": 0.1,
            "recovery-sleep": 0.3,
            "publish-confirm-timeout": 1.5,
            # a cursor read at the log tail holds its consumer open for
            # the read timeout when nothing arrives; at the default 5 s a
            # few early reads would eat the whole 2 s load window
            "read-timeout": 0.4,
        }
        test = build_rabbitmq_test(
            opts=opts, nodes=nodes, transport=t, db=_fast_db(t, nodes),
            checker_backend="cpu", store_root=tempfile.mkdtemp(),
            workload="stream", concurrency=3,
        )
        run = run_test(test)
        assert run.results["valid?"] is True, run.results
        s = run.results["stream"]
        assert s["attempt-count"] > 10
        assert s["read-value-count"] > 0  # the full read really read
    finally:
        t.close()


def test_kill_is_genuinely_nondurable(_reset, native_lib):
    """The kill mapping SIGKILLs the broker process: in-memory state dies
    with it, and a restarted node comes back empty.  (Real quorum queues
    survive via Raft — this documents the stand-in's limits, and that a
    kill-nemesis run here SHOULD flag loss.)"""
    t = LocalProcTransport(n_nodes=1)
    try:
        node = t.nodes[0]
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        assert t.alive(node)
        d = native_lib.NativeQueueDriver(
            [node], node, connect_retry_ms=3000
        )
        d.setup()
        assert d.enqueue(7, 5.0) is True
        d.close()
        t.run(node, "killall -q -9 beam.smp epmd || true")
        assert not t.alive(node)
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        d2 = native_lib.NativeQueueDriver(
            [node], node, connect_retry_ms=3000
        )
        d2.setup()
        assert d2.dequeue(1.0) is None  # the acked value died with the node
        d2.close()
    finally:
        t.close()


def test_pause_mapping_freezes_and_resumes(_reset, native_lib):
    """SIGSTOP/SIGCONT mapping: a paused node stops confirming (publish
    times out → indeterminate), and resumes where it left off."""
    from jepsen_tpu.client.protocol import DriverTimeout

    t = LocalProcTransport(n_nodes=1)
    try:
        node = t.nodes[0]
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        d = native_lib.NativeQueueDriver([node], node, connect_retry_ms=3000)
        d.setup()
        assert d.enqueue(1, 5.0) is True
        t.run(node, "killall -q -STOP beam.smp || true")
        # SIGSTOP delivery can race one in-flight confirm on a loaded
        # host; the broker is certainly frozen by the second publish
        with pytest.raises(DriverTimeout):
            d.enqueue(2, 0.5)
            d.enqueue(20, 0.5)
        t.run(node, "killall -q -CONT beam.smp || true")
        # the paused-then-resumed broker finishes the in-flight publish;
        # reconnect to a clean channel and the node is fully live again
        d.reconnect()
        assert d.enqueue(3, 5.0) is True
    finally:
        t.close()


def test_full_mutex_run_single_node(_reset):
    """The mutex family live: the single-token quorum-queue lock over a
    real broker process, checked by the owned-mutex WGL engine."""
    t = LocalProcTransport(n_nodes=1)
    try:
        nodes = t.nodes
        opts = {
            **DEFAULT_OPTS,
            "rate": 40.0,
            "time-limit": 2.0,
            "time-before-partition": 30.0,
            "recovery-sleep": 0.4,
            "publish-confirm-timeout": 1.5,
        }
        test = build_rabbitmq_test(
            opts=opts, nodes=nodes, transport=t, db=_fast_db(t, nodes),
            checker_backend="cpu", store_root=tempfile.mkdtemp(),
            workload="mutex", concurrency=3,
        )
        run = run_test(test)
        assert run.results["valid?"] is True, run.results
    finally:
        t.close()


def test_full_elle_run_checks_the_suts_actual_contract(_reset):
    """The elle family live: AMQP tx gives atomic commit visibility but
    no cross-key read isolation, so concurrent txns form genuine G2
    anti-dependency cycles.  The live assembly checks read-committed
    (the SUT's contract — valid), while the same history fails a
    serializable re-check: the checker sees the anomaly either way and
    the LEVEL, not the detection, is what the workload configures."""
    from jepsen_tpu.checkers.elle import check_elle_cpu

    t = LocalProcTransport(n_nodes=1)
    try:
        nodes = t.nodes
        opts = {
            **DEFAULT_OPTS,
            "rate": 80.0,
            "time-limit": 2.0,
            "time-before-partition": 30.0,
            "recovery-sleep": 0.4,
            "publish-confirm-timeout": 1.5,
        }
        test = build_rabbitmq_test(
            opts=opts, nodes=nodes, transport=t, db=_fast_db(t, nodes),
            checker_backend="cpu", store_root=tempfile.mkdtemp(),
            workload="elle", concurrency=3,
        )
        run = run_test(test)
        assert run.results["valid?"] is True, run.results
        assert run.results["elle"]["consistency-model"] == "read-committed"
        # the stricter level on the same recorded history: if concurrency
        # produced G2 cycles (it usually does), serializable flags them
        strict = check_elle_cpu(run.history)
        assert strict["G2-count"] == run.results["elle"]["G2-count"]
    finally:
        t.close()


def test_full_stream_run_three_node_replicated(_reset):
    """The stream family across a 3-node replicated cluster WITH a real
    partition: appends quorum-commit, reads commit through the log
    (linearizable even from lagging followers), offset-proof full read,
    valid verdict."""
    from _live import run_live_with_triage

    def checks(run):
        s = run.results["stream"]
        assert s["attempt-count"] > 10
        assert s["read-value-count"] > 0

    run_live_with_triage(
        lambda: _three_node_build("stream", {"read-timeout": 0.8}),
        expect="valid",
        checks=checks,
    )


def _three_node_build(workload, extra_opts=None, concurrency=3):
    """Builder for one replicated 3-node run (fresh per triage attempt)."""
    t = LocalProcTransport(n_nodes=3)
    nodes = t.nodes
    opts = {
        **DEFAULT_OPTS,
        "rate": 80.0,
        "time-limit": 4.0,
        "time-before-partition": 1.0,
        "partition-duration": 1.2,
        "recovery-sleep": 1.0,
        "publish-confirm-timeout": 2.5,
        **(extra_opts or {}),
    }
    test = build_rabbitmq_test(
        opts=opts, nodes=nodes, transport=t, db=_fast_db(t, nodes),
        checker_backend="cpu", store_root=tempfile.mkdtemp(),
        workload=workload, concurrency=concurrency,
    )
    return test, t


def test_full_elle_run_three_node_replicated(_reset):
    """Elle list-append across a 3-node replicated cluster with a real
    partition: txn appends quorum-commit atomically (TXN log entries),
    per-key reads commit through the log — valid at the SUT's
    contractual read-committed level.  Triage-retried (tests/_live.py)."""
    from _live import run_live_with_triage

    def checks(run):
        assert run.results["elle"]["txn-count"] > 5
        assert run.results["elle"]["consistency-model"] == "read-committed"

    run_live_with_triage(
        lambda: _three_node_build("elle"), expect="valid", checks=checks
    )


def test_full_mutex_run_three_node_replicated(_reset):
    """The mutex family (single-token quorum-queue lock) across a 3-node
    replicated cluster with a real partition: grants/releases are
    replicated queue ops through the leader.

    Triage-retried: a loaded host can stall a token holder past the
    broker's dead-owner window, which revokes the grant (the
    unfenced-lock hazard this mapping documents) — a legitimate verdict,
    but not the correct-operation path this test pins."""
    from _live import run_live_with_triage

    def checks(run):
        # the search ran
        assert run.results["mutex"]["configs-explored"] > 0

    run_live_with_triage(
        lambda: _three_node_build("mutex", {"rate": 40.0}),
        expect="valid",
        checks=checks,
    )


def test_full_fenced_mutex_run_three_node_replicated(_reset):
    """The fenced lock across a 3-node replicated cluster with a real
    partition: grants carry Raft-commit-index tokens, revocations (the
    dead-owner reap that REDS the unfenced family under load) advance
    the fence, and the run checks green against the FencedMutex model —
    the mutex family's green ending (VERDICT r5 weak #2)."""
    from _live import run_live_with_triage
    from jepsen_tpu.history.ops import OpF

    def checks(run):
        assert run.results["mutex"]["model"] == "fenced-mutex"
        assert run.results["mutex"]["configs-explored"] > 0
        # at least one grant actually carried a token
        assert any(
            op.is_ok and op.f == OpF.ACQUIRE and isinstance(op.value, int)
            for op in run.history
        )

    run_live_with_triage(
        lambda: _three_node_build("mutex", {"rate": 40.0, "fenced": True}),
        expect="valid",
        checks=checks,
    )
