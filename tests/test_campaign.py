"""Continuous campaigns under fire (ISSUE 17): the durable trial
ledger, supervisor SIGKILL→resume ≡ one uninterrupted run, incremental
verdict PUSH with torn-subscription replay, service-restart gap
quarantine, auto-grown pins, and the live-stream tailer — all
differential against the serial :class:`SegmentedChecker` oracle."""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from jepsen_tpu.campaign.ledger import (
    LedgerError,
    clear_ledger,
    load_ledger_chain,
    read_ledger,
    write_ledger,
)
from jepsen_tpu.campaign.supervisor import (
    DIE_AFTER_ENV,
    CampaignSupervisor,
    oracle_verdict,
    verdict_fingerprint,
)
from jepsen_tpu.campaign.tail import LiveStreamTailer
from jepsen_tpu.checkers.segmented import SegmentedChecker
from jepsen_tpu.fuzz.pins import append_pin, load_pins, pin_key, replay_pins
from jepsen_tpu.history.columnar import iter_row_blocks
from jepsen_tpu.history.rows import _rows_for
from jepsen_tpu.history.synth import SynthSpec, synth_history
from jepsen_tpu.obs.metrics import Registry
from jepsen_tpu.service import CheckerClient, CheckerServer, RetryPolicy
from jepsen_tpu.service.client import SubscriptionGap
from jepsen_tpu.service.stream import _wire_safe

REPO = Path(__file__).resolve().parent.parent

#: in-process fault vocabulary — no serve-checker subprocess, so the
#: whole file stays CI-sized (the restart arm's subprocess story is
#: tools/chaos_check.py --campaign's, its PROTOCOL consequence — a
#: reopened stream fed at seq > 0 — is pinned in-proc below)
INPROC_FAULTS = ("none", "kill-worker", "torn-subscription")


def _history(n_ops=200, seed=3, **anoms):
    sh = synth_history(SynthSpec(n_ops=n_ops, seed=seed, **anoms))
    return _rows_for(sh.ops), len(sh.ops)


def _server(**ingest_opts):
    ingest_opts.setdefault("device", False)
    srv = CheckerServer(
        host="127.0.0.1", port=0, metrics_registry=Registry(),
        ingest_opts=ingest_opts,
    )
    srv.start_background()
    return srv


# -- ledger ----------------------------------------------------------------


class TestLedger:
    def test_roundtrip_and_crc(self, tmp_path):
        path = tmp_path / "ledger.json"
        write_ledger(path, {"campaign_id": "abc", "trials": [{"t": 0}]})
        doc = read_ledger(path)
        assert doc["campaign_id"] == "abc"
        assert doc["trials"] == [{"t": 0}]
        assert doc["format"] == 1 and "crc32" in doc

    def test_torn_ledger_refused_loudly(self, tmp_path):
        path = tmp_path / "ledger.json"
        write_ledger(path, {"campaign_id": "abc", "trials": []})
        raw = path.read_bytes()
        path.write_bytes(raw[: len(raw) // 2])  # torn mid-write
        with pytest.raises(LedgerError):
            read_ledger(path)

    def test_chain_falls_back_to_prev_with_refusal(self, tmp_path):
        path = tmp_path / "ledger.json"
        write_ledger(path, {"campaign_id": "abc", "trials": [{"t": 0}]})
        write_ledger(path, {"campaign_id": "abc",
                            "trials": [{"t": 0}, {"t": 1}]})
        path.write_text("{torn")
        doc, refusals = load_ledger_chain(path)
        # the .prev generation answers, and the tear is NAMED, not eaten
        assert doc is not None and len(doc["trials"]) == 1
        assert refusals and "ledger.json" in refusals[0]

    def test_clear_removes_both_generations(self, tmp_path):
        path = tmp_path / "ledger.json"
        write_ledger(path, {"trials": []})
        write_ledger(path, {"trials": [{"t": 0}]})
        clear_ledger(path)
        doc, refusals = load_ledger_chain(path)
        assert doc is None and not refusals


# -- pins ------------------------------------------------------------------


class TestPins:
    SPEC = {"db": "sim", "workload": "queue", "seed_bug": 5,
            "sim_faults": {"drop": 1}, "contract": {}}

    def test_append_dedups_by_finding_identity(self, tmp_path):
        _, added = append_pin(tmp_path, self.SPEC, ["lost"], source="t")
        assert added is True
        _, added = append_pin(tmp_path, self.SPEC, ["lost"], source="t2")
        assert added is False  # re-found, not multiplied
        pins = load_pins(tmp_path)
        assert len(pins) == 1 and pins[0]["refound"] == 1

    def test_campaign_spec_keys_on_service_dimensions(self):
        camp = {"fault": "kill-worker", "pressure": "tight",
                "history": 2, "workload": None, "db": None}
        other = dict(camp, fault="torn-subscription")
        assert pin_key(camp, ["service-divergence"]) != pin_key(
            other, ["service-divergence"]
        )

    def test_replay_skips_campaign_pins(self, tmp_path):
        camp = {"fault": "none", "pressure": "none", "history": 0}
        append_pin(tmp_path, camp, ["books-imbalance"], source="t",
                   kind="campaign")
        out = replay_pins(tmp_path, log=lambda s: None)
        assert out == [{"key": pin_key(camp, ["books-imbalance"]),
                        "status": "skipped", "kind": "campaign"}]

    def test_torn_pins_file_refused(self, tmp_path):
        (tmp_path / "fuzz_pins.json").write_text('{"format": 1, "pins')
        with pytest.raises(ValueError):
            load_pins(tmp_path)


# -- incremental verdict push ----------------------------------------------


class _Collector(threading.Thread):
    def __init__(self, host, port, sid, from_window=0):
        super().__init__(daemon=True)
        self.client = CheckerClient(host, port, retry=RetryPolicy(seed=0))
        self.sid, self.from_window = sid, from_window
        self.windows: list[dict] = []
        self.error = None

    def run(self):
        try:
            for w in self.client.subscribe_windows(
                self.sid, self.from_window
            ):
                self.windows.append(w)
        except Exception as e:  # noqa: BLE001 — asserted by the test
            self.error = e
        finally:
            self.client.close()


class TestVerdictPush:
    def _feed(self, client, sid, rows, n_ops, block_rows=32):
        for seq, (blk, b_ops) in enumerate(
            iter_row_blocks(rows, block_rows)
        ):
            rep = client.stream_feed_rows(sid, seq, blk, b_ops)
            assert rep["op"] == "accepted", rep

    def test_windows_pushed_before_finish_and_final_matches(self):
        rows, n_ops = _history(lost=1)
        srv = _server()
        try:
            with CheckerClient(port=srv.port) as client:
                sid = client.stream_open("queue")["stream"]
                col = _Collector("127.0.0.1", srv.port, sid)
                col.start()
                self._feed(client, sid, rows, n_ops)
                deadline = time.monotonic() + 30
                while not col.windows and time.monotonic() < deadline:
                    time.sleep(0.01)
                # PUSHED, not polled: windows arrive while the stream
                # is still open, before any finish call
                assert col.windows, "no window pushed before finish"
                verdict = client.stream_finish(sid, timeout=60)
            col.join(timeout=60)
        finally:
            srv.shutdown()
            srv.server_close()
        assert col.error is None
        final = [w for w in col.windows if w.get("final")]
        assert len(final) == 1
        assert verdict_fingerprint(final[0]["verdict"]) == \
            verdict_fingerprint(verdict)

    def test_torn_subscription_reconnects_exactly_once_each(self):
        rows, n_ops = _history(n_ops=400)
        srv = _server()
        try:
            srv._sub_drop = 2  # server tears the push socket: 2 frames
            with CheckerClient(port=srv.port) as client:
                sid = client.stream_open("queue")["stream"]
                col = _Collector("127.0.0.1", srv.port, sid)
                col.start()
                self._feed(client, sid, rows, n_ops, block_rows=16)
                client.stream_finish(sid, timeout=60)
            col.join(timeout=60)
        finally:
            srv.shutdown()
            srv.server_close()
        assert col.error is None
        # the reconnect replayed EXACTLY the missed windows: every
        # index once, contiguous from 0, no duplicate from the replay
        idx = [w["window"] for w in col.windows]
        assert idx == list(range(len(idx))) and len(idx) > 2
        assert col.windows[-1]["final"] is True

    def test_resume_past_retained_floor_raises_gap(self, monkeypatch):
        from jepsen_tpu.service import stream as stream_mod

        monkeypatch.setattr(stream_mod, "WINDOW_LOG_CAP", 3)
        rows, n_ops = _history(n_ops=400)
        srv = _server()
        try:
            with CheckerClient(port=srv.port) as client:
                sid = client.stream_open("queue")["stream"]
                self._feed(client, sid, rows, n_ops, block_rows=16)
                # > 3 windows emitted: the floor moved past window 0
                col = _Collector("127.0.0.1", srv.port, sid,
                                 from_window=0)
                col.start()
                col.join(timeout=60)
                client.stream_finish(sid, timeout=60)
        finally:
            srv.shutdown()
            srv.server_close()
        # a hole is a refusal with the machine-readable gap — never a
        # silent resume that fabricates continuity
        assert isinstance(col.error, SubscriptionGap)
        assert col.error.gap["requested"] == 0
        assert col.error.gap["floor"] > 0


# -- service-restart: the protocol consequence ------------------------------


class TestRestartGap:
    def test_reopened_stream_fed_at_old_seq_quarantines(self):
        """A restarted service knows nothing of pre-crash streams: a
        client that reopens and resumes at its old seq must get a
        quarantine WITH the gap as evidence — continuing would be a
        gapped carry, a fabricated verdict."""
        rows, n_ops = _history()
        blocks = list(iter_row_blocks(rows, 64))
        srv = _server()
        try:
            with CheckerClient(port=srv.port) as client:
                # "post-restart": a fresh stream, client resumes at 3
                sid = client.stream_open("queue")["stream"]
                rep = client.stream_feed_rows(sid, 3, *blocks[3])
                assert rep["op"] == "quarantined"
                assert rep["expected"] == 0 and rep["got"] == 3
                v = client.stream_finish(sid, timeout=60)
        finally:
            srv.shutdown()
            srv.server_close()
        assert v["valid?"] == "unknown"
        assert "gap in block sequence" in json.dumps(_wire_safe(v))


# -- the campaign supervisor ------------------------------------------------


@pytest.fixture(scope="module")
def green_campaign(tmp_path_factory):
    """One uninterrupted in-proc campaign shared by the read-only
    assertions below (each trial spins a real wire server)."""
    out = tmp_path_factory.mktemp("camp")
    sup = CampaignSupervisor(
        out, seed=23, trials=3, n_base=2, n_ops=120,
        faults=INPROC_FAULTS, log=lambda s: None,
    )
    return out, sup, sup.run()


class TestSupervisor:
    def test_campaign_green_books_balance_windows_pushed(
        self, green_campaign
    ):
        _out, _sup, summary = green_campaign
        assert summary["completed"] == summary["planned"] == 3
        assert summary["reds"] == 0
        assert summary["oracle_matches"] == 3
        assert summary["books_balanced"] is True
        # ≥1 incremental window PUSHED per trial, and latency measured
        assert summary["windows_pushed"] >= 3
        assert summary["record_to_verdict_ms"]["p50"] is not None
        assert sorted(summary["faults_fired"]) == sorted(INPROC_FAULTS)

    def test_every_trial_verdict_equals_serial_oracle(
        self, green_campaign
    ):
        out, sup, _summary = green_campaign
        doc = read_ledger(out / "campaign_ledger.json")
        for t in doc["trials"]:
            assert t["oracle_match"], t
            b = t["books"]
            assert b["submitted"] == (
                b["verdicts"] + b["rejects"] + b["interrupted"]
            ), t

    def test_resume_refuses_foreign_campaign(self, green_campaign):
        out, _sup, _summary = green_campaign
        alien = CampaignSupervisor(
            out, seed=999, trials=3, n_base=2, n_ops=120,
            faults=INPROC_FAULTS, resume=True, log=lambda s: None,
        )
        with pytest.raises(LedgerError, match="refusing to splice"):
            alien.run()

    def test_sigkill_then_resume_identical_verdict_set(self, tmp_path):
        """The tentpole pin: kill the supervisor after trial 0 (the
        deterministic die-hook — ``os._exit(137)`` right after the
        journal write, a SIGKILL at the worst instant), resume, and the
        full fingerprint set must equal an uninterrupted run's."""
        kw = dict(seed=29, trials=3, n_base=2, n_ops=120,
                  faults=INPROC_FAULTS)
        flags = [
            "--seed", "29", "--trials", "3", "--base", "2",
            "--ops", "120", "--faults", ",".join(INPROC_FAULTS),
        ]
        killed = tmp_path / "killed"
        p = subprocess.run(
            [sys.executable, "-m", "jepsen_tpu", "campaign",
             "--out", str(killed)] + flags,
            cwd=str(REPO),
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     **{DIE_AFTER_ENV: "0"}),
            capture_output=True, text=True, timeout=300,
        )
        assert p.returncode == 137, p.stderr[-2000:]
        journaled = read_ledger(killed / "campaign_ledger.json")
        assert len(journaled["trials"]) == 1

        resumed = CampaignSupervisor(
            killed, resume=True, log=lambda s: None, **kw
        ).run()
        assert resumed["resumed_from"] == 1
        assert resumed["completed"] == 3 and resumed["reds"] == 0

        fresh_dir = tmp_path / "fresh"
        fresh = CampaignSupervisor(
            fresh_dir, log=lambda s: None, **kw
        ).run()
        assert fresh["completed"] == 3 and fresh["reds"] == 0
        fps = lambda d: [  # noqa: E731
            t["fingerprint"]
            for t in read_ledger(d / "campaign_ledger.json")["trials"]
        ]
        assert fps(killed) == fps(fresh_dir)


# -- the live tailer --------------------------------------------------------


class TestLiveTailer:
    def test_tailed_ops_reach_live_verdict_equal_oracle(self):
        sh = synth_history(SynthSpec(n_ops=150, seed=11, lost=1))
        srv = _server()
        try:
            # a tight observe() loop enqueues everything instantly, so
            # the whole history must fit the pending-block window (a
            # real soak trickles ops in at wall-clock rate instead)
            tailer = LiveStreamTailer(
                "127.0.0.1", srv.port, "queue", block_ops=32
            )
            for op in sh.ops:
                tailer.observe(op)
            summary = tailer.close(timeout=60)
        finally:
            srv.shutdown()
            srv.server_close()
        eng = SegmentedChecker("queue", device=False)
        eng.feed(sh.ops)
        oracle = eng.finish()
        assert "saturated_at_op" not in summary
        assert summary["verdict"] is not None
        assert verdict_fingerprint(summary["verdict"]) == \
            verdict_fingerprint(oracle)
        assert summary["ops_fed"] == len(sh.ops)
        assert summary["windows_pushed"] >= 1
        assert not summary["errors"]
        assert summary["record_to_verdict_p50_ms"] is not None

    def test_overrun_freezes_honestly_never_drops_silently(self):
        sh = synth_history(SynthSpec(n_ops=150, seed=11))
        srv = _server()
        try:
            # tiny blocks + an instant burst: the pending window MUST
            # overflow — the tailer freezes at a named op and reports
            # the unverified suffix instead of silently shedding ops
            tailer = LiveStreamTailer(
                "127.0.0.1", srv.port, "queue", block_ops=4
            )
            for op in sh.ops:
                tailer.observe(op)
            summary = tailer.close(timeout=60)
        finally:
            srv.shutdown()
            srv.server_close()
        assert summary["saturated_at_op"] is not None
        assert summary["ops_unverified"] > 0
        # books balance: every observed op is either fed or named
        # unverified — no third, silent bucket
        assert summary["ops_fed"] + summary["ops_unverified"] == \
            summary["ops"]
        # the fed prefix still gets a real verdict over the wire
        assert summary["verdict"] is not None
        assert not summary["errors"]
