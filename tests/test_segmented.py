"""Differential + crash coverage for segmented online checking
(ISSUE 15, SEGMENTED.md): segmented ≡ monolithic verdicts across
queue/stream/elle/pcomp on the synth corpus — including violations
that SPAN a segment boundary, the settled-value reopen path, the
degenerate-elle splice, and the pcomp overflow→unknown carry — plus
the checkpoint contract: kill-mid-segment resume ≡ uninterrupted run,
torn/corrupt checkpoints refused loudly and recomputed from the
previous one, poison quarantined as unknown-with-evidence that can
never fold into valid."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

from jepsen_tpu.checkers.elle import check_elle_cpu
from jepsen_tpu.checkers.queue_lin import check_queue_lin_cpu
from jepsen_tpu.checkers.segmented import (
    LiveSegmentChecker,
    SegmentedChecker,
    checkpoint_path_for,
    clear_checkpoints,
    read_checkpoint,
    segmented_check_file,
)
from jepsen_tpu.checkers.stream_lin import check_stream_lin_cpu
from jepsen_tpu.checkers.total_queue import check_total_queue_cpu
from jepsen_tpu.history.ops import Op, OpF, OpType
from jepsen_tpu.history.segments import (
    SegmentPoisonError,
    SourceMismatchError,
    iter_segments,
    prefix_sha256,
)
from jepsen_tpu.history.store import _json_default, write_history_jsonl
from jepsen_tpu.history.synth import (
    ElleSynthSpec,
    MutexSynthSpec,
    StreamSynthSpec,
    SynthSpec,
    synth_elle_history,
    synth_history,
    synth_mutex_history,
    synth_stream_history,
)


def norm(x):
    return json.loads(json.dumps(x, default=_json_default))


def run_segmented(workload, ops, segment_ops, opts=None, device=False,
                  carry_cap=None):
    eng = SegmentedChecker(
        workload, opts=opts or {}, device=device, carry_cap=carry_cap
    )
    for i in range(0, len(ops), segment_ops):
        eng.feed(ops[i : i + segment_ops])
    return eng.finish()


# ---------------------------------------------------------------------------
# queue family: segmented ≡ total-queue AND queue-linearizability
# ---------------------------------------------------------------------------

QUEUE_ANOMALIES = (
    {},
    {"lost": 2},
    {"duplicated": 2},
    {"unexpected": 1},
    {"phantom_fail": 1},
    {"causality": 1},
    {"lost": 1, "duplicated": 1, "unexpected": 1, "causality": 1},
)


class TestQueueSegmentedDifferential:
    @pytest.mark.parametrize("kw", QUEUE_ANOMALIES)
    @pytest.mark.parametrize("delivery", ["exactly-once", "at-least-once"])
    def test_matches_monolithic(self, kw, delivery):
        sh = synth_history(SynthSpec(n_ops=173, seed=5, **kw))
        mono_q = norm(check_total_queue_cpu(sh.ops))
        mono_l = norm(check_queue_lin_cpu(sh.ops, delivery=delivery))
        for seg in (7, 64):
            r = run_segmented(
                "queue", sh.ops, seg, opts={"delivery": delivery}
            )
            assert norm(r["queue"]) == mono_q, f"total-queue @ seg={seg}"
            assert norm(r["linear"]) == mono_l, f"queue-lin @ seg={seg}"

    def test_device_program_matches_host_carry(self):
        sh = synth_history(
            SynthSpec(n_ops=173, seed=5, lost=1, duplicated=1)
        )
        host = run_segmented("queue", sh.ops, 50, device=False)
        dev = run_segmented("queue", sh.ops, 50, device=True)
        assert norm(host["queue"]) == norm(dev["queue"])
        assert norm(host["linear"]) == norm(dev["linear"])
        assert norm(dev["queue"]) == norm(check_total_queue_cpu(sh.ops))

    def test_carry_is_residual_not_linear(self):
        """The bounded-memory mechanism itself: on a healthy history
        almost every value settles to one bit — the dict residue must
        be a small fraction of the distinct-value count."""
        sh = synth_history(SynthSpec(n_ops=2000, seed=3))
        eng = SegmentedChecker("queue", device=False)
        for i in range(0, len(sh.ops), 200):
            eng.feed(sh.ops[i : i + 200])
        carry = eng.carry.carry_size()
        assert carry["settled"] > 300
        assert carry["open"] + carry["reopened"] < carry["settled"] / 4
        assert norm(eng.finish()["queue"]) == norm(
            check_total_queue_cpu(sh.ops)
        )


def _op(type_, f, process, value, t):
    return Op(OpType[type_], OpF[f], process, value, time=t)


class TestQueueBoundarySpanning:
    """Violations whose evidence spans a segment boundary — including
    the settled→reopened path (the value left the residue for a
    presence bit segments earlier)."""

    def _base(self):
        ops = []
        t = 0
        for v in range(6):  # six clean settled lives
            t += 2
            ops.append(_op("INVOKE", "ENQUEUE", v % 3, v, t))
            ops.append(_op("OK", "ENQUEUE", v % 3, v, t + 1))
            ops.append(_op("INVOKE", "DEQUEUE", v % 3, None, t + 2))
            ops.append(_op("OK", "DEQUEUE", v % 3, v, t + 3))
        return ops, t

    def test_duplicate_read_of_long_settled_value(self):
        ops, t = self._base()
        # value 0 settled ~5 segments ago (seg=4); a second read now
        ops.append(_op("INVOKE", "DEQUEUE", 0, None, t + 10))
        ops.append(_op("OK", "DEQUEUE", 0, 0, t + 11))
        for seg in (4, 5):
            r = run_segmented("queue", ops, seg)
            assert norm(r["queue"]) == norm(check_total_queue_cpu(ops))
            assert norm(r["linear"]) == norm(check_queue_lin_cpu(ops))
            assert r["queue"]["duplicated"] == {0}
            assert r["linear"]["duplicate"] == {0}

    def test_late_ack_turns_settled_value_lost(self):
        ops, t = self._base()
        # a duplicate ack of settled value 1, far later: e > d => lost
        ops.append(_op("OK", "ENQUEUE", 1, 1, t + 10))
        for seg in (4, 100):
            r = run_segmented("queue", ops, seg)
            assert norm(r["queue"]) == norm(check_total_queue_cpu(ops))
            assert r["queue"]["valid?"] is False
            assert r["queue"]["lost"] == {1}

    def test_loss_across_the_whole_history(self):
        ops, t = self._base()
        # acked in segment 0, never read: lost only judged at the end
        ops.insert(0, _op("OK", "ENQUEUE", 4, 99, 1))
        ops.insert(0, _op("INVOKE", "ENQUEUE", 4, 99, 0))
        for seg in (4, 6):
            r = run_segmented("queue", ops, seg)
            assert norm(r["queue"]) == norm(check_total_queue_cpu(ops))
            assert r["queue"]["lost"] == {99}

    def test_causality_pair_spanning_boundary(self):
        ops, t = self._base()
        # read completes now; its enqueue is only invoked segments later
        ops.append(_op("INVOKE", "DEQUEUE", 4, None, t + 10))
        ops.append(_op("OK", "DEQUEUE", 4, 777, t + 11))
        for v in range(700, 706):  # filler segment between
            ops.append(_op("INVOKE", "ENQUEUE", 3, v, t + 12))
            ops.append(_op("OK", "ENQUEUE", 3, v, t + 13))
            ops.append(_op("INVOKE", "DEQUEUE", 3, None, t + 14))
            ops.append(_op("OK", "DEQUEUE", 3, v, t + 15))
        ops.append(_op("INVOKE", "ENQUEUE", 4, 777, t + 20))
        ops.append(_op("OK", "ENQUEUE", 4, 777, t + 21))
        for seg in (5, 9):
            r = run_segmented("queue", ops, seg)
            assert norm(r["linear"]) == norm(check_queue_lin_cpu(ops))
            assert r["linear"]["causality"] == {777}
            assert r["linear"]["valid?"] is False


# ---------------------------------------------------------------------------
# stream
# ---------------------------------------------------------------------------


class TestStreamSegmentedDifferential:
    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"lost": 1},
            {"duplicated": 1},
            {"phantom": 1},
            {"reorder": 1},
            {"divergent": 1},
            {"nonmonotonic": 1},
            {"recovered": 1},
        ],
    )
    @pytest.mark.parametrize("append_fail", ["definite", "indeterminate"])
    def test_matches_monolithic(self, kw, append_fail):
        sh = synth_stream_history(
            StreamSynthSpec(n_ops=180, seed=3, **kw)
        )
        mono = norm(check_stream_lin_cpu(sh.ops, append_fail=append_fail))
        for seg in (11, 60):
            r = run_segmented(
                "stream", sh.ops, seg, opts={"append_fail": append_fail}
            )
            assert norm(r["stream"]) == mono, f"stream @ seg={seg}"

    def test_full_read_pending_across_boundary(self):
        """A full read invoked in one segment and completing two
        segments later must still arm loss judgment."""
        sh = synth_stream_history(StreamSynthSpec(n_ops=120, seed=9))
        mono = norm(check_stream_lin_cpu(sh.ops))
        r = run_segmented("stream", sh.ops, 7)
        assert norm(r["stream"]) == mono
        assert r["stream"]["full-read"] == mono["full-read"]


# ---------------------------------------------------------------------------
# elle
# ---------------------------------------------------------------------------

ELLE_ANOMALIES = (
    {},
    {"g1a": 1},
    {"g1b": 1},
    {"g0_cycle": 1},
    {"g1c_cycle": 1},
    {"g2_cycle": 1},
    {"g1a": 1, "g0_cycle": 1, "g2_cycle": 1},
)


class TestElleSegmentedDifferential:
    @pytest.mark.parametrize("kw", ELLE_ANOMALIES)
    @pytest.mark.parametrize("model", ["serializable", "read-committed"])
    def test_matches_monolithic(self, kw, model):
        sh = synth_elle_history(ElleSynthSpec(n_txns=60, seed=4, **kw))
        mono = norm(check_elle_cpu(sh.ops, model=model))
        for seg in (13, 50):
            r = run_segmented(
                "elle", sh.ops, seg, opts={"model": model}
            )
            assert norm(r["elle"]) == mono, f"elle {kw} @ seg={seg}"

    def test_cycle_spanning_boundary(self):
        """A G0 cycle whose txns land in DIFFERENT segments: the
        condensed carry (refs + writer map) must still close it."""
        sh = synth_elle_history(
            ElleSynthSpec(n_txns=40, seed=8, g0_cycle=1)
        )
        mono = norm(check_elle_cpu(sh.ops))
        assert mono["G0-count"] >= 1
        # segment size 3: every multi-txn structure spans boundaries
        r = run_segmented("elle", sh.ops, 3)
        assert norm(r["elle"]) == mono

    def test_g1b_with_same_value_under_two_keys(self):
        """Review finding: one txn appending the SAME value under two
        keys must not mask G1b on the first key — the carry's writer
        map keeps a per-key last-append flag, mirroring the monolithic
        appends_of[(txn, key)] lookup."""
        mk = lambda t, f, p, v, time_: Op(t, f, p, v, time=time_)
        T, F = OpType, OpF
        ops = []
        t = 0
        for value in (
            # A: 5 is an INTERMEDIATE append to k1 (6 follows), but
            # the LAST append to k2 — the k2 entry must not launder
            # the k1 intermediate read below
            [["append", 1, 5], ["append", 1, 6], ["append", 2, 5]],
            [["r", 1, [5]]],  # B reads k1 -> [5]: G1b
        ):
            t += 2
            ops.append(mk(T.INVOKE, F.TXN, 0, value, t))
            ops.append(mk(T.OK, F.TXN, 0, value, t + 1))
        mono = norm(check_elle_cpu(ops))
        assert 1 in mono["G1b"] and mono["valid?"] is False
        for seg in (1, 4):
            r = run_segmented("elle", ops, seg)
            assert norm(r["elle"]) == mono, f"G1b two-key @ seg={seg}"

    def test_degenerate_splice(self):
        """The degenerate shapes the DEVICE elle encoding refuses
        (value appended twice, observed under two keys, duplicated in
        one read — elle_mops_for's host-fallback cases) must check
        identically through the segmented carry, because its finish
        pass mirrors the host infer_txn_graph rules exactly."""
        mk = lambda t, f, p, v, time_: Op(t, f, p, v, time=time_)
        T, F = OpType, OpF
        ops = []
        t = 0
        # txn 0 appends v=5 to key 1; txn 1 appends v=5 AGAIN (twice,
        # once under another key); txn 2 reads [5, 5] (duplicated in
        # one read) on key 1
        for value in (
            [["append", 1, 5]],
            [["append", 1, 5], ["append", 2, 5]],
            [["r", 1, [5, 5]]],
            [["r", 2, [5]]],
        ):
            t += 2
            ops.append(mk(T.INVOKE, F.TXN, 0, value, t))
            ops.append(mk(T.OK, F.TXN, 0, value, t + 1))
        mono = norm(check_elle_cpu(ops))
        for seg in (1, 2, 8):
            r = run_segmented("elle", ops, seg)
            assert norm(r["elle"]) == mono, f"degenerate @ seg={seg}"


# ---------------------------------------------------------------------------
# mutex / pcomp
# ---------------------------------------------------------------------------


class TestMutexSegmented:
    @pytest.mark.parametrize(
        "kw",
        [
            {},
            {"double_grant": 1},
            {"n_locks": 3},
            {"n_locks": 3, "double_grant": 2},
        ],
    )
    def test_verdict_matches_monolithic(self, kw):
        from jepsen_tpu.checkers.wgl import MutexWgl

        sh = synth_mutex_history(MutexSynthSpec(n_ops=80, seed=2, **kw))
        mono = MutexWgl(backend="tpu").check({}, sh.ops)
        for seg in (19, 200):
            r = run_segmented("mutex", sh.ops, seg, device=True)
            assert r["mutex"]["valid?"] == mono["valid?"], (
                f"mutex {kw} @ seg={seg}: {r['mutex']} vs {mono}"
            )

    def test_violation_spanning_boundary(self):
        """A double grant whose two acquires straddle a segment
        boundary: the open-class carry must deliver both to one
        frontier search."""
        mk = _op
        ops = [
            mk("INVOKE", "ACQUIRE", 0, None, 0),
            mk("OK", "ACQUIRE", 0, None, 1),
            # --- boundary lands here at seg=2 ---
            mk("INVOKE", "ACQUIRE", 1, None, 2),
            mk("OK", "ACQUIRE", 1, None, 3),  # split-brain grant
            mk("INVOKE", "RELEASE", 0, None, 4),
            mk("OK", "RELEASE", 0, None, 5),
            mk("INVOKE", "RELEASE", 1, None, 6),
            mk("OK", "RELEASE", 1, None, 7),
        ]
        from jepsen_tpu.checkers.wgl import MutexWgl

        assert MutexWgl(backend="tpu").check({}, ops)["valid?"] is False
        for seg in (2, 3):
            r = run_segmented("mutex", ops, seg, device=True)
            assert r["mutex"]["valid?"] is False

    def test_overflow_escalates_to_unknown_with_evidence(self):
        """pcomp overflow→unknown carry: a lock held open past the
        carry cap must surface as unknown WITH the class named —
        never a silent truncation, never a fabricated verdict."""
        mk = _op
        ops = []
        t = 0
        # a lock that is NEVER free at any boundary: overlapping
        # hold chain acquire(p)->acquire(q)... with releases lagging
        ops.append(mk("INVOKE", "ACQUIRE", 0, None, t))
        ops.append(mk("OK", "ACQUIRE", 0, None, t + 1))
        for i in range(30):
            t += 2
            p = (i + 1) % 3
            ops.append(mk("INVOKE", "ACQUIRE", p, None, t))
            ops.append(mk("INVOKE", "RELEASE", (i % 3), None, t + 1))
            ops.append(mk("OK", "RELEASE", (i % 3), None, t + 2))
            ops.append(mk("OK", "ACQUIRE", p, None, t + 3))
        r = run_segmented("mutex", ops, 8, carry_cap=10)
        assert r["mutex"]["valid?"] == "unknown"
        ov = r["mutex"]["carry-overflow"]
        assert ov["carry-cap"] == 10
        assert ov["carried-ops"] > 10
        assert "largest-class" in ov

    def test_indeterminate_acquire_carries_to_finish(self):
        """An info acquire never completes, so its class never closes
        mid-stream — it must be judged at finish exactly as the
        monolithic engine sees it (ret = INF)."""
        from jepsen_tpu.checkers.wgl import MutexWgl

        sh = synth_mutex_history(
            MutexSynthSpec(n_ops=60, seed=6, p_info=0.3)
        )
        mono = MutexWgl(backend="tpu").check({}, sh.ops)
        r = run_segmented("mutex", sh.ops, 11, device=True)
        assert r["mutex"]["valid?"] == mono["valid?"]

    def test_fenced_autodetect(self):
        from jepsen_tpu.checkers.wgl import MutexWgl

        # fenced grants carry int tokens: build a tiny fenced history
        mk = _op
        ops = [
            mk("INVOKE", "ACQUIRE", 0, None, 0),
            mk("OK", "ACQUIRE", 0, 1, 1),  # token 1
            mk("INVOKE", "RELEASE", 0, 1, 2),
            mk("OK", "RELEASE", 0, 1, 3),
            mk("INVOKE", "ACQUIRE", 1, None, 4),
            mk("OK", "ACQUIRE", 1, 2, 5),
            mk("INVOKE", "RELEASE", 1, 2, 6),
            mk("OK", "RELEASE", 1, 2, 7),
        ]
        mono = MutexWgl(backend="tpu").check({}, ops)
        r = run_segmented("mutex", ops, 4, device=True)
        assert r["mutex"]["valid?"] == mono["valid?"] is True
        assert r["mutex"]["model"] == mono["model"]


# ---------------------------------------------------------------------------
# checkpoints: resume ≡ uninterrupted, torn refused loudly
# ---------------------------------------------------------------------------


@pytest.fixture()
def queue_history_file(tmp_path):
    sh = synth_history(
        SynthSpec(n_ops=400, seed=9, lost=1, duplicated=1)
    )
    hp = tmp_path / "history.jsonl"
    write_history_jsonl(hp, sh.ops)
    return hp, sh


def _die_env_child(hpath, seg_ops, die_after, resume=False):
    code = (
        "import sys; sys.path.insert(0, sys.argv[1])\n"
        "from jepsen_tpu.checkers.segmented import segmented_check_file\n"
        f"segmented_check_file(sys.argv[2], segment_ops={seg_ops},"
        f" device=False, resume={resume})\n"
    )
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        JEPSEN_TPU_SEG_DIE_AFTER=str(die_after),
    )
    return subprocess.run(
        [sys.executable, "-c", code, str(REPO), str(hpath)],
        env=env, capture_output=True, text=True, timeout=300,
    )


class TestCheckpointResume:
    def test_state_roundtrip_mid_stream(self, queue_history_file):
        _, sh = queue_history_file
        eng = SegmentedChecker("queue", device=False)
        ops = sh.ops
        for i in range(0, 300, 100):
            eng.feed(ops[i : i + 100])
        # serialize through JSON (exactly what the checkpoint stores)
        state = json.loads(json.dumps(eng.state()))
        eng2 = SegmentedChecker.from_state(state, device=False)
        for i in range(300, len(ops), 100):
            eng.feed(ops[i : i + 100])
            eng2.feed(ops[i : i + 100])
        assert norm(eng.finish()["queue"]) == norm(
            eng2.finish()["queue"]
        ) == norm(check_total_queue_cpu(ops))

    def test_kill_mid_segment_resume_identical(self, queue_history_file):
        hp, _ = queue_history_file
        r0 = segmented_check_file(hp, segment_ops=100, device=False)
        assert not checkpoint_path_for(hp).exists(), (
            "a completed check must clear its checkpoints"
        )
        assert r0["segmented"]["resumed"] is False
        p = _die_env_child(hp, 100, die_after=2)
        assert p.returncode == 137, p.stderr[-500:]
        cp = checkpoint_path_for(hp)
        assert cp.exists()
        doc = read_checkpoint(cp)  # valid CRC, anchored
        assert doc["segment_idx"] == 2
        assert doc["source_sha256"] == prefix_sha256(
            hp, doc["source_bytes"]
        )
        r1 = segmented_check_file(
            hp, segment_ops=100, device=False, resume=True
        )
        assert r1["segmented"]["resumed"] is True
        assert r1["segmented"]["resumed_from"] == 2
        for fam in ("queue", "linear", "valid?"):
            assert norm(r1[fam]) == norm(r0[fam])

    def test_resume_from_final_short_segment_checkpoint(self, tmp_path):
        """A checkpoint written at the FINAL (short) segment must
        resume cleanly to the identical verdict — the skipped prefix
        is the whole file, which the reader must accept (review
        finding: the full-segments assumption raised a false
        'source truncated' SourceMismatchError here)."""
        sh = synth_history(SynthSpec(n_ops=200, seed=4, lost=1))
        hp = tmp_path / "history.jsonl"
        write_history_jsonl(hp, sh.ops)
        n_lines = sum(1 for line in hp.read_bytes().splitlines() if line)
        seg = 100
        last = (n_lines - 1) // seg  # index of the final, SHORT segment
        assert n_lines % seg != 0, "fixture must end on a short segment"
        r0 = segmented_check_file(hp, segment_ops=seg, device=False)
        p = _die_env_child(hp, seg, die_after=last)
        assert p.returncode == 137, p.stderr[-500:]
        r1 = segmented_check_file(
            hp, segment_ops=seg, device=False, resume=True
        )
        assert r1["segmented"]["resumed"] is True
        assert r1["segmented"]["resumed_from"] == last
        for fam in ("queue", "linear", "valid?"):
            assert norm(r1[fam]) == norm(r0[fam])

    def test_mismatched_config_recomputes_from_scratch(
        self, queue_history_file
    ):
        hp, _ = queue_history_file
        p = _die_env_child(hp, 100, die_after=1)
        assert p.returncode == 137
        # a different segment size must refuse the checkpoint (its
        # carry is anchored to other boundaries), not graft onto it
        r = segmented_check_file(
            hp, segment_ops=64, device=False, resume=True
        )
        assert r["segmented"]["resumed"] is False
        assert r["segmented"]["checkpoints_refused"]

    def test_contract_mismatch_refused(self, queue_history_file):
        """Review finding: resuming with a DIFFERENT checker contract
        must refuse the checkpoint (its carry was judged under the old
        one), not silently adopt the checkpoint's contract."""
        hp, _ = queue_history_file
        p = _die_env_child(hp, 100, die_after=2)
        assert p.returncode == 137
        r = segmented_check_file(
            hp, segment_ops=100, device=False, resume=True,
            opts={"delivery": "at-least-once"},
        )
        assert r["segmented"]["resumed"] is False
        assert r["segmented"]["checkpoints_refused"]
        assert r["linear"]["delivery"] == "at-least-once"
        # same contract resumes fine
        p = _die_env_child(hp, 100, die_after=2)
        assert p.returncode == 137
        r2 = segmented_check_file(
            hp, segment_ops=100, device=False, resume=True, opts={}
        )
        assert r2["segmented"]["resumed"] is True

    def test_source_mutation_refused(self, queue_history_file):
        hp, sh = queue_history_file
        p = _die_env_child(hp, 100, die_after=2)
        assert p.returncode == 137
        raw = hp.read_bytes()
        hp.write_bytes(raw[:50] + b"X" + raw[51:])  # flip a prefix byte
        with pytest.raises(SourceMismatchError):
            segmented_check_file(
                hp, segment_ops=100, device=False, resume=True
            )


class TestCheckpointIntegrity:
    def test_torn_checkpoint_refused_falls_back_to_prev(
        self, queue_history_file, caplog
    ):
        hp, _ = queue_history_file
        r0 = segmented_check_file(hp, segment_ops=100, device=False)
        p = _die_env_child(hp, 100, die_after=3)
        assert p.returncode == 137
        cp = checkpoint_path_for(hp)
        raw = cp.read_bytes()
        cp.write_bytes(raw[: len(raw) // 2])
        import logging

        with caplog.at_level(logging.ERROR):
            r1 = segmented_check_file(
                hp, segment_ops=100, device=False, resume=True
            )
        refusals = r1["segmented"]["checkpoints_refused"]
        assert refusals and "torn/corrupt" in refusals[0]
        assert any(
            "REFUSED checkpoint" in rec.message for rec in caplog.records
        )
        # fell back to .prev: resumed from the previous segment
        assert r1["segmented"]["resumed"] is True
        assert r1["segmented"]["resumed_from"] == 2
        for fam in ("queue", "linear"):
            assert norm(r1[fam]) == norm(r0[fam])

    def test_both_torn_recomputes_from_scratch(self, queue_history_file):
        hp, _ = queue_history_file
        r0 = segmented_check_file(hp, segment_ops=100, device=False)
        p = _die_env_child(hp, 100, die_after=3)
        assert p.returncode == 137
        cp = checkpoint_path_for(hp)
        cp.write_bytes(b"garbage")
        cp.with_name(cp.name + ".prev").write_bytes(b"worse")
        r1 = segmented_check_file(
            hp, segment_ops=100, device=False, resume=True
        )
        assert len(r1["segmented"]["checkpoints_refused"]) == 2
        assert r1["segmented"]["resumed"] is False
        for fam in ("queue", "linear"):
            assert norm(r1[fam]) == norm(r0[fam])


# ---------------------------------------------------------------------------
# the .jtc zero-parse segment producer (queue family)
# ---------------------------------------------------------------------------


class TestJtcSegmentProducer:
    @pytest.fixture()
    def recorded_run(self, tmp_path):
        from jepsen_tpu.history.store import Store

        st = Store(tmp_path)
        rd = st.run_dir("t")
        sh = synth_history(
            SynthSpec(n_ops=400, seed=9, lost=1, duplicated=1)
        )
        hp = st.save_history(rd, sh.ops)  # leaves the .jtc sibling
        assert hp.with_suffix(".jtc").exists()
        return hp, sh

    def test_jtc_slices_equal_jsonl_stream(
        self, recorded_run, monkeypatch
    ):
        hp, sh = recorded_run
        from jepsen_tpu.obs.metrics import REGISTRY

        hits0 = REGISTRY.value("jtc.hit")
        r_jtc = segmented_check_file(hp, segment_ops=100, device=False)
        assert REGISTRY.value("jtc.hit") > hits0
        assert r_jtc["segmented"]["substrate"] == "jtc"
        monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "1")
        r_jsonl = segmented_check_file(hp, segment_ops=100, device=False)
        assert r_jsonl["segmented"]["substrate"] == "jsonl"
        for fam in ("queue", "linear", "valid?"):
            assert norm(r_jtc[fam]) == norm(r_jsonl[fam])
        assert norm(r_jtc["queue"]) == norm(
            check_total_queue_cpu(sh.ops)
        )

    def test_jtc_kill_resume_identical(self, recorded_run):
        hp, _ = recorded_run
        r0 = segmented_check_file(hp, segment_ops=100, device=False)
        p = _die_env_child(hp, 100, die_after=2)
        assert p.returncode == 137, p.stderr[-500:]
        doc = read_checkpoint(checkpoint_path_for(hp))
        assert doc["substrate"] == "jtc"
        r1 = segmented_check_file(
            hp, segment_ops=100, device=False, resume=True
        )
        assert r1["segmented"]["resumed_from"] == 2
        for fam in ("queue", "linear"):
            assert norm(r1[fam]) == norm(r0[fam])

    def test_substrate_mismatch_refused(self, recorded_run, monkeypatch):
        """A checkpoint written on one substrate must not graft onto
        the other's segment geometry — refuse and recompute."""
        hp, _ = recorded_run
        p = _die_env_child(hp, 100, die_after=2)  # jtc-substrate ckpt
        assert p.returncode == 137
        monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "1")  # resume via jsonl
        r = segmented_check_file(
            hp, segment_ops=100, device=False, resume=True
        )
        assert r["segmented"]["resumed"] is False
        assert r["segmented"]["checkpoints_refused"]


# ---------------------------------------------------------------------------
# poison: quarantine precedence (PR-13 rule)
# ---------------------------------------------------------------------------


class TestPoisonQuarantine:
    def test_torn_line_quarantines_as_unknown_with_evidence(
        self, tmp_path
    ):
        sh = synth_history(SynthSpec(n_ops=200, seed=9))
        hp = tmp_path / "history.jsonl"
        write_history_jsonl(hp, sh.ops)
        lines = hp.read_bytes().splitlines(keepends=True)
        hp.write_bytes(
            b"".join(lines[:150])
            + b'{"type": "torn mid-rec'
            + b"".join(lines[150:])
        )
        r = segmented_check_file(hp, segment_ops=64, device=False)
        assert r["valid?"] == "unknown"
        for fam in ("queue", "linear"):
            assert r[fam]["valid?"] == "unknown"
            ev = r[fam]["quarantined"]["segments"]
            assert ev and ev[0]["line"] == 151
            assert "JSONDecodeError" in ev[0]["error"]

    def test_queue_invalid_before_poison_goes_unknown(self):
        """Queue loss is an END-state class — a prefix that LOOKS
        invalid is not final (a later segment could deliver the
        value), so poison caps it at unknown, never a fabricated
        False and never valid."""
        ops = [
            _op("INVOKE", "ENQUEUE", 0, 1, 0),
            _op("OK", "ENQUEUE", 0, 1, 1),
        ]
        eng = SegmentedChecker("queue", device=False)
        eng.feed(ops)
        eng.quarantine(1, "synthetic poison")
        r = eng.finish()
        assert r["queue"]["valid?"] == "unknown"
        assert r["valid?"] == "unknown"

    def test_mutex_prefix_invalid_survives_poison(self):
        """Invalid trumps all — but ONLY where it is prefix-final: a
        refuted (flushed) mutex chunk refutes every extension, so the
        poison cannot launder it back to unknown."""
        ops = [
            _op("INVOKE", "ACQUIRE", 0, None, 0),
            _op("OK", "ACQUIRE", 0, None, 1),
            _op("INVOKE", "ACQUIRE", 1, None, 2),
            _op("OK", "ACQUIRE", 1, None, 3),  # double grant
            _op("INVOKE", "RELEASE", 0, None, 4),
            _op("OK", "RELEASE", 0, None, 5),
            _op("INVOKE", "RELEASE", 1, None, 6),
            _op("OK", "RELEASE", 1, None, 7),
        ]
        eng = SegmentedChecker("mutex", device=False)
        eng.feed(ops)  # class closes balanced -> flushes -> refuted
        assert eng.carry.final_invalid
        eng.quarantine(1, "synthetic poison")
        r = eng.finish()
        assert r["mutex"]["valid?"] is False
        assert r["mutex"]["quarantined"]["segments"]
        assert r["valid?"] is False

    def test_feeding_stops_after_poison(self):
        eng = SegmentedChecker("queue", device=False)
        eng.quarantine(0, "poison first")
        eng.feed([_op("INVOKE", "ENQUEUE", 0, 1, 0)])
        assert eng.ops_seen == 0  # the poisoned carry never advanced


# ---------------------------------------------------------------------------
# live checking (the soak --live-check observer)
# ---------------------------------------------------------------------------


class TestLiveSegmentChecker:
    def test_windows_and_latency_sketch(self):
        sh = synth_history(SynthSpec(n_ops=300, seed=11))
        lc = LiveSegmentChecker("queue", 64, device=False)
        for op in sh.ops:
            lc.observe(op)
        s = lc.close()
        assert s["windows"] >= 2
        assert s["ops"] == len(sh.ops)
        assert s["samples"] == len(sh.ops)
        assert s["p99_ms"] >= s["p50_ms"] >= 0
        assert not s["errors"]
        assert s["verdict"] == check_total_queue_cpu(sh.ops)["valid?"]

    def test_no_ops_means_no_windows(self):
        lc = LiveSegmentChecker("queue", 64, device=False)
        s = lc.close()
        assert s["windows"] == 0  # the soak driver fail-louds on this


# ---------------------------------------------------------------------------
# the segment reader
# ---------------------------------------------------------------------------


class TestSegmentReader:
    def test_anchors_and_counts(self, tmp_path):
        sh = synth_history(SynthSpec(n_ops=100, seed=1))
        hp = tmp_path / "h.jsonl"
        write_history_jsonl(hp, sh.ops)
        segs = list(iter_segments(hp, 40))
        assert sum(len(s.ops) for s in segs) == len(sh.ops)
        assert segs[-1].final
        last = segs[-1]
        assert last.byte_end == hp.stat().st_size
        assert last.sha256 == prefix_sha256(hp, last.byte_end)
        # mid-anchor verifies too
        mid = segs[0]
        assert mid.sha256 == prefix_sha256(hp, mid.byte_end)

    def test_resume_skip_verifies_anchor(self, tmp_path):
        sh = synth_history(SynthSpec(n_ops=100, seed=1))
        hp = tmp_path / "h.jsonl"
        write_history_jsonl(hp, sh.ops)
        segs = list(iter_segments(hp, 40))
        resumed = list(
            iter_segments(
                hp, 40, start_segment=1,
                expect_sha256=segs[0].sha256,
                expect_bytes=segs[0].byte_end,
            )
        )
        assert [s.idx for s in resumed] == [
            s.idx for s in segs[1:]
        ]
        assert [len(s.ops) for s in resumed] == [
            len(s.ops) for s in segs[1:]
        ]
        with pytest.raises(SourceMismatchError):
            list(
                iter_segments(
                    hp, 40, start_segment=1,
                    expect_sha256="0" * 64,
                    expect_bytes=segs[0].byte_end,
                )
            )

    def test_poison_carries_line_number(self, tmp_path):
        hp = tmp_path / "h.jsonl"
        hp.write_text('{"type": "invoke", "f": "enqueue", "process": 0}\n'
                      "not json at all\n")
        with pytest.raises(SegmentPoisonError) as ei:
            list(iter_segments(hp, 10))
        assert ei.value.line_no == 2


# ---------------------------------------------------------------------------
# checkpoint identity: content hash, never basename (ISSUE 19)
# ---------------------------------------------------------------------------


class TestCheckpointCollision:
    def test_same_basename_checkpoint_never_serves_stale_carry(
        self, tmp_path
    ):
        """Two different histories that share a BASENAME (the store's
        run dirs all call theirs ``history.jsonl``): a checkpoint from
        one copied beside the other (dir clone, rsync of a crashed
        run) passes every name/config gate, so the content anchor is
        the only thing standing between the resume and a stale carry —
        it must refuse loudly (SourceMismatchError), never check the
        wrong file quietly."""
        a_dir, b_dir = tmp_path / "a", tmp_path / "b"
        a_dir.mkdir(), b_dir.mkdir()
        ha, hb = a_dir / "history.jsonl", b_dir / "history.jsonl"
        write_history_jsonl(
            ha, synth_history(SynthSpec(n_ops=400, seed=9)).ops
        )
        write_history_jsonl(
            hb, synth_history(SynthSpec(n_ops=400, seed=10, lost=1)).ops
        )
        p = _die_env_child(ha, 100, die_after=2)
        assert p.returncode == 137
        cpa, cpb = checkpoint_path_for(ha), checkpoint_path_for(hb)
        cpb.write_bytes(cpa.read_bytes())  # the collision
        with pytest.raises(SourceMismatchError):
            segmented_check_file(
                hb, segment_ops=100, device=False, resume=True
            )
        # and the honest path: clearing the foreign checkpoint yields
        # b's own from-scratch verdict
        clear_checkpoints(cpb)
        r = segmented_check_file(hb, segment_ops=100, device=False)
        assert r["segmented"]["resumed"] is False

    def test_clear_checkpoints_sweeps_tmp_not_fleet_entries(
        self, tmp_path
    ):
        """``clear_checkpoints`` removes the checkpoint, its ``.prev``
        rotation, AND crashed-writer ``.tmp`` leftovers — but never
        fleet prefix-index entries, which are keyed by content hash
        and can serve any future file sharing those bytes."""
        from jepsen_tpu.history.prefix_index import PrefixCheckpointIndex

        hp = tmp_path / "history.jsonl"
        write_history_jsonl(
            hp, synth_history(SynthSpec(n_ops=300, seed=3)).ops
        )
        idx = PrefixCheckpointIndex(tmp_path / "ckpt_index")
        r = segmented_check_file(
            hp, segment_ops=100, device=False, prefix_index=idx,
            keep_checkpoint=True,
        )
        assert r["segmented"]["resumed"] is False
        entries_before = idx.stats()["entries"]
        assert entries_before > 0
        cp = checkpoint_path_for(hp)
        assert cp.exists()
        cp.with_name(cp.name + ".prev").write_bytes(b"{}")
        stale_tmp = cp.with_name(cp.name + ".12345.tmp")
        stale_tmp.write_bytes(b"torn")
        clear_checkpoints(cp)
        assert not cp.exists()
        assert not cp.with_name(cp.name + ".prev").exists()
        assert not stale_tmp.exists()
        assert idx.stats()["entries"] == entries_before
