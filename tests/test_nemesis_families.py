"""Red/green pairs for the three new fault families (slow-disk fsync
latency, asymmetric one-way partitions, wire corruption) plus the
``make_nemesis`` opts-validation contract.

Every family proves BOTH directions at the replication layer (fast,
in-process, seeded):

- green: a correct configuration under the fault loses nothing;
- red: the family's seeded bug (or the documented hazard) under the
  SAME schedule produces the observable violation the checker exists
  to flag — confirming the fault is real, not a silent no-op.
"""

from __future__ import annotations

import shutil
import socket
import tempfile
import time

from _load import scaled

import pytest

from jepsen_tpu.harness.replication import (
    ReplicatedBackend,
    WireFaultSpec,
)

FAST = dict(
    election_timeout=(0.1, 0.2),
    heartbeat_s=0.03,
    dead_owner_s=1.0,
    submit_timeout_s=2.5,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Cluster:
    """In-process replication-layer cluster (the test_r7 idiom)."""

    def __init__(self, n=3, seed_bug=None, root=None, **overrides):
        self.root = root
        self.names = [f"n{i}" for i in range(n)]
        self.peers = {nm: ("127.0.0.1", _free_port())
                      for nm in self.names}
        self.seed_bug = seed_bug
        self.opts = {**FAST, **overrides}
        self.backends: dict[str, ReplicatedBackend] = {}
        for i, nm in enumerate(self.names):
            self._boot(nm, i)

    def _boot(self, nm: str, idx: int) -> None:
        self.backends[nm] = ReplicatedBackend(
            nm,
            self.peers,
            seed_bug=self.seed_bug,
            rng_seed=1000 + idx,
            data_dir=(
                None if self.root is None else f"{self.root}/{nm}"
            ),
            **self.opts,
        )

    def leader(self, timeout=8.0) -> str:
        deadline = time.monotonic() + scaled(timeout)
        while time.monotonic() < deadline:
            for nm, b in self.backends.items():
                if b.raft.is_leader():
                    return nm
            time.sleep(0.02)
        raise AssertionError("no leader")

    def crash_restart_all(self) -> None:
        """The power failure: stop every node, reboot from the WALs."""
        assert self.root is not None, "crash-restart needs durable dirs"
        for b in self.backends.values():
            b.stop()
        # ports are being rebound immediately: retry transient clashes
        for i, nm in enumerate(self.names):
            for attempt in range(40):
                try:
                    self._boot(nm, i)
                    break
                except OSError:
                    if attempt == 39:
                        raise
                    time.sleep(0.1)

    def one_way_out(self, victim: str) -> None:
        """NOBODY hears ``victim``; it hears everyone (the
        partition-one-way-out grudge, applied directly)."""
        for nm, b in self.backends.items():
            if nm != victim:
                b.raft.block(victim)

    def heal(self) -> None:
        for b in self.backends.values():
            b.raft.unblock_all()

    def queue_bodies(self, nm: str, q: str) -> list[bytes]:
        m = self.backends[nm].machine
        with m.lock:
            return [msg.body for msg in m.queues.get(q, ())]

    def converged(self, q: str, timeout=8.0) -> bool:
        deadline = time.monotonic() + scaled(timeout)
        while time.monotonic() < deadline:
            views = {
                nm: tuple(self.queue_bodies(nm, q))
                for nm in self.names
            }
            if len(set(views.values())) == 1:
                return True
            time.sleep(0.05)
        return False

    def stop(self) -> None:
        for b in self.backends.values():
            b.stop()


# ---------------------------------------------------------------------------
# Family 1: slow-disk / fsync latency
# ---------------------------------------------------------------------------


class TestSlowDisk:
    def test_green_durable_cluster_survives_slow_disks_and_power_loss(
        self, tmp_path
    ):
        """Fsync latency on EVERY node: confirms must actually stall
        (the fault is real) yet everything confirmed survives a
        whole-cluster crash-restart — the correct-durable green."""
        c = _Cluster(root=str(tmp_path / "d"))
        try:
            lead = c.leader()
            b = c.backends[lead]
            b.declare("q")
            assert b.enqueue("q", b"0", b"") is True  # fast baseline
            for nm in c.names:
                c.backends[nm].raft.set_fsync_latency(60.0, 20.0)
            acked = [b"0"]
            t0 = time.monotonic()
            for v in (b"1", b"2", b"3"):
                if c.backends[c.leader()].enqueue("q", v, b""):
                    acked.append(v)
            stalled = time.monotonic() - t0
            # 3 submits x (leader WAL + majority replication, each
            # fsync >=40ms): well over 120ms in aggregate — proves the
            # latency reached the write path (no-silent-no-op)
            assert stalled > 0.12, f"fsync stall never happened ({stalled:.3f}s)"
            assert len(acked) >= 3
            c.crash_restart_all()
            c.leader(timeout=12.0)
            # recovery replays the WAL as the new leader's noop commit
            # advances — poll until the confirmed set is back (an
            # all-empty snapshot taken before replay proves nothing)
            deadline = time.monotonic() + scaled(12.0)
            recovered: set[bytes] = set()
            while time.monotonic() < deadline and not (
                set(acked) <= recovered
            ):
                recovered = set(c.queue_bodies(c.names[0], "q"))
                time.sleep(0.05)
            missing = set(acked) - recovered
            assert missing == set(), (
                f"slow disk lost confirmed values: {missing}"
            )
            assert c.converged("q", timeout=8.0)
        finally:
            c.stop()

    def test_red_ack_before_fsync_under_the_same_schedule(self, tmp_path):
        """The same slow-disk + power-loss schedule over the
        ``ack-before-fsync`` seeded bug: the lying node is FAST (the
        tell) and confirmed values vanish — the family's red."""
        c = _Cluster(root=str(tmp_path / "d"), seed_bug="ack-before-fsync")
        try:
            lead = c.leader()
            b = c.backends[lead]
            b.declare("q")
            for nm in c.names:
                # the seeded bug never reaches the (slowed) disk, so
                # this latency is installed yet cannot stall anything
                c.backends[nm].raft.set_fsync_latency(60.0, 20.0)
            acked = []
            t0 = time.monotonic()
            for v in (b"1", b"2", b"3"):
                if b.enqueue("q", v, b""):
                    acked.append(v)
            fast = time.monotonic() - t0
            assert acked, "nothing confirmed"
            # the tell: a node lying about fsync confirms at full speed
            # under a disk that should cost >=40ms per write
            assert fast < 1.0
            c.crash_restart_all()
            c.leader(timeout=12.0)
            time.sleep(0.5)
            recovered = set()
            for nm in c.names:
                recovered |= set(c.queue_bodies(nm, "q"))
            lost = set(acked) - recovered
            assert lost, (
                "ack-before-fsync under the slow-disk schedule lost "
                "nothing — the red pair no longer catches the bug"
            )
        finally:
            c.stop()

    def test_memory_only_node_refuses_the_fault(self):
        """No WAL, no fault: the latency hook refuses rather than
        silently no-opping (the false-green-by-absent-fault class)."""
        c = _Cluster()
        try:
            with pytest.raises(ValueError, match="memory-only"):
                c.backends[c.names[0]].raft.set_fsync_latency(50.0)
        finally:
            c.stop()


# ---------------------------------------------------------------------------
# Family 2: asymmetric one-way partitions
# ---------------------------------------------------------------------------


class TestOneWayPartition:
    def test_green_correct_cluster_survives_one_way_out(self):
        """Nobody hears the leader, it hears everyone: the majority
        elects past it, the deposed leader truncates nothing committed,
        every confirmed value survives the heal."""
        c = _Cluster()
        try:
            lead = c.leader()
            b = c.backends[lead]
            b.declare("q")
            assert b.enqueue("q", b"1", b"") is True
            c.one_way_out(lead)
            # the old leader must NOT confirm into the void: a correct
            # submit either times out (no acks arrive) or forwards
            ok, _ = b.raft.submit(
                {"k": "enq", "q": "q", "body": "Mg==", "props": "",
                 "ts": 0.0},
                timeout_s=1.0,
            )
            # a new leader rises among the majority (they stopped
            # hearing the old one's appends)
            deadline = time.monotonic() + scaled(8.0)
            new_lead = None
            while time.monotonic() < deadline and new_lead is None:
                for nm, nb in c.backends.items():
                    if nm != lead and nb.raft.is_leader():
                        new_lead = nm
                time.sleep(0.02)
            assert new_lead, "majority never elected past the muted leader"
            assert c.backends[new_lead].enqueue("q", b"3", b"") is True
            c.heal()
            assert c.converged("q", timeout=8.0)
            bodies = set(c.queue_bodies(lead, "q"))
            assert b"1" in bodies and b"3" in bodies
            if ok:  # the old leader's submit may have legally forwarded
                assert b"2" in bodies
        finally:
            c.stop()

    def test_red_confirm_before_quorum_truncates_through_one_way_out(self):
        """The same one-way-out window over ``confirm-before-quorum``:
        the muted leader confirms on local append, the majority's new
        term truncates it — a confirmed write is GONE (what the checker
        must flag as lost)."""
        c = _Cluster(seed_bug="confirm-before-quorum")
        try:
            lead = c.leader()
            b = c.backends[lead]
            b.declare("q")
            assert b.enqueue("q", b"1", b"") is True
            time.sleep(0.2)  # let the declare+first enq replicate
            c.one_way_out(lead)
            # THE BUG: local-append confirm while nobody can hear it
            assert b.enqueue("q", b"2", b"") is True
            deadline = time.monotonic() + scaled(8.0)
            new_lead = None
            while time.monotonic() < deadline and new_lead is None:
                for nm, nb in c.backends.items():
                    if nm != lead and nb.raft.is_leader():
                        new_lead = nm
                time.sleep(0.02)
            assert new_lead, "majority never elected past the muted leader"
            c.heal()
            assert c.converged("q", timeout=8.0)
            bodies = set(c.queue_bodies(lead, "q"))
            assert b"2" not in bodies, (
                "the confirmed-without-quorum value SURVIVED — the "
                "one-way window no longer exposes confirm-before-quorum"
            )
            # only the pre-window write is guaranteed: with the bug on
            # every node, even the new leader's confirms are unsafe
            assert b"1" in bodies
        finally:
            c.stop()

    def test_sim_net_refuses_asymmetric_strategies(self):
        """A net that symmetrizes grudges must refuse a one-way
        strategy instead of silently running the two-way fault."""
        from jepsen_tpu.control.nemesis import PartitionNemesis
        from jepsen_tpu.control.net import SimNet

        net = SimNet(cluster=None)
        with pytest.raises(ValueError, match="one-way"):
            PartitionNemesis(
                "partition-one-way-out", net, ["a", "b", "c"], seed=1
            )

    def test_one_way_grudges_are_directed(self):
        """The strategy functions themselves: exactly one direction."""
        import random

        from jepsen_tpu.control.nemesis import one_way_in, one_way_out

        nodes = ["a", "b", "c"]
        g_in = one_way_in(nodes, random.Random(0))
        (victim,) = g_in.keys()
        assert g_in[victim] == set(nodes) - {victim}
        g_out = one_way_out(nodes, random.Random(0))
        assert victim not in g_out  # the victim drops nothing
        assert all(v == {victim} for v in g_out.values())


# ---------------------------------------------------------------------------
# Family 3: wire corruption / duplication / reordering
# ---------------------------------------------------------------------------


class TestWireChaos:
    def _run_traffic(self, c: _Cluster, n_ops: int = 40) -> list[bytes]:
        lead = c.leader()
        b = c.backends[lead]
        b.declare("q")
        acked: list[bytes] = []
        for i in range(n_ops):
            v = f"{10000 + i}".encode()  # digit-rich bodies (the
            # corruptor flips digits — payload bytes dominate real
            # frames, and these are all payload)
            if c.backends[c.leader()].enqueue("q", v, b""):
                acked.append(v)
        return acked

    def test_green_checksummed_wire_drops_corruption(self):
        """Heavy corrupt+duplicate+delay on the leader's wire: every
        mangled frame is dropped on CRC (degrading to retried loss),
        replicas converge byte-identically, nothing confirmed is lost,
        nothing phantom appears."""
        c = _Cluster()
        try:
            lead = c.leader()
            spec = WireFaultSpec(
                corrupt_p=0.5, duplicate_p=0.3, delay_p=0.2,
                delay_ms=30.0,
            )
            c.backends[lead].raft.set_wire_faults(spec)
            acked = self._run_traffic(c)
            assert len(acked) >= 10, "chaos starved all progress"
            c.backends[lead].raft.set_wire_faults(None)
            assert c.converged("q", timeout=10.0), (
                "replicas diverged UNDER CHECKSUMS"
            )
            bodies = set(c.queue_bodies(c.names[0], "q"))
            assert set(acked) - bodies == set(), "confirmed value lost"
            # no phantom: every body present was genuinely sent (an
            # unacked-but-present value is a legal indeterminate
            # commit; a never-sent byte pattern would be corruption
            # applied instead of dropped)
            sent = {f"{10000 + i}".encode() for i in range(40)}
            assert bodies <= sent, f"phantom bodies: {bodies - sent}"
        finally:
            c.stop()

    def test_red_no_wire_checksum_diverges_replicas(self):
        """The same chaos over ``no-wire-checksum``: mangled-but-
        parseable frames are PROCESSED, a corrupted entry body lands in
        one replica's state machine, and the replicas silently diverge
        (the phantom/lost pair a client would observe)."""
        c = _Cluster(seed_bug="no-wire-checksum")
        try:
            lead = c.leader()
            c.backends[lead].raft.set_wire_faults(
                WireFaultSpec(corrupt_p=0.6)
            )

            def snap(nm):
                m = c.backends[nm].machine
                with m.lock:
                    return [
                        (msg.mid, msg.ts_ms, msg.body)
                        for msg in m.queues.get("q", ())
                    ]

            def diverged() -> bool:
                # zip-compare per position (queue order = commit order,
                # stable under lag: a shorter replica is just behind —
                # only a DIFFERENT entry at the same slot is divergence.
                # Any field counts: a mutated body is a phantom value, a
                # mutated ts diverges TTL expiry across replicas).
                views = [snap(nm) for nm in c.names]
                for a in views:
                    for b2 in views:
                        if any(x != y for x, y in zip(a, b2)):
                            return True
                return False

            b = c.backends[lead]
            b.declare("q")
            deadline = time.monotonic() + scaled(30.0)
            i = 0
            while not diverged() and time.monotonic() < deadline:
                v = f"{10000 + i}".encode()
                i += 1
                c.backends[c.leader()].enqueue("q", v, b"")
            assert diverged(), (
                "corruption never slipped a mangled frame through the "
                "unchecksummed wire — the red pair no longer catches "
                "no-wire-checksum"
            )
        finally:
            c.stop()

    def test_corrupt_frame_flips_exactly_one_digit(self):
        import random

        from jepsen_tpu.harness.replication import corrupt_frame

        data = b'{"rpc":"append_entries","term":12,"body":"abc123"}'
        rng = random.Random(7)
        out = corrupt_frame(data, rng)
        assert out != data and len(out) == len(data)
        diffs = [
            (a, x) for a, x in zip(data, out) if a != x
        ]
        assert len(diffs) == 1
        old, new = diffs[0]
        assert chr(old).isdigit() and chr(new).isdigit()
        import json

        json.loads(out)  # digit->digit corruption keeps JSON parseable


# ---------------------------------------------------------------------------
# make_nemesis opts validation: loud, never a silent no-op
# ---------------------------------------------------------------------------


class _StubNet:
    one_way = True

    def partition(self, grudges):
        pass

    def heal(self):
        pass


class _StubSurface:
    def __getattr__(self, name):
        return lambda *a, **k: None


class TestMakeNemesisValidation:
    def _mk(self, opts, **kw):
        from jepsen_tpu.control.nemesis import make_nemesis

        kw.setdefault("net", _StubNet())
        kw.setdefault("procs", _StubSurface())
        kw.setdefault("nodes", ["a", "b", "c"])
        return make_nemesis(opts, kw.pop("net"), kw.pop("procs"),
                            kw.pop("nodes"), **kw)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown nemesis"):
            self._mk({"nemesis": "zap-the-router"})

    def test_unknown_fault_tunable_rejected(self):
        with pytest.raises(ValueError, match="unknown nemesis option"):
            self._mk({
                "nemesis": "wire-chaos",
                "wire-corruptt": 0.5,  # the typo must not run defaults
            }, wire=_StubSurface())

    def test_slow_disk_needs_surface_and_durable(self):
        with pytest.raises(ValueError, match="disks surface"):
            self._mk({"nemesis": "slow-disk", "durable": True})
        with pytest.raises(ValueError, match="durable"):
            self._mk({"nemesis": "slow-disk"}, disks=_StubSurface())

    def test_wire_chaos_needs_surface_and_nonzero_rates(self):
        with pytest.raises(ValueError, match="wire surface"):
            self._mk({"nemesis": "wire-chaos"})
        with pytest.raises(ValueError, match="no-fault no-op"):
            self._mk({
                "nemesis": "wire-chaos",
                "wire-corrupt": 0.0, "wire-duplicate": 0.0,
                "wire-delay": 0.0,
            }, wire=_StubSurface())
        with pytest.raises(ValueError, match="outside"):
            self._mk({
                "nemesis": "wire-chaos", "wire-corrupt": 1.5,
            }, wire=_StubSurface())

    def test_partition_without_strategy_rejected(self):
        with pytest.raises(ValueError, match="partition strategy"):
            self._mk({"nemesis": "partition"})

    def test_explicit_schedule_rejected_outside_fuzz_runner(self):
        with pytest.raises(ValueError, match="nemesis-schedule"):
            self._mk({
                "nemesis": "partition",
                "network-partition": "partition-halves",
                "nemesis-schedule": [[1.0, 2.0]],
            })

    def test_slow_disk_zero_latency_rejected(self):
        with pytest.raises(ValueError, match="no-fault no-op"):
            self._mk({
                "nemesis": "slow-disk", "durable": True,
                "slow-disk-mean-ms": 0.0, "slow-disk-jitter-ms": 0.0,
            }, disks=_StubSurface())


class TestScheduledNemesis:
    def test_schedule_validation_is_loud(self):
        from jepsen_tpu.fuzz.schedule import (
            NemesisEvent,
            validate_events,
        )

        ok = [
            NemesisEvent(1.0, 2.0, "kill", 1),
            NemesisEvent(4.0, 1.0, "partition", 2),
        ]
        validate_events(ok, 10.0)
        with pytest.raises(ValueError, match="unknown nemesis family"):
            validate_events([NemesisEvent(1.0, 1.0, "gremlin", 1)], 10.0)
        with pytest.raises(ValueError, match="overlaps"):
            validate_events(
                [NemesisEvent(1.0, 3.0, "kill", 1),
                 NemesisEvent(2.0, 1.0, "pause", 2)], 10.0,
            )
        with pytest.raises(ValueError, match="never fire"):
            validate_events([NemesisEvent(11.0, 1.0, "kill", 1)], 10.0)

    def test_missing_surface_is_a_build_error(self):
        from jepsen_tpu.fuzz.schedule import (
            NemesisEvent,
            ScheduledNemesis,
        )

        with pytest.raises(ValueError, match="no fault surface"):
            ScheduledNemesis(
                [NemesisEvent(1.0, 1.0, "slow-disk", 1)],
                {"time-limit": 10.0},  # not durable, no disks surface
                _StubNet(), _StubSurface(), ["a", "b", "c"],
            )

    def test_generator_emits_start_stop_at_offsets(self):
        from jepsen_tpu.fuzz.schedule import schedule_generator
        from jepsen_tpu.generators.core import Ctx, Pending
        from jepsen_tpu.history.ops import OpF

        gen = schedule_generator([[1.0, 2.0], [5.0, 1.0]])

        def at(t_s):
            return Ctx(time=int(t_s * 1e9), thread=-1, process=-1,
                       n_threads=1)

        got = gen.next_for(at(0.0))
        assert isinstance(got, Pending) and got.wake == int(1e9)
        assert gen.next_for(at(1.0)).f == OpF.START
        assert isinstance(gen.next_for(at(1.5)), Pending)
        assert gen.next_for(at(3.0)).f == OpF.STOP
        assert gen.next_for(at(5.0)).f == OpF.START
        assert gen.next_for(at(6.0)).f == OpF.STOP
        assert gen.next_for(at(7.0)) is None
