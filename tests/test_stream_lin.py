"""Stream (append-only log) linearizability: anomaly detection + CPU≡TPU.

BASELINE.json config #4.  Every case runs the CPU reference and the TPU
kernel and asserts identical result maps (differential testing — SURVEY.md
§4.5), then asserts the injected ground truth is detected.
"""

import pytest

from jepsen_tpu.checkers.stream_lin import (
    FULL_READ,
    check_stream_lin_batch,
    check_stream_lin_cpu,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import (
    StreamSynthSpec,
    synth_stream_batch,
    synth_stream_history,
)


def both(history, append_fail="definite"):
    cpu = check_stream_lin_cpu(history, append_fail=append_fail)
    tpu = check_stream_lin_batch([history], append_fail=append_fail)[0]
    assert cpu == tpu, f"cpu/tpu divergence:\n{cpu}\n{tpu}"
    return cpu


def test_clean_history_linearizable():
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=21))
    assert sh.clean
    r = both(sh.ops)
    assert r["valid?"]
    assert r["full-read"]
    assert r["acknowledged-count"] <= r["attempt-count"]


def test_lost_append_detected():
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=22, lost=2))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["lost"] == sh.lost


def test_lost_not_judged_without_full_read():
    spec = StreamSynthSpec(n_ops=300, seed=23, lost=2, full_reads=False)
    sh = synth_stream_history(spec)
    r = both(sh.ops)
    assert not r["full-read"]
    assert r["lost"] == set()
    assert r["valid?"]


def test_duplicate_offset_detected():
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=24, duplicated=2))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["duplicate"] == sh.duplicated


def test_divergent_offset_detected():
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=25, divergent=2))
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.divergent <= r["divergent"]


def test_divergent_single_consumer_vs_incremental_read():
    sh = synth_stream_history(
        StreamSynthSpec(n_ops=300, seed=26, n_consumers=1, divergent=1)
    )
    r = both(sh.ops)
    if sh.divergent:  # needs an incrementally-read prefix to disagree with
        assert not r["valid?"]
        assert sh.divergent <= r["divergent"]


def test_phantom_detected():
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=27, phantom=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.phantom <= r["phantom"]


def test_reorder_detected():
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=28, reorder=1))
    r = both(sh.ops)
    assert sh.reorder, "injection must have materialized"
    assert not r["valid?"]
    # reorder-only injection: ground truth is exactly the jumped-over
    # offsets the checker's suffix-min rule flags
    assert r["reorder"] == sh.reorder


def test_multiple_reorders_ground_truth_exact():
    # two moves shift the log under each other — ground truth must be
    # computed against the final log, not per-move
    sh = synth_stream_history(StreamSynthSpec(n_ops=300, seed=29, reorder=2))
    r = both(sh.ops)
    assert sh.reorder, "injection must have materialized"
    assert not r["valid?"]
    assert r["reorder"] == sh.reorder


def test_nonmonotonic_batch_detected():
    sh = synth_stream_history(
        StreamSynthSpec(n_ops=300, seed=29, nonmonotonic=2)
    )
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["nonmonotonic-count"] == sh.nonmonotonic == 2


def test_rewind_between_reads_is_legal():
    # separate read ops may re-attach at an earlier offset; only
    # within-batch regressions are violations
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op(OpType.OK, OpF.APPEND, 0, 0),
            Op.invoke(OpF.APPEND, 0, 1),
            Op(OpType.OK, OpF.APPEND, 0, 1),
            Op.invoke(OpF.READ, 1, 0),
            Op(OpType.OK, OpF.READ, 1, [[0, 0], [1, 1]]),
            Op.invoke(OpF.READ, 1, 0),  # rewind to offset 0
            Op(OpType.OK, OpF.READ, 1, [[0, 0], [1, 1]]),
        ]
    )
    r = both(ops)
    assert r["valid?"]
    assert r["nonmonotonic-count"] == 0


def test_indeterminate_append_read_is_legal():
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op(OpType.INFO, OpF.APPEND, 0, 0, error="timeout"),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.OK, OpF.READ, 1, [[0, 0]]),
        ]
    )
    r = both(ops)
    assert r["valid?"]  # info append may have taken effect — not a phantom


def test_indeterminate_append_unread_is_not_lost():
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op(OpType.OK, OpF.APPEND, 0, 0),
            Op.invoke(OpF.APPEND, 0, 1),
            Op(OpType.INFO, OpF.APPEND, 0, 1, error="timeout"),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.OK, OpF.READ, 1, [[0, 0]]),
        ]
    )
    r = both(ops)
    assert r["valid?"]  # only *acked* appends must surface in the full read
    assert r["lost"] == set()


def test_failed_append_read_scoped_by_append_fail_contract():
    """r5 stream burn-in find: a 29-s partition stall returned
    ConnectionError for appends the broker had committed; the client's
    ``fail`` is the reference's own mapping for unexpected exceptions
    (``rabbitmq.clj:211-213``) and on a real-socket SUT is the CLIENT's
    verdict, not the broker's.  Under ``append_fail="indeterminate"``
    (the live assemblies) the read is ``recovered`` (reported, run stays
    valid) — the bucket ``total-queue`` already carries.  Under the
    default ``definite`` contract (the sim, whose False return IS
    authoritative) it stays an invalidating phantom — forgiveness must
    never leak into the substrate whose fails are exact (review r5)."""
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 7),
            Op(OpType.FAIL, OpF.APPEND, 0, 7, error="publish-failed"),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.OK, OpF.READ, 1, [[0, 7]]),
        ]
    )
    r = both(ops, append_fail="indeterminate")
    assert r["valid?"]
    assert r["recovered"] == {7}
    assert r["phantom"] == set()
    assert r["append-fail"] == "indeterminate"

    strict = both(ops)  # definite is the default
    assert not strict["valid?"]
    assert strict["phantom"] == {7}
    assert strict["recovered"] == set()


def test_synth_recovered_injection_differential():
    """The synth `recovered` knob produces the connection-error-after-
    commit shape with exact ground truth, CPU ≡ TPU under both
    contracts (review r5: the bucket needs random coverage, not just
    one handcrafted history)."""
    from jepsen_tpu.history.synth import synth_stream_batch

    hit = 0
    for sh in synth_stream_batch(
        6, StreamSynthSpec(n_ops=120), recovered=2
    ):
        if not sh.recovered:
            continue  # no mutable tail under this seed
        hit += 1
        lenient = both(sh.ops, append_fail="indeterminate")
        assert lenient["valid?"]
        assert lenient["recovered"] == sh.recovered
        strict = both(sh.ops)
        assert not strict["valid?"]
        assert strict["phantom"] >= sh.recovered
    assert hit >= 3  # the injection actually fires across seeds


def test_never_attempted_read_is_still_phantom():
    """The invalidating half survives the recovered split: a value with
    NO append invocation at all is fabricated data."""
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 1),
            Op(OpType.OK, OpF.APPEND, 0, 1),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.OK, OpF.READ, 1, [[0, 1], [1, 999]]),
        ]
    )
    r = both(ops)
    assert not r["valid?"]
    assert r["phantom"] == {999}
    assert r["recovered"] == set()


def test_real_time_reorder_minimal():
    # append(0) completes before append(1) is invoked, but 0 lands at the
    # higher offset — no linearization order exists
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op(OpType.OK, OpF.APPEND, 0, 0),
            Op.invoke(OpF.APPEND, 0, 1),
            Op(OpType.OK, OpF.APPEND, 0, 1),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.OK, OpF.READ, 1, [[0, 1], [1, 0]]),
        ]
    )
    r = both(ops)
    assert not r["valid?"]
    assert r["reorder"] == {0}


def test_concurrent_appends_any_order_is_legal():
    # both appends in flight simultaneously — either log order linearizes
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op.invoke(OpF.APPEND, 1, 1),
            Op(OpType.OK, OpF.APPEND, 0, 0),
            Op(OpType.OK, OpF.APPEND, 1, 1),
            Op.invoke(OpF.READ, 2, FULL_READ),
            Op(OpType.OK, OpF.READ, 2, [[0, 1], [1, 0]]),
        ]
    )
    r = both(ops)
    assert r["valid?"]


def test_batch_of_mixed_histories():
    shs = synth_stream_batch(6, StreamSynthSpec(n_ops=200))
    shs += synth_stream_batch(2, StreamSynthSpec(n_ops=200, seed=50), lost=1)
    rs = check_stream_lin_batch([sh.ops for sh in shs])
    for sh, r in zip(shs, rs):
        assert r["valid?"] == sh.clean
        assert r == check_stream_lin_cpu(sh.ops)


def test_aborted_full_read_does_not_judge_loss():
    # a full read that never completes ok observed nothing — unread acked
    # appends are merely unread, not lost
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op(OpType.OK, OpF.APPEND, 0, 0),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.INFO, OpF.READ, 1, error="connection-lost"),
        ]
    )
    r = both(ops)
    assert not r["full-read"]
    assert r["lost"] == set()
    assert r["valid?"]


def test_divergent_offset_with_two_appended_values_cpu_eq_tpu():
    # both observed values at offset 0 were really appended — the CPU
    # reference and the kernel must combine them identically (reorder
    # representative = max s / min e)
    ops = reindex(
        [
            Op.invoke(OpF.APPEND, 0, 0),
            Op(OpType.OK, OpF.APPEND, 0, 0),
            Op.invoke(OpF.APPEND, 0, 1),
            Op(OpType.OK, OpF.APPEND, 0, 1),
            Op.invoke(OpF.APPEND, 0, 5),
            Op(OpType.OK, OpF.APPEND, 0, 5),
            Op.invoke(OpF.READ, 1, FULL_READ),
            Op(OpType.OK, OpF.READ, 1, [[0, 0], [1, 1]]),
            Op.invoke(OpF.READ, 2, 0),
            Op(OpType.OK, OpF.READ, 2, [[0, 5]]),
        ]
    )
    r = both(ops)  # both() asserts CPU == TPU exactly
    assert not r["valid?"]
    assert r["divergent"] == {0}


def test_ten_k_op_history():
    # the BASELINE config-#4 scale point: 10k-op single-partition histories
    sh = synth_stream_history(StreamSynthSpec(n_ops=4000, seed=31, lost=1))
    assert len(sh.ops) >= 10_000
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["lost"] == sh.lost
