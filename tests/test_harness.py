"""Matrix runner triage semantics + timeline rendering."""

from jepsen_tpu.checkers.timeline import render_timeline
from jepsen_tpu.harness.matrix import CI_MATRIX, MatrixRunner, matrix_opts
from jepsen_tpu.history.synth import SynthSpec, synth_history


def test_matrix_has_reference_shape():
    assert len(CI_MATRIX) == 14
    opts = matrix_opts(CI_MATRIX[0])
    # textually the reference's own spelling (ci/jepsen-test.sh:93)
    assert opts["network-partition"] == "random-partition-halves"
    assert opts["partition-duration"] == 30.0
    assert opts["time-limit"] == 180.0
    # dead-letter configs present (12th/13th entries)
    assert sum(1 for c in CI_MATRIX if c.get("dead-letter")) == 2
    assert sum(
        1 for c in CI_MATRIX if c.get("quorum-initial-group-size") == 3
    ) == 2


def _results(valid=True, attempts=10, ok=9):
    return {
        "valid?": valid,
        "queue": {"valid?": valid, "attempt-count": attempts, "ok-count": ok},
    }


def test_valid_run_passes_first_attempt():
    runner = MatrixRunner(
        lambda opts: (_results(), {"jepsen.queue": 0}), CI_MATRIX[:2]
    )
    outcomes = runner.run()
    assert all(o.status == "valid" and o.attempts == 1 for o in outcomes)


def test_analysis_invalid_fails_without_retry():
    calls = []

    def run_fn(opts):
        calls.append(1)
        return _results(valid=False), {"jepsen.queue": 0}

    outcomes = MatrixRunner(run_fn, CI_MATRIX[:1]).run()
    assert outcomes[0].status == "invalid"
    assert len(calls) == 1  # genuine violation: no retry


def test_crash_retries_then_errors():
    calls = []

    def run_fn(opts):
        calls.append(1)
        raise RuntimeError("ssh broke")

    outcomes = MatrixRunner(run_fn, CI_MATRIX[:1]).run()
    assert outcomes[0].status == "error"
    assert len(calls) == 3


def test_final_read_missing_retries_then_succeeds():
    calls = []

    def run_fn(opts):
        calls.append(1)
        if len(calls) == 1:
            return _results(ok=0), {"jepsen.queue": 0}  # set never read
        return _results(), {"jepsen.queue": 0}

    outcomes = MatrixRunner(run_fn, CI_MATRIX[:1]).run()
    assert outcomes[0].status == "valid"
    assert outcomes[0].attempts == 2


def test_undrained_queue_with_valid_verdict_exhausts_to_error():
    """Persistent leftover + clean verdict: retried (late-commit race),
    and if it never clears, the config ends 'error' — never silently
    valid, never a fabricated violation."""
    outcomes = MatrixRunner(
        lambda opts: (_results(), {"jepsen.queue": 4}), CI_MATRIX[:1]
    ).run()
    assert outcomes[0].status == "error"
    assert all("not drained" in n for n in outcomes[0].notes[:-1])
    assert outcomes[0].notes[-1] == "all attempts exhausted"


def test_timeline_renders(tmp_path):
    sh = synth_history(SynthSpec(n_ops=80, seed=51))
    p = render_timeline(sh.ops, tmp_path / "timeline.html")
    content = p.read_text()
    assert content.startswith("<!doctype html>")
    assert 'class="op"' in content
    assert "proc 0" in content
    assert content.count('class="row"') >= 5


def test_leftover_with_valid_verdict_retries_not_invalid():
    """Clean verdict + non-empty queue = late-committing indeterminate
    publishes (the client timed out mid-election; its entry was already
    in the Raft log and committed after the drain) — an inherent quorum-
    system race, not a violation: retry, and pass on a clean attempt."""
    calls = []

    def run_fn(opts):
        calls.append(1)
        leftover = {"jepsen.queue@n1": 1} if len(calls) == 1 else {}
        return _results(valid=True), leftover

    (o,) = MatrixRunner(run_fn, CI_MATRIX[:1]).run()
    assert o.status == "valid" and o.attempts == 2
    assert "late indeterminate commits" in o.notes[0]


def test_leftover_with_invalid_verdict_is_final():
    """Leftover + invalid verdict stays a final failure (genuine loss
    territory — the reference's queue-empty contract)."""

    def run_fn(opts):
        return _results(valid=False), {"jepsen.queue@n1": 3}

    (o,) = MatrixRunner(run_fn, CI_MATRIX[:1]).run()
    assert o.status == "invalid" and o.attempts == 1
