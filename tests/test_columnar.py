"""The ``.jtc`` zero-copy columnar substrate (``history/columnar.py``).

Three gates:

1. **Format honesty** — round-trip bit-identity, the two-tier freshness
   contract, and the corruption classes: a flipped byte, a truncated
   tail, or a stale format version must raise a LOUD
   :class:`ColumnarFormatError`; the cache layers may fall back to the
   legacy parse only with the reason logged (pinned alongside the
   ``BadZipFile`` guards of the npz era).
2. **Differential** — the columnar path must be verdict-identical to
   the JSONL-parse path for all three checker families (including the
   degenerate-elle host-fallback splice), through record→check and
   through concurrent-lane striped reads.
3. **Migration** — ``tools/migrate_store.py`` is idempotent and refuses
   corrupt substrates.
"""

from __future__ import annotations

import logging
import os
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from jepsen_tpu.history import columnar
from jepsen_tpu.history.columnar import (
    ColumnarFormatError,
    jtc_path_for,
    load_jtc,
    pack_jtc,
    read_jtc,
    write_jtc,
)
from jepsen_tpu.history.store import (
    Store,
    read_history,
    write_history_jsonl,
)
from jepsen_tpu.history.synth import (
    ElleSynthSpec,
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_elle_batch,
    synth_stream_batch,
)

REPO = Path(__file__).resolve().parent.parent


def _write(td, shs, prefix="h"):
    files = []
    for i, sh in enumerate(shs):
        p = Path(td) / f"{prefix}{i:03d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(p)
    return files


def _pack_all(files):
    # ensure the .jtc mtime strictly exceeds the source's (same-tick
    # writes would force the digest path — fine, but slower)
    time.sleep(0.01)
    for f in files:
        pack_jtc(f)


# ---------------------------------------------------------------------------
# 1. Format honesty
# ---------------------------------------------------------------------------


class TestFormat:
    def test_roundtrip_queue_rows_bitwise(self, tmp_path):
        from jepsen_tpu.history.rows import _rows_for

        h = synth_batch(1, SynthSpec(n_ops=40, seed=1), lost=1)[0].ops
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, h)
        _pack_all([p])
        jtc = load_jtc(p)
        assert jtc is not None and jtc.workload == "queue"
        np.testing.assert_array_equal(jtc.rows(), _rows_for(h))
        # zero-copy contract: the view maps the file, it does not own a
        # host copy (read-only buffer)
        assert not jtc.rows().flags.writeable

    def test_roundtrip_stream_and_elle_sections(self, tmp_path):
        from jepsen_tpu.checkers.elle import elle_mops_for
        from jepsen_tpu.checkers.stream_lin import _stream_rows

        (ps,) = _write(
            tmp_path, synth_stream_batch(1, StreamSynthSpec(n_ops=30)), "s"
        )
        (pe,) = _write(
            tmp_path,
            synth_elle_batch(1, ElleSynthSpec(n_txns=12), g1a=1),
            "e",
        )
        _pack_all([ps, pe])
        cols, full = load_jtc(ps).stream()
        rc, rf = _stream_rows(read_history(ps))
        np.testing.assert_array_equal(cols, rc)
        assert full == rf
        mat, meta = load_jtc(pe).emops()
        rm, rg = elle_mops_for(read_history(pe))
        np.testing.assert_array_equal(mat, rm)
        assert (meta.n_txns, meta.txn_index, meta.keys, meta.degenerate) == (
            rg.n_txns, rg.txn_index, rg.keys, rg.degenerate
        )

    def test_stale_on_source_rewrite(self, tmp_path):
        shs = synth_batch(2, SynthSpec(n_ops=40, seed=2), lost=1)
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, shs[0].ops)
        _pack_all([p])
        assert load_jtc(p) is not None
        write_history_jsonl(p, shs[1].ops)  # rewrite: substrate is stale
        assert load_jtc(p) is None  # a MISS, not an error

    def test_src_name_disambiguates_jsonl_vs_edn(self, tmp_path):
        """jsonl and edn twins share the sibling .jtc slot; the header's
        source-name stamp must keep one's substrate from serving the
        other."""
        h = synth_batch(1, SynthSpec(n_ops=20, seed=3))[0].ops
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, h)
        _pack_all([p])
        e = tmp_path / "history.edn"
        from jepsen_tpu.history.edn import write_history_edn

        write_history_edn(e, h)
        assert load_jtc(p) is not None
        assert load_jtc(e) is None  # packed from the jsonl, not the edn

    def test_format_version_roundtrip_and_stale_version(self, tmp_path):
        (p,) = _write(tmp_path, synth_batch(1, SynthSpec(n_ops=20)))
        _pack_all([p])
        target = jtc_path_for(p)
        jtc, stamp = read_jtc(target)  # structural round trip
        assert stamp["src_name"] == p.name
        assert jtc.rows() is not None
        raw = bytearray(target.read_bytes())
        raw[4] = 99  # version field
        target.write_bytes(raw)
        with pytest.raises(ColumnarFormatError, match="format version"):
            read_jtc(target)
        with pytest.raises(ColumnarFormatError, match="format version"):
            load_jtc(p)

    def test_write_discipline_verifies_before_rename(self, tmp_path):
        """write_jtc re-reads what hit the disk before installing; no
        temp file survives a failure."""
        (p,) = _write(tmp_path, synth_batch(1, SynthSpec(n_ops=20)))
        _pack_all([p])
        leftovers = [
            f for f in p.parent.iterdir() if f.name.endswith(".tmp")
        ]
        assert leftovers == []
        with pytest.raises(ValueError):
            write_jtc(p, "queue")  # section-less: refused loudly


class TestCorruptionHonesty:
    def _packed(self, tmp_path):
        (p,) = _write(
            tmp_path, synth_batch(1, SynthSpec(n_ops=40, seed=4), lost=1)
        )
        _pack_all([p])
        return p, jtc_path_for(p)

    def test_flipped_payload_byte_raises(self, tmp_path):
        p, t = self._packed(tmp_path)
        raw = bytearray(t.read_bytes())
        raw[-3] ^= 0xFF
        t.write_bytes(raw)
        with pytest.raises(ColumnarFormatError, match="checksum"):
            load_jtc(p)

    def test_flipped_header_byte_raises(self, tmp_path):
        p, t = self._packed(tmp_path)
        raw = bytearray(t.read_bytes())
        raw[50] ^= 0xFF  # inside the source stamp
        t.write_bytes(raw)
        with pytest.raises(ColumnarFormatError, match="header checksum"):
            load_jtc(p)

    def test_truncated_tail_raises(self, tmp_path):
        p, t = self._packed(tmp_path)
        raw = t.read_bytes()
        t.write_bytes(raw[: len(raw) - 16])
        with pytest.raises(ColumnarFormatError):
            load_jtc(p)

    def test_empty_file_raises(self, tmp_path):
        p, t = self._packed(tmp_path)
        t.write_bytes(b"")
        with pytest.raises(ColumnarFormatError):
            load_jtc(p)

    def test_fallback_is_never_silent(self, tmp_path, caplog):
        """The cache layer falls back to the legacy parse on a corrupt
        substrate — but ONLY with the reason logged (the satellite
        contract: never a silent re-parse)."""
        from jepsen_tpu.history.rows import _rows_for, load_rows_cache

        p, t = self._packed(tmp_path)
        raw = bytearray(t.read_bytes())
        raw[-3] ^= 0xFF
        t.write_bytes(raw)
        with caplog.at_level(
            logging.WARNING, "jepsen_tpu.history.columnar"
        ):
            assert load_rows_cache(p) is None
        assert any(
            "corrupt columnar substrate" in r.message
            for r in caplog.records
        )
        # and the parse path still yields the right rows
        from jepsen_tpu.history.rows import rows_with_cache

        wl, rows, _hit = rows_with_cache(p)
        assert wl == "queue"
        np.testing.assert_array_equal(
            rows, _rows_for(read_history(p))
        )

    def test_strict_mode_raises_through_the_cache_layer(
        self, tmp_path, monkeypatch
    ):
        from jepsen_tpu.history.rows import load_rows_cache

        p, t = self._packed(tmp_path)
        raw = bytearray(t.read_bytes())
        raw[-3] ^= 0xFF
        t.write_bytes(raw)
        monkeypatch.setenv("JEPSEN_TPU_JTC_STRICT", "1")
        with pytest.raises(ColumnarFormatError):
            load_rows_cache(p)

    def test_native_reader_refuses_corrupt_substrate(self, tmp_path):
        """The C++ fast path must also refuse (ERR_JTC -> None), never
        serve corrupt blocks or silently re-parse them itself."""
        from jepsen_tpu.history.fastpack import _load, pack_file

        if _load() is None:
            pytest.skip("native packer unavailable")
        p, t = self._packed(tmp_path)
        ref = pack_file(p)
        assert ref is not None  # served from the fresh substrate
        raw = bytearray(t.read_bytes())
        raw[-3] ^= 0xFF
        t.write_bytes(raw)
        assert pack_file(p) is None

    def test_native_serves_from_substrate(self, tmp_path):
        """Prove the native fast path reads the .jtc, not the JSONL:
        rewrite the source bytes in place with size+mtime restored (the
        stat fast path still holds) — the served rows must be the
        substrate's."""
        from jepsen_tpu.history.fastpack import _load, pack_file
        from jepsen_tpu.history.rows import _rows_for

        if _load() is None:
            pytest.skip("native packer unavailable")
        p, _t = self._packed(tmp_path)
        ref = _rows_for(read_history(p))
        st = os.stat(p)
        p.write_bytes(b"X" * st.st_size)  # same size, garbage bytes
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
        got = pack_file(p)
        assert got is not None and got[0] == "queue"
        np.testing.assert_array_equal(got[1], ref)


class TestSubstratePolicy:
    """The knobs that decide when the substrate may serve: the no-cache
    contract, the env kill switch's value semantics, and the
    name-field representability refusal (review findings, pinned)."""

    def _swapped_source(self, tmp_path):
        """A source whose .jtc is stat-fresh but holds DIFFERENT content
        than the live bytes (same size, mtime restored) — serving vs
        parsing is observable in the value column."""
        l1 = '{"type": "invoke", "f": "enqueue", "value": 11, "process": 0}\n'
        l2 = '{"type": "invoke", "f": "enqueue", "value": 22, "process": 0}\n'
        assert len(l1) == len(l2)
        p = tmp_path / "h.jsonl"
        p.write_text(l1)
        time.sleep(0.01)
        pack_jtc(p)  # substrate: value 11
        st = os.stat(p)
        p.write_text(l2)  # live bytes: value 22
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
        return p

    def test_no_cache_batch_genuinely_parses(self, tmp_path):
        """``use_jtc=False`` (what ``check_sources(use_cache=False)``
        passes down) must force a real parse — cached column blocks
        must not be re-served when the caller asked for independence."""
        from jepsen_tpu.history.fastpack import _load, pack_files

        if _load() is None:
            pytest.skip("native packer unavailable")
        p = self._swapped_source(tmp_path)
        (served,) = pack_files([p], use_jtc=True)
        (parsed,) = pack_files([p], use_jtc=False)
        assert served[1][0, 4] == 11  # the substrate's blocks
        assert parsed[1][0, 4] == 22  # the live bytes, parsed

    def test_env_value_zero_means_enabled_on_both_sides(
        self, tmp_path, monkeypatch
    ):
        """`JEPSEN_TPU_NO_JTC=0` must mean ENABLED for the Python
        loaders AND the native reader — a value-semantics split would
        cache to two different stores in one process."""
        from jepsen_tpu.history.fastpack import _load, pack_file

        p = self._swapped_source(tmp_path)
        monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "0")
        assert load_jtc(p) is not None
        monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "1")
        assert load_jtc(p) is None
        if _load() is not None:
            monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "0")
            assert pack_file(p)[1][0, 4] == 11  # served
            monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "1")
            assert pack_file(p)[1][0, 4] == 22  # parsed

    def test_long_basename_refused_and_npz_fallback(self, tmp_path):
        """A basename over the 32-byte name field is refused at write
        (a truncated stamp would never load — the substrate would be
        rewritten on every check yet never served); the best-effort
        savers fall back to the legacy npz so caching still works."""
        from jepsen_tpu.history.rows import (
            _rows_for,
            cache_path_for,
            load_rows_cache,
            save_rows_cache,
        )

        p = tmp_path / ("h" * 40 + ".jsonl")
        write_history_jsonl(
            p, synth_batch(1, SynthSpec(n_ops=20))[0].ops
        )
        with pytest.raises(ValueError, match="32-byte"):
            write_jtc(p, "queue", rows=np.zeros((1, 8), np.int32))
        save_rows_cache(p, "queue", _rows_for(read_history(p)))
        assert not jtc_path_for(p).exists()
        assert cache_path_for(p).exists()
        got = load_rows_cache(p)
        assert got is not None and got[0] == "queue"


# ---------------------------------------------------------------------------
# 2. Differential: columnar ≡ legacy parse, all families
# ---------------------------------------------------------------------------


def _degenerate_elle_ops():
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

    mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
    # the same value appended twice: elle_mops_for flags it degenerate,
    # routing this history through the host-inference fallback splice
    return reindex([mk([["append", 0, 1]]), mk([["append", 0, 1]])])


class TestDifferential:
    """Columnar and legacy paths must produce byte-identical verdicts
    (the acceptance gate)."""

    def _legacy_then_columnar(self, files, workload, monkeypatch, **opts):
        from jepsen_tpu.parallel.pipeline import check_sources

        monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "1")
        legacy, _ = check_sources(
            workload, files, chunk=4, serial=True, use_cache=False, **opts
        )
        monkeypatch.delenv("JEPSEN_TPU_NO_JTC")
        _pack_all(files)
        columnar_r, _ = check_sources(
            workload, files, chunk=4, use_cache=True, **opts
        )
        return legacy, columnar_r

    def test_queue_verdicts_identical(self, tmp_path, monkeypatch):
        base = synth_batch(
            8, SynthSpec(n_ops=50), lost=1, duplicated=1, unexpected=1
        )
        files = _write(tmp_path, base)
        legacy, col = self._legacy_then_columnar(
            files, "queue", monkeypatch
        )
        assert legacy == col

    def test_stream_verdicts_identical(self, tmp_path, monkeypatch):
        base = synth_stream_batch(
            8, StreamSynthSpec(n_ops=40), lost=1, duplicated=1
        )
        files = _write(tmp_path, base)
        legacy, col = self._legacy_then_columnar(
            files, "stream", monkeypatch
        )
        assert legacy == col

    def test_elle_verdicts_identical_with_degenerate_splice(
        self, tmp_path, monkeypatch
    ):
        base = synth_elle_batch(
            6, ElleSynthSpec(n_txns=16), g1a=1, g2_cycle=1
        )
        files = _write(tmp_path, base)
        pdeg = tmp_path / "degen.jsonl"
        write_history_jsonl(pdeg, _degenerate_elle_ops())
        files = files[:3] + [pdeg] + files[3:]
        legacy, col = self._legacy_then_columnar(
            files, "elle", monkeypatch
        )
        assert legacy == col
        # the degenerate history really took the host-fallback splice
        # through the columnar path too
        mat, meta = load_jtc(pdeg).emops()
        assert meta.degenerate

    def test_record_to_check_roundtrip(self, tmp_path):
        """Store.save_history cuts the substrate at record time; the
        first re-check maps it with zero parse and agrees with the CPU
        oracle."""
        from jepsen_tpu.checkers.queue_lin import check_queue_lin_cpu
        from jepsen_tpu.checkers.total_queue import check_total_queue_cpu
        from jepsen_tpu.history.rows import load_rows_cache
        from jepsen_tpu.parallel.pipeline import check_sources

        store = Store(tmp_path / "s")
        sh = synth_batch(1, SynthSpec(n_ops=40), lost=1)[0]
        d = store.run_dir("t")
        time.sleep(0.01)  # run-dir mkdir and history write same tick
        p = store.save_history(d, sh.ops)
        assert jtc_path_for(p).exists()
        assert load_rows_cache(p) is not None  # substrate hit, no parse
        results, _ = check_sources("queue", [p], chunk=1)
        assert (
            results[0]["queue"]["valid?"]
            == check_total_queue_cpu(sh.ops)["valid?"]
        )
        assert (
            results[0]["linear"]["valid?"]
            == check_queue_lin_cpu(sh.ops)["valid?"]
        )

    def test_striped_lane_reads_equal_full_scan(self, tmp_path, monkeypatch):
        """Concurrent-lane striped reads over the substrate ≡ the
        serial full scan (the scale-out acceptance leg)."""
        from jepsen_tpu.parallel.pipeline import check_sources

        base = synth_batch(10, SynthSpec(n_ops=40), lost=1, duplicated=1)
        files = _write(tmp_path, base)
        monkeypatch.setenv("JEPSEN_TPU_NO_JTC", "1")
        serial, _ = check_sources(
            "queue", files, chunk=3, serial=True, use_cache=False
        )
        monkeypatch.delenv("JEPSEN_TPU_NO_JTC")
        _pack_all(files)
        laned, stats = check_sources(
            "queue", files, chunk=3, lanes=4, use_cache=True
        )
        assert laned == serial
        assert stats.lanes >= 2

    def test_edn_source_substrate(self, tmp_path):
        """An imported EDN run carries its own substrate: record-time
        emission via save_history_edn, keyed to the EDN bytes."""
        from jepsen_tpu.history.rows import _rows_for, load_rows_cache

        store = Store(tmp_path / "s")
        sh = synth_batch(1, SynthSpec(n_ops=30), lost=1)[0]
        d = store.run_dir("t")
        time.sleep(0.01)
        p = store.save_history_edn(d, sh.ops)
        assert p.suffix == ".edn"
        assert jtc_path_for(p).exists()
        got = load_rows_cache(p)
        assert got is not None and got[0] == "queue"
        np.testing.assert_array_equal(
            got[1], _rows_for(read_history(p))
        )


# ---------------------------------------------------------------------------
# 3. Migration tool
# ---------------------------------------------------------------------------


class TestMigrateStore:
    def _mk_store(self, tmp_path, n=3):
        root = tmp_path / "store"
        files = []
        for i, sh in enumerate(synth_batch(n, SynthSpec(n_ops=30), lost=1)):
            d = root / "t" / f"run{i}"
            d.mkdir(parents=True)
            p = d / "history.jsonl"
            write_history_jsonl(p, sh.ops)
            files.append(p)
        time.sleep(0.01)
        return root, files

    def _migrate(self, *argv):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import migrate_store
        finally:
            sys.path.pop(0)
        return migrate_store, migrate_store.main([str(a) for a in argv])

    def test_migrates_then_idempotent(self, tmp_path, capsys):
        import json

        root, files = self._mk_store(tmp_path)
        _m, rc = self._migrate(root)
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["migrated"] == 3 and out["fresh"] == 0
        for p in files:
            assert jtc_path_for(p).exists()
            assert load_jtc(p) is not None
        _m, rc = self._migrate(root)  # idempotent: zero work
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["migrated"] == 0 and out["fresh"] == 3

    def test_refuses_corrupt_substrate(self, tmp_path, capsys):
        import json

        root, files = self._mk_store(tmp_path)
        _m, rc = self._migrate(root)
        assert rc == 0
        capsys.readouterr()
        t = jtc_path_for(files[1])
        raw = bytearray(t.read_bytes())
        raw[-3] ^= 0xFF
        t.write_bytes(raw)
        _m, rc = self._migrate(root)
        assert rc == 3  # refused, non-zero
        cap = capsys.readouterr()
        assert "REFUSED" in cap.err
        out = json.loads(cap.out.strip().splitlines()[-1])
        assert out["corrupt_refused"] == 1
        # the corrupt file was NOT repaved
        assert bytes(raw) == t.read_bytes()
        # explicit repave fixes it
        _m, rc = self._migrate(root, "--repave-corrupt")
        assert rc == 0
        assert load_jtc(files[1]) is not None

    def test_dry_run_writes_nothing(self, tmp_path, capsys):
        import json

        root, files = self._mk_store(tmp_path)
        _m, rc = self._migrate(root, "--dry-run")
        assert rc == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["migrated"] == 3
        assert not any(jtc_path_for(p).exists() for p in files)
