"""Jepsen ``history.edn`` import (the reference ecosystem's artifact)."""

import subprocess
import sys
from pathlib import Path

import pytest

from jepsen_tpu.history.edn import (
    EdnError,
    Keyword,
    op_from_edn,
    parse_edn_forms,
    read_history_edn,
)
from jepsen_tpu.history.ops import NEMESIS_PROCESS, OpF, OpType

REPO = Path(__file__).resolve().parent.parent


class TestParser:
    def test_unicode_escape(self):
        assert parse_edn_forms(r'"caf\u00e9 \u0041"') == ["café A"]

    def test_scalars_and_collections(self):
        forms = parse_edn_forms(
            '[1 -2 3.5 "hi\\n" :kw :ns/kw nil true false sym 42N]'
        )
        assert forms == [
            [1, -2, 3.5, "hi\n", "kw", "ns/kw", None, True, False, "sym", 42]
        ]
        assert isinstance(forms[0][4], Keyword)

    def test_maps_sets_lists_comments(self):
        forms = parse_edn_forms(
            "; a comment\n{:a 1, :b [2 3]} #{4 5} (6 7)"
        )
        assert forms[0] == {"a": 1, "b": [2, 3]}
        assert forms[1] == {4, 5}
        assert forms[2] == [6, 7]

    def test_tagged_literals_and_discard(self):
        forms = parse_edn_forms(
            '#jepsen.history.Op{:type :ok, :f :enqueue, :value 1, '
            ':process 0} #_ {:dropped true} 9'
        )
        assert forms == [
            {"type": "ok", "f": "enqueue", "value": 1, "process": 0},
            9,
        ]

    def test_errors(self):
        with pytest.raises(EdnError):
            parse_edn_forms("[1 2")
        with pytest.raises(EdnError):
            parse_edn_forms('"open')
        with pytest.raises(EdnError):
            parse_edn_forms("{:odd}")


class TestOpMapping:
    def test_client_op(self):
        op = op_from_edn(
            parse_edn_forms(
                "{:type :invoke, :f :enqueue, :value 3, :process 2, "
                ":time 100, :index 7}"
            )[0]
        )
        assert op.type == OpType.INVOKE and op.f == OpF.ENQUEUE
        assert (op.value, op.process, op.time, op.index) == (3, 2, 100, 7)

    def test_nemesis_and_error(self):
        op = op_from_edn(
            parse_edn_forms(
                "{:type :info, :f :start, :process :nemesis, "
                ':value "partitioned"}'
            )[0]
        )
        assert op.process == NEMESIS_PROCESS and op.f == OpF.START
        op = op_from_edn(
            parse_edn_forms(
                "{:type :fail, :f :dequeue, :process 1, :error :exhausted}"
            )[0]
        )
        assert op.error == "exhausted"

    def test_unknown_f_raises(self):
        with pytest.raises(EdnError):
            op_from_edn(
                parse_edn_forms("{:type :ok, :f :frobnicate, :process 0}")[0]
            )

    def test_non_nemesis_keyword_process_raises(self):
        """Only :nemesis names the pseudo-process; any other keyword (or a
        symbol/string) must raise EdnError, not silently become nemesis."""
        with pytest.raises(EdnError, match="keyword :process"):
            op_from_edn(
                parse_edn_forms(
                    "{:type :ok, :f :enqueue, :value 1, :process :writer}"
                )[0]
            )
        with pytest.raises(EdnError, match="non-integer"):
            op_from_edn(
                parse_edn_forms(
                    '{:type :ok, :f :enqueue, :value 1, :process "w3"}'
                )[0]
            )
        # a float is refused too, never silently truncated to an int
        with pytest.raises(EdnError, match="non-integer"):
            op_from_edn(
                parse_edn_forms(
                    "{:type :ok, :f :enqueue, :value 1, :process 1.5}"
                )[0]
            )


JEPSEN_STYLE_HISTORY = """[
 {:type :invoke, :f :enqueue, :value 0, :process 0, :time 10, :index 0}
 {:type :ok,     :f :enqueue, :value 0, :process 0, :time 20, :index 1}
 {:type :invoke, :f :enqueue, :value 1, :process 1, :time 30, :index 2}
 #jepsen.history.Op{:type :info, :f :enqueue, :value 1, :process 1,
                    :time 40, :index 3}
 {:type :info, :f :start, :process :nemesis, :time 45, :index 4}
 {:type :invoke, :f :dequeue, :process 2, :time 50, :index 5}
 {:type :ok,     :f :dequeue, :value 0, :process 2, :time 60, :index 6}
 {:type :info, :f :stop, :process :nemesis, :time 65, :index 7}
 {:type :invoke, :f :drain, :process 3, :time 70, :index 8}
 {:type :ok,     :f :drain, :value [1], :process 3, :time 80, :index 9}
]
"""


class TestHistoryImport:
    def test_vector_and_line_layouts_agree(self, tmp_path):
        pv = tmp_path / "vec.edn"
        pv.write_text(JEPSEN_STYLE_HISTORY)
        lines = JEPSEN_STYLE_HISTORY.strip()[1:-1].strip()
        pl = tmp_path / "lines.edn"
        pl.write_text(lines)
        hv, hl = read_history_edn(pv), read_history_edn(pl)
        assert hv == hl and len(hv) == 10

    def test_checker_verdict_on_imported_history(self, tmp_path):
        p = tmp_path / "history.edn"
        p.write_text(JEPSEN_STYLE_HISTORY)
        from jepsen_tpu.checkers.total_queue import check_total_queue_cpu

        h = read_history_edn(p)
        r = check_total_queue_cpu(h)
        assert r["valid?"] is True, r
        # the indeterminate enqueue drained at the end is `recovered`
        assert r["recovered-count"] == 1

    def test_lost_value_flagged(self, tmp_path):
        lossy = JEPSEN_STYLE_HISTORY.replace(
            ":value [1], :process 3", ":value [], :process 3"
        )
        p = tmp_path / "history.edn"
        p.write_text(lossy)
        from jepsen_tpu.checkers.total_queue import check_total_queue_cpu

        h = read_history_edn(p)
        r = check_total_queue_cpu(h)
        # value 0 was acked-and-read; value 1 was indeterminate and never
        # read — with the info rule that is not a definite loss, but the
        # acked value 0 WAS read, so this stays valid; make value 0 lost:
        assert r["valid?"] is True, r
        lossy2 = lossy.replace(
            ":type :ok,     :f :dequeue, :value 0",
            ":type :fail,   :f :dequeue, :value nil",
        )
        p.write_text(lossy2)
        r2 = check_total_queue_cpu(read_history_edn(p))
        assert r2["valid?"] is False and r2["lost-count"] == 1

    def test_check_cli_consumes_edn(self, tmp_path):
        run = tmp_path / "r"
        run.mkdir()
        (run / "history.edn").write_text(JEPSEN_STYLE_HISTORY)
        r = subprocess.run(
            [sys.executable, "-m", "jepsen_tpu", "check", "--checker",
             "cpu", str(run)],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "Everything looks good" in r.stdout

    def test_export_roundtrip(self, tmp_path):
        """Our histories export to jepsen-style EDN and re-import equal
        (so jepsen-ecosystem tooling can consume runs recorded here)."""
        from jepsen_tpu.history.edn import write_history_edn
        from jepsen_tpu.history.synth import SynthSpec, synth_history

        h = synth_history(SynthSpec(n_ops=60, seed=4, lost=1))
        p = tmp_path / "out.edn"
        write_history_edn(p, h.ops)
        back = read_history_edn(p)
        assert back == list(h.ops)

    def test_export_escapes_control_chars(self, tmp_path):
        """A multi-line error string (client-crash backtrace) must not
        break the one-op-per-line streaming layout."""
        from jepsen_tpu.history.edn import write_history_edn
        from jepsen_tpu.history.ops import Op, OpF, OpType

        op = Op(
            type=OpType.FAIL,
            f=OpF.ENQUEUE,
            process=0,
            value=1,
            time=5,
            index=0,
            error="client-crash: boom\n  at line 1\ttab",
        )
        p = tmp_path / "out.edn"
        write_history_edn(p, [op])
        lines = p.read_text().splitlines()
        assert len(lines) == 1  # layout intact
        (back,) = read_history_edn(p)
        assert back.error == op.error

    def test_rich_nemesis_fs_import_as_log_rows(self, tmp_path):
        """jepsen.nemesis.combined f's (:start-partition, :kill, ...) are
        kept as nemesis log rows instead of refusing the file; unknown
        CLIENT f's still raise."""
        h = read_history_edn(
            self._write(
                tmp_path,
                "{:type :info, :f :start-partition, :process :nemesis, "
                ':value "majority"}\n'
                "{:type :info, :f :kill, :process :nemesis}\n"
                "{:type :invoke, :f :enqueue, :value 1, :process 0}\n"
                "{:type :ok, :f :enqueue, :value 1, :process 0}\n"
                "{:type :invoke, :f :drain, :process 1}\n"
                "{:type :ok, :f :drain, :value [1], :process 1}\n",
            )
        )
        assert len(h) == 6
        assert h[0].f == OpF.LOG and "start-partition" in str(h[0].value)
        assert h[1].f == OpF.LOG
        from jepsen_tpu.checkers.total_queue import check_total_queue_cpu

        assert check_total_queue_cpu(h)["valid?"] is True
        with pytest.raises(EdnError):
            read_history_edn(
                self._write(
                    tmp_path,
                    "{:type :ok, :f :frobnicate, :process 3}",
                    name="bad.edn",
                )
            )

    @staticmethod
    def _write(tmp_path, text, name="h.edn"):
        p = tmp_path / name
        p.write_text(text)
        return p

    def test_synth_format_edn_checks_roundtrip(self, tmp_path):
        """synth --format edn writes jepsen-layout fixtures that check
        end-to-end (injected loss flagged through the EDN path)."""
        r = subprocess.run(
            [sys.executable, "-m", "jepsen_tpu", "synth", "--count", "2",
             "--ops", "60", "--lost", "2", "--format", "edn",
             "--store", str(tmp_path / "s")],
            capture_output=True, text=True, cwd=REPO,
        )
        assert r.returncode == 0, r.stderr
        edns = list((tmp_path / "s").glob("**/history.edn"))
        assert len(edns) == 2
        # the injection is best-effort per seed (it needs an acked value
        # still outstanding at drain time); at least one must land
        verdicts = []
        for e in edns:
            r = subprocess.run(
                [sys.executable, "-m", "jepsen_tpu", "check", "--checker",
                 "cpu", str(e)],
                capture_output=True, text=True, cwd=REPO,
            )
            verdicts.append(r.returncode)
        assert 1 in verdicts, verdicts
