"""Host-load-aware deadline scaling for the live-cluster tests.

The round-4/round-5 tier-1 runs on a 2-core container produced a
rotating cast of red live tests (membership admin-port/self-elect,
replicated-broker heal/ttl/minority-read) — different tests each run,
every one green re-run in isolation.  The mechanism is always the
same: the test pins a wall-clock deadline sized for an idle host, and
a loaded scheduler (the rest of the suite, a background soak) starves
broker/Raft threads past it.  Retrying whole runs launders real
regressions; raising every constant 4x punishes the idle case.

Instead: scale the deadline by the MEASURED host pressure at the
moment the wait starts.  ``scaled(s)`` returns ``s`` on an idle box
and up to ``cap``x ``s`` when the 1-minute load average exceeds the
core count — the same run that flaked at 5 s idle-sized deadlines
simply waits proportionally longer when the box is busy, while a
genuine hang still fails (the cap bounds the stretch).
"""

from __future__ import annotations

import os

#: never stretch a deadline past this factor — a real hang must fail
CAP = 4.0


def host_load_factor(cap: float = CAP) -> float:
    """max(1, load1/cores), capped: 1.0 on an idle host, ``cap`` on a
    badly oversubscribed one.  Measured fresh per call so a deadline
    taken mid-suite sees the pressure that will actually starve it."""
    try:
        load1 = os.getloadavg()[0]
    except OSError:  # platform without getloadavg: no scaling
        return 1.0
    cpus = os.cpu_count() or 1
    return max(1.0, min(cap, load1 / cpus))


def scaled(seconds: float, cap: float = CAP) -> float:
    """A deadline of ``seconds`` sized for an idle host, stretched by
    the current host-load factor."""
    return seconds * host_load_factor(cap)
