"""Dynamic Raft membership: ``join_cluster`` as a real AddServer.

Round-4 closed the last vacuous choreography step in ``--db local``:
secondaries first-boot OUTSIDE any cluster (self-only, no self-election)
and ``rabbitmqctl join_cluster rabbit@primary`` maps to a join_request
RPC whose AddServer config entry commits through the Raft log —
effective on append (Raft §6), one join at a time.  The cluster the
partition nemeses later stress is *formed* by the same choreography the
reference runs (``rabbitmq.clj:99-119``).
"""

import time

import pytest

from jepsen_tpu.harness.replication import FOLLOWER, RaftNode, ReplicatedBackend


def _backend(name, bootstrap, **kw):
    return ReplicatedBackend(
        name,
        {name: ("127.0.0.1", 0)},
        election_timeout=(0.05, 0.1),
        heartbeat_s=0.02,
        bootstrap=bootstrap,
        **kw,
    )


def _wait(pred, timeout_s=5.0, what="condition"):
    # idle-host deadline, stretched by measured load: the round-4 flake
    # class was exactly these waits expiring under full-suite scheduler
    # pressure (tests/_load.py)
    from _load import scaled

    deadline = time.monotonic() + scaled(timeout_s)
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def test_cluster_forms_by_joining():
    """Bootstrap a 1-node cluster, join two pending nodes one at a time
    (the boot choreography's shape); ops then commit under the full
    3-node quorum and replicate everywhere."""
    a = _backend("a", bootstrap=True)
    b = _backend("b", bootstrap=False)
    c = _backend("c", bootstrap=False)
    try:
        _wait(lambda: a.raft.is_leader(), what="bootstrap leader")
        a_addr = ("127.0.0.1", a.raft.port)
        assert b.raft.request_join(a_addr) is True
        assert set(b.raft.peers) == {"a", "b"}
        assert set(a.raft.peers) == {"a", "b"}
        assert c.raft.request_join(a_addr) is True
        assert set(c.raft.peers) == {"a", "b", "c"}

        a.declare("q")
        assert a.enqueue("q", b"x", b"") is True
        # committed state reaches the joined followers
        for node in (b, c):
            _wait(
                lambda n=node: n.counts().get("q") == 1,
                what=f"replication to {node.raft.name}",
            )
        # and the cluster survives losing a minority (real 3-node quorum)
        c.stop()
        assert a.enqueue("q", b"y", b"") is True
    finally:
        for n in (a, b, c):
            n.stop()


def test_pending_node_never_self_elects():
    """The safety property the pending state exists for: an unjoined
    node must NOT become a 1-node 'quorum' that confirms unreplicated
    publishes.  (Its bootstrap twin legitimately does.)"""
    from _load import scaled

    p = _backend("p", bootstrap=False)
    try:
        # many election timeouts' worth — load-scaled so a starved
        # ticker thread still gets its chances to (wrongly) campaign
        time.sleep(scaled(0.8))
        assert p.raft.role()[0] == FOLLOWER
        ok, _ = p.raft.submit({"k": "noop"}, timeout_s=scaled(0.3))
        assert ok is False  # nothing can commit outside a cluster
    finally:
        p.stop()


def test_join_is_idempotent_and_serialized():
    """Re-joining a member answers OK without growing the config; two
    racing joins both land (serialized one at a time, each from the
    then-current config — §6's one-change rule)."""
    import threading

    a = _backend("a", bootstrap=True)
    b = _backend("b", bootstrap=False)
    c = _backend("c", bootstrap=False)
    try:
        _wait(lambda: a.raft.is_leader(), what="bootstrap leader")
        a_addr = ("127.0.0.1", a.raft.port)
        results = {}
        ts = [
            threading.Thread(
                target=lambda n=n: results.update(
                    {n.raft.name: n.raft.request_join(a_addr)}
                )
            )
            for n in (b, c)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=15)
        assert results == {"b": True, "c": True}
        assert set(a.raft.peers) == {"a", "b", "c"}
        # idempotent re-join of an existing member
        assert b.raft.request_join(a_addr) is True
        assert set(a.raft.peers) == {"a", "b", "c"}
    finally:
        for n in (a, b, c):
            n.stop()


def test_cfg_truncation_reverts_membership():
    """A follower that appended an uncommitted cfg entry from a deposed
    leader must revert to its prior config when the new leader's
    conflict truncation removes that entry."""
    n = RaftNode(
        "a",
        {"a": ("127.0.0.1", 0), "b": ("127.0.0.1", 1)},
        lambda i, op: None,
        election_timeout=(5.0, 9.0),  # never fires during the test
    )
    try:
        cfg = {
            "k": "cfg",
            "peers": {
                "a": ["127.0.0.1", n.port],
                "b": ["127.0.0.1", 1],
                "z": ["127.0.0.1", 2],
            },
        }
        # term-1 leader "b" hands us a cfg entry adding z
        assert n._on_append_entries({
            "rpc": "append_entries", "term": 1, "from": "b",
            "prev_idx": 0, "prev_term": 0,
            "entries": [(1, cfg)], "leader_commit": 0,
        })["ok"] is True
        assert set(n.peers) == {"a", "b", "z"}
        # a term-2 leader never saw it: conflict truncation at idx 1
        assert n._on_append_entries({
            "rpc": "append_entries", "term": 2, "from": "b",
            "prev_idx": 0, "prev_term": 0,
            "entries": [(2, {"k": "noop"})], "leader_commit": 0,
        })["ok"] is True
        assert set(n.peers) == {"a", "b"}  # z is gone with the entry
    finally:
        n.stop()


def test_join_survives_crash_restart_durable(tmp_path):
    """Durable + dynamic membership compose: a cluster formed by joins,
    crash-restarted wholesale, recovers BOTH its data and its
    membership from the WAL (cfg entries replay like any other)."""
    dirs = {n: str(tmp_path / n) for n in "ab"}
    a = _backend("a", bootstrap=True, data_dir=dirs["a"])
    b = _backend("b", bootstrap=False, data_dir=dirs["b"])
    try:
        _wait(lambda: a.raft.is_leader(), what="bootstrap leader")
        assert b.raft.request_join(("127.0.0.1", a.raft.port)) is True
        a.declare("q")
        assert a.enqueue("q", b"x", b"") is True
    finally:
        a.stop()
        b.stop()
    # whole-cluster restart from disk: same dirs, no join this time.
    # Ports changed (OS-assigned), so recovered cfg addresses are stale;
    # hand each node the full live config as its initial peers — the
    # localcluster transport does exactly this on restart (fixed ports
    # there make recovered AND initial configs agree).
    a2 = ReplicatedBackend(
        "a", {"a": ("127.0.0.1", 0), "b": ("127.0.0.1", 0)},
        election_timeout=(0.05, 0.1), heartbeat_s=0.02,
        data_dir=dirs["a"],
    )
    # recovery must already know the 2-node membership from the WAL
    assert set(a2.raft.peers) == {"a", "b"}
    a2.stop()


def test_malformed_admin_join_does_not_kill_the_admin_loop():
    """Review r4 find: 'JOIN n1' (no port) must answer ERR, not raise
    ValueError out of the single-threaded admin accept loop — a dead
    admin port silently disables partition enforcement (BLOCK) and the
    drain cross-check (DEPTHS) for the rest of the run."""
    from jepsen_tpu.harness.localcluster import LocalProcTransport

    t = LocalProcTransport(n_nodes=1, replicated=True)
    try:
        node = t.nodes[0]
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        r = t._admin(node, "JOIN n1")
        assert r.rc == 0 and r.out.startswith("ERR"), r
        r = t._admin(node, "JOIN ")
        assert r.rc == 0 and r.out.startswith("ERR"), r
        # the loop is still alive: DEPTHS answers
        r = t._admin(node, "DEPTHS")
        assert r.rc == 0, r
    finally:
        t.close()


def test_localcluster_join_cluster_is_real():
    """Transport-level proof over real OS processes: a freshly-booted
    secondary is PENDING (follower of nothing), and the exact command
    string the DB choreography runs turns it into a member of the
    primary's cluster."""
    from jepsen_tpu.harness.localcluster import LocalProcTransport

    t = LocalProcTransport(n_nodes=2)
    try:
        primary, sec = t.nodes
        t.run(primary, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        t.run(sec, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        r = t._admin(sec, "ROLE")
        assert r.rc == 0 and r.out.startswith("follower"), r
        res = t.run(sec, f"rabbitmqctl join_cluster rabbit@{primary}")
        assert res.rc == 0, (res.out, res.err)
        assert t._nodes[sec].booted_once is True
        # the formed cluster has a leader and both members see it
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and t.leader() is None:
            time.sleep(0.05)
        assert t.leader() == primary
        r2 = t._admin(sec, "ROLE")
        assert r2.out.split()[2] == primary  # leader hint = primary
    finally:
        t.close()


# ---------------------------------------------------------------------------
# RemoveServer: forget_cluster_node + the membership-churn nemesis
# ---------------------------------------------------------------------------


def test_forget_shrinks_cluster_and_it_still_serves():
    """Kill one member of a joined 3-node cluster, forget it from a
    survivor: the config commits down to {a,b} and ops keep committing
    under the SMALLER majority (2/2)."""
    a = _backend("a", bootstrap=True)
    b = _backend("b", bootstrap=False)
    c = _backend("c", bootstrap=False)
    try:
        _wait(lambda: a.raft.is_leader(), what="bootstrap leader")
        addr = ("127.0.0.1", a.raft.port)
        assert b.raft.request_join(addr) and c.raft.request_join(addr)
        a.declare("q")
        assert a.enqueue("q", b"1", b"") is True
        c.stop()  # the node dies (rabbitmqctl requires it stopped)
        assert b.raft.request_forget("c") is True  # via a FOLLOWER
        # request_forget waits for the CALLER's view; the other
        # member's copy converges within a replication round — a loaded
        # host can lag it, so wait, don't assert instantly (r4 flake)
        for n in (a, b):
            _wait(
                lambda n=n: set(n.raft.peers) == {"a", "b"},
                what=f"{n.raft.name} sees the 2-node config",
            )
        assert a.enqueue("q", b"2", b"") is True  # 2/2 majority serves
        # idempotent: forgetting an absent node answers ok
        assert a.raft.request_forget("c") is True
    finally:
        for n in (a, b, c):
            n.stop()


def test_leader_refuses_to_forget_itself():
    """Run on a 1-node cluster so the target is DETERMINISTICALLY the
    leader (in a multi-node cluster under load, leadership can move and
    the request legitimately proxies to a peer that may grant it —
    which is exactly real rabbitmqctl's model: run it from another
    node)."""
    a = _backend("a", bootstrap=True)
    try:
        _wait(lambda: a.raft.is_leader(), what="leader")
        assert a.raft.request_forget("a", timeout_s=2.0) is False
    finally:
        a.stop()


def test_removed_node_retires_defensively():
    """Defense-in-depth for the API-misuse path (forgetting an ALIVE
    node): a node that appends a cfg excluding itself retires — no
    campaigning, no acks — and un-retires if the entry truncates."""
    n = RaftNode(
        "a",
        {"a": ("127.0.0.1", 0), "b": ("127.0.0.1", 1)},
        lambda i, op: None,
        election_timeout=(5.0, 9.0),
    )
    try:
        gone = {"k": "cfg", "peers": {"b": ["127.0.0.1", 1]}}
        n._on_append_entries({
            "rpc": "append_entries", "term": 1, "from": "b",
            "prev_idx": 0, "prev_term": 0,
            "entries": [(1, gone)], "leader_commit": 0,
        })
        assert n._retired is True
        ok, _ = n.submit({"k": "noop"}, timeout_s=0.2)
        assert ok is False
        n._on_append_entries({
            "rpc": "append_entries", "term": 2, "from": "b",
            "prev_idx": 0, "prev_term": 0,
            "entries": [(2, {"k": "noop"})], "leader_commit": 0,
        })
        assert n._retired is False  # truncation reversed the removal
    finally:
        n.stop()


def test_localcluster_forget_requires_stopped_node():
    """The transport mirrors rabbitmqctl: forgetting a RUNNING node is
    refused; a stopped one is removed and its slate wiped so a restart
    boots outside the cluster."""
    from jepsen_tpu.harness.localcluster import LocalProcTransport

    t = LocalProcTransport(n_nodes=3)
    try:
        n1, n2, n3 = t.nodes
        for n in (n1, n2, n3):
            t.run(n, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        t.run(n2, f"rabbitmqctl join_cluster rabbit@{n1}")
        t.run(n3, f"rabbitmqctl join_cluster rabbit@{n1}")
        r = t.run(n1, f"rabbitmqctl forget_cluster_node rabbit@{n3}")
        assert r.rc == 1 and "running" in r.err, r
        t.run(n3, "killall -q -9 beam.smp epmd || true")
        r = t.run(n1, f"rabbitmqctl forget_cluster_node rabbit@{n3}")
        assert r.rc == 0, (r.out, r.err)
        assert t._nodes[n3].booted_once is False  # fresh boot next time
        # the survivors still serve: depth query answers on both
        assert t._admin(n1, "DEPTHS").rc == 0
        assert t._admin(n2, "DEPTHS").rc == 0
    finally:
        t.close()


def test_membership_churn_nemesis_cycle():
    from jepsen_tpu.control.nemesis import MembershipNemesis
    from jepsen_tpu.history.ops import Op, OpF

    class Procs:
        def __init__(self):
            self.calls = []

        def kill(self, n):
            self.calls.append(("kill", n))

        def restart(self, n):
            self.calls.append(("restart", n))

    class Mem:
        def __init__(self):
            self.calls = []

        def forget(self, via, target):
            self.calls.append(("forget", via, target))
            return True

        def join(self, node, via):
            self.calls.append(("join", node, via))
            return True

    procs, mem = Procs(), Mem()
    nodes = ["n1", "n2", "n3"]
    nem = MembershipNemesis(procs, mem, nodes, seed=2)
    start = Op.invoke(OpF.START, -1)
    stop = Op.invoke(OpF.STOP, -1)
    r = nem.invoke({}, start)
    assert r.value.startswith("removed ")
    victim = r.value.split()[-1]
    assert procs.calls == [("kill", victim)]
    via = mem.calls[0][1]
    assert mem.calls == [("forget", via, victim)] and via != victim
    r = nem.invoke({}, stop)
    assert r.value == f"rejoined {victim}"
    assert procs.calls[-1] == ("restart", victim)
    assert mem.calls[-1] == ("join", victim, via)
    # teardown restores a removal left behind by an aborted run
    nem.invoke({}, start)
    nem.teardown({})
    assert procs.calls[-1][0] == "restart" and nem.out is None


def test_membership_churn_refused_without_surface_or_quorum():
    from jepsen_tpu.control.nemesis import make_nemesis

    with pytest.raises(ValueError, match="membership"):
        make_nemesis(
            {"nemesis": "membership-churn"}, None, None, ["a", "b", "c"]
        )
    with pytest.raises(ValueError, match="3 nodes"):
        make_nemesis(
            {"nemesis": "membership-churn"}, None, None, ["a", "b"],
            membership=object(),
        )


# native_lib / _reset fixtures come from conftest.py


def test_membership_churn_green_end_to_end(_reset):
    """The full assembly under membership churn: nodes leave (kill +
    forget, cluster genuinely shrinks to 2/2) and rejoin fresh
    (AddServer + catch-up) while clients publish — valid verdict,
    nothing lost.  Runs under the matrix's retry-with-triage semantics
    (tests/_live.py — VERDICT r4 weak #2: this test flaked under
    full-suite scheduler pressure); a genuine violation still fails
    after retries, with the invalidating checker named."""
    import tempfile

    from _live import run_live_with_triage
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.suite import DEFAULT_OPTS

    opts = {
        **DEFAULT_OPTS,
        "rate": 120.0,
        "time-limit": 6.0,
        "time-before-partition": 0.8,
        "partition-duration": 1.2,
        "recovery-sleep": 1.5,
        "publish-confirm-timeout": 2.5,
        "nemesis": "membership-churn",
        "seed": 7,
    }

    def build():
        return build_local_test(
            opts, n_nodes=3, concurrency=4, checker_backend="cpu",
            store_root=tempfile.mkdtemp(), workload="queue",
        )

    def checks(run):
        assert run.results["queue"]["lost-count"] == 0, run.results["queue"]
        removed = [
            op for op in run.history
            if op.value is not None and str(op.value).startswith("removed ")
        ]
        assert removed, "membership churn never removed a node"

    run_live_with_triage(build, expect="valid", checks=checks)


def test_admin_port_serves_concurrently_past_a_stalled_connection():
    """Advisor r4: a JOIN can block its handler for 12-20s inside the
    request_join retry loop; partition enforcement (BLOCK), the drain
    cross-check (DEPTHS), and ROLE must not queue behind it.  Proxy: a
    connection that never finishes its request line stalls ITS handler
    thread on readline — every other admin query must still answer
    promptly."""
    import socket as _socket
    import time as _time

    from jepsen_tpu.harness.localcluster import LocalProcTransport

    t = LocalProcTransport(n_nodes=1, replicated=True)
    try:
        node = t.nodes[0]
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        assert t._admin(node, "DEPTHS").rc == 0  # the port is up
        n = t._nodes[node]
        stalled = _socket.create_connection(
            ("127.0.0.1", n.admin_port), 2.0
        )
        try:
            stalled.sendall(b"JOIN")  # no newline: handler sits in readline
            _time.sleep(0.1)
            from _load import scaled

            t0 = _time.monotonic()
            r = t._admin(node, "DEPTHS", timeout_s=scaled(2.0))
            dt = _time.monotonic() - t0
            assert r.rc == 0, r
            # promptness bound sized for an idle host; a loaded
            # scheduler may lawfully add its own latency on top
            assert dt < scaled(1.0), (
                f"DEPTHS stalled {dt:.1f}s behind an open conn"
            )
            r = t._admin(node, "ROLE")
            assert r.rc == 0 and r.out.split()[0] in (
                "leader", "follower", "candidate"
            ), r
        finally:
            stalled.close()
    finally:
        t.close()


def test_fresh_join_catches_up_through_a_long_log():
    """r5 burn-in find #2: rejoins FAILED in long runs ("join ok=False")
    because catch-up shipped one 256-entry batch per ticker tick — a
    fresh joiner replaying a long run's log needed hundreds of ticks
    while ``request_join`` waits seconds.  The leader now loops catch-up
    batches back-to-back (single-flight per peer), so a 40k-entry log
    replays within one join window."""
    # production-like tick: catch-up speed must come from the loop, not
    # from a fast test clock papering over one-batch-per-tick
    mk = lambda name, boot: ReplicatedBackend(
        name, {name: ("127.0.0.1", 0)},
        election_timeout=(0.3, 0.6), heartbeat_s=0.1, bootstrap=boot,
    )
    a = mk("a", True)
    b = mk("b", False)
    try:
        _wait(lambda: a.raft.is_leader(), what="bootstrap leader")
        with a.raft.lock:
            t = a.raft.term
            for _ in range(150_000):
                a.raft.log.append((t, {"k": "noop"}))
            a.raft.commit_idx = len(a.raft.log)  # 1-node: self-quorum
            a.raft.applied_idx = a.raft.commit_idx

        # pre-fix: 150000/256 ≈ 586 batches at one per 100 ms tick is a
        # ≥ 58 s floor BEFORE any RPC cost, so the join window expires;
        # post-fix the batches stream back-to-back and the whole join —
        # membership commit + full-log catch-up — fits comfortably
        assert b.raft.request_join(
            ("127.0.0.1", a.raft.port), timeout_s=20.0
        )
        _wait(
            lambda: len(b.raft.log) >= 150_000,
            timeout_s=10.0,
            what="joiner log catch-up",
        )
        assert set(b.raft.peers) == {"a", "b"}
    finally:
        a.stop()
        b.stop()
