"""The shared build-on-first-use protocol (utils/nativebuild.py) used by
both ctypes bindings (client/native.py, history/fastpack.py)."""

from __future__ import annotations

from pathlib import Path

from jepsen_tpu.utils.nativebuild import ensure_built


def test_existing_file_is_a_noop(tmp_path):
    lib = tmp_path / "libx.so"
    lib.write_bytes(b"present")
    # no Makefile in tmp_path: would fail loudly if a build were attempted
    assert ensure_built(lib) == ""


def test_successful_build(tmp_path):
    (tmp_path / "Makefile").write_text(
        "libx.so:\n\techo built > libx.so\n"
    )
    lib = tmp_path / "libx.so"
    assert ensure_built(lib, target="libx.so") == ""
    assert lib.exists()


def test_failing_build_returns_error_text(tmp_path):
    (tmp_path / "Makefile").write_text(
        "libx.so:\n\t@echo the-compiler-exploded >&2; exit 1\n"
    )
    err = ensure_built(tmp_path / "libx.so", target="libx.so")
    assert "the-compiler-exploded" in err
    assert not (tmp_path / "libx.so").exists()


def test_build_producing_no_output_is_an_error(tmp_path):
    (tmp_path / "Makefile").write_text("libx.so:\n\t@true\n")
    err = ensure_built(tmp_path / "libx.so", target="libx.so")
    assert err == "build produced no output"


def test_missing_makefile_reports_error(tmp_path):
    err = ensure_built(tmp_path / "libx.so", target="libx.so")
    assert err != ""


def test_build_serialized_under_lock(tmp_path):
    """A peer that built the library while we waited on the lock is
    detected under the lock — no rebuild, no error."""
    import fcntl
    import threading
    import time

    (tmp_path / "Makefile").write_text(
        "libx.so:\n\t@echo should-not-run >&2; exit 1\n"
    )
    lib = tmp_path / "libx.so"
    lock = open(tmp_path / ".build.lock", "w")
    fcntl.flock(lock, fcntl.LOCK_EX)

    result = {}

    def contender():
        result["err"] = ensure_built(lib, target="libx.so")

    t = threading.Thread(target=contender)
    t.start()
    time.sleep(0.2)  # contender is blocked on the flock
    lib.write_bytes(b"peer built it")  # the lock holder produces the lib
    fcntl.flock(lock, fcntl.LOCK_UN)
    lock.close()
    t.join(10)
    assert result["err"] == ""  # detected the peer's build, didn't run make
