"""``stats`` / ``unhandled-exceptions``: the default checkers jepsen's
runner composes into every test (alongside the user's) — success/failure
rates per op function and the distinct client error classes."""

from jepsen_tpu.checkers.stats import Stats, UnhandledExceptions
from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType


def _h():
    return [
        Op(OpType.INVOKE, OpF.ENQUEUE, 0, 1),
        Op(OpType.OK, OpF.ENQUEUE, 0, 1),
        Op(OpType.INVOKE, OpF.ENQUEUE, 1, 2),
        Op(OpType.FAIL, OpF.ENQUEUE, 1, 2, error="conn-reset"),
        Op(OpType.INVOKE, OpF.DEQUEUE, 0),
        Op(OpType.INFO, OpF.DEQUEUE, 0, error="timeout"),
        Op(OpType.INVOKE, OpF.DEQUEUE, 1),
        Op(OpType.FAIL, OpF.DEQUEUE, 1, error="conn-reset"),
        # nemesis ops must not count as client outcomes
        Op(OpType.INFO, OpF.START, NEMESIS_PROCESS, "cut"),
        Op(OpType.INFO, OpF.STOP, NEMESIS_PROCESS, "heal"),
    ]


def test_stats_counts_completions_per_f():
    r = Stats().check({}, _h())
    assert r["valid?"] is True
    assert r["ok-count"] == 1 and r["fail-count"] == 2
    assert r["info-count"] == 1 and r["count"] == 4
    assert r["by-f"]["enqueue"] == {
        "ok-count": 1, "fail-count": 1, "info-count": 0, "count": 2,
    }
    assert r["by-f"]["dequeue"]["info-count"] == 1


def test_unhandled_exceptions_groups_error_classes():
    r = UnhandledExceptions().check({}, _h())
    assert r["valid?"] is True
    assert r["exception-count"] == 3
    assert r["by-error"]["conn-reset"]["count"] == 2
    assert r["by-error"]["conn-reset"]["example"]["f"] in (
        "enqueue", "dequeue",
    )
    assert r["by-error"]["timeout"]["count"] == 1


def test_composed_into_every_suite_checker():
    """jepsen's runner composes these defaults into every test; the four
    workload checker builders here do the same."""
    from jepsen_tpu.suite import (
        elle_checker,
        mutex_checker,
        queue_checker,
        stream_checker,
    )

    for build in (queue_checker, stream_checker, elle_checker, mutex_checker):
        composed = build(backend="cpu", with_perf=False)
        names = set(composed.checkers)
        assert {"stats", "exceptions"} <= names, (build.__name__, names)


def test_log_file_pattern_checker(tmp_path):
    """jepsen.checker/log-file-pattern: a crash indicator in any
    collected node log invalidates the run; clean logs (or no logs at
    all — collection is best-effort) stay valid."""
    from jepsen_tpu.checkers.logpattern import LogFilePattern

    n1 = tmp_path / "nodes" / "n1"
    n1.mkdir(parents=True)
    (n1 / "broker.log").write_text(
        "boot ok\nCRASH REPORT process <0.1.0> exited\nrecovered\n"
    )
    n2 = tmp_path / "nodes" / "n2"
    n2.mkdir(parents=True)
    (n2 / "broker.log").write_text("boot ok\nall quiet\n")

    c = LogFilePattern("CRASH REPORT|Segmentation fault")
    r = c.check({}, [], {"out_dir": str(tmp_path)})
    assert r["valid?"] is False
    assert r["count"] == 1
    assert r["matches"][0]["node"] == "n1"
    assert r["matches"][0]["line"] == 2
    assert "CRASH REPORT" in r["matches"][0]["text"]

    clean = LogFilePattern("Segmentation fault")
    assert clean.check({}, [], {"out_dir": str(tmp_path)})["valid?"] is True
    # no logs collected at all: not a violation
    assert clean.check({}, [], {"out_dir": str(tmp_path / "nope")})[
        "valid?"
    ] is True
    assert clean.check({}, [], None)["valid?"] is True


def test_log_file_pattern_invalidates_composed_verdict(tmp_path):
    """A log match must flip the COMPOSED verdict (merge_valid), not
    just its own entry — the run is invalid however clean the history
    checkers came out."""
    from jepsen_tpu.checkers.logpattern import LogFilePattern
    from jepsen_tpu.checkers.protocol import compose

    (tmp_path / "nodes" / "n1").mkdir(parents=True)
    (tmp_path / "nodes" / "n1" / "b.log").write_text("CRASH REPORT x\n")
    checker = compose({
        "stats": Stats(),  # always-valid neighbor
        "log-file-pattern": LogFilePattern("CRASH REPORT"),
    })
    r = checker.check({}, [], {"out_dir": str(tmp_path)})
    assert r["log-file-pattern"]["valid?"] is False
    assert r["valid?"] is False


def test_log_file_pattern_cli_wiring(tmp_path):
    """The flag parses, joins the composed result (sim runs collect no
    node logs, so the entry reports valid with zero matches — the
    invalidation path is pinned by the composition test above), and an
    invalid regex is a clean usage error, not a traceback."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "test", "--db", "sim",
         "--time-limit", "1", "--rate", "50", "--recovery-sleep", "0.2",
         "--checker", "cpu", "--store", str(tmp_path),
         "--log-file-pattern", "CRASH REPORT"],
        capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert '"log-file-pattern"' in r.stdout
    bad = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "test", "--db", "sim",
         "--log-file-pattern", "["],
        capture_output=True, text=True, timeout=60,
    )
    assert bad.returncode == 2
    assert "invalid regex" in bad.stderr and "Traceback" not in bad.stderr


def test_log_file_pattern_survives_recheck(tmp_path):
    """Review r4 find: `check` must inherit the run's recorded log
    pattern (like consistency-model/delivery) — a log-invalidated run
    must not re-check back to valid because the bare re-check forgot
    the pattern."""
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "test", "--db", "sim",
         "--time-limit", "1", "--rate", "50", "--recovery-sleep", "0.2",
         "--checker", "cpu", "--store", str(tmp_path),
         "--log-file-pattern", "CRASH REPORT"],
        capture_output=True, text=True, timeout=180,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # seed a crash line into the stored run's (empty) log collection,
    # as if a broker had crashed and its log had been scp'd in
    run_dir = (tmp_path / "latest").resolve()
    (run_dir / "nodes" / "n1").mkdir(parents=True)
    (run_dir / "nodes" / "n1" / "broker.log").write_text(
        "CRASH REPORT process exited\n"
    )
    chk = subprocess.run(
        [sys.executable, "-m", "jepsen_tpu", "check", "--checker", "cpu",
         str(tmp_path)],
        capture_output=True, text=True, timeout=180,
    )
    assert chk.returncode == 1, chk.stdout + chk.stderr  # invalid now
    assert "Analysis invalid" in chk.stdout
