"""``stats`` / ``unhandled-exceptions``: the default checkers jepsen's
runner composes into every test (alongside the user's) — success/failure
rates per op function and the distinct client error classes."""

from jepsen_tpu.checkers.stats import Stats, UnhandledExceptions
from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType


def _h():
    return [
        Op(OpType.INVOKE, OpF.ENQUEUE, 0, 1),
        Op(OpType.OK, OpF.ENQUEUE, 0, 1),
        Op(OpType.INVOKE, OpF.ENQUEUE, 1, 2),
        Op(OpType.FAIL, OpF.ENQUEUE, 1, 2, error="conn-reset"),
        Op(OpType.INVOKE, OpF.DEQUEUE, 0),
        Op(OpType.INFO, OpF.DEQUEUE, 0, error="timeout"),
        Op(OpType.INVOKE, OpF.DEQUEUE, 1),
        Op(OpType.FAIL, OpF.DEQUEUE, 1, error="conn-reset"),
        # nemesis ops must not count as client outcomes
        Op(OpType.INFO, OpF.START, NEMESIS_PROCESS, "cut"),
        Op(OpType.INFO, OpF.STOP, NEMESIS_PROCESS, "heal"),
    ]


def test_stats_counts_completions_per_f():
    r = Stats().check({}, _h())
    assert r["valid?"] is True
    assert r["ok-count"] == 1 and r["fail-count"] == 2
    assert r["info-count"] == 1 and r["count"] == 4
    assert r["by-f"]["enqueue"] == {
        "ok-count": 1, "fail-count": 1, "info-count": 0, "count": 2,
    }
    assert r["by-f"]["dequeue"]["info-count"] == 1


def test_unhandled_exceptions_groups_error_classes():
    r = UnhandledExceptions().check({}, _h())
    assert r["valid?"] is True
    assert r["exception-count"] == 3
    assert r["by-error"]["conn-reset"]["count"] == 2
    assert r["by-error"]["conn-reset"]["example"]["f"] in (
        "enqueue", "dequeue",
    )
    assert r["by-error"]["timeout"]["count"] == 1


def test_composed_into_every_suite_checker():
    """jepsen's runner composes these defaults into every test; the four
    workload checker builders here do the same."""
    from jepsen_tpu.suite import (
        elle_checker,
        mutex_checker,
        queue_checker,
        stream_checker,
    )

    for build in (queue_checker, stream_checker, elle_checker, mutex_checker):
        composed = build(backend="cpu", with_perf=False)
        names = set(composed.checkers)
        assert {"stats", "exceptions"} <= names, (build.__name__, names)
