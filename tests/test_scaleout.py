"""Scale-out pipeline differential contracts (PR 5).

The per-device input lanes (``run_lanes`` / ``check_sources(lanes=)``),
the collective verdict reduction (``reduce=True``), and the striped
native cursors must all produce verdicts IDENTICAL to the serial oracle
— for every pipelined family, including the degenerate-elle
host-fallback splice crossing a shard boundary — plus the lanes-path
honesty contracts: unreadable/zero-length files are dropped loudly
(explicit unknown entries, ``stats.dropped``), and a crashed lane
aborts with ``PipelineError`` and no results under ``fail_fast=True``;
the elastic default retries the crashed unit on another lane, then
quarantines it while every other unit's verdict survives (PR 13).
"""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu.history.store import write_history_jsonl
from jepsen_tpu.history.synth import (
    ElleSynthSpec,
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_elle_batch,
    synth_stream_batch,
)
from jepsen_tpu.parallel.pipeline import (
    PipelineError,
    check_sources,
    run_lanes,
)


def _write(tmp_path, base, tag="h"):
    files = []
    for i, sh in enumerate(base):
        p = tmp_path / f"{tag}{i:03d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(p)
    return files


def _first_invalid(flags):
    return flags.index(True) if any(flags) else -1


class TestLanesDifferential:
    """Multi-lane verdicts ≡ serial, every family."""

    def test_stream(self, cpu_devices, tmp_path):
        base = synth_stream_batch(
            11, StreamSynthSpec(n_ops=35), lost=2, duplicated=1, reorder=1
        )
        files = _write(tmp_path, base)
        serial, _ = check_sources("stream", files, chunk=4, serial=True)
        laned, stats = check_sources("stream", files, chunk=4, lanes=0)
        assert laned == serial
        assert stats.lanes == len(cpu_devices)
        assert stats.dropped == 0

    def test_queue_both_subverdicts(self, cpu_devices, tmp_path):
        base = synth_batch(
            10, SynthSpec(n_ops=40), lost=1, duplicated=1, unexpected=1
        )
        files = _write(tmp_path, base)
        serial, _ = check_sources("queue", files, chunk=3, serial=True)
        laned, _ = check_sources("queue", files, chunk=3, lanes=4)
        assert laned == serial

    def test_elle_with_degenerate_splice(self, cpu_devices, tmp_path):
        from test_fuzz_elle_device import fuzz_history

        from jepsen_tpu.checkers.elle import elle_mops_for

        class _SH:
            def __init__(self, ops):
                self.ops = ops

        base = [_SH(fuzz_history(seed, n_txns=10)) for seed in range(8)]
        degen = [elle_mops_for(sh.ops)[1].degenerate for sh in base]
        assert any(degen) and not all(degen)
        files = _write(tmp_path, base)
        serial, _ = check_sources("elle", files, chunk=3, serial=True)
        laned, _ = check_sources("elle", files, chunk=3, lanes=0)
        assert laned == serial

    def test_lanes_with_mesh(self, cpu_devices, tmp_path):
        """Lanes feeding the shared mesh (serialized dispatch) ≡ serial."""
        from jepsen_tpu.parallel.mesh import checker_mesh

        base = synth_stream_batch(9, StreamSynthSpec(n_ops=30), lost=1)
        files = _write(tmp_path, base)
        serial, _ = check_sources("stream", files, chunk=3, serial=True)
        meshed, _ = check_sources(
            "stream", files, chunk=3, lanes=0, mesh=checker_mesh()
        )
        assert meshed == serial


class TestCollectiveReduction:
    """reduce=True: the two-scalar on-device verdict vs the oracle."""

    @pytest.mark.parametrize("lanes", [None, 0], ids=["chunked", "lanes"])
    @pytest.mark.parametrize("workload", ["stream", "queue", "elle"])
    def test_reduced_matches_oracle(
        self, cpu_devices, tmp_path, workload, lanes
    ):
        from jepsen_tpu.parallel.mesh import checker_mesh

        if workload == "stream":
            base = synth_stream_batch(
                10, StreamSynthSpec(n_ops=30), lost=2
            )
        elif workload == "queue":
            base = synth_batch(10, SynthSpec(n_ops=40), lost=1)
        else:
            base = synth_elle_batch(
                10, ElleSynthSpec(n_txns=8), g1a=1, g2_cycle=1
            )
        files = _write(tmp_path, base)
        serial, _ = check_sources(workload, files, chunk=4, serial=True)
        if workload == "queue":
            flags = [
                not (
                    r["queue"]["valid?"] is True
                    and r["linear"]["valid?"] is True
                )
                for r in serial
            ]
        else:
            flags = [r[workload]["valid?"] is not True for r in serial]
        merged, stats = check_sources(
            workload,
            files,
            chunk=4,
            mesh=checker_mesh(),
            lanes=lanes,
            reduce=True,
        )
        assert merged["histories"] == len(files)
        assert merged["invalid"] == sum(flags)
        assert merged["first_invalid"] == _first_invalid(flags)
        assert stats.histories == len(files)

    def test_elle_degenerate_fallback_folds_in(self, cpu_devices, tmp_path):
        """The reduced verdict must count host-fallback (degenerate)
        invalids too, and first_invalid must be the minimum across the
        device and host populations — with the splice crossing shard
        boundaries on the 8-device mesh."""
        from test_fuzz_elle_device import fuzz_history

        from jepsen_tpu.checkers.elle import elle_mops_for
        from jepsen_tpu.parallel.mesh import checker_mesh

        class _SH:
            def __init__(self, ops):
                self.ops = ops

        base = [_SH(fuzz_history(seed, n_txns=10)) for seed in range(10)]
        assert any(elle_mops_for(sh.ops)[1].degenerate for sh in base)
        files = _write(tmp_path, base)
        serial, _ = check_sources("elle", files, chunk=4, serial=True)
        flags = [r["elle"]["valid?"] is not True for r in serial]
        merged, _ = check_sources(
            "elle", files, chunk=4, mesh=checker_mesh(), lanes=0,
            reduce=True,
        )
        assert merged["invalid"] == sum(flags)
        assert merged["first_invalid"] == _first_invalid(flags)

    def test_reduce_without_mesh_rejected(self, tmp_path):
        base = synth_stream_batch(2, StreamSynthSpec(n_ops=10))
        files = _write(tmp_path, base)
        with pytest.raises((ValueError, PipelineError)):
            check_sources("stream", files, reduce=True)


class TestLaneCensus:
    """Size-aware balancing's honest fallback: drops are loud."""

    def test_dropped_files_logged_counted_and_explicit(
        self, cpu_devices, tmp_path, caplog
    ):
        import logging

        base = synth_stream_batch(6, StreamSynthSpec(n_ops=25), lost=1)
        files = _write(tmp_path, base)
        empty = tmp_path / "zero.jsonl"
        empty.write_text("")
        missing = tmp_path / "not" / "here.jsonl"
        mix = files[:2] + [empty] + files[2:4] + [missing] + files[4:]
        from jepsen_tpu.obs.metrics import REGISTRY

        zero_before = REGISTRY.value(
            "pipeline.files_dropped", reason="zero-length"
        )
        unread_before = REGISTRY.value(
            "pipeline.files_dropped", reason="unreadable"
        )
        with caplog.at_level(logging.WARNING, "jepsen_tpu.parallel.pipeline"):
            res, stats = check_sources("stream", mix, chunk=3, lanes=2)
        assert stats.dropped == 2
        # every drop named in the log — no silent truncation
        assert "zero.jsonl" in caplog.text and "here.jsonl" in caplog.text
        # ... and countable AFTER the run in the global obs registry,
        # by reason (ISSUE 10: the log line alone was the blind spot)
        assert REGISTRY.value(
            "pipeline.files_dropped", reason="zero-length"
        ) == zero_before + 1
        assert REGISTRY.value(
            "pipeline.files_dropped", reason="unreadable"
        ) == unread_before + 1
        # the results list keeps one entry per source, with explicit
        # unknown verdicts at the dropped positions
        assert len(res) == len(mix)
        assert res[2]["stream"]["valid?"] == "unknown"
        assert res[5]["stream"]["valid?"] == "unknown"
        serial, _ = check_sources("stream", files, chunk=3, serial=True)
        assert [r for i, r in enumerate(res) if i not in (2, 5)] == serial

    def test_reduce_counts_drops(self, cpu_devices, tmp_path):
        from jepsen_tpu.parallel.mesh import checker_mesh

        base = synth_stream_batch(5, StreamSynthSpec(n_ops=20))
        files = _write(tmp_path, base)
        empty = tmp_path / "zero.jsonl"
        empty.write_text("")
        merged, stats = check_sources(
            "stream", files + [empty], chunk=2, mesh=checker_mesh(),
            lanes=0, reduce=True,
        )
        assert merged["dropped"] == 1 and stats.dropped == 1
        assert merged["histories"] == len(files)


class TestLaneCrashContract:
    def test_crashed_lane_aborts_with_no_results(self, cpu_devices):
        """--fail-fast: a lane crash aborts the whole run —
        PipelineError, nothing returned (the PR-5 contract, preserved
        verbatim under the escape hatch)."""
        import dataclasses as dc

        from jepsen_tpu.parallel.pipeline import _Family

        def produce(unit):
            if unit == 3:
                raise RuntimeError("lane packer exploded")
            return np.full((4,), unit, np.int32)

        import jax.numpy as jnp

        fam = _Family(
            produce=produce,
            check=lambda x: jnp.asarray(x) + 1,
            place=lambda x: x,
            convert=lambda item, col: [col],
        )
        fams = [dc.replace(fam) for _ in range(4)]
        with pytest.raises(PipelineError, match="lane .* crashed"):
            run_lanes(list(range(12)), fams, depth=2, fail_fast=True)

    def test_crashed_unit_retries_on_another_lane_then_quarantines(
        self, cpu_devices
    ):
        """The elastic default, N-lane edition: the crashing unit is
        retried on a DIFFERENT lane, then quarantined; every other
        unit's result survives."""
        import dataclasses as dc

        from jepsen_tpu.parallel.pipeline import _Family, Quarantined

        def produce(unit):
            if unit == 3:
                raise RuntimeError("lane packer exploded")
            return np.full((4,), unit, np.int32)

        import jax.numpy as jnp

        fam = _Family(
            produce=produce,
            check=lambda x: jnp.asarray(x) + 1,
            place=lambda x: x,
            convert=lambda item, col: [col],
        )
        fams = [dc.replace(fam) for _ in range(4)]
        res, stats = run_lanes(list(range(12)), fams, depth=2)
        assert isinstance(res[3], Quarantined)
        # two attempts, on two different lanes
        assert len(res[3].attempts) == 2
        assert res[3].attempts[0] != res[3].attempts[1]
        assert all(
            not isinstance(r, Quarantined)
            for i, r in enumerate(res)
            if i != 3
        )
        assert stats.unit_retries >= 1

    def test_corrupt_history_mid_lanes_aborts(self, cpu_devices, tmp_path):
        base = synth_stream_batch(5, StreamSynthSpec(n_ops=20))
        files = _write(tmp_path, base)
        bad = tmp_path / "torn.jsonl"
        bad.write_text('{"type": "not a real op"\n')  # torn JSON line
        with pytest.raises(PipelineError):
            check_sources(
                "stream", files[:2] + [bad] + files[2:], chunk=2, lanes=2,
                fail_fast=True,
            )

    def test_corrupt_history_mid_lanes_quarantines_elastically(
        self, cpu_devices, tmp_path
    ):
        """Elastic lanes: the torn file quarantines alone; the other
        histories' verdicts equal the serial oracle."""
        base = synth_stream_batch(5, StreamSynthSpec(n_ops=20))
        files = _write(tmp_path, base)
        bad = tmp_path / "torn.jsonl"
        bad.write_text('{"type": "not a real op"\n')
        res, stats = check_sources(
            "stream", files[:2] + [bad] + files[2:], chunk=2, lanes=2
        )
        assert res[2]["stream"]["valid?"] == "unknown"
        assert "quarantined" in res[2]["stream"]
        serial, _ = check_sources("stream", files, chunk=2, serial=True)
        assert [r for i, r in enumerate(res) if i != 2] == serial
        assert stats.quarantined == 1


class TestNativeStripedCursors:
    """jt_*_files_part: striped calls over ONE shared path array ==
    the full-scan results restricted to the stripe."""

    @pytest.fixture(autouse=True)
    def _lib(self):
        from jepsen_tpu.history import fastpack

        lib = fastpack._load()
        if lib is None:
            pytest.skip("native packer unavailable")
        if not hasattr(lib, "jt_stream_rows_files_part"):
            pytest.skip("stale native build without striped cursors")

    def test_stream_stripes_cover_exactly(self, tmp_path):
        from jepsen_tpu.history.fastpack import stream_rows_files

        base = synth_stream_batch(9, StreamSynthSpec(n_ops=20), lost=1)
        files = _write(tmp_path, base)
        full = stream_rows_files(files, threads=2)
        for part in range(3):
            got = stream_rows_files(files, threads=2, part=part, n_parts=3)
            for i in range(len(files)):
                if i % 3 == part:
                    assert (got[i][0] == full[i][0]).all()
                    assert got[i][1] == full[i][1]
                else:
                    assert got[i] is None

    def test_queue_and_elle_stripes(self, tmp_path):
        from jepsen_tpu.history.fastpack import elle_mops_files, pack_files

        qfiles = _write(
            tmp_path, synth_batch(5, SynthSpec(n_ops=30), lost=1), "q"
        )
        full = pack_files(qfiles, threads=2)
        got = pack_files(qfiles, threads=2, part=1, n_parts=2)
        for i in range(5):
            if i % 2 == 1:
                assert got[i][0] == full[i][0]
                assert (got[i][1] == full[i][1]).all()
            else:
                assert got[i] is None

        efiles = _write(
            tmp_path, synth_elle_batch(5, ElleSynthSpec(n_txns=8)), "e"
        )
        full = elle_mops_files(efiles, threads=2)
        got = elle_mops_files(efiles, threads=2, part=0, n_parts=2)
        for i in range(5):
            if i % 2 == 0:
                assert (got[i][0] == full[i][0]).all()
                assert got[i][1] == full[i][1]
            else:
                assert got[i] is None
