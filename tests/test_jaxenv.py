"""Backend-bootstrap guards: the round-1 failure mode as regression tests.

Round 1 lost both driver artifacts to a hanging chip-plugin init: an
in-process probe blocked jax's backend lock forever, so even a CPU
fallback was impossible.  `ensure_backend` now probes in a killable
subprocess — these tests prove a too-slow probe (a) raises TimeoutError
instead of hanging, (b) leaves the parent process unpoisoned, and (c)
still allows a working CPU fallback — in-process and through the CLI.
"""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_FALLBACK_PROBE = r"""
import os

os.environ.pop("JAX_PLATFORMS", None)  # let sitecustomize / default win
from jepsen_tpu.utils.jaxenv import ensure_backend, pin_cpu_platform

try:
    # deadline far below any real plugin init: the probe subprocess is
    # killed, which must surface as TimeoutError (never a hang)
    ensure_backend(deadline=0.05)
    print("NO-TIMEOUT")  # plugin initialized implausibly fast — still fine
except TimeoutError:
    pin_cpu_platform()
    import jax

    assert jax.default_backend() == "cpu"
    assert jax.devices()[0].platform == "cpu"
    print("FALLBACK-OK")
"""


def test_probe_deadline_raises_and_cpu_fallback_works():
    r = subprocess.run(
        [sys.executable, "-c", _FALLBACK_PROBE],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert r.stdout.strip().splitlines()[-1] in ("FALLBACK-OK", "NO-TIMEOUT")


def test_cli_check_survives_backend_deadline(tmp_path):
    """`check --checker tpu` under an impossibly small probe deadline must
    warn, fall back to CPU, and still deliver the verdict (exit 0/1, not a
    hang or traceback)."""
    import os

    store = tmp_path / "s"
    env = dict(os.environ)
    env["JEPSEN_TPU_BACKEND_DEADLINE"] = "0.05"
    # the probe path must actually run: an inherited cpu pin would take
    # the fast path and never exercise the fallback under test
    env.pop("JAX_PLATFORMS", None)
    synth = subprocess.run(
        [
            sys.executable, "-m", "jepsen_tpu", "synth",
            "--count", "2", "--ops", "30", "--store", str(store),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert synth.returncode == 0, synth.stderr[-2000:]
    r = subprocess.run(
        [
            sys.executable, "-m", "jepsen_tpu", "check",
            "--checker", "tpu", str(store),
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=180,
    )
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-1500:])
    assert "Everything looks good" in r.stdout
    # either the warning fired (deadline hit) or the probe beat 50 ms —
    # in this environment the tunnel takes seconds, so expect the warning
    assert "falling back to the CPU backend" in (r.stdout + r.stderr)


class TestCompilationCache:
    def test_env_off_disables(self, tmp_path, monkeypatch):
        from jepsen_tpu.utils import jaxenv

        for off in ("0", "off", "none", ""):
            monkeypatch.setenv(jaxenv.COMPILE_CACHE_ENV, off)
            assert jaxenv.enable_compilation_cache(str(tmp_path)) is None

    def test_env_path_overrides_argument(self, tmp_path, monkeypatch):
        import jax

        from jepsen_tpu.utils import jaxenv

        prev = jax.config.jax_compilation_cache_dir
        override = tmp_path / "elsewhere"
        monkeypatch.setenv(jaxenv.COMPILE_CACHE_ENV, str(override))
        try:
            got = jaxenv.enable_compilation_cache(str(tmp_path / "arg"))
            assert got == str(override)
            assert override.is_dir()  # created
            assert jax.config.jax_compilation_cache_dir == str(override)
        finally:
            # the tmp dir dies with the test: a dangling global cache
            # path would soft-fail every later compile in this process
            jax.config.update("jax_compilation_cache_dir", prev)

    def test_unusable_dir_fails_soft(self, tmp_path, monkeypatch):
        """A missing cache must never sink a run: unusable dir -> None,
        the caller proceeds uncached."""
        from jepsen_tpu.utils import jaxenv

        monkeypatch.delenv(jaxenv.COMPILE_CACHE_ENV, raising=False)
        blocker = tmp_path / "f"
        blocker.write_text("not a dir")
        assert (
            jaxenv.enable_compilation_cache(str(blocker / "sub")) is None
        )

    def test_entry_count(self, tmp_path):
        from jepsen_tpu.utils.jaxenv import compile_cache_entries

        assert compile_cache_entries(None) == 0
        assert compile_cache_entries(str(tmp_path / "nope")) == 0
        (tmp_path / "a-cache").write_text("x")
        (tmp_path / ".hidden").write_text("x")
        assert compile_cache_entries(str(tmp_path)) == 1


class TestCpuPinNormalization:
    """Advisor r5: the CPU fast-path check must normalize the pin —
    'CPU', ' cpu ', and 'cpu,tpu' must all skip the 3×45 s probe, while
    non-CPU-first pins must not."""

    import pytest as _pytest

    @_pytest.mark.parametrize(
        "value,expected",
        [
            ("cpu", True),
            ("CPU", True),
            (" cpu ", True),
            ("cpu,tpu", True),
            ("CPU,TPU", True),
            (" Cpu , tpu", True),
            ("tpu", False),
            ("tpu,cpu", False),  # CPU is not the default platform here
            ("", False),
            (None, False),
            ("cpux", False),
        ],
    )
    def test_pins_cpu(self, value, expected):
        from jepsen_tpu.utils.jaxenv import _pins_cpu

        assert _pins_cpu(value) is expected

    def test_fast_path_taken_for_mixed_case_env(self, monkeypatch):
        """ensure_backend with JAX_PLATFORMS=CPU must return instantly
        (config pinned to cpu) — no subprocess probe, no deadline risk."""
        import time

        from jepsen_tpu.utils import jaxenv

        monkeypatch.setenv("JAX_PLATFORMS", "CPU")
        t0 = time.monotonic()
        # deadline far below the probe's runtime: if the fast path were
        # missed, the probe subprocess (python -c 'import jax...') could
        # not possibly finish in time and we'd see TimeoutError
        backend = jaxenv.ensure_backend(deadline=120.0)
        assert backend == "cpu"
        assert time.monotonic() - t0 < 30.0  # no 45 s probe rounds
