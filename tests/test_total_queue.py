"""total-queue checker: anomaly detection + CPU≡TPU differential tests."""

import pytest

from jepsen_tpu.checkers.total_queue import (
    check_total_queue_batch,
    check_total_queue_cpu,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import SynthSpec, synth_batch, synth_history


def both(history):
    cpu = check_total_queue_cpu(history)
    tpu = check_total_queue_batch([history])[0]
    assert cpu == tpu, f"cpu/tpu divergence:\n{cpu}\n{tpu}"
    return cpu


def test_clean_history_valid():
    sh = synth_history(SynthSpec(n_ops=300, seed=1))
    r = both(sh.ops)
    assert r["valid?"]
    assert r["lost-count"] == 0 and r["unexpected-count"] == 0
    assert r["attempt-count"] >= r["acknowledged-count"]


def test_lost_detected():
    sh = synth_history(SynthSpec(n_ops=300, seed=2, lost=3))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["lost"] == sh.lost


def test_duplicates_reported_but_valid():
    sh = synth_history(SynthSpec(n_ops=300, seed=3, duplicated=2))
    r = both(sh.ops)
    assert r["valid?"]  # at-least-once delivery is legal
    assert r["duplicated"] == sh.duplicated
    assert r["duplicated-count"] == 2


def test_unexpected_detected():
    sh = synth_history(SynthSpec(n_ops=300, seed=4, unexpected=2))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["unexpected"] == sh.unexpected


def test_recovered_from_indeterminate_enqueue():
    # an :info enqueue whose value surfaces later is recovered, and valid
    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 7, time=0),
            Op(OpType.INFO, OpF.ENQUEUE, 0, 7, time=1_000_000, error="timeout"),
            Op.invoke(OpF.DEQUEUE, 1, time=2_000_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 7, time=3_000_000),
        ]
    )
    r = both(ops)
    assert r["valid?"]
    assert r["recovered"] == {7}
    assert r["ok-count"] == 1 and r["acknowledged-count"] == 0


def test_phantom_fail_is_recovered_not_unexpected():
    # total-queue counts attempts (invokes), so a read of a *failed* enqueue
    # still matched an attempt: recovered here, flagged by queue-lin instead
    sh = synth_history(SynthSpec(n_ops=200, seed=5, phantom_fail=1))
    r = both(sh.ops)
    assert r["valid?"]
    assert sh.phantom_fail <= r["recovered"]


def test_readme_shape_keys():
    r = both(synth_history(SynthSpec(n_ops=100, seed=6)).ops)
    expect = {
        "valid?",
        "attempt-count",
        "acknowledged-count",
        "ok-count",
        "lost",
        "lost-count",
        "unexpected",
        "unexpected-count",
        "duplicated",
        "duplicated-count",
        "recovered",
        "recovered-count",
    }
    assert set(r) == expect


@pytest.mark.parametrize("seed", range(5))
def test_differential_random_mixed_anomalies(seed):
    sh = synth_history(
        SynthSpec(
            n_ops=400,
            seed=100 + seed,
            lost=seed % 3,
            duplicated=(seed + 1) % 2,
            unexpected=seed % 2,
        )
    )
    r = both(sh.ops)
    assert r["lost"] == sh.lost
    assert sh.unexpected == r["unexpected"]
    assert r["valid?"] == (not sh.lost and not sh.unexpected)


def test_batched_matches_per_history():
    batch = synth_batch(8, SynthSpec(n_ops=150), lost=1)
    histories = [sh.ops for sh in batch]
    rs = check_total_queue_batch(histories)
    for sh, r in zip(batch, rs):
        assert r == check_total_queue_cpu(sh.ops)
