"""Fenced queues reject ``basic.consume`` (r7 review): fencing tokens
are minted and attached only on the ``basic.get`` reply path, and in
replicated mode a push delivery's DEQ apply would still advance the
fence — the consumer would hold the lock with a superseded-by-nobody
token it never received.  The broker must refuse the consume loudly
(channel close, 540 not-implemented) instead of silently diverging
from the get path."""

import socket
import struct
import time

from _load import scaled

from jepsen_tpu.harness.broker import (
    FRAME_END,
    MiniAmqpBroker,
    _longstr,
    _shortstr,
)
from jepsen_tpu.harness.replication import ReplicatedBackend


def _send_method(sock, ch, cls, mth, args=b""):
    payload = struct.pack(">HH", cls, mth) + args
    sock.sendall(
        struct.pack(">BHI", 1, ch, len(payload))
        + payload
        + bytes([FRAME_END])
    )


def _read_frame(sock):
    hdr = b""
    while len(hdr) < 7:
        hdr += sock.recv(7 - len(hdr))
    ftype, ch, size = struct.unpack(">BHI", hdr)
    payload = b""
    while len(payload) < size:
        payload += sock.recv(size - len(payload))
    sock.recv(1)  # frame end
    return ftype, ch, payload


def _read_method(sock):
    ftype, ch, payload = _read_frame(sock)
    assert ftype == 1, f"expected a method frame, got type {ftype}"
    cls, mth = struct.unpack(">HH", payload[:4])
    return ch, cls, mth, payload[4:]


def _handshake(port):
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    sock.settimeout(10)
    sock.sendall(b"AMQP\x00\x00\x09\x01")
    assert _read_method(sock)[1:3] == (10, 10)  # Start
    _send_method(sock, 0, 10, 11)  # Start-Ok
    assert _read_method(sock)[1:3] == (10, 30)  # Tune
    _send_method(sock, 0, 10, 31)  # Tune-Ok
    _send_method(sock, 0, 10, 40)  # Open
    assert _read_method(sock)[1:3] == (10, 41)  # Open-Ok
    _send_method(sock, 1, 20, 10)  # Channel.Open
    assert _read_method(sock)[1:3] == (20, 11)
    return sock


def _declare(sock, qname, args_table=b""):
    _send_method(
        sock, 1, 50, 10,
        struct.pack(">H", 0) + _shortstr(qname) + b"\x00"
        + _longstr(args_table),
    )
    assert _read_method(sock)[1:3] == (50, 11)


_FENCING = _shortstr("x-fencing") + b"t\x01"


def test_consume_on_fenced_queue_is_rejected():
    b = MiniAmqpBroker(port=0).start()
    try:
        sock = _handshake(b.port)
        _declare(sock, "jepsen.lock", _FENCING)
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok
        ch, cls, mth, args = _read_method(sock)
        assert (cls, mth) == (20, 40), "expected Channel.Close"
        (code,) = struct.unpack(">H", args[:2])
        assert code == 540
        assert b"fenced" in args
        sock.close()
    finally:
        b.stop()


def test_redeclare_without_fencing_allows_consume_again():
    # last declare wins: the fenced observation must not stick forever
    b = MiniAmqpBroker(port=0).start()
    try:
        sock = _handshake(b.port)
        _declare(sock, "jepsen.lock", _FENCING)
        _declare(sock, "jepsen.lock")  # redeclared plain
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok
        # channel survived: a get on the same channel answers get-empty
        _send_method(
            sock, 1, 60, 70,
            struct.pack(">H", 0) + _shortstr("jepsen.lock") + b"\x00",
        )
        assert _read_method(sock)[1:3] == (60, 72)  # Get-Empty
        sock.close()
    finally:
        b.stop()


def test_consumer_registered_before_fenced_declare_is_closed_loudly():
    # the registration-time rejection can't see a declare that hasn't
    # happened yet: the delivery-time re-check must refuse just as
    # loudly (540 channel close), never stall silently or push a
    # tokenless grant
    b = MiniAmqpBroker(port=0).start()
    try:
        sock = _handshake(b.port)
        _declare(sock, "jepsen.lock")  # plain at consume time
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok
        other = _handshake(b.port)
        _declare(other, "jepsen.lock", _FENCING)  # now fenced
        # a publish kicks the delivery loop for the waiting consumer
        _send_method(
            other, 1, 60, 40,
            struct.pack(">H", 0) + _shortstr("")
            + _shortstr("jepsen.lock") + b"\x00",
        )
        body = b"grant"
        other.sendall(
            struct.pack(">BHI", 2, 1, 14)
            + struct.pack(">HHQH", 60, 0, len(body), 0)
            + bytes([FRAME_END])
        )
        other.sendall(
            struct.pack(">BHI", 3, 1, len(body)) + body
            + bytes([FRAME_END])
        )
        ch, cls, mth, args = _read_method(sock)
        assert (cls, mth) == (20, 40), "expected Channel.Close, not a push"
        (code,) = struct.unpack(">H", args[:2])
        assert code == 540
        sock.close()
        other.close()
    finally:
        b.stop()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_consume_rejected_when_declare_came_via_another_node():
    """r7 review follow-up: the fenced-consume rejection must key off the
    COMMITTED queue meta, not a node-local observation of the declare —
    a broker whose serve loop never processed the queue.declare (it
    arrived via a peer) would otherwise fail open and push tokenless
    grants."""
    names = ["n0", "n1"]
    peers = {nm: ("127.0.0.1", _free_port()) for nm in names}
    brokers = {
        nm: MiniAmqpBroker(
            port=0,
            replication=ReplicatedBackend(
                nm, peers, election_timeout=(0.15, 0.3),
                heartbeat_s=0.04, submit_timeout_s=2.0,
            ),
        ).start()
        for nm in names
    }
    try:
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline and not any(
            b.replication.raft.is_leader() for b in brokers.values()
        ):
            time.sleep(0.02)
        assert any(b.replication.raft.is_leader() for b in brokers.values())

        sock_a = _handshake(brokers["n0"].port)
        _declare(sock_a, "jepsen.lock", _FENCING)  # commits via n0

        # wait for n1's replica to apply the committed declare
        mach = brokers["n1"].replication.machine
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline:
            with mach.lock:
                if (mach.meta.get("jepsen.lock") or {}).get("fenced"):
                    break
            time.sleep(0.02)
        else:
            raise AssertionError("declare never applied on n1")
        # n1's serve loop never saw the declare frame: its local
        # observation set is empty — the committed meta must carry it
        assert "jepsen.lock" not in brokers["n1"]._fenced_queues

        sock_b = _handshake(brokers["n1"].port)
        _send_method(
            sock_b, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock_b)[1:3] == (60, 21)  # Consume-Ok
        ch, cls, mth, args = _read_method(sock_b)
        assert (cls, mth) == (20, 40), "expected Channel.Close"
        (code,) = struct.unpack(">H", args[:2])
        assert code == 540
        sock_a.close()
        sock_b.close()
    finally:
        for b in brokers.values():
            b.stop()


def test_plain_redeclare_via_another_node_clears_fencedness():
    """Second r7 advisor pass: the committed meta must win in BOTH
    directions.  A fenced declare served by n0 leaves a shadow entry in
    n0's local observation set; when the queue is later redeclared
    PLAIN via n1 (last declare wins, committed), n0's stale shadow entry
    must not keep rejecting consumes forever."""
    names = ["n0", "n1"]
    peers = {nm: ("127.0.0.1", _free_port()) for nm in names}
    brokers = {
        nm: MiniAmqpBroker(
            port=0,
            replication=ReplicatedBackend(
                nm, peers, election_timeout=(0.15, 0.3),
                heartbeat_s=0.04, submit_timeout_s=2.0,
            ),
        ).start()
        for nm in names
    }
    try:
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline and not any(
            b.replication.raft.is_leader() for b in brokers.values()
        ):
            time.sleep(0.02)
        assert any(b.replication.raft.is_leader() for b in brokers.values())

        sock_a = _handshake(brokers["n0"].port)
        _declare(sock_a, "jepsen.lock", _FENCING)   # fenced via n0
        sock_b = _handshake(brokers["n1"].port)
        _declare(sock_b, "jepsen.lock")             # plain via n1

        # wait for n0's replica to apply the committed plain redeclare
        mach = brokers["n0"].replication.machine
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline:
            with mach.lock:
                meta = mach.meta.get("jepsen.lock")
                if meta is not None and not meta.get("fenced"):
                    break
            time.sleep(0.02)
        else:
            raise AssertionError("plain redeclare never applied on n0")
        # n0's serve loop only ever saw the FENCED declare: its shadow
        # set still carries the stale entry the committed meta overrides
        assert "jepsen.lock" in brokers["n0"]._fenced_queues

        _send_method(
            sock_a, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock_a)[1:3] == (60, 21)  # Consume-Ok
        # channel survived: a get on the same channel answers get-empty
        _send_method(
            sock_a, 1, 60, 70,
            struct.pack(">H", 0) + _shortstr("jepsen.lock") + b"\x00",
        )
        assert _read_method(sock_a)[1:3] == (60, 72)  # Get-Empty
        sock_a.close()
        sock_b.close()
    finally:
        for b in brokers.values():
            b.stop()


def test_local_meta_wins_over_stale_shadow_entry():
    # the non-replicated helper decides under state_lock, meta entry
    # first: a stale shadow entry (declare raced against a concurrent
    # plain redeclare) must not override the last committed declare
    b = MiniAmqpBroker(port=0).start()
    try:
        sock = _handshake(b.port)
        _declare(sock, "jepsen.lock")
        with b.state_lock:
            b._fenced_queues.add("jepsen.lock")  # stale observation
            assert not b._is_fenced_queue_locked("jepsen.lock")
        assert not b._is_fenced_queue("jepsen.lock")
        # and a queue with no meta entry at all falls back to the shadow
        with b.state_lock:
            b._fenced_queues.add("jepsen.undeclared")
        assert b._is_fenced_queue("jepsen.undeclared")
        sock.close()
    finally:
        b.stop()


def test_unacked_consumer_on_newly_fenced_queue_is_closed_not_stalled():
    """Replicated push path: a consumer holding an unacked delivery from
    before the queue went fenced must still get the loud 540 close on
    the next kick — the QoS-1 one-in-flight return must not starve the
    fenced re-check into a silent stall (third advisor pass)."""
    peers = {"n0": ("127.0.0.1", _free_port())}
    b = MiniAmqpBroker(
        port=0,
        replication=ReplicatedBackend(
            "n0", peers, election_timeout=(0.15, 0.3),
            heartbeat_s=0.04, submit_timeout_s=2.0,
        ),
    ).start()
    try:
        deadline = time.monotonic() + scaled(5.0)
        while time.monotonic() < deadline and not b.replication.raft.is_leader():
            time.sleep(0.02)
        assert b.replication.raft.is_leader()

        sock = _handshake(b.port)
        _declare(sock, "jepsen.lock")  # plain at consume time
        # subscribe, then publish one message and receive it (acking
        # consumer: the delivery stays unacked)
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok
        body = b"grant"
        _send_method(
            sock, 1, 60, 40,
            struct.pack(">H", 0) + _shortstr("")        # default exchange
            + _shortstr("jepsen.lock") + b"\x00",       # routing key
        )
        sock.sendall(
            struct.pack(">BHI", 2, 1, 14)
            + struct.pack(">HHQH", 60, 0, len(body), 0)
            + bytes([FRAME_END])
        )
        sock.sendall(
            struct.pack(">BHI", 3, 1, len(body)) + body
            + bytes([FRAME_END])
        )
        assert _read_method(sock)[1:3] == (60, 60)  # Deliver (unacked)
        sock.recv(4096)  # drain the content frames

        other = _handshake(b.port)
        _declare(other, "jepsen.lock", _FENCING)  # now fenced
        # a second publish kicks the delivery loop for the consumer
        _send_method(
            other, 1, 60, 40,
            struct.pack(">H", 0) + _shortstr("")
            + _shortstr("jepsen.lock") + b"\x00",
        )
        other.sendall(
            struct.pack(">BHI", 2, 1, 14)
            + struct.pack(">HHQH", 60, 0, len(body), 0)
            + bytes([FRAME_END])
        )
        other.sendall(
            struct.pack(">BHI", 3, 1, len(body)) + body
            + bytes([FRAME_END])
        )
        ch, cls, mth, args = _read_method(sock)
        assert (cls, mth) == (20, 40), "expected Channel.Close, not a stall"
        (code,) = struct.unpack(">H", args[:2])
        assert code == 540
        sock.close()
        other.close()
    finally:
        b.stop()


def test_rejected_fenced_consume_keeps_prior_subscription_alive():
    """The registration-time rejection must not clear a pre-existing
    subscription to a DIFFERENT, unfenced queue, nor clobber its ack
    mode (fourth/fifth advisor passes): after the 540 close for the
    fenced consume, pushes on the original no-ack subscription keep
    flowing — TWO deliveries, which would stall at the QoS-1 gate had
    the rejected consume's default-ack mode stuck."""
    b = MiniAmqpBroker(port=0).start()
    try:
        sock = _handshake(b.port)
        _declare(sock, "jepsen.queue")
        _declare(sock, "jepsen.lock", _FENCING)
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.queue")
            + _shortstr("") + b"\x02" + _longstr(b""),  # no-ack
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok (plain)
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.lock")
            + _shortstr("") + b"\x00" + _longstr(b""),  # default ack
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok (fenced)
        ch, cls, mth, args = _read_method(sock)
        assert (cls, mth) == (20, 40)  # the fenced consume's 540 close
        assert struct.unpack(">H", args[:2])[0] == 540
        # the plain no-ack subscription survived with its mode intact:
        # two publishes both get pushed (an acking consumer that never
        # acks would stall after the first)
        other = _handshake(b.port)
        body = b"msg"
        for _ in range(2):
            _send_method(
                other, 1, 60, 40,
                struct.pack(">H", 0) + _shortstr("")
                + _shortstr("jepsen.queue") + b"\x00",
            )
            other.sendall(
                struct.pack(">BHI", 2, 1, 14)
                + struct.pack(">HHQH", 60, 0, len(body), 0)
                + bytes([FRAME_END])
            )
            other.sendall(
                struct.pack(">BHI", 3, 1, len(body)) + body
                + bytes([FRAME_END])
            )
        deliveries = 0
        while deliveries < 2:  # content frames are skipped naturally
            ftype, _, payload = _read_frame(sock)
            if ftype == 1 and struct.unpack(">HH", payload[:4]) == (60, 60):
                deliveries += 1
        sock.close()
        other.close()
    finally:
        b.stop()


def test_consume_on_plain_queue_still_works():
    b = MiniAmqpBroker(port=0).start()
    try:
        sock = _handshake(b.port)
        _declare(sock, "jepsen.queue")
        _send_method(
            sock, 1, 60, 20,
            struct.pack(">H", 0) + _shortstr("jepsen.queue")
            + _shortstr("") + b"\x00" + _longstr(b""),
        )
        assert _read_method(sock)[1:3] == (60, 21)  # Consume-Ok
        # no channel close follows: basic.get on the same channel
        # answers get-empty, proving the channel survived the consume
        _send_method(
            sock, 1, 60, 70,
            struct.pack(">H", 0) + _shortstr("jepsen.queue") + b"\x00",
        )
        assert _read_method(sock)[1:3] == (60, 72)  # Get-Empty
        sock.close()
    finally:
        b.stop()
