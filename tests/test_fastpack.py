"""Differential tests for the native history packer (history/fastpack.py
+ native/rows_packer.cpp) against the Python packer.

The native packer must be BIT-IDENTICAL to ``read_history`` +
``workload_of`` + ``_rows_for`` on everything it accepts, and must
return None (never a wrong matrix) on anything it doesn't — the Python
path is the single source of truth for all error behavior.  Coverage:
every synth workload family with anomalies injected, the value-shape
edge cases (bool/null/float/string/object/nested/empty-list/negative),
missing fields, blank lines, and the int32 overflow contract.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from jepsen_tpu.history.fastpack import pack_file
from jepsen_tpu.history.ops import workload_of
from jepsen_tpu.history.rows import _rows_for
from jepsen_tpu.history.store import read_history, write_history_jsonl
from jepsen_tpu.history.synth import (
    ElleSynthSpec,
    MutexSynthSpec,
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_elle_batch,
    synth_mutex_batch,
    synth_stream_batch,
)

@pytest.fixture(autouse=True)
def _require_native():
    # the library builds on first use; if the toolchain is absent these
    # tests skip rather than silently passing through the fallback
    from jepsen_tpu.history import fastpack

    if fastpack._load() is None:
        pytest.skip("native rows packer unavailable")


def _assert_identical(path):
    fast = pack_file(path)
    assert fast is not None
    history = read_history(path)
    assert fast[0] == workload_of(history)
    np.testing.assert_array_equal(fast[1], _rows_for(history))


def _write(tmp_path, dicts, name="history.jsonl"):
    p = tmp_path / name
    with open(p, "w") as fh:
        for d in dicts:
            fh.write(json.dumps(d) + "\n")
    return p


# ---------------------------------------------------------------------------
# Synth families: the native packer must reproduce the Python matrices
# exactly on realistic histories, anomalies included
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_queue_family_identical(tmp_path, seed):
    spec = SynthSpec(
        n_ops=120, seed=seed, lost=2, duplicated=1, unexpected=1
    )
    for i, sh in enumerate(synth_batch(3, spec)):
        p = tmp_path / f"h{i}.jsonl"
        write_history_jsonl(p, sh.ops)
        _assert_identical(p)


def test_stream_family_identical(tmp_path):
    for i, sh in enumerate(
        synth_stream_batch(3, StreamSynthSpec(n_ops=80), lost=1)
    ):
        p = tmp_path / f"s{i}.jsonl"
        write_history_jsonl(p, sh.ops)
        _assert_identical(p)


def test_elle_family_identical(tmp_path):
    for i, sh in enumerate(
        synth_elle_batch(3, ElleSynthSpec(), g1a=1)
    ):
        p = tmp_path / f"e{i}.jsonl"
        write_history_jsonl(p, sh.ops)
        _assert_identical(p)


def test_mutex_family_identical(tmp_path):
    for i, sh in enumerate(
        synth_mutex_batch(3, MutexSynthSpec(), double_grant=1)
    ):
        p = tmp_path / f"m{i}.jsonl"
        write_history_jsonl(p, sh.ops)
        _assert_identical(p)


# ---------------------------------------------------------------------------
# Value-shape and field edge cases
# ---------------------------------------------------------------------------


def test_value_shapes_identical(tmp_path):
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "invoke", "f": "enqueue", "process": 0,
             "time": 1_500_000, "value": 5},
            # bool values: isinstance(True, int) -> 1/0
            {"index": 1, "type": "ok", "f": "enqueue", "process": 0,
             "time": 3_700_001, "value": True},
            {"index": 2, "type": "ok", "f": "enqueue", "process": 4,
             "time": 3_700_001, "value": False},
            # null and absent -> NO_VALUE
            {"index": 3, "type": "ok", "f": "enqueue", "process": 5,
             "time": -1, "value": None},
            {"index": 4, "type": "invoke", "f": "dequeue", "process": 6},
            # float / string / object -> NO_VALUE
            {"index": 5, "type": "ok", "f": "enqueue", "process": 7,
             "time": 9, "value": 3.5},
            {"index": 6, "type": "ok", "f": "enqueue", "process": 8,
             "time": 9, "value": "surprise"},
            {"index": 7, "type": "ok", "f": "enqueue", "process": 9,
             "time": 9, "value": {"k": [1, 2]}},
            # drain explosion, incl. empty list -> single NO_VALUE row
            {"index": 8, "type": "ok", "f": "drain", "process": 1,
             "time": 20_000_000, "value": [7, 8, 9]},
            {"index": 9, "type": "ok", "f": "drain", "process": 2,
             "time": 21_000_000, "value": []},
            # nested lists (stream read pairs) -> NO_VALUE elements;
            # bools inside lists stay ints
            {"index": 10, "type": "ok", "f": "drain", "process": 3,
             "time": 22_000_000, "value": [[0, 5], 11, True, "x", None]},
            # a real value equal to the explode sentinel (-2) survives
            {"index": 11, "type": "ok", "f": "enqueue", "process": 10,
             "time": 23_000_000, "value": -2},
            # negative times stay -1 ms; nemesis ops lack "process"
            {"index": 12, "type": "info", "f": "start", "time": -1},
            {"index": 13, "type": "info", "f": "stop", "time": -1},
        ],
    )
    _assert_identical(p)


def test_latency_pairing_identical(tmp_path):
    # interleaved processes; a completion pairs with its own process's
    # open invoke only, and only when both timestamps are valid
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "invoke", "f": "enqueue", "process": 0,
             "time": 1_000_000, "value": 1},
            {"index": 1, "type": "invoke", "f": "enqueue", "process": 1,
             "time": 2_000_000, "value": 2},
            {"index": 2, "type": "ok", "f": "enqueue", "process": 1,
             "time": 5_999_999, "value": 2},  # floor((5999999-2e6)/1e6)=3
            {"index": 3, "type": "ok", "f": "enqueue", "process": 0,
             "time": 10_000_000, "value": 1},
            # completion with no preceding invoke (reconnect info)
            {"index": 4, "type": "info", "f": "enqueue", "process": 0,
             "time": 11_000_000, "value": 9},
            # invoke with missing time -> its completion gets no latency
            {"index": 5, "type": "invoke", "f": "enqueue", "process": 2,
             "value": 3},
            {"index": 6, "type": "ok", "f": "enqueue", "process": 2,
             "time": 12_000_000, "value": 3},
            # completion earlier than invoke (clock skew): negative
            # latency, floor-divided
            {"index": 7, "type": "invoke", "f": "enqueue", "process": 3,
             "time": 20_000_000, "value": 4},
            {"index": 8, "type": "ok", "f": "enqueue", "process": 3,
             "time": 19_500_000, "value": 4},
        ],
    )
    _assert_identical(p)


def test_blank_lines_and_whitespace(tmp_path):
    p = tmp_path / "history.jsonl"
    with open(p, "w") as fh:
        fh.write("\n")
        fh.write(
            '  {"index": 0, "type": "invoke", "f": "enqueue", '
            '"process": 0, "time": 1000000, "value": 3}  \n'
        )
        fh.write("   \n")
        fh.write(
            '{"index": 1, "type": "ok", "f": "enqueue", '
            '"process": 0, "time": 2000000, "value": 3}'
        )  # no trailing newline
    _assert_identical(p)


def test_error_field_and_unknown_keys_skipped(tmp_path):
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "invoke", "f": "dequeue", "process": 0,
             "time": 1_000_000},
            {"index": 1, "type": "fail", "f": "dequeue", "process": 0,
             "time": 2_000_000, "error": "exhausted",
             "extra": {"nested": ["deep", {"x": 1}]},
             "harmless-unknown-key": [1, 2]},
        ],
    )
    _assert_identical(p)


def test_workload_classification_first_match(tmp_path):
    # txn appears before acquire: elle wins (first non-queue f in order)
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "invoke", "f": "enqueue", "process": 0,
             "time": 1},
            {"index": 1, "type": "invoke", "f": "txn", "process": 1,
             "time": 2},
            {"index": 2, "type": "invoke", "f": "acquire", "process": 2,
             "time": 3},
        ],
    )
    fast = pack_file(p)
    assert fast is not None and fast[0] == "elle"
    _assert_identical(p)


def test_empty_file(tmp_path):
    p = tmp_path / "history.jsonl"
    p.write_text("")
    fast = pack_file(p)
    assert fast is not None
    assert fast[0] == "queue"
    assert fast[1].shape == (0, 8)


# ---------------------------------------------------------------------------
# Fallback contract: anything irregular -> None, Python raises
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "line",
    [
        '{"index": 0, "f": "enqueue", "process": 0}',  # missing type
        '{"index": 0, "type": "ok", "process": 0}',  # missing f
        '{"type": "levitate", "f": "enqueue"}',  # unknown type name
        '{"type": "ok", "f": "teleport"}',  # unknown f name
        '{"type": "ok", "f": "enqueue", "process": "zero"}',  # str process
        '{"type": "ok", "f": "enqueue", "process": 0',  # truncated JSON
        "42",  # non-object line
        '{"type": "ok", "f": "enqueue"} trailing',  # trailing junk
        # malformed JSON the canonical parser rejects (review r4: the
        # native parser must never accept what json.loads refuses)
        '{"type": "ok", "f": "enqueue", "index": 01}',  # leading zero
        '{"type": "ok", "f": "enqueue", "value": +5}',  # leading plus
        '{"type": "ok", "f": "enqueue", "value": 1e}',  # bare exponent
        '{"type": "ok", "f": "enqueue", "value": 1.}',  # bare fraction
        '{"type": "ok", "f": "enqueue", "value": 5abc}',  # trailing junk
        '{"type": "ok", "f": "enqueue", "extra": {oops!!}}',  # bad nested
        '{"type": "ok", "f": "enqueue", "error": "bad \\q escape"}',
        '{"type": "ok", "f": "enqueue", "value": [1, 2,]}',  # trailing ,
        # \u-escaped key spelling of "value": raw-span key matching
        # would skip it and emit a wrong matrix (review r4) — any
        # escaped key must fall back to the canonical parser
        '{"type": "ok", "f": "enqueue", "process": 3, '
        '"\\u0076alue": 7}',
        '{"type": "ok", "f": "enqueue", "proc\\u0065ss": 3, "value": 7}',
    ],
)
def test_irregular_input_falls_back(tmp_path, line):
    p = tmp_path / "history.jsonl"
    p.write_text(line + "\n")
    assert pack_file(p) is None


def test_duplicate_value_keys_last_wins(tmp_path):
    """json.loads resolves duplicate keys last-wins; the native packer
    must not accumulate list elements across duplicates (review r4)."""
    p = tmp_path / "history.jsonl"
    p.write_text(
        '{"index": 0, "type": "ok", "f": "drain", "process": 0, '
        '"time": 1000000, "value": [1], "value": [2, 3]}\n'
        '{"index": 1, "type": "ok", "f": "enqueue", "process": 1, '
        '"time": 2000000, "value": [4], "value": 9}\n'
    )
    _assert_identical(p)


def test_valid_json_the_parser_must_accept(tmp_path):
    """The strict grammar must not over-reject: escapes, \\uXXXX,
    nested structures, zero, negative zero, exponents in skipped
    fields."""
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "ok", "f": "enqueue", "process": 0,
             "time": 1_000_000, "value": 0,
             "error": 'quote " backslash \\ tab \t unicode é'},
            {"index": 1, "type": "ok", "f": "enqueue", "process": -0,
             "time": 2_000_000, "value": -5,
             "extra": {"deep": [{"er": 1.5e-3}, []]}},
        ],
    )
    _assert_identical(p)


def test_value_overflow_falls_back_and_python_raises(tmp_path):
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "ok", "f": "enqueue", "process": 0,
             "time": 1_000_000, "value": 2**33},
        ],
    )
    assert pack_file(p) is None
    with pytest.raises(OverflowError):
        _rows_for(read_history(p))


def test_time_overflow_falls_back(tmp_path):
    # time_ms beyond int32 (year ~2038 in ms since epoch... here: ns
    # value whose //1e6 exceeds int32)
    p = _write(
        tmp_path,
        [
            {"index": 0, "type": "ok", "f": "enqueue", "process": 0,
             "time": (2**31 + 5) * 1_000_000, "value": 1},
        ],
    )
    assert pack_file(p) is None
    with pytest.raises(OverflowError):
        _rows_for(read_history(p))


def test_missing_file_falls_back(tmp_path):
    assert pack_file(tmp_path / "nope.jsonl") is None


def test_edn_suffix_falls_back(tmp_path):
    p = tmp_path / "history.edn"
    p.write_text("[]")
    assert pack_file(p) is None


# ---------------------------------------------------------------------------
# Integration: rows_with_cache uses the native path and cuts the cache
# ---------------------------------------------------------------------------


def test_rows_with_cache_native_miss_then_hit(tmp_path):
    from jepsen_tpu.history.columnar import jtc_path_for
    from jepsen_tpu.history.rows import rows_with_cache

    sh = synth_batch(1, SynthSpec(n_ops=60, seed=3, lost=1))[0]
    p = tmp_path / "history.jsonl"
    write_history_jsonl(p, sh.ops)
    wl, rows, hit = rows_with_cache(p)
    assert not hit and wl == "queue"
    # the miss leaves the unified .jtc columnar substrate behind (the
    # legacy rows.npz is read-only fallback territory now)
    assert jtc_path_for(p).exists()
    np.testing.assert_array_equal(rows, _rows_for(read_history(p)))
    wl2, rows2, hit2 = rows_with_cache(p)
    assert hit2 and wl2 == wl
    np.testing.assert_array_equal(rows2, rows)


def test_random_fuzz_identical(tmp_path):
    """Randomized op soup across every field shape the recorder can
    produce (plus shapes it can't — the packer sees files, not the
    recorder)."""
    import random

    rng = random.Random(1234)
    types = ["invoke", "ok", "fail", "info"]
    fs = ["enqueue", "dequeue", "drain", "start", "stop", "log",
          "append", "read", "txn", "acquire", "release"]
    for trial in range(10):
        dicts = []
        for i in range(rng.randrange(0, 120)):
            d = {"index": i, "type": rng.choice(types),
                 "f": rng.choice(fs)}
            if rng.random() < 0.9:
                d["process"] = rng.randrange(-1, 6)
            if rng.random() < 0.9:
                d["time"] = rng.randrange(-2, 10**9)
            r = rng.random()
            if r < 0.4:
                d["value"] = rng.randrange(-5, 2**31 - 1)
            elif r < 0.6:
                d["value"] = [
                    rng.randrange(0, 1000)
                    for _ in range(rng.randrange(0, 5))
                ]
            elif r < 0.7:
                d["value"] = rng.choice(
                    [None, True, False, "s", 1.25, {"k": 1}, [[1, 2]]]
                )
            if rng.random() < 0.2:
                d["error"] = rng.choice(["timeout", ["nested", 1]])
            dicts.append(d)
        p = _write(tmp_path, dicts, name=f"fuzz{trial}.jsonl")
        _assert_identical(p)


# ---------------------------------------------------------------------------
# Native elle inference (jt_elle_infer_file)
# ---------------------------------------------------------------------------

from jepsen_tpu.checkers.elle import infer_txn_graph  # noqa: E402
from jepsen_tpu.checkers.stream_lin import _stream_rows  # noqa: E402
from jepsen_tpu.history.fastpack import (  # noqa: E402
    elle_graph_file,
    stream_rows_file,
)


def _assert_graph_identical(tmp_path, history, name="history.jsonl"):
    p = tmp_path / name
    write_history_jsonl(p, history)
    g = elle_graph_file(p)
    assert g is not None
    ref = infer_txn_graph(read_history(p))
    assert g.n == ref.n
    assert g.txn_index == ref.txn_index
    assert g.ww == ref.ww
    assert g.wr == ref.wr
    assert g.rw == ref.rw
    assert g.g1a == ref.g1a
    assert g.g1b == ref.g1b
    assert g.incompatible_order == ref.incompatible_order
    return g, ref


class TestElleInferNative:
    """The native inference must reproduce infer_txn_graph's edge and
    anomaly sets exactly on every mappable history, and fall back (None)
    on everything else — never a wrong graph."""

    @pytest.mark.parametrize(
        "spec_kw",
        [
            {},  # clean serializable
            {"g1a": 2},
            {"g1b": 2},
            {"g1c_cycle": 1},
            {"g2_cycle": 1},
            {"g1a": 1, "g1b": 1, "g1c_cycle": 1, "g2_cycle": 1},
            {"p_fail": 0.2, "p_info": 0.15},  # heavy abort/indeterminate
            {"n_keys": 1, "max_micro_ops": 6},
        ],
    )
    def test_differential_per_spec(self, tmp_path, spec_kw):
        for sh in synth_elle_batch(3, ElleSynthSpec(n_txns=40), **spec_kw):
            g, ref = _assert_graph_identical(tmp_path, sh.ops)

    def test_anomalous_graph_is_actually_anomalous(self, tmp_path):
        sh = synth_elle_batch(1, ElleSynthSpec(n_txns=40), g1a=2)[0]
        g, _ = _assert_graph_identical(tmp_path, sh.ops)
        assert g.g1a  # the differential test isn't comparing empties

    def test_full_history_with_nemesis_ops(self, tmp_path):
        """txn_index counts history POSITIONS over all ops, including
        interleaved nemesis/log lines."""
        from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType

        sh = synth_elle_batch(1, ElleSynthSpec(n_txns=20))[0]
        history = []
        for i, op in enumerate(sh.ops):
            if i % 5 == 0:
                history.append(Op(
                    type=OpType.INFO, f=OpF.START,
                    process=NEMESIS_PROCESS, value="partition start",
                ))
            history.append(op)
        _assert_graph_identical(tmp_path, history)

    def test_string_key_falls_back(self, tmp_path):
        p = _write(tmp_path, [
            {"type": "ok", "f": "txn", "process": 0,
             "value": [["append", "k", 1]]},
        ])
        assert elle_graph_file(p) is None  # Python handles string keys

    def test_malformed_json_falls_back(self, tmp_path):
        p = tmp_path / "history.jsonl"
        p.write_text('{"type": "ok", "f": "txn", "value": [[\n')
        assert elle_graph_file(p) is None

    def test_non_list_txn_value_contributes_nothing(self, tmp_path):
        from jepsen_tpu.history.ops import Op, OpF, OpType

        history = [
            Op(type=OpType.OK, f=OpF.TXN, process=0, value=7),
            Op(type=OpType.OK, f=OpF.TXN, process=0,
               value=[["append", 0, 1], ["r", 0, [1]]]),
        ]
        g, ref = _assert_graph_identical(tmp_path, history)
        assert g.n == 2

    def test_own_append_suffix_normalization(self, tmp_path):
        """A txn reading its own staged appends after the committed
        prefix: the suffix strips; a mid-list own value stays."""
        from jepsen_tpu.history.ops import Op, OpF, OpType

        mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
        history = [
            mk([["append", 0, 1]]),
            # reads prefix [1] + own staged [5] -> suffix strips
            mk([["append", 0, 5], ["r", 0, [1, 5]]]),
            # own value mid-list: a genuine misorder, stays visible
            mk([["append", 0, 9], ["r", 0, [9, 1]]]),
        ]
        g, ref = _assert_graph_identical(tmp_path, history)
        assert g.incompatible_order  # the mid-list case flagged

    def test_scalar_micro_op_slots_are_skipped_not_crashed(self, tmp_path):
        """Fuzz find (r5): a txn value like [7, 16, 7] made the Python
        twin raise TypeError from len() while the native side skipped
        the non-list elements; both now skip (the same treatment as
        wrong-arity and unknown-f micro-ops)."""
        from jepsen_tpu.history.ops import Op, OpF, OpType

        mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
        history = [
            mk([7, 16, 7]),
            mk(["stray", ["append", 0, 1], None, ["r", 0, [1]]]),
        ]
        g, ref = _assert_graph_identical(tmp_path, history)
        assert g.n == 2


# ---------------------------------------------------------------------------
# Native elle micro-op cell emission (jt_elle_mops_file) — the packed
# substrate of the DEVICE-side edge inference
# ---------------------------------------------------------------------------

from jepsen_tpu.checkers.elle import elle_mops_for  # noqa: E402
from jepsen_tpu.history.fastpack import elle_mops_file  # noqa: E402


def _assert_mops_identical(tmp_path, history, name="history.jsonl"):
    p = tmp_path / name
    write_history_jsonl(p, history)
    got = elle_mops_file(p)
    assert got is not None
    mat, meta = got
    ref_mat, ref_meta = elle_mops_for(read_history(p))
    np.testing.assert_array_equal(mat, ref_mat)
    assert meta.n_txns == ref_meta.n_txns
    assert meta.txn_index == ref_meta.txn_index
    assert meta.keys == ref_meta.keys
    assert meta.degenerate == ref_meta.degenerate
    return mat, meta


class TestElleMopsNative:
    """The native cell emission must be BIT-identical to elle_mops_for
    on every mappable history (same cell rows, same dense id assignment
    order, same degeneracy flags) — the device inference consumes these
    columns verbatim, so any skew would silently change verdicts."""

    @pytest.mark.parametrize(
        "spec_kw",
        [
            {},
            {"g1a": 2},
            {"g1b": 2},
            {"g0_cycle": 1},
            {"g1c_cycle": 1},
            {"g2_cycle": 1},
            {"p_fail": 0.2, "p_info": 0.15},
            {"n_keys": 1, "max_micro_ops": 6},
        ],
    )
    def test_differential_per_spec(self, tmp_path, spec_kw):
        for sh in synth_elle_batch(3, ElleSynthSpec(n_txns=40), **spec_kw):
            mat, meta = _assert_mops_identical(tmp_path, sh.ops)
            assert mat.shape[0] > 0 and not meta.degenerate

    def test_degenerate_duplicate_append_flagged_identically(self, tmp_path):
        from jepsen_tpu.history.ops import Op, OpF, OpType

        mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
        history = [
            mk([["append", 0, 1]]),
            mk([["append", 0, 1]]),  # same value appended twice
        ]
        _, meta = _assert_mops_identical(tmp_path, history)
        assert meta.degenerate

    def test_degenerate_value_under_two_keys_flagged(self, tmp_path):
        from jepsen_tpu.history.ops import Op, OpF, OpType

        mk = lambda v: Op(type=OpType.OK, f=OpF.TXN, process=0, value=v)
        history = [
            mk([["r", 0, [7]]]),
            mk([["r", 1, [7]]]),  # 7 observed under keys 0 AND 1
        ]
        _, meta = _assert_mops_identical(tmp_path, history)
        assert meta.degenerate

    def test_failed_append_key_not_interned(self, tmp_path):
        """infer_txn_graph never hashes a failed append's key, so the
        key-id table must not contain it either (canonical id order)."""
        from jepsen_tpu.history.ops import Op, OpF, OpType

        history = [
            Op(type=OpType.FAIL, f=OpF.TXN, process=0,
               value=[["append", 99, 5]], error="aborted"),
            Op(type=OpType.OK, f=OpF.TXN, process=0,
               value=[["append", 3, 6], ["r", 3, [6]]]),
        ]
        mat, meta = _assert_mops_identical(tmp_path, history)
        assert meta.keys == [3]

    def test_string_key_falls_back(self, tmp_path):
        p = _write(tmp_path, [
            {"type": "ok", "f": "txn", "process": 0,
             "value": [["append", "k", 1]]},
        ])
        assert elle_mops_file(p) is None  # Python handles string keys

    def test_malformed_json_falls_back(self, tmp_path):
        p = tmp_path / "history.jsonl"
        p.write_text('{"type": "ok", "f": "txn", "value": [[\n')
        assert elle_mops_file(p) is None

    def test_oom_faults_err_not_segfault(self, tmp_path, monkeypatch):
        sh = synth_elle_batch(1, ElleSynthSpec(n_txns=10))[0]
        p = tmp_path / "history.jsonl"
        write_history_jsonl(p, sh.ops)
        monkeypatch.setenv("JT_PACK_FAKE_OOM", "1")
        assert elle_mops_file(p) is None


# ---------------------------------------------------------------------------
# Native stream explosion (jt_stream_rows_file)
# ---------------------------------------------------------------------------


def _assert_stream_identical(tmp_path, history, name="history.jsonl"):
    p = tmp_path / name
    write_history_jsonl(p, history)
    got = stream_rows_file(p)
    assert got is not None
    cols, full = got
    ref_cols, ref_full = _stream_rows(read_history(p))
    np.testing.assert_array_equal(cols, ref_cols)
    assert full == ref_full
    return cols, full


class TestStreamRowsNative:
    @pytest.mark.parametrize(
        "spec_kw",
        [
            {},
            {"lost": 1, "duplicated": 1},
            {"divergent": 1, "phantom": 1},
            {"reorder": 1, "nonmonotonic": 1},
            {"full_reads": False},
            {"p_app_info": 0.2, "p_app_fail": 0.2},
        ],
    )
    def test_differential_per_spec(self, tmp_path, spec_kw):
        for sh in synth_stream_batch(
            3, StreamSynthSpec(n_ops=60), **spec_kw
        ):
            _assert_stream_identical(tmp_path, sh.ops)

    def test_empty_history_sentinel_row(self, tmp_path):
        cols, full = _assert_stream_identical(tmp_path, [])
        assert cols.shape == (1, 6) and not full

    def test_non_stream_ops_are_skipped_but_counted_in_pos(self, tmp_path):
        from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType

        sh = synth_stream_batch(1, StreamSynthSpec(n_ops=40))[0]
        history = []
        for i, op in enumerate(sh.ops):
            if i % 7 == 0:
                history.append(Op(
                    type=OpType.INFO, f=OpF.STOP,
                    process=NEMESIS_PROCESS, value="heal",
                ))
            history.append(op)
        _assert_stream_identical(tmp_path, history)

    def test_value_overflow_falls_back(self, tmp_path):
        p = _write(tmp_path, [
            {"type": "ok", "f": "append", "process": 0,
             "value": 2**40},
        ])
        assert stream_rows_file(p) is None  # np.int32 would raise

    def test_weird_read_values(self, tmp_path):
        """Null, scalar, pair, list-of-pairs, lists with non-pair noise."""
        from jepsen_tpu.history.ops import Op, OpF, OpType

        inv = lambda pr, v=None: Op(
            type=OpType.INVOKE, f=OpF.READ, process=pr, value=v
        )
        ok = lambda pr, v: Op(type=OpType.OK, f=OpF.READ, process=pr, value=v)
        history = [
            inv(0), ok(0, None),
            inv(0), ok(0, [3, 7]),                 # single pair
            inv(1), ok(1, [[0, 5], [1, 6]]),       # list of pairs
            inv(1), ok(1, [[0, 5], "noise", [2]]),  # noise skipped
            inv(2), ok(2, 42),                     # scalar -> no pairs
            inv(2, "full"), ok(2, [[0, 5]]),       # full read
            inv(0, "full"), Op(type=OpType.FAIL, f=OpF.READ, process=0),
        ]
        cols, full = _assert_stream_identical(tmp_path, history)
        assert full  # process 2's full read completed ok

    def test_failed_full_read_does_not_count(self, tmp_path):
        from jepsen_tpu.history.ops import Op, OpF, OpType

        history = [
            Op(type=OpType.INVOKE, f=OpF.READ, process=0, value="full"),
            Op(type=OpType.FAIL, f=OpF.READ, process=0),
        ]
        cols, full = _assert_stream_identical(tmp_path, history)
        assert not full


# ---------------------------------------------------------------------------
# Allocation-failure path (advisor r5): a malloc failure in the native
# result-copy must set err (None-fallback in the binding), never hand the
# binding a NULL pointer with positive counts (segfault)
# ---------------------------------------------------------------------------


class TestFakeOom:
    @pytest.fixture(autouse=True)
    def _oom(self, monkeypatch):
        monkeypatch.setenv("JT_PACK_FAKE_OOM", "1")

    def test_pack_file_falls_back(self, tmp_path):
        from jepsen_tpu.history.synth import SynthSpec, synth_history

        sh = synth_history(SynthSpec(n_ops=40, seed=3))
        p = _write(tmp_path, [op.to_json() for op in sh.ops])
        assert pack_file(p) is None  # err surfaced -> Python fallback

    def test_elle_graph_file_falls_back(self, tmp_path):
        from jepsen_tpu.history.fastpack import elle_graph_file
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        (sh,) = synth_elle_batch(1, ElleSynthSpec(n_txns=16))
        p = _write(tmp_path, [op.to_json() for op in sh.ops])
        assert elle_graph_file(p) is None

    def test_stream_rows_file_falls_back(self, tmp_path):
        from jepsen_tpu.history.fastpack import stream_rows_file
        from jepsen_tpu.history.synth import (
            StreamSynthSpec,
            synth_stream_batch,
        )

        (sh,) = synth_stream_batch(1, StreamSynthSpec(n_ops=40))
        p = _write(tmp_path, [op.to_json() for op in sh.ops])
        assert stream_rows_file(p) is None

    def test_off_by_default(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JT_PACK_FAKE_OOM", "0")
        from jepsen_tpu.history.synth import SynthSpec, synth_history

        sh = synth_history(SynthSpec(n_ops=40, seed=3))
        p = _write(tmp_path, [op.to_json() for op in sh.ops])
        assert pack_file(p) is not None  # '0' does not trip the hook
