"""Clock-skew nemesis (``jepsen.nemesis.time``'s role).

A correct quorum SUT tolerates wall-clock skew BY CONSTRUCTION: Raft
election/heartbeat timers run on monotonic clocks, and TTL timestamps
travel inside the replicated log, so skew moves *when* a message
expires, never *whether* the drain can account for it.  These tests pin
the mechanism at each layer and then prove the survivability claim
end-to-end (dead-letter + skew + partitions on a live cluster).
"""

import time

from _load import scaled

import pytest

from jepsen_tpu.harness.replication import ReplicatedBackend


def _backend():
    return ReplicatedBackend(
        "a",
        {"a": ("127.0.0.1", 0)},
        election_timeout=(0.05, 0.1),
        heartbeat_s=0.02,
    )


def _wait_leader(b, timeout_s=5.0):
    deadline = time.monotonic() + scaled(timeout_s)
    while time.monotonic() < deadline:
        if b.raft.is_leader():
            return
        time.sleep(0.01)
    raise AssertionError("no leader")


def test_skew_shifts_ttl_expiry():
    """A forward-bumped clock makes this node stamp older-looking
    timestamps nowhere — it stamps *newer* ones; the DEQ path's skewed
    "now" is what expires heads early.  Either way the message lands in
    the dead-letter queue, never nowhere."""
    b = _backend()
    try:
        _wait_leader(b)
        b.declare("dlq")
        b.declare("q", ttl_ms=60_000, dlx="dlq")
        assert b.enqueue("q", b"x", b"") is True
        assert b.counts()["q"] == 1  # minutes from expiring
        b.clock_offset_ms = 120_000.0  # jump 2 minutes forward
        assert b.counts().get("q", 0) == 0  # head expired...
        assert b.counts()["dlq"] == 1  # ...INTO the dead-letter queue
        assert b.dequeue("q", "a|c1") is None  # deq performs the expiry
        m = b.dequeue("dlq", "a|c1")
        assert m is not None and m.body == b"x"  # nothing vanished
    finally:
        b.stop()


def test_transport_maps_date_command_to_clock_set(tmp_path):
    """The exact command string ``TransportClocks`` emits lands as an
    admin CLOCK_SET on the node's broker process."""
    from jepsen_tpu.control.net import TransportClocks
    from jepsen_tpu.harness.localcluster import LocalProcTransport

    t = LocalProcTransport(n_nodes=1, replicated=True)
    try:
        node = t.nodes[0]
        t.run(node, "/tmp/rabbitmq-server/sbin/rabbitmq-server -detached")
        clocks = TransportClocks(t, t.nodes)
        # the applied offset is (controller_now_at_send + 2.5s) minus
        # broker_now_at_receipt, so transit shrinks it — bound by the
        # MEASURED elapsed time, not a guess (a loaded 1-core host can
        # stall seconds between those two reads; full-suite flake, r4)
        t0 = time.time()
        clocks.bump(node, 2.5)
        off = float(t._admin(node, "CLOCK_GET").out)
        elapsed_ms = (time.time() - t0) * 1000.0
        assert 2500 - elapsed_ms - 250 <= off <= 2600, (off, elapsed_ms)
        t0 = time.time()
        clocks.reset(node)
        off = float(t._admin(node, "CLOCK_GET").out)
        elapsed_ms = (time.time() - t0) * 1000.0
        assert -elapsed_ms - 250 <= off <= 100, (off, elapsed_ms)
        # a dead node: clock command succeeds vacuously (a VM's clock is
        # settable whether or not the broker process is up)
        t.run(node, "killall -q -9 beam.smp epmd || true")
        r = t.run(node, "sudo date -u -s @12345.0")
        assert r.rc == 0
    finally:
        t.close()


def test_clock_skew_nemesis_bumps_and_resets():
    from jepsen_tpu.control.nemesis import ClockSkewNemesis
    from jepsen_tpu.history.ops import Op, OpF

    class Log:
        def __init__(self):
            self.calls = []

        def bump(self, node, delta_s):
            self.calls.append(("bump", node, delta_s))

        def reset(self, node):
            self.calls.append(("reset", node))

    clocks = Log()
    nodes = ["n1", "n2", "n3"]
    nem = ClockSkewNemesis(clocks, nodes, seed=5)
    start = Op.invoke(OpF.START, -1)
    stop = Op.invoke(OpF.STOP, -1)
    r = nem.invoke({}, start)
    assert r.value.startswith("clock-bump ")
    kind, victim, delta = clocks.calls[0]
    assert kind == "bump" and victim in nodes
    assert 0.1 <= abs(delta) <= 3.0
    nem.invoke({}, stop)
    assert clocks.calls[-1] == ("reset", victim)
    # teardown resets a skew left behind by an aborted run
    nem.invoke({}, start)
    nem.teardown({})
    assert clocks.calls[-1][0] == "reset" and not nem.skewed


def test_clock_skew_refused_without_a_clocks_surface():
    """The sim models no wall clocks; a silently-noop clock nemesis
    would be a false green."""
    from jepsen_tpu.control.nemesis import make_nemesis

    with pytest.raises(ValueError, match="clocks"):
        make_nemesis({"nemesis": "clock-skew"}, None, None, ["n1"])


def test_clock_skew_refused_on_non_replicated_local_cluster():
    """Review r4 find: a NON-replicated local cluster times TTL
    monotonically, so a clock bump cannot reach it — the transport must
    refuse (rc=1) rather than silently succeed, and the suite assembly
    must not hand such a transport a clocks surface at all."""
    from jepsen_tpu.harness.localcluster import (
        LocalProcTransport,
        build_local_test,
    )
    from jepsen_tpu.suite import DEFAULT_OPTS

    t = LocalProcTransport(n_nodes=1)  # single node: non-replicated
    try:
        r = t.run(t.nodes[0], "sudo date -u -s @12345.0")
        assert r.rc == 1 and "replicated" in r.err
    finally:
        t.close()
    with pytest.raises(ValueError, match="clocks"):
        test, t2 = build_local_test(
            {**DEFAULT_OPTS, "nemesis": "clock-skew"}, n_nodes=1,
        )


def test_mixed_gains_clock_member_with_surface():
    from jepsen_tpu.control.nemesis import MixedNemesis, make_nemesis
    from jepsen_tpu.control.net import SimProcs

    class NoopClocks:
        def bump(self, node, delta_s):
            pass

        def reset(self, node):
            pass

    nem = make_nemesis(
        {"nemesis": "mixed", "network-partition": "partition-halves"},
        None, SimProcs(None), ["n1", "n2"], seed=1, clocks=NoopClocks(),
    )
    assert isinstance(nem, MixedNemesis)
    assert "clock-skew" in nem.members


# native_lib / _reset fixtures come from conftest.py


def test_skew_survivable_end_to_end_with_dead_letter(_reset):
    """The survivability claim, live: dead-letter mode (1s TTL — the
    skew-sensitive config) + clock-skew nemesis on a replicated 3-node
    cluster.  Skewed clocks move expiry times around; the checker must
    still account for every acknowledged message (drain reads the DLQ
    too) — valid verdict, nothing lost."""
    import tempfile

    from _live import run_live_with_triage
    from jepsen_tpu.harness.localcluster import build_local_test
    from jepsen_tpu.suite import DEFAULT_OPTS

    opts = {
        **DEFAULT_OPTS,
        "rate": 120.0,
        "time-limit": 5.0,
        "time-before-partition": 0.6,
        "partition-duration": 1.0,
        "recovery-sleep": 1.5,
        "publish-confirm-timeout": 2.5,
        "nemesis": "clock-skew",
        "dead-letter": True,
        "seed": 3,
    }

    def build():
        return build_local_test(
            opts, n_nodes=3, concurrency=4, checker_backend="cpu",
            store_root=tempfile.mkdtemp(), workload="queue",
        )

    def checks(run):
        assert run.results["queue"]["lost-count"] == 0
        bumps = [
            op for op in run.history
            if op.value is not None and "clock-bump" in str(op.value)
        ]
        assert bumps, "clock nemesis never fired"

    run_live_with_triage(build, expect="valid", checks=checks)


def test_transport_clocks_raise_on_failed_clock_set():
    """A failing `sudo date` (no sudo, protected clock) must never
    silently no-op: the run would claim 'tolerates clock skew' with no
    skew ever applied (advisor r4 — the false-green-by-absent-fault
    class).  TransportClocks raises on nonzero rc for bump AND reset."""
    from jepsen_tpu.control.net import TransportClocks
    from jepsen_tpu.control.ssh import RunResult

    class NoSudoTransport:
        def run(self, node, cmd, timeout=None):
            return RunResult(1, "", "sudo: a password is required")

    clocks = TransportClocks(NoSudoTransport(), ["n1"])
    with pytest.raises(RuntimeError, match="no actual skew"):
        clocks.bump("n1", 2.0)
    with pytest.raises(RuntimeError, match="no actual skew"):
        clocks.reset("n1")
