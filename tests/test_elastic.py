"""Elastic checker resilience (PR 13): degraded-but-honest verdicts.

The quarantine contract, pinned end to end: one poison history in a
64-history batch yields EXACTLY ONE ``unknown``-with-evidence entry and
63 verdicts identical to the serial oracle; the composed verdict is
downgraded from valid (a quarantine can never fold into ``valid``) and
an ``invalid`` elsewhere in the batch still trumps it (the PR-8
precedence rule).  Plus the distributed layer's wedge path: a
SIGSTOP-shaped worker trips the per-stripe deadline, gets killed by the
launcher, and its stripes complete on the survivors with accurate
``degraded`` provenance.
"""

from __future__ import annotations

import json
import os

import pytest

from jepsen_tpu.checkers.protocol import UNKNOWN, merge_valid
from jepsen_tpu.history.store import _json_default, write_history_jsonl
from jepsen_tpu.history.synth import (
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_stream_batch,
)
from jepsen_tpu.parallel.pipeline import (
    check_sources,
    reduced_valid,
)

POISON = '{"type": "not a real op"\n'  # torn JSON line


def _write(tmp_path, base, tag="h"):
    files = []
    for i, sh in enumerate(base):
        p = tmp_path / f"{tag}{i:03d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(p)
    return files


def _norm(x):
    return json.loads(json.dumps(x, default=_json_default))


class TestPoisonHistoryQuarantine:
    def test_one_poison_in_64_batch_yields_one_unknown(self, tmp_path):
        """63 green histories + 1 poison, ONE 64-history chunk: exactly
        one quarantined ``unknown`` with the exception as evidence, 63
        correct verdicts, and the composed verdict downgraded from
        valid to unknown."""
        base = synth_stream_batch(63, StreamSynthSpec(n_ops=15, seed=11))
        files = _write(tmp_path, base)
        bad = tmp_path / "poison.jsonl"
        bad.write_text(POISON)
        mix = files[:31] + [bad] + files[31:]
        res, stats = check_sources("stream", mix, chunk=64)
        assert len(res) == 64
        quarantined = [
            i for i, r in enumerate(res) if "quarantined" in r["stream"]
        ]
        assert quarantined == [31], quarantined
        row = res[31]["stream"]
        assert row["valid?"] == UNKNOWN
        assert row["quarantined"]["errors"], "evidence must be captured"
        assert "quarantined" in row["error"]
        serial, _ = check_sources("stream", files, chunk=64, serial=True)
        assert [r for i, r in enumerate(res) if i != 31] == serial
        assert all(r["stream"]["valid?"] is True for r in serial)
        assert stats.quarantined == 1
        # downgraded from valid: 63 greens + 1 quarantine == unknown
        assert merge_valid(r["stream"]["valid?"] for r in res) == UNKNOWN

    def test_invalid_elsewhere_still_trumps_quarantine(self, tmp_path):
        """The precedence rule: a real violation in the batch surfaces
        as ``invalid`` even with a quarantine present."""
        base = synth_stream_batch(
            15, StreamSynthSpec(n_ops=20, seed=12), lost=1
        )
        files = _write(tmp_path, base)
        bad = tmp_path / "poison.jsonl"
        bad.write_text(POISON)
        res, _stats = check_sources("stream", files + [bad], chunk=8)
        vals = [r["stream"]["valid?"] for r in res]
        assert UNKNOWN in vals and False in vals
        assert merge_valid(vals) is False

    def test_queue_family_poison_quarantines_both_subverdicts(
        self, tmp_path
    ):
        """The queue workload surfaces as two sub-checkers; a
        quarantined history must report unknown on BOTH (a half-judged
        history would read as a tighter verdict than was computed)."""
        base = synth_batch(7, SynthSpec(n_ops=30, seed=13), lost=1)
        files = _write(tmp_path, base)
        bad = tmp_path / "poison.jsonl"
        bad.write_text(POISON)
        res, _ = check_sources("queue", files + [bad], chunk=4)
        row = res[-1]
        assert row["queue"]["valid?"] == UNKNOWN
        assert row["linear"]["valid?"] == UNKNOWN
        assert row["queue"]["quarantined"]["errors"]
        serial, _ = check_sources("queue", files, chunk=4, serial=True)
        assert res[:-1] == serial

    def test_reduce_mode_counts_quarantines(self, cpu_devices, tmp_path):
        """Reduce mode: the quarantined member is COUNTED in the
        on-device-reduced verdict dict and caps :func:`reduced_valid`
        at unknown; a seeded invalid still wins."""
        from jepsen_tpu.parallel.mesh import checker_mesh

        base = synth_stream_batch(7, StreamSynthSpec(n_ops=20, seed=14))
        files = _write(tmp_path, base)
        bad = tmp_path / "poison.jsonl"
        bad.write_text(POISON)
        merged, stats = check_sources(
            "stream", files + [bad], chunk=4, mesh=checker_mesh(),
            lanes=0, reduce=True,
        )
        assert merged["histories"] == 8
        assert merged["quarantined"] == 1
        assert merged["invalid"] == 0
        assert reduced_valid(merged) == UNKNOWN
        assert stats.quarantined == 1
        # invalid trumps: seed a lost write into a second corpus
        base2 = synth_stream_batch(
            6, StreamSynthSpec(n_ops=20, seed=15), lost=1
        )
        files2 = _write(tmp_path, base2, tag="g")
        merged2, _ = check_sources(
            "stream", files2 + [bad], chunk=4, mesh=checker_mesh(),
            lanes=0, reduce=True,
        )
        assert merged2["invalid"] >= 1 and merged2["quarantined"] == 1
        assert reduced_valid(merged2) is False


class TestElasticDistributedWedge:
    def test_wedged_worker_killed_by_stripe_deadline(self, tmp_path):
        """The SIGSTOP shape: worker 1 wedges after claiming its
        stripe.  The launcher's per-stripe deadline SIGKILLs it, the
        stripe requeues onto a survivor, the run completes with
        verdicts ≡ serial oracle, and the provenance records the wedge
        kill + the death + the requeue."""
        from jepsen_tpu.parallel.distributed import run_multiprocess_check

        base = synth_stream_batch(
            6, StreamSynthSpec(n_ops=20, seed=16), lost=1
        )
        files = _write(tmp_path, base)
        os.environ["JEPSEN_TPU_DIST_WEDGE_PID"] = "1"
        try:
            results, info = run_multiprocess_check(
                "stream", files, 2, chunk=3, timeout_s=300,
                stripe_timeout_s=6.0,
            )
        finally:
            del os.environ["JEPSEN_TPU_DIST_WEDGE_PID"]
        deg = info["degraded"]
        assert 1 in deg["wedged_killed"]
        assert any(d["pid"] == 1 for d in deg["dead_workers"])
        assert any(
            r["from_pid"] == 1 for r in deg["requeued_stripes"]
        )
        serial, _ = check_sources("stream", files, chunk=3, serial=True)
        assert _norm(results) == _norm(serial)


class TestReducedValid:
    def test_precedence(self):
        assert reduced_valid({"invalid": 0, "quarantined": 0}) is True
        assert reduced_valid({"invalid": 0, "quarantined": 3}) == UNKNOWN
        assert reduced_valid({"invalid": 1, "quarantined": 3}) is False
