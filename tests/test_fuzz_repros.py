"""Pinned red/green pairs for every committed fuzz repro driver.

The matrix fuzzer (``tools/fuzz_matrix.py``, FUZZING.md) emits each
minimized finding as ``store/fuzz_repro_*.py`` with an embedded JSON
spec.  This module is the pinning side of that contract:

- **red**: the spec reproduces its violation (the minimal window still
  fails) — if a fix lands and this direction goes green, move the
  driver to the fixed section of PARITY.md and flip its expectation,
  the ``tools/repro_r7_*`` lifecycle;
- **green twin**: the same schedule with the cause stripped (seeded
  bug removed, contract relaxed to the SUT's claim) stays green — the
  red is the bug's, not the harness's.

Specs are parsed out of the drivers without executing them (the
drivers are also standalone entry points; here only their SPEC
literal is consumed)."""

from __future__ import annotations

import os
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
STORE = REPO / "store"

REPROS = sorted(STORE.glob("fuzz_repro_*.py"))


def _spec(path):
    from jepsen_tpu.fuzz.emit import load_spec, validate_spec

    spec = load_spec(str(path))
    validate_spec(spec)  # schema-gate every committed driver
    return spec


def _ids(paths):
    return [p.stem.replace("fuzz_repro_", "") for p in paths]


@pytest.mark.skipif(not REPROS, reason="no committed fuzz repros yet")
@pytest.mark.parametrize("path", REPROS, ids=_ids(REPROS))
def test_committed_repro_schema_round_trips(path):
    from jepsen_tpu.fuzz.space import FuzzConfig

    cfg = FuzzConfig.from_spec(_spec(path))
    assert float(cfg.opts["time-limit"]) > 0.0
    assert cfg.opts["nemesis-schedule"] == [
        [e.at_s, e.dur_s] for e in cfg.events
    ]


@pytest.mark.parametrize("path", REPROS, ids=_ids(REPROS))
def test_pinned_red_reproduces(path, tmp_path):
    """The minimal failing window still fails.

    Bounded retry-with-reseed (the round-4 load-flake class): triage
    finalizes on the first green, and under full-suite scheduler
    pressure a minimal window can land a legal schedule in which the
    bug simply was not exercised — so the PIN retries the whole window
    on a fresh store.  A genuinely fixed bug greens every attempt and
    still fails loud."""
    from jepsen_tpu.fuzz.repro import run_spec

    for attempt in range(3):
        out = run_spec(
            _spec(path),
            store_root=str(tmp_path / f"s{attempt}"),
            attempts=2,
        )
        if out.status == "red":
            return
    assert out.status == "red", (
        f"{path.name}: expected the pinned red to reproduce, got "
        f"{out.status} ({out.notes}) — if the underlying bug was "
        f"FIXED, move this driver to PARITY.md's fixed section and "
        f"flip this pin"
    )


@pytest.mark.parametrize("path", REPROS, ids=_ids(REPROS))
def test_pinned_green_twin_stays_green(path, tmp_path):
    """Same schedule, cause stripped: the correct config is green."""
    from jepsen_tpu.fuzz.repro import green_twin_spec, run_spec

    spec = _spec(path)
    twin = green_twin_spec(spec)
    assert twin["seed_bug"] is None and twin["sim_faults"] == {}
    out = run_spec(
        twin, store_root=str(tmp_path / "s"), attempts=3
    )
    assert out.status == "green", (
        f"{path.name}: the green twin went {out.status} "
        f"({out.notes}, {out.invalidating}) — the minimal window reds "
        f"WITHOUT its seeded cause, i.e. a real (or harness) bug"
    )
