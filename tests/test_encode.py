"""History substrate tests: op schema, packing, store round-trips."""

import numpy as np

from jepsen_tpu.history import NO_VALUE, Op, OpF, OpType, pack_histories
from jepsen_tpu.history.ops import reindex
from jepsen_tpu.history.store import Store, read_history_jsonl, write_history_jsonl
from jepsen_tpu.history.synth import SynthSpec, synth_history


def _small_history():
    t = 1_000_000  # 1 ms in ns
    ops = [
        Op.invoke(OpF.ENQUEUE, 0, 0, time=1 * t),
        Op(OpType.OK, OpF.ENQUEUE, 0, 0, time=3 * t),
        Op.invoke(OpF.DEQUEUE, 1, time=2 * t),
        Op(OpType.OK, OpF.DEQUEUE, 1, 0, time=6 * t),
        Op.invoke(OpF.DRAIN, 0, time=10 * t),
        Op(OpType.OK, OpF.DRAIN, 0, [1, 2, 3], time=14 * t),
    ]
    return reindex(ops)


def test_pack_shapes_and_mask():
    h = _small_history()
    p = pack_histories([h])
    assert p.batch == 1
    assert p.length % 128 == 0
    # drain [1,2,3] explodes into 3 rows: 6 ops -> 8 rows
    assert int(np.asarray(p.mask).sum()) == 8
    assert p.value_space % 128 == 0 and p.value_space >= 4


def test_drain_explosion_values():
    h = _small_history()
    p = pack_histories([h])
    f = np.asarray(p.f)[0]
    v = np.asarray(p.value)[0]
    ty = np.asarray(p.type)[0]
    drain_rows = (f == int(OpF.DRAIN)) & (ty == int(OpType.OK))
    assert sorted(v[drain_rows].tolist()) == [1, 2, 3]


def test_latency_computed_on_completions():
    h = _small_history()
    p = pack_histories([h])
    lat = np.asarray(p.latency_ms)[0]
    ty = np.asarray(p.type)[0]
    f = np.asarray(p.f)[0]
    enq_ok = (ty == int(OpType.OK)) & (f == int(OpF.ENQUEUE))
    deq_ok = (ty == int(OpType.OK)) & (f == int(OpF.DEQUEUE))
    assert lat[enq_ok].tolist() == [2]  # 3ms - 1ms
    assert lat[deq_ok].tolist() == [4]  # 6ms - 2ms
    assert (lat[ty == int(OpType.INVOKE)] == -1).all()


def test_pack_batch_padding():
    h1 = _small_history()
    h2 = _small_history()[:2]
    p = pack_histories([h1, h2], length=256)
    assert p.type.shape == (2, 256)
    m = np.asarray(p.mask)
    assert m[0].sum() == 8 and m[1].sum() == 2


def test_empty_drain_row_is_masked_no_value():
    ops = reindex(
        [
            Op.invoke(OpF.DRAIN, 0, time=0),
            Op(OpType.OK, OpF.DRAIN, 0, [], time=1),
        ]
    )
    p = pack_histories([ops])
    v = np.asarray(p.value)[0]
    m = np.asarray(p.mask)[0]
    assert m.sum() == 2
    f = np.asarray(p.f)[0]
    ty = np.asarray(p.type)[0]
    row = m & (f == int(OpF.DRAIN)) & (ty == int(OpType.OK))
    assert v[row].tolist() == [NO_VALUE]


def test_jsonl_roundtrip(tmp_path):
    h = synth_history(SynthSpec(n_ops=50, seed=3)).ops
    path = tmp_path / "history.jsonl"
    write_history_jsonl(path, h)
    h2 = read_history_jsonl(path)
    assert len(h2) == len(h)
    for a, b in zip(h, h2):
        assert (a.type, a.f, a.process, a.value, a.time, a.index) == (
            b.type,
            b.f,
            b.process,
            b.value,
            b.time,
            b.index,
        )


def test_store_layout_and_symlinks(tmp_path):
    st = Store(tmp_path / "store")
    d = st.run_dir("rabbitmq-simple-partition", "20260729T000000")
    h = synth_history(SynthSpec(n_ops=20, seed=1)).ops
    st.save_history(d, h)
    st.save_results(d, {"valid?": True, "lost": set()})
    assert (tmp_path / "store" / "latest").resolve() == d.resolve()
    assert (tmp_path / "store" / "current").resolve() == d.resolve()
    assert st.load_history(st.latest())[0].index == 0
    # a new run dir does NOT repoint latest until a history is recorded —
    # a run that crashes before recording must not steal the symlinks
    d2 = st.run_dir("rabbitmq-simple-partition", "20260729T000001")
    assert (tmp_path / "store" / "latest").resolve() == d.resolve()
    st.save_history(d2, h)
    assert (tmp_path / "store" / "latest").resolve() == d2.resolve()
    assert (tmp_path / "store" / "current").resolve() == d2.resolve()


def test_value_overflow_raises():
    import pytest

    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 500, time=0),
            Op(OpType.OK, OpF.ENQUEUE, 0, 500, time=1),
        ]
    )
    with pytest.raises(ValueError, match="value_space"):
        pack_histories([ops], value_space=128)
    # automatic sizing covers the value
    assert pack_histories([ops]).value_space >= 501


def test_unindexed_history_not_masked_out():
    # ops recorded without reindex() (index = -1) must still be checked
    from jepsen_tpu.checkers.total_queue import (
        check_total_queue_batch,
        check_total_queue_cpu,
    )

    ops = [
        Op.invoke(OpF.DEQUEUE, 0, time=0),
        Op(OpType.OK, OpF.DEQUEUE, 0, 7, time=1),  # unexpected read
    ]
    cpu = check_total_queue_cpu(ops)
    tpu = check_total_queue_batch([ops])[0]
    assert cpu == tpu
    assert not tpu["valid?"] and tpu["unexpected"] == {7}


def test_empty_batch_raises():
    import pytest

    with pytest.raises(ValueError, match="empty batch"):
        pack_histories([])
