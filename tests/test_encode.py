"""History substrate tests: op schema, packing, store round-trips."""

import numpy as np

from jepsen_tpu.history import NO_VALUE, Op, OpF, OpType, pack_histories
from jepsen_tpu.history.ops import reindex
from jepsen_tpu.history.store import Store, read_history_jsonl, write_history_jsonl
from jepsen_tpu.history.synth import SynthSpec, synth_history


def _small_history():
    t = 1_000_000  # 1 ms in ns
    ops = [
        Op.invoke(OpF.ENQUEUE, 0, 0, time=1 * t),
        Op(OpType.OK, OpF.ENQUEUE, 0, 0, time=3 * t),
        Op.invoke(OpF.DEQUEUE, 1, time=2 * t),
        Op(OpType.OK, OpF.DEQUEUE, 1, 0, time=6 * t),
        Op.invoke(OpF.DRAIN, 0, time=10 * t),
        Op(OpType.OK, OpF.DRAIN, 0, [1, 2, 3], time=14 * t),
    ]
    return reindex(ops)


def test_pack_shapes_and_mask():
    h = _small_history()
    p = pack_histories([h])
    assert p.batch == 1
    assert p.length % 128 == 0
    # drain [1,2,3] explodes into 3 rows: 6 ops -> 8 rows
    assert int(np.asarray(p.mask).sum()) == 8
    assert p.value_space % 128 == 0 and p.value_space >= 4


def test_drain_explosion_values():
    h = _small_history()
    p = pack_histories([h])
    f = np.asarray(p.f)[0]
    v = np.asarray(p.value)[0]
    ty = np.asarray(p.type)[0]
    drain_rows = (f == int(OpF.DRAIN)) & (ty == int(OpType.OK))
    assert sorted(v[drain_rows].tolist()) == [1, 2, 3]


def test_latency_computed_on_completions():
    h = _small_history()
    p = pack_histories([h])
    lat = np.asarray(p.latency_ms)[0]
    ty = np.asarray(p.type)[0]
    f = np.asarray(p.f)[0]
    enq_ok = (ty == int(OpType.OK)) & (f == int(OpF.ENQUEUE))
    deq_ok = (ty == int(OpType.OK)) & (f == int(OpF.DEQUEUE))
    assert lat[enq_ok].tolist() == [2]  # 3ms - 1ms
    assert lat[deq_ok].tolist() == [4]  # 6ms - 2ms
    assert (lat[ty == int(OpType.INVOKE)] == -1).all()


def test_pack_batch_padding():
    h1 = _small_history()
    h2 = _small_history()[:2]
    p = pack_histories([h1, h2], length=256)
    assert p.type.shape == (2, 256)
    m = np.asarray(p.mask)
    assert m[0].sum() == 8 and m[1].sum() == 2


def test_empty_drain_row_is_masked_no_value():
    ops = reindex(
        [
            Op.invoke(OpF.DRAIN, 0, time=0),
            Op(OpType.OK, OpF.DRAIN, 0, [], time=1),
        ]
    )
    p = pack_histories([ops])
    v = np.asarray(p.value)[0]
    m = np.asarray(p.mask)[0]
    assert m.sum() == 2
    f = np.asarray(p.f)[0]
    ty = np.asarray(p.type)[0]
    row = m & (f == int(OpF.DRAIN)) & (ty == int(OpType.OK))
    assert v[row].tolist() == [NO_VALUE]


def test_jsonl_roundtrip(tmp_path):
    h = synth_history(SynthSpec(n_ops=50, seed=3)).ops
    path = tmp_path / "history.jsonl"
    write_history_jsonl(path, h)
    h2 = read_history_jsonl(path)
    assert len(h2) == len(h)
    for a, b in zip(h, h2):
        assert (a.type, a.f, a.process, a.value, a.time, a.index) == (
            b.type,
            b.f,
            b.process,
            b.value,
            b.time,
            b.index,
        )


def test_store_layout_and_symlinks(tmp_path):
    st = Store(tmp_path / "store")
    d = st.run_dir("rabbitmq-simple-partition", "20260729T000000")
    h = synth_history(SynthSpec(n_ops=20, seed=1)).ops
    st.save_history(d, h)
    st.save_results(d, {"valid?": True, "lost": set()})
    assert (tmp_path / "store" / "latest").resolve() == d.resolve()
    assert (tmp_path / "store" / "current").resolve() == d.resolve()
    assert st.load_history(st.latest())[0].index == 0
    # a new run dir does NOT repoint latest until a history is recorded —
    # a run that crashes before recording must not steal the symlinks
    d2 = st.run_dir("rabbitmq-simple-partition", "20260729T000001")
    assert (tmp_path / "store" / "latest").resolve() == d.resolve()
    st.save_history(d2, h)
    assert (tmp_path / "store" / "latest").resolve() == d2.resolve()
    assert (tmp_path / "store" / "current").resolve() == d2.resolve()


def test_value_overflow_raises():
    import pytest

    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 500, time=0),
            Op(OpType.OK, OpF.ENQUEUE, 0, 500, time=1),
        ]
    )
    with pytest.raises(ValueError, match="value_space"):
        pack_histories([ops], value_space=128)
    # automatic sizing covers the value
    assert pack_histories([ops]).value_space >= 501


def test_unindexed_history_not_masked_out():
    # ops recorded without reindex() (index = -1) must still be checked
    from jepsen_tpu.checkers.total_queue import (
        check_total_queue_batch,
        check_total_queue_cpu,
    )

    ops = [
        Op.invoke(OpF.DEQUEUE, 0, time=0),
        Op(OpType.OK, OpF.DEQUEUE, 0, 7, time=1),  # unexpected read
    ]
    cpu = check_total_queue_cpu(ops)
    tpu = check_total_queue_batch([ops])[0]
    assert cpu == tpu
    assert not tpu["valid?"] and tpu["unexpected"] == {7}


def test_empty_batch_raises():
    import pytest

    with pytest.raises(ValueError, match="empty batch"):
        pack_histories([])


def test_rows_for_matches_reference_loop():
    """The vectorized row exploder must agree byte-for-byte with the
    original per-op reference loop (kept here as the spec) on histories
    with drains, indeterminate ops, unmatched completions, and nemesis
    rows."""
    import numpy as np

    from jepsen_tpu.history.encode import _COLUMNS, _rows_for
    from jepsen_tpu.history.ops import NO_VALUE, Op, OpF, OpType
    from jepsen_tpu.history.synth import SynthSpec, synth_history

    def rows_for_ref(history):
        open_invoke_time = {}
        rows = []
        for op in history:
            t_ms = op.time // 1_000_000 if op.time >= 0 else -1
            latency = -1
            if op.type == OpType.INVOKE:
                open_invoke_time[op.process] = op.time
            else:
                inv_t = open_invoke_time.pop(op.process, -1)
                if inv_t >= 0 and op.time >= 0:
                    latency = (op.time - inv_t) // 1_000_000
            values = (
                op.value if isinstance(op.value, (list, tuple)) else [op.value]
            )
            if len(values) == 0:
                values = [None]
            first = True
            for v in values:
                vi = v if isinstance(v, int) else NO_VALUE
                rows.append((op.index, op.process, int(op.type), int(op.f),
                             vi, t_ms, latency if first else -1,
                             1 if first else 0))
                first = False
        return np.asarray(rows, dtype=np.int32).reshape(-1, len(_COLUMNS))

    for seed in range(6):
        h = synth_history(
            SynthSpec(n_ops=300, seed=seed, lost=1, duplicated=1)
        ).ops
        np.testing.assert_array_equal(_rows_for(h), rows_for_ref(h))

    # hand-built corner cases: unmatched completion, time -1 invoke,
    # empty drain, string value, nemesis pseudo-process
    h = [
        Op(OpType.OK, OpF.DEQUEUE, 2, 5, time=10_000_000, index=0),  # unmatched
        Op(OpType.INVOKE, OpF.ENQUEUE, 0, 1, time=-1, index=1),
        Op(OpType.OK, OpF.ENQUEUE, 0, 1, time=20_000_000, index=2),
        Op(OpType.INVOKE, OpF.START, -1, None, time=25_000_000, index=3),
        Op(OpType.INFO, OpF.START, -1, "cut", time=26_000_000, index=4),
        Op(OpType.INVOKE, OpF.DRAIN, 1, None, time=30_000_000, index=5),
        Op(OpType.OK, OpF.DRAIN, 1, [7, 8, 9], time=40_000_000, index=6),
        Op(OpType.INVOKE, OpF.DRAIN, 3, None, time=41_000_000, index=7),
        Op(OpType.OK, OpF.DRAIN, 3, [], time=42_000_000, index=8),
    ]
    np.testing.assert_array_equal(_rows_for(h), rows_for_ref(h))

    # int subclasses (bool) encode like the reference loop's isinstance
    hb = [Op(OpType.OK, OpF.ENQUEUE, 0, True, time=1_000_000, index=0)]
    np.testing.assert_array_equal(_rows_for(hb), rows_for_ref(hb))

    # an out-of-int32 value fails LOUDLY, never silently wraps (a wrapped
    # value would alias onto a legitimate one and evade the value_space
    # guard)
    import pytest

    hbig = [Op(OpType.OK, OpF.ENQUEUE, 0, 2**40, time=1_000_000, index=0)]
    with pytest.raises(OverflowError):
        _rows_for(hbig)


def test_parallel_pack_matches_serial():
    """Worker-process packing (history.parpack) is seed-deterministic:
    identical packed tensors to the serial synth->pack path (the workers
    are spawn-isolated and jax-free; on a core-starved host the CLI caps
    them, but correctness holds at any worker count)."""
    import numpy as np

    from jepsen_tpu.history.encode import pack_histories, pack_row_matrices
    from jepsen_tpu.history.parpack import synth_queue_rows_parallel
    from jepsen_tpu.history.synth import SynthSpec, synth_batch

    count, ops = 12, 120
    serial = pack_histories(
        [
            sh.ops
            for sh in synth_batch(count, SynthSpec(n_ops=ops), lost=1)
        ],
        to_device=False,
    )
    mats = synth_queue_rows_parallel(count, ops, lost=1, workers=3)
    par = pack_row_matrices(mats, to_device=False)
    assert par.value_space == serial.value_space
    for field in ("index", "process", "type", "f", "value", "time_ms",
                  "latency_ms", "mask", "first"):
        np.testing.assert_array_equal(
            getattr(par, field), getattr(serial, field), err_msg=field
        )


def test_parallel_read_tags_workload(tmp_path):
    """read_rows_parallel tags every history with its workload family so
    the CLI can apply the same mixed-store filter as the serial path."""
    from jepsen_tpu.history.parpack import read_rows_parallel
    from jepsen_tpu.history.store import write_history_jsonl
    from jepsen_tpu.history.synth import (
        StreamSynthSpec,
        SynthSpec,
        synth_history,
        synth_stream_history,
    )

    pq = tmp_path / "q.jsonl"
    ps = tmp_path / "s.jsonl"
    write_history_jsonl(pq, synth_history(SynthSpec(n_ops=30)).ops)
    write_history_jsonl(
        ps, synth_stream_history(StreamSynthSpec(n_ops=30)).ops
    )
    tagged = read_rows_parallel([pq, ps], workers=2)
    assert [k for k, _ in tagged] == ["queue", "stream"]
    assert all(m.shape[1] == 8 for _, m in tagged)
