"""Telemetry ≡ history differential suite (ISSUE 12): the cluster
telemetry plane (``jepsen_tpu/obs/cluster.py`` + the RaftNode/broker
instrumentation) against what the cluster actually did.

Pinned here, as counters — not log lines:

- green runs: exactly one leader per poll, elections-won ≥ observed
  leader changes, per-node term/commit monotone across samples, the
  SAFETY-VIOLATION tripwire counter stays 0;
- the tripwire COUNTS when committed entries truncate (driven
  deterministically at the RPC layer);
- the fsync latency sketch visibly shifts under the slow-disk fault,
  and stays EMPTY under ``ack-before-fsync`` (a node lying about
  fsync never reaches the timed fsync — the telemetry tell);
- wire-fault injection counters match what the wire actually did:
  sender corrupt counts ≥ receiver CRC rejections > 0 with checksums
  on, and receiver CRC rejections stay 0 under ``no-wire-checksum``
  while corruption flows (the bug made visible);
- the poller's samples/events/gauges, the report's cluster panel, the
  forensics cluster-window answer + surfaced log-pattern matches, and
  the end-to-end live run with ``cluster.json`` + admin ``STATS``.
"""

from __future__ import annotations

import json
import socket
import tempfile
import time

from _load import scaled
import xml.etree.ElementTree as ET
from pathlib import Path

import pytest

from jepsen_tpu.harness.replication import (
    NodeCounters,
    RaftNode,
    ReplicatedBackend,
    WireFaultSpec,
)
from jepsen_tpu.history.ops import Op, OpF, OpType
from jepsen_tpu.obs import trace as obs_trace
from jepsen_tpu.obs.cluster import (
    ClusterPoller,
    DirectStatsSource,
    cluster_window_summary,
    load_cluster_json,
    summary_line,
    write_cluster_json,
)
from jepsen_tpu.obs.metrics import (
    QuantileSketch,
    Registry,
    render_prometheus,
    sketch_state_delta,
)

FAST = dict(
    election_timeout=(0.1, 0.2),
    heartbeat_s=0.03,
    dead_owner_s=1.0,
    submit_timeout_s=2.5,
)

_COUNTER_KEYS = tuple(NodeCounters.__slots__)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _Cluster:
    """In-process replication-layer cluster (the test_nemesis idiom)."""

    def __init__(self, n=3, seed_bug=None, root=None, **overrides):
        self.root = root
        self.names = [f"n{i}" for i in range(n)]
        self.peers = {nm: ("127.0.0.1", _free_port())
                      for nm in self.names}
        self.seed_bug = seed_bug
        self.opts = {**FAST, **overrides}
        self.backends: dict[str, ReplicatedBackend] = {}
        for i, nm in enumerate(self.names):
            self.backends[nm] = ReplicatedBackend(
                nm,
                self.peers,
                seed_bug=self.seed_bug,
                rng_seed=1000 + i,
                data_dir=(
                    None if self.root is None else f"{self.root}/{nm}"
                ),
                **self.opts,
            )

    def leader(self, timeout=8.0) -> str:
        deadline = time.monotonic() + scaled(timeout)
        while time.monotonic() < deadline:
            for nm, b in self.backends.items():
                if b.raft.is_leader():
                    return nm
            time.sleep(0.02)
        raise AssertionError("no leader")

    def stop(self) -> None:
        for b in self.backends.values():
            b.stop()


# ---------------------------------------------------------------------------
# node-level counters + sketch
# ---------------------------------------------------------------------------


class TestNodeTelemetry:
    def test_green_run_counters_and_snapshot_shape(self, tmp_path):
        c = _Cluster(root=str(tmp_path / "d"))
        try:
            lead = c.leader()
            b = c.backends[lead]
            b.declare("q")
            for v in (b"1", b"2", b"3"):
                assert b.enqueue("q", v, b"") is True
            snaps = {
                nm: bb.stats_snapshot() for nm, bb in c.backends.items()
            }
            # JSON-safe (the STATS wire contract)
            json.dumps(snaps)
            leaders = [
                nm for nm, s in snaps.items()
                if s["raft"]["role"] == "leader"
            ]
            assert leaders == [lead]
            won = sum(
                s["raft"]["counters"]["elections_won"]
                for s in snaps.values()
            )
            assert won >= 1
            for nm, s in snaps.items():
                raft = s["raft"]
                assert set(raft["counters"]) == set(_COUNTER_KEYS)
                assert raft["counters"]["safety_violations"] == 0
                assert raft["commit_idx"] <= raft["log_len"]
                # durable green: real fsyncs were timed, WAL grew
                assert raft["counters"]["wal_bytes"] > 0, nm
                assert raft["fsync_ms"]["count"] > 0, nm
            assert snaps[lead]["broker"]["ready"] == 3
        finally:
            c.stop()

    def test_fsync_sketch_shifts_under_slow_disk(self, tmp_path):
        c = _Cluster(root=str(tmp_path / "d"))
        try:
            lead = c.leader()
            b = c.backends[lead]
            b.declare("q")
            assert b.enqueue("q", b"0", b"") is True  # fast baseline
            before = c.backends[lead].raft._fsync_ms.state()
            for nm in c.names:
                c.backends[nm].raft.set_fsync_latency(60.0, 10.0)
            for v in (b"1", b"2"):
                c.backends[c.leader()].enqueue("q", v, b"")
            after = c.backends[lead].raft._fsync_ms.state()
            delta = sketch_state_delta(before, after)
            assert delta["count"] > 0, "no fsyncs under the fault"
            shifted = QuantileSketch.from_state(delta)
            assert shifted.quantile(0.5) >= 40.0, (
                "slow-disk fault did not move the fsync sketch: "
                f"p50={shifted.quantile(0.5):.2f}ms"
            )
        finally:
            c.stop()

    def test_ack_before_fsync_red_is_visible_in_telemetry(self, tmp_path):
        """The lying node confirms writes while its fsync sketch stays
        EMPTY and its WAL byte counter stays 0 — durability theater,
        readable straight off the telemetry."""
        c = _Cluster(
            root=str(tmp_path / "d"), seed_bug="ack-before-fsync"
        )
        try:
            lead = c.leader()
            b = c.backends[lead]
            # baseline AFTER election: term/vote meta fsyncs are real
            # even under the bug — only the WAL path lies
            before = c.backends[lead].stats_snapshot()["raft"]
            b.declare("q")
            acked = [v for v in (b"1", b"2") if b.enqueue("q", v, b"")]
            assert acked, "nothing confirmed"
            after = c.backends[lead].stats_snapshot()["raft"]
            assert (
                after["fsync_ms"]["count"] == before["fsync_ms"]["count"]
            ), "confirmed writes fsynced — the bug is gone?"
            assert after["counters"]["wal_bytes"] == 0
        finally:
            c.stop()

    def test_wire_fault_counters_match_injected_events(self):
        c = _Cluster()
        try:
            lead = c.leader()
            L = c.backends[lead].raft
            L.set_wire_faults(WireFaultSpec(corrupt_p=1.0))
            time.sleep(0.5)  # heartbeats flow at 30 ms tick
            corrupt = L.counters.wire_corrupt
            rejected = sum(
                c.backends[nm].raft.counters.crc_rejected
                for nm in c.names
                if nm != lead
            )
            assert corrupt > 0, "wire fault injected nothing"
            assert 0 < rejected <= corrupt, (corrupt, rejected)
            # heal: the injection counter freezes
            L.set_wire_faults(None)
            frozen = L.counters.wire_corrupt
            time.sleep(0.3)
            assert L.counters.wire_corrupt == frozen
        finally:
            c.stop()

    def test_no_wire_checksum_red_rejects_nothing(self):
        """Under the seeded bug, corruption flows (sender counter
        grows) while NO receiver ever rejects a frame — the telemetry
        differential that distinguishes the bug from the correct
        checksummed transport."""
        c = _Cluster(seed_bug="no-wire-checksum")
        try:
            lead = c.leader()
            L = c.backends[lead].raft
            L.set_wire_faults(WireFaultSpec(corrupt_p=1.0))
            time.sleep(0.5)
            assert L.counters.wire_corrupt > 0
            assert all(
                c.backends[nm].raft.counters.crc_rejected == 0
                for nm in c.names
            )
        finally:
            c.stop()

    def test_tripwire_counts_committed_truncation(self):
        """Deterministic committed-truncation at the RPC layer: a
        single-node leader with committed entries receives a
        conflicting higher-term AppendEntries overlapping its committed
        prefix — the SAFETY-VIOLATION tripwire must COUNT, not just
        log."""
        node = RaftNode(
            "n0",
            {"n0": ("127.0.0.1", _free_port())},
            lambda i, op: None,
            election_timeout=(0.05, 0.1),
            heartbeat_s=0.02,
        )
        try:
            deadline = time.monotonic() + scaled(5.0)
            while not node.is_leader():
                assert time.monotonic() < deadline, "no self-election"
                time.sleep(0.01)
            for _ in range(3):
                ok, _r = node.submit({"k": "noop"}, timeout_s=2.0)
                assert ok
            assert node.commit_idx == 3
            assert node.counters.safety_violations == 0
            resp = node._on_append_entries({
                "term": node.term + 1,
                "from": "nX",
                "prev_idx": 0,
                "prev_term": 0,
                "entries": [(node.term + 1, {"k": "noop"})],
                "leader_commit": 0,
            })
            assert resp["ok"] is True
            assert node.counters.safety_violations == 1
        finally:
            node.stop()


# ---------------------------------------------------------------------------
# the poller: samples, events, gauges, document
# ---------------------------------------------------------------------------


class TestClusterPoller:
    def test_leader_change_events_gauges_and_tracks(self):
        c = _Cluster()
        reg = Registry()
        obs_trace.enable()
        try:
            lead = c.leader()
            poller = ClusterPoller(
                DirectStatsSource(c.backends),
                interval_s=0.05,
                registry=reg,
            ).start()
            time.sleep(0.3)
            for nm, bb in c.backends.items():
                if nm != lead:
                    bb.raft.block(lead)  # one-way-out the leader
            new = None
            deadline = time.monotonic() + scaled(8.0)
            while time.monotonic() < deadline:
                for nm, bb in c.backends.items():
                    if nm != lead and bb.raft.is_leader():
                        new = nm
                if new:
                    break
                time.sleep(0.02)
            assert new, "no failover"
            time.sleep(0.3)  # let the poller observe the flip
            doc = poller.stop()
        finally:
            obs_trace.disable()
            c.stop()

        s = doc["summary"]
        assert set(s["leaders-seen"]) >= {lead, new}
        assert s["leader-changes"] >= 2
        assert s["elections-won"] >= s["leader-changes"]
        assert s["safety-violations"] == 0
        role_events = [
            e for e in doc["events"] if e["kind"] == "role"
        ]
        assert any(
            e["node"] == new and e["to"] == "leader"
            for e in role_events
        )
        assert any(e["kind"] == "term" for e in doc["events"])
        # per-node monotone invariants (telemetry ≡ history, green)
        by_node: dict[str, list] = {}
        for smp in doc["samples"]:
            by_node.setdefault(smp["node"], []).append(smp)
        for nm, rows in by_node.items():
            rows.sort(key=lambda r: r["t"])
            terms = [r["term"] for r in rows]
            commits = [r["commit"] for r in rows]
            assert terms == sorted(terms), (nm, terms)
            assert commits == sorted(commits), (nm, commits)
        # registry gauges carry node labels; prometheus renders them
        prom = render_prometheus(reg)
        assert f'jepsen_tpu_cluster_node_role{{node="{new}"}} 2' in prom
        assert "jepsen_tpu_cluster_node_commit_idx" in prom
        # trace instants landed on per-node tracks
        tracks = {rec[2] for rec in obs_trace.snapshot()}
        assert f"node:{new}" in tracks

    def test_unreachable_node_samples_as_down(self):
        """A node whose poll raises (or a dead out-of-process node
        answering None) must read as down — role ``down``, ``node_up``
        gauge 0 — never crash the poller."""
        reg = Registry()
        p = ClusterPoller(
            DirectStatsSource({"ghost": object()}),
            interval_s=0.05,
            registry=reg,
        )
        p.poll_once()
        p.poll_once()
        assert p.samples and all(
            smp["role"] == "down" for smp in p.samples
        )
        assert reg.value("cluster.node_up", node="ghost") == 0.0

    def test_final_summary_keeps_counters_of_a_down_node(self):
        """A node that dies before the final poll must not lose its
        counters from the summary — its tripwire/election totals are
        exactly what a post-mortem needs (down-ness lives in the
        samples)."""
        c = _Cluster(n=1)
        try:
            c.leader()
            src = DirectStatsSource(c.backends)
            p = ClusterPoller(src, interval_s=0.05, registry=Registry())
            p.poll_once()
            # the node dies: subsequent polls read it as down
            src._nodes[c.names[0]] = object()
            p.poll_once()
            doc = p.stop()
        finally:
            c.stop()
        assert doc["samples"][-1]["role"] == "down"
        assert doc["summary"]["elections-won"] >= 1, (
            "a down node's counters vanished from the summary"
        )
        assert doc["final"][c.names[0]] is not None

    def test_window_summary_answers_leader_and_lag(self):
        doc = _synth_cluster_doc(t_max_ns=4_000_000_000)
        w = cluster_window_summary(
            doc, 2_500_000_000, 3_500_000_000
        )
        assert {e["node"] for e in w["leaders"]} == {"n1"}
        assert w["max-commit-lag"] == 3
        assert w["samples-in-window"] > 0
        assert w["tripwires-in-window"] == 0
        # summary_line renders without blowing up
        assert "leaders" in summary_line(doc)


# ---------------------------------------------------------------------------
# synthetic cluster.json for the render-side tests
# ---------------------------------------------------------------------------


def _synth_cluster_doc(t_max_ns: int) -> dict:
    sk = QuantileSketch()
    for v in (0.5, 1.0, 2.0, 40.0):
        sk.add(v)
    counters0 = {k: 0 for k in _COUNTER_KEYS}

    def raft(name, role, term, commit, **extra):
        return {
            "name": name, "role": role, "term": term,
            "leader_hint": None, "commit_idx": commit,
            "applied_idx": commit, "log_len": commit, "durable": True,
            "counters": {**counters0, **extra},
            "fsync_ms": sk.state(),
        }

    nodes = ("n0", "n1", "n2")
    samples, events = [], []
    for i, t in enumerate((0, t_max_ns // 2, t_max_ns)):
        lead = "n0" if i == 0 else "n1"
        term = 1 if i == 0 else 2
        for n in nodes:
            commit = 10 * (i + 1) - (3 if n == "n2" else 0)
            samples.append({
                "t": t, "node": n,
                "role": "leader" if n == lead else "follower",
                "term": term, "commit": commit, "applied": commit,
                "log": commit, "wal": 100 * (i + 1), "ready": 1,
                "inflight": 0,
            })
    events.append({
        "t": t_max_ns // 2, "node": "n1", "kind": "role",
        "frm": "follower", "to": "leader", "term": 2,
    })
    final = {
        n: {
            "broker": {
                "connections": 1, "ready": 1, "inflight": 0,
                "published": 5, "delivered": 5, "appended": 0,
                "chan_close_540": 0, "chan_close_541": 0,
            },
            "raft": raft(
                n, "leader" if n == "n1" else "follower", 2, 30,
                elections_won=1 if n in ("n0", "n1") else 0,
                elections_started=1 if n in ("n0", "n1") else 0,
            ),
        }
        for n in nodes
    }
    return {
        "interval-s": 1.0,
        "nodes": list(nodes),
        "samples": samples,
        "events": events,
        "final": final,
        "summary": {
            "polls": 3, "leaders-seen": ["n0", "n1"],
            "leader-changes": 2, "max-term": 2, "elections-won": 2,
            "safety-violations": 0, "crc-rejected": 0,
            "wire-faults": 0,
            "fsync-p99-ms": {n: 40.0 for n in nodes},
        },
    }


class TestReportClusterPanel:
    def test_report_renders_cluster_panels(self, tmp_path):
        from jepsen_tpu.history.store import Store
        from jepsen_tpu.history.synth import SynthSpec, synth_batch
        from jepsen_tpu.report.render import render_run_report

        sh = synth_batch(1, SynthSpec(n_ops=40, n_processes=3))[0]
        d = tmp_path / "run"
        d.mkdir()
        st = Store(tmp_path)
        st.save_history(d, sh.ops)
        st.save_results(d, {"valid?": True})
        t_max = max(op.time for op in sh.ops if op.time >= 0)
        write_cluster_json(d, _synth_cluster_doc(t_max))
        paths = render_run_report(d)
        html = Path(paths["report"]).read_text()
        ET.fromstring(html)  # well-formed XML, panels included
        assert "cluster telemetry" in html
        assert "commit-index lag" in html
        assert "per-node internals" in html
        assert "fsync p50/p99" in html
        rj = json.loads(Path(paths["report-json"]).read_text())
        assert rj["cluster"]["leaders-seen"] == ["n0", "n1"]

    def test_report_without_cluster_json_has_no_panel(self, tmp_path):
        from jepsen_tpu.history.store import Store
        from jepsen_tpu.history.synth import SynthSpec, synth_batch
        from jepsen_tpu.report.render import render_run_report

        sh = synth_batch(1, SynthSpec(n_ops=20, n_processes=3))[0]
        d = tmp_path / "run"
        d.mkdir()
        st = Store(tmp_path)
        st.save_history(d, sh.ops)
        st.save_results(d, {"valid?": True})
        paths = render_run_report(d)
        html = Path(paths["report"]).read_text()
        ET.fromstring(html)
        assert "cluster telemetry" not in html


class TestForensicsCluster:
    def _invalid_run(self, tmp_path):
        from jepsen_tpu.history.store import Store

        ops = [
            Op(OpType.INVOKE, OpF.ENQUEUE, 0, 3, 2_600_000_000, 0),
            Op(OpType.OK, OpF.ENQUEUE, 0, 3, 2_700_000_000, 1),
            Op(OpType.INVOKE, OpF.DEQUEUE, 1, None, 3_000_000_000, 2),
            Op(OpType.FAIL, OpF.DEQUEUE, 1, None, 3_100_000_000, 3),
        ]
        d = tmp_path / "run"
        d.mkdir()
        Store(tmp_path).save_history(d, ops)
        results = {
            "valid?": False,
            "queue": {"valid?": False, "lost": [3]},
            "log-file-pattern": {
                "valid?": False,
                "pattern": "CRASH REPORT",
                "count": 1,
                "matches": [{
                    "node": "n1",
                    "file": "n1/broker.log",
                    "line": 42,
                    "text": "=CRASH REPORT==== broker died",
                }],
            },
        }
        return d, ops, results

    def test_cluster_window_and_logpattern_on_the_page(self, tmp_path):
        from jepsen_tpu.report.forensics import render_forensics

        d, ops, results = self._invalid_run(tmp_path)
        write_cluster_json(d, _synth_cluster_doc(4_000_000_000))
        p = render_forensics(d, history=ops, results=results)
        assert p is not None
        html = Path(p).read_text()
        ET.fromstring(html)
        # the cluster answer: who led during the violating window
        assert "cluster during the violating window" in html
        assert "n1 (term 2)" in html
        assert "max commit-index lag" in html
        # the log-only blind spot, fixed: matched lines on the page
        assert "matched node-log lines" in html
        assert "n1/broker.log" in html and "42" in html
        assert "CRASH REPORT==== broker died" in html

    def test_page_renders_without_cluster_json(self, tmp_path):
        from jepsen_tpu.report.forensics import render_forensics

        d, ops, results = self._invalid_run(tmp_path)
        p = render_forensics(d, history=ops, results=results)
        html = Path(p).read_text()
        ET.fromstring(html)
        assert "cluster during the violating window" not in html
        assert "matched node-log lines" in html  # logpattern still shows


# ---------------------------------------------------------------------------
# end-to-end: a live local-cluster run harvests cluster.json
# ---------------------------------------------------------------------------


class TestLiveClusterTelemetry:
    def test_live_run_harvests_cluster_json_and_stats_wire(self, _reset):
        """The e2e differential: a real 3-node replicated run under the
        partition nemesis ends with a ``cluster.json`` whose telemetry
        agrees with the history's clock and the cluster's elections —
        and the admin ``STATS`` wire answers the same shape live."""
        import sys as _sys

        _sys.path.insert(0, str(Path(__file__).parent))
        from _live import run_live_with_triage

        from jepsen_tpu.control.db_rabbitmq import RabbitMQDB
        from jepsen_tpu.harness.localcluster import LocalProcTransport
        from jepsen_tpu.suite import DEFAULT_OPTS, build_rabbitmq_test

        state: dict = {}

        def build():
            t = LocalProcTransport(n_nodes=3)
            nodes = t.nodes
            opts = {
                **DEFAULT_OPTS,
                "rate": 120.0,
                "time-limit": 3.0,
                "time-before-partition": 0.6,
                "partition-duration": 1.0,
                "recovery-sleep": 0.8,
                "publish-confirm-timeout": 1.5,
            }
            db = RabbitMQDB(
                t, nodes, primary_wait_s=0.2, secondary_wait_s=0.2,
                join_stagger_max_s=0.1,
            )
            test = build_rabbitmq_test(
                opts=opts, nodes=nodes, transport=t, db=db,
                checker_backend="cpu", store_root=tempfile.mkdtemp(),
                workload="queue", concurrency=3,
            )
            assert test.cluster_source is not None, (
                "LocalProcTransport must wire the telemetry source"
            )
            state["transport"], state["nodes"] = t, nodes
            return test, t

        def checks(run):
            # live STATS wire (cluster still up): full snapshot shape
            snap = state["transport"].node_stats(state["nodes"][0])
            assert snap is not None and snap["raft"] is not None
            assert set(snap["raft"]["counters"]) == set(_COUNTER_KEYS)
            assert "fsync_ms" in snap["raft"]
            assert {"ready", "inflight"} <= set(snap["broker"])

            doc = load_cluster_json(run.run_dir)
            assert doc is not None, "runner never harvested cluster.json"
            s = doc["summary"]
            assert s["polls"] >= 2
            assert len(doc["samples"]) >= s["polls"]
            assert set(doc["nodes"]) == set(state["nodes"])
            # telemetry ≡ history: leader changes need elections won,
            # the tripwire stays silent on green, terms/commits monotone
            assert 1 <= s["leader-changes"] <= s["elections-won"]
            assert s["safety-violations"] == 0
            by_node: dict[str, list] = {}
            for smp in doc["samples"]:
                by_node.setdefault(smp["node"], []).append(smp)
            for nm, rows in by_node.items():
                rows.sort(key=lambda r: r["t"])
                live = [r for r in rows if r["role"] != "down"]
                terms = [r["term"] for r in live]
                commits = [r["commit"] for r in live]
                assert terms == sorted(terms), (nm, terms)
                assert commits == sorted(commits), (nm, commits)
            # sample clock = the op clock (ns from run start)
            t_hist = max(op.time for op in run.history if op.time >= 0)
            assert all(
                -1e9 <= smp["t"] <= t_hist + 60e9
                for smp in doc["samples"]
            )
            # the default-on report carries the cluster panel
            report = Path(run.run_dir) / "report.html"
            assert report.is_file()
            html = report.read_text()
            assert "cluster telemetry" in html
            ET.fromstring(html)

        run_live_with_triage(build, expect="valid", checks=checks)
