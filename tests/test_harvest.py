"""Opportunistic chip-bench harvest (utils/harvest.py + bench.py --watch).

The capture problem these exist for (VERDICT r3 #1): three rounds of
CPU-fallback BENCH artifacts because the tunnel happened to be wedged at
the one moment bench.py ran.  These tests prove the harvest machinery —
staleness detection, single-flight locking, detached spawn, recursion
guard, and the watch loop's probe/run/stop cycle — without any chip.
"""

from __future__ import annotations

import json
import os
import sys

from jepsen_tpu.utils import harvest


def _write_details(root, payload) -> None:
    with open(os.path.join(root, "BENCH_DETAILS.json"), "w") as fh:
        json.dump(payload, fh)


class TestNeedsChipRefresh:
    def test_missing_file(self, tmp_path):
        assert harvest.needs_chip_refresh(str(tmp_path))

    def test_unparseable(self, tmp_path):
        (tmp_path / "BENCH_DETAILS.json").write_text("{nope")
        assert harvest.needs_chip_refresh(str(tmp_path))

    def test_cpu_backend(self, tmp_path):
        _write_details(tmp_path, {"backend": "cpu", "provenance": {}})
        assert harvest.needs_chip_refresh(str(tmp_path))

    def test_chip_but_no_provenance(self, tmp_path):
        # the round-2 file shape the verdict flagged: numbers, no evidence
        _write_details(tmp_path, {"backend": "tpu"})
        assert harvest.needs_chip_refresh(str(tmp_path))

    def test_chip_with_provenance_is_fresh(self, tmp_path):
        # tmp_path is not a git checkout: HEAD is unknowable, so the
        # stamped rev cannot be judged stale — no thrash on non-git roots
        _write_details(
            tmp_path,
            {"backend": "tpu", "provenance": {"git_rev": "abc"}},
        )
        assert not harvest.needs_chip_refresh(str(tmp_path))

    @staticmethod
    def _git_repo(tmp_path):
        import subprocess

        def g(*a):
            return subprocess.run(
                ["git", "-C", str(tmp_path), *a],
                capture_output=True, text=True, check=True,
            ).stdout.strip()

        subprocess.run(
            ["git", "init", "-q", str(tmp_path)], check=True,
            capture_output=True,
        )
        g("-c", "user.email=t@t", "-c", "user.name=t", "commit",
          "--allow-empty", "-q", "-m", "x")
        return g("rev-parse", "--short", "HEAD")

    def test_rev_drift_re_arms_the_harvest(self, tmp_path):
        """VERDICT r4 weak #5: a capture stamped with a pre-HEAD rev no
        longer counts as fresh — the next healthy chip window re-runs it
        so the committed numbers describe the judged tree."""
        head = self._git_repo(tmp_path)
        _write_details(
            tmp_path,
            {"backend": "tpu", "provenance": {"git_rev": "0000000"}},
        )
        assert harvest.needs_chip_refresh(str(tmp_path))
        _write_details(
            tmp_path,
            {"backend": "tpu", "provenance": {"git_rev": head}},
        )
        assert not harvest.needs_chip_refresh(str(tmp_path))

    def test_unstamped_capture_does_not_thrash_in_git(self, tmp_path):
        self._git_repo(tmp_path)
        _write_details(
            tmp_path, {"backend": "tpu", "provenance": {}}
        )
        assert not harvest.needs_chip_refresh(str(tmp_path))


class TestLock:
    def test_single_flight(self, tmp_path):
        root = str(tmp_path)
        assert harvest._try_lock(root)
        # the holder (this pid) is alive — a second flight must refuse
        assert not harvest._try_lock(root)
        harvest.release_lock(root)
        assert harvest._try_lock(root)

    def test_stale_pid_reaped(self, tmp_path):
        root = str(tmp_path)
        lock = tmp_path / "store" / "harvest.lock"
        lock.parent.mkdir()
        lock.write_text("999999999")  # no such pid
        assert harvest._try_lock(root)

    def test_garbage_lock_reaped(self, tmp_path):
        root = str(tmp_path)
        lock = tmp_path / "store" / "harvest.lock"
        lock.parent.mkdir()
        lock.write_text("not-a-pid")
        assert harvest._try_lock(root)

    def test_release_missing_is_quiet(self, tmp_path):
        harvest.release_lock(str(tmp_path))


def _fake_repo(tmp_path):
    """A repo root whose bench.py just records its argv (the real child's
    lock-release-at-exit is covered by TestHarvestChild instead, so the
    spawner's post-spawn lock retargeting can be asserted race-free)."""
    root = tmp_path / "repo"
    root.mkdir()
    (root / "bench.py").write_text(
        "import json, os, sys\n"
        "open('ran.json', 'w').write(json.dumps(\n"
        "    {'argv': sys.argv[1:], 'pid': os.getpid(),\n"
        "     'guard': os.environ.get('JEPSEN_TPU_HARVEST_CHILD')}))\n"
    )
    return str(root)


def _wait_for(path, timeout=20.0):
    import time

    t0 = time.monotonic()
    while not os.path.exists(path):
        assert time.monotonic() - t0 < timeout, f"no {path} after {timeout}s"
        time.sleep(0.05)


class TestOpportunistic:
    def test_spawns_when_stale(self, tmp_path):
        root = _fake_repo(tmp_path)
        assert harvest.opportunistic(root)
        _wait_for(os.path.join(root, "ran.json"))
        ran = json.load(open(os.path.join(root, "ran.json")))
        # the child must wait for the (chip-holding) spawner, never race it
        assert ran["argv"][:3] == [
            "--harvest-child", "--wait-pid", str(os.getpid())
        ]
        assert ran["guard"] == "1"  # the child can never re-harvest
        # the lock was retargeted at the child's pid, not the spawner's:
        # liveness tracking must survive this (short-lived) CLI exiting
        lock = os.path.join(root, "store", "harvest.lock")
        assert int(open(lock).read()) == ran["pid"]

    def test_noop_when_fresh(self, tmp_path):
        root = _fake_repo(tmp_path)
        _write_details(root, {"backend": "tpu", "provenance": {"x": 1}})
        assert not harvest.opportunistic(root)
        assert not os.path.exists(os.path.join(root, "ran.json"))

    def test_noop_from_inside_harvest(self, tmp_path, monkeypatch):
        root = _fake_repo(tmp_path)
        monkeypatch.setenv(harvest.GUARD_ENV, "1")
        assert not harvest.opportunistic(root)

    def test_noop_without_bench(self, tmp_path):
        assert not harvest.opportunistic(str(tmp_path))

    def test_single_flight_across_calls(self, tmp_path):
        root = _fake_repo(tmp_path)
        assert harvest._try_lock(root)  # simulate a live harvest
        assert not harvest.opportunistic(root)


def _load_bench(name="bench_under_test"):
    """Import bench.py (not a package module) fresh under ``name``."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(__file__), "..", "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestHarvestChild:
    """bench.py's --harvest-child/--wait-pid contract, unit-level."""

    def _bench_mod(self):
        return _load_bench("bench_child_under_test")

    def test_await_pid_exit(self):
        import subprocess

        bench = self._bench_mod()
        p = subprocess.Popen([sys.executable, "-c", "pass"])
        p.wait()
        assert bench._await_pid_exit(p.pid, budget=10.0, poll_s=0.01)

    def test_await_pid_budget_expires_on_live_pid(self):
        bench = self._bench_mod()
        assert not bench._await_pid_exit(
            os.getpid(), budget=0.05, poll_s=0.01
        )

    def test_child_waits_then_runs_and_releases(self, tmp_path, monkeypatch):
        bench = self._bench_mod()
        monkeypatch.chdir(tmp_path)  # release_lock uses the real repo root
        ran = []
        monkeypatch.setattr(bench, "_run_once", lambda: ran.append(1))
        waited = []
        monkeypatch.setattr(
            bench,
            "_await_pid_exit",
            lambda pid, budget: waited.append(pid) or True,
        )
        released = []
        import jepsen_tpu.utils.harvest as hv

        monkeypatch.setattr(hv, "release_lock", lambda: released.append(1))
        assert bench.main(["--harvest-child", "--wait-pid", "12345"]) == 0
        assert waited == [12345] and ran == [1] and released == [1]

    def test_plain_run_takes_and_releases_the_lock(self, monkeypatch):
        """A direct `python bench.py` (the round driver) must not bench
        beside a mid-flight harvest on the exclusive chip."""
        bench = self._bench_mod()
        ran = []
        monkeypatch.setattr(bench, "_run_once", lambda: ran.append(1))
        import jepsen_tpu.utils.harvest as hv

        calls = []
        monkeypatch.setattr(
            hv, "_try_lock", lambda root: calls.append("lock") or True
        )
        monkeypatch.setattr(
            hv, "release_lock", lambda root=None: calls.append("release")
        )
        assert bench.main([]) == 0
        assert ran == [1] and calls == ["lock", "release"]

    def test_locked_flag_skips_lock_handling(self, monkeypatch):
        bench = self._bench_mod()
        ran = []
        monkeypatch.setattr(bench, "_run_once", lambda: ran.append(1))
        import jepsen_tpu.utils.harvest as hv

        def boom(root):
            raise AssertionError("--locked must not touch the lock")

        monkeypatch.setattr(hv, "_try_lock", boom)
        assert bench.main(["--locked"]) == 0
        assert ran == [1]

    def test_child_skips_bench_when_spawner_never_exits(
        self, tmp_path, monkeypatch
    ):
        bench = self._bench_mod()
        ran = []
        monkeypatch.setattr(bench, "_run_once", lambda: ran.append(1))
        monkeypatch.setattr(
            bench, "_await_pid_exit", lambda pid, budget: False
        )
        released = []
        import jepsen_tpu.utils.harvest as hv

        monkeypatch.setattr(hv, "release_lock", lambda: released.append(1))
        assert bench.main(["--harvest-child", "--wait-pid", "12345"]) == 0
        assert ran == [] and released == [1]  # lock freed either way




class _FakePopen:
    """Stands in for the watch loop's streaming bench child: `.stdout`
    iterates the scripted lines (the real object is a pipe), `.wait()`
    returns the exit code."""

    def __init__(self, lines, returncode=0):
        self.stdout = iter(lines)
        self.returncode = returncode

    def wait(self):
        return self.returncode


class TestWatchLoop:
    """Unit-level: the loop's probe/run/stop protocol, fakes for both."""

    def _bench_mod(self):
        return _load_bench()

    def _stub_lock(self, monkeypatch, available=True):
        import jepsen_tpu.utils.harvest as hv

        monkeypatch.setattr(hv, "_try_lock", lambda root: available)
        monkeypatch.setattr(hv, "release_lock", lambda root=None: None)

    def test_stops_on_chip_measurement(self, monkeypatch):
        bench = self._bench_mod()
        self._stub_lock(monkeypatch)
        probes = iter([False, True])
        monkeypatch.setattr(
            bench, "_probe_chip", lambda d: next(probes)
        )

        line = json.dumps({"metric": "m", "fallback": False}) + "\n"
        monkeypatch.setattr(
            bench.subprocess,
            "Popen",
            lambda *a, **k: _FakePopen([line]),
        )
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        assert bench._watch(interval=1.0, budget=0.0) == 0

    def test_skips_cycle_while_another_harvest_holds_lock(
        self, monkeypatch
    ):
        bench = self._bench_mod()
        self._stub_lock(monkeypatch, available=False)
        monkeypatch.setattr(bench, "_probe_chip", lambda d: True)
        ran = []
        monkeypatch.setattr(
            bench.subprocess, "run", lambda *a, **k: ran.append(1)
        )
        monkeypatch.setattr(bench, "_run_once", lambda: None)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        # budget expires after the skipped cycle; no bench child ever ran
        assert bench._watch(interval=0.01, budget=0.0001) == 0
        assert ran == []

    def test_keeps_watching_after_fallback_run(self, monkeypatch):
        bench = self._bench_mod()
        self._stub_lock(monkeypatch)
        monkeypatch.setattr(bench, "_probe_chip", lambda d: True)
        results = iter(
            [
                json.dumps({"metric": "m", "fallback": True}),
                json.dumps({"metric": "m", "fallback": False}),
            ]
        )
        monkeypatch.setattr(
            bench.subprocess,
            "Popen",
            lambda *a, **k: _FakePopen([next(results) + "\n"]),
        )
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        assert bench._watch(interval=1.0, budget=0.0) == 0

    def test_budget_exhaustion_runs_fallback_bench(self, monkeypatch):
        bench = self._bench_mod()
        self._stub_lock(monkeypatch)
        monkeypatch.setattr(bench, "_probe_chip", lambda d: False)
        ran = []
        monkeypatch.setattr(bench, "_run_once", lambda: ran.append(1))
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        assert bench._watch(interval=0.01, budget=0.0001) == 0
        assert ran == [1]

    def test_probe_chip_healthy_on_cpu(self, monkeypatch):
        # pin the *subprocess* env to cpu (conftest pins only in-process;
        # the inherited sitecustomize pin would target the real tunnel)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        bench = self._bench_mod()
        assert bench._probe_chip(deadline=60.0)


class TestHeadlineOrdering:
    """VERDICT r4 #1: the one-line headline JSON must hit stdout BEFORE
    any secondary section runs — four consecutive rounds of driver
    artifacts were lost to sections that outlived the driver's budget
    (rc=1, cpu-fallback ×2, then rc=124 mid-stream on a healthy chip)."""

    def _run(self, monkeypatch, failing=()):
        import io

        bench = _load_bench("bench_headline_under_test")
        buf = io.StringIO()
        monkeypatch.setattr(sys, "stdout", buf)
        events = []
        monkeypatch.setattr(bench, "_init_backend_with_retry", lambda: "tpu")
        monkeypatch.setattr(bench, "_provenance", lambda b: {"backend": b})

        def fake_queue(details):
            details["queue"] = {"device_histories_per_sec": 100.0}
            return 100.0, 2.0

        monkeypatch.setattr(bench, "_bench_queue", fake_queue)
        for name in (
            "_bench_queue_pipeline", "_bench_stream", "_bench_stream_long",
            "_bench_elle", "_bench_mutex", "_bench_wgl_pcomp",
            "_bench_bitpack_section", "_bench_segmented_section",
            "_bench_fleet_memory_section",
            "_bench_serve_section", "_bench_serve_batching_section",
            "_bench_campaign_section",
            "_bench_north_star_section", "_bench_north_star_100k_section",
            "_bench_cold_vs_warm_section",
            "_bench_obs_overhead_section",
            "_bench_elastic_overhead_section",
            "_bench_cluster_obs_overhead_section",
            "_bench_report_section", "_bench_scaling",
        ):
            def fake_section(details, _n=name):
                # record whether the headline was already on stdout when
                # this section started — the contract under test
                events.append((_n, '"metric"' in buf.getvalue()))
                if _n in failing:
                    raise RuntimeError("section blew up")
                details[_n] = {"ok": True}

            monkeypatch.setattr(bench, name, fake_section)
        monkeypatch.setattr(
            bench, "_bench_wgl_hard",
            lambda details: events.append(("wgl_hard", True)),
        )
        # the real multi-chip capture (and its scale-out harness) is
        # covered by tests/test_multichip_capture.py — here it would
        # only burn suite budget inside a mocked-section contract test
        monkeypatch.setattr(
            bench, "_capture_multichip_if_present",
            lambda: events.append(("multichip", True)),
        )
        written = []
        monkeypatch.setattr(
            bench, "_write_details", lambda d: written.append(dict(d))
        )
        bench._run_once()
        return buf.getvalue(), events, written

    def test_headline_prints_before_every_secondary_section(
        self, monkeypatch
    ):
        out, events, written = self._run(monkeypatch)
        headline = json.loads(out.strip().splitlines()[0])
        assert headline["backend"] == "tpu" and not headline["fallback"]
        assert headline["value"] == 100.0 and headline["vs_baseline"] == 50.0
        secondary = [
            e for e in events if e[0] not in ("wgl_hard", "multichip")
        ]
        assert len(secondary) == 20
        assert all(seen for _, seen in secondary), (
            "a secondary section started before the headline printed: "
            f"{secondary}"
        )

    def test_details_persist_incrementally_per_section(self, monkeypatch):
        out, events, written = self._run(monkeypatch)
        # one write after the queue section, one after each of the
        # twenty secondary sections (a timeout after N sections leaves
        # N fresh), one final with the compile-cache evidence
        assert len(written) == 22
        assert "queue" in written[0] and "_bench_stream" not in written[0]
        assert "_bench_mutex" in written[-1]
        assert "entries_final" in written[-1]["compile_cache"]

    def test_failing_section_never_sinks_headline_or_later_writes(
        self, monkeypatch
    ):
        out, events, written = self._run(
            monkeypatch, failing={"_bench_elle"}
        )
        assert '"metric"' in out
        assert len(written) == 22  # the write still happens after a failure
        assert "_bench_elle" not in written[-1]
        assert "_bench_mutex" in written[-1]
