"""tools/soak.py — the long-soak entry point's fail-loud artifact
capture (round-7 review: a supervisor tee'd a file-not-found error from
a nonexistent driver path into ``store/`` evidence files; the driver
now owns capture, and a failed run must never produce an artifact)."""

import importlib.util
import os
import sys

import pytest

_SOAK_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "soak.py",
)
_spec = importlib.util.spec_from_file_location("soak_driver", _SOAK_PATH)
soak = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(soak)


def test_capture_writes_artifact_only_on_success(tmp_path):
    out = tmp_path / "evidence.txt"

    def run():
        print("verdict line")
        return 0

    assert soak.capture(str(out), run) == 0
    assert "verdict line" in out.read_text()
    assert not os.path.exists(str(out) + ".failed")


def test_capture_failure_never_creates_the_artifact(tmp_path):
    out = tmp_path / "evidence.txt"

    def run():
        print("partial log before the failure")
        return 3

    assert soak.capture(str(out), run) == 3
    assert not out.exists()
    failed = out.with_suffix(".txt.failed")
    assert "partial log" in failed.read_text()


def test_capture_non_int_return_is_a_failure(tmp_path):
    # a bare `return` from the run body must not reach sys.exit(None)
    # (process exit 0) while the log went to .failed — the silent
    # success-with-no-artifact shape capture() exists to prevent
    out = tmp_path / "evidence.txt"
    assert soak.capture(str(out), lambda: None) == 1
    assert not out.exists()
    assert (tmp_path / "evidence.txt.failed").exists()


def test_capture_exception_is_fail_loud(tmp_path):
    out = tmp_path / "evidence.txt"

    def run():
        raise RuntimeError("cluster exploded")

    assert soak.capture(str(out), run) == 1
    assert not out.exists()
    text = (tmp_path / "evidence.txt.failed").read_text()
    assert "cluster exploded" in text  # traceback lands in the log


def test_capture_bare_sys_exit_never_mints_an_artifact(tmp_path):
    # SystemExit(None) is rc 0 by shell convention, but inside capture
    # it is a library fatal path — treat as failure
    out = tmp_path / "evidence.txt"

    def run():
        sys.exit()

    assert soak.capture(str(out), run) == 1
    assert not out.exists()
    assert (tmp_path / "evidence.txt.failed").exists()


def test_capture_string_sys_exit_is_a_loud_failure(tmp_path):
    out = tmp_path / "evidence.txt"

    def run():
        sys.exit("broker never booted")

    assert soak.capture(str(out), run) == 1
    assert not out.exists()
    assert (tmp_path / "evidence.txt.failed").exists()
    assert not list(tmp_path.glob("*.tmp"))  # no orphaned capture file


def test_capture_bool_success_never_mints_an_artifact(tmp_path):
    # bool IS an int: sys.exit(False) / `return False` would pass an
    # isinstance(int) gate and exit 0 with the artifact minted
    out = tmp_path / "evidence.txt"
    assert soak.capture(str(out), lambda: False) == 1
    assert not out.exists()

    def run():
        sys.exit(False)

    assert soak.capture(str(out), run) == 1
    assert not out.exists()


def test_capture_reraises_keyboard_interrupt_after_cleanup(tmp_path):
    # the operator's Ctrl-C must propagate (interrupt exit status, so a
    # supervisor doesn't retry a stopped run) AND route the log to
    # .failed, never to the artifact
    out = tmp_path / "evidence.txt"

    def run():
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        soak.capture(str(out), run)
    assert not out.exists()
    assert (tmp_path / "evidence.txt.failed").exists()
    assert sys.stdout is not None and not isinstance(sys.stdout, soak._Tee)


def test_capture_artifact_is_world_readable(tmp_path):
    # mkstemp's 0600 must not survive into store/: evidence files are
    # read by CI/other users like every other committed artifact
    out = tmp_path / "evidence.txt"
    assert soak.capture(str(out), lambda: 0) == 0
    assert (out.stat().st_mode & 0o777) == 0o644


def test_capture_restores_std_streams(tmp_path):
    before = (sys.stdout, sys.stderr)
    soak.capture(str(tmp_path / "o.txt"), lambda: 0)
    assert (sys.stdout, sys.stderr) == before


def test_capture_rebinds_logging_off_the_dead_tee(tmp_path):
    # run_soak binds the root handler to the tee via basicConfig;
    # a daemon-thread log record arriving after capture() returns
    # must not hit the closed file
    import logging

    def run():
        logging.basicConfig(stream=sys.stdout, force=True)
        logging.getLogger("soak-test").info("inside the capture")
        return 0

    assert soak.capture(str(tmp_path / "o.txt"), run) == 0
    assert not any(
        isinstance(getattr(h, "stream", None), soak._Tee)
        for h in logging.root.handlers
    )
    logging.getLogger("soak-test").info("after the capture")  # no spray


def test_fenced_requires_mutex_workload():
    with pytest.raises(SystemExit) as e:
        soak.main(["--workload", "queue", "--fenced"])
    assert e.value.code == 2


def test_unfenced_mutex_cannot_expect_valid():
    # the documented hazard: an unfenced lock soaking green would be
    # luck, not evidence — the driver refuses the combination
    with pytest.raises(SystemExit) as e:
        soak.main(["--workload", "mutex", "--minutes", "1"])
    assert e.value.code == 2


def test_burnin_mutex_delegates_to_the_shared_driver(monkeypatch, tmp_path):
    # tools/burnin_mutex.py translates its argv onto soak.py's OWN
    # parser (one argument surface) and calls soak.main — the mutex
    # expectation wired in per mode, capture handled by the driver
    _bspec = importlib.util.spec_from_file_location(
        "burnin_mutex_driver",
        os.path.join(os.path.dirname(_SOAK_PATH), "burnin_mutex.py"),
    )
    burnin = importlib.util.module_from_spec(_bspec)
    _bspec.loader.exec_module(burnin)

    seen = {}

    def fake_run(args):
        seen.update(vars(args))
        print("fake run")
        return 0

    monkeypatch.setattr(burnin.soak, "run_soak", fake_run)
    out = tmp_path / "evidence.txt"
    assert burnin.main(["--fenced", "--out", str(out)]) == 0
    assert seen["workload"] == "mutex" and seen["fenced"] is True
    assert seen["expect"] == "valid"
    assert "fake run" in out.read_text()

    seen.clear()
    assert burnin.main([]) == 0
    assert seen["expect"] == "invalid" and seen["fenced"] is False
