"""Sharded checking on the virtual 8-device CPU mesh: results must be
identical to the single-device path for every mesh shape."""

import numpy as np
import pytest

from jepsen_tpu.checkers.queue_lin import queue_lin_tensor_check
from jepsen_tpu.checkers.total_queue import total_queue_tensor_check
from jepsen_tpu.history.encode import pack_histories
from jepsen_tpu.history.synth import SynthSpec, synth_batch
from jepsen_tpu.parallel import (
    checker_mesh,
    shard_packed,
    sharded_queue_lin,
    sharded_total_queue,
)


def _tree_equal(a, b):
    fa = {k: np.asarray(getattr(a, k)) for k in a.__dataclass_fields__}
    fb = {k: np.asarray(getattr(b, k)) for k in b.__dataclass_fields__}
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)


@pytest.fixture(scope="module")
def packed():
    batch = synth_batch(16, SynthSpec(n_ops=200), lost=1, duplicated=1)
    return pack_histories([sh.ops for sh in batch], length=512)


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_total_queue_matches(cpu_devices, packed, seq):
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = shard_packed(packed, mesh)
    _tree_equal(
        sharded_total_queue(sharded, mesh), total_queue_tensor_check(packed)
    )


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_queue_lin_matches(cpu_devices, packed, seq):
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = shard_packed(packed, mesh)
    _tree_equal(
        sharded_queue_lin(sharded, mesh), queue_lin_tensor_check(packed)
    )


def test_mesh_shapes(cpu_devices):
    m = checker_mesh(cpu_devices, seq=2)
    assert m.shape == {"hist": 4, "seq": 2}
    m1 = checker_mesh(cpu_devices)
    assert m1.shape == {"hist": 8, "seq": 1}


def test_sharded_stream_lin_matches_single_device(cpu_devices):
    from jepsen_tpu.checkers.stream_lin import (
        pack_stream_histories,
        stream_lin_tensor_check,
    )
    from jepsen_tpu.history.synth import StreamSynthSpec, synth_stream_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_stream_lin

    shs = synth_stream_batch(8, StreamSynthSpec(n_ops=60), lost=1)
    batch = pack_stream_histories([sh.ops for sh in shs])
    mesh = checker_mesh(cpu_devices)
    sharded = sharded_stream_lin(batch, mesh)
    local = stream_lin_tensor_check(batch)
    np.testing.assert_array_equal(
        np.asarray(sharded.valid), np.asarray(local.valid)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.lost), np.asarray(local.lost)
    )
    assert not np.asarray(sharded.valid).any()  # every history lost a value


def test_sharded_elle_matches_single_device(cpu_devices):
    from jepsen_tpu.checkers.elle import (
        elle_tensor_check,
        infer_txn_graph,
        pack_txn_graphs,
    )
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_elle

    shs = synth_elle_batch(4, ElleSynthSpec(n_txns=40))
    shs += synth_elle_batch(4, ElleSynthSpec(n_txns=40, seed=70), g2_cycle=1)
    batch = pack_txn_graphs([infer_txn_graph(sh.ops) for sh in shs])
    mesh = checker_mesh(cpu_devices)
    sharded = sharded_elle(batch, mesh)
    local = elle_tensor_check(batch)
    np.testing.assert_array_equal(
        np.asarray(sharded.valid), np.asarray(local.valid)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.g2), np.asarray(local.g2)
    )
    assert list(np.asarray(sharded.valid)) == [True] * 4 + [False] * 4


@pytest.mark.parametrize("seq", [2, 4])
def test_seq_parallel_stream_lin_matches(cpu_devices, seq):
    """The seq-sharded stream program (phase-A/B combines + the boundary
    ppermute for within-batch monotonicity) must equal the single-device
    check field-for-field, across every anomaly family."""
    from jepsen_tpu.checkers.stream_lin import (
        pack_stream_histories,
        stream_lin_tensor_check,
    )
    from jepsen_tpu.history.synth import StreamSynthSpec, synth_stream_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_stream_lin

    shs = synth_stream_batch(2, StreamSynthSpec(n_ops=80, seed=1))
    shs += synth_stream_batch(2, StreamSynthSpec(n_ops=80, seed=2), lost=1)
    shs += synth_stream_batch(
        2, StreamSynthSpec(n_ops=80, seed=3), duplicated=1
    )
    shs += synth_stream_batch(
        2, StreamSynthSpec(n_ops=80, seed=4, nonmonotonic=2)
    )
    batch = pack_stream_histories([sh.ops for sh in shs])
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = sharded_stream_lin(batch, mesh)
    local = stream_lin_tensor_check(batch)
    _tree_equal(sharded, local)


def test_seq_parallel_stream_boundary_pair(cpu_devices):
    """A nonmonotonic read-batch pair that straddles the seq shard cut is
    caught only by the ppermute boundary exchange — place it there
    deterministically and require the count to survive sharding."""
    from jepsen_tpu.checkers.stream_lin import (
        pack_stream_histories,
        stream_lin_tensor_check,
    )
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
    from jepsen_tpu.parallel import checker_mesh, sharded_stream_lin

    ops = []
    for v in range(2):
        inv = Op.invoke(OpF.APPEND, 0, v)
        ops += [inv, inv.complete(OpType.OK)]
    rinv = Op.invoke(OpF.READ, 1, 0)
    # offsets 1 then 0: a within-batch monotonicity violation whose two
    # exploded rows land at indices 5 and 6
    ops += [rinv, rinv.complete(OpType.OK, value=[[1, 1], [0, 0]])]
    h = reindex(ops)

    # L=12, seq=2 → the shard cut falls exactly between rows 5 and 6
    batch = pack_stream_histories([h] * 4, length=12)
    mesh = checker_mesh(cpu_devices, seq=2)
    sharded = sharded_stream_lin(batch, mesh)
    local = stream_lin_tensor_check(batch)
    import numpy as np

    np.testing.assert_array_equal(
        np.asarray(sharded.nonmonotonic_count), [1, 1, 1, 1]
    )
    _tree_equal(sharded, local)


@pytest.mark.parametrize("seq", [2, 4])
def test_seq_sharded_elle_matches(cpu_devices, seq):
    """With a seq axis, the elle adjacency matrices shard their column
    axis and GSPMD partitions the closure matmuls — verdicts must equal
    the unsharded check."""
    from jepsen_tpu.checkers.elle import (
        elle_tensor_check,
        infer_txn_graph,
        pack_txn_graphs,
    )
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_elle

    shs = synth_elle_batch(2, ElleSynthSpec(n_txns=100))
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=100, seed=5), g2_cycle=1)
    batch = pack_txn_graphs([infer_txn_graph(sh.ops) for sh in shs])
    mesh = checker_mesh(cpu_devices, seq=seq)
    _tree_equal(sharded_elle(batch, mesh), elle_tensor_check(batch))


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_elle_mops_matches(cpu_devices, seq):
    """The fused device-inference elle path over the mesh: micro-op cell
    columns shard over hist (seq=1 is the zero-communication fused
    program; seq>1 re-shards the inferred adjacency for the closure
    matmuls) — verdicts and anomaly masks must equal both the unsharded
    fused check and the host-inference oracle."""
    from jepsen_tpu.checkers.elle import (
        check_elle_cpu,
        elle_mops_check,
        pack_elle_mops,
    )
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_elle_mops

    shs = synth_elle_batch(2, ElleSynthSpec(n_txns=60))
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=60, seed=5), g2_cycle=1)
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=60, seed=9), g1a=1)
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=60, seed=13), g0_cycle=1)
    mops, metas = pack_elle_mops([sh.ops for sh in shs])
    assert not any(g.degenerate for g in metas)
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = sharded_elle_mops(mops, mesh)
    local, _ = elle_mops_check(mops)
    _tree_equal(sharded, local)
    oracle = [check_elle_cpu(sh.ops)["valid?"] for sh in shs]
    np.testing.assert_array_equal(np.asarray(sharded.valid), oracle)
    assert list(np.asarray(sharded.valid)) == [True] * 2 + [False] * 6


def test_long_history_seq_sharded(cpu_devices):
    """Long-context robustness: one ~33k-row packed batch sharded
    hist×seq checks correctly (the history-length-as-sequence-length
    story at a scale well past the bench's 1k rows)."""
    from jepsen_tpu.checkers.total_queue import check_total_queue_cpu

    shs = synth_batch(4, SynthSpec(n_ops=15_000, n_processes=7), lost=3)
    packed = pack_histories([s.ops for s in shs])
    assert packed.length >= 30_000
    mesh = checker_mesh(cpu_devices, seq=2)
    sharded = shard_packed(packed, mesh)
    tq = sharded_total_queue(sharded, mesh)
    ref = [check_total_queue_cpu(s.ops) for s in shs]
    import numpy as np

    np.testing.assert_array_equal(
        np.asarray(tq.valid), [r["valid?"] for r in ref]
    )
    assert int((np.asarray(tq.lost) > 0).sum()) == sum(
        r["lost-count"] for r in ref
    )


@pytest.mark.parametrize("seq", [1, 2])
def test_sharded_wgl_mutex_matches(cpu_devices, seq):
    """The mutex/WGL family over the mesh (data-parallel frontier
    search): verdicts must match the single-device engine, including a
    genuinely non-linearizable double-grant history."""
    from jepsen_tpu.checkers.wgl import (
        mutex_wgl_ops,
        pack_wgl_batch,
        wgl_tensor_check,
    )
    from jepsen_tpu.history.synth import MutexSynthSpec, synth_mutex_batch
    from jepsen_tpu.models.core import OwnedMutex
    from jepsen_tpu.parallel import sharded_wgl

    shs = synth_mutex_batch(8, MutexSynthSpec(n_ops=24)) + synth_mutex_batch(
        8, MutexSynthSpec(n_ops=24, double_grant=1, seed=99)
    )
    batch = pack_wgl_batch([mutex_wgl_ops(sh.ops) for sh in shs])
    ref_ok, ref_unknown = wgl_tensor_check(batch, (OwnedMutex, ()))

    mesh = checker_mesh(cpu_devices, seq=seq)
    ok, unknown = sharded_wgl(batch, mesh, (OwnedMutex, ()))
    ok, unknown = np.asarray(ok), np.asarray(unknown)
    # identical contract to wgl_tensor_check: cand_overflow already folded
    np.testing.assert_array_equal(ok, ref_ok)
    np.testing.assert_array_equal(unknown, ref_unknown)
    assert not ok.all()  # the injected double grant is refuted


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_packed_sharded_closure_differential(cpu_devices, seq):
    """ISSUE 18's headline kernel, differentially: the packed multi-chip
    closure (uint32 bitplanes, plane axis sharded over ``seq``,
    all_gather/psum fixpoint) must equal the forced-DENSE GSPMD closure
    AND the host oracle on the same batch — and it must actually LOWER
    (the ``mesh.closure_dense_fallbacks`` counter stays flat)."""
    from jepsen_tpu.checkers.elle import (
        check_elle_cpu,
        elle_tensor_check,
        infer_txn_graph,
        pack_txn_graphs,
    )
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.obs.metrics import REGISTRY
    from jepsen_tpu.parallel import checker_mesh, sharded_elle

    shs = synth_elle_batch(4, ElleSynthSpec(n_txns=100))
    shs += synth_elle_batch(4, ElleSynthSpec(n_txns=100, seed=5), g2_cycle=1)
    batch = pack_txn_graphs([infer_txn_graph(sh.ops) for sh in shs])
    # T=128 splits into whole uint32 words for seq ≤ 4 — the packed
    # path has no excuse not to lower here
    assert batch.ww.shape[-1] % (32 * seq) == 0
    mesh = checker_mesh(cpu_devices, seq=seq)
    before = REGISTRY.counter("mesh.closure_dense_fallbacks").value
    packed = sharded_elle(batch, mesh)  # default: packed multi-chip
    assert REGISTRY.counter("mesh.closure_dense_fallbacks").value == before
    dense = sharded_elle(batch, mesh, closure="dense")
    local = elle_tensor_check(batch)
    _tree_equal(packed, local)
    _tree_equal(dense, local)
    oracle = [check_elle_cpu(sh.ops)["valid?"] for sh in shs]
    np.testing.assert_array_equal(np.asarray(packed.valid), oracle)
    assert list(np.asarray(packed.valid)) == [True] * 4 + [False] * 4


def test_packed_refusal_seq8_t128_counts_dense_fallback(cpu_devices):
    """The honest DENSE pin replacement: at seq=8 a T=128 batch cannot
    split its ceil(T/32)=4 plane words across 8 devices, so the packed
    path REFUSES — the run falls back to the dense GSPMD closure, the
    ``mesh.closure_dense_fallbacks`` counter bumps, and the verdict is
    still identical to the unsharded check (never silently wrong, never
    silently slow)."""
    from jepsen_tpu.checkers.elle import (
        elle_tensor_check,
        infer_txn_graph,
        pack_txn_graphs,
    )
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.obs.metrics import REGISTRY
    from jepsen_tpu.parallel import checker_mesh, sharded_elle

    shs = synth_elle_batch(2, ElleSynthSpec(n_txns=100))
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=100, seed=5), g2_cycle=1)
    batch = pack_txn_graphs([infer_txn_graph(sh.ops) for sh in shs])
    assert batch.ww.shape[-1] == 128 and 128 % (32 * 8) != 0
    mesh = checker_mesh(cpu_devices, seq=8)
    before = REGISTRY.counter("mesh.closure_dense_fallbacks").value
    res = sharded_elle(batch, mesh)
    assert (
        REGISTRY.counter("mesh.closure_dense_fallbacks").value == before + 1
    )
    _tree_equal(res, elle_tensor_check(batch))
