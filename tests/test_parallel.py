"""Sharded checking on the virtual 8-device CPU mesh: results must be
identical to the single-device path for every mesh shape."""

import numpy as np
import pytest

from jepsen_tpu.checkers.queue_lin import queue_lin_tensor_check
from jepsen_tpu.checkers.total_queue import total_queue_tensor_check
from jepsen_tpu.history.encode import pack_histories
from jepsen_tpu.history.synth import SynthSpec, synth_batch
from jepsen_tpu.parallel import (
    checker_mesh,
    shard_packed,
    sharded_queue_lin,
    sharded_total_queue,
)


def _tree_equal(a, b):
    fa = {k: np.asarray(getattr(a, k)) for k in a.__dataclass_fields__}
    fb = {k: np.asarray(getattr(b, k)) for k in b.__dataclass_fields__}
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)


@pytest.fixture(scope="module")
def packed():
    batch = synth_batch(16, SynthSpec(n_ops=200), lost=1, duplicated=1)
    return pack_histories([sh.ops for sh in batch], length=512)


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_total_queue_matches(cpu_devices, packed, seq):
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = shard_packed(packed, mesh)
    _tree_equal(
        sharded_total_queue(sharded, mesh), total_queue_tensor_check(packed)
    )


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_queue_lin_matches(cpu_devices, packed, seq):
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = shard_packed(packed, mesh)
    _tree_equal(
        sharded_queue_lin(sharded, mesh), queue_lin_tensor_check(packed)
    )


def test_mesh_shapes(cpu_devices):
    m = checker_mesh(cpu_devices, seq=2)
    assert m.shape == {"hist": 4, "seq": 2}
    m1 = checker_mesh(cpu_devices)
    assert m1.shape == {"hist": 8, "seq": 1}


def test_sharded_stream_lin_matches_single_device(cpu_devices):
    from jepsen_tpu.checkers.stream_lin import (
        pack_stream_histories,
        stream_lin_tensor_check,
    )
    from jepsen_tpu.history.synth import StreamSynthSpec, synth_stream_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_stream_lin

    shs = synth_stream_batch(8, StreamSynthSpec(n_ops=60), lost=1)
    batch = pack_stream_histories([sh.ops for sh in shs])
    mesh = checker_mesh(cpu_devices)
    sharded = sharded_stream_lin(batch, mesh)
    local = stream_lin_tensor_check(batch)
    np.testing.assert_array_equal(
        np.asarray(sharded.valid), np.asarray(local.valid)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.lost), np.asarray(local.lost)
    )
    assert not np.asarray(sharded.valid).any()  # every history lost a value


def test_sharded_elle_matches_single_device(cpu_devices):
    from jepsen_tpu.checkers.elle import (
        elle_tensor_check,
        infer_txn_graph,
        pack_txn_graphs,
    )
    from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch
    from jepsen_tpu.parallel import checker_mesh, sharded_elle

    shs = synth_elle_batch(4, ElleSynthSpec(n_txns=40))
    shs += synth_elle_batch(4, ElleSynthSpec(n_txns=40, seed=70), g2_cycle=1)
    batch = pack_txn_graphs([infer_txn_graph(sh.ops) for sh in shs])
    mesh = checker_mesh(cpu_devices)
    sharded = sharded_elle(batch, mesh)
    local = elle_tensor_check(batch)
    np.testing.assert_array_equal(
        np.asarray(sharded.valid), np.asarray(local.valid)
    )
    np.testing.assert_array_equal(
        np.asarray(sharded.g2), np.asarray(local.g2)
    )
    assert list(np.asarray(sharded.valid)) == [True] * 4 + [False] * 4
