"""Sharded checking on the virtual 8-device CPU mesh: results must be
identical to the single-device path for every mesh shape."""

import numpy as np
import pytest

from jepsen_tpu.checkers.queue_lin import queue_lin_tensor_check
from jepsen_tpu.checkers.total_queue import total_queue_tensor_check
from jepsen_tpu.history.encode import pack_histories
from jepsen_tpu.history.synth import SynthSpec, synth_batch
from jepsen_tpu.parallel import (
    checker_mesh,
    shard_packed,
    sharded_queue_lin,
    sharded_total_queue,
)


def _tree_equal(a, b):
    fa = {k: np.asarray(getattr(a, k)) for k in a.__dataclass_fields__}
    fb = {k: np.asarray(getattr(b, k)) for k in b.__dataclass_fields__}
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)


@pytest.fixture(scope="module")
def packed():
    batch = synth_batch(16, SynthSpec(n_ops=200), lost=1, duplicated=1)
    return pack_histories([sh.ops for sh in batch], length=512)


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_total_queue_matches(cpu_devices, packed, seq):
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = shard_packed(packed, mesh)
    _tree_equal(
        sharded_total_queue(sharded, mesh), total_queue_tensor_check(packed)
    )


@pytest.mark.parametrize("seq", [1, 2, 4])
def test_sharded_queue_lin_matches(cpu_devices, packed, seq):
    mesh = checker_mesh(cpu_devices, seq=seq)
    sharded = shard_packed(packed, mesh)
    _tree_equal(
        sharded_queue_lin(sharded, mesh), queue_lin_tensor_check(packed)
    )


def test_mesh_shapes(cpu_devices):
    m = checker_mesh(cpu_devices, seq=2)
    assert m.shape == {"hist": 4, "seq": 2}
    m1 = checker_mesh(cpu_devices)
    assert m1.shape == {"hist": 8, "seq": 1}
