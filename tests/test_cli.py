"""CLI: check / bench-check / synth subcommands end-to-end."""

import json

import pytest

from jepsen_tpu.cli.main import GOOD_BANNER, INVALID_BANNER, main
from jepsen_tpu.history.store import Store
from jepsen_tpu.history.synth import SynthSpec, synth_history


@pytest.fixture()
def run_dir(tmp_path):
    def make(**anomalies):
        sh = synth_history(SynthSpec(n_ops=200, seed=31, **anomalies))
        st = Store(tmp_path / "store")
        d = st.run_dir("t")
        st.save_history(d, sh.ops)
        return d

    return make


def test_check_valid_run(run_dir, capsys):
    d = run_dir()
    rc = main(["check", str(d), "--checker", "tpu"])
    out = capsys.readouterr().out
    assert rc == 0
    assert GOOD_BANNER in out
    assert (d / "results.json").is_file()
    saved = json.loads((d / "results.json").read_text())
    assert saved["valid?"] and saved["queue"]["valid?"]


def test_check_invalid_run_exit_code_and_banner(run_dir, capsys):
    d = run_dir(lost=2)
    rc = main(["check", str(d), "--checker", "cpu"])
    out = capsys.readouterr().out
    assert rc == 1
    assert INVALID_BANNER in out
    assert json.loads((d / "results.json").read_text())["queue"]["lost-count"] == 2


def test_check_resolves_store_root(run_dir, capsys):
    d = run_dir()
    rc = main(["check", str(d.parent.parent)])  # store root via latest link
    assert rc == 0


def test_check_missing_path(tmp_path, capsys):
    rc = main(["check", str(tmp_path / "nope")])
    assert rc == 2
    assert "error:" in capsys.readouterr().err


def test_bench_check_synthetic(capsys):
    rc = main(["bench-check", "--count", "8", "--ops", "60"])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    stats = json.loads(line)
    assert stats["histories"] == 8
    assert stats["invalid"] >= 1  # bench injects one lost value per history
    assert stats["histories_per_sec"] > 0


def test_synth_then_bench_on_store(tmp_path, capsys):
    rc = main(
        ["synth", "--store", str(tmp_path), "--count", "4", "--ops", "50"]
    )
    assert rc == 0
    rc = main(["bench-check", "--histories", str(tmp_path)])
    assert rc == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["histories"] == 4


def test_stream_workload_end_to_end(tmp_path, capsys):
    rc = main(
        [
            "synth", "--store", str(tmp_path), "--workload", "stream",
            "--count", "2", "--ops", "80", "--lost", "1",
        ]
    )
    assert rc == 0
    runs = sorted((tmp_path / "synth").iterdir())
    rc = main(["check", str(runs[0])])  # workload auto-detected
    out = capsys.readouterr().out
    assert rc == 1 and INVALID_BANNER in out
    rc = main(["bench-check", "--histories", str(tmp_path)])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and stats["histories"] == 2 and stats["invalid"] == 2


def test_elle_workload_end_to_end(tmp_path, capsys):
    rc = main(
        [
            "synth", "--store", str(tmp_path), "--workload", "elle",
            "--count", "2", "--ops", "80", "--g2-cycle", "1",
        ]
    )
    assert rc == 0
    runs = sorted((tmp_path / "synth").iterdir())
    rc = main(["check", str(runs[0])])
    out = capsys.readouterr().out
    assert rc == 1 and INVALID_BANNER in out
    saved = json.loads((runs[0] / "results.json").read_text())
    assert saved["elle"]["G2-count"] >= 2
    rc = main(["bench-check", "--histories", str(tmp_path)])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and stats["histories"] == 2 and stats["invalid"] == 2


def test_bench_check_mixed_store_filters_majority(tmp_path, capsys):
    main(["synth", "--store", str(tmp_path), "--count", "3", "--ops", "40"])
    main(
        [
            "synth", "--store", str(tmp_path), "--workload", "stream",
            "--count", "1", "--ops", "40",
        ]
    )
    rc = main(["bench-check", "--histories", str(tmp_path)])
    err = capsys.readouterr()
    stats = json.loads(err.out.strip().splitlines()[-1])
    assert rc == 0 and stats["histories"] == 3  # queue majority wins
    assert "mixed store" in err.err


def test_bench_check_elle_counts_host_anomalies(tmp_path, capsys):
    # G1a is inferred host-side (no cycle): bench must still count it
    main(
        [
            "synth", "--store", str(tmp_path), "--workload", "elle",
            "--count", "2", "--ops", "60", "--g1a", "1",
        ]
    )
    rc = main(["bench-check", "--histories", str(tmp_path)])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and stats["invalid"] == 2


def test_synth_and_bench_check_mutex(tmp_path, capsys):
    """The mutex family has the full synth → store → check → bench-check
    pipeline like every other workload (batched WGL tensor search)."""
    store = tmp_path / "s"
    rc = main(
        [
            "synth", "--workload", "mutex", "--count", "2", "--ops", "50",
            "--double-grant", "1", "--store", str(store),
        ]
    )
    assert rc == 0
    rc = main(["check", "--checker", "cpu", str(store)])
    out = capsys.readouterr().out
    # the refutation verdict, not just a nonzero exit
    assert rc == 1 and '"valid?": false' in out and "Analysis invalid" in out
    rc = main(["bench-check", "--histories", str(store)])
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, stats
    assert stats["histories"] == 2 and stats["invalid"] >= 1
    assert stats["unknown"] == 0


def test_live_check_flag_reports_and_persists(tmp_path, capsys):
    """--live-check attaches the workload's monitor, prints the summary
    line, and persists live.json beside results.json."""
    rc = main(
        [
            "test", "--db", "sim", "--workload", "queue", "--live-check",
            "--time-limit", "1", "--rate", "100",
            "--recovery-sleep", "0.1",
            "--store", str(tmp_path / "s"),
        ]
    )
    assert rc == 0
    err = capsys.readouterr().err
    assert "live monitor (live-total-queue)" in err
    live = list((tmp_path / "s").glob("**/live.json"))
    assert len(live) == 1
    data = json.loads(live[0].read_text())
    assert data["monitor"] == "live-total-queue"
    assert data["violation-so-far"] is False


def test_db_local_dress_rehearsal(tmp_path, capsys):
    """`test --db local`: the full --db rabbitmq assembly against local
    mini-broker OS processes, straight from the CLI (the operator-facing
    dress rehearsal surface)."""
    rc = main([
        "test", "--db", "local", "--workload", "queue",
        "--time-limit", "2", "--rate", "100",
        "--time-before-partition", "0.5", "--partition-duration", "0.8",
        "--recovery-sleep", "0.6", "--publish-confirm-timeout", "1500",
        "--concurrency", "3", "--checker", "cpu",
        "--store", str(tmp_path / "s"),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert GOOD_BANNER in out


def test_matrix_db_local_one_config(tmp_path, capsys):
    """The CI matrix against the local process cluster: config #1 runs
    the full rabbitmq assembly on fresh broker OS processes and passes
    the drained-to-zero cross-check."""
    rc = main([
        "matrix", "--db", "local", "--limit", "1",
        "--time-scale", "0.02", "--rate", "120", "--checker", "cpu",
        "--store", str(tmp_path / "s"),
    ])
    captured = capsys.readouterr()
    assert rc == 0
    summary = json.loads(captured.out)
    assert summary[0]["status"] == "valid"
    assert GOOD_BANNER in captured.err  # matrix banner rides stderr


def test_bench_check_workers_mixed_store_filters(tmp_path, capsys):
    """--workers on a stored mixed store applies the same family filter
    as the serial path (other families must not be checked as queue),
    and reports produce_s so pack_s keeps its serial meaning."""
    main(["synth", "--count", "3", "--ops", "60", "--store",
          str(tmp_path / "s")])
    main(["synth", "--workload", "stream", "--count", "2", "--ops", "40",
          "--store", str(tmp_path / "s")])
    capsys.readouterr()
    rc = main([
        "bench-check", "--histories", str(tmp_path / "s"),
        "--workload", "queue", "--workers", "2",
    ])
    captured = capsys.readouterr()
    assert rc == 0
    stats = json.loads(captured.out.strip().splitlines()[-1])
    assert stats["histories"] == 3  # the 2 stream runs were filtered out
    # on a multi-core host the parallel path reports its worker phase as
    # produce_s (so pack_s keeps its serial meaning); on a core-starved
    # host the CLI caps workers and falls back to the serial path, whose
    # family filter the assertion above just exercised
    assert "produce_s" in stats or "capped to" in captured.err


def test_reference_ci_parameter_strings_parse_verbatim():
    """Drop-in contract (VERDICT r3 missing #3): every parameter string
    from the reference's CI matrix (ci/jepsen-test.sh:93-107, including
    the 'random-partition-halves' spelling and '--dead-letter true')
    parses against `jepsen_tpu test` unchanged, and the partition value
    resolves to a real nemesis strategy."""
    import shlex

    from jepsen_tpu.cli.main import build_parser
    from jepsen_tpu.control.nemesis import STRATEGIES

    ci_lines = [
        "--time-limit 180 --time-before-partition 20 --partition-duration 30 --network-partition random-partition-halves --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 30 --network-partition partition-halves --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 30 --network-partition partition-majorities-ring --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 30 --network-partition partition-random-node --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition random-partition-halves --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition random-partition-halves --net-ticktime 15 --consumer-type mixed --quorum-initial-group-size 3",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition partition-halves --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition partition-majorities-ring --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition partition-random-node --net-ticktime 15 --consumer-type mixed",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition partition-random-node --net-ticktime 15 --consumer-type asynchronous",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition partition-random-node --net-ticktime 15 --consumer-type asynchronous --quorum-initial-group-size 3",
        "--time-limit 180 --time-before-partition 20 --partition-duration 10 --network-partition partition-random-node --net-ticktime 15 --consumer-type polling",
        "--time-limit 180 --time-before-partition 20 --partition-duration 30 --network-partition random-partition-halves --net-ticktime 15 --consumer-type mixed --dead-letter true",
        "--time-limit 180 --time-before-partition 20 --partition-duration 30 --network-partition partition-halves --net-ticktime 15 --consumer-type mixed --dead-letter true",
    ]
    parser = build_parser()
    for line in ci_lines:
        args = parser.parse_args(["test", *shlex.split(line)])
        assert args.network_partition in STRATEGIES, line
        assert args.time_limit == 180
        if "--dead-letter true" in line:
            assert args.dead_letter is True
    # both spellings of the shuffled-halves strategy are the same code
    assert (
        STRATEGIES["random-partition-halves"]
        is STRATEGIES["partition-random-halves"]
    )
    # and the reference's -r short flag for rate parses
    a = parser.parse_args(["test", "-r", "75"])
    assert a.rate == 75.0
    # bare --dead-letter (no value) still means True; absent means False
    assert parser.parse_args(["test", "--dead-letter"]).dead_letter is True
    assert parser.parse_args(["test"]).dead_letter is False


def test_bench_check_elle_and_stream_native_matches_python(
    tmp_path, capsys, monkeypatch
):
    """The store bench routes elle/stream files through the native
    substrates (elle_graph_file / stream_rows_file); verdict counts must
    be identical with the native path disabled (JEPSEN_TPU_NO_FASTPACK),
    i.e. the fast path changes the wall clock, never the verdict."""
    main(
        [
            "synth", "--store", str(tmp_path), "--workload", "elle",
            "--count", "3", "--ops", "60", "--g1c-cycle", "1",
        ]
    )
    main(
        [
            "synth", "--store", str(tmp_path), "--workload", "stream",
            "--count", "2", "--ops", "60", "--divergent", "1",
        ]
    )
    capsys.readouterr()
    out = {}
    for label, env in (("native", None), ("python", "1")):
        if env:
            monkeypatch.setenv("JEPSEN_TPU_NO_FASTPACK", env)
        for wl in ("elle", "stream"):
            rc = main(
                ["bench-check", "--histories", str(tmp_path),
                 "--workload", wl]
            )
            stats = json.loads(
                capsys.readouterr().out.strip().splitlines()[-1]
            )
            assert rc == 0
            out[(label, wl)] = (stats["histories"], stats["invalid"])
    assert out[("native", "elle")] == out[("python", "elle")] == (3, 3)
    assert out[("native", "stream")] == out[("python", "stream")] == (2, 2)


def test_fenced_flag_parses_and_defaults_off():
    from jepsen_tpu.cli.main import build_parser

    p = build_parser()
    ns = p.parse_args(["test", "--workload", "mutex", "--fenced"])
    assert ns.fenced is True
    ns = p.parse_args(["test", "--workload", "mutex"])
    assert ns.fenced is False
