"""The run-report subsystem (ISSUE 11): device windowed stats, the
deterministic HTML/SVG artifacts, forensics, the cross-run index, and
the CLI/obs wiring.

Determinism and well-formedness contracts pinned here:

- byte-stable artifacts given a fixed store (no wall-clock, no
  dict-order leakage);
- every emitted artifact parses as XML (``xml.etree.ElementTree`` —
  unclosed tags and HTML-only entities cannot ship);
- device windowed percentiles within 2% of host ``np.percentile``
  (the PR-9 sketch bar).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET
from pathlib import Path

import numpy as np
import pytest

from jepsen_tpu.checkers.protocol import compose
from jepsen_tpu.checkers.total_queue import TotalQueue
from jepsen_tpu.history.ops import NEMESIS_PROCESS, Op, OpF, OpType
from jepsen_tpu.history.rows import _rows_for
from jepsen_tpu.history.store import Store
from jepsen_tpu.history.synth import SynthSpec, synth_batch
from jepsen_tpu.report.forensics import (
    flag_ops,
    render_forensics,
    violating_values,
)
from jepsen_tpu.report.index import build_store_index, run_dirs
from jepsen_tpu.report.perfstats import (
    ALPHA,
    QUANTILES,
    WindowedPerf,
    quantiles_from_hist,
    sketch_from_hist,
    windowed_stats,
    windowed_stats_rows,
)
from jepsen_tpu.report.render import (
    nemesis_windows,
    render_run_report,
)


def _parse_xml(path: Path) -> ET.Element:
    return ET.fromstring(Path(path).read_text())


def _rows_with_lats(lats: np.ndarray) -> np.ndarray:
    """A synthetic [n, 8] row matrix of OK completions carrying the
    given integer-ms latencies."""
    n = len(lats)
    rows = np.zeros((n, 8), np.int32)
    rows[:, 0] = np.arange(n)
    rows[:, 1] = np.arange(n) % 5
    rows[:, 2] = int(OpType.OK)
    rows[:, 3] = int(OpF.ENQUEUE)
    rows[:, 4] = 1
    rows[:, 5] = np.arange(n) % 60_000
    rows[:, 6] = lats
    rows[:, 7] = 1
    return rows


class TestWindowedStats:
    @pytest.mark.parametrize("dist", ["uniform", "lognormal", "pareto"])
    def test_quantiles_within_2pct_of_numpy(self, dist):
        """The acceptance differential (the PR-9 sketch bar): device
        whole-history percentiles vs plain host ``np.percentile`` on
        wide continuous-ish distributions."""
        rng = np.random.default_rng(7)
        n = 4000
        if dist == "uniform":
            lats = rng.integers(1, 2000, n)
        elif dist == "lognormal":
            lats = np.maximum(rng.lognormal(3, 1, n).astype(int), 1)
        else:
            lats = np.maximum((rng.pareto(1.5, n) * 10).astype(int), 1)
        t = windowed_stats_rows([_rows_with_lats(lats)])
        got = quantiles_from_hist(np.asarray(t.hist)[0])
        for q, g in zip(QUANTILES, got):
            want = float(np.percentile(lats, q * 100))
            assert abs(g - want) / want <= 0.02, (dist, q, g, want)

    def test_rank_semantics_match_numpy_lower(self):
        """On tiny discrete samples the kernel implements the sketch's
        rank pick — element at floor(q*(n-1)), numpy's
        ``method='lower'`` — within the bucket accuracy ALPHA."""
        lats = np.array([0, 0, 1, 1, 1, 2, 4, 4, 9, 100], np.int64)
        t = windowed_stats_rows([_rows_with_lats(lats)])
        got = quantiles_from_hist(np.asarray(t.hist)[0])
        for q, g in zip(QUANTILES, got):
            want = float(np.percentile(lats, q * 100, method="lower"))
            if want == 0.0:
                assert g == 0.0
            else:
                assert abs(g - want) / want <= ALPHA + 1e-6

    def test_rates_count_completions_once(self):
        sh = synth_batch(1, SynthSpec(n_ops=200), lost=1)[0]
        from jepsen_tpu.history.encode import pack_histories

        t = windowed_stats(pack_histories([sh.ops]))
        by_type = np.asarray(t.rates)[0].sum(axis=(0, 1))
        want = {"ok": 0, "fail": 0, "info": 0}
        open_ops = 0
        for op in sh.ops:
            if op.process == NEMESIS_PROCESS:
                continue
            if op.type == OpType.OK:
                want["ok"] += 1
            elif op.type == OpType.FAIL:
                want["fail"] += 1
            elif op.type == OpType.INFO:
                want["info"] += 1
        assert by_type.tolist() == [
            want["ok"], want["fail"], want["info"],
        ], (by_type, want, open_ops)

    def test_hist_bridges_into_obs_sketch(self):
        """Device histograms merge with live PR-9 sketches (same bucket
        geometry) — merged quantiles match the combined population."""
        from jepsen_tpu.obs.metrics import QuantileSketch

        rng = np.random.default_rng(3)
        a = np.maximum(rng.lognormal(3, 1, 1500).astype(int), 1)
        b = np.maximum(rng.lognormal(4, 0.5, 1500).astype(int), 1)
        t = windowed_stats_rows([_rows_with_lats(a)])
        dev = sketch_from_hist(np.asarray(t.hist)[0])
        live = QuantileSketch()
        for x in b:
            live.add(float(x))
        live.merge(dev)
        assert live.count == len(a) + len(b)
        both = np.concatenate([a, b])
        for q in (0.5, 0.99):
            want = float(np.percentile(both, q * 100))
            assert abs(live.quantile(q) - want) / want <= 0.02

    def test_sketch_bridge_refuses_foreign_alpha(self):
        with pytest.raises(ValueError, match="alpha"):
            sketch_from_hist(np.zeros(8, np.int64), alpha=0.05)

    def test_windowed_perf_composes_like_checker_compose(self):
        sh = synth_batch(1, SynthSpec(n_ops=120))[0]
        checker = compose(
            {"perf": WindowedPerf(), "queue": TotalQueue(backend="cpu")}
        )
        res = checker.check({}, sh.ops)
        assert res["valid?"] is True
        assert res["perf"]["valid?"] is True
        assert res["perf"]["completions"] > 0
        assert "latency-ms" in res["perf"]


@pytest.fixture(scope="module")
def fixed_store(tmp_path_factory):
    """A fixed two-run store (one green, one red) with rendered
    reports — module-scoped so the determinism/index/XML tests share
    one render."""
    root = tmp_path_factory.mktemp("fixed_store")
    st = Store(root)
    checker = compose({"queue": TotalQueue(backend="cpu")})
    dirs = []
    for i, lost in enumerate((0, 1)):
        sh = synth_batch(1, SynthSpec(n_ops=160, seed=11 + i), lost=lost)[0]
        d = st.run_dir("fixed", f"run-{i}")
        st.save_history(d, sh.ops)
        res = checker.check({}, sh.ops)
        st.save_results(d, res)
        render_run_report(d, history=sh.ops, results=res)
        dirs.append(d)
    return root, dirs


class TestRenderedArtifacts:
    def test_every_artifact_is_well_formed_xml(self, fixed_store):
        root, dirs = fixed_store
        seen = 0
        for d in dirs:
            for p in d.glob("*.html"):
                _parse_xml(p)
                seen += 1
        assert seen >= 3  # 2x report+timeline, 1x forensics

    def test_byte_stable_given_fixed_store(self, fixed_store):
        root, dirs = fixed_store
        before = {
            p: p.read_bytes()
            for d in dirs
            for p in list(d.glob("*.html")) + [d / "report.json"]
        }
        for d in dirs:
            render_run_report(d)
        for p, body in before.items():
            assert p.read_bytes() == body, f"{p} changed across renders"

    def test_report_json_headline(self, fixed_store):
        root, (green, red) = fixed_store
        s = json.loads((green / "report.json").read_text())
        assert s["valid?"] is True
        assert s["ops"] > 0
        assert "latency-ms" in s
        s2 = json.loads((red / "report.json").read_text())
        assert s2["valid?"] is False

    def test_invalid_run_gets_forensics_valid_does_not(self, fixed_store):
        root, (green, red) = fixed_store
        assert not (green / "forensics.html").exists()
        assert (red / "forensics.html").is_file()

    def test_nemesis_windows_shade_the_panels(self, tmp_path):
        """A history with real nemesis START/STOP ops renders shaded
        windows + the window table, on the op clock."""
        sh = synth_batch(1, SynthSpec(n_ops=120))[0]
        ops = list(sh.ops)
        t0, t1 = 10_000_000, 400_000_000
        idx = len(ops)
        ops += [
            Op(OpType.INVOKE, OpF.START, NEMESIS_PROCESS, None, t0, idx),
            Op(OpType.INFO, OpF.START, NEMESIS_PROCESS,
               "partition-halves", t0 + 1000, idx + 1),
            Op(OpType.INVOKE, OpF.STOP, NEMESIS_PROCESS, None, t1, idx + 2),
            Op(OpType.INFO, OpF.STOP, NEMESIS_PROCESS, "healed",
               t1 + 1000, idx + 3),
        ]
        wins = nemesis_windows(ops)
        assert len(wins) == 1
        w0, w1, label = wins[0]
        assert label == "partition-halves"
        assert w0 == t0 + 1000 and w1 == t1 + 1000
        d = tmp_path / "run"
        d.mkdir()
        Store(tmp_path).save_history(d, ops)
        paths = render_run_report(
            d, history=ops, results={"valid?": True}
        )
        html = Path(paths["report"]).read_text()
        assert "partition-halves" in html
        assert "nemesis windows" in html
        _parse_xml(Path(paths["report"]))

    def test_all_ops_at_t0_render_without_crash(self, tmp_path):
        """A history whose only timestamps sit at t=0 ns (hand-built /
        imported) must render, not divide by zero (review finding)."""
        from jepsen_tpu.report.render import render_timeline

        ops = [
            Op(OpType.INVOKE, OpF.ENQUEUE, 0, 1, 0, 0),
            Op(OpType.OK, OpF.ENQUEUE, 0, 1, 0, 1),
        ]
        p = render_timeline(ops, tmp_path / "t.html")
        _parse_xml(p)

    def test_unclosed_window_closes_at_history_end(self):
        ops = [
            Op(OpType.INVOKE, OpF.START, NEMESIS_PROCESS, None, 5, 0),
            Op(OpType.INFO, OpF.START, NEMESIS_PROCESS, "kill", 10, 1),
            Op(OpType.INVOKE, OpF.ENQUEUE, 0, 1, 50, 2),
        ]
        wins = nemesis_windows(ops)
        assert wins == [(10, 50, "kill")]


class TestForensics:
    def test_lost_values_flagged(self, fixed_store):
        root, (_, red) = fixed_store
        results = json.loads((red / "results.json").read_text())
        lost = set(results["queue"]["lost"])
        assert lost
        html = (red / "forensics.html").read_text()
        assert "lost" in html
        # the flagged rows carry the highlight style
        assert "background:#ffe0e0" in html
        history = Store(root).load_history(red)
        flagged = flag_ops(history, violating_values(results))
        assert flagged, "no ops flagged for a lost value"
        flagged_vals = {
            v
            for i in flagged
            for v in ([history[i].value]
                      if not isinstance(history[i].value, (list, tuple))
                      else history[i].value)
        }
        assert lost & {v for v in flagged_vals if isinstance(v, int)}

    def test_valid_run_refuses_a_page(self, fixed_store):
        root, (green, _) = fixed_store
        assert render_forensics(green) is None

    def test_pcomp_refuted_class_flagged(self, tmp_path):
        """A mutex pcomp result naming its refuted projection class
        flags the ops touching that class."""
        ops = [
            Op(OpType.INVOKE, OpF.ACQUIRE, 0, 3, 10, 0),
            Op(OpType.OK, OpF.ACQUIRE, 0, 3, 20, 1),
            Op(OpType.INVOKE, OpF.ACQUIRE, 1, 4, 30, 2),
            Op(OpType.OK, OpF.ACQUIRE, 1, 4, 40, 3),
        ]
        results = {
            "valid?": False,
            "mutex": {"valid?": False, "invalid-class": ["value", 3]},
        }
        d = tmp_path / "run"
        d.mkdir()
        Store(tmp_path).save_history(d, ops)
        p = render_forensics(d, history=ops, results=results)
        assert p is not None
        _parse_xml(p)
        flagged = flag_ops(ops, violating_values(results))
        assert set(flagged) == {0, 1}

    def test_repro_link_lands_on_the_page(self, fixed_store, tmp_path):
        root, (_, red) = fixed_store
        history = Store(root).load_history(red)
        out = tmp_path / "red.forensics.html"
        p = render_forensics(
            red,
            history=history,
            repro_path="fuzz_repro_x.py",
            out_path=out,
        )
        assert p == out
        html = out.read_text()
        assert "fuzz_repro_x.py" in html
        _parse_xml(out)


class TestDegradedProvenance:
    """PR 13: a degraded elastic check must SHOW in the artifacts — a
    report that renders a degraded verdict like a clean one is the
    silent-fold failure mode the elastic contract forbids."""

    _DEG = {
        "elastic": True,
        "procs": 3,
        "effective_procs": 2,
        "dead_workers": [{"pid": 1, "rc": 42, "log_tail": ""}],
        "requeued_stripes": [
            {"stripe": 1, "retries": 1, "from_pid": 1,
             "completed_by": 0, "recovery_s": 0.41}
        ],
        "quarantined_stripes": [],
        "wedged_killed": [],
        "quarantined_histories": 2,
    }

    def _run(self, tmp_path, results):
        sh = synth_batch(1, SynthSpec(n_ops=80, seed=23))[0]
        d = tmp_path / "run"
        d.mkdir()
        Store(tmp_path).save_history(d, sh.ops)
        (d / "results.json").write_text(json.dumps(results))
        return d, sh.ops

    def test_degraded_row_renders_in_report(self, tmp_path):
        results = {
            "valid?": "unknown",
            "queue": {"valid?": True},
            "degraded": self._DEG,
        }
        d, ops = self._run(tmp_path, results)
        render_run_report(d, history=ops, results=results)
        html = (d / "report.html").read_text()
        assert "DEGRADED" in html
        assert "worker 1 (rc=42)" in html
        assert "quarantined histories: 2" in html
        _parse_xml(d / "report.html")
        s = json.loads((d / "report.json").read_text())
        assert s["degraded"]["dead_workers"] == 1
        assert s["degraded"]["effective_procs"] == 2
        assert s["degraded"]["quarantined_histories"] == 2

    def test_inactive_degraded_renders_nothing(self, tmp_path):
        """The no-fault elastic run's provenance (everything empty)
        must NOT stamp a clean report as degraded."""
        deg = {
            **self._DEG,
            "effective_procs": 3,
            "dead_workers": [],
            "requeued_stripes": [],
            "quarantined_histories": 0,
        }
        results = {
            "valid?": True, "queue": {"valid?": True}, "degraded": deg,
        }
        d, ops = self._run(tmp_path, results)
        render_run_report(d, history=ops, results=results)
        html = (d / "report.html").read_text()
        assert "DEGRADED" not in html
        assert "degraded" not in json.loads(
            (d / "report.json").read_text()
        )

    def test_forensics_notes_nearby_quarantine(self, tmp_path):
        """An invalid verdict out of a quarantine-carrying batch gets
        the honesty note on the forensics page."""
        results = {
            "valid?": False,
            "queue": {"valid?": False, "lost": [3]},
            "degraded": self._DEG,
        }
        d, ops = self._run(tmp_path, results)
        p = render_forensics(d, history=ops, results=results)
        assert p is not None
        html = p.read_text()
        assert "quarantine nearby" in html
        assert "2 histories of the same degraded batch" in html
        _parse_xml(p)

    def test_forensics_notes_sub_checker_quarantine(self, tmp_path):
        results = {
            "valid?": False,
            "stream": {"valid?": False, "lost": [5]},
            "queue": {
                "valid?": "unknown",
                "quarantined": {"stage": "produce", "errors": ["boom"]},
            },
        }
        d, ops = self._run(tmp_path, results)
        p = render_forensics(d, history=ops, results=results)
        assert p is not None
        assert "quarantine evidence for THIS history" in p.read_text()


class TestStoreIndex:
    def test_index_rows_trend_and_links(self, fixed_store):
        root, dirs = fixed_store
        idx = build_store_index(root)
        assert idx == root / "index.html"
        _parse_xml(idx)
        html = idx.read_text()
        for d in dirs:
            assert str(d.relative_to(root)) in html
        assert "forensics" in html  # the red run's link
        assert "<svg" in html  # the trend sparkline
        assert "2 runs" in html

    def test_index_is_byte_stable(self, fixed_store):
        root, _ = fixed_store
        b1 = build_store_index(root).read_bytes()
        b2 = build_store_index(root).read_bytes()
        assert b1 == b2

    def test_symlinks_do_not_double_index(self, fixed_store):
        root, dirs = fixed_store
        st = Store(root)
        st.link_run("fixed", dirs[0])  # current/latest symlinks
        assert len(run_dirs(root)) == len(dirs)

    def test_empty_store_returns_none(self, tmp_path):
        assert build_store_index(tmp_path) is None

    def test_malformed_report_json_costs_one_cell_not_the_index(
        self, tmp_path
    ):
        """A hand-edited/foreign report.json with a non-numeric p50
        must not abort the whole index build (review finding)."""
        d = tmp_path / "runs" / "r0"
        d.mkdir(parents=True)
        (d / "results.json").write_text('{"valid?": true}')
        (d / "report.json").write_text(
            json.dumps({
                "run": "r0", "valid?": True, "ops": 3,
                "latency-ms": {"p50": "12ms", "p99": None},
            })
        )
        idx = build_store_index(tmp_path, render_missing=False)
        assert idx is not None
        _parse_xml(idx)
        assert "r0" in idx.read_text()


class TestRunnerDefaultOn:
    """``run`` writes the report by default, like jepsen's
    store/report; ``report=False`` opts out."""

    FAST = {
        "rate": 400.0,
        "time-limit": 0.8,
        "time-before-partition": 0.2,
        "partition-duration": 0.2,
        "recovery-sleep": 0.1,
    }

    def _run(self, tmp_path, report=True):
        from jepsen_tpu.control.runner import run_test
        from jepsen_tpu.suite import build_sim_test

        test, _ = build_sim_test(
            opts=self.FAST, store_root=str(tmp_path / "store"),
            checker_backend="cpu",
        )
        test.report = report
        return run_test(test)

    def test_report_rendered_by_default(self, tmp_path):
        run = self._run(tmp_path)
        assert (run.run_dir / "report.html").is_file()
        assert (run.run_dir / "timeline.html").is_file()
        assert (run.run_dir / "report.json").is_file()
        _parse_xml(run.run_dir / "report.html")
        # the run's results carry the device windowed-stats summary
        assert run.results["perf-windowed"]["valid?"] is True
        assert run.results["perf-windowed"]["completions"] > 0

    def test_no_report_opts_out(self, tmp_path):
        run = self._run(tmp_path, report=False)
        assert not (run.run_dir / "report.html").exists()


class TestCliWiring:
    def _store_with_run(self, tmp_path) -> Path:
        st = Store(tmp_path / "store")
        sh = synth_batch(1, SynthSpec(n_ops=100), lost=1)[0]
        d = st.run_dir("cli", "r0")
        st.save_history(d, sh.ops)
        return d

    def test_check_report_flag(self, tmp_path, capsys):
        from jepsen_tpu.cli.main import main

        d = self._store_with_run(tmp_path)
        rc = main(["check", str(d), "--checker", "cpu", "--report"])
        assert rc == 1  # lost value -> invalid
        assert (d / "report.html").is_file()
        assert (d / "forensics.html").is_file()

    def test_report_subcommand_builds_index(self, tmp_path, capsys):
        from jepsen_tpu.cli.main import main

        d = self._store_with_run(tmp_path)
        from jepsen_tpu.history.store import save_results

        save_results(d, {"valid?": True})
        rc = main(["report", str(tmp_path / "store")])
        assert rc == 0
        idx = tmp_path / "store" / "index.html"
        assert idx.is_file()
        assert (d / "report.html").is_file()

    def test_report_subcommand_single_run_dir(self, tmp_path, capsys):
        from jepsen_tpu.cli.main import main

        d = self._store_with_run(tmp_path)
        rc = main(["report", str(d)])
        assert rc == 0
        assert (d / "report.html").is_file()

    def test_report_subcommand_missing_dir(self, tmp_path):
        from jepsen_tpu.cli.main import main

        assert main(["report", str(tmp_path / "nope")]) == 2


class TestTraceKeepOnFailure:
    """ISSUE-11 satellite: ``jepsen-tpu trace`` discards the artifact
    on non-zero exit; ``--keep-on-failure`` keeps the recording at
    ``<out>.failed`` — never the artifact path."""

    def test_failure_discards_by_default(self, tmp_path):
        from jepsen_tpu.cli.main import main

        out = tmp_path / "t.json"
        rc = main(
            ["trace", "--out", str(out), "--",
             "check", str(tmp_path / "missing")]
        )
        assert rc == 2
        assert not out.exists()
        assert not Path(str(out) + ".failed").exists()

    def test_keep_on_failure_writes_failed_sibling(self, tmp_path):
        from jepsen_tpu.cli.main import main

        out = tmp_path / "t.json"
        rc = main(
            ["trace", "--out", str(out), "--keep-on-failure", "--",
             "check", str(tmp_path / "missing")]
        )
        assert rc == 2
        assert not out.exists(), "the artifact path must stay clean"
        failed = Path(str(out) + ".failed")
        assert failed.is_file()
        doc = json.loads(failed.read_text())
        assert "traceEvents" in doc

    def test_success_still_writes_the_artifact(self, tmp_path):
        from jepsen_tpu.cli.main import main

        st = Store(tmp_path / "store")
        d = st.run_dir("t", "r0")
        sh = synth_batch(1, SynthSpec(n_ops=60))[0]
        st.save_history(d, sh.ops)
        out = tmp_path / "t.json"
        rc = main(
            ["trace", "--out", str(out), "--keep-on-failure", "--",
             "check", str(d), "--checker", "cpu"]
        )
        assert rc == 0
        assert out.is_file()
        assert not Path(str(out) + ".failed").exists()


class TestObsSurface:
    def test_metrics_render_carries_trace_health(self):
        from jepsen_tpu.obs import trace
        from jepsen_tpu.obs.metrics import render_prometheus

        trace.enable(512)
        try:
            with trace.span("a", track="lane0"):
                pass
            out = render_prometheus()
        finally:
            trace.disable()
        assert "jepsen_tpu_trace_ring_occupancy" in out
        assert "jepsen_tpu_trace_spans_dropped_total" in out
        assert 'jepsen_tpu_trace_spans_total{track="lane0"} 1' in out

    def test_dropped_total_counts_ring_wrap(self):
        from jepsen_tpu.obs import trace
        from jepsen_tpu.obs.metrics import render_prometheus

        trace.enable(256)  # floor capacity
        try:
            for _ in range(300):
                trace.event("e")
            out = render_prometheus()
        finally:
            trace.disable()
        line = next(
            ln for ln in out.splitlines()
            if ln.startswith("jepsen_tpu_trace_spans_dropped_total")
        )
        assert int(line.split()[-1]) == 300 - 256

    def test_report_route_on_metrics_server(self, tmp_path):
        from jepsen_tpu.history.store import save_results
        from jepsen_tpu.obs.metrics import serve_metrics

        st = Store(tmp_path / "store")
        sh = synth_batch(1, SynthSpec(n_ops=80))[0]
        d = st.run_dir("svc", "r0")
        st.save_history(d, sh.ops)
        save_results(d, {"valid?": True})
        srv = serve_metrics("127.0.0.1", 0, store=str(tmp_path / "store"))
        srv.start_background()
        try:
            port = srv.server_address[1]
            url = f"http://127.0.0.1:{port}/report/svc/r0/report.html"
            body = urllib.request.urlopen(url, timeout=10).read()
            assert b"<svg" in body  # rendered on demand
            assert (d / "report.html").is_file()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/report/"
                    f"..%2f..%2fetc%2fpasswd",
                    timeout=10,
                )
            assert ei.value.code in (403, 404)
            # a run-DIR request redirects to its report.html off the
            # QUERY-STRIPPED path (a raw-path redirect looped forever
            # on any ?query URL — review finding, pinned)
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/report/svc/r0?x=1",
                timeout=10,
            ).read()
            assert b"<svg" in body
            # the store ROOT is not a run dir: 404 with advice until an
            # index.html exists, then a redirect to it — never a 500
            # from rendering a report of the store root
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/report/", timeout=10
                )
            assert ei.value.code == 404
            build_store_index(tmp_path / "store")
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/report/", timeout=10
            ).read()
            assert b"run index" in body
        finally:
            srv.shutdown()
            srv.server_close()
