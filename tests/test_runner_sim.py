"""End-to-end: the full test lifecycle against the in-process simulator.

This is the framework's answer to the reference's cluster tests
(``rabbitmq_test.clj:46-77``) without needing a broker: run the real
generator program, real clients, real nemesis, record a real history, and
check it on the TPU path.  Timescales are compressed (seconds → tens of
milliseconds) so the suite stays fast.
"""

import pytest

from jepsen_tpu.control.runner import run_test
from jepsen_tpu.history.ops import OpF, OpType
from jepsen_tpu.suite import build_sim_test

FAST_OPTS = {
    "rate": 400.0,
    "time-limit": 1.5,
    "time-before-partition": 0.3,
    "partition-duration": 0.4,
    "recovery-sleep": 0.2,
}


def _run(tmp_path, **kw):
    test, cluster = build_sim_test(
        opts=FAST_OPTS, store_root=str(tmp_path / "store"), **kw
    )
    run = run_test(test)
    return run, cluster


def test_healthy_cluster_is_valid(tmp_path):
    run, cluster = _run(tmp_path)
    assert run.results["queue"]["valid?"], run.results["queue"]
    assert run.results["linear"]["valid?"], run.results["linear"]
    assert run.valid
    # drain emptied the queue (the CI cross-check, ci/jepsen-test.sh:144-155)
    assert cluster.queue_length() == 0


def test_history_structure(tmp_path):
    run, _ = _run(tmp_path)
    h = run.history
    # indices sequential, times monotonic
    assert [op.index for op in h] == list(range(len(h)))
    assert all(
        h[i].time <= h[i + 1].time for i in range(len(h) - 1)
    )
    fs = {op.f for op in h}
    assert OpF.ENQUEUE in fs and OpF.DEQUEUE in fs and OpF.DRAIN in fs
    # the nemesis actually cut and healed
    assert any(op.f == OpF.START for op in h)
    assert any(op.f == OpF.STOP for op in h)
    # partitions produced at least some failed/indeterminate ops
    assert any(op.type in (OpType.FAIL, OpType.INFO) for op in h)
    # one drain per worker thread
    drains = [
        op for op in h if op.f == OpF.DRAIN and op.type == OpType.INVOKE
    ]
    assert len(drains) == run.test.concurrency


def test_lossy_broker_is_caught(tmp_path):
    # a broker bug that drops every 5th confirmed message MUST fail the run
    run, _ = _run(tmp_path, drop_acked_every=5)
    q = run.results["queue"]
    assert not q["valid?"]
    assert q["lost-count"] > 0
    assert not run.valid


def test_duplicating_broker_reported_but_valid(tmp_path):
    run, _ = _run(tmp_path, duplicate_every=4)
    q = run.results["queue"]
    assert q["duplicated-count"] > 0
    assert q["valid?"]  # at-least-once is legal for total-queue
    # but duplicates ARE a linearizability violation for the queue model
    assert not run.results["linear"]["valid?"]
    assert run.results["linear"]["duplicate-count"] > 0


def test_store_artifacts_written(tmp_path):
    run, _ = _run(tmp_path)
    d = run.run_dir
    assert (d / "history.jsonl").is_file()
    assert (d / "results.json").is_file()
    assert (d / "latency-raw.png").is_file()
    assert (d / "rate.png").is_file()


@pytest.mark.parametrize(
    "strategy",
    [
        "partition-halves",
        "partition-majorities-ring",
        "partition-random-node",
    ],
)
def test_all_partition_strategies_run_clean(tmp_path, strategy):
    test, cluster = build_sim_test(
        opts={**FAST_OPTS, "network-partition": strategy, "time-limit": 1.0},
        store_root=str(tmp_path / "store"),
    )
    run = run_test(test)
    assert run.results["queue"]["valid?"], run.results["queue"]


def test_unconnectable_client_fails_ops_but_run_completes(tmp_path):
    # a client that cannot connect must not deadlock the run: its ops fail
    from jepsen_tpu.suite import build_sim_test

    test, _ = build_sim_test(
        opts={**FAST_OPTS, "time-limit": 0.5, "recovery-sleep": 0.1},
        store_root=str(tmp_path / "store"),
    )

    class BrokenClient:
        def open(self, t, node):
            raise ConnectionRefusedError("nope")

    test.client = BrokenClient()
    run = run_test(test)
    client_ops = [op for op in run.history if op.process >= 0]
    assert client_ops, "run recorded no client ops"
    completions = [op for op in client_ops if op.type != OpType.INVOKE]
    assert all(op.type == OpType.FAIL for op in completions)


def test_time_limit_clamps_nemesis_sleep():
    # a nemesis mid-cycle sleep must not outlive the time limit
    from jepsen_tpu.generators.core import (
        Ctx,
        Cycle,
        Once,
        OpGen,
        Pending,
        Sleep,
        TimeLimit,
    )
    from jepsen_tpu.history.ops import NEMESIS_PROCESS

    g = TimeLimit(
        Cycle(lambda: [Sleep(100.0), Once(OpGen(OpF.START, OpType.INFO))]),
        1.0,
    )
    got = g.next_for(
        Ctx(time=0, thread=NEMESIS_PROCESS, process=-1, n_threads=1)
    )
    assert isinstance(got, Pending) and got.wake == int(1e9)


# ---------------------------------------------------------------------------
# Stream workload (BASELINE config #4) through the live pipeline
# ---------------------------------------------------------------------------


def _run_stream(tmp_path, **kw):
    from jepsen_tpu.suite import build_sim_test

    test, cluster = build_sim_test(
        opts=FAST_OPTS,
        store_root=str(tmp_path / "store"),
        workload="stream",
        **kw,
    )
    return run_test(test), cluster


def test_stream_healthy_cluster_is_valid(tmp_path):
    run, cluster = _run_stream(tmp_path)
    assert run.results["stream"]["valid?"], run.results["stream"]
    assert run.valid
    assert run.results["stream"]["full-read"]
    assert run.results["stream"]["attempt-count"] > 0


def test_stream_partition_bites(tmp_path):
    # the partition must actually block minority clients: some append or
    # read times out (appends indeterminate, reads fail)
    run, _ = _run_stream(tmp_path)
    timeouts = [
        op
        for op in run.history
        if op.f in (OpF.APPEND, OpF.READ)
        and op.type in (OpType.INFO, OpType.FAIL)
        and op.error == "timeout"
    ]
    assert timeouts, "no client op timed out under the partition"


def test_stream_lossy_broker_detected(tmp_path):
    run, _ = _run_stream(tmp_path, drop_appended_every=7)
    assert not run.results["stream"]["valid?"]
    assert run.results["stream"]["lost-count"] >= 1


def test_stream_duplicating_broker_detected(tmp_path):
    run, _ = _run_stream(tmp_path, duplicate_append_every=7)
    assert not run.results["stream"]["valid?"]
    assert run.results["stream"]["duplicate-count"] >= 1


# ---------------------------------------------------------------------------
# Elle transactional workload (BASELINE config #5) through the live pipeline
# ---------------------------------------------------------------------------


def test_elle_healthy_cluster_is_serializable(tmp_path):
    from jepsen_tpu.suite import build_sim_test

    test, _cluster = build_sim_test(
        opts=FAST_OPTS,
        store_root=str(tmp_path / "store"),
        workload="elle",
    )
    run = run_test(test)
    assert run.results["elle"]["valid?"], run.results["elle"]
    assert run.valid
    assert run.results["elle"]["txn-count"] > 0
    # the final read-only txns give every key an observed order, so the
    # dependency graph is non-trivial
    assert run.results["elle"]["ww-edges"] > 0


def test_sim_dead_letter_expiry_recovered_by_drain():
    """Dead-letter mode in the sim: a committed message that outlives the
    TTL moves to the DLQ, gets stop serving it, and the drain recovers it
    — consumed ∪ drained ≡ published survives expiry (the reference's
    MESSAGE_TTL-1s mode, Utils.java:55).  A virtual clock keeps the test
    deterministic."""
    from jepsen_tpu.client.sim import SimCluster

    now = [0.0]
    c = SimCluster(
        ["n1", "n2", "n3"],
        dead_letter=True,
        message_ttl_s=1.0,
        clock=lambda: now[0],
    )
    assert c.publish("n1", 7) is True
    assert c.publish("n1", 8) is True
    assert c.get("n1") in (7, 8)  # before the TTL: served normally
    now[0] = 1.5  # the remaining message outlives the TTL
    assert c.get("n1") is None  # expired out of the main queue
    assert c.queue_length() == 1  # still counted: it lives in the DLQ
    drained = c.drain_from_all()
    assert len(drained) == 1 and drained[0] in (7, 8)
    assert c.queue_length() == 0


# ---------------------------------------------------------------------------
# Mutex workload (the reference's legacy commented variant) end to end
# ---------------------------------------------------------------------------


def test_mutex_healthy_cluster_is_linearizable(tmp_path):
    from jepsen_tpu.suite import build_sim_test

    test, _cluster = build_sim_test(
        opts=FAST_OPTS,
        store_root=str(tmp_path / "store"),
        workload="mutex",
        checker_backend="cpu",
    )
    run = run_test(test)
    assert run.results["mutex"]["valid?"], run.results["mutex"]
    assert not run.results["mutex"]["unknown"]
    assert run.valid


def test_mutex_double_grant_detected(tmp_path):
    """Split-brain lock bug: the service grants an acquire while the lock
    is held — two concurrent ok-acquires with no release between cannot
    linearize against the owned-mutex model."""
    from jepsen_tpu.suite import build_sim_test

    test, _cluster = build_sim_test(
        opts=FAST_OPTS,
        store_root=str(tmp_path / "store"),
        workload="mutex",
        checker_backend="cpu",
        double_grant_every=3,
    )
    run = run_test(test)
    assert not run.results["mutex"]["valid?"]
    assert not run.results["mutex"]["unknown"]  # a definite violation


@pytest.mark.parametrize("kind", ["kill-random-node", "pause-random-node"])
def test_process_nemesis_run_clean(tmp_path, kind):
    """Kill/pause of a random node mid-run (beyond the reference's
    partition-only set): the cluster loses a voter, ops on the dead node
    fail cleanly, the stop restores it, and the verdict stays valid."""
    test, cluster = build_sim_test(
        opts={**FAST_OPTS, "nemesis": kind},
        store_root=str(tmp_path / "store"),
    )
    run = run_test(test)
    assert run.valid, run.results
    assert cluster.queue_length() == 0
    assert not cluster.down  # every victim restored
    # the nemesis actually did something
    infos = [
        op for op in run.history
        if op.f == OpF.START and op.type == OpType.INFO
    ]
    assert infos and any(
        str(op.value).startswith(("kill ", "pause ")) for op in infos
    )


def test_sim_down_node_semantics():
    """Down nodes neither vote nor serve: killing a majority stalls
    commits (timeouts), killing a minority does not."""
    from jepsen_tpu.client.protocol import DriverTimeout
    from jepsen_tpu.client.sim import SimCluster

    c = SimCluster(["n1", "n2", "n3"])
    c.set_down("n3")
    assert c.publish("n1", 1) is True  # 2/3 still a majority
    with pytest.raises(ConnectionError):
        c.publish("n3", 2)  # the down node itself refuses
    c.set_down("n2")
    with pytest.raises(DriverTimeout):
        c.publish("n1", 3)  # 1/3 is a minority now
    c.set_up("n2")
    c.set_up("n3")
    assert c.publish("n3", 4) is True


class TestLiveMonitor:
    """Mid-run anomaly monitor (checkers/live.py): monotone total-queue
    anomalies surface the moment they are recorded."""

    def test_unit_monotone_flags(self):
        from jepsen_tpu.checkers.live import LiveTotalQueue
        from jepsen_tpu.history.ops import Op, OpF, OpType

        fired = []
        m = LiveTotalQueue(on_anomaly=lambda k, v, i: fired.append((k, v)))
        enq = Op.invoke(OpF.ENQUEUE, 0, 7)
        m.observe(enq)  # invocation alone makes 7 explicable
        deq = Op.invoke(OpF.DEQUEUE, 1)
        m.observe(deq.complete(OpType.OK, value=7))
        assert not fired  # first read of an attempted value: clean
        m.observe(deq.complete(OpType.OK, value=7))
        assert fired == [("duplicated", 7)]
        m.observe(deq.complete(OpType.OK, value=99))
        assert fired[-1] == ("unexpected", 99)
        snap = m.snapshot()
        assert snap["violation-so-far"] is True
        assert snap["anomalies"] == {"duplicated": 1, "unexpected": 1}
        # monotone: repeats never re-fire
        m.observe(deq.complete(OpType.OK, value=99))
        assert len(fired) == 2

    def test_clean_run_stays_silent(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts=FAST_OPTS, store_root=str(tmp_path / "store")
        )
        m = attach_live_monitor_for(test, "queue")
        run = run_test(test)
        assert run.valid
        snap = m.snapshot()
        assert snap["observations"] > 0
        assert snap["violation-so-far"] is False and not snap["events"]

    def test_duplicating_broker_flagged_mid_run(self, tmp_path):
        """The injected at-least-once duplicates are caught DURING the run
        (event op-indices precede the history's end) and agree with the
        post-hoc checker's classification."""
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts=FAST_OPTS,
            store_root=str(tmp_path / "store"),
            duplicate_every=3,
        )
        m = attach_live_monitor_for(test, "queue")
        run = run_test(test)
        snap = m.snapshot()
        assert snap["anomalies"]["duplicated"] > 0
        assert snap["anomalies"]["unexpected"] == 0
        assert all(
            e["op-index"] < len(run.history) for e in snap["events"]
        )
        assert run.results["queue"]["valid?"]  # duplicates stay legal
        assert (
            run.results["queue"]["duplicated-count"]
            >= snap["anomalies"]["duplicated"]
        )


class TestLiveStreamMonitor:
    def test_unit_monotone_flags(self):
        from jepsen_tpu.checkers.live import LiveStream
        from jepsen_tpu.history.ops import Op, OpF, OpType

        fired = []
        m = LiveStream(on_anomaly=lambda k, v, i: fired.append((k, v)))
        m.observe(Op.invoke(OpF.APPEND, 0, 10))
        m.observe(Op.invoke(OpF.APPEND, 0, 11))
        read = Op.invoke(OpF.READ, 1)
        m.observe(read.complete(OpType.OK, value=[[0, 10], [1, 11]]))
        assert not fired  # clean prefix
        # same offset, different value → divergent
        m.observe(read.complete(OpType.OK, value=[[0, 11]]))
        assert ("divergent", 0) in fired
        # same value at a second offset → duplicated
        m.observe(read.complete(OpType.OK, value=[[2, 10]]))
        assert ("duplicated", 10) in fired
        # value never appended → phantom; offsets going backwards → nonmono
        m.observe(read.complete(OpType.OK, value=[[3, 99], [1, 11]]))
        assert ("phantom", 99) in fired
        assert any(k == "nonmonotonic" for k, _ in fired)
        snap = m.snapshot()
        assert snap["violation-so-far"] is True

    def test_stream_run_duplicates_flagged_mid_run(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts=FAST_OPTS,
            store_root=str(tmp_path / "store"),
            workload="stream",
            duplicate_append_every=3,
        )
        m = attach_live_monitor_for(test, "stream")
        run = run_test(test)
        snap = m.snapshot()
        assert snap["anomalies"]["duplicated"] > 0
        assert snap["violation-so-far"] is True
        assert run.results["stream"]["valid?"] is False  # post-hoc agrees

    def test_clean_stream_run_stays_silent(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts=FAST_OPTS,
            store_root=str(tmp_path / "store"),
            workload="stream",
        )
        m = attach_live_monitor_for(test, "stream")
        run = run_test(test)
        assert run.valid
        assert m.snapshot()["violation-so-far"] is False


class TestLiveElleMonitor:
    def test_unit_monotone_flags(self):
        from jepsen_tpu.checkers.live import LiveElle
        from jepsen_tpu.history.ops import Op, OpF, OpType

        fired = []
        m = LiveElle(on_anomaly=lambda k, v, i: fired.append((k, v)))
        t1 = Op.invoke(OpF.TXN, 0, [["append", 0, 1]])
        m.observe(t1)
        m.observe(t1.complete(OpType.OK, value=[["append", 0, 1]]))
        r = Op.invoke(OpF.TXN, 1, [["r", 0, None]])
        m.observe(r.complete(OpType.OK, value=[["r", 0, [1]]]))
        assert not fired
        # contradictory read of key 0: [2] vs [1]
        m.observe(r.complete(OpType.OK, value=[["r", 0, [2]]]))
        assert ("incompatible-order", 0) in fired
        # G1a, fail-then-read order
        f = Op.invoke(OpF.TXN, 2, [["append", 1, 50]])
        m.observe(f.complete(OpType.FAIL, value=[["append", 1, 50]]))
        m.observe(r.complete(OpType.OK, value=[["r", 1, [50]]]))
        assert ("G1a", 50) in fired
        # G1a, read-then-fail order is decisive too
        m.observe(r.complete(OpType.OK, value=[["r", 2, [60]]]))
        f2 = Op.invoke(OpF.TXN, 3, [["append", 2, 60]])
        m.observe(f2.complete(OpType.FAIL, value=[["append", 2, 60]]))
        assert ("G1a", 60) in fired
        assert m.snapshot()["violation-so-far"] is True

    def test_clean_elle_run_stays_silent(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts=FAST_OPTS,
            store_root=str(tmp_path / "store"),
            workload="elle",
        )
        m = attach_live_monitor_for(test, "elle")
        run = run_test(test)
        assert run.valid
        snap = m.snapshot()
        assert snap["observations"] > 0
        assert snap["violation-so-far"] is False


class TestLiveMutexMonitor:
    def test_unit_double_grant_rule(self):
        from jepsen_tpu.checkers.live import LiveMutex
        from jepsen_tpu.history.ops import Op, OpF, OpType

        fired = []
        m = LiveMutex(on_anomaly=lambda k, v, i: fired.append((k, v)))
        acq_a = Op.invoke(OpF.ACQUIRE, 0)
        m.observe(acq_a)
        m.observe(acq_a.complete(OpType.OK))
        rel_a = Op.invoke(OpF.RELEASE, 0)
        m.observe(rel_a)  # release INVOKE clears the certain hold...
        acq_b = Op.invoke(OpF.ACQUIRE, 1)
        m.observe(acq_b.complete(OpType.OK))
        assert not fired  # ...so B's grant is explicable
        # C granted while B certainly holds (no release invoked since)
        acq_c = Op.invoke(OpF.ACQUIRE, 2)
        m.observe(acq_c.complete(OpType.OK))
        assert fired == [("double-grant", 2)]
        assert m.snapshot()["violation-so-far"] is True

    def test_split_brain_sim_run_flagged_mid_run(self, tmp_path):
        """The sim's injected split-brain double grant fires DURING the
        run and the post-hoc WGL verdict agrees it is a violation."""
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts={**FAST_OPTS, "rate": 600.0},
            store_root=str(tmp_path / "store"),
            workload="mutex",
            double_grant_every=3,
        )
        m = attach_live_monitor_for(test, "mutex")
        run = run_test(test)
        snap = m.snapshot()
        assert snap["anomalies"]["double-grant"] > 0
        assert snap["violation-so-far"] is True
        assert run.results["mutex"]["valid?"] is False

    def test_clean_mutex_run_stays_silent(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts=FAST_OPTS,
            store_root=str(tmp_path / "store"),
            workload="mutex",
        )
        m = attach_live_monitor_for(test, "mutex")
        run = run_test(test)
        assert run.valid
        assert m.snapshot()["violation-so-far"] is False


def test_fenced_mutex_revocation_injection_is_valid(tmp_path):
    """The injection that REDS the unfenced mutex family (double_grant:
    grant-while-held) models a revocation + re-grant in fenced mode —
    tokens keep increasing, the superseded holder's release fails, and
    the fenced checker stays green.  The green ending the family was
    missing (VERDICT r5 weak #2)."""
    test, _cluster = build_sim_test(
        opts={**FAST_OPTS, "fenced": True},
        store_root=str(tmp_path / "store"),
        workload="mutex",
        double_grant_every=3,
    )
    run = run_test(test)
    assert run.results["mutex"]["valid?"] is True, run.results["mutex"]
    assert run.results["mutex"]["model"] == "fenced-mutex"
    # tokens actually flowed into the history
    assert any(
        op.is_ok and op.f == OpF.ACQUIRE and isinstance(op.value, int)
        for op in run.history
    )


def test_fenced_mutex_stale_token_injection_is_refuted(tmp_path):
    """The fencing BUG (a grant re-issuing an already-granted token) is
    a definite violation under the fenced model."""
    test, _cluster = build_sim_test(
        opts={**FAST_OPTS, "fenced": True},
        store_root=str(tmp_path / "store"),
        workload="mutex",
        double_grant_every=3,
        stale_token_every=2,
    )
    run = run_test(test)
    assert run.results["mutex"]["valid?"] is False
    assert run.results["mutex"]["model"] == "fenced-mutex"


class TestLiveFencedMutexMonitor:
    def test_unit_token_reuse_rule(self):
        from jepsen_tpu.checkers.live import LiveFencedMutex
        from jepsen_tpu.history.ops import Op, OpF, OpType

        fired = []
        m = LiveFencedMutex(on_anomaly=lambda k, v, i: fired.append((k, v)))
        a = Op.invoke(OpF.ACQUIRE, 0)
        m.observe(a.complete(OpType.OK, value=5))
        # overlapping grant with a HIGHER token: the tolerated revocation
        # shape — must NOT fire (LiveMutex would have)
        b = Op.invoke(OpF.ACQUIRE, 1)
        m.observe(b.complete(OpType.OK, value=9))
        assert not fired
        # the same token granted twice: definitive the moment it lands
        c = Op.invoke(OpF.ACQUIRE, 2)
        m.observe(c.complete(OpType.OK, value=9))
        assert fired == [("token-reuse", 9)]
        assert m.snapshot()["violation-so-far"] is True

    def test_fenced_sim_run_with_revocations_stays_silent(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts={**FAST_OPTS, "fenced": True},
            store_root=str(tmp_path / "store"),
            workload="mutex",
            double_grant_every=3,
        )
        m = attach_live_monitor_for(test, "fenced-mutex")
        run = run_test(test)
        assert run.results["mutex"]["valid?"] is True
        assert m.snapshot()["violation-so-far"] is False

    def test_fenced_sim_stale_tokens_flagged_mid_run(self, tmp_path):
        from jepsen_tpu.checkers.live import attach_live_monitor_for

        test, _cluster = build_sim_test(
            opts={**FAST_OPTS, "rate": 600.0, "fenced": True},
            store_root=str(tmp_path / "store"),
            workload="mutex",
            double_grant_every=2,
            stale_token_every=2,
        )
        m = attach_live_monitor_for(test, "fenced-mutex")
        run = run_test(test)
        snap = m.snapshot()
        assert snap["anomalies"]["token-reuse"] > 0
        assert snap["violation-so-far"] is True
        assert run.results["mutex"]["valid?"] is False
