"""Raft quorum replication for the mini broker (harness/replication.py).

Covers the state machine's determinism, then live 3-node clusters over
real TCP: election, majority-commit, per-link partition semantics (leader
step-down, majority-side failover), heal/catch-up with truncation, the
restarted-node grace period, and the seeded ``confirm-before-quorum`` bug
whose confirmed-then-truncated writes are the red-run proof downstream.
"""

from __future__ import annotations

import base64
import socket
import time

import pytest

from jepsen_tpu.harness.replication import (
    QueueMachine,
    RaftNode,
    ReplicatedBackend,
)


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class TestQueueMachine:
    def test_enq_deq_settle_roundtrip(self):
        m = QueueMachine()
        m.apply(1, {"k": "declare", "q": "q"})
        m.apply(2, {"k": "enq", "q": "q", "body": _b64(b"7"), "ts": 0.0})
        msg = m.apply(3, {"k": "deq", "q": "q", "owner": "n1|c1", "now": 1.0})
        assert msg.body == b"7" and msg.mid == "m2"
        assert m.counts(1.0) == {"q": 1}  # inflight still counts
        m.apply(4, {"k": "settle", "owner": "n1|c1", "mid": msg.mid})
        assert m.counts(1.0) == {"q": 0}

    def test_settle_wrong_owner_is_noop(self):
        m = QueueMachine()
        m.apply(1, {"k": "declare", "q": "q"})
        m.apply(2, {"k": "enq", "q": "q", "body": _b64(b"x"), "ts": 0.0})
        msg = m.apply(3, {"k": "deq", "q": "q", "owner": "n1|c1", "now": 0.0})
        m.apply(4, {"k": "settle", "owner": "n2|c9", "mid": msg.mid})
        assert m.counts(0.0) == {"q": 1}

    def test_requeue_owner_and_node(self):
        m = QueueMachine()
        m.apply(1, {"k": "declare", "q": "q"})
        for i in range(3):
            m.apply(
                2 + i, {"k": "enq", "q": "q", "body": _b64(b"%d" % i),
                        "ts": 0.0}
            )
        a = m.apply(5, {"k": "deq", "q": "q", "owner": "n1|c1", "now": 0.0})
        b = m.apply(6, {"k": "deq", "q": "q", "owner": "n1|c2", "now": 0.0})
        c = m.apply(7, {"k": "deq", "q": "q", "owner": "n2|c1", "now": 0.0})
        assert {x.body for x in (a, b, c)} == {b"0", b"1", b"2"}
        m.apply(8, {"k": "requeue_owner", "owner": "n1|c2"})
        assert len(m.queues["q"]) == 1
        m.apply(9, {"k": "requeue_node", "node": "n1"})
        assert len(m.queues["q"]) == 2  # n1|c1 came back; n2|c1 still out

    def test_deterministic_ttl_expiry_with_dlx(self):
        m = QueueMachine()
        m.apply(
            1,
            {"k": "declare", "q": "q", "ttl_ms": 100, "dlx": "q.dead"},
        )
        m.apply(2, {"k": "declare", "q": "q.dead"})
        m.apply(3, {"k": "enq", "q": "q", "body": _b64(b"v"), "ts": 0.0})
        # counts() simulates expiry without mutating (advisor r3 #5)
        assert m.counts(50.0) == {"q": 1, "q.dead": 0}
        assert m.counts(150.0) == {"q": 0, "q.dead": 1}
        assert len(m.queues["q"]) == 1  # still un-mutated
        # DEQ at now=150 performs the expiry: q empty, dead-letter holds it
        assert (
            m.apply(4, {"k": "deq", "q": "q", "owner": "o", "now": 150.0})
            is None
        )
        got = m.apply(
            5, {"k": "deq", "q": "q.dead", "owner": "o", "now": 150.0}
        )
        assert got.body == b"v"

    def test_txn_applies_atomically_in_order(self):
        m = QueueMachine()
        m.apply(1, {"k": "declare", "q": "q"})
        m.apply(
            2,
            {
                "k": "txn",
                "ops": [
                    {"k": "enq", "q": "q", "body": _b64(b"a"), "ts": 0.0},
                    {"k": "enq", "q": "q", "body": _b64(b"b"), "ts": 0.0},
                ],
            },
        )
        assert [x.body for x in m.queues["q"]] == [b"a", b"b"]
        assert [x.mid for x in m.queues["q"]] == ["m2.0", "m2.1"]

    def test_stream_append_and_snapshot(self):
        m = QueueMachine()
        m.apply(1, {"k": "declare", "q": "s", "qtype": "stream"})
        m.apply(2, {"k": "enq", "q": "s", "body": _b64(b"r0"), "ts": 0.0})
        m.apply(3, {"k": "enq", "q": "s", "body": _b64(b"r1"), "ts": 0.0})
        assert m.stream_snapshot("s") == [b"r0", b"r1"]
        assert m.counts(0.0) == {"s": 2}


# ---------------------------------------------------------------------------
# Live clusters
# ---------------------------------------------------------------------------

FAST = dict(election_timeout=(0.15, 0.3), heartbeat_s=0.04, dead_owner_s=0.8)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _mk_cluster(n=3, seed_bug=None, **kw):
    names = [f"n{i}" for i in range(n)]
    peers = {nm: ("127.0.0.1", _free_port()) for nm in names}
    opts = {**FAST, **kw}
    nodes = {
        nm: ReplicatedBackend(
            nm, peers, seed_bug=seed_bug if nm else None, **opts
        )
        for nm in names
    }
    return nodes


def _wait_leader(nodes, timeout=5.0, among=None):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [
            nm
            for nm, b in nodes.items()
            if (among is None or nm in among) and b.raft.is_leader()
        ]
        if leaders:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no leader elected")


def _shutdown(nodes):
    for b in nodes.values():
        b.stop()


@pytest.fixture
def cluster():
    nodes = _mk_cluster()
    try:
        yield nodes
    finally:
        _shutdown(nodes)


def _partition(nodes, group_a, group_b):
    """Cut every cross-group link, both directions (complete grudge)."""
    for a in group_a:
        for b in group_b:
            nodes[a].raft.block(b)
            nodes[b].raft.block(a)


def _heal(nodes):
    for b in nodes.values():
        b.raft.unblock_all()


class TestRaftCluster:
    def test_elects_leader_and_commits_everywhere(self, cluster):
        leader = _wait_leader(cluster)
        b = cluster[leader]
        b.declare("q")
        assert b.enqueue("q", b"v1", b"")
        # committed state reaches every replica
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if all(
                len(x.machine.queues.get("q", ())) == 1
                for x in cluster.values()
            ):
                break
            time.sleep(0.02)
        for x in cluster.values():
            assert [m.body for m in x.machine.queues["q"]] == [b"v1"]

    def test_follower_forwards_to_leader(self, cluster):
        leader = _wait_leader(cluster)
        follower = next(nm for nm in cluster if nm != leader)
        fb = cluster[follower]
        fb.declare("q")
        assert fb.enqueue("q", b"fwd", b"")
        msg = fb.dequeue("q", owner=f"{follower}|c1")
        assert msg is not None and msg.body == b"fwd"
        fb.settle(f"{follower}|c1", msg.mid)
        assert cluster[leader].counts()["q"] == 0

    def test_minority_leader_steps_down_majority_elects(self, cluster):
        leader = _wait_leader(cluster)
        others = [nm for nm in cluster if nm != leader]
        _partition(cluster, [leader], others)
        # majority side elects a fresh leader
        new_leader = _wait_leader(
            {nm: cluster[nm] for nm in others}, timeout=5.0
        )
        assert new_leader != leader
        # the isolated ex-leader steps down (cannot confirm)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if not cluster[leader].raft.is_leader():
                break
            time.sleep(0.02)
        assert not cluster[leader].raft.is_leader()
        # and an enqueue at the minority node does NOT confirm
        assert not cluster[leader].enqueue("q", b"x", b"")

    def test_heal_catches_up_and_truncates_divergence(self, cluster):
        leader = _wait_leader(cluster)
        lb = cluster[leader]
        lb.declare("q")
        assert lb.enqueue("q", b"before", b"")
        others = [nm for nm in cluster if nm != leader]
        _partition(cluster, [leader], others)
        new_leader = _wait_leader(
            {nm: cluster[nm] for nm in others}, timeout=5.0
        )
        assert cluster[new_leader].enqueue("q", b"majority", b"")
        _heal(cluster)
        # the old leader rejoins and converges on the majority's history
        deadline = time.monotonic() + 4.0
        while time.monotonic() < deadline:
            bodies = [m.body for m in lb.machine.queues.get("q", ())]
            if bodies == [b"before", b"majority"]:
                break
            time.sleep(0.05)
        assert [m.body for m in lb.machine.queues["q"]] == [
            b"before",
            b"majority",
        ]

    def test_confirmed_quorum_write_survives_leader_kill(self):
        nodes = _mk_cluster()
        try:
            leader = _wait_leader(nodes)
            lb = nodes[leader]
            lb.declare("q")
            assert lb.enqueue("q", b"safe", b"")
            lb.stop()  # SIGKILL stand-in
            rest = {nm: b for nm, b in nodes.items() if nm != leader}
            new_leader = _wait_leader(rest, timeout=5.0)
            msg = rest[new_leader].dequeue("q", owner="x|c")
            assert msg is not None and msg.body == b"safe"
        finally:
            _shutdown(nodes)


class TestSeededBug:
    def test_confirm_before_quorum_loses_confirmed_write(self):
        """The whole point of the seeded bug: a write confirmed by the
        buggy leader while isolated is truncated on heal — an
        acknowledged-then-lost write the checker must catch.

        Bounded retry-with-reseed (the round-4 load-flake class): under
        full-suite scheduler pressure the isolated leader can step down
        BEFORE this test lands its "instant" buggy confirm — a legal
        schedule in which the bug simply was not exercised.  A fresh
        cluster retries the window; a genuine regression (truncation
        not happening, "doomed" surviving) still fails every attempt."""
        from _load import scaled

        last: AssertionError | None = None
        for _attempt in range(3):
            try:
                self._window(scaled)
                return
            except AssertionError as e:
                last = e
        raise last

    def _window(self, scaled):
        names = ["n0", "n1", "n2"]
        peers = {nm: ("127.0.0.1", _free_port()) for nm in names}
        nodes = {
            nm: ReplicatedBackend(
                nm, peers, seed_bug="confirm-before-quorum", **FAST
            )
            for nm in names
        }
        try:
            leader = _wait_leader(nodes)
            lb = nodes[leader]
            lb.declare("q")
            others = [nm for nm in names if nm != leader]
            _partition(nodes, [leader], others)
            # the buggy leader confirms instantly with no quorum (before
            # step-down kicks in)
            assert lb.enqueue("q", b"doomed", b"")
            new_leader = _wait_leader(
                {nm: nodes[nm] for nm in others}, timeout=scaled(5.0)
            )
            assert nodes[new_leader].enqueue("q", b"kept", b"")
            _heal(nodes)
            deadline = time.monotonic() + scaled(4.0)
            while time.monotonic() < deadline:
                bodies = [
                    m.body for m in lb.machine.queues.get("q", ())
                ]
                if bodies == [b"kept"]:
                    break
                time.sleep(0.05)
            # "doomed" was CONFIRMED to the client yet is gone everywhere
            for b in nodes.values():
                assert [m.body for m in b.machine.queues["q"]] == [b"kept"]
        finally:
            _shutdown(nodes)


class TestDeadOwnerRequeue:
    def test_inflight_of_dead_node_is_requeued(self):
        nodes = _mk_cluster()
        try:
            leader = _wait_leader(nodes)
            lb = nodes[leader]
            lb.declare("q")
            assert lb.enqueue("q", b"v", b"")
            victim = next(nm for nm in nodes if nm != leader)
            msg = nodes[victim].dequeue("q", owner=f"{victim}|c1")
            assert msg is not None
            assert lb.counts()["q"] == 1  # inflight
            nodes[victim].stop()  # node dies holding the delivery
            # generous: dead-owner detection rides heartbeat-gap timing,
            # and on a loaded 1-core host scheduling can stretch the
            # reaper's window well past the nominal dead_owner_s
            deadline = time.monotonic() + 15.0
            redelivered = None
            while time.monotonic() < deadline:
                redelivered = lb.dequeue("q", owner=f"{leader}|c9")
                if redelivered is not None:
                    break
                time.sleep(0.1)
            assert redelivered is not None and redelivered.body == b"v"
        finally:
            _shutdown(nodes)


class TestMembershipSafety:
    """Advisor r4: the two Raft-layer membership hardenings — re-added
    peers must not inherit their previous incarnation's replication
    bookkeeping, and a second cfg change must not stack on an
    appended-but-uncommitted first (single-server-change anchoring)."""

    def _node(self):
        from jepsen_tpu.harness.replication import RaftNode

        n = RaftNode(
            "a",
            {"a": ("127.0.0.1", 0), "b": ("127.0.0.1", 1), },
            apply_fn=lambda i, op: None,
        )
        n.stop()  # pure state-machine tests: no live RPC needed
        return n

    def test_readded_peer_bookkeeping_resets(self):
        n = self._node()
        with n.lock:
            # leader-side view: b fully caught up at log length 5
            n.log = [(1, {"k": "x"})] * 5
            n.commit_idx = 5
            n.next_idx["b"] = 6
            n.match_idx["b"] = 5
            # forget b (cfg without it), then re-add a fresh b
            n.log.append((1, {"k": "cfg", "peers": {
                "a": ["127.0.0.1", n.port],
            }}))
            n._recompute_config_locked()
            assert n.others == []
            n.log.append((1, {"k": "cfg", "peers": {
                "a": ["127.0.0.1", n.port], "b": ["127.0.0.1", 1],
            }}))
            n._recompute_config_locked()
            assert n.others == ["b"]
            # the wiped-and-rejoined b has NONE of our log: stale
            # match_idx=5 would count ghost acks toward commit
            assert n.match_idx["b"] == 0
            assert n.next_idx["b"] == len(n.log) + 1

    def test_unchanged_peer_bookkeeping_survives_recompute(self):
        n = self._node()
        with n.lock:
            n.log = [(1, {"k": "x"})] * 3
            n.next_idx["b"] = 2  # mid-backoff: must NOT reset
            n.match_idx["b"] = 1
            n.log.append((1, {"k": "cfg", "peers": {
                "a": ["127.0.0.1", n.port], "b": ["127.0.0.1", 1],
                "c": ["127.0.0.1", 2],
            }}))
            n._recompute_config_locked()
            assert n.match_idx["b"] == 1 and n.next_idx["b"] == 2
            assert n.match_idx["c"] == 0  # new peer seeded fresh

    def test_uncommitted_cfg_blocks_second_change(self):
        n = self._node()
        with n.lock:
            n.log = [(1, {"k": "x"}), (1, {"k": "cfg", "peers": {
                "a": ["127.0.0.1", n.port], "b": ["127.0.0.1", 1],
            }})]
            n.commit_idx = 1  # the cfg entry is appended, not committed
            assert n._uncommitted_cfg_locked()
            n.commit_idx = 2
            assert not n._uncommitted_cfg_locked()

    def test_join_refused_while_cfg_uncommitted(self):
        n = self._node()
        with n.lock:
            n.state = "leader"
            n.log = [(1, {"k": "cfg", "peers": {
                "a": ["127.0.0.1", n.port], "b": ["127.0.0.1", 1],
            }})]
            n.commit_idx = 0
            n._recompute_config_locked()
        resp = n._on_join_request({
            "rpc": "join_request", "name": "c",
            "host": "127.0.0.1", "port": 2, "from": "c",
        })
        assert resp == {"ok": False}
        resp = n._on_forget_request(
            {"rpc": "forget_request", "name": "b", "from": "a"}
        )
        assert resp == {"ok": False}


class TestFencingMachine:
    """Fencing-token semantics of the replicated state machine: every
    ownership transition (grant / revocation-requeue / release) advances
    the queue's fence to its own commit index, and stale-token
    operations are rejected deterministically at apply time."""

    def _lock_machine(self):
        m = QueueMachine()
        m.apply(1, {"k": "declare", "q": "lock", "fenced": True})
        m.apply(
            2,
            {"k": "enq", "q": "lock", "body": _b64(b"1"), "props": "",
             "ts": 0.0},
        )
        return m

    def test_grant_token_is_commit_index_and_monotonic(self):
        m = self._lock_machine()
        msg = m.apply(3, {"k": "deq", "q": "lock", "owner": "a|c1",
                          "now": 0.0})
        assert msg.fence == 3
        assert m.fences["lock"] == 3
        # revocation: the requeue advances the fence past the holder
        m.apply(4, {"k": "requeue_owner", "owner": "a|c1"})
        assert m.fences["lock"] == 4
        # re-grant: strictly higher token, stripped of the old fence
        msg2 = m.apply(5, {"k": "deq", "q": "lock", "owner": "b|c1",
                           "now": 0.0})
        assert msg2.fence == 5 > msg.fence

    def test_stale_release_rejected_current_release_accepted(self):
        m = self._lock_machine()
        m.apply(3, {"k": "deq", "q": "lock", "owner": "a|c1", "now": 0.0})
        m.apply(4, {"k": "requeue_owner", "owner": "a|c1"})  # revoked
        m.apply(5, {"k": "deq", "q": "lock", "owner": "b|c1", "now": 0.0})
        # the revoked holder's release: REJECTED (token 3 superseded)
        r = m.apply(
            6,
            {"k": "fence_release", "q": "lock", "token": 3,
             "body": _b64(b"1"), "props": "", "ts": 0.0},
        )
        assert r == {"stale": True}
        assert "lock" not in {q for q, d in m.queues.items() if d} or not (
            m.queues.get("lock")
        )
        # the current holder's release: grant settles atomically with the
        # token's return, fence advances to the release commit
        r = m.apply(
            7,
            {"k": "fence_release", "q": "lock", "token": 5,
             "body": _b64(b"1"), "props": "", "ts": 0.0},
        )
        assert r["released"] and not m.inflight
        assert m.fences["lock"] == 7
        assert len(m.queues["lock"]) == 1  # exactly one token, ever

    def test_fenced_protected_publish_stale_vs_current(self):
        m = self._lock_machine()
        msg = m.apply(3, {"k": "deq", "q": "lock", "owner": "a|c1",
                          "now": 0.0})
        m.apply(1000, {"k": "declare", "q": "data"})
        # current token: the protected publish lands
        r = m.apply(
            1001,
            {"k": "enq", "q": "data", "body": _b64(b"x"), "props": "",
             "ts": 0.0, "fence": msg.fence, "fence_q": "lock"},
        )
        assert r is None and len(m.queues["data"]) == 1
        # revoke + re-grant: the old token's publish is REJECTED
        m.apply(1002, {"k": "requeue_owner", "owner": "a|c1"})
        m.apply(1003, {"k": "deq", "q": "lock", "owner": "b|c1",
                       "now": 0.0})
        r = m.apply(
            1004,
            {"k": "enq", "q": "data", "body": _b64(b"y"), "props": "",
             "ts": 0.0, "fence": msg.fence, "fence_q": "lock"},
        )
        assert r == {"stale": True}
        assert len(m.queues["data"]) == 1  # the stale write never landed


class TestCommitAdvanceCap:
    """Red/green regression for the advisor-r5 high finding
    (replication.py commit advance): an empty heartbeat at a low
    prev_idx must never commit a follower's divergent uncommitted
    suffix.  Pre-fix, `commit_idx = min(leader_commit, len(log))`
    applied the divergent entry (permanently — applies never revert);
    the §5.3 cap bounds commit at prev + len(entries)."""

    def _follower(self, applied):
        peers = {
            "f": ("127.0.0.1", 0),
            "l1": ("127.0.0.1", 1),  # never listening: scripted RPCs only
            "l2": ("127.0.0.1", 2),
        }
        return RaftNode(
            "f",
            peers,
            lambda i, op: applied.append((i, op["k"])),
            election_timeout=(60.0, 120.0),  # never campaigns in-test
        )

    def test_heartbeat_cannot_commit_divergent_suffix(self):
        applied = []
        n = self._follower(applied)
        try:
            # term-1 leader replicates two entries; the second will turn
            # out to be divergent (uncommitted when the leader fell)
            r = n._on_append_entries({
                "rpc": "append_entries", "term": 1, "from": "l1",
                "prev_idx": 0, "prev_term": 0,
                "entries": [[1, {"k": "noop"}], [1, {"k": "divergent"}]],
                "leader_commit": 0,
            })
            assert r["ok"] and applied == []
            # new term-2 leader (elected without entry 2), match_idx
            # still 0: its first heartbeat carries prev_idx=0, no
            # entries, and its own commit index 2 (noop + its no-op)
            r = n._on_append_entries({
                "rpc": "append_entries", "term": 2, "from": "l2",
                "prev_idx": 0, "prev_term": 0, "entries": [],
                "leader_commit": 2,
            })
            assert r["ok"]
            # THE BUG (pre-fix): commit_idx jumped to min(2, len(log))=2
            # and applied the divergent entry.  Post-fix: the heartbeat
            # proved nothing past prev_idx=0 — commit must not move.
            assert n.commit_idx == 0, (
                "heartbeat committed past its proven-matching prefix"
            )
            assert ("divergent" not in [k for _i, k in applied])
            # the repair AppendEntries truncates the divergence and
            # carries the real entry 2; NOW commit legitimately reaches 2
            r = n._on_append_entries({
                "rpc": "append_entries", "term": 2, "from": "l2",
                "prev_idx": 1, "prev_term": 1,
                "entries": [[2, {"k": "cfg_probe"}]],
                "leader_commit": 2,
            })
            assert r["ok"]
            assert n.commit_idx == 2
            assert applied == [(1, "noop"), (2, "cfg_probe")]
        finally:
            n.stop()

    def test_commit_never_regresses_on_low_prev_heartbeat(self):
        """The cap must also never move commit BACKWARD: a heartbeat at
        prev_idx=0 arriving after entries committed must leave
        commit_idx alone."""
        applied = []
        n = self._follower(applied)
        try:
            n._on_append_entries({
                "rpc": "append_entries", "term": 1, "from": "l1",
                "prev_idx": 0, "prev_term": 0,
                "entries": [[1, {"k": "noop"}], [1, {"k": "noop"}]],
                "leader_commit": 2,
            })
            assert n.commit_idx == 2
            n._on_append_entries({
                "rpc": "append_entries", "term": 1, "from": "l1",
                "prev_idx": 0, "prev_term": 0, "entries": [],
                "leader_commit": 2,
            })
            assert n.commit_idx == 2  # unchanged, not clamped to 0
        finally:
            n.stop()
