"""Retry-with-triage harness for the live local-cluster e2e tests.

VERDICT r4 weak #2: the live assembly tests (membership churn first)
flaked under full-suite scheduler pressure — a red that vanishes on
re-run either hides a real rare anomaly or trains operators to ignore
red, and the bare ``assert results["valid?"]`` didn't even say *which*
checker invalidated.  The reference CI retries whole runs for exactly
this reason (``/root/reference/ci/jepsen-test.sh:116-197``), and this
repo's matrix runner (``harness/matrix.py`` MatrixRunner) already
implements the triage; this module lifts the same semantics into
pytest:

- crash / final-read-missing / verdict ``unknown`` → retry (the run
  can't attest either way)
- verdict invalid → retry, and on exhaustion fail with the
  *invalidating checkers and their anomaly counts* named
- a genuine red (seeded bug) still reds every attempt, so
  ``expect="invalid"`` returns the first invalid run — flake retries
  never launder a real violation into a green.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

from jepsen_tpu.checkers.protocol import UNKNOWN, VALID
from jepsen_tpu.harness.matrix import MatrixRunner


def describe_invalid(results: Mapping[str, Any]) -> dict[str, Any]:
    """Name every invalidating sub-checker with its anomaly counts —
    the triage evidence a failure message must carry."""
    bad: dict[str, Any] = {}
    for name, r in results.items():
        if not isinstance(r, Mapping) or r.get(VALID) is not False:
            continue
        counts = {
            k: v for k, v in r.items()
            if (k.endswith("-count") or k.endswith("_count")) and v
        }
        for k, v in r.items():
            if isinstance(v, (list, tuple)) and v and k != "examples":
                counts[f"{k}-len"] = len(v)
        bad[name] = counts or {
            k: v for k, v in r.items() if k != VALID
        }
    return bad


def run_live_with_triage(
    build_fn: Callable[[], tuple[Any, Any]],
    expect: str = "valid",
    max_attempts: int = 3,
    checks: Callable[[Any], None] | None = None,
):
    """Build + run a live test up to ``max_attempts`` times with the
    matrix's triage rules.

    ``build_fn() -> (test, transport)`` builds a FRESH cluster per
    attempt (a retry on a half-torn-down cluster proves nothing).
    ``checks(run)`` holds the caller's extra assertions (nemesis
    actually fired, anomaly counts, …); an AssertionError from it is
    treated as a retryable load artifact, surfaced on exhaustion.
    Returns the accepted run.
    """
    assert expect in ("valid", "invalid")
    from jepsen_tpu.control.runner import run_test

    notes: list[str] = []
    for attempt in range(1, max_attempts + 1):
        test, transport = build_fn()
        try:
            try:
                run = run_test(test)
            except Exception as e:  # noqa: BLE001 - triaged, reported
                notes.append(f"attempt {attempt}: crashed: {e!r}")
                continue
            results = run.results
            verdict = results.get(VALID)

            if MatrixRunner._final_read_missing(results):
                notes.append(
                    f"attempt {attempt}: final read missing (drain "
                    f"observed nothing — cannot attest loss either way); "
                    f"retrying"
                )
                continue
            if verdict == UNKNOWN:
                notes.append(
                    f"attempt {attempt}: analysis unknown; retrying"
                )
                continue

            if verdict is True:
                if expect == "invalid":
                    notes.append(
                        f"attempt {attempt}: valid, but a seeded bug "
                        f"should have gone red; retrying"
                    )
                    continue
            else:
                if expect == "valid":
                    notes.append(
                        f"attempt {attempt}: analysis invalid — "
                        f"invalidating checkers: "
                        f"{describe_invalid(results)}"
                    )
                    continue

            # verdict matches expectation — run the caller's checks
            # while the cluster is still up (drain cross-checks may
            # query the live brokers)
            if checks is not None:
                try:
                    checks(run)
                except AssertionError as e:
                    notes.append(f"attempt {attempt}: checks failed: {e}")
                    continue
            return run
        finally:
            transport.close()

    raise AssertionError(
        f"live run never reached expect={expect!r} in {max_attempts} "
        f"attempts:\n" + "\n".join(notes)
    )
