"""perf checker: windowed rates/quantiles and plot rendering."""

import numpy as np

from jepsen_tpu.checkers.perf import (
    N_WINDOWS,
    Perf,
    perf_tensor_check,
    render_perf_plots,
)
from jepsen_tpu.history.encode import pack_histories
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import SynthSpec, synth_history


def test_rates_count_every_completion():
    sh = synth_history(SynthSpec(n_ops=300, seed=21))
    packed = pack_histories([sh.ops])
    t = perf_tensor_check(packed)
    rates = np.asarray(t.rates)[0]  # [W, F, T]
    n_completions = sum(
        1 for op in sh.ops if op.type != OpType.INVOKE and op.time >= 0
    )
    assert rates.sum() == n_completions


def test_quantiles_match_known_latencies():
    # all enqueues complete in exactly 5ms -> every quantile bucket edge >= 5
    ms = 1_000_000
    ops = []
    for i in range(20):
        ops.append(Op.invoke(OpF.ENQUEUE, 0, i, time=i * 100 * ms))
        ops.append(Op(OpType.OK, OpF.ENQUEUE, 0, i, time=(i * 100 + 5) * ms))
    packed = pack_histories([reindex(ops)])
    t = perf_tensor_check(packed)
    q = np.asarray(t.quantiles)[0]  # [W, F, 3]
    enq = q[:, 0, :]
    present = enq[enq[:, 0] > 0]
    assert len(present) > 0
    # 5ms falls in a log bucket whose upper edge is within ~35% of 5ms
    assert (present >= 5).all() and (present <= 7).all()


def test_window_covers_history_span():
    sh = synth_history(SynthSpec(n_ops=200, seed=22))
    packed = pack_histories([sh.ops])
    t = perf_tensor_check(packed)
    t_max_ms = max(op.time for op in sh.ops) // 1_000_000
    w = int(np.asarray(t.window_ms)[0])
    assert w * N_WINDOWS >= t_max_ms


def test_perf_checker_and_plots(tmp_path):
    sh = synth_history(SynthSpec(n_ops=200, seed=23))
    res = Perf(out_dir=tmp_path).check({}, sh.ops)
    assert res["valid?"]
    assert (tmp_path / "latency-raw.png").stat().st_size > 1000
    assert (tmp_path / "rate.png").stat().st_size > 1000
    assert res["latency-graph"]["valid?"] and res["rate-graph"]["valid?"]


def test_render_without_latencies(tmp_path):
    # histories with no ok completions must not crash rendering
    ops = reindex([Op.invoke(OpF.DEQUEUE, 0, time=0)])
    packed = pack_histories([ops])
    t = perf_tensor_check(packed)
    paths = render_perf_plots(t, tmp_path)
    assert set(paths) == {"latency-graph", "rate-graph"}


def test_drain_counts_once_in_rates():
    # a drain of k values must count as ONE completion, not k
    ms = 1_000_000
    ops = reindex(
        [
            Op.invoke(OpF.DRAIN, 0, time=1 * ms),
            Op(OpType.OK, OpF.DRAIN, 0, [1, 2, 3, 4], time=2 * ms),
        ]
    )
    t = perf_tensor_check(pack_histories([ops]))
    assert np.asarray(t.rates)[0].sum() == 1
