"""Bit-packed boolean kernels: the round-14 differential suites.

One substrate (``checkers/bitset.py``), three consumer families, zero
verdict divergence allowed:

- primitives — pack/unpack round trips and popcount vs numpy EXACTLY,
  across lane boundaries (n = 31, 32, 33, 1024); the boolean-semiring
  bitmat multiply and the warm-started fixpoint closure vs their dense
  twins on random matrices;
- elle — packed ≡ dense ≡ int8 verdict tensors on the synth corpus
  (every anomaly class) and the 300-history randomized fuzz corpus
  (tier-1 slice here, the full corpus ``slow``);
- WGL — the subset-lattice frontier ≡ the row frontier ≡ the classic
  CPU search on per-value queue classes (synth, hard-generator, and
  adversarial interval fuzz); refuted double-grants, fenced token-order
  violations, and FIFO order violations must SURVIVE packing;
- queue — packed presence-bitplane verdict buffers render result maps
  identical to the dense tensors for both checkers and both delivery
  contracts;
- donation — every ``donated()`` verdict program (and the donating WGL
  bucket programs) marks its staged batch donated in the lowered
  module (the no-copy pin the round-14 satellite calls for).
"""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu.checkers.bitset import (
    bit_transpose,
    bitmat_mul_packed,
    closure_on_cycle_packed,
    closure_packed,
    identity_bits,
    n_words,
    pack_bits,
    pack_bits_np,
    popcount32,
    popcount_bits,
    shift_bitset,
    subset_lattice_tables,
    unpack_bits,
    unpack_bits_np,
)

LANE_SIZES = (31, 32, 33, 1024)


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


class TestPrimitives:
    @pytest.mark.parametrize("n", LANE_SIZES)
    def test_pack_unpack_round_trip(self, n):
        rng = np.random.default_rng(n)
        bits = rng.random((5, n)) < 0.4
        packed = np.asarray(pack_bits(bits))
        assert packed.shape == (5, n_words(n))
        assert packed.dtype == np.uint32
        # jittable pack == numpy pack (the packbits little-endian layout)
        np.testing.assert_array_equal(packed, pack_bits_np(bits))
        # both unpackers invert it
        np.testing.assert_array_equal(np.asarray(unpack_bits(packed, n)), bits)
        np.testing.assert_array_equal(unpack_bits_np(packed, n), bits)

    @pytest.mark.parametrize("n", LANE_SIZES)
    def test_popcount_vs_numpy(self, n):
        rng = np.random.default_rng(100 + n)
        bits = rng.random((7, n)) < 0.5
        packed = pack_bits(bits)
        per_word = np.asarray(popcount32(packed))
        # numpy oracle: bit_count over the packed words
        expect = np.bitwise_count(np.asarray(packed)).astype(np.int32)
        np.testing.assert_array_equal(per_word, expect)
        # total popcount == the number of True bits (pad bits are zero)
        np.testing.assert_array_equal(
            np.asarray(popcount_bits(packed)), bits.sum(-1).astype(np.int32)
        )

    @pytest.mark.parametrize("t", (32, 104, 128))
    def test_bitmat_mul_vs_numpy(self, t):
        rng = np.random.default_rng(t)
        a = rng.random((t, t)) < 0.1
        b = rng.random((t, t)) < 0.1
        got = np.asarray(bitmat_mul_packed(pack_bits(a), pack_bits(b)))
        expect = pack_bits_np((a.astype(np.int32) @ b.astype(np.int32)) > 0)
        np.testing.assert_array_equal(got, expect)

    def test_bit_transpose(self):
        rng = np.random.default_rng(7)
        a = rng.random((96, 96)) < 0.2
        got = np.asarray(bit_transpose(pack_bits(a), 96))
        np.testing.assert_array_equal(got, pack_bits_np(a.T))

    def test_closure_matches_dense_reachability(self):
        rng = np.random.default_rng(11)
        t = 64
        a = rng.random((t, t)) < 0.05
        r0 = np.asarray(pack_bits(a)) | identity_bits(t)
        r = np.asarray(closure_packed(np.asarray(r0), 6))
        # numpy oracle: boolean matrix powers to fixpoint
        m = a | np.eye(t, dtype=bool)
        while True:
            m2 = (m.astype(np.int32) @ m.astype(np.int32)) > 0
            if np.array_equal(m2, m):
                break
            m = m2
        np.testing.assert_array_equal(r, pack_bits_np(m))

    def test_closure_on_cycle_matches_tarjan(self):
        from jepsen_tpu.checkers.elle import _on_cycle_nodes

        rng = np.random.default_rng(13)
        t = 64
        for density in (0.01, 0.05):
            ww = rng.random((t, t)) < density
            wr = rng.random((t, t)) < density
            rw = rng.random((t, t)) < density
            g0, g1c, g2 = (
                np.asarray(x)
                for x in closure_on_cycle_packed(
                    pack_bits(ww), pack_bits(wr), pack_bits(rw), 6
                )
            )
            for got, adj in ((g0, ww), (g1c, ww | wr), (g2, ww | wr | rw)):
                edges = {
                    (int(i), int(j)) for i, j in zip(*np.nonzero(adj))
                }
                expect = _on_cycle_nodes(t, edges)
                assert set(np.nonzero(got)[0].tolist()) == expect

    def test_shift_bitset_matches_numpy(self):
        rng = np.random.default_rng(17)
        for n_bits, shift in ((64, 1), (64, 2), (1024, 4), (1024, 32),
                              (1024, 256), (1024, 1024)):
            bits = rng.random(n_bits) < 0.3
            got = np.asarray(shift_bitset(pack_bits(bits), shift))
            expect = np.zeros(n_bits, bool)
            if shift < n_bits:
                expect[shift:] = bits[: n_bits - shift]
            np.testing.assert_array_equal(got, pack_bits_np(expect))

    def test_subset_lattice_tables(self):
        without, with_ = subset_lattice_tables(4)
        for q in range(4):
            w = unpack_bits_np(with_[q], 16)
            assert set(np.nonzero(w)[0].tolist()) == {
                s for s in range(16) if (s >> q) & 1
            }
            np.testing.assert_array_equal(
                unpack_bits_np(without[q], 16), ~w
            )


# ---------------------------------------------------------------------------
# elle: packed ≡ dense ≡ int8
# ---------------------------------------------------------------------------


def _elle_tensors_equal(mops, modes=("packed", "dense", "int8")):
    from jepsen_tpu.checkers.elle import elle_mops_check

    ref_mode = modes[0]
    ref, _ = elle_mops_check(mops, closure=ref_mode)
    for mode in modes[1:]:
        got, _ = elle_mops_check(mops, closure=mode)
        for fld in ("valid", "g0", "g1c", "g2"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, fld)),
                np.asarray(getattr(got, fld)),
                err_msg=f"elle {fld} diverges: {ref_mode} vs {mode}",
            )


class TestElleClosureParity:
    def test_synth_corpus_all_anomaly_classes(self):
        from jepsen_tpu.checkers.elle import pack_elle_mops
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        shs = synth_elle_batch(3, ElleSynthSpec(n_txns=60))
        for kw in ("g0_cycle", "g1c_cycle", "g2_cycle", "g1a", "g1b"):
            shs += synth_elle_batch(
                2, ElleSynthSpec(n_txns=60, seed=hash(kw) % 1000), **{kw: 1}
            )
        mops, metas = pack_elle_mops([sh.ops for sh in shs])
        assert not any(g.degenerate for g in metas)
        _elle_tensors_equal(mops)

    def test_fuzz_corpus_tier1_slice(self):
        from jepsen_tpu.checkers.elle import split_elle_mops, elle_mops_for
        from tests.test_fuzz_elle_device import fuzz_history

        histories = [fuzz_history(s) for s in range(16)]
        live, mops, degen = split_elle_mops(
            [elle_mops_for(h) for h in histories]
        )
        assert live, "corpus must exercise the device path"
        _elle_tensors_equal(mops)

    def test_full_checker_verdicts_with_packed_default(self):
        """check_elle_batch (which rides DEFAULT_CLOSURE = packed)
        still reports maps identical to the host oracle."""
        from jepsen_tpu.checkers.elle import (
            DEFAULT_CLOSURE,
            check_elle_batch,
            check_elle_cpu,
        )
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        assert DEFAULT_CLOSURE == "packed"
        shs = synth_elle_batch(2, ElleSynthSpec(n_txns=50), g2_cycle=1)
        shs += synth_elle_batch(2, ElleSynthSpec(n_txns=50, seed=9), g1c_cycle=1)
        for sh, r in zip(shs, check_elle_batch([sh.ops for sh in shs])):
            assert r == check_elle_cpu(sh.ops)

    @pytest.mark.slow
    @pytest.mark.parametrize("chunk", range(6))
    def test_fuzz_corpus_heavy(self, chunk):
        """The 300-history randomized corpus (the ISSUE-14 slow slice):
        packed ≡ dense ≡ int8 on every tensor-representable history."""
        from jepsen_tpu.checkers.elle import elle_mops_for, split_elle_mops
        from tests.test_fuzz_elle_device import fuzz_history

        histories = [
            fuzz_history(1000 + chunk * 50 + i, n_txns=40, n_keys=5)
            for i in range(50)
        ]
        live, mops, _degen = split_elle_mops(
            [elle_mops_for(h) for h in histories]
        )
        assert live
        _elle_tensors_equal(mops)


# ---------------------------------------------------------------------------
# WGL: subset lattice ≡ row frontier ≡ classic CPU
# ---------------------------------------------------------------------------


def _pcomp_both_engines(decomps):
    from jepsen_tpu.checkers.wgl_pcomp import (
        bucketize,
        finish_buckets,
        run_bucket,
    )

    out = []
    for subset in (True, False):
        buckets = bucketize(decomps, subset_engine=subset)
        if subset:
            assert any(b.engine == "subset" for b in buckets), (
                "per-value queue classes must ride the subset engine"
            )
        else:
            assert all(b.engine == "rows" for b in buckets)
        results = [run_bucket(b) for b in buckets]
        out.append(finish_buckets(decomps, buckets, results))
    return out


class TestWglSubsetEngine:
    def test_synth_corpus_engines_agree(self):
        from jepsen_tpu.checkers.wgl import check_wgl_cpu, queue_wgl_ops
        from jepsen_tpu.checkers.wgl_pcomp import decompose
        from jepsen_tpu.history.synth import SynthSpec, synth_history
        from jepsen_tpu.models.core import UnorderedQueue

        opss = [
            queue_wgl_ops(
                synth_history(
                    SynthSpec(
                        n_ops=120,
                        seed=700 + s,
                        duplicated=s % 2,
                        unexpected=(s // 2) % 2,
                    )
                ).ops
            )
            for s in range(4)
        ]
        vs = 32 * max(
            1,
            (max((o.call.a0 for ops in opss for o in ops), default=0) + 32)
            // 32,
        )
        mk = (UnorderedQueue, (vs,))
        decomps = [decompose(ops, mk) for ops in opss]
        (ok_s, unk_s, _), (ok_r, unk_r, _) = _pcomp_both_engines(decomps)
        np.testing.assert_array_equal(ok_s, ok_r)
        np.testing.assert_array_equal(unk_s, unk_r)
        assert not unk_s.any()
        for ops, ok in zip(opss, ok_s):
            assert bool(ok) == check_wgl_cpu(ops, UnorderedQueue(vs))["valid?"]

    @pytest.mark.parametrize("window", [0, 2, 4, 6])
    def test_hard_generator_engines_agree(self, window):
        from jepsen_tpu.checkers.wgl import queue_wgl_ops
        from jepsen_tpu.checkers.wgl_pcomp import decompose
        from jepsen_tpu.history.synth import synth_hard_queue_history
        from jepsen_tpu.models.core import UnorderedQueue

        ops = queue_wgl_ops(synth_hard_queue_history(200, window, seed=21))
        vs = 32 * max(
            1, (max((o.call.a0 for o in ops), default=0) + 32) // 32
        )
        decomps = [decompose(ops, (UnorderedQueue, (vs,)))]
        (ok_s, unk_s, _), (ok_r, unk_r, _) = _pcomp_both_engines(decomps)
        assert bool(ok_s[0]) == bool(ok_r[0]) is True
        assert not unk_s[0] and not unk_r[0]

    def test_adversarial_interval_fuzz_vs_classic(self):
        """Randomized single-class op sets — every (inv, ret] window
        shape incl. INF-open ops, retried enqueues, duplicate dequeues
        — through subset vs rows vs the exact classic search."""
        import random

        from jepsen_tpu.checkers.wgl import INF, Call, WglOp, check_wgl_cpu
        from jepsen_tpu.checkers.wgl_pcomp import Decomposition, SubHist
        from jepsen_tpu.models.core import UnorderedQueue

        rng = random.Random(23)
        mk = (UnorderedQueue, (32,))
        n_invalid = 0
        for trial in range(120):
            n = rng.randint(1, 8)
            ops = []
            t = 0
            for i in range(n):
                f = rng.choice(
                    (UnorderedQueue.ENQUEUE, UnorderedQueue.DEQUEUE)
                )
                inv = t
                t += rng.randint(1, 3)
                ret = INF if rng.random() < 0.25 else t + rng.randint(0, 4)
                ops.append(WglOp(Call(f, 0), inv, ret))
            sub = SubHist(
                ops=ops, class_id=0, width=0, src_idx=list(range(n))
            )
            d = Decomposition(
                subs=[sub], model_key=mk, sound=True, kind="per-value",
                n_ops=n,
            )
            (ok_s, unk_s, _), (ok_r, unk_r, _) = _pcomp_both_engines([d])
            cpu = check_wgl_cpu(ops, UnorderedQueue(32))
            assert not unk_s[0], (trial, ops)
            assert bool(ok_s[0]) == cpu["valid?"], (trial, ops, cpu)
            if not unk_r[0]:
                assert bool(ok_r[0]) == bool(ok_s[0]), (trial, ops)
            n_invalid += not cpu["valid?"]
        assert n_invalid > 10, "fuzz corpus must exercise refutations"

    def test_duplicate_dequeue_refuted_by_subset_engine(self):
        from jepsen_tpu.checkers.wgl import Call, WglOp
        from jepsen_tpu.checkers.wgl_pcomp import (
            bucketize,
            decompose,
            pcomp_check_ops,
        )
        from jepsen_tpu.models.core import UnorderedQueue

        E, D = UnorderedQueue.ENQUEUE, UnorderedQueue.DEQUEUE
        ops = [
            WglOp(Call(E, 5), 0, 1),
            WglOp(Call(D, 5), 2, 3),
            WglOp(Call(D, 5), 4, 5),  # the value comes out twice
        ]
        mk = (UnorderedQueue, (32,))
        d = decompose(ops, mk)
        assert {b.engine for b in bucketize([d])} == {"subset"}
        r = pcomp_check_ops(ops, mk)
        assert r["valid?"] is False

    def test_mutex_violations_survive_packing(self):
        """The packed-enabled bucketizer routes mutex classes to the
        ROW engine (state depends on linearization order, not the set)
        and the refuted double-grant / fenced token-order corpus from
        test_wgl_pcomp stays refuted end-to-end."""
        from jepsen_tpu.checkers.wgl import (
            fenced_mutex_wgl_ops,
            mutex_wgl_ops,
        )
        from jepsen_tpu.checkers.wgl_pcomp import (
            bucketize,
            decompose,
            pcomp_check_ops,
        )
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
        from jepsen_tpu.history.synth import MutexSynthSpec, synth_mutex_batch
        from jepsen_tpu.models.core import FencedMutex, OwnedMutex

        shs = [
            s
            for s in synth_mutex_batch(
                4, MutexSynthSpec(n_ops=120, seed=50), n_locks=3,
                double_grant=1,
            )
            if s.double_grant == 1
        ]
        assert shs
        for sh in shs:
            ops = mutex_wgl_ops(sh.ops)
            d = decompose(ops, (OwnedMutex, ()))
            assert all(b.engine == "rows" for b in bucketize([d]))
            r = pcomp_check_ops(ops, (OwnedMutex, ()))
            assert r["valid?"] is False and "invalid-class" in r

        hist = []
        for key, token in ((0, 5), (1, 3), (0, 9), (1, 7), (1, 7)):
            inv = Op.invoke(OpF.ACQUIRE, len(hist))
            hist.append(inv)
            hist.append(inv.complete(OpType.OK, value=[key, token]))
        r = pcomp_check_ops(
            fenced_mutex_wgl_ops(reindex(hist)), (FencedMutex, ())
        )
        assert r["valid?"] is False and r["invalid-class"] == 1

    def test_fifo_order_violation_survives_packing(self):
        """FIFO per-value classes ride the subset engine; the HOST
        pairwise-order half still refutes a non-FIFO interleaving."""
        from jepsen_tpu.checkers.wgl import Call, WglOp
        from jepsen_tpu.checkers.wgl_pcomp import (
            bucketize,
            decompose,
            pcomp_check_ops,
        )
        from jepsen_tpu.models.core import FifoQueue

        E, D = FifoQueue.ENQUEUE, FifoQueue.DEQUEUE
        ops = [
            WglOp(Call(E, 1), 0, 1),
            WglOp(Call(E, 2), 2, 3),  # enq(1) wholly before enq(2)
            WglOp(Call(D, 2), 4, 5),  # ...but 2 comes out, 1 never does
        ]
        mk = (FifoQueue, (8,))
        d = decompose(ops, mk)
        assert d.sound and d.order_ok is False
        assert {b.engine for b in bucketize([d])} == {"subset"}
        r = pcomp_check_ops(ops, mk)
        assert r["valid?"] is False
        assert r["order-violation"] == [1, 2]

    def test_mesh_sharded_pcomp_with_subset_engine(self, cpu_devices):
        from jepsen_tpu.checkers.wgl import queue_wgl_ops
        from jepsen_tpu.checkers.wgl_pcomp import (
            decompose,
            pcomp_tensor_check,
        )
        from jepsen_tpu.history.synth import synth_hard_queue_history
        from jepsen_tpu.models.core import UnorderedQueue
        from jepsen_tpu.parallel.mesh import checker_mesh, sharded_wgl_pcomp

        mesh = checker_mesh(cpu_devices, seq=1)
        opss = [
            queue_wgl_ops(synth_hard_queue_history(60, w, seed=5))
            for w in (0, 2)
        ]
        vs = 32 * max(
            1,
            (max((o.call.a0 for ops in opss for o in ops), default=0) + 32)
            // 32,
        )
        mk = (UnorderedQueue, (vs,))
        ok_s, unk_s, _ = sharded_wgl_pcomp(
            [decompose(ops, mk) for ops in opss], mesh
        )
        ok, unk, _ = pcomp_tensor_check(
            [decompose(ops, mk) for ops in opss]
        )
        np.testing.assert_array_equal(ok_s, ok)
        np.testing.assert_array_equal(unk_s, unk)


# ---------------------------------------------------------------------------
# queue: packed verdict buffers ≡ dense result maps
# ---------------------------------------------------------------------------


class TestQueuePackedVerdicts:
    def _packed_histories(self):
        from jepsen_tpu.history.encode import pack_histories
        from jepsen_tpu.history.synth import SynthSpec, synth_batch

        shs = synth_batch(
            8,  # divisible by the 8-device virtual mesh
            SynthSpec(n_ops=120, n_processes=4),
            lost=1,
            duplicated=1,
            unexpected=1,
        )
        return pack_histories([sh.ops for sh in shs])

    @pytest.mark.parametrize("delivery", ["exactly-once", "at-least-once"])
    def test_combined_check_packed_equals_dense(self, delivery):
        from jepsen_tpu.checkers.fused import combined_tensor_check
        from jepsen_tpu.checkers.queue_lin import (
            QueueLinTensorsPacked,
            queue_lin_tensors_to_results,
        )
        from jepsen_tpu.checkers.total_queue import (
            TotalQueueTensorsPacked,
            _tensors_to_results,
        )

        packed = self._packed_histories()
        tq_d, ql_d = combined_tensor_check(packed, delivery=delivery)
        tq_p, ql_p = combined_tensor_check(
            packed, delivery=delivery, packed_out=True
        )
        assert isinstance(tq_p, TotalQueueTensorsPacked)
        assert isinstance(ql_p, QueueLinTensorsPacked)
        # the packed masks are genuinely 8-32x smaller
        assert tq_p.lost.shape[-1] * 32 == np.asarray(tq_d.lost).shape[-1]
        assert _tensors_to_results(tq_p) == _tensors_to_results(tq_d)
        assert queue_lin_tensors_to_results(
            ql_p
        ) == queue_lin_tensors_to_results(ql_d)

    def test_anomalies_render_identically(self):
        """The synth ground-truth anomalies surface through the packed
        path exactly (sets AND totals — total-queue counts are sums of
        per-value counts, not set sizes)."""
        from jepsen_tpu.checkers.fused import combined_tensor_check
        from jepsen_tpu.checkers.total_queue import (
            _tensors_to_results,
            check_total_queue_cpu,
        )
        from jepsen_tpu.history.encode import pack_histories
        from jepsen_tpu.history.synth import SynthSpec, synth_batch

        shs = synth_batch(
            4, SynthSpec(n_ops=150, seed=31), lost=2, duplicated=2
        )
        packed = pack_histories([sh.ops for sh in shs])
        tq_p, _ = combined_tensor_check(packed, packed_out=True)
        for sh, r in zip(shs, _tensors_to_results(tq_p)):
            assert r == check_total_queue_cpu(sh.ops)

    def test_mesh_sharded_check_packed(self, cpu_devices):
        from jepsen_tpu.checkers.queue_lin import queue_lin_tensors_to_results
        from jepsen_tpu.checkers.total_queue import _tensors_to_results
        from jepsen_tpu.parallel.mesh import (
            checker_mesh,
            shard_packed,
            sharded_check,
        )

        packed = self._packed_histories()
        mesh = checker_mesh(cpu_devices, seq=1)
        placed = shard_packed(packed, mesh)
        tq_p, ql_p = sharded_check(placed, mesh, packed_out=True)
        tq_d, ql_d = sharded_check(placed, mesh, packed_out=False)
        assert _tensors_to_results(tq_p) == _tensors_to_results(tq_d)
        assert queue_lin_tensors_to_results(
            ql_p
        ) == queue_lin_tensors_to_results(ql_d)

    def test_pipeline_family_serves_packed_results(self, tmp_path):
        """The pipeline queue family rides packed verdict buffers by
        default; bytes-to-verdict results must equal the serial dense
        checkers'."""
        import json

        from jepsen_tpu.checkers.queue_lin import check_queue_lin_cpu
        from jepsen_tpu.checkers.total_queue import check_total_queue_cpu
        from jepsen_tpu.history.synth import SynthSpec, synth_batch
        from jepsen_tpu.parallel.pipeline import check_sources

        shs = synth_batch(4, SynthSpec(n_ops=80, seed=3), lost=1)
        paths = []
        for i, sh in enumerate(shs):
            d = tmp_path / f"run{i}"
            d.mkdir()
            p = d / "history.jsonl"
            with open(p, "w") as fh:
                for op in sh.ops:
                    row = {
                        "index": op.index,
                        "type": op.type.name.lower(),
                        "f": op.f.name.lower(),
                        "process": op.process,
                        "value": op.value,
                        "time": op.time,
                    }
                    fh.write(json.dumps(row) + "\n")
            paths.append(str(p))
        results, stats = check_sources("queue", paths, chunk=2)
        assert stats.histories == len(paths)
        for sh, r in zip(shs, results):
            assert r["queue"] == check_total_queue_cpu(sh.ops)
            ql = dict(check_queue_lin_cpu(sh.ops))
            got = dict(r["linear"])
            assert got.pop("delivery") == ql.pop("delivery")
            assert got == ql


# ---------------------------------------------------------------------------
# donation: the staged batch is marked donated in the lowered module
# ---------------------------------------------------------------------------


def _donation_pinned(prog, *args) -> bool:
    """True iff lowering ``prog(*args)`` proves the staged batch was
    donated: either the lowered module carries donation metadata
    (``tf.aliasing_output`` for aliased outputs, ``jax.buffer_donor``
    when the runtime decides later) or jax raised its donated-buffers
    warning (the donation was REQUESTED but this backend/shape pair
    cannot alias it — e.g. every output smaller than every input, the
    usual CPU case).  A program jitted WITHOUT donate_argnums produces
    neither."""
    import warnings

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        txt = prog.lower(*args).as_text()
    if "tf.aliasing_output" in txt or "jax.buffer_donor" in txt:
        return True
    return any(
        "donated buffers were not usable" in str(w.message) for w in caught
    )


class TestDonation:
    def test_donated_queue_stream_elle_programs(self):
        from jepsen_tpu.checkers.elle import elle_mops_check, pack_elle_mops
        from jepsen_tpu.checkers.fused import combined_tensor_check
        from jepsen_tpu.checkers.stream_lin import (
            pack_stream_histories,
            stream_lin_tensor_check,
        )
        from jepsen_tpu.history.encode import pack_histories
        from jepsen_tpu.history.synth import (
            ElleSynthSpec,
            StreamSynthSpec,
            SynthSpec,
            synth_batch,
            synth_elle_batch,
            synth_stream_batch,
        )
        from jepsen_tpu.parallel.pipeline import donated

        q = pack_histories(
            [sh.ops for sh in synth_batch(2, SynthSpec(n_ops=40))]
        )
        s = pack_stream_histories(
            [
                sh.ops
                for sh in synth_stream_batch(2, StreamSynthSpec(n_ops=30))
            ]
        )
        e, _metas = pack_elle_mops(
            [
                sh.ops
                for sh in synth_elle_batch(2, ElleSynthSpec(n_txns=20))
            ]
        )
        import jax

        cases = [
            (
                donated(
                    lambda p: combined_tensor_check(p, packed_out=True),
                    key=("test", "queue-packed"),
                ),
                q,
            ),
            (
                donated(
                    stream_lin_tensor_check, key=("test", "stream")
                ),
                s,
            ),
            (donated(elle_mops_check, key=("test", "elle")), e),
        ]
        for prog, batch in cases:
            assert _donation_pinned(prog, batch)
        # control: an undonated jit of the same program pins NOTHING —
        # the detector really keys on the donation
        assert not _donation_pinned(
            jax.jit(lambda p: combined_tensor_check(p, packed_out=True)), q
        )

    def test_wgl_bucket_programs_donate(self):
        from jepsen_tpu.checkers.wgl import (
            _wgl_program_cached,
            pack_wgl_batch,
            queue_wgl_ops,
        )
        from jepsen_tpu.checkers.wgl_pcomp import (
            _subset_program_cached,
            pack_subset_batch,
        )
        from jepsen_tpu.history.synth import SynthSpec, synth_history
        from jepsen_tpu.models.core import UnorderedQueue

        ops = queue_wgl_ops(synth_history(SynthSpec(n_ops=40)).ops)
        rows = pack_wgl_batch([ops])
        prog = _wgl_program_cached(
            (UnorderedQueue, (32,)), rows.n, 16,
            int(rows.cands.shape[-1]), donate=True,
        )
        assert _donation_pinned(
            prog, rows.f, rows.a0, rows.a1, rows.ret_op, rows.cands
        )

        sub = pack_subset_batch([ops[:3]], 4)
        sprog = _subset_program_cached(4, True)
        assert _donation_pinned(
            sprog, sub.enq, sub.deq, sub.ret_op, sub.cands
        )

    def test_donated_cache_memoizes_by_key(self):
        from jepsen_tpu.checkers.fused import combined_tensor_check
        from jepsen_tpu.parallel.pipeline import donated

        a = donated(
            lambda p: combined_tensor_check(p, packed_out=True),
            key=("test-memo", "exactly-once", "packed"),
        )
        b = donated(
            lambda p: combined_tensor_check(p, packed_out=True),
            key=("test-memo", "exactly-once", "packed"),
        )
        assert a is b
