"""Native C++ AMQP driver against the in-memory mini-broker.

Ports the reference's driver test strategy (``UtilsTest.java:32-99``):
randomized multi-client enqueue/dequeue with random reconnects, then drain,
asserting consumed ∪ drained ≡ published — plus fault-injection runs that
push broker bugs through the full pipeline to the checkers.
"""

import random
import subprocess
from pathlib import Path

import pytest

from jepsen_tpu.client.protocol import DriverTimeout

NATIVE = Path(__file__).resolve().parent.parent / "native"


@pytest.fixture(scope="session")
def native_lib():
    r = subprocess.run(
        ["make", "-C", str(NATIVE)], capture_output=True, text=True
    )
    if r.returncode != 0:
        pytest.skip(f"native build failed:\n{r.stderr}")
    from jepsen_tpu.client import native

    native.load_library()
    native.load_library().amqp_set_logging(0)
    return native


@pytest.fixture()
def broker():
    from jepsen_tpu.harness.broker import MiniAmqpBroker

    b = MiniAmqpBroker().start()
    yield b
    b.stop()


@pytest.fixture(autouse=True)
def _reset_driver(native_lib):
    native_lib.reset(drain_wait_ms=50)
    yield
    native_lib.reset(drain_wait_ms=50)


def _driver(native_lib, broker, **kw):
    kw.setdefault("connect_retry_ms", 3000)
    return native_lib.NativeQueueDriver(
        ["127.0.0.1"], "127.0.0.1", port=broker.port, **kw
    )


def test_enqueue_dequeue_roundtrip(native_lib, broker):
    d = _driver(native_lib, broker)
    d.setup()
    assert d.enqueue(42, 5.0) is True
    assert d.dequeue(5.0) == 42
    assert d.dequeue(1.0) is None  # empty → None (:fail :exhausted)
    d.close()


def test_async_consumer_roundtrip(native_lib, broker):
    d = _driver(native_lib, broker, consumer_type="asynchronous")
    d.setup()
    assert d.enqueue(7, 5.0) is True
    assert d.dequeue(5.0) == 7
    d.close()


def test_confirm_timeout_is_indeterminate(native_lib, broker):
    from jepsen_tpu.client.protocol import DriverTimeout

    broker.drop_confirms = True
    d = _driver(native_lib, broker)
    d.setup()
    with pytest.raises(DriverTimeout):
        d.enqueue(1, 0.3)
    d.close()


def test_drain_returns_outstanding_messages(native_lib, broker):
    d = _driver(native_lib, broker)
    d.setup()
    for v in (1, 2, 3):
        assert d.enqueue(v, 5.0)
    assert d.dequeue(5.0) in (1, 2, 3)
    drained = d.drain()
    assert len(drained) == 2
    assert broker.queue_depth() == 0


def test_reconnect_requeues_unacked(native_lib, broker):
    d = _driver(native_lib, broker)
    d.setup()
    assert d.enqueue(9, 5.0)
    d.reconnect()
    assert d.dequeue(5.0) == 9
    d.close()


@pytest.mark.parametrize("consumer_type", ["polling", "asynchronous", "mixed"])
def test_all_messages_published_are_consumed(native_lib, broker, consumer_type):
    """The UtilsTest invariant (UtilsTest.java:41-99): 5 clients, random
    ops + reconnects, then drain; consumed ∪ drained ≡ published."""
    rng = random.Random(17)
    clients = [
        _driver(native_lib, broker, consumer_type=consumer_type)
        for _ in range(5)
    ]
    clients[0].setup()
    published, consumed = [], []
    value = 0
    for i in range(50):
        c = rng.choice(clients)
        if rng.random() < 0.1:
            c.reconnect()
        if rng.random() < 0.5:
            if c.enqueue(value, 5.0):
                published.append(value)
            value += 1
        else:
            try:
                v = c.dequeue(1.0)
            except DriverTimeout:
                v = None  # async dequeue on empty queue times out
            if v is not None:
                consumed.append(v)
    drained = clients[0].drain()
    assert sorted(consumed + drained) == sorted(published)
    assert broker.queue_depth() == 0


def test_full_run_native_driver_lossy_broker_caught(native_lib):
    """End-to-end: runner + native driver + mini-broker with injected data
    loss → total-queue must flag lost values."""
    from jepsen_tpu.client.protocol import QueueClient
    from jepsen_tpu.client.native import native_driver_factory
    from jepsen_tpu.control.runner import Test, run_test
    from jepsen_tpu.suite import DEFAULT_OPTS, queue_checker, queue_generator
    from jepsen_tpu.harness.broker import MiniAmqpBroker
    import tempfile

    b = MiniAmqpBroker(lose_acked_every=7).start()
    try:
        opts = {
            **DEFAULT_OPTS,
            "rate": 150.0,
            "time-limit": 1.5,
            "time-before-partition": 10.0,  # no partition fires in 1.5s
            "partition-duration": 0.1,
            "recovery-sleep": 0.2,
        }
        test = Test(
            name="native-lossy",
            nodes=["127.0.0.1"],
            client=QueueClient(
                native_driver_factory(
                    ["127.0.0.1"], port=b.port, connect_retry_ms=3000
                )
            ),
            generator=queue_generator(opts),
            checker=queue_checker("tpu", with_perf=False),
            concurrency=4,
            store_root=tempfile.mkdtemp(),
            opts=opts,
        )
        run = run_test(test)
        q = run.results["queue"]
        assert q["attempt-count"] > 20
        assert not q["valid?"]
        assert q["lost-count"] >= 1
    finally:
        b.stop()


# ---------------------------------------------------------------------------
# Stream client (x-queue-type=stream over AMQP 0-9-1) — BASELINE config #4
# ---------------------------------------------------------------------------


def _stream_driver(native_lib, broker, **kw):
    kw.setdefault("connect_retry_ms", 3000)
    return native_lib.NativeStreamDriver("127.0.0.1", port=broker.port, **kw)


def test_stream_append_read_roundtrip(native_lib, broker):
    d = _stream_driver(native_lib, broker)
    d.setup()
    for v in (10, 11, 12):
        assert d.append(v, 5.0) is True
    got = d.read_from(0, 10, 2.0)
    assert got == [[0, 10], [1, 11], [2, 12]]
    d.close()


def test_stream_reads_are_non_destructive(native_lib, broker):
    d = _stream_driver(native_lib, broker)
    d.setup()
    for v in range(5):
        assert d.append(v, 5.0) is True
    first = d.read_from(0, 10, 2.0)
    again = d.read_from(0, 10, 2.0)
    assert first == again == [[o, o] for o in range(5)]
    assert broker.stream_depth() == 5  # nothing consumed


def test_stream_offset_attach(native_lib, broker):
    d = _stream_driver(native_lib, broker)
    d.setup()
    for v in range(6):
        assert d.append(v, 5.0) is True
    got = d.read_from(3, 10, 2.0)
    assert got == [[3, 3], [4, 4], [5, 5]]
    got = d.read_from(2, 2, 2.0)  # max_n caps the batch
    assert got == [[2, 2], [3, 3]]


def test_stream_empty_read(native_lib, broker):
    d = _stream_driver(native_lib, broker)
    d.setup()
    assert d.read_from(0, 10, 1.0) == []


def test_stream_last_offset_probe(native_lib, broker):
    """The x-stream-offset="last" probe (string spec through the C++
    codec and the broker): -1 on an empty log, the final offset after
    appends — the offset proof the client's full read relies on."""
    d = _stream_driver(native_lib, broker)
    d.setup()
    assert d.last_offset(1.0) == -1  # empty: unknown, never 0
    for v in range(4):
        assert d.append(v, 5.0) is True
    assert d.last_offset(2.0) == 3
    # non-destructive: the probe consumed nothing
    assert broker.stream_depth() == 4
    assert d.read_from(0, 10, 2.0) == [[o, o] for o in range(4)]


def test_stream_two_clients_share_the_log(native_lib, broker):
    a = _stream_driver(native_lib, broker)
    b = _stream_driver(native_lib, broker)
    a.setup()
    b.setup()
    assert a.append(1, 5.0) is True
    assert b.append(2, 5.0) is True
    assert a.read_from(0, 10, 2.0) == b.read_from(0, 10, 2.0)


def test_stream_full_pipeline_lossy_broker_caught(native_lib):
    """End-to-end: StreamClient + native driver + lossy fake broker →
    the stream checker must report the lost append."""
    from jepsen_tpu.checkers.stream_lin import check_stream_lin_batch
    from jepsen_tpu.client.native import native_stream_driver_factory
    from jepsen_tpu.client.protocol import StreamClient
    from jepsen_tpu.history.ops import FULL_READ, Op, OpF, reindex
    from jepsen_tpu.harness.broker import MiniAmqpBroker

    b = MiniAmqpBroker(lose_appended_every=5).start()
    try:
        client = StreamClient(
            native_stream_driver_factory(port=b.port),
            publish_confirm_timeout_s=2.0,
            read_timeout_s=2.0,
        ).open({}, "127.0.0.1")
        client.setup({})
        history = []
        for i in range(12):
            inv = Op.invoke(OpF.APPEND, 0, i)
            history.append(inv)
            history.append(client.invoke({}, inv))
        inv = Op.invoke(OpF.READ, 0, FULL_READ)
        history.append(inv)
        history.append(client.invoke({}, inv))
        client.close({})
        r = check_stream_lin_batch([reindex(history)])[0]
        assert not r["valid?"]
        assert r["lost-count"] == 2  # appends 5 and 10 dropped
    finally:
        b.stop()


class TestInteropProbe:
    """Independent-implementation conformance: rabbitmq-c (librabbitmq.so.4,
    shipped with the image) drives the mini broker over TCP.  A shared
    spec misreading between the in-tree C++ codec and the in-tree broker
    cannot survive this — see native/BROKER_NOTE.md."""

    @pytest.fixture(scope="class")
    def probe(self):
        r = subprocess.run(
            ["make", "-C", str(NATIVE), "interop_probe"],
            capture_output=True,
            text=True,
        )
        if r.returncode != 0:
            pytest.skip(f"probe build failed:\n{r.stderr}")
        return NATIVE / "interop_probe"

    def test_rabbitmq_c_interop(self, probe, broker):
        r = subprocess.run(
            [str(probe), "127.0.0.1", str(broker.port)],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PROBE OK" in r.stdout

    def test_rabbitmq_c_interop_tx_and_stream(self, probe, broker):
        """The tx class and the stream subset (x-queue-type declare arg,
        x-stream-offset consume arg, per-delivery offset headers — the
        custom table grammar) conformance-checked through rabbitmq-c's
        own serializer/parser."""
        r = subprocess.run(
            [str(probe), "127.0.0.1", str(broker.port), "tx", "stream"],
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert "tx, stream" in r.stdout


class TestNativeTxn:
    """Elle list-append over AMQP tx (BASELINE config #5 live path)."""

    def _txn_driver(self, native_lib, broker, **kw):
        from jepsen_tpu.client.native import NativeTxnDriver

        kw.setdefault("connect_retry_ms", 3000)
        kw.setdefault("read_timeout_s", 0.4)
        return NativeTxnDriver("127.0.0.1", port=broker.port, **kw)

    def test_txn_commit_roundtrip_and_read_your_writes(
        self, native_lib, broker
    ):
        d = self._txn_driver(native_lib, broker)
        d.setup()
        done = d.txn(
            [["append", 0, 1], ["r", 0, None], ["append", 0, 2]], 5.0
        )
        # read-your-writes: the mid-txn read sees the staged append
        assert done[1] == ["r", 0, [1]]
        d2 = self._txn_driver(native_lib, broker)
        d2.setup()
        done2 = d2.txn([["r", 0, None]], 5.0)
        assert done2 == [["r", 0, [1, 2]]]  # commit made both visible
        d.close()
        d2.close()

    def test_txn_rollback_invisible(self, native_lib, broker):
        lib = native_lib.load_library()
        h = lib.amqp_txn_client_create(
            b"127.0.0.1", broker.port, b"guest", b"guest", 3000
        )
        assert lib.amqp_txn_client_setup(h) == 0
        assert lib.amqp_txn_append(h, 5, 77) == 0
        assert lib.amqp_txn_rollback(h, 5000) == 0
        d = self._txn_driver(native_lib, broker)
        d.setup()
        assert d.txn([["r", 5, None]], 5.0) == [["r", 5, []]]
        lib.amqp_txn_destroy(h)
        d.close()

    def test_live_elle_clean_run_is_valid(self, native_lib, broker):
        from jepsen_tpu.checkers.elle import check_elle_batch, check_elle_cpu
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

        d = self._txn_driver(native_lib, broker)
        d.setup()
        history = []
        ctr = iter(range(1000))
        for i in range(8):
            k = i % 3
            mops = [["append", k, next(ctr)], ["r", k, None]]
            inv = Op.invoke(OpF.TXN, 0, mops)
            history.append(inv)
            done = d.txn(mops, 5.0)
            history.append(inv.complete(OpType.OK, value=done))
        d.close()
        h = reindex(history)
        r = check_elle_cpu(h)
        assert r["valid?"], r
        assert check_elle_batch([h])[0]["valid?"]

    def test_live_elle_g1c_dirty_reads_caught(self, native_lib):
        """Two transactions each read the other's *uncommitted* write
        (broker fault: read-uncommitted visibility) — a wr-cycle the elle
        checker must classify as G1c, through the real native driver."""
        from jepsen_tpu.checkers.elle import check_elle_batch, check_elle_cpu
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
        from jepsen_tpu.harness.broker import MiniAmqpBroker

        b = MiniAmqpBroker(dirty_tx_reads=True).start()
        lib = native_lib.load_library()
        try:
            ha = lib.amqp_txn_client_create(
                b"127.0.0.1", b.port, b"guest", b"guest", 3000
            )
            hb = lib.amqp_txn_client_create(
                b"127.0.0.1", b.port, b"guest", b"guest", 3000
            )
            assert lib.amqp_txn_client_setup(ha) == 0
            assert lib.amqp_txn_client_setup(hb) == 0
            # interleaved: both append, then both read the other's key
            assert lib.amqp_txn_append(ha, 0, 100) == 0
            assert lib.amqp_txn_append(hb, 1, 200) == 0

            def read_key(h, k):
                import ctypes

                vals = (ctypes.c_int * 64)()
                n = lib.amqp_txn_read_key(h, k, 400, vals, 64)
                assert n >= 0
                return [int(vals[i]) for i in range(n)]

            ra = read_key(ha, 1)  # A observes B's uncommitted append
            rb = read_key(hb, 0)  # B observes A's uncommitted append
            assert ra == [200] and rb == [100]
            assert lib.amqp_txn_commit(ha, 5000) == 1
            assert lib.amqp_txn_commit(hb, 5000) == 1

            mops_a = [["append", 0, 100], ["r", 1, ra]]
            mops_b = [["append", 1, 200], ["r", 0, rb]]
            inv_a = Op.invoke(OpF.TXN, 0, mops_a)
            inv_b = Op.invoke(OpF.TXN, 1, mops_b)
            h = reindex(
                [
                    inv_a,
                    inv_b,
                    inv_a.complete(OpType.OK, value=mops_a),
                    inv_b.complete(OpType.OK, value=mops_b),
                ]
            )
            r = check_elle_cpu(h)
            assert not r["valid?"]
            assert r["G1c-count"] == 2 and r["G0-count"] == 0, r
            rt = check_elle_batch([h])[0]
            assert not rt["valid?"] and rt["G1c-count"] == 2
            lib.amqp_txn_destroy(ha)
            lib.amqp_txn_destroy(hb)
        finally:
            b.stop()


class TestDeadLetter:
    """Dead-letter mode (reference Utils.java:55: MESSAGE_TTL 1 s, DLX
    routing, drain reads both queues): an expired message must leave the
    main queue, land in jepsen.queue.dead.letter, and still be recovered
    by the drain — so consumed ∪ drained ≡ published survives expiry."""

    def test_expired_messages_dead_letter_and_drain(self, native_lib, broker):
        import time

        d = _driver(native_lib, broker, dead_letter=True)
        d.setup()
        assert d.enqueue(11, 5.0) is True
        assert d.enqueue(12, 5.0) is True
        time.sleep(1.3)  # > MESSAGE_TTL (1 s): both expire to the DLQ
        assert d.dequeue(0.6) is None  # main queue is empty post-expiry
        assert broker.queue_depth("jepsen.queue.dead.letter") == 2
        drained = d.drain()
        assert sorted(drained) == [11, 12]
        d.close()

    def test_unexpired_messages_stay_consumable(self, native_lib, broker):
        d = _driver(native_lib, broker, dead_letter=True)
        d.setup()
        assert d.enqueue(21, 5.0) is True
        assert d.dequeue(2.0) == 21  # consumed before the TTL fires
        assert broker.queue_depth("jepsen.queue.dead.letter") == 0
        d.close()


class TestNativeMutex:
    """The legacy mutex variant live (``rabbitmq_test.clj:18-44``): a
    single-token quorum-queue lock.  Mutual exclusion comes from holding
    the token un-acked; a dropped connection requeues it — the unfenced-
    lock revocation the checker must see as a double grant."""

    def _lock(self, native_lib, broker, **kw):
        from jepsen_tpu.client.native import NativeMutexDriver

        kw.setdefault("connect_retry_ms", 3000)
        return NativeMutexDriver("127.0.0.1", port=broker.port, **kw)

    def test_acquire_release_roundtrip(self, native_lib, broker):
        a = self._lock(native_lib, broker)
        b = self._lock(native_lib, broker)
        a.setup()
        b.setup()
        assert a.acquire(5.0) is True
        assert b.acquire(5.0) is False  # busy: A holds the token
        assert a.acquire(5.0) is False  # re-acquire by the holder: busy
        assert b.release(5.0) is False  # not the holder
        assert a.release(5.0) is True
        assert b.acquire(5.0) is True  # the token came back
        assert a.release(5.0) is False  # no longer the holder
        assert b.release(5.0) is True
        a.close()
        b.close()

    def test_reconnect_revokes_grant(self, native_lib, broker):
        a = self._lock(native_lib, broker)
        b = self._lock(native_lib, broker)
        a.setup()
        b.setup()
        assert a.acquire(5.0) is True
        a.reconnect()  # the broker requeues A's un-acked token
        assert b.acquire(5.0) is True  # granted: the lock was revoked
        assert a.release(5.0) is False  # A is not the holder any more
        a.close()
        b.close()

    def test_live_mutex_clean_history_is_valid(self, native_lib, broker):
        """Contended acquire/release rounds through the full MutexClient
        op mapping produce a history both WGL engines call linearizable."""
        from jepsen_tpu.checkers.wgl import MutexWgl
        from jepsen_tpu.client.protocol import MutexClient
        from jepsen_tpu.client.native import native_mutex_driver_factory
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

        factory = native_mutex_driver_factory(
            port=broker.port, connect_retry_ms=3000
        )
        base = MutexClient(factory, op_timeout_s=2.0)
        test = {"quorum-initial-group-size": 0}
        clients = [base.open(test, "127.0.0.1") for _ in range(3)]
        for c in clients:
            c.setup(test)
        history = []

        def run(proc, f):
            inv = Op.invoke(f, proc)
            history.append(inv)
            history.append(clients[proc].invoke(test, inv))

        rng = random.Random(7)
        for _ in range(30):
            proc = rng.randrange(3)
            run(proc, rng.choice([OpF.ACQUIRE, OpF.RELEASE]))
        for proc in range(3):  # final release per thread (the generator's)
            run(proc, OpF.RELEASE)
        for c in clients:
            c.close(test)
        h = reindex(history)
        assert any(op.is_ok and op.f == OpF.ACQUIRE for op in h)
        for backend in ("cpu", "tpu"):
            r = MutexWgl(backend=backend).check({}, h)
            assert r["valid?"] is True, (backend, r)

    def test_live_mutex_double_grant_caught(self, native_lib, broker):
        """End-to-end unfenced-lock hazard: the holder's connection blips
        (token requeues broker-side), the next contender is granted, and
        the holder never released — the checker must refute the history."""
        from jepsen_tpu.checkers.wgl import MutexWgl
        from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

        a = self._lock(native_lib, broker)
        b = self._lock(native_lib, broker)
        a.setup()
        b.setup()
        history = []
        inv_a = Op.invoke(OpF.ACQUIRE, 0)
        history.append(inv_a)
        assert a.acquire(5.0) is True
        history.append(inv_a.complete(OpType.OK))
        # network blip: A's client survives but its connection does not —
        # the broker requeues the token; A still believes it holds the lock
        a.reconnect()
        inv_b = Op.invoke(OpF.ACQUIRE, 1)
        history.append(inv_b)
        assert b.acquire(5.0) is True
        history.append(inv_b.complete(OpType.OK))
        a.close()
        b.close()
        h = reindex(history)
        for backend in ("cpu", "tpu"):
            r = MutexWgl(backend=backend).check({}, h)
            assert r["valid?"] is False, (backend, r)


class TestNativeFencedMutex:
    """Fencing-token mode end-to-end over the wire: grants carry
    monotonically increasing tokens in the ``x-fence-token`` header,
    releases publish the token back under ``x-fence-release``, and the
    broker REJECTS (nacks) stale tokens — the green counterpart of the
    unfenced revocation hazard ``TestNativeMutex`` documents."""

    def _lock(self, native_lib, broker, **kw):
        from jepsen_tpu.client.native import NativeMutexDriver

        kw.setdefault("connect_retry_ms", 3000)
        kw.setdefault("fenced", True)
        return NativeMutexDriver("127.0.0.1", port=broker.port, **kw)

    def test_tokens_strictly_increase_across_grants(self, native_lib, broker):
        a = self._lock(native_lib, broker)
        b = self._lock(native_lib, broker)
        a.setup()
        b.setup()
        t1 = a.acquire_fenced(5.0)
        assert t1 > 0
        assert b.acquire_fenced(5.0) == 0  # busy
        assert a.release_fenced(5.0) == t1
        t2 = b.acquire_fenced(5.0)
        assert t2 > t1
        assert b.release_fenced(5.0) == t2
        a.close()
        b.close()

    def test_revocation_regrant_outranks_and_stale_release_fails(
        self, native_lib, broker
    ):
        """The exact shape that REDS unfenced: holder's connection blips,
        token requeues, next contender granted.  Fenced: the re-grant's
        token strictly outranks the revoked one, and the revoked holder's
        release reports failure instead of success."""
        a = self._lock(native_lib, broker)
        b = self._lock(native_lib, broker)
        a.setup()
        b.setup()
        t1 = a.acquire_fenced(5.0)
        assert t1 > 0
        a.reconnect()  # revocation: the broker requeues the grant
        t2 = b.acquire_fenced(5.0)
        assert t2 > t1  # the fence advanced past the revoked token
        assert a.release_fenced(5.0) == 0  # not the holder any more
        assert b.release_fenced(5.0) == t2
        a.close()
        b.close()

    def test_wire_level_stale_release_is_nacked(self, native_lib, broker):
        """A holder whose token was superseded while its CONNECTION
        stayed alive (the replicated dead-owner reap shape) gets a
        broker-side nack: the release publish travels the wire and comes
        back REJECTED."""
        a = self._lock(native_lib, broker)
        a.setup()
        t1 = a.acquire_fenced(5.0)
        assert t1 > 0
        # supersede the token broker-side without touching a's connection
        with broker.state_lock:
            broker._fence_seq += 1
            broker.fences["jepsen.lock"] = broker._fence_seq
        assert a.release_fenced(5.0) == 0  # nacked: stale token
        a.close()

    def test_fenced_history_through_client_is_valid_under_revocation(
        self, native_lib, broker
    ):
        """The MutexClient mapping records tokens into the history; the
        revocation double-grant shape that refutes OwnedMutex checks
        GREEN against the auto-detected FencedMutex model."""
        from jepsen_tpu.checkers.wgl import MutexWgl
        from jepsen_tpu.client.native import native_mutex_driver_factory
        from jepsen_tpu.client.protocol import MutexClient
        from jepsen_tpu.history.ops import Op, OpF, reindex

        factory = native_mutex_driver_factory(
            port=broker.port, connect_retry_ms=3000
        )
        test = {"quorum-initial-group-size": 0, "fenced": True}
        base = MutexClient(factory, op_timeout_s=2.0, fenced=True)
        c0 = base.open(test, "127.0.0.1")
        c1 = base.open(test, "127.0.0.1")
        c0.setup(test)
        c1.setup(test)
        history = []

        def run(client, proc, f):
            inv = Op.invoke(f, proc)
            history.append(inv)
            history.append(client.invoke(test, inv))

        run(c0, 0, OpF.ACQUIRE)          # granted, token recorded
        assert history[-1].is_ok and isinstance(history[-1].value, int)
        c0.driver.reconnect()            # revocation mid-hold
        run(c1, 1, OpF.ACQUIRE)          # re-granted, higher token
        run(c0, 0, OpF.RELEASE)          # stale: FAIL, not silent success
        assert history[-1].is_fail
        run(c1, 1, OpF.RELEASE)
        c0.close(test)
        c1.close(test)
        h = reindex(history)
        r = MutexWgl(backend="cpu").check({}, h)
        assert r["model"] == "fenced-mutex"
        assert r["valid?"] is True, r
        # the SAME run judged unfenced (tokens ignored, holds only)
        # shows the double grant — proof the green is fencing, not luck
        r_unfenced = MutexWgl(backend="cpu", fenced=False).check({}, h)
        assert r_unfenced["valid?"] is False
