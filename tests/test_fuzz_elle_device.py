"""Three-way differential fuzz of the elle edge inference.

The device kernel (``checkers/elle.py`` device inference), the Python
twin (``infer_txn_graph`` — the source of truth), and the native C++
inference (``jt_elle_infer_file``) must report IDENTICAL edge sets,
anomaly sets, and verdicts on randomized histories — including
fail-typed txns, info (indeterminate) ops, partial reads, dropped-middle
reads, phantom values, and reads of failed writes.  Histories the tensor
encoding cannot represent must be flagged degenerate and take the host
fallback (which this corpus deliberately also exercises via cross-key
phantom collisions).

Tier-1 runs a small slice; the heavy corpus is ``slow``.
"""

from __future__ import annotations

import random

import pytest

from jepsen_tpu.checkers.elle import (
    APPEND,
    READ,
    check_elle_batch,
    check_elle_cpu,
    device_txn_graphs,
    infer_txn_graph,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

_GRAPH_FIELDS = ("ww", "wr", "rw", "g1a", "g1b", "incompatible_order")


def fuzz_history(seed: int, n_txns: int = 30, n_keys: int = 4) -> list[Op]:
    """A randomized elle history with anomaly-shaped corruptions.
    Values stay globally unique except the cross-key phantom class
    (seeds ≡ 3 mod 4), which intentionally produces tensor-degenerate
    histories so the fallback path stays in the corpus."""
    rng = random.Random(seed)
    cross_key_phantoms = seed % 4 == 3
    ops: list[Op] = []
    state: dict[int, list[int]] = {}  # committed lists per key
    failed: list[int] = []  # values of definitely-aborted appends
    nv = 0
    phantom = 10_000
    for _ in range(n_txns):
        p = rng.randrange(4)
        n_mops = rng.randint(1, 4)
        mi, md, applied = [], [], []
        for _ in range(n_mops):
            k = rng.randrange(n_keys)
            if rng.random() < 0.5:
                v = nv
                nv += 1
                mi.append([APPEND, k, v])
                md.append([APPEND, k, v])
                applied.append((k, v))
            else:
                base = list(state.get(k, []))
                r = rng.random()
                if r < 0.15 and base:
                    base = base[: rng.randrange(len(base))]  # partial read
                elif r < 0.25 and len(base) > 1:
                    del base[rng.randrange(len(base) - 1)]  # drop mid
                elif r < 0.30 and failed:
                    base.append(rng.choice(failed))  # observe failed write
                elif r < 0.35:
                    if cross_key_phantoms:
                        base.append(10_000 + rng.randrange(30))
                    else:
                        phantom += 1
                        base.append(phantom)
                # read-your-writes: own staged appends after the prefix
                own = [v2 for (k2, v2) in applied if k2 == k]
                mi.append([READ, k, None])
                md.append([READ, k, base + own])
        roll = rng.random()
        t0 = rng.randrange(10**6)
        ops.append(Op.invoke(OpF.TXN, p, mi, time=t0))
        if roll < 0.08:
            ops.append(
                Op(OpType.FAIL, OpF.TXN, p, mi, time=t0 + 1, error="aborted")
            )
            failed.extend(v for (_k, v) in applied)
        elif roll < 0.14:
            ops.append(
                Op(OpType.INFO, OpF.TXN, p, mi, time=t0 + 1, error="timeout")
            )
            if rng.random() < 0.5:  # indeterminate: may have applied
                for k, v in applied:
                    state.setdefault(k, []).append(v)
        else:
            ops.append(Op(OpType.OK, OpF.TXN, p, md, time=t0 + 1))
            for k, v in applied:
                state.setdefault(k, []).append(v)
    return reindex(ops)


def _assert_three_way(histories, tmp_path):
    """Device vs Python vs native on one corpus; returns the degenerate
    count so callers can assert the corpus shape."""
    from jepsen_tpu.history.fastpack import elle_graph_file
    from jepsen_tpu.history.store import read_history, write_history_jsonl

    dev_graphs, degen = device_txn_graphs(histories)
    n_native = 0
    for i, (h, gd) in enumerate(zip(histories, dev_graphs)):
        gp = infer_txn_graph(h)
        for f in _GRAPH_FIELDS:
            assert getattr(gd, f) == getattr(gp, f), (
                f"device/python divergence on {f} (history {i}, "
                f"degenerate={degen[i]}): "
                f"{sorted(getattr(gd, f))} != {sorted(getattr(gp, f))}"
            )
        assert gd.n == gp.n and gd.txn_index == gp.txn_index

        p = tmp_path / f"h{i}.jsonl"
        write_history_jsonl(p, h)
        assert read_history(p) is not None  # round-trips
        gn = elle_graph_file(p)
        if gn is not None:  # None only when the native lib is absent
            n_native += 1
            for f in _GRAPH_FIELDS:
                assert getattr(gn, f) == getattr(gp, f), (
                    f"native/python divergence on {f} (history {i})"
                )

        # verdicts through the full checkers, both consistency models
        for model in ("serializable", "read-committed"):
            rc = check_elle_cpu(h, model=model)
            rd = check_elle_batch([h], model=model)[0]
            assert rc == rd, (
                f"verdict divergence at {model} (history {i}, "
                f"degenerate={degen[i]}):\n{rc}\n{rd}"
            )
    return sum(degen), n_native


def test_fuzz_differential_tier1(tmp_path):
    """Small tier-1 slice: every seed class (clean, corrupted, cross-key
    phantom/degenerate) represented; batch verdicts match per-history
    CPU verdicts; native inference agrees where available."""
    histories = [fuzz_history(s) for s in range(16)]
    n_degen, n_native = _assert_three_way(histories, tmp_path)
    assert n_degen > 0, "corpus must exercise the degenerate fallback"
    assert n_degen < len(histories), "corpus must exercise the device path"


def test_batch_mixes_degenerate_and_device_histories():
    """One batch call splices host-fallback results into device results
    at the right indices."""
    histories = [fuzz_history(s) for s in (3, 0, 7, 1)]  # degen mixed in
    _graphs, degen = device_txn_graphs(histories)
    assert any(degen) and not all(degen)
    rs = check_elle_batch(histories)
    for h, r in zip(histories, rs):
        assert r == check_elle_cpu(h)


@pytest.mark.slow
@pytest.mark.parametrize("chunk", range(6))
def test_fuzz_differential_heavy(tmp_path, chunk):
    """The heavy corpus: 300 randomized histories in 6 chunks."""
    histories = [
        fuzz_history(1000 + chunk * 50 + i, n_txns=40, n_keys=5)
        for i in range(50)
    ]
    _assert_three_way(histories, tmp_path)
