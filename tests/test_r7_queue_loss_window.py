"""The open r7 durable-queue acked-loss window, as a deterministic
seeded regression harness (VERDICT #4; PARITY index row for
``store/soak_r7_30min_5node_queue_red.txt``).

The red soak lost acked messages whose enqueues spanned a
partition → pause → membership-remove(+wipe)+rejoin → kill window.
``tools/repro_r7_queue_loss.py`` replays exactly that window against the
in-process durable replication layer with confirmed-publish traffic and
a broker-faithful sweep-drain; its sibling ``..._broker.py`` does the
same through real AMQP sockets.  The bisect's outcome (this PR):

- the replication layer is CLEAN — across 30+ seeded windows every
  acked value stayed committed and recoverable (the Raft log never lost
  an entry); the window tests below pin that green;
- broker-layer seed 40 REPRODUCED the soak's signature — 180 of 282
  confirmed values "lost" while still READY cluster-wide, because the
  final drain ended early: a quorum-less DEQ answered an authoritative
  ``Basic.Get-Empty`` (the broker conflated committed-empty with
  no-commit) and the native drain's exit counted an all-timeout pass as
  a quiet full pass.  Both halves are FIXED; the drain tests below go
  red under either pre-fix behavior.
"""

import importlib.util
import os

import pytest

_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "repro_r7_queue_loss.py",
)
_spec = importlib.util.spec_from_file_location("repro_r7", _PATH)
repro = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(repro)


def _assert_no_loss(result):
    assert result["lost"] == [], (
        f"acked values lost through the remove+rejoin->kill window: "
        f"{result['lost'][:20]} (post-mortem {result['post']}; "
        f"events {result['events']})"
    )
    assert result["acked"] > 0, "window produced no confirmed publishes"


def test_remove_rejoin_kill_window_loses_nothing_seeded():
    """One seeded window cycle (tier-1 slice): confirmed enqueues across
    partition + forget(+wipe) + rejoin + kill must all be deliverable
    after heal."""
    _assert_no_loss(repro.run_window(seed=10, minutes=0.12))


@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 10, 12, 17])
def test_remove_rejoin_kill_window_seed_sweep(seed):
    """The seeds that surfaced the (harness-artifact) stranded-inflight
    losses during the r7 bisect, at full window length."""
    _assert_no_loss(repro.run_window(seed=seed, minutes=0.4))


# ---------------------------------------------------------------------------
# The r7 loss MECHANISM, pinned red/green: the final drain through a
# no-quorum window.  Broker-layer window sweeps (seed 40 of
# tools/repro_r7_queue_loss_broker.py) reproduced the soak's signature —
# a large block of CONFIRMED values "lost" while still sitting READY
# cluster-wide — because (a) a quorum-less committed-DEQ answered
# Basic.Get-EMPTY (the broker lied: `dequeue` conflated committed-empty
# with no-commit), and (b) the native drain ended on a "quiet" pass even
# when every get had timed out or broken rather than authoritatively
# answered empty.  Both halves are fixed; these tests fail if either
# regresses.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def native_lib():
    import subprocess

    native_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "native",
    )
    r = subprocess.run(
        ["make", "-C", native_dir], capture_output=True, text=True
    )
    if r.returncode != 0:
        pytest.skip(f"native build failed:\n{r.stderr}")
    from jepsen_tpu.client import native

    native.load_library().amqp_set_logging(0)
    return native


def _broker_cluster(n=3):
    import socket as _socket

    from jepsen_tpu.harness.broker import MiniAmqpBroker
    from jepsen_tpu.harness.replication import ReplicatedBackend

    names = [f"n{i}" for i in range(n)]
    peers = {}
    for nm in names:
        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            peers[nm] = ("127.0.0.1", s.getsockname()[1])
    brokers = {}
    for nm in names:
        backend = ReplicatedBackend(
            nm,
            peers,
            election_timeout=(0.15, 0.3),
            heartbeat_s=0.04,
            dead_owner_s=0.8,
            submit_timeout_s=1.0,
        )
        brokers[nm] = MiniAmqpBroker(port=0, replication=backend).start()
    import time as _time

    deadline = _time.monotonic() + 8.0
    while _time.monotonic() < deadline:
        if any(
            b.replication.raft.is_leader() for b in brokers.values()
        ):
            return brokers
        _time.sleep(0.02)
    for b in brokers.values():
        b.stop()
    raise AssertionError("no leader")


def _block_all(brokers):
    names = list(brokers)
    for nm, b in brokers.items():
        for other in names:
            if other != nm:
                b.replication.raft.block(other)


def _heal_all(brokers):
    for b in brokers.values():
        b.replication.raft.unblock_all()


def test_get_without_quorum_is_not_an_empty_answer(native_lib):
    """A quorum-less basic.get must NOT answer Get-Empty (the queue's
    committed state is unknown).  Red before the fix: the broker
    conflated a failed DEQ submit with committed-empty, so a drain pass
    through an election window looked authoritatively clean."""
    native_lib.reset(drain_wait_ms=100)
    brokers = _broker_cluster()
    try:
        lead = next(
            nm
            for nm, b in brokers.items()
            if b.replication.raft.is_leader()
        )
        d = native_lib.NativeQueueDriver(
            ["127.0.0.1"], "127.0.0.1", port=brokers[lead].port,
            connect_retry_ms=2000,
        )
        d.setup()
        assert d.enqueue(7, 5.0) is True
        _block_all(brokers)
        try:
            got = d.dequeue(2.5)
        except Exception:
            got = "error"  # broken connection surfaces: also correct
        assert got != 0 and got is not None, (
            "a quorum-less basic.get answered EMPTY — the committed "
            "value 7 would read as lost through a drain window"
        )
        assert got in ("error", 7), got
    finally:
        _heal_all(brokers)
        for b in brokers.values():
            b.stop()
        native_lib.reset(drain_wait_ms=100)


def test_drain_survives_a_no_quorum_window(native_lib):
    """The seed-40 shape end-to-end: confirmed enqueues, then the whole
    cluster loses quorum exactly as the drain starts; quorum returns
    mid-drain.  The drain must keep passing until a CLEAN quiet pass and
    recover EVERY confirmed value — before the fix it ended on the first
    quiet (all-timeout / all-lied-empty) pass and the checker counted
    the block lost."""
    import threading
    import time as _time

    native_lib.reset(drain_wait_ms=300)
    brokers = _broker_cluster()
    try:
        lead = next(
            nm
            for nm, b in brokers.items()
            if b.replication.raft.is_leader()
        )
        hosts = [f"127.0.0.1:{b.port}" for b in brokers.values()]
        d = native_lib.NativeQueueDriver(
            hosts, "127.0.0.1", port=brokers[lead].port,
            connect_retry_ms=2000,
        )
        d.setup()
        acked = []
        for v in range(1, 9):
            if d.enqueue(v, 5.0) is True:
                acked.append(v)
        assert len(acked) >= 6, f"setup could not confirm enough: {acked}"

        _block_all(brokers)
        drained: list = []

        def run_drain():
            drained.extend(d.drain())

        t = threading.Thread(target=run_drain)
        t.start()
        # outlast the drain's first TWO full passes (~1 s submit
        # timeout per host per get): before the fix the second quiet
        # pass ended the drain right here, with every confirmed value
        # still committed-ready cluster-wide
        _time.sleep(9.0)
        _heal_all(brokers)
        t.join(timeout=60.0)
        assert not t.is_alive(), "drain never finished"
        missing = sorted(set(acked) - set(drained))
        assert missing == [], (
            f"drain ended with committed values still queued: {missing} "
            f"(drained {sorted(drained)})"
        )
    finally:
        _heal_all(brokers)
        for b in brokers.values():
            b.stop()
        native_lib.reset(drain_wait_ms=100)
