"""StreamClient final-read semantics: offset-proof end-of-log.

The final FULL_READ decides the stream verdict, so concluding
"end-of-log" early is the one client bug that manufactures false `lost`
verdicts (advisor r1/r2; the reference's drain analog is
``Utils.java:413-470``, which loops per-host until brokers answer
empty).  These tests drive the client against scripted drivers:

- with the ``x-stream-offset="last"`` proof available, a mid-read broker
  stall of ANY length must not truncate the read;
- a stall that never resolves FAILS the op (an absent final read is
  sound, a truncated one is not);
- without the probe (a driver that cannot answer), the confirmed-empties
  heuristic still terminates the empty-log case.
"""

from jepsen_tpu.client.protocol import StreamClient, StreamDriver
from jepsen_tpu.history.ops import FULL_READ, Op, OpF, OpType


class ScriptedStreamDriver(StreamDriver):
    """A log of ``records``; serves at most ``per_call`` records per read;
    returns empty batches while ``stalls`` has entries for that offset."""

    def __init__(self, records, per_call=5, stalls=None, with_probe=True):
        self.records = list(records)  # [(offset, value)]
        self.per_call = per_call
        self.stalls = dict(stalls or {})  # offset -> remaining empty reads
        self.with_probe = with_probe
        self.probe_calls = 0

    def setup(self):
        pass

    def append(self, value, timeout_s):
        raise AssertionError("not used")

    def read_from(self, offset, max_n, timeout_s):
        if self.stalls.get(offset, 0) > 0:
            self.stalls[offset] -= 1
            return []
        out = [list(p) for p in self.records if p[0] >= offset]
        return out[: min(self.per_call, max_n)]

    def last_offset(self, timeout_s):
        self.probe_calls += 1
        if not self.with_probe or not self.records:
            return -1
        return self.records[-1][0]

    def reconnect(self):
        pass

    def close(self):
        pass


def _client(driver, **kw):
    c = StreamClient(lambda test, node: driver, read_timeout_s=0.05, **kw)
    return c.open({}, "n1")


def _full_read(client):
    return client.invoke({}, Op.invoke(OpF.READ, 0, FULL_READ))


def test_mid_read_stall_does_not_truncate():
    """3 consecutive empty batches mid-log (> 2x the old confirmed-empties
    budget) — with the offset proof the client keeps reading and returns
    the complete log."""
    records = [[o, 100 + o] for o in range(10)]
    d = ScriptedStreamDriver(records, per_call=5, stalls={5: 3})
    r = _full_read(_client(d))
    assert r.type == OpType.OK
    assert r.value == records  # nothing truncated

    # the same stall WITHOUT the probe truncates under the heuristic —
    # this is exactly the gap the offset proof closes (kept as a
    # documented contrast, not a desired behavior)
    d2 = ScriptedStreamDriver(
        records, per_call=5, stalls={5: 3}, with_probe=False
    )
    r2 = _full_read(_client(d2))
    assert r2.type == OpType.OK
    assert r2.value == records[:5]


def test_persistent_stall_fails_instead_of_truncating():
    """Committed records through offset 9 are known; the broker never
    serves past 4 — the op must FAIL (absent read), never OK-truncate."""
    records = [[o, o] for o in range(10)]
    d = ScriptedStreamDriver(records, per_call=5, stalls={5: 10**9})
    r = _full_read(_client(d, full_read_stall_timeout_s=0.3))
    assert r.type == OpType.FAIL
    assert r.error == "timeout"


def test_unanswered_confirm_probe_is_inconclusive():
    """The end-of-log confirm probe returning -1 (unknown) must not be
    taken as proof: the read retries and, if the probe never answers,
    FAILS rather than concluding with possibly-missing commits."""
    records = [[o, o] for o in range(6)]

    class ConfirmGoesDark(ScriptedStreamDriver):
        def last_offset(self, timeout_s):
            self.probe_calls += 1
            return 5 if self.probe_calls == 1 else -1

    d = ConfirmGoesDark(records, per_call=10)
    r = _full_read(_client(d, full_read_stall_timeout_s=0.3))
    assert r.type == OpType.FAIL
    assert r.error == "timeout"


def test_empty_log_terminates_promptly():
    d = ScriptedStreamDriver([])
    r = _full_read(_client(d))
    assert r.type == OpType.OK and r.value == []


def test_concurrent_append_past_first_probe_is_read():
    """The end-of-log confirm re-probes: records committed after the
    first probe (mid-drain appends) are still collected."""
    records = [[o, o] for o in range(6)]

    class Growing(ScriptedStreamDriver):
        def last_offset(self, timeout_s):
            self.probe_calls += 1
            if self.probe_calls == 2 and len(self.records) == 6:
                # between the first probe and the confirm, one more
                # append commits — the confirm must observe it
                self.records.append([6, 6])
            return self.records[-1][0]

    d = Growing(records, per_call=10)
    r = _full_read(_client(d))
    assert r.type == OpType.OK
    assert r.value == [[o, o] for o in range(7)]


def test_sim_driver_answers_the_probe():
    from jepsen_tpu.client.sim import SimCluster, SimStreamDriver

    cluster = SimCluster(["n1", "n2", "n3"])
    d = SimStreamDriver(cluster, "n1")
    assert d.last_offset(1.0) == -1  # empty log: unknown, never 0
    for v in (7, 8):
        assert d.append(v, 1.0) is True
    assert d.last_offset(1.0) == 1
    # a minority node cannot answer: the probe is unknown, not an error
    cluster.set_blocked(
        {frozenset({"n1", "n2"}), frozenset({"n1", "n3"})}
    )
    assert d.last_offset(1.0) == -1
    cluster.heal()
    assert d.last_offset(1.0) == 1
