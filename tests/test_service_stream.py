"""Always-on streaming ingestion service (ISSUE 16): admission,
backpressure, torn-block quarantine, kill-mid-stream recovery — all
differential against the serial :class:`SegmentedChecker` oracle."""

import hashlib
import json
import socket
import struct
import time
import zlib

import numpy as np
import pytest

from jepsen_tpu.checkers.segmented import SegmentedChecker
from jepsen_tpu.history.columnar import iter_row_blocks
from jepsen_tpu.history.rows import _rows_for
from jepsen_tpu.history.synth import SynthSpec, synth_history
from jepsen_tpu.obs.metrics import Registry
from jepsen_tpu.service import (
    CheckerClient,
    CheckerServer,
    RetryPolicy,
    ServiceUnavailable,
)
from jepsen_tpu.service.cache import VerdictCache, cache_key, contract_key
from jepsen_tpu.service.protocol import (
    MAGIC,
    TornPayloadError,
    recv_frame,
    send_frame,
)
from jepsen_tpu.service.stream import SATURATED, IngestService, _wire_safe


def _history(n_ops=400, seed=3, **anoms):
    sh = synth_history(SynthSpec(n_ops=n_ops, seed=seed, **anoms))
    return _rows_for(sh.ops), len(sh.ops)


def _oracle(rows, n_ops):
    eng = SegmentedChecker("queue", device=False)
    eng.feed_rows(rows, n_ops)
    return eng.finish()


def _families_equal(served, oracle):
    """Wire verdicts carry sorted lists for value sets; normalize BOTH
    sides through ``_wire_safe`` so direct (sets), wire-raw (lists) and
    client-desetted (sets again) verdicts all compare."""
    o = _wire_safe(oracle)
    keys = set(o) - {"segmented"}
    s = _wire_safe({k: served.get(k) for k in keys})
    return s == {k: o[k] for k in keys}


def _svc(**kw):
    kw.setdefault("workers", 2)
    kw.setdefault("device", False)
    kw.setdefault("registry", Registry())
    return IngestService(**kw)


def _feed_stream(svc, rows, n_ops, block_rows=128):
    r = svc.open("queue", None, kind="stream", deadline_s=60.0)
    assert r["op"] == "opened"
    sid = r["stream"]
    for seq, (blk, b_ops) in enumerate(iter_row_blocks(rows, block_rows)):
        rep = svc.feed(sid, seq, "rows", blk, b_ops)
        assert rep["op"] == "accepted", rep
    return sid


class TestIngestCore:
    def test_stream_verdict_equals_oracle(self):
        rows, n_ops = _history(lost=1, duplicated=1)
        svc = _svc()
        try:
            sid = _feed_stream(svc, rows, n_ops)
            v = svc.finish(sid, timeout=30)
        finally:
            svc.close()
        assert _families_equal(v, _oracle(rows, n_ops))
        assert v["provenance"]["ops"] >= n_ops
        assert "degraded" not in v  # zero-kill: no recovery story

    def test_submit_collect_verdicts_equal_oracle(self):
        corpus = [_history(n_ops=120, seed=s, lost=s % 2) for s in range(5)]
        svc = _svc()
        try:
            ids = []
            for rows, n_ops in corpus:
                rep = svc.submit("queue", None, "rows", rows, n_ops)
                assert rep["op"] == "accepted"
                ids.append(rep["id"])
            got = svc.collect(ids, timeout=30)
        finally:
            svc.close()
        assert not got["pending"]
        for sid, (rows, n_ops) in zip(ids, corpus):
            assert _families_equal(got["done"][sid], _oracle(rows, n_ops))

    def test_sequence_gap_quarantines_never_gapped_carry(self):
        rows, n_ops = _history()
        svc = _svc()
        try:
            r = svc.open("queue", None, kind="stream")
            sid = r["stream"]
            blocks = list(iter_row_blocks(rows, 128))
            svc.feed(sid, 0, "rows", *blocks[0])
            rep = svc.feed(sid, 2, "rows", *blocks[2])  # hole at seq 1
            assert rep["op"] == "quarantined"
            assert rep["expected"] == 1 and rep["got"] == 2
            v = svc.finish(sid, timeout=30)
        finally:
            svc.close()
        # unknown WITH the gap as evidence — a carry fed around a hole
        # would have fabricated a verdict
        assert v["valid?"] == "unknown"
        assert "gap in block sequence" in json.dumps(v)

    def test_dup_seq_is_idempotent_ack(self):
        rows, n_ops = _history()
        svc = _svc()
        try:
            r = svc.open("queue", None, kind="stream")
            sid = r["stream"]
            blocks = list(iter_row_blocks(rows, 128))
            for seq, (blk, b_ops) in enumerate(blocks):
                svc.feed(sid, seq, "rows", blk, b_ops)
            # a client resend after a reset: acked, never double-fed
            rep = svc.feed(sid, 0, "rows", *blocks[0])
            assert rep["op"] == "accepted" and rep["dup"] is True
            v = svc.finish(sid, timeout=30)
        finally:
            svc.close()
        assert _families_equal(v, _oracle(rows, n_ops))

    def test_abort_frees_admission_slot(self):
        rows, n_ops = _history(n_ops=80)
        svc = _svc(max_streams=1)
        try:
            sid = svc.open("queue", None, kind="stream")["stream"]
            rej = svc.open("queue", None, kind="stream")
            assert rej["op"] == "rejected" and rej["reason"] == SATURATED
            assert svc.abort(sid)["op"] == "aborted"
            again = svc.open("queue", None, kind="stream")
            assert again["op"] == "opened"
        finally:
            svc.close()

    def test_bad_workload_is_a_loud_error(self):
        svc = _svc()
        try:
            r = svc.open("nonesuch", None)
            assert r["op"] == "error" and r["reason"] == "bad-workload"
        finally:
            svc.close()


class TestAdmissionControl:
    def test_stream_cap_rejects_saturated(self):
        svc = _svc(max_streams=2)
        try:
            for _ in range(2):
                assert svc.open("queue", None)["op"] == "opened"
            rej = svc.open("queue", None)
            assert rej["op"] == "rejected"
            assert rej["reason"] == SATURATED
            assert rej["saturated"] == "streams"
        finally:
            svc.close()

    def test_ingress_cap_rejects_block_not_consumed(self):
        rows, n_ops = _history(n_ops=120)
        blocks = list(iter_row_blocks(rows, 64))
        svc = _svc(workers=1, ingress_cap=2, block_delay_s=0.2)
        try:
            sid = svc.open("queue", None, kind="stream")["stream"]
            rejects = 0
            for seq, (blk, b_ops) in enumerate(blocks):
                # the honest client: a SATURATED block was NOT consumed
                # — re-offer the SAME seq until the queue drains
                while True:
                    rep = svc.feed(sid, seq, "rows", blk, b_ops)
                    if rep["op"] == "accepted":
                        break
                    assert rep["op"] == "rejected"
                    assert rep["reason"] == SATURATED
                    rejects += 1
                    time.sleep(0.05)
            assert rejects > 0  # the tiny queue really overflowed
            v = svc.finish(sid, timeout=60)
        finally:
            svc.close()
        # zero silent drops: after honest re-offers the verdict is the
        # oracle's, every block accounted for
        assert _families_equal(v, _oracle(rows, n_ops))
        assert v["provenance"]["blocks"] == len(blocks)

    def test_saturation_accounting_balances(self):
        corpus = [_history(n_ops=60, seed=s) for s in range(24)]
        svc = _svc(workers=1, ingress_cap=2, block_delay_s=0.05)
        try:
            ids, rejects = [], 0
            for rows, n_ops in corpus:
                rep = svc.submit("queue", None, "rows", rows, n_ops)
                if rep["op"] == "accepted":
                    ids.append(rep["id"])
                else:
                    assert rep["op"] == "rejected"
                    rejects += 1
            got = svc.collect(ids, timeout=60)
        finally:
            svc.close()
        assert not got["pending"]
        assert len(corpus) == len(got["done"]) + rejects  # books balance
        assert rejects > 0


class TestChaosRecovery:
    def test_kill_mid_stream_verdicts_equal_oracle(self):
        """Worker 0 dies MID-FEED (after the engine mutation, before
        the ack) under concurrent streams: the PR-13 requeue protocol
        restores from the post-block snapshot and every verdict must
        still equal the serial oracle, with the dead worker named."""
        corpus = [
            _history(n_ops=300, seed=s, duplicated=s % 2)
            for s in range(4)
        ]
        svc = _svc(die_after=(0, 3))
        try:
            sids = [_feed_stream(svc, r, n, block_rows=64) for r, n in corpus]
            verdicts = [svc.finish(s, timeout=60) for s in sids]
            stats = svc.stats()
        finally:
            svc.close()
        assert stats["worker_deaths"] == 1
        degraded = [v for v in verdicts if "degraded" in v]
        assert len(degraded) >= 1
        assert degraded[0]["degraded"]["dead_workers"] == ["svcworker0"]
        assert degraded[0]["degraded"]["requeued_blocks"]
        for v, (rows, n_ops) in zip(verdicts, corpus):
            assert _families_equal(v, _oracle(rows, n_ops))

    def test_all_workers_dead_fails_loud_not_silent(self):
        rows, n_ops = _history(n_ops=200)
        svc = _svc(workers=1, die_after=(0, 1))
        try:
            sid = _feed_stream(svc, rows, n_ops, block_rows=64)
            v = svc.finish(sid, timeout=30)
            rej = svc.open("queue", None)
        finally:
            svc.close()
        assert v["valid?"] == "unknown"
        assert "quarantined" in json.dumps(v)
        assert rej["op"] == "rejected"
        assert rej["saturated"] == "no-live-workers"

    def test_zero_kill_run_claims_no_recovery(self):
        rows, n_ops = _history(n_ops=200)
        svc = _svc()
        try:
            sid = _feed_stream(svc, rows, n_ops)
            v = svc.finish(sid, timeout=30)
            stats = svc.stats()
        finally:
            svc.close()
        assert stats["worker_deaths"] == 0
        assert stats["block_requeues"] == 0
        assert "degraded" not in v


class TestVerdictCache:
    def test_content_addressed_hit_roundtrip(self):
        rows, n_ops = _history(n_ops=200, lost=1)
        key = hashlib.sha256(
            np.ascontiguousarray(rows).tobytes()
        ).hexdigest()
        reg = Registry()
        svc = _svc(cache=VerdictCache(8, registry=reg), registry=reg)
        try:
            rep = svc.submit("queue", None, "rows", rows, n_ops)
            got = svc.collect([rep["id"]], timeout=30)
            cold = got["done"][rep["id"]]
            hit = svc.open("queue", None, content_key=key)
        finally:
            svc.close()
        assert hit["op"] == "cached"
        assert hit["verdict"]["valid?"] == cold["valid?"]

    def test_degraded_verdicts_never_cached(self):
        """Replaying a verdict that reflects THIS run's faults would
        make transient damage permanent."""
        rows, n_ops = _history(n_ops=200)
        key = hashlib.sha256(
            np.ascontiguousarray(rows).tobytes()
        ).hexdigest()
        reg = Registry()
        # one worker: its death is deterministic and the fail-all path
        # quarantines the stream — the faulted verdict must not land in
        # the cache either way
        svc = _svc(
            cache=VerdictCache(8, registry=reg), registry=reg,
            workers=1, die_after=(0, 2),
        )
        try:
            sid = _feed_stream(svc, rows, n_ops, block_rows=64)
            v = svc.finish(sid, timeout=30)
            miss = svc.open("queue", None, content_key=key)
            if miss["op"] == "opened":
                svc.abort(miss["stream"])
        finally:
            svc.close()
        assert "degraded" in v or v["valid?"] == "unknown"
        assert miss["op"] != "cached"

    def test_cache_key_separates_contracts(self):
        k1 = cache_key("c" * 64, "queue", {})
        k2 = cache_key("c" * 64, "queue", {"delivery": "at-least-once"})
        k3 = cache_key("c" * 64, "stream", {})
        assert len({k1, k2, k3}) == 3
        assert contract_key("queue", {"a": 1}) == contract_key(
            "queue", {"a": 1}
        )


@pytest.fixture(scope="module")
def server():
    srv = CheckerServer(host="127.0.0.1", port=0)
    srv.start_background()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture()
def client(server):
    with CheckerClient(port=server.port) as c:
        yield c


class TestWireStreaming:
    def test_wire_stream_equals_oracle_with_sets(self, client):
        rows, n_ops = _history(n_ops=300, lost=1)
        sid = client.stream_open("queue")["stream"]
        for seq, (blk, b_ops) in enumerate(iter_row_blocks(rows, 128)):
            rep = client.stream_feed_rows(sid, seq, blk, b_ops)
            assert rep["op"] == "accepted"
        v = client.stream_finish(sid, timeout=30)
        oracle = _oracle(rows, n_ops)
        keys = set(oracle) - {"segmented"}
        assert {k: v.get(k) for k in keys} == {
            k: oracle[k] for k in keys
        }  # incl. value SETS restored client-side

    def test_submit_batch_and_collect(self, client):
        corpus = [_history(n_ops=100, seed=s) for s in range(3)]
        rep = client.submit_batch_rows(
            "queue", [r for r, _ in corpus], [n for _, n in corpus]
        )
        assert rep["op"] == "submitted"
        ids = [r["id"] for r in rep["replies"]]
        got = client.collect(ids, timeout=30)
        assert not got["pending"]
        for sid, (rows, n_ops) in zip(ids, corpus):
            assert _families_equal(got["done"][sid], _oracle(rows, n_ops))

    def test_torn_block_quarantines_stream_connection_survives(
        self, server, client
    ):
        """A CRC-failed block poisons exactly ITS stream (unknown with
        the torn evidence) — the frame stays in sync, the connection
        and every other stream keep working."""
        rows, n_ops = _history(n_ops=200)
        blocks = list(iter_row_blocks(rows, 128))
        sid = client.stream_open("queue")["stream"]
        client.stream_feed_rows(sid, 0, blocks[0][0], blocks[0][1])

        blk = np.ascontiguousarray(blocks[1][0], np.int32)
        raw = blk.astype(blk.dtype.newbyteorder("<"), copy=False).tobytes()
        hdr = {
            "op": "stream-feed", "stream": sid, "seq": 1,
            "n_ops": blocks[1][1],
            "arrays": [{
                "name": "rows", "dtype": str(blk.dtype),
                "shape": list(blk.shape),
                "crc32": zlib.crc32(raw) ^ 0xDEADBEEF,  # torn
            }],
        }
        hb = json.dumps(hdr).encode()
        client.sock.sendall(
            struct.pack(">4sI", MAGIC, len(hb)) + hb + raw
        )
        reply, _ = recv_frame(client.sock)
        assert reply["op"] == "quarantined"
        assert "torn" in reply["error"]

        v = client.stream_finish(sid, timeout=30)
        assert v["valid?"] == "unknown"
        assert "torn" in json.dumps(v, default=sorted)
        # connection still in frame-sync; an unrelated stream is clean
        assert client.ping()["op"] == "pong"
        rows2, n2 = _history(n_ops=100, seed=9)
        sid2 = client.stream_open("queue")["stream"]
        client.stream_feed_rows(sid2, 0, rows2, n2)
        v2 = client.stream_finish(sid2, timeout=30)
        assert _families_equal(v2, _oracle(rows2, n2))

    def test_service_stats_over_wire(self, client):
        stats = client.service_stats()
        assert stats["op"] == "stats"
        assert "workers_alive" in stats and "admission_rejects" in stats


class TestClientRetry:
    def test_retry_policy_delays_bounded_and_growing(self):
        rp = RetryPolicy(attempts=5, base_s=0.1, cap_s=1.0, jitter=0.5,
                         seed=7)
        rng = __import__("random").Random(7)
        delays = [rp.delay_s(k, rng) for k in range(6)]
        assert all(d <= 1.0 for d in delays)
        assert delays[0] <= 0.1  # jittered below base
        assert max(delays[3:]) >= 0.4  # grew toward the cap

    def test_budget_exhaustion_machine_readable(self, server):
        """A saturated server plus a spent retry budget surfaces as
        ServiceUnavailable with a machine-readable reason — never a raw
        socket error, never a silent drop."""
        svc = server.ingest_service()
        # wedge admission: fill every stream slot
        held = []
        while True:
            r = svc.open("queue", None, kind="stream")
            if r["op"] != "opened":
                break
            held.append(r["stream"])
        try:
            with CheckerClient(
                port=server.port,
                retry=RetryPolicy(attempts=3, base_s=0.01, cap_s=0.02,
                                  seed=1),
            ) as c:
                with pytest.raises(ServiceUnavailable) as ei:
                    c.stream_open("queue")
            reason = ei.value.reason
            assert reason["reason"] == SATURATED
            assert reason["attempts"] == 3
            assert reason["last"]["saturated"] == "streams"
        finally:
            for sid in held:
                svc.abort(sid)


class TestProtocolTorn:
    def test_torn_error_carries_header_and_names(self):
        a, b = socket.socketpair()
        try:
            arr = np.arange(8, dtype=np.int32)
            raw = arr.tobytes()
            hdr = {
                "op": "stream-feed", "stream": "s9", "seq": 4,
                "arrays": [{"name": "rows", "dtype": "int32",
                            "shape": [8], "crc32": zlib.crc32(raw) ^ 1}],
            }
            hb = json.dumps(hdr).encode()
            a.sendall(struct.pack(">4sI", MAGIC, len(hb)) + hb + raw)
            send_frame(a, {"op": "ping"})  # next frame, same socket
            with pytest.raises(TornPayloadError) as ei:
                recv_frame(b)
            assert ei.value.header["stream"] == "s9"
            assert ei.value.torn == ["rows"]
            # the torn frame was fully consumed: the NEXT frame parses
            header, _ = recv_frame(b)
            assert header["op"] == "ping"
        finally:
            a.close()
            b.close()

    def test_crc_optin_roundtrip_clean(self):
        a, b = socket.socketpair()
        try:
            arr = np.arange(6, dtype=np.int32).reshape(2, 3)
            send_frame(a, {"op": "stream-feed"}, {"rows": arr}, crc=True)
            header, arrays = recv_frame(b)
            assert header["arrays"][0]["crc32"] == zlib.crc32(
                arr.tobytes()
            )
            np.testing.assert_array_equal(arrays["rows"], arr)
        finally:
            a.close()
            b.close()


class TestColumnarHelpers:
    def test_iter_row_blocks_covers_and_counts(self):
        rows, n_ops = _history(n_ops=150)
        blocks = list(iter_row_blocks(rows, 64))
        np.testing.assert_array_equal(
            np.concatenate([b for b, _ in blocks]), rows
        )
        assert all(n >= 1 for _, n in blocks)
        with pytest.raises(ValueError):
            list(iter_row_blocks(rows, 0))

    def test_streamed_digest_equals_jtc_content_key(self, tmp_path):
        """The client's block-wise sha256 must equal the server's and
        the ``.jtc`` file's content key — one address, three sites."""
        from jepsen_tpu.history.columnar import (
            payload_sha256,
            read_jtc,
            write_jtc,
        )
        from jepsen_tpu.history.store import write_history_jsonl

        sh = synth_history(SynthSpec(n_ops=150, seed=4))
        src = tmp_path / "h.jsonl"
        write_history_jsonl(src, sh.ops)
        jtc_path = write_jtc(src, "queue", rows=_rows_for(sh.ops))
        jtc, _ = read_jtc(jtc_path)
        key = jtc.content_key()
        assert payload_sha256(jtc_path) == key
        h = hashlib.sha256()
        for kind in sorted(jtc.arrays):
            h.update(np.ascontiguousarray(jtc.arrays[kind]).tobytes())
        assert h.hexdigest() == key
