"""Checker sidecar: framing, round-trip verdicts, differential parity."""

import socket
import threading

import numpy as np
import pytest

from jepsen_tpu.checkers.queue_lin import check_queue_lin_cpu
from jepsen_tpu.checkers.total_queue import check_total_queue_cpu
from jepsen_tpu.history.synth import SynthSpec, synth_batch, synth_history
from jepsen_tpu.service import CheckerClient, CheckerServer
from jepsen_tpu.service.protocol import (
    MAGIC,
    ProtocolError,
    recv_frame,
    send_frame,
)


@pytest.fixture(scope="module")
def server():
    srv = CheckerServer(host="127.0.0.1", port=0)
    srv.start_background()
    yield srv
    srv.shutdown()
    srv.server_close()


@pytest.fixture()
def client(server):
    with CheckerClient(port=server.port) as c:
        yield c


class TestProtocol:
    def test_roundtrip_arrays(self):
        a, b = socket.socketpair()
        try:
            arrays = {
                "x": np.arange(12, dtype=np.int32).reshape(3, 4),
                "m": np.array([[True, False]]),
            }
            send_frame(a, {"op": "check", "k": 1}, arrays)
            header, got = recv_frame(b)
            assert header["op"] == "check" and header["k"] == 1
            np.testing.assert_array_equal(got["x"], arrays["x"])
            np.testing.assert_array_equal(
                got["m"].astype(bool), arrays["m"]
            )
        finally:
            a.close()
            b.close()

    def test_bad_magic_rejected(self):
        a, b = socket.socketpair()
        try:
            a.sendall(b"XXXX" + b"\x00" * 4)
            with pytest.raises(ProtocolError):
                recv_frame(b)
        finally:
            a.close()
            b.close()

    def test_magic_constant(self):
        assert MAGIC == b"JTQ1"


class TestSidecar:
    def test_ping(self, client):
        pong = client.ping()
        assert pong["op"] == "pong"
        assert pong["device_count"] >= 1

    def test_clean_histories_valid(self, client):
        shs = synth_batch(4, SynthSpec(n_ops=120))
        results = client.check_histories([s.ops for s in shs])
        assert len(results) == 4
        assert all(r["valid?"] for r in results)

    def test_verdicts_match_cpu_reference(self, client):
        """Differential: sidecar verdicts ≡ local single-threaded CPU
        checkers, including injected anomalies."""
        specs = [
            SynthSpec(n_ops=150, seed=3),
            SynthSpec(n_ops=150, lost=2, seed=4),
            SynthSpec(n_ops=150, duplicated=2, seed=5),
            SynthSpec(n_ops=150, unexpected=1, seed=6),
        ]
        histories = [synth_history(s).ops for s in specs]
        remote = client.check_histories(histories)
        for h, r in zip(histories, remote):
            cpu_q = check_total_queue_cpu(h)
            cpu_l = check_queue_lin_cpu(h)
            assert r["queue"]["valid?"] == cpu_q["valid?"]
            for k in ("lost", "duplicated", "unexpected", "recovered"):
                assert r["queue"][k] == cpu_q[k], k
            assert r["linear"]["duplicate"] == cpu_l["duplicate"]
            assert r["valid?"] == (cpu_q["valid?"] and cpu_l["valid?"])

    def test_concurrent_clients(self, server):
        shs = synth_batch(2, SynthSpec(n_ops=60))
        histories = [s.ops for s in shs]
        errors = []

        def worker():
            try:
                with CheckerClient(port=server.port) as c:
                    res = c.check_histories(histories)
                    assert len(res) == 2
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors

    def test_unknown_op_is_error_not_disconnect(self, client):
        with pytest.raises(RuntimeError, match="unknown op"):
            client._call({"op": "nonsense"})
        # connection still usable
        assert client.ping()["op"] == "pong"

    def test_bad_value_space_rejected(self, client):
        with pytest.raises(RuntimeError, match="value_space"):
            client._call(
                {"op": "check", "value_space": 0},
                {
                    "f": np.zeros((1, 8), np.int32),
                    "type": np.zeros((1, 8), np.int32),
                    "value": np.zeros((1, 8), np.int32),
                    "mask": np.zeros((1, 8), bool),
                },
            )


class TestDistributedHelpers:
    def test_global_mesh_all_devices(self, cpu_devices):
        from jepsen_tpu.parallel.distributed import global_checker_mesh

        mesh = global_checker_mesh(seq=2)
        assert mesh.shape["hist"] * mesh.shape["seq"] == len(cpu_devices)

    def test_seq_must_divide(self, cpu_devices):
        from jepsen_tpu.parallel.distributed import global_checker_mesh

        with pytest.raises(ValueError):
            global_checker_mesh(seq=3)

    def test_is_coordinator_single_process(self):
        from jepsen_tpu.parallel.distributed import is_coordinator

        assert is_coordinator() is True


class TestStreamAndElleOps:
    def test_check_stream_roundtrip(self, client):
        from jepsen_tpu.checkers.stream_lin import check_stream_lin_cpu
        from jepsen_tpu.history.synth import (
            StreamSynthSpec,
            synth_stream_batch,
        )

        shs = synth_stream_batch(3, StreamSynthSpec(n_ops=80), lost=1)
        results = client.check_stream_histories([sh.ops for sh in shs])
        assert len(results) == 3
        for sh, r in zip(shs, results):
            assert not r["valid?"]
            assert r["stream"] == check_stream_lin_cpu(sh.ops)

    def test_check_elle_roundtrip(self, client):
        from jepsen_tpu.checkers.elle import check_elle_cpu
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        shs = synth_elle_batch(2, ElleSynthSpec(n_txns=40))
        shs += synth_elle_batch(
            1, ElleSynthSpec(n_txns=40, seed=80), g1c_cycle=1
        )
        results = client.check_elle_histories([sh.ops for sh in shs])
        assert [r["valid?"] for r in results] == [True, True, False]
        for sh, r in zip(shs, results):
            assert r["elle"] == check_elle_cpu(sh.ops)

    def test_check_stream_requires_space(self, client):
        with pytest.raises(RuntimeError, match="space"):
            client._call({"op": "check-stream", "space": 0}, {})

    def test_check_elle_requires_histories(self, client):
        with pytest.raises(RuntimeError, match="histories"):
            client._call({"op": "check-elle"})


class TestMeshServer:
    """The sidecar sharding batches over the full (hist, seq) device mesh
    — every op must agree with the single-device server, including batch
    sizes that don't divide the hist axis (masked padding + slice)."""

    @pytest.fixture(scope="class")
    def mesh_server(self, cpu_devices):
        from jepsen_tpu.parallel import checker_mesh

        srv = CheckerServer(
            host="127.0.0.1", port=0, mesh=checker_mesh(cpu_devices, seq=2)
        )
        srv.start_background()
        yield srv
        srv.shutdown()
        srv.server_close()

    @pytest.fixture()
    def mesh_client(self, mesh_server):
        with CheckerClient(port=mesh_server.port) as c:
            yield c

    def test_queue_verdicts_match_cpu(self, mesh_client):
        # B=6 does not divide hist=4: exercises the pad + slice path
        shs = synth_batch(6, SynthSpec(n_ops=40), lost=1)
        results = mesh_client.check_histories([sh.ops for sh in shs])
        assert len(results) == 6
        for sh, r in zip(shs, results):
            ref = check_total_queue_cpu(sh.ops)
            assert r["valid?"] == ref["valid?"]
            assert r["queue"]["lost-count"] == ref["lost-count"]

    def test_stream_verdicts_match_cpu(self, mesh_client):
        from jepsen_tpu.checkers.stream_lin import check_stream_lin_cpu
        from jepsen_tpu.history.synth import (
            StreamSynthSpec,
            synth_stream_batch,
        )

        shs = synth_stream_batch(3, StreamSynthSpec(n_ops=50), lost=1)
        results = mesh_client.check_stream_histories([sh.ops for sh in shs])
        assert len(results) == 3
        for sh, r in zip(shs, results):
            ref = check_stream_lin_cpu(sh.ops)
            assert r["valid?"] == ref["valid?"]
            assert r["stream"]["lost-count"] == ref["lost-count"]

    def test_elle_verdicts_match_cpu(self, mesh_client):
        from jepsen_tpu.checkers.elle import check_elle_cpu
        from jepsen_tpu.history.synth import ElleSynthSpec, synth_elle_batch

        shs = synth_elle_batch(2, ElleSynthSpec(n_txns=30))
        shs += synth_elle_batch(
            1, ElleSynthSpec(n_txns=30, seed=9), g2_cycle=1
        )
        results = mesh_client.check_elle_histories([sh.ops for sh in shs])
        assert len(results) == 3
        for sh, r in zip(shs, results):
            assert r["valid?"] == check_elle_cpu(sh.ops)["valid?"]

    def test_odd_history_length_pads_to_seq(self, mesh_client):
        # L=101 does not divide seq=2: the server must pad masked rows,
        # not error (regression: shard_map rejects indivisible op axes)
        shs = synth_batch(2, SynthSpec(n_ops=30), lost=1)
        results = mesh_client.check_histories(
            [sh.ops for sh in shs], length=101
        )
        assert len(results) == 2
        for sh, r in zip(shs, results):
            ref = check_total_queue_cpu(sh.ops)
            assert r["valid?"] == ref["valid?"]
