"""Multi-chip readiness capture (tools/capture_multichip.py).

VERDICT r4 #7: when a backend with >1 device appears, the capture must
run every sharded checker family on the real mesh and leave a
provenance-stamped ``MULTICHIP_DETAILS.json``; single-device runs must
record the skip instead.  These tests drive the tool on the virtual
8-device CPU mesh the conftest pins.
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "capture_multichip_under_test",
        os.path.join(
            os.path.dirname(__file__), "..", "tools", "capture_multichip.py"
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_capture_runs_all_families_on_virtual_mesh(tmp_path):
    tool = _load_tool()
    out_path = str(tmp_path / "MULTICHIP_DETAILS.json")
    out = tool.capture(out_path)
    assert out["skipped"] is False
    assert out["n_devices"] == 8
    assert out["mesh"] == {"hist": 4, "seq": 2}
    assert set(out["families"]) == {
        "queue", "stream", "elle", "mutex", "pipeline_scaleout",
        "global_mesh",
    }
    for fam, row in out["families"].items():
        if fam in ("pipeline_scaleout", "global_mesh"):
            continue  # their schemas are asserted below
        assert row["valid_all"] is True, (fam, row)
        assert row["steady_run_ms"] > 0
    # the ISSUE-18 closure provenance: on the seq=2 virtual mesh the
    # packed multi-chip path must LOWER, not fall back
    assert out["families"]["elle"]["closure"] == "packed-sharded"
    assert out["families"]["elle"]["dense_fallbacks"] == 0
    # the armed global-mesh arm: a real 2-process fleet on one
    # jax.distributed mesh, outcome recorded either way — on the
    # virtual CPU mesh it must succeed cleanly
    gm = out["families"]["global_mesh"]
    assert gm["ok"] is True, gm
    assert gm["procs"] == 2 and gm["verdict"]["histories"] > 0
    assert gm["degraded"]["dead_workers"] == []
    # the armed scale-out harness: meshed multi-lane bytes-to-verdict
    # with the collective reduction, per family
    so = out["families"]["pipeline_scaleout"]
    assert so["lanes"] == 8
    for fam in ("stream", "elle"):
        assert so[fam]["e2e_histories_per_sec"] > 0, so
        assert so[fam]["invalid"] > 0  # seeded anomalies must surface
        assert so[fam]["histories"] > 0
    assert out["provenance"]["git_rev"] != "unknown"
    # the artifact landed on disk, identically
    assert json.loads(open(out_path).read())["families"].keys() == \
        out["families"].keys()


def test_cpu_capture_never_clobbers_a_chip_capture(tmp_path):
    tool = _load_tool()
    out_path = str(tmp_path / "MULTICHIP_DETAILS.json")
    with open(out_path, "w") as fh:
        json.dump({"backend": "tpu", "n_devices": 8, "families": {}}, fh)
    out = tool.capture(out_path)
    assert out["not_written"] == "existing tpu capture kept"
    assert json.loads(open(out_path).read())["backend"] == "tpu"


def test_cpu_capture_refused_at_default_artifact_path(tmp_path, monkeypatch):
    """A virtual-mesh (cpu) run must never leave a file at the DEFAULT
    artifact path — one `git add -A` away from shipping virtual numbers
    under the multichip-evidence filename."""
    tool = _load_tool()
    monkeypatch.setattr(
        tool, "OUT_PATH", str(tmp_path / "MULTICHIP_DETAILS.json")
    )
    out = tool.capture(tool.OUT_PATH)
    assert out["not_written"] == (
        "cpu capture refused at the default artifact path"
    )
    assert not os.path.exists(tool.OUT_PATH)
