"""Control plane: exec DSL, RabbitMQ DB choreography, iptables nemesis.

The reference only exercises this layer against live clusters; here the
command *choreography* is unit-tested against a scripted transport (boot
order, join sequence, iptables rules), which is what the reference's CI
debugging actually depends on.
"""

import concurrent.futures

import pytest

from jepsen_tpu.control.db_rabbitmq import CTL, RabbitMQDB, SERVER_DIR
from jepsen_tpu.control.net import IptablesNet, complete_grudges, undirected
from jepsen_tpu.control.nemesis import PartitionNemesis, STRATEGIES
from jepsen_tpu.control.ssh import (
    Control,
    FakeTransport,
    RemoteError,
    RunResult,
)

NODES = ["n1", "n2", "n3"]
TEST_MAP = {
    "archive-url": "https://example.com/rabbitmq-server-generic-unix.tar.xz",
    "net-ticktime": 15,
}


def test_exec_quotes_and_raises():
    t = FakeTransport(responses={"false": RunResult(1, "", "boom")})
    c = Control(t, "n1")
    c.exec("echo", "hello world")
    assert t.commands("n1")[-1] == "echo 'hello world'"
    with pytest.raises(RemoteError):
        c.exec(shell="false")


def test_su_wraps_with_sudo():
    t = FakeTransport()
    Control(t, "n1").su().exec("whoami")
    assert t.commands("n1")[-1] == "sudo sh -c whoami"


def test_write_file_substitutes_vars():
    t = FakeTransport()
    Control(t, "n1").write_file(
        "ticktime = $NET_TICKTIME\n", "/etc/x", {"NET_TICKTIME": 15}
    )
    assert t.files[("n1", "/etc/x")] == b"ticktime = 15\n"


def _setup_all(db, transport):
    with concurrent.futures.ThreadPoolExecutor(len(NODES)) as pool:
        list(pool.map(lambda n: db.setup(TEST_MAP, n), NODES))


def _uploaded(t: FakeTransport, node: str, final_path: str) -> bytes | None:
    """Content written to ``final_path`` — directly, or staged through /tmp
    and ``mv``'d by a sudo write_file."""
    direct = t.files.get((node, final_path))
    if direct is not None:
        return direct
    import re

    for cmd in t.commands(node):
        m = re.search(rf"mv (\S+) {re.escape(final_path)}", cmd)
        if m:
            return t.files.get((node, m.group(1)))
    return None


@pytest.fixture()
def db_and_transport():
    t = FakeTransport(
        # Erlang probe succeeds → skip apt installation
        responses={"erl -noshell": RunResult(0, "", "")}
    )
    db = RabbitMQDB(
        t,
        NODES,
        primary_wait_s=0.01,
        secondary_wait_s=0.01,
        join_stagger_max_s=0.01,
        seed=7,
    )
    return db, t


def test_setup_choreography(db_and_transport):
    db, t = db_and_transport
    _setup_all(db, t)
    # every node: cleanup, archive install, configs, cookie
    for n in NODES:
        cmds = t.commands(n)
        assert any("killall" in c for c in cmds)
        assert any("tar xf" in c and SERVER_DIR in c for c in cmds)
        assert _uploaded(t, n, f"{SERVER_DIR}/etc/rabbitmq/rabbitmq.conf")
        advanced = _uploaded(
            t, n, f"{SERVER_DIR}/etc/rabbitmq/advanced.config"
        )
        assert advanced and b"net_ticktime, 15" in advanced
        assert _uploaded(t, n, "/root/.erlang.cookie") == b"jepsen-rabbitmq"
    # primary boots + khepri; secondaries join the primary
    assert any("rabbitmq-server -detached" in c for c in t.commands("n1"))
    assert any("khepri_db" in c for c in t.commands("n1"))
    for n in ("n2", "n3"):
        cmds = t.commands(n)
        join = [c for c in cmds if "join_cluster" in c]
        assert join and "rabbit@n1" in join[0]
        # stop_app before join, start_app after
        assert cmds.index(
            next(c for c in cmds if "stop_app" in c)
        ) < cmds.index(join[0])
        assert cmds.index(join[0]) < cmds.index(
            next(c for c in cmds if "start_app" in c)
        )


def test_primary_boots_before_secondaries_join(db_and_transport):
    db, t = db_and_transport
    _setup_all(db, t)
    full_log = t.log
    primary_boot = next(
        i
        for i, (n, c) in enumerate(full_log)
        if n == "n1" and "rabbitmq-server -detached" in c
    )
    first_join = next(
        i for i, (_n, c) in enumerate(full_log) if "join_cluster" in c
    )
    assert primary_boot < first_join


def test_teardown_dumps_quorum_status(db_and_transport):
    db, t = db_and_transport
    db.teardown(TEST_MAP, "n1")
    cmds = t.commands("n1")
    assert any("jepsen.queue" in c and "sys:get_status" in c for c in cmds)
    assert any("rabbit_fifo_dlx_sup" in c for c in cmds)


def test_log_files_and_collect(db_and_transport, tmp_path):
    db, t = db_and_transport
    paths = db.log_files(TEST_MAP, "n2")
    assert any("rabbit@n2.log" in p for p in paths)
    t.files[("n2", paths[0])] = b"broker log line"
    dest = tmp_path / "rabbit.log"
    assert db.collect_log(TEST_MAP, "n2", paths[0], dest)
    assert dest.read_bytes() == b"broker log line"
    assert not db.collect_log(TEST_MAP, "n2", "/nope", tmp_path / "x")


def test_setup_failure_aborts_barrier(db_and_transport):
    # a failing node must not leave peers blocked on the setup barrier
    import threading

    db, t = db_and_transport
    t.responses["tar xf"] = RunResult(1, "", "download broken")
    errors = []

    def run_one(n):
        try:
            db.setup(TEST_MAP, n)
        except Exception as e:
            errors.append(type(e).__name__)

    threads = [
        threading.Thread(target=run_one, args=(n,), daemon=True)
        for n in NODES
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not any(th.is_alive() for th in threads), "setup deadlocked"
    assert len(errors) == len(NODES)


def test_sudo_write_file_stages_through_tmp():
    t = FakeTransport()
    Control(t, "n1").su().write_file("cookie", "/root/.erlang.cookie")
    put = next(c for c in t.commands("n1") if c.startswith("PUT"))
    assert "/tmp/.jepsen-upload-" in put
    assert any(
        "mv" in c and "/root/.erlang.cookie" in c for c in t.commands("n1")
    )


def test_queue_lengths_parse(db_and_transport):
    db, t = db_and_transport
    t.responses["list_queues"] = RunResult(
        0, "jepsen.queue\t0\njepsen.queue.dead.letter\t3\n", ""
    )
    assert db.queue_lengths("n1") == {
        "jepsen.queue": 0,
        "jepsen.queue.dead.letter": 3,
    }


def test_iptables_partition_and_heal():
    t = FakeTransport()
    net = IptablesNet(t, NODES)
    net.partition(complete_grudges([["n1"], ["n2", "n3"]]))
    n1 = t.commands("n1")
    assert any("iptables -A INPUT -s n2 -j DROP" in c for c in n1)
    assert any("iptables -A INPUT -s n3 -j DROP" in c for c in n1)
    assert any("iptables -A INPUT -s n1 -j DROP" in c for c in t.commands("n2"))
    net.heal()
    assert any("iptables -F" in c for c in t.commands("n1"))


@pytest.mark.parametrize("strategy", sorted(STRATEGIES))
def test_nemesis_drives_iptables(strategy):
    from jepsen_tpu.history.ops import Op, OpF, OpType

    nodes5 = [f"n{i}" for i in range(1, 6)]
    t = FakeTransport()
    nem = PartitionNemesis(strategy, IptablesNet(t, nodes5), nodes5, seed=3)
    nem.setup({})
    start = Op.invoke(OpF.START, -1)
    done = nem.invoke({}, start)
    assert done.type == OpType.INFO
    assert any("iptables -A" in c for _n, c in t.log)
    nem.invoke({}, Op.invoke(OpF.STOP, -1))
    assert any("iptables -F" in c for _n, c in t.log)


def test_grudges_shapes():
    g = STRATEGIES["partition-random-node"](NODES, __import__("random").Random(1))
    blocked = undirected(g)
    # one node isolated from the other two
    assert len(blocked) == 2
    g5 = STRATEGIES["partition-majorities-ring"](
        [f"n{i}" for i in range(1, 6)], __import__("random").Random(1)
    )
    # every node cuts exactly the 2 non-adjacent peers
    assert all(len(b) == 2 for b in g5.values())


def test_build_rabbitmq_test_elle_constructs():
    """The live elle workload is buildable (tx support landed in the
    native driver) — client/generator/checker wired, no NotImplementedError."""
    from jepsen_tpu.client.protocol import TxnClient
    from jepsen_tpu.control.ssh import FakeTransport
    from jepsen_tpu.suite import build_rabbitmq_test

    test = build_rabbitmq_test(
        workload="elle", transport=FakeTransport()
    )
    assert isinstance(test.client, TxnClient)
    assert test.name == "rabbitmq-elle-txn"


def test_build_rabbitmq_test_mutex_constructs():
    """The live mutex workload is buildable (single-token lock landed in
    the native driver) — client/generator/checker wired, no
    NotImplementedError."""
    from jepsen_tpu.client.protocol import MutexClient
    from jepsen_tpu.control.ssh import FakeTransport
    from jepsen_tpu.suite import build_rabbitmq_test

    test = build_rabbitmq_test(
        workload="mutex", transport=FakeTransport()
    )
    assert isinstance(test.client, MutexClient)
    assert test.name == "rabbitmq-mutex"


def test_rabbitmq_procs_command_stream():
    """Process-fault surface over SSH: kill/restart/pause/resume issue the
    expected commands on the right node."""
    from jepsen_tpu.control.db_rabbitmq import RabbitMQProcs

    t = FakeTransport()
    procs = RabbitMQProcs(t, NODES)
    procs.kill("n2")
    procs.restart("n2")
    procs.pause("n1")
    procs.resume("n1")
    cmds = [(n, c) for n, c in t.log]
    assert any(n == "n2" and "killall -q -9 beam.smp" in c for n, c in cmds)
    assert any(
        n == "n2" and "rabbitmq-server -detached" in c for n, c in cmds
    )
    assert any(n == "n1" and "killall -q -STOP beam.smp" in c for n, c in cmds)
    assert any(n == "n1" and "killall -q -CONT beam.smp" in c for n, c in cmds)


def test_process_nemesis_start_stop_cycle():
    """ProcessNemesis: start picks one victim, stop restores every victim;
    teardown restores leftovers."""
    from jepsen_tpu.control.nemesis import ProcessNemesis
    from jepsen_tpu.history.ops import Op, OpF

    class Log:
        def __init__(self):
            self.calls = []

        def kill(self, n):
            self.calls.append(("kill", n))

        def restart(self, n):
            self.calls.append(("restart", n))

        def pause(self, n):
            self.calls.append(("pause", n))

        def resume(self, n):
            self.calls.append(("resume", n))

    procs = Log()
    nem = ProcessNemesis("kill", procs, NODES, seed=3)
    start = Op.invoke(OpF.START, -1)
    stop = Op.invoke(OpF.STOP, -1)
    r = nem.invoke({}, start)
    assert r.value.startswith("kill ")
    victim = r.value.split()[1]
    assert procs.calls == [("kill", victim)]
    nem.invoke({}, stop)
    assert procs.calls[-1] == ("restart", victim)
    # teardown restores a victim left behind by an aborted run
    nem.invoke({}, start)
    nem.teardown({})
    assert procs.calls[-1][0] == "restart" and not nem.victims


def test_process_nemesis_consecutive_starts_pick_fresh_victims():
    """Consecutive starts must each inject a NEW fault (never re-kill an
    already-down node and claim 'kill n' in the history); once every node
    is down, the op records 'already-down' instead of a fresh kill."""
    from jepsen_tpu.control.nemesis import ProcessNemesis
    from jepsen_tpu.history.ops import Op, OpF

    class Log:
        def __init__(self):
            self.calls = []

        def kill(self, n):
            self.calls.append(("kill", n))

        def restart(self, n):
            self.calls.append(("restart", n))

    procs = Log()
    nem = ProcessNemesis("kill", procs, NODES, seed=0)
    start = Op.invoke(OpF.START, -1)
    victims = [nem.invoke({}, start).value.split()[1] for _ in NODES]
    assert sorted(victims) == sorted(NODES)  # each start hit a fresh node
    assert [c for c in procs.calls if c[0] == "kill"] == [
        ("kill", v) for v in victims
    ]
    r = nem.invoke({}, start)  # all down now
    assert r.value.startswith("already-down")
    assert len([c for c in procs.calls if c[0] == "kill"]) == len(NODES)


def test_make_nemesis_selection():
    from jepsen_tpu.control.nemesis import (
        PartitionNemesis,
        ProcessNemesis,
        make_nemesis,
    )
    from jepsen_tpu.control.net import SimProcs

    net = IptablesNet(FakeTransport(), NODES)
    assert isinstance(
        make_nemesis(
            {"nemesis": "partition",
             "network-partition": "partition-halves"},
            net, None, NODES,
        ),
        PartitionNemesis,
    )
    nem = make_nemesis(
        {"nemesis": "pause-random-node"}, net, SimProcs(None), NODES
    )
    assert isinstance(nem, ProcessNemesis) and nem.mode == "pause"
    with pytest.raises(ValueError):
        make_nemesis({"nemesis": "meteor-strike"}, net, None, NODES)


# ---------------------------------------------------------------------------
# SshTransport against a fake `ssh` on PATH (VERDICT r3 #5: this is the
# one load-bearing class that would otherwise first run in production —
# the image has no ssh binary and no network)
# ---------------------------------------------------------------------------


import json as _json
import os as _os
import stat as _stat
import sys as _sys

import pytest as _pytest

from jepsen_tpu.control.ssh import RemoteError, SshTransport


@_pytest.fixture
def fake_ssh(tmp_path, monkeypatch):
    """A fake `ssh` prepended to PATH: records argv (JSON-per-line) and
    stdin to files, then behaves per env knobs FAKE_SSH_RC /
    FAKE_SSH_OUT / FAKE_SSH_ERR / FAKE_SSH_SLEEP."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    argv_log = tmp_path / "argv.jsonl"
    stdin_log = tmp_path / "stdin.bin"
    script = bindir / "ssh"
    script.write_text(
        "#!"
        + _sys.executable
        + "\n"
        + f"""
import json, os, sys, time
with open({str(argv_log)!r}, "a") as fh:
    fh.write(json.dumps(sys.argv[1:]) + "\\n")
data = sys.stdin.buffer.read() if not sys.stdin.isatty() else b""
with open({str(stdin_log)!r}, "ab") as fh:
    fh.write(data)
time.sleep(float(os.environ.get("FAKE_SSH_SLEEP", "0")))
sys.stdout.write(os.environ.get("FAKE_SSH_OUT", ""))
sys.stderr.write(os.environ.get("FAKE_SSH_ERR", ""))
sys.exit(int(os.environ.get("FAKE_SSH_RC", "0")))
"""
    )
    script.chmod(script.stat().st_mode | _stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{bindir}{_os.pathsep}{_os.environ['PATH']}")

    class Shim:
        def argv_calls(self):
            if not argv_log.exists():
                return []
            return [
                _json.loads(line)
                for line in argv_log.read_text().splitlines()
            ]

        def stdin_bytes(self):
            return stdin_log.read_bytes() if stdin_log.exists() else b""

    return Shim()


def test_ssh_args_construction_snapshot(fake_ssh, monkeypatch):
    """The exact argv contract: options, port, key, control-persist,
    user@host, then the command string as ONE argv element."""
    t = SshTransport(user="admin", private_key="/k/id", port=2222,
                     connect_timeout=7)
    monkeypatch.setenv("FAKE_SSH_OUT", "hi\n")
    r = t.run("n1.example", "echo hi")
    assert (r.rc, r.out) == (0, "hi\n")
    (argv,) = fake_ssh.argv_calls()
    assert argv == [
        "-o", "BatchMode=yes",
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", "LogLevel=ERROR",
        "-o", "ConnectTimeout=7",
        "-p", "2222",
        "-o", "ControlMaster=auto",
        "-o", "ControlPath=/tmp/jepsen-tpu-ssh-admin-%h-%p",
        "-o", "ControlPersist=60",
        "-i", "/k/id",
        "admin@n1.example",
        "echo hi",
    ]


def test_ssh_args_minimal_no_key_no_persist(fake_ssh):
    t = SshTransport(control_persist=False)
    t.run("db1", "true")
    (argv,) = fake_ssh.argv_calls()
    assert "-i" not in argv
    assert not any("ControlMaster" in a for a in argv)
    assert argv[-2:] == ["root@db1", "true"]


def test_run_maps_rc_stdout_stderr(fake_ssh, monkeypatch):
    monkeypatch.setenv("FAKE_SSH_RC", "3")
    monkeypatch.setenv("FAKE_SSH_OUT", "partial")
    monkeypatch.setenv("FAKE_SSH_ERR", "boom")
    r = SshTransport().run("n1", "failing-cmd")
    assert (r.rc, r.out, r.err) == (3, "partial", "boom")


def test_run_timeout_is_remote_error(fake_ssh, monkeypatch):
    monkeypatch.setenv("FAKE_SSH_SLEEP", "5")
    with _pytest.raises(RemoteError) as ei:
        SshTransport().run("n1", "sleepy", timeout=0.3)
    assert "timed out" in str(ei.value)


def test_put_pipes_content_through_cat(fake_ssh):
    t = SshTransport()
    t.put("n1", b"\x00binary\xff", "/etc/rabbitmq/rabbitmq.conf")
    (argv,) = fake_ssh.argv_calls()
    assert argv[-1] == "cat > /etc/rabbitmq/rabbitmq.conf"
    assert fake_ssh.stdin_bytes() == b"\x00binary\xff"


def test_put_nonzero_rc_raises(fake_ssh, monkeypatch):
    monkeypatch.setenv("FAKE_SSH_RC", "1")
    monkeypatch.setenv("FAKE_SSH_ERR", "read-only fs")
    with _pytest.raises(RemoteError) as ei:
        SshTransport().put("n1", b"x", "/nope")
    assert "read-only fs" in str(ei.value)


def test_get_streams_to_local_file(fake_ssh, tmp_path, monkeypatch):
    monkeypatch.setenv("FAKE_SSH_OUT", "log line\n")
    dest = tmp_path / "out.log"
    assert SshTransport().get("n1", "/var/log/rabbit.log", dest) is True
    assert dest.read_text() == "log line\n"
    (argv,) = fake_ssh.argv_calls()
    assert argv[-1] == "cat /var/log/rabbit.log"


def test_get_missing_remote_is_false_and_cleans_up(
    fake_ssh, tmp_path, monkeypatch
):
    monkeypatch.setenv("FAKE_SSH_RC", "1")
    dest = tmp_path / "out.log"
    assert SshTransport().get("n1", "/gone", dest) is False
    assert not dest.exists()  # no empty/partial artifact left behind


def test_mixed_nemesis_delegates_and_pairs_stop_with_start():
    """MixedNemesis (jepsen.nemesis/compose's role): each start picks ONE
    member and the paired stop heals that SAME member; the history value
    names which family fired; teardown reaches every member."""
    from jepsen_tpu.control.nemesis import MixedNemesis
    from jepsen_tpu.history.ops import Op, OpF, OpType

    class Member:
        def __init__(self, name):
            self.name = name
            self.calls = []

        def setup(self, test):
            self.calls.append("setup")

        def invoke(self, test, op):
            self.calls.append("start" if op.f == OpF.START else "stop")
            return op.complete(OpType.INFO, value=f"{self.name}-did-it")

        def teardown(self, test):
            self.calls.append("teardown")

    a, b = Member("a"), Member("b")
    nem = MixedNemesis({"alpha": a, "beta": b}, seed=7)
    nem.setup({})
    assert a.calls == ["setup"] and b.calls == ["setup"]
    start = Op.invoke(OpF.START, -1)
    stop = Op.invoke(OpF.STOP, -1)
    for _ in range(6):  # every stop must land on the starter
        r = nem.invoke({}, start)
        family = r.value.split(":")[0]
        starter = a if family == "alpha" else b
        before = list(starter.calls)
        nem.invoke({}, stop)
        assert starter.calls == before + ["stop"]
    # both families eventually fire under the seeded RNG
    assert "start" in a.calls and "start" in b.calls
    # a stop with nothing active is a no-op, not a crash
    r = nem.invoke({}, stop)
    assert r.value == "nothing active"
    nem.teardown({})
    assert a.calls[-1] == "teardown" and b.calls[-1] == "teardown"


def test_make_nemesis_mixed_membership_follows_durable():
    """--nemesis mixed composes partition/kill/pause; crash-restart joins
    only when the SUT is durable AND has real per-node state — on the sim
    (cluster-global state) a whole-cluster crash recovers vacuously, so
    the member stays out even under durable (advisor r4)."""
    from jepsen_tpu.control.nemesis import MixedNemesis, make_nemesis
    from jepsen_tpu.control.net import Procs, SimProcs

    class RealProcs(Procs):
        def kill(self, node): pass
        def restart(self, node): pass
        def pause(self, node): pass
        def resume(self, node): pass

    net = IptablesNet(FakeTransport(), NODES)
    base = {"nemesis": "mixed", "network-partition": "partition-halves"}
    nem = make_nemesis(base, net, SimProcs(None), NODES, seed=1)
    assert isinstance(nem, MixedNemesis)
    assert sorted(nem.members) == ["kill", "partition", "pause"]
    # durable + sim: crash-restart must NOT join (vacuous fault)
    nem2 = make_nemesis(
        {**base, "durable": True}, net, SimProcs(None), NODES, seed=1
    )
    assert sorted(nem2.members) == ["kill", "partition", "pause"]
    # durable + real procs: crash-restart joins
    nem3 = make_nemesis(
        {**base, "durable": True}, net, RealProcs(), NODES, seed=1
    )
    assert sorted(nem3.members) == [
        "crash-restart", "kill", "partition", "pause",
    ]


def test_make_nemesis_refuses_crash_restart_on_sim():
    """Standalone crash-restart-cluster on SimProcs raises instead of
    running a power-failure test that cannot fail (the no-silent-noop-
    fault rule that already gates clock-skew and membership-churn)."""
    import pytest

    from jepsen_tpu.control.nemesis import make_nemesis
    from jepsen_tpu.control.net import SimProcs

    net = IptablesNet(FakeTransport(), NODES)
    with pytest.raises(ValueError, match="vacuously"):
        make_nemesis(
            {"nemesis": "crash-restart-cluster"}, net, SimProcs(None), NODES
        )
