"""P-compositional WGL: decomposer round-trip, the three-engine
differential gate (pcomp ≡ monolithic tensor ≡ classic CPU), overflow
honesty (sub overflow ⇒ whole-history unknown with the class named),
capacity sizing from measured width, the mutex WGL-cell substrate
(Python ≡ native ≡ .jtc round-trip), the pipeline family, and the
sharded sub-history axis."""

import json
import tempfile
from pathlib import Path

import numpy as np
import pytest

from jepsen_tpu.checkers.wgl import (
    INF,
    Call,
    FifoWgl,
    MutexWgl,
    QueueWgl,
    WglOp,
    check_wgl_cpu,
    fenced_mutex_wgl_ops,
    mutex_history_is_fenced,
    mutex_key_token,
    mutex_wgl_ops,
    pack_wgl_batch,
    queue_wgl_ops,
    wgl_tensor_check,
)
from jepsen_tpu.checkers.wgl_pcomp import (
    MAX_SUB_CAPACITY,
    bucketize,
    capacity_for,
    cells_fenced,
    decompose,
    decomposition_union,
    mutex_ops_from_cells,
    pcomp_check_cpu,
    pcomp_check_ops,
    pcomp_tensor_check,
    wgl_cells_for,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import (
    MutexSynthSpec,
    SynthSpec,
    synth_hard_queue_history,
    synth_history,
    synth_mutex_batch,
)
from jepsen_tpu.models.core import (
    FencedMutex,
    FifoQueue,
    OwnedMutex,
    UnorderedQueue,
)


def _queue_model_key(opss):
    vs = 32 * max(
        1,
        (max((o.call.a0 for ops in opss for o in ops), default=0) + 32)
        // 32,
    )
    return (UnorderedQueue, (vs,))


def _write_jsonl(run_dir: Path, ops) -> Path:
    p = run_dir / "history.jsonl"
    with open(p, "w") as fh:
        for op in ops:
            row = {
                "index": op.index,
                "type": op.type.name.lower(),
                "f": op.f.name.lower(),
                "process": op.process,
                "value": op.value,
                "time": op.time,
            }
            if op.error is not None:
                row["error"] = op.error
            fh.write(json.dumps(row) + "\n")
    return p


# ---------------------------------------------------------------------------
# decomposer
# ---------------------------------------------------------------------------


class TestDecomposer:
    def test_queue_round_trip_union(self):
        for seed in (0, 1):
            ops = queue_wgl_ops(
                synth_history(SynthSpec(n_ops=120, seed=seed)).ops
            )
            d = decompose(ops, _queue_model_key([ops]))
            assert d.sound and d.kind == "per-value"
            assert decomposition_union(d) == list(ops)

    def test_hard_history_round_trip_union(self):
        ops = queue_wgl_ops(synth_hard_queue_history(80, 6, seed=3))
        d = decompose(ops, _queue_model_key([ops]))
        assert decomposition_union(d) == list(ops)
        # every open (indeterminate) enqueue is its own width-1 class
        open_classes = [s for s in d.subs if s.width]
        assert len(open_classes) == 6
        assert all(s.width == 1 for s in open_classes)

    def test_mutex_round_trip_union_multi_lock(self):
        sh = synth_mutex_batch(
            1, MutexSynthSpec(n_ops=100), n_locks=3
        )[0]
        ops = mutex_wgl_ops(sh.ops)
        d = decompose(ops, (OwnedMutex, ()))
        assert d.sound and d.kind == "per-key"
        assert len(d.subs) == 3
        assert decomposition_union(d) == list(ops)

    def test_clean_subhistories_fit_capacity_16(self):
        """Satellite contract: clean classes (width 0) compile at
        capacity ≤ 16 — the heuristic must come from the MEASURED
        width, never a global constant."""
        assert capacity_for(0) == 16
        ops = queue_wgl_ops(
            synth_history(SynthSpec(n_ops=160, seed=5)).ops
        )
        d = decompose(ops, _queue_model_key([ops]))
        buckets = bucketize([d])
        assert buckets, "clean history produced no buckets"
        assert all(b.capacity == 16 for b in buckets)

    def test_width_scales_capacity(self):
        assert capacity_for(1) == 16
        assert capacity_for(2) == 16
        assert capacity_for(3) == 32
        assert capacity_for(8) >= 1024 or capacity_for(8) == 1024
        assert capacity_for(40) == MAX_SUB_CAPACITY

    def test_shared_program_per_bucket(self):
        """Two different clean histories share ONE cached XLA program
        per (model, n_ops-bucket, capacity-bucket) — the decomposition
        must not compile per history."""
        from jepsen_tpu.checkers.wgl import _wgl_program_cached

        opss = [
            queue_wgl_ops(synth_history(SynthSpec(n_ops=100, seed=s)).ops)
            for s in (11, 12)
        ]
        mk = _queue_model_key(opss)
        decomps = [decompose(ops, mk) for ops in opss]
        pcomp_tensor_check([decomps[0]])
        before = _wgl_program_cached.cache_info()
        pcomp_tensor_check([decomps[1]])
        after = _wgl_program_cached.cache_info()
        assert after.misses == before.misses, (
            "second clean history compiled a new program instead of "
            "hitting the shared (model, n, capacity) bucket"
        )

    def test_cas_register_is_unsound(self):
        from jepsen_tpu.models.core import CasRegister

        d = decompose(
            [WglOp(Call(0, 1), 0, 1)], (CasRegister, (0,))
        )
        assert not d.sound and "couple" in d.reason


# ---------------------------------------------------------------------------
# the differential gate: pcomp ≡ monolithic tensor ≡ classic CPU
# ---------------------------------------------------------------------------


class TestDifferentialGate:
    @pytest.mark.parametrize("seed", range(4))
    def test_queue_corpus_three_way(self, seed):
        sh = synth_history(
            SynthSpec(
                n_ops=100,
                seed=400 + seed,
                duplicated=seed % 2,
                unexpected=(seed // 2) % 2,
            )
        )
        ops = queue_wgl_ops(sh.ops)
        mk = _queue_model_key([ops])
        pc = pcomp_check_ops(ops, mk)
        batch = pack_wgl_batch([ops])
        ok, unknown = wgl_tensor_check(batch, mk)
        cls, args = mk
        cpu = check_wgl_cpu(ops, cls(*args))
        assert not unknown[0]
        assert pc["valid?"] == bool(ok[0]) == cpu["valid?"], (
            pc, bool(ok[0]), cpu["valid?"],
        )

    @pytest.mark.parametrize("window", [0, 2, 4])
    def test_hard_generator_three_way(self, window):
        ops = queue_wgl_ops(synth_hard_queue_history(60, window, seed=7))
        mk = _queue_model_key([ops])
        pc = pcomp_check_ops(ops, mk)
        batch = pack_wgl_batch([ops])
        ok, unknown = wgl_tensor_check(batch, mk, capacity=128)
        cls, args = mk
        cpu = check_wgl_cpu(ops, cls(*args))
        assert not unknown[0]
        assert pc["valid?"] is True
        assert pc["valid?"] == bool(ok[0]) == cpu["valid?"]

    def test_hard_generator_wide_window_pcomp_vs_classic(self):
        # w=6 at n=200: the monolithic tensor engine would need a
        # capacity-256 compile — the classic search is the exact
        # comparator here (the monolithic column is the round-3 table)
        ops = queue_wgl_ops(synth_hard_queue_history(200, 6, seed=1))
        mk = _queue_model_key([ops])
        pc = pcomp_check_ops(ops, mk)
        cls, args = mk
        cpu = check_wgl_cpu(ops, cls(*args))
        assert pc["valid?"] is True and cpu["valid?"] is True

    @pytest.mark.parametrize("double_grant", [0, 1])
    def test_mutex_corpus_three_way(self, double_grant):
        shs = synth_mutex_batch(
            3, MutexSynthSpec(n_ops=80), double_grant=double_grant
        )
        for sh in shs:
            ops = mutex_wgl_ops(sh.ops)
            pc = pcomp_check_ops(ops, (OwnedMutex, ()))
            batch = pack_wgl_batch([ops])
            ok, unknown = wgl_tensor_check(batch, (OwnedMutex, ()))
            cpu = check_wgl_cpu(ops, OwnedMutex())
            assert pc["valid?"] is (sh.double_grant == 0)
            if not unknown[0]:
                assert bool(ok[0]) == pc["valid?"]
            assert cpu["valid?"] == pc["valid?"]

    def test_double_grant_survives_multi_lock_decomposition(self):
        """The injected split-brain grant must stay refuted when the
        history spans several locks and the search runs per key."""
        shs = synth_mutex_batch(
            4, MutexSynthSpec(n_ops=120, seed=50), n_locks=3,
            double_grant=1,
        )
        shs = [s for s in shs if s.double_grant == 1]
        assert shs, "no seed injected a certain double grant"
        for sh in shs:
            ops = mutex_wgl_ops(sh.ops)
            pc = pcomp_check_ops(ops, (OwnedMutex, ()))
            assert pc["valid?"] is False, pc
            assert "invalid-class" in pc
            # the per-class classic twin agrees
            cpu = pcomp_check_cpu(ops, (OwnedMutex, ()))
            assert cpu["valid?"] is False

    def test_fenced_token_order_violation_survives_decomposition(self):
        """A token granted twice on ONE key must refute even when the
        history spans several fenced locks (keys must not launder each
        other's token order)."""
        hist = []
        for key, token in (
            (0, 5), (1, 3), (0, 9), (1, 7), (2, 4),
            (1, 7),  # THE BUG: token 7 re-granted on key 1
        ):
            inv = Op.invoke(OpF.ACQUIRE, len(hist))
            hist.append(inv)
            hist.append(inv.complete(OpType.OK, value=[key, token]))
        h = reindex(hist)
        assert mutex_history_is_fenced(h)
        ops = fenced_mutex_wgl_ops(h)
        pc = pcomp_check_ops(ops, (FencedMutex, ()))
        assert pc["valid?"] is False
        assert pc["invalid-class"] == 1
        assert pcomp_check_cpu(ops, (FencedMutex, ()))["valid?"] is False
        # drop the buggy grant: the same multi-key history is legal —
        # per-key token order holds even though the GLOBAL sequence of
        # grants (5, 3, 9, 7, 4) is not monotone
        clean = reindex(h[:-2])
        pc2 = pcomp_check_ops(
            fenced_mutex_wgl_ops(clean), (FencedMutex, ())
        )
        assert pc2["valid?"] is True

    def test_multi_lock_overlapping_holds_are_legal(self):
        """Two concurrent holds on DIFFERENT locks are fine; the same
        shape on one lock is the classic double grant."""
        two_locks = reindex(
            [
                Op.invoke(OpF.ACQUIRE, 0, [0]),
                Op(OpType.OK, OpF.ACQUIRE, 0, [0]),
                Op.invoke(OpF.ACQUIRE, 1, [1]),
                Op(OpType.OK, OpF.ACQUIRE, 1, [1]),
            ]
        )
        ops = mutex_wgl_ops(two_locks)
        assert pcomp_check_ops(ops, (OwnedMutex, ()))["valid?"] is True
        assert pcomp_check_cpu(ops, (OwnedMutex, ()))["valid?"] is True
        one_lock = reindex(
            [
                Op.invoke(OpF.ACQUIRE, 0, [0]),
                Op(OpType.OK, OpF.ACQUIRE, 0, [0]),
                Op.invoke(OpF.ACQUIRE, 1, [0]),
                Op(OpType.OK, OpF.ACQUIRE, 1, [0]),
            ]
        )
        ops1 = mutex_wgl_ops(one_lock)
        assert pcomp_check_ops(ops1, (OwnedMutex, ()))["valid?"] is False

    def test_checker_wrappers_use_pcomp_and_agree(self):
        sh = synth_history(SynthSpec(n_ops=120, seed=41))
        r = QueueWgl(backend="tpu").check({}, sh.ops)
        assert r["valid?"] is True and r["engine"] == "tpu-pcomp"
        r_mono = QueueWgl(backend="tpu", pcomp=False).check({}, sh.ops)
        assert r_mono["valid?"] is True and r_mono["engine"] == "tpu"
        bad = synth_mutex_batch(
            1, MutexSynthSpec(n_ops=80), double_grant=1
        )[0]
        r2 = MutexWgl(backend="tpu").check({}, bad.ops)
        assert r2["valid?"] is False and r2["engine"] == "tpu-pcomp"
        assert MutexWgl(backend="cpu").check({}, bad.ops)["valid?"] is False


# ---------------------------------------------------------------------------
# overflow honesty
# ---------------------------------------------------------------------------


def _pending_pair_ops(pairs: int) -> list:
    """``pairs`` indeterminate acquire+release pairs on ONE lock, then a
    definite acquire: ~2^pairs configurations stay live through the one
    return event — the shape that genuinely overflows a narrow frontier.
    """
    ops = []
    for p in range(pairs):
        ops.append(WglOp(Call(OwnedMutex.ACQUIRE, a0=p), 2 * p, INF))
        ops.append(WglOp(Call(OwnedMutex.RELEASE, a0=p), 2 * p + 1, INF))
    n = 2 * pairs
    ops.append(WglOp(Call(OwnedMutex.ACQUIRE, a0=99), n, n + 1))
    return ops


class TestOverflowHonesty:
    def test_sub_overflow_is_whole_history_unknown_with_class(self):
        """A sub-history whose frontier overflows surfaces as unknown
        for the WHOLE history with the offending class identified —
        never a silent per-piece skip."""
        ops = _pending_pair_ops(6)
        d = decompose(ops, (OwnedMutex, ()))
        ok, unknown, info = pcomp_tensor_check([d], capacity_cap=16)
        assert unknown[0] and not ok[0]
        assert info[0]["overflow-class"] == 0

    def test_escalation_resolves_moderate_overflow(self):
        """A dense-concurrency class (width 0 — no indeterminate ops,
        but every interval overlapping) under-sizes the width heuristic
        (capacity 16); one escalation to the max capacity resolves it
        without the CPU fallback."""

        def dense(m, key=0, base=0):
            ops = []
            n = 4 * m
            for p in range(m):
                ops.append(
                    WglOp(Call(OwnedMutex.ACQUIRE, a0=p), base,
                          base + n + 2 * p, key=key)
                )
                ops.append(
                    WglOp(Call(OwnedMutex.RELEASE, a0=p), base + 1,
                          base + n + 2 * p + 1, key=key)
                )
            return ops

        ops = dense(6)
        d = decompose(ops, (OwnedMutex, ()))
        ok, unknown, info = pcomp_tensor_check([d])
        assert ok[0] and not unknown[0]
        assert info[0].get("escalated") is True
        assert "_overflow_subs" not in info[0]  # private key never leaks
        assert check_wgl_cpu(ops, OwnedMutex())["valid?"] is True
        # a clean neighboring class keeps its first-pass verdict while
        # ONLY the overflowed class escalates (merge correctness)
        base = 100
        clean = [
            WglOp(Call(OwnedMutex.ACQUIRE, a0=7), base, base + 1, key=1),
            WglOp(Call(OwnedMutex.RELEASE, a0=7), base + 2, base + 3,
                  key=1),
        ]
        d2 = decompose(dense(6) + clean, (OwnedMutex, ()))
        ok2, unknown2, info2 = pcomp_tensor_check([d2])
        assert ok2[0] and not unknown2[0]
        assert info2[0]["subhistories"] == 2
        assert info2[0].get("escalated") is True
        # the all-pending shape needs no escalation at all: its width
        # (12 INF ops) sizes the first pass at the max capacity already
        ops_p = _pending_pair_ops(6)
        dp = decompose(ops_p, (OwnedMutex, ()))
        okp, unkp, infp = pcomp_tensor_check([dp])
        assert okp[0] and not unkp[0]
        assert infp[0]["max-capacity"] == MAX_SUB_CAPACITY
        assert check_wgl_cpu(ops_p, OwnedMutex())["valid?"] is True

    def test_invalid_trumps_unknown_across_classes(self):
        """One refuted projection refutes the WHOLE history even when a
        neighboring class overflows: a device-proven violation must
        never be downgraded to unknown (review finding)."""
        # key 1: a >1024-config overflow shape; key 0: a definite
        # double grant.  Key 1's ops come FIRST so an
        # order-of-iteration bug would surface.
        overflow_ops = [
            WglOp(
                Call(o.call.f, a0=o.call.a0), o.inv, o.ret, key=1
            )
            for o in _pending_pair_ops(12)
        ]
        base = len(overflow_ops) * 2
        bad = [
            WglOp(Call(OwnedMutex.ACQUIRE, a0=1), base, base + 1, key=0),
            WglOp(
                Call(OwnedMutex.ACQUIRE, a0=2), base + 2, base + 3, key=0
            ),
        ]
        ops = overflow_ops + bad
        d = decompose(ops, (OwnedMutex, ()))
        ok, unknown, info = pcomp_tensor_check([d])
        assert not ok[0] and not unknown[0]
        assert info[0]["first-invalid-class"] == 0
        r = pcomp_check_ops(ops, (OwnedMutex, ()))
        assert r["valid?"] is False and r["invalid-class"] == 0
        # the classic twin applies the same rule even when the capped
        # class is scanned first
        cpu = pcomp_check_cpu(ops, (OwnedMutex, ()), max_configs=64)
        assert cpu["valid?"] is False and cpu["invalid-class"] == 0
        # and with NO refuted class, a capped search stays undecided
        cpu2 = pcomp_check_cpu(
            overflow_ops, (OwnedMutex, ()), max_configs=64
        )
        assert cpu2["valid?"] == "unknown" and cpu2["overflow-class"] == 1

    def test_checker_falls_back_to_cpu_on_true_overflow(self):
        """Past the 1024-row escalation ceiling the checker keeps the
        documented overflow ⇒ unknown ⇒ CPU-fallback contract, with the
        offending class still visible in the result."""
        ops = _pending_pair_ops(12)  # ≥ 2^12 configs > 1024 rows
        d = decompose(ops, (OwnedMutex, ()))
        ok, unknown, info = pcomp_tensor_check([d])
        assert unknown[0]
        assert info[0]["overflow-class"] == 0
        r = pcomp_check_ops(ops, (OwnedMutex, ()))
        assert r["valid?"] == "unknown" and r["overflow-class"] == 0

        class _Chk(MutexWgl):
            def _ops_and_model(self, history):
                return ops, (OwnedMutex, ())

        out = _Chk(backend="tpu").check({}, [])
        assert out["engine"] == "cpu"
        assert out["pcomp-overflow-class"] == 0
        assert out["valid?"] is True  # the exact search decides


# ---------------------------------------------------------------------------
# FIFO: per-value classes + host pairwise order
# ---------------------------------------------------------------------------


def _random_fifo_ops(rng) -> list:
    """Random COMPLETE distinct-value FIFO interval history: a mix of
    honest FIFO executions and shuffled (frequently illegal) ones."""
    n_vals = rng.randrange(2, 6)
    events = []
    for v in range(1, n_vals + 1):
        events.append(("e", v))
        if rng.random() < 0.8:
            events.append(("d", v))
    rng.shuffle(events)
    t = 0
    ops = []
    for kind, v in events:
        dur = rng.randrange(1, 4)
        f = FifoQueue.ENQUEUE if kind == "e" else FifoQueue.DEQUEUE
        ops.append(WglOp(Call(f, v), t, t + dur))
        t += rng.randrange(1, 3)
    return ops


class TestFifoPcomp:
    def test_random_differential_vs_classic(self):
        import random

        rng = random.Random(9)
        checked = sound = 0
        for _ in range(60):
            ops = _random_fifo_ops(rng)
            mk = (FifoQueue, (8,))
            d = decompose(ops, mk)
            cpu = check_wgl_cpu(ops, FifoQueue(8))
            checked += 1
            if not d.sound:
                continue
            sound += 1
            ok, unknown, info = pcomp_tensor_check([d])
            assert not unknown[0]
            assert bool(ok[0]) == cpu["valid?"], (ops, info, cpu)
            assert pcomp_check_cpu(ops, mk)["valid?"] == cpu["valid?"]
        assert sound == checked, "complete histories must all be sound"

    def test_pending_enqueue_is_unsound(self):
        ops = [
            WglOp(Call(FifoQueue.ENQUEUE, 1), 0, INF),
            WglOp(Call(FifoQueue.DEQUEUE, 1), 2, 3),
        ]
        d = decompose(ops, (FifoQueue, (8,)))
        assert not d.sound and "pending" in d.reason
        # the checker still answers, through the monolithic engine
        assert pcomp_check_ops(ops, (FifoQueue, (8,))) is None
        assert check_wgl_cpu(ops, FifoQueue(8))["valid?"] is True

    def test_duplicate_enqueue_is_unsound(self):
        """Review counterexample (executed): re-enqueueing a value
        breaks the distinct-value premise of the pairwise order proof —
        the per-value dicts would keep only the LAST interval and pass
        a genuinely non-FIFO history.  Must bail to the monolithic
        engine, which refutes it."""
        E, D = FifoQueue.ENQUEUE, FifoQueue.DEQUEUE
        ops = [
            WglOp(Call(E, 5), 0, 1),
            WglOp(Call(E, 7), 2, 3),
            WglOp(Call(D, 7), 4, 5),   # head is 5: not FIFO
            WglOp(Call(D, 5), 6, 7),
            WglOp(Call(E, 5), 8, 9),
            WglOp(Call(D, 5), 10, 11),
        ]
        d = decompose(ops, (FifoQueue, (8,)))
        assert not d.sound and "distinct" in d.reason
        assert pcomp_check_ops(ops, (FifoQueue, (8,))) is None
        assert pcomp_check_cpu(ops, (FifoQueue, (8,)))["valid?"] is False
        assert check_wgl_cpu(ops, FifoQueue(8))["valid?"] is False

    def test_binding_capacity_is_unsound(self):
        ops = [WglOp(Call(FifoQueue.ENQUEUE, v), 2 * v, 2 * v + 1)
               for v in range(4)]
        d = decompose(ops, (FifoQueue, (2,)))
        assert not d.sound and "capacity" in d.reason

    def test_fifo_wgl_checker_still_correct(self):
        hist = []
        for v in range(6):
            inv = Op.invoke(OpF.ENQUEUE, 0, v)
            hist.append(inv)
            hist.append(inv.complete(OpType.OK))
        for v in range(6):
            inv = Op.invoke(OpF.DEQUEUE, 0)
            hist.append(inv)
            hist.append(inv.complete(OpType.OK, value=v))
        h = reindex(hist)
        r = FifoWgl(backend="tpu").check({}, h)
        assert r["valid?"] is True and r["engine"] == "tpu-pcomp"
        # swapped dequeues: a genuine FIFO violation through pcomp
        bad = list(h)
        bad[-1] = bad[-1].complete(OpType.OK, value=0)  # re-reads head
        r2 = FifoWgl(backend="tpu").check({}, reindex(bad[:-2]))
        assert r2["valid?"] is True  # truncated tail stays legal


# ---------------------------------------------------------------------------
# mutex WGL cells: Python ≡ native ≡ .jtc (the zero-copy substrate)
# ---------------------------------------------------------------------------


class TestWglCells:
    def _histories(self):
        return (
            synth_mutex_batch(2, MutexSynthSpec(n_ops=80), n_locks=3)
            + synth_mutex_batch(1, MutexSynthSpec(n_ops=60))
            + synth_mutex_batch(
                1, MutexSynthSpec(n_ops=60), double_grant=1
            )
        )

    def test_cells_reproduce_op_mappers(self):
        for sh in self._histories():
            cells = wgl_cells_for(sh.ops)
            ops, mk = mutex_ops_from_cells(cells)
            assert ops == mutex_wgl_ops(sh.ops)
            assert mk == (OwnedMutex, ())
        # fenced: tokens ride the cells too
        hist = []
        for tok in (5, 9):
            inv = Op.invoke(OpF.ACQUIRE, tok)
            hist.append(inv)
            hist.append(inv.complete(OpType.OK, value=tok))
        h = reindex(hist)
        cells = wgl_cells_for(h)
        assert cells_fenced(cells)
        ops, mk = mutex_ops_from_cells(cells)
        assert ops == fenced_mutex_wgl_ops(h)
        assert mk == (FencedMutex, ())

    def test_native_twin_and_jtc_round_trip(self):
        from jepsen_tpu.history.columnar import load_jtc, pack_jtc
        from jepsen_tpu.history.fastpack import wgl_cells_file
        from jepsen_tpu.history.storecache import (
            load_wgl_cells_cache,
            save_wgl_cells_cache,
            wgl_cells_with_cache,
        )

        with tempfile.TemporaryDirectory() as td:
            for i, sh in enumerate(self._histories()):
                d = Path(td) / f"run{i}"
                d.mkdir()
                p = _write_jsonl(d, sh.ops)
                py = wgl_cells_for(sh.ops)
                nat = wgl_cells_file(p)
                if nat is not None:  # no-lib container: Python-only
                    np.testing.assert_array_equal(nat, py)
                # record-time substrate carries SEC_WGL for mutex
                pack_jtc(p)
                jtc = load_jtc(p)
                assert jtc is not None
                np.testing.assert_array_equal(jtc.wgl_cells(), py)
                # cache layer round-trips through the substrate
                got = load_wgl_cells_cache(p)
                np.testing.assert_array_equal(got, py)
                cells, hit = wgl_cells_with_cache(p)
                assert hit
                np.testing.assert_array_equal(cells, py)
                save_wgl_cells_cache(p, py)  # idempotent merge

    def test_store_records_wgl_section_at_record_time(self):
        from jepsen_tpu.history.columnar import load_jtc
        from jepsen_tpu.history.store import Store

        sh = synth_mutex_batch(1, MutexSynthSpec(n_ops=40))[0]
        with tempfile.TemporaryDirectory() as td:
            store = Store(td)
            run = store.run_dir("mutex-test")
            p = store.save_history(run, sh.ops)
            jtc = load_jtc(p)
            assert jtc is not None and jtc.workload == "mutex"
            np.testing.assert_array_equal(
                jtc.wgl_cells(), wgl_cells_for(sh.ops)
            )
            # and the generic rows section rode along (PR-7 contract)
            assert jtc.rows() is not None

    def test_keyed_value_conventions(self):
        assert mutex_key_token(None) == (0, -1)
        assert mutex_key_token(7) == (0, 7)
        assert mutex_key_token([3]) == (3, -1)
        assert mutex_key_token([3, 9]) == (3, 9)
        assert mutex_key_token("junk") == (0, -1)
        assert mutex_key_token([1, 2, 3]) == (0, -1)
        # [key] must NOT flip fenced detection
        h = reindex(
            [
                Op.invoke(OpF.ACQUIRE, 0, [2]),
                Op(OpType.OK, OpF.ACQUIRE, 0, [2]),
            ]
        )
        assert not mutex_history_is_fenced(h)


# ---------------------------------------------------------------------------
# pipeline family + sharded sub-history axis
# ---------------------------------------------------------------------------


class TestMutexPipelineFamily:
    @pytest.fixture(scope="class")
    def store_paths(self, tmp_path_factory):
        td = tmp_path_factory.mktemp("mutex_store")
        shs = (
            synth_mutex_batch(2, MutexSynthSpec(n_ops=60), n_locks=2)
            + synth_mutex_batch(
                2, MutexSynthSpec(n_ops=60), double_grant=1
            )
            + synth_mutex_batch(1, MutexSynthSpec(n_ops=60))
        )
        paths = []
        for i, sh in enumerate(shs):
            d = td / f"run{i}"
            d.mkdir()
            paths.append(str(_write_jsonl(d, sh.ops)))
        return paths, shs

    def test_pipelined_equals_serial_equals_lanes(self, store_paths):
        from jepsen_tpu.parallel.pipeline import check_sources

        paths, shs = store_paths
        results, stats = check_sources("mutex", paths, chunk=2)
        assert stats.histories == len(paths)
        for r, sh in zip(results, shs):
            serial = MutexWgl(backend="cpu").check({}, sh.ops)
            assert (r["mutex"]["valid?"] is True) == (
                serial["valid?"] is True
            )
            assert r["mutex"]["model"] == "owned-mutex"
        serial_r, _ = check_sources("mutex", paths, chunk=2, serial=True)
        lanes_r, _ = check_sources("mutex", paths, chunk=2, lanes=0)
        verdicts = [r["mutex"]["valid?"] for r in results]
        assert [r["mutex"]["valid?"] for r in serial_r] == verdicts
        assert [r["mutex"]["valid?"] for r in lanes_r] == verdicts

    def test_no_cache_still_parses(self, store_paths):
        from jepsen_tpu.parallel.pipeline import check_sources

        paths, _ = store_paths
        results, _ = check_sources(
            "mutex", paths, chunk=2, use_cache=False
        )
        assert len(results) == len(paths)

    def test_reduce_mode_refused(self, store_paths):
        from jepsen_tpu.parallel.mesh import checker_mesh
        from jepsen_tpu.parallel.pipeline import check_sources

        paths, _ = store_paths
        with pytest.raises(Exception, match="reduce"):
            check_sources(
                "mutex", paths, reduce=True, mesh=checker_mesh(),
            )


class TestShardedPcomp:
    def test_sharded_matches_single_device(self, cpu_devices):
        from jepsen_tpu.parallel.mesh import checker_mesh, sharded_wgl_pcomp

        mesh = checker_mesh(cpu_devices, seq=1)
        opss = [
            queue_wgl_ops(synth_hard_queue_history(80, w, seed=2))
            for w in (0, 3, 5)
        ]
        mk = _queue_model_key(opss)
        decomps = [decompose(ops, mk) for ops in opss]
        ok_s, unknown_s, info_s = sharded_wgl_pcomp(decomps, mesh)
        decomps2 = [decompose(ops, mk) for ops in opss]
        ok, unknown, info = pcomp_tensor_check(decomps2)
        np.testing.assert_array_equal(ok_s, ok)
        np.testing.assert_array_equal(unknown_s, unknown)
        assert [i["subhistories"] for i in info_s] == [
            i["subhistories"] for i in info
        ]
