"""Differential fuzz of the native JSONL substrate parsers.

The C++ fast paths (``jt_pack_file``, ``jt_elle_infer_file``,
``jt_stream_rows_file``) carry a *never-wrong, maybe-absent* contract:
whatever they return must be bit-identical to the Python twin, and
anything they cannot map must come back as a fallback (None), never a
silently different result.  The structured differential tests
(``test_fastpack.py``) pin known edge cases; this fuzz drives seeded
random op streams with adversarial value shapes — boundary ints,
floats, escaped/unicode strings, nested lists in and out of micro-op
shape, objects, wrong-arity micro-ops, invalid enum names — through
both sides and asserts:

- native result present  ⇒ equals the Python twin's exactly;
- Python twin raises     ⇒ native must NOT have produced a result.

``FUZZ_N`` scales the case count (seeded: failures reproduce).
"""

from __future__ import annotations

import json
import os
import random

import numpy as np
import pytest

FUZZ_N = int(os.environ.get("FUZZ_N", "120"))


@pytest.fixture(autouse=True)
def _require_native():
    from jepsen_tpu.history import fastpack

    if fastpack._load() is None:
        pytest.skip("native rows packer unavailable")


TYPES = ["invoke", "ok", "fail", "info"]
FS = ["enqueue", "dequeue", "drain", "start", "stop", "log",
      "append", "read", "txn", "acquire", "release"]
#: mostly clean strings so files usually stay on the fast path, plus
#: occasional escape-carrying ones (which force the deep parser's
#: fallback — the contract under test, not the common case)
STRINGS = ["", "full", "x", "append", "nullish", "r"]
NASTY_STRINGS = ["with \\\\ backslash", "a\tb", '"quoted"', "unié"]


def _value(rng: random.Random, depth: int = 0):
    roll = rng.random()
    if roll < 0.3:
        # boundary/overflow ints stay rare: one per file on average, so
        # most files exercise the agree path instead of the fallback
        if rng.random() < 0.04:
            return rng.choice([2**31 - 1, -(2**31), 2**31, 2**40,
                               -(2**40)])
        return rng.choice([0, 1, -1, 7, rng.randrange(-100, 1000)])
    if roll < 0.36:
        return rng.choice([0.5, -1.25, 1e10, 3.0])
    if roll < 0.46:
        pool = STRINGS if rng.random() < 0.9 else NASTY_STRINGS
        return rng.choice(pool)
    if roll < 0.54:
        return rng.choice([True, False, None])
    if roll < 0.62 and depth < 3:
        return {rng.choice(STRINGS): _value(rng, depth + 1)}
    if depth >= 3:
        return rng.randrange(100)
    # lists: sometimes micro-op / pair shaped, sometimes arbitrary
    shape = rng.random()
    if shape < 0.35:
        return [rng.choice(["append", "r", "w", 7]),
                rng.randrange(32) if rng.random() < 0.8
                else _value(rng, depth + 1),
                _value(rng, depth + 1)]
    if shape < 0.55:
        return [rng.randrange(32), rng.randrange(1000)]
    return [_value(rng, depth + 1) for _ in range(rng.randrange(0, 4))]


def _op(rng: random.Random, f_pool) -> dict:
    d = {
        "type": rng.choice(TYPES),
        "f": rng.choice(f_pool),
        "process": rng.choice([0, 1, 2, -1, rng.randrange(8)]),
    }
    if rng.random() < 0.85:
        d["value"] = _value(rng)
    if rng.random() < 0.2:
        d["time"] = rng.choice([-1, 0, rng.randrange(10**12)])
    if rng.random() < 0.15:
        d["error"] = rng.choice(STRINGS + NASTY_STRINGS)
    if rng.random() < 0.1:
        d["index"] = rng.randrange(10**6)
    if rng.random() < 0.005:
        d["type"] = "bogus"  # Python raises KeyError: native must fail
    return d


def _write(tmp_path, rng, f_pool, n_ops=25):
    p = tmp_path / f"fuzz{rng.randrange(10**9)}.jsonl"
    with open(p, "w") as fh:
        for _ in range(n_ops):
            fh.write(json.dumps(_op(rng, f_pool)) + "\n")
            if rng.random() < 0.05:
                fh.write("\n")  # blank lines are skipped by both sides
    return p


def _python_history(p):
    from jepsen_tpu.history.store import read_history

    try:
        return read_history(p), None
    except Exception as e:  # noqa: BLE001 - canonical error path
        return None, e


def test_fuzz_pack_file(tmp_path):
    from jepsen_tpu.history.fastpack import pack_file
    from jepsen_tpu.history.ops import workload_of
    from jepsen_tpu.history.rows import _rows_for

    rng = random.Random(1234)
    agreed = 0
    for _ in range(FUZZ_N):
        p = _write(tmp_path, rng, FS)
        fast = pack_file(p)
        history, err = _python_history(p)
        if err is not None:
            assert fast is None, (p, err)
            continue
        if fast is None:
            continue  # fallback is always allowed
        try:
            ref = _rows_for(history)
        except OverflowError:
            pytest.fail(f"native accepted what Python overflows: {p}")
        assert fast[0] == workload_of(history), p
        np.testing.assert_array_equal(fast[1], ref, err_msg=str(p))
        agreed += 1
    assert agreed > FUZZ_N // 4  # the fuzz isn't all-fallback vacuous


def test_fuzz_elle_graph_file(tmp_path):
    from jepsen_tpu.checkers.elle import infer_txn_graph
    from jepsen_tpu.history.fastpack import elle_graph_file

    rng = random.Random(99)
    agreed = 0
    for _ in range(FUZZ_N):
        p = _write(tmp_path, rng, ["txn", "log", "start"])
        g = elle_graph_file(p)
        history, err = _python_history(p)
        if err is not None:
            assert g is None, (p, err)
            continue
        if g is None:
            continue
        try:
            ref = infer_txn_graph(history)
        except Exception:  # noqa: BLE001 - e.g. unhashable fuzzed keys
            pytest.fail(f"native accepted what Python rejects: {p}")
        assert g.n == ref.n and g.txn_index == ref.txn_index, p
        assert (g.ww, g.wr, g.rw) == (ref.ww, ref.wr, ref.rw), p
        assert (g.g1a, g.g1b) == (ref.g1a, ref.g1b), p
        assert g.incompatible_order == ref.incompatible_order, p
        agreed += 1
    assert agreed > FUZZ_N // 4


def test_fuzz_stream_rows_file(tmp_path):
    from jepsen_tpu.checkers.stream_lin import _stream_rows
    from jepsen_tpu.history.fastpack import stream_rows_file

    rng = random.Random(4242)
    agreed = 0
    for _ in range(FUZZ_N):
        p = _write(tmp_path, rng, ["append", "read", "log", "stop"])
        got = stream_rows_file(p)
        history, err = _python_history(p)
        if err is not None:
            assert got is None, (p, err)
            continue
        if got is None:
            continue
        ref_cols, ref_full = _stream_rows(history)
        np.testing.assert_array_equal(got[0], ref_cols, err_msg=str(p))
        assert got[1] == ref_full, p
        agreed += 1
    assert agreed > FUZZ_N // 4
