"""Differential contract of the pipelined bytes-to-verdict executor.

``parallel/pipeline.py`` must produce verdicts IDENTICAL to the serial
checker paths for every family — queue (both sub-verdicts), stream
(short and 10k-op), elle (including degenerate-history host-fallback
splices) — from history FILES, pipelined and strictly serial, warm and
cold caches.  Plus both crash contracts: under ``fail_fast=True`` a
stage failure aborts the whole run with ``PipelineError`` and NO
verdict escapes for any batch (preserved verbatim from PR 4); under
the elastic default a failing chunk is retried then isolated per
history, the crasher quarantines as an explicit ``unknown`` with
evidence, and every other verdict survives (PR 13; the deeper proofs
live in ``tests/test_elastic.py``).
"""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu.checkers.elle import check_elle_cpu
from jepsen_tpu.checkers.queue_lin import check_queue_lin_cpu
from jepsen_tpu.checkers.stream_lin import check_stream_lin_cpu
from jepsen_tpu.checkers.total_queue import check_total_queue_cpu
from jepsen_tpu.history.store import write_history_jsonl
from jepsen_tpu.history.synth import (
    ElleSynthSpec,
    StreamSynthSpec,
    SynthSpec,
    synth_batch,
    synth_elle_batch,
    synth_stream_batch,
)
from jepsen_tpu.parallel.pipeline import (
    PipelineError,
    PipelineStats,
    check_sources,
    run_pipeline,
)


def _write(tmp_path, base):
    files = []
    for i, sh in enumerate(base):
        p = tmp_path / f"h{i:03d}.jsonl"
        write_history_jsonl(p, sh.ops)
        files.append(p)
    return files


@pytest.fixture(scope="module")
def stream_corpus(tmp_path_factory):
    base = synth_stream_batch(
        14,
        StreamSynthSpec(n_ops=40),
        lost=2,
        duplicated=1,
        divergent=1,
        reorder=1,
        recovered=1,
    )
    td = tmp_path_factory.mktemp("stream")
    return base, _write(td, base)


@pytest.fixture(scope="module")
def queue_corpus(tmp_path_factory):
    base = synth_batch(
        12, SynthSpec(n_ops=50), lost=1, duplicated=1, unexpected=1
    )
    td = tmp_path_factory.mktemp("queue")
    return base, _write(td, base)


class TestStreamDifferential:
    def test_pipeline_equals_serial_equals_cpu(self, stream_corpus):
        base, files = stream_corpus
        piped, _ = check_sources("stream", files, chunk=4, depth=2)
        serial, _ = check_sources("stream", files, chunk=4, serial=True)
        assert piped == serial, "pipelined verdicts diverged from serial"
        for r, sh in zip(piped, base):
            cpu = check_stream_lin_cpu(sh.ops)
            assert r["stream"]["valid?"] == cpu["valid?"]
            for k in ("lost", "duplicate", "phantom", "divergent"):
                assert r["stream"][k] == cpu[k], k

    def test_warm_cache_run_identical(self, stream_corpus):
        """Second run hits the digest-guarded columnar substrate (the
        ``.jtc`` that replaced ``stream_rows.npz``); verdicts must be
        byte-identical to the cold run."""
        _base, files = stream_corpus
        cold, _ = check_sources("stream", files, chunk=8, use_cache=True)
        warm, _ = check_sources("stream", files, chunk=8, use_cache=True)
        assert cold == warm
        from jepsen_tpu.history.columnar import jtc_path_for

        assert jtc_path_for(files[0]).exists()

    def test_long_histories_chunked(self, tmp_path):
        """The stream_10k shape (longer rows, several chunks, tail chunk
        shorter than the pad) through the executor."""
        base = synth_stream_batch(5, StreamSynthSpec(n_ops=400), lost=1)
        files = _write(tmp_path, base)
        piped, stats = check_sources("stream", files, chunk=2)
        assert stats.histories == 5 and stats.batches == 3
        for r, sh in zip(piped, base):
            assert (
                r["stream"]["valid?"]
                == check_stream_lin_cpu(sh.ops)["valid?"]
            )


class TestQueueDifferential:
    def test_pipeline_equals_serial_equals_cpu(self, queue_corpus):
        base, files = queue_corpus
        piped, _ = check_sources("queue", files, chunk=5)
        serial, _ = check_sources("queue", files, chunk=5, serial=True)
        assert piped == serial
        for r, sh in zip(piped, base):
            tq = check_total_queue_cpu(sh.ops)
            ql = check_queue_lin_cpu(sh.ops)
            assert r["queue"]["valid?"] == tq["valid?"]
            assert r["queue"]["lost"] == tq["lost"]
            assert r["linear"]["valid?"] == ql["valid?"]

    def test_result_keys_match_serial_class_path(self, queue_corpus):
        """Byte-identical to the SERIAL checker classes, including the
        recorded contract level: `linear.delivery` feeds a later bare
        re-check's no-silent-tightening inheritance (cmd_check)."""
        from jepsen_tpu.checkers.queue_lin import check_queue_lin_batch
        from jepsen_tpu.checkers.total_queue import check_total_queue_cpu

        base, files = queue_corpus
        piped, _ = check_sources("queue", files, chunk=4)
        ql = check_queue_lin_batch([sh.ops for sh in base])
        for r, serial_lin, sh in zip(piped, ql, base):
            assert r["linear"] == serial_lin
            tq = check_total_queue_cpu(sh.ops)
            for k in ("valid?", "lost", "duplicated", "unexpected"):
                assert r["queue"][k] == tq[k], k

    def test_delivery_contract_threads_through(self, queue_corpus):
        base, files = queue_corpus
        alo, _ = check_sources(
            "queue", files, chunk=6, delivery="at-least-once"
        )
        for r, sh in zip(alo, base):
            assert (
                r["linear"]["valid?"]
                == check_queue_lin_cpu(sh.ops, delivery="at-least-once")[
                    "valid?"
                ]
            )


class TestElleDifferential:
    def test_pipeline_equals_serial_equals_cpu_with_degenerates(
        self, tmp_path
    ):
        """Corpus splicing tensor-checkable and DEGENERATE histories
        (cross-key phantom collisions — the host-fallback class from the
        elle device-inference fuzz) through one pipelined run."""
        from test_fuzz_elle_device import fuzz_history

        from jepsen_tpu.checkers.elle import elle_mops_for

        class _SH:  # _write expects .ops
            def __init__(self, ops):
                self.ops = ops

        base = [_SH(fuzz_history(seed, n_txns=12)) for seed in range(8)]
        degen = [
            elle_mops_for(sh.ops)[1].degenerate for sh in base
        ]
        assert any(degen), "corpus must exercise the degenerate fallback"
        assert not all(degen), "corpus must exercise the device path too"
        files = _write(tmp_path, base)
        piped, _ = check_sources("elle", files, chunk=3)
        serial, _ = check_sources("elle", files, chunk=3, serial=True)
        assert piped == serial
        for r, sh in zip(piped, base):
            cpu = check_elle_cpu(sh.ops)
            assert r["elle"]["valid?"] == cpu["valid?"]
            for k in ("G0", "G1c", "G2", "G1a", "G1b",
                      "incompatible-order"):
                assert r["elle"][k] == cpu[k], k

    def test_synthetic_anomalies(self, tmp_path):
        base = synth_elle_batch(
            8, ElleSynthSpec(n_txns=10), g1a=1, g1b=1, g2_cycle=1
        )
        files = _write(tmp_path, base)
        piped, _ = check_sources("elle", files, chunk=4)
        for r, sh in zip(piped, base):
            assert r["elle"]["valid?"] == check_elle_cpu(sh.ops)["valid?"]


class TestCrashContract:
    """``fail_fast=True``: the PR-4 abort-all contract, preserved
    verbatim.  The elastic default's quarantine contract lives in
    :class:`TestElasticQuarantine` and ``tests/test_elastic.py``."""

    def test_produce_crash_emits_no_verdicts(self):
        """A crash in the host stage of batch k aborts the run with NO
        results for any batch — earlier chunks' verdicts never escape."""
        produced = []

        def produce(i):
            if i == 2:
                raise RuntimeError("packer exploded")
            produced.append(i)
            return np.full((4,), i, np.int32)

        import jax.numpy as jnp

        with pytest.raises(PipelineError, match="produce stage crashed"):
            run_pipeline(
                list(range(5)), produce, lambda x: jnp.asarray(x) + 1,
                fail_fast=True,
            )
        assert produced == [0, 1]

    def test_check_crash_emits_no_verdicts(self):
        def check(x):
            if int(np.asarray(x)[0]) == 1:
                raise ValueError("bad batch on device")
            import jax.numpy as jnp

            return jnp.asarray(x) + 1

        with pytest.raises(PipelineError, match="check stage crashed"):
            run_pipeline(
                list(range(4)),
                lambda i: np.full((2,), i, np.int32),
                check,
                fail_fast=True,
            )

    def test_unpacked_batch_never_reaches_check(self, tmp_path):
        """check_sources --fail-fast: a corrupt history file mid-corpus
        aborts the whole run (no partial verdict list escapes)."""
        base = synth_stream_batch(4, StreamSynthSpec(n_ops=20))
        files = _write(tmp_path, base)
        bad = tmp_path / "h999.jsonl"
        bad.write_text('{"type": "not a real op"\n')  # torn JSON line
        with pytest.raises((PipelineError, Exception)):
            check_sources(
                "stream", files[:2] + [bad] + files[2:], chunk=2,
                fail_fast=True,
            )

    def test_crashed_producer_does_not_wedge(self):
        """The bounded queue must not deadlock the producer thread when
        the consumer dies first (abort flag re-checked on full puts)."""
        import jax.numpy as jnp

        def check(x):
            raise ValueError("dies immediately")

        with pytest.raises(PipelineError):
            run_pipeline(
                list(range(64)),
                lambda i: np.full((1,), i, np.int32),
                check,
                depth=1,
                fail_fast=True,
            )


class TestElasticQuarantine:
    """The default (PR 13) contract: work-unit isolation — a crashing
    chunk is retried, then isolated per history; only the crasher
    quarantines (explicit ``unknown`` with the exception as evidence)
    and every other verdict survives ≡ serial."""

    def test_produce_crash_quarantines_only_its_item(self):
        from jepsen_tpu.parallel.pipeline import Quarantined

        def produce(i):
            if i == 2:
                raise RuntimeError("packer exploded")
            return np.full((4,), i, np.int32)

        import jax.numpy as jnp

        res, stats = run_pipeline(
            list(range(5)), produce, lambda x: jnp.asarray(x) + 1
        )
        assert isinstance(res[2], Quarantined)
        assert res[2].stage == "produce"
        assert "packer exploded" in res[2].evidence()["errors"][-1]
        for i in (0, 1, 3, 4):
            assert not isinstance(res[i], Quarantined)
            assert int(np.asarray(res[i])[0]) == i + 1
        # the retry is counted — requeues are evidence, not log lines
        assert stats.unit_retries >= 1

    def test_corrupt_history_mid_corpus_quarantines_one(self, tmp_path):
        """A torn-JSON history inside a chunk quarantines exactly ITSELF
        (per-history isolation inside the failed chunk), the other
        members' verdicts equal the serial oracle, and the composed
        verdict can never be valid."""
        from jepsen_tpu.checkers.protocol import merge_valid

        base = synth_stream_batch(6, StreamSynthSpec(n_ops=20), lost=1)
        files = _write(tmp_path, base)
        bad = tmp_path / "h999.jsonl"
        bad.write_text('{"type": "not a real op"\n')  # torn JSON line
        mix = files[:2] + [bad] + files[2:]
        res, stats = check_sources("stream", mix, chunk=4)
        assert len(res) == 7
        assert res[2]["stream"]["valid?"] == "unknown"
        ev = res[2]["stream"]["quarantined"]
        assert ev["errors"], "quarantine must carry the exception"
        serial, _ = check_sources("stream", files, chunk=4, serial=True)
        assert [r for i, r in enumerate(res) if i != 2] == serial
        assert stats.quarantined == 1
        # precedence: unknown can never fold into valid; the seeded
        # lost-write invalid still trumps it
        assert merge_valid(r["stream"]["valid?"] for r in res) is False
        clean = [r["stream"]["valid?"] for i, r in enumerate(res)
                 if i == 2 or r["stream"]["valid?"] is True]
        assert merge_valid(clean) == "unknown"


class TestStatsAndMesh:
    def test_stats_schema(self, stream_corpus):
        _base, files = stream_corpus
        _res, stats = check_sources("stream", files, chunk=4)
        assert isinstance(stats, PipelineStats)
        assert stats.histories == len(files)
        assert 0.0 <= stats.stage_overlap_frac <= 1.0
        assert 0.0 <= stats.device_idle_frac <= 1.0
        assert stats.wall_s > 0

    @pytest.mark.parametrize("workload", ["stream", "queue", "elle"])
    def test_mesh_dispatch_matches_single_device(
        self, cpu_devices, tmp_path, workload
    ):
        """The pipeline's mesh placement (parallel/mesh.py sharded
        dispatch) yields the same verdicts as the default placement."""
        from jepsen_tpu.parallel.mesh import checker_mesh

        if workload == "stream":
            base = synth_stream_batch(6, StreamSynthSpec(n_ops=30), lost=1)
        elif workload == "queue":
            base = synth_batch(6, SynthSpec(n_ops=40), lost=1)
        else:
            base = synth_elle_batch(6, ElleSynthSpec(n_txns=8), g1a=1)
        files = _write(tmp_path, base)
        mesh = checker_mesh(cpu_devices)
        meshed, _ = check_sources(workload, files, chunk=3, mesh=mesh)
        plain, _ = check_sources(workload, files, chunk=3)
        assert meshed == plain

    def test_mesh_elle_with_degenerate_splice(self, cpu_devices, tmp_path):
        """A degenerate history shrinks a chunk's LIVE batch below the
        mesh's hist divisibility: the producer must re-pad, not crash."""
        from test_fuzz_elle_device import fuzz_history

        from jepsen_tpu.checkers.elle import elle_mops_for
        from jepsen_tpu.parallel.mesh import checker_mesh

        class _SH:
            def __init__(self, ops):
                self.ops = ops

        base = [_SH(fuzz_history(seed, n_txns=10)) for seed in range(8)]
        assert any(
            elle_mops_for(sh.ops)[1].degenerate for sh in base
        ), "corpus lost its degenerate member"
        files = _write(tmp_path, base)
        mesh = checker_mesh(cpu_devices)
        meshed, _ = check_sources("elle", files, chunk=4, mesh=mesh)
        plain, _ = check_sources("elle", files, chunk=4)
        assert meshed == plain


class TestNativeMultiFile:
    """Thread-pool multi-file native entry points == per-file calls."""

    @pytest.fixture(autouse=True)
    def _lib(self):
        from jepsen_tpu.history import fastpack

        if fastpack._load() is None:
            pytest.skip("native packer unavailable")

    def test_stream_rows_files(self, stream_corpus):
        from jepsen_tpu.history.fastpack import (
            stream_rows_file,
            stream_rows_files,
        )

        _base, files = stream_corpus
        multi = stream_rows_files(files, threads=3)
        assert multi is not None
        for p, got in zip(files, multi):
            one = stream_rows_file(p)
            assert (got[0] == one[0]).all() and got[1] == one[1]

    def test_pack_files(self, queue_corpus):
        from jepsen_tpu.history.fastpack import pack_file, pack_files

        _base, files = queue_corpus
        multi = pack_files(files, threads=2)
        assert multi is not None
        for p, got in zip(files, multi):
            kind, rows = pack_file(p)
            assert got[0] == kind and (got[1] == rows).all()

    def test_elle_mops_files(self, tmp_path):
        from jepsen_tpu.history.fastpack import (
            elle_mops_file,
            elle_mops_files,
        )

        base = synth_elle_batch(5, ElleSynthSpec(n_txns=8))
        files = _write(tmp_path, base)
        multi = elle_mops_files(files, threads=2)
        assert multi is not None
        for p, got in zip(files, multi):
            mat, meta = elle_mops_file(p)
            gmat, gmeta = got
            assert (gmat == mat).all()
            assert gmeta == meta

    def test_edn_files_fall_back(self, tmp_path):
        """.edn paths are excluded from the native call (per-slot None →
        Python twin), not crashed on."""
        from jepsen_tpu.history.fastpack import stream_rows_files

        base = synth_stream_batch(2, StreamSynthSpec(n_ops=10))
        files = _write(tmp_path, base)
        edn = tmp_path / "history.edn"
        edn.write_text("[]")
        got = stream_rows_files([files[0], edn, files[1]], threads=2)
        assert got is not None
        assert got[0] is not None and got[2] is not None
        assert got[1] is None


class TestStreamRowsCache:
    def test_round_trip_and_staleness(self, tmp_path):
        from jepsen_tpu.checkers.stream_lin import _stream_rows
        from jepsen_tpu.history.store import read_history
        from jepsen_tpu.history.storecache import (
            load_stream_rows_cache,
            save_stream_rows_cache,
            stream_rows_with_cache,
        )

        base = synth_stream_batch(1, StreamSynthSpec(n_ops=25), lost=1)
        (p,) = _write(tmp_path, base)
        cols, full, hit = stream_rows_with_cache(p)
        assert not hit
        ref_cols, ref_full = _stream_rows(read_history(p))
        assert (cols == ref_cols).all() and full == ref_full
        cols2, full2, hit2 = stream_rows_with_cache(p)
        assert hit2 and (cols2 == cols).all() and full2 == full
        # rewriting the history invalidates the cache
        write_history_jsonl(p, base[0].ops[:10])
        got = load_stream_rows_cache(p)
        if got is not None:  # same-mtime-ns race: digest must catch it
            fresh = _stream_rows(read_history(p))
            assert (got[0] == fresh[0]).all()
        _c3, _f3, hit3 = stream_rows_with_cache(p)
        cols4, full4, hit4 = stream_rows_with_cache(p)
        assert hit4
        assert (cols4 == _stream_rows(read_history(p))[0]).all()

    def test_corrupt_cache_ignored(self, tmp_path, caplog):
        """Corruption in EITHER backing store (the ``.jtc`` substrate or
        a legacy npz) must never serve wrong data: the jtc corruption is
        LOGGED (never a silent fallback), COUNTED in the obs registry
        (``jtc.fallback{reason=corrupt}`` — the after-the-run record the
        scrolled-away log line never was, ISSUE 10), and the load
        reports a miss."""
        import logging

        from jepsen_tpu.history.columnar import jtc_path_for
        from jepsen_tpu.history.storecache import (
            load_stream_rows_cache,
            save_stream_rows_cache,
            stream_rows_cache_path,
        )
        from jepsen_tpu.obs.metrics import REGISTRY

        base = synth_stream_batch(1, StreamSynthSpec(n_ops=10))
        (p,) = _write(tmp_path, base)
        save_stream_rows_cache(
            p, np.zeros((1, 6), np.int32), False
        )
        raw = bytearray(jtc_path_for(p).read_bytes())
        raw[-1] ^= 0xFF
        jtc_path_for(p).write_bytes(raw)
        stream_rows_cache_path(p).write_bytes(b"not an npz")
        before = REGISTRY.value("jtc.fallback", reason="corrupt")
        with caplog.at_level(logging.WARNING, "jepsen_tpu.history.columnar"):
            assert load_stream_rows_cache(p) is None
        assert any(
            "corrupt columnar substrate" in r.message for r in caplog.records
        )
        # the counter, not just the log line: triage after the run can
        # ask the registry how many fallbacks happened and why
        assert REGISTRY.value("jtc.fallback", reason="corrupt") >= before + 1
