"""Unit tests for the live-test retry/triage harness (tests/_live.py).

The harness itself must be trustworthy: flake retries may never launder
a genuine red into a green (and vice versa), and an exhausted retry
budget must fail loudly *naming the invalidating checker* — VERDICT r4
weak #2's exact complaint about the bare ``assert valid?``.
"""

from __future__ import annotations

import pytest

from _live import describe_invalid, run_live_with_triage


class _Run:
    def __init__(self, results, history=()):
        self.results = results
        self.history = list(history)


class _Transport:
    def __init__(self, log):
        self.log = log

    def close(self):
        self.log.append("close")


def _harness(monkeypatch, outcomes):
    """Patch run_test to pop scripted outcomes (a results map, or an
    exception to raise); returns (build_fn, log)."""
    import jepsen_tpu.control.runner as runner

    log: list = []
    seq = iter(outcomes)

    def fake_run_test(test):
        log.append("run")
        out = next(seq)
        if isinstance(out, Exception):
            raise out
        return _Run(out)

    monkeypatch.setattr(runner, "run_test", fake_run_test)

    def build():
        log.append("build")
        return object(), _Transport(log)

    return build, log


GREEN = {"valid?": True, "queue": {"valid?": True, "lost-count": 0,
                                   "attempt-count": 50, "ok-count": 40}}
RED = {"valid?": False,
       "queue": {"valid?": False, "lost-count": 3, "attempt-count": 50,
                 "ok-count": 40, "lost": ["q_1", "q_2", "q_3"]},
       "stats": {"valid?": True}}
NEVER_READ = {"valid?": False,
              "queue": {"valid?": False, "lost-count": 50,
                        "attempt-count": 50, "ok-count": 0}}


def test_green_first_attempt_builds_once(monkeypatch):
    build, log = _harness(monkeypatch, [GREEN])
    run = run_live_with_triage(build, expect="valid")
    assert run.results["valid?"] is True
    assert log == ["build", "run", "close"]


def test_flaky_red_retries_then_green(monkeypatch):
    """The scheduler-pressure case: one invalid attempt, then green —
    a fresh cluster per attempt, transports always closed."""
    build, log = _harness(monkeypatch, [RED, GREEN])
    run = run_live_with_triage(build, expect="valid")
    assert run.results["valid?"] is True
    assert log == ["build", "run", "close", "build", "run", "close"]


def test_persistent_red_fails_naming_the_checker(monkeypatch):
    """A genuine violation survives the retry budget and the failure
    message carries the invalidating checker + anomaly counts."""
    build, log = _harness(monkeypatch, [RED, RED, RED])
    with pytest.raises(AssertionError) as e:
        run_live_with_triage(build, expect="valid")
    msg = str(e.value)
    assert "queue" in msg and "lost-count" in msg and "3" in msg
    assert msg.count("analysis invalid") == 3
    assert log.count("close") == 3  # every attempt's cluster torn down


def test_final_read_missing_retries_not_triaged_as_red(monkeypatch):
    """'Set was never read': ok-count == 0 cannot attest loss — retry,
    even though the verdict also says invalid (the reference's triage
    order, matrix.py _final_read_missing)."""
    build, log = _harness(monkeypatch, [NEVER_READ, GREEN])
    run = run_live_with_triage(build, expect="valid")
    assert run.results["valid?"] is True


def test_crash_retries(monkeypatch):
    build, log = _harness(monkeypatch, [RuntimeError("boom"), GREEN])
    run = run_live_with_triage(build, expect="valid")
    assert run.results["valid?"] is True
    assert log.count("close") == 2


def test_expect_invalid_returns_first_red(monkeypatch):
    build, log = _harness(monkeypatch, [RED])
    run = run_live_with_triage(build, expect="invalid")
    assert run.results["valid?"] is False


def test_expect_invalid_never_laundered_by_green_flake(monkeypatch):
    """A seeded-bug test that keeps coming back green must FAIL — the
    bug should have been caught."""
    build, log = _harness(monkeypatch, [GREEN, GREEN, GREEN])
    with pytest.raises(AssertionError, match="should have gone red"):
        run_live_with_triage(build, expect="invalid")


def test_checks_failure_is_retryable(monkeypatch):
    build, log = _harness(monkeypatch, [GREEN, GREEN])
    calls = []

    def checks(run):
        calls.append(1)
        if len(calls) == 1:
            raise AssertionError("nemesis never fired")

    run = run_live_with_triage(build, expect="valid", checks=checks)
    assert len(calls) == 2


def test_unknown_verdict_retries(monkeypatch):
    build, log = _harness(
        monkeypatch, [{"valid?": "unknown", "queue": {"ok-count": 5,
                                                      "attempt-count": 9}},
                      GREEN],
    )
    run = run_live_with_triage(build, expect="valid")
    assert run.results["valid?"] is True


def test_describe_invalid_names_checkers_and_counts():
    bad = describe_invalid(RED)
    assert set(bad) == {"queue"}  # stats was valid
    assert bad["queue"]["lost-count"] == 3
    assert bad["queue"]["lost-len"] == 3
