"""CI layer: branch extractor, rate limiter, matrix↔shell parity."""

import json
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
URL = (
    "https://github.com/rabbitmq/server-packages/releases/download/"
    "alphas.1731926502914/rabbitmq-server-generic-unix-4.1.0-alpha."
    "047cc5a0.tar.xz"
)


def sh(script, *args, env=None, cwd=None):
    return subprocess.run(
        ["bash", str(script), *args],
        capture_output=True,
        text=True,
        env=env,
        cwd=cwd or REPO,
    )


class TestBranchExtractor:
    SCRIPT = REPO / "ci" / "extract-rabbitmq-branch-from-binary-url.sh"

    def test_alpha_url(self):
        r = sh(self.SCRIPT, URL)
        assert r.returncode == 0
        assert r.stdout.strip() == "41"

    def test_release_url(self):
        r = sh(
            self.SCRIPT,
            "https://example.com/rabbitmq-server-generic-unix-4.2.1.tar.xz",
        )
        assert r.stdout.strip() == "42"

    def test_missing_arg_fails(self):
        r = sh(self.SCRIPT)
        assert r.returncode != 0


class TestRateLimiter:
    SCRIPT = REPO / "ci" / "check-last-execution.sh"

    def _run(self, tmp_path, last_execution=None, skip_check=None):
        # the script downloads the artifact via `gh`; in tests `gh` is a
        # stub and the artifact state is pre-seeded in cwd
        (tmp_path / "ci").mkdir(exist_ok=True)
        for f in ("extract-rabbitmq-branch-from-binary-url.sh",):
            (tmp_path / "ci" / f).write_text((REPO / "ci" / f).read_text())
        gh = tmp_path / "gh"
        gh.write_text("#!/bin/sh\nexit 1\n")
        gh.chmod(0o755)
        if last_execution is not None:
            (tmp_path / "last-execution.txt").write_text(str(last_execution))
        out = tmp_path / "out.txt"
        out.write_text("")
        env = {
            "PATH": f"{tmp_path}:/usr/bin:/bin",
            "BINARY_URL": URL,
            "GITHUB_OUTPUT": str(out),
        }
        if skip_check is not None:
            env["SKIP_CHECK"] = skip_check
        r = sh(self.SCRIPT.resolve(), env=env, cwd=tmp_path)
        assert r.returncode == 0, r.stderr
        return dict(
            line.split("=", 1)
            for line in out.read_text().splitlines()
            if "=" in line
        )

    def test_first_run_allowed(self, tmp_path):
        assert self._run(tmp_path)["allow_execution"] == "true"

    def test_recent_run_blocked(self, tmp_path):
        import time

        got = self._run(tmp_path, last_execution=int(time.time()) - 60)
        assert got["allow_execution"] == "false"

    def test_old_run_allowed(self, tmp_path):
        import time

        got = self._run(tmp_path, last_execution=int(time.time()) - 90000)
        assert got["allow_execution"] == "true"

    def test_skip_check_forces(self, tmp_path):
        import time

        got = self._run(
            tmp_path,
            last_execution=int(time.time()) - 60,
            skip_check="true",
        )
        assert got["allow_execution"] == "true"


class TestMatrixCliParity:
    def test_fourteen_configs(self):
        from jepsen_tpu.harness.matrix import CI_MATRIX, matrix_cli_flags

        lines = matrix_cli_flags()
        assert len(lines) == len(CI_MATRIX) == 14

    def test_flags_parse_back_through_test_subcommand(self):
        """Every emitted config line must be accepted verbatim by the
        ``test`` subcommand's parser (the CI shell contract)."""
        from jepsen_tpu.cli.main import build_parser
        from jepsen_tpu.harness.matrix import CI_MATRIX, matrix_cli_flags

        parser = build_parser()
        for cfg, line in zip(CI_MATRIX, matrix_cli_flags()):
            ns = parser.parse_args(["test", *line.split()])
            assert ns.network_partition == cfg["partition"]
            assert ns.partition_duration == cfg["duration"]
            assert ns.consumer_type == cfg["consumer-type"]
            assert ns.dead_letter == bool(cfg.get("dead-letter"))

    def test_print_configs_cli(self):
        r = subprocess.run(
            ["python", "-m", "jepsen_tpu", "matrix", "--print-configs"],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert r.returncode == 0
        assert len(r.stdout.strip().splitlines()) == 14

    def test_dead_letter_configs_present(self):
        from jepsen_tpu.harness.matrix import matrix_cli_flags

        assert sum("--dead-letter" in l for l in matrix_cli_flags()) == 2

    def test_extended_configs_parse_and_stay_opt_in(self):
        """The extended (process-fault) rows parse through the test
        parser like every reference row, and never leak into the default
        14 (reference parity)."""
        from jepsen_tpu.cli.main import build_parser
        from jepsen_tpu.harness.matrix import (
            CI_MATRIX,
            EXTENDED_MATRIX,
            matrix_cli_flags,
        )

        assert len(CI_MATRIX) == 14 and len(EXTENDED_MATRIX) == 4
        assert not any("--nemesis" in l for l in matrix_cli_flags())
        parser = build_parser()
        for cfg, line in zip(
            EXTENDED_MATRIX, matrix_cli_flags(EXTENDED_MATRIX)
        ):
            ns = parser.parse_args(["test", *line.split()])
            assert ns.nemesis == cfg["nemesis"]


class TestCiDriverShell:
    def test_driver_is_syntactically_valid(self):
        r = subprocess.run(
            ["bash", "-n", str(REPO / "ci" / "jepsen-tpu-test.sh")],
            capture_output=True,
        )
        assert r.returncode == 0, r.stderr

    def test_provision_script_is_syntactically_valid(self):
        r = subprocess.run(
            ["bash", "-n", str(REPO / "ci" / "provision-jepsen-tpu-controller.sh")],
            capture_output=True,
        )
        assert r.returncode == 0, r.stderr

    def test_workflow_helper_scripts_are_syntactically_valid(self):
        for name in ("verify-binary-signature.sh", "destroy-cluster.sh"):
            r = subprocess.run(
                ["bash", "-n", str(REPO / "ci" / name)],
                capture_output=True,
            )
            assert r.returncode == 0, (name, r.stderr)


def test_local_extended_tier_parses_and_stays_out_of_sim():
    """clock-skew / membership-churn configs need fault surfaces the sim
    cannot honestly provide: they parse like every row, ship only with
    --db local/rabbitmq, and never leak into the sim-safe tiers."""
    from jepsen_tpu.cli.main import build_parser
    from jepsen_tpu.harness.matrix import (
        EXTENDED_MATRIX,
        LOCAL_EXTENDED_MATRIX,
        matrix_cli_flags,
    )

    assert len(LOCAL_EXTENDED_MATRIX) == 7
    parser = build_parser()
    for line in matrix_cli_flags(LOCAL_EXTENDED_MATRIX):
        parser.parse_args(["test"] + line.split())
    # the sim-safe tier must carry none of the faults the sim would noop:
    # no wall clocks (clock-skew), no real membership (churn), no per-node
    # durable state for a power failure to threaten (crash-restart and the
    # durable mixed soak — advisor r4: these passed vacuously on sim), no
    # WAL for a slow disk to stall, no peer wire for chaos to mangle, and
    # no direction-honoring net for a one-way partition
    sim_safe = {c.get("nemesis") for c in EXTENDED_MATRIX}
    assert not sim_safe & {
        "clock-skew", "membership-churn", "crash-restart-cluster", "mixed",
        "slow-disk", "wire-chaos",
    }
    assert not any(c.get("durable") for c in EXTENDED_MATRIX)
    assert not any(
        "one-way" in str(c.get("partition", "")) for c in EXTENDED_MATRIX
    )


class TestBenchElleSmoke:
    """Offline bench gate: the elle section of ``bench.py`` at a tiny
    batch on the CPU backend.  Packer/schema regressions in the new
    device-inference keys (fused rate, end-to-end, roofline) must fail
    the suite here instead of surfacing only on a chip window."""

    @pytest.fixture()
    def bench(self, monkeypatch):
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        import bench as bench_mod

        # smoke scale: a handful of tiny graphs, one timed block
        monkeypatch.setattr(bench_mod, "ELLE_BASE", 16)
        monkeypatch.setattr(bench_mod, "ELLE_BATCH", 16)
        monkeypatch.setattr(bench_mod, "ELLE_TXNS", 8)
        monkeypatch.setattr(bench_mod, "BLOCKS", 1)
        monkeypatch.setattr(bench_mod, "BLOCK_ITERS", 2)
        monkeypatch.setattr(bench_mod, "CPU_BASELINE_SAMPLES", 2)
        monkeypatch.setattr(bench_mod, "MUTEX_OPS", 16)
        return bench_mod

    def test_elle_section_schema(self, bench):
        details = {}
        bench._bench_elle(details)
        e = details["elle"]
        for key in (
            "device_histories_per_sec",
            "device_fused_histories_per_sec",
            "end_to_end_histories_per_sec",
            "end_to_end_histories_per_sec_python",
            "end_to_end_vs_device_only",
            "achieved_gbps",
            "hbm_util",
            "mxu_util",
        ):
            assert key in e, f"elle bench schema lost key {key!r}"
        assert e["device_histories_per_sec"] > 0
        assert e["device_fused_histories_per_sec"] > 0
        assert e["end_to_end_histories_per_sec"] > 0
        assert e["achieved_gbps"] > 0
        import math

        r = e["roofline"]
        assert r["closure_dots"] == 3 * (
            math.ceil(math.log2(max(r["txn_slots"], 2))) + 1
        )
        assert r["flops_per_history"] == r["closure_dots"] * 2 * r[
            "txn_slots"
        ] ** 3
        # round-14 roofline honesty: the row says WHICH representation
        # was dispatched and computes bytes from ITS dtypes/shapes
        from jepsen_tpu.checkers.elle import DEFAULT_CLOSURE

        assert r["representation"] == DEFAULT_CLOSURE
        assert e["closure"] == DEFAULT_CLOSURE
        T = r["txn_slots"]
        per_dot = {
            "packed": 3 * T * ((T + 31) // 32) * 4,
            "dense": 3 * T * T * 2,
            "int8": 3 * T * T,
        }[r["representation"]]
        assert r["hbm_bytes_per_history"] == r["closure_dots"] * per_dot
        # CPU backend: achieved numbers present, utils honestly None
        assert e["hbm_util"] is None and e["mxu_util"] is None

    def test_roofline_accounting_per_representation(self, bench):
        """The packed/dense/int8 byte accounting, pinned: packed rows
        must charge uint32-bitplane bytes (the 16× delta vs bf16 is
        exactly the format tax the old accounting laundered), and
        ``mxu_util`` must be None for the representation that does no
        MXU work — packed and dense rows stay comparable because each
        states its own traffic."""
        import math

        T = 128
        dots = 3 * (math.ceil(math.log2(T)) + 1)
        packed = bench._elle_roofline(T, 10.0, 10.0, representation="packed")
        dense = bench._elle_roofline(T, 10.0, 10.0, representation="dense")
        int8 = bench._elle_roofline(T, 10.0, 10.0, representation="int8")
        assert packed["hbm_bytes_per_history"] == dots * 3 * T * (T // 32) * 4
        assert dense["hbm_bytes_per_history"] == dots * 3 * T * T * 2
        assert int8["hbm_bytes_per_history"] == dots * 3 * T * T
        assert dense["hbm_bytes_per_history"] == (
            16 * packed["hbm_bytes_per_history"]
        )
        # identical boolean-semiring op count across representations
        assert (
            packed["flops_per_history"]
            == dense["flops_per_history"]
            == int8["flops_per_history"]
        )
        assert "fixed-squaring upper bound" in packed["dots_note"]
        assert "dots_note" not in dense
        import pytest as _pytest

        with _pytest.raises(ValueError):
            bench._elle_roofline(T, 1.0, 1.0, representation="bf8")

    def test_mutex_device_section_scoped_off_cpu(self, bench):
        """The pathological CPU-backend mutex device rows (BENCH_r05:
        36 hist/s at 1.8 s/iter vs 22,159 CPU) stay skipped: the section
        must record the scoping note and the CPU reference only."""
        details = {}
        bench._bench_mutex(details)
        m = details["mutex"]
        assert "device_skipped" in m and "chip-only" in m["device_skipped"]
        assert m["cpu_histories_per_sec"] > 0
        assert "device_histories_per_sec" not in m


class TestBenchPipelineSmoke:
    """Offline gate for the pipeline-utilization bench keys: the stream
    section (tiny shapes) must report the measured bytes-to-verdict
    executor keys next to the classic device/e2e rows, and the queue
    pipeline section must do the same — schema regressions fail here,
    not on a chip window."""

    PIPELINE_KEYS = (
        "pipeline_e2e_histories_per_sec",
        "stage_overlap_frac",
        "device_idle_frac",
        "pipeline_e2e_vs_device_only",
        "pipeline_e2e_vs_async_device",
    )

    @pytest.fixture()
    def bench(self, monkeypatch):
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        import bench as bench_mod

        monkeypatch.setattr(bench_mod, "BLOCKS", 1)
        monkeypatch.setattr(bench_mod, "BLOCK_ITERS", 2)
        monkeypatch.setattr(bench_mod, "CPU_BASELINE_SAMPLES", 2)
        return bench_mod

    def test_stream_section_reports_pipeline_keys(self, bench):
        details = {}
        bench._bench_stream_sized(
            details, "stream", n_ops=40, batch=16, blocks=1,
            base_n=8, cpu_samples=2,
        )
        e = details["stream"]
        for key in self.PIPELINE_KEYS:
            assert key in e, f"stream bench schema lost key {key!r}"
        assert e["pipeline_e2e_histories_per_sec"] > 0
        assert 0.0 <= e["device_idle_frac"] <= 1.0
        assert 0.0 <= e["stage_overlap_frac"] <= 1.0
        # the occupancy ratio is 1 - device_idle_frac by construction
        assert abs(
            e["pipeline_e2e_vs_device_only"]
            - (1.0 - e["device_idle_frac"])
        ) < 5e-3
        # classic keys must survive alongside
        assert "end_to_end_histories_per_sec" in e

    def test_queue_pipeline_section(self, bench, monkeypatch):
        monkeypatch.setattr(bench, "BASE_HISTORIES", 8)
        monkeypatch.setattr(bench, "N_OPS", 40)
        details = {"queue": {"device_histories_per_sec": 100.0}}
        bench._bench_queue_pipeline(details)
        for key in self.PIPELINE_KEYS:
            assert key in details["queue"], key
        assert details["queue"]["pipeline_e2e_histories_per_sec"] > 0


class TestCompileCacheRoundTrip:
    """The persistent XLA compile cache, offline: a first (cold) process
    must POPULATE the store cache dir, a second (warm) process must find
    it non-empty and not shrink it — the BENCH_r05 `compile cache:
    entries 0` regression gate, CPU backend, no network."""

    SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from jepsen_tpu.utils.jaxenv import (
    compile_cache_entries, enable_compilation_cache, pin_cpu_platform,
)
pin_cpu_platform()
d = enable_compilation_cache({cache!r}, backend="cpu")
assert d is not None, "cache dir unusable"
import jax
# cache even instant compiles for this probe
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
import jax.numpy as jnp
import numpy as np
before = compile_cache_entries(d)
f = jax.jit(lambda x: jnp.cumsum(x * 2) - jnp.sort(x))
jax.block_until_ready(f(jnp.arange(512)))
after = compile_cache_entries(d)
print(f"CACHE {{d}} {{before}} {{after}}")
"""

    def _run(self, cache_dir):
        import subprocess
        import sys as _sys

        r = subprocess.run(
            [
                _sys.executable,
                "-c",
                self.SCRIPT.format(repo=str(REPO), cache=str(cache_dir)),
            ],
            capture_output=True,
            text=True,
            env={
                **__import__("os").environ,
                "JAX_PLATFORMS": "cpu",
            },
        )
        assert r.returncode == 0, r.stderr[-800:]
        line = [
            ln for ln in r.stdout.splitlines() if ln.startswith("CACHE ")
        ][-1]
        _tag, d, before, after = line.rsplit(" ", 3)
        return d.split(" ", 1)[-1], int(before), int(after)

    def test_cold_populates_then_warm_reuses(self, tmp_path):
        cache = tmp_path / "xla_cache"
        d1, before1, after1 = self._run(cache)
        assert before1 == 0 and after1 > 0, (
            f"cold run never populated the cache ({before1}->{after1})"
        )
        d2, before2, after2 = self._run(cache)
        assert d2 == d1
        # the warm-run contract the bench now asserts: entries_after is
        # NON-ZERO on a second warm run, and the same program adds no
        # new entry (XLA deserialized the existing executable)
        assert before2 == after1 > 0
        assert after2 == after1, (
            f"warm run recompiled: {after1} -> {after2} entries"
        )

    def test_cpu_cache_is_machine_fingerprinted(self, tmp_path):
        from jepsen_tpu.utils.jaxenv import _cpu_cache_fingerprint

        _d, _b, _a = self._run(tmp_path / "xla_cache")
        sub = (
            tmp_path / "xla_cache" / f"cpu-{_cpu_cache_fingerprint()}"
        )
        assert sub.is_dir(), (
            "CPU-backend cache entries must land in the fingerprinted "
            "subdirectory, never the TPU root layout"
        )


class TestHclGate:
    """Offline HCL syntax gate (VERDICT r5 #7): the terraform files have
    never been parsed by any terraform binary in this image — the fake-
    cloud shim stubs it — so a vendored grammar check must catch the
    cheap failure class (truncated edits, stray braces, missing '=')."""

    TF = REPO / "ci" / "jepsen-tpu-aws.tf"

    def test_repo_terraform_files_pass(self):
        from jepsen_tpu.utils.hcl import check_hcl_file

        tfs = sorted(REPO.glob("ci/**/*.tf"))
        assert tfs, "no terraform files found under ci/"
        for tf in tfs:
            assert check_hcl_file(tf) == [], tf

    def _broken(self, mutate):
        from jepsen_tpu.utils.hcl import check_hcl

        return check_hcl(mutate(self.TF.read_text()))

    def test_unclosed_brace_fails(self):
        errs = self._broken(
            lambda s: s.replace('resource "aws_instance" "controller" {',
                                'resource "aws_instance" "controller" {{')
        )
        assert errs and "unclosed" in errs[0]

    def test_truncated_file_fails(self):
        errs = self._broken(lambda s: s[: len(s) // 2].rsplit("\n", 1)[0])
        assert errs  # a mid-file cut cannot stay balanced/complete

    def test_unterminated_string_fails(self):
        errs = self._broken(
            lambda s: s.replace('region = var.region', 'region = "eu-west')
        )
        assert errs and "string" in errs[0]

    def test_missing_equals_fails(self):
        errs = self._broken(
            lambda s: s.replace("region = var.region", "region var.region")
        )
        assert errs

    def test_mismatched_bracket_fails(self):
        from jepsen_tpu.utils.hcl import check_hcl

        errs = check_hcl('x = [1, 2}\n')
        assert errs and "mismatched" in errs[0]

    def test_empty_rhs_fails(self):
        from jepsen_tpu.utils.hcl import check_hcl

        assert check_hcl("a =\nb = 2\n")


class TestBenchScaleOutSmoke:
    """Offline gates for the PR-5 scale-out bench schema: the
    ``north_star`` wall-time row and the virtual-device ``scaling``
    section must keep their keys (``north_star.wall_s``,
    ``scaling.devices``, ``scaling.e2e_histories_per_sec``) — schema
    regressions fail here, not on a chip window.  Tiny configs; the
    scaling smoke runs two real subprocess points (1 and 2 virtual
    devices) through the meshed multi-lane reduced pipeline."""

    @pytest.fixture()
    def bench(self):
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        import bench as bench_mod

        return bench_mod

    def test_north_star_section_schema(self, bench):
        details = {}
        bench._bench_north_star(
            details, histories=24, base_n=8, n_ops=40, chunk=8
        )
        ns = details["north_star"]
        for key in (
            "wall_s",
            "vs_baseline_target_s",
            "met_target",
            "e2e_histories_per_sec",
            "histories",
            "devices",
            "lanes",
            "backend",
        ):
            assert key in ns, f"north_star schema lost key {key!r}"
        assert ns["wall_s"] > 0
        assert ns["vs_baseline_target_s"] == 60
        assert ns["histories"] == 24
        assert ns["e2e_histories_per_sec"] > 0
        # the virtual mesh the conftest pins: all 8 devices fed
        assert ns["devices"] == 8 and ns["lanes"] == 8

    def test_north_star_100k_section_schema_and_gm_smoke(self, bench):
        """The ISSUE-18 bench gate AND the offline 2-process global-mesh
        smoke in one: a tiny config through the REAL
        ``_bench_north_star_100k`` section spawns both fleet sizes (1
        and 2 processes joined into one ``jax.distributed`` mesh under
        ``JAX_PLATFORMS=cpu``), so a schema regression or a broken
        cross-process collective path fails here, not on a chip
        window."""
        details = {}
        bench._bench_north_star_100k(
            details, histories=16, base_n=8, n_ops=40, chunk=8,
            timeout_s=420,
        )
        ns = details["north_star_100k"]
        for key in (
            "histories",
            "rows",
            "verdicts_match",
            "scaling_2proc_vs_1",
            "host_cores",
            "scaling_note",
            "collectives",
        ):
            assert key in ns, f"north_star_100k schema lost key {key!r}"
        assert ns["histories"] == 16
        assert [r["procs"] for r in ns["rows"]] == [1, 2]
        assert all(r["wall_s"] > 0 for r in ns["rows"])
        assert all(r["dead_workers"] == 0 for r in ns["rows"])
        # the acceptance criterion in miniature: the 2-proc global mesh
        # reproduces the 1-proc verdict exactly
        assert ns["verdicts_match"] is True
        assert ns["scaling_2proc_vs_1"] > 0

    def test_scaling_section_schema(self, bench):
        details = {}
        bench._bench_scaling(
            details,
            device_counts=(1, 2),
            files=6,
            repeat=1,
            chunk=4,
            persist=False,  # the smoke must never touch BENCH_DETAILS
        )
        sc = details["scaling"]
        assert sc["devices"] == [1, 2]
        for fam in ("stream", "elle"):
            rates = sc["e2e_histories_per_sec"][fam]
            assert len(rates) == 2
            assert all(r and r > 0 for r in rates), (fam, sc)
        assert "host_cores" in sc and "note" in sc


class TestBenchColdWarmSmoke:
    """Offline gates for the PR-7 columnar-substrate bench schema: the
    ``cold_vs_warm`` section must keep its keys (cold/warm walls, the
    2x ratio, ``pack_bytes_per_sec`` for the columnar reader) so the
    tentpole's claim stays a measured schema key, not prose — plus a
    format-version round-trip smoke for the ``.jtc`` itself."""

    @pytest.fixture()
    def bench(self):
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        import bench as bench_mod

        return bench_mod

    def test_cold_vs_warm_section_schema(self, bench):
        details = {}
        bench._bench_cold_vs_warm(
            details, histories=24, base_n=8, n_ops=40, chunk=8
        )
        cw = details["cold_vs_warm"]
        for key in (
            "legacy_cold_wall_s",
            "record_pack_s",
            "columnar_cold_wall_s",
            "warm_wall_s",
            "cold_vs_warm_ratio",
            "within_2x",
            "cold_speedup_vs_legacy",
            "pack_bytes_per_sec",
            "columnar_read_src_bytes_per_sec",
            "jsonl_parse_python_bytes_per_sec",
            "columnar_speedup_vs_python_parse",
            "jsonl_parse_native_bytes_per_sec",
            "columnar_speedup_vs_native_parse",
            "verdicts_match",
            "backend",
        ):
            assert key in cw, f"cold_vs_warm schema lost key {key!r}"
        assert cw["histories"] == 24
        # the DIFFERENTIAL half of the acceptance gate: all three runs
        # (legacy parse, cold substrate, warm substrate) agreed
        assert cw["verdicts_match"] is True
        assert cw["pack_bytes_per_sec"] > 0
        assert cw["columnar_speedup_vs_python_parse"] > 0

    def test_wgl_pcomp_section_schema(self, bench):
        """Offline gate for the ISSUE-9 ``wgl_pcomp`` bench schema: one
        tiny real row (n=40, w=2, per-row subprocess with deadline —
        exactly the production harness) must carry the keys the round-6
        table and the crossover done-bar read."""
        details = {}
        bench._bench_wgl_pcomp(
            details, rows_spec=((40, 2),), batch=2, deadline=240.0,
            persist=False,  # the smoke must never touch BENCH_DETAILS
        )
        wp = details["wgl_pcomp"]
        for key in ("rows", "crossover_met", "best_speedup_vs_classic"):
            assert key in wp, f"wgl_pcomp schema lost key {key!r}"
        assert len(wp["rows"]) == 1
        row = wp["rows"][0]
        for key in (
            "n_ops",
            "window",
            "backend",
            "compile_s",
            "pcomp_per_history_ms",
            "pcomp_subhistories",
            "pcomp_sub_capacity",
            "classic_per_history_ms",
            "classic_samples",
            "speedup_vs_classic",
            "winner",
            "all_linearizable",
            "unknown_frac",
        ):
            assert key in row, f"wgl_pcomp row schema lost key {key!r}"
        assert row["all_linearizable"] is True
        assert row["unknown_frac"] == 0.0
        assert row["winner"] in ("pcomp", "classic")
        # a tiny easy row must not accidentally claim the ≥1k-op
        # crossover done-bar
        assert wp["crossover_met"] is False

    def test_bitpack_section_schema(self, bench, monkeypatch):
        """Offline gate for the round-14 ``bitpack`` bench schema: a
        tiny REAL packed-vs-dense A/B per family (elle closure, queue
        verdict buffers, wgl_pcomp engines) on the CPU backend must
        carry the per-family rate/speedup keys and the done-bar block —
        and, at scaled-down shapes, must be structurally UNABLE to
        claim the ≥4× north-star done-bar no matter what ratios it
        happens to measure."""
        monkeypatch.setattr(bench, "ELLE_TXNS", 8)
        monkeypatch.setattr(bench, "N_OPS", 40)
        monkeypatch.setattr(bench, "LENGTH", 128)
        monkeypatch.setattr(bench, "BITPACK_ELLE_BASE", 8)
        monkeypatch.setattr(bench, "BITPACK_ELLE_BATCH", 8)
        monkeypatch.setattr(bench, "BITPACK_QUEUE_BASE", 8)
        monkeypatch.setattr(bench, "BITPACK_QUEUE_BATCH", 8)
        monkeypatch.setattr(bench, "BITPACK_WGL_OPS", 60)
        monkeypatch.setattr(bench, "BITPACK_WGL_WINDOW", 2)
        monkeypatch.setattr(bench, "BITPACK_WGL_HISTS", 2)
        monkeypatch.setattr(bench, "BITPACK_BLOCKS", 1)
        monkeypatch.setattr(bench, "BITPACK_ITERS", 2)
        details = {}
        bench._bench_bitpack(details)
        bp = details["bitpack"]
        for key in ("families", "backend", "north_star", "done_bar"):
            assert key in bp, f"bitpack schema lost key {key!r}"
        assert set(bp["families"]) == {"elle", "queue", "wgl_pcomp"}
        for name, row in bp["families"].items():
            assert "error" not in row, (name, row)
            assert row["packed_histories_per_sec"] > 0, name
            assert row["dense_histories_per_sec"] > 0, name
            assert row["speedup_packed_vs_dense"] > 0, name
            assert row["winner"] in ("packed", "dense", "int8"), name
            # the smoke runs SCALED-DOWN shapes: every row must say so
            assert row["north_star_shape"] is False, name
        assert "fused_speedup_packed_vs_dense" in bp["families"]["elle"]
        db = bp["done_bar"]
        assert db["threshold"] == 4.0 and db["families_needed"] == 2
        # the easy-shape guarantee: no north-star row ⇒ no done-bar,
        # regardless of the measured ratios
        assert db["families_met"] == [] and db["met"] is False

    def test_obs_overhead_section_schema(self, bench):
        """Offline gate for the ISSUE-10 ``obs_overhead`` bench schema:
        a tiny real tracing-on-vs-off pair must carry the overhead
        fraction, the span count, and the p50/p99 check-batch latency
        keys the flight-recorder done-bar reads.  The fraction itself
        is asserted only as finite here — a 24-history smoke is noise;
        the ≤2% claim belongs to the committed full-config log."""
        details = {}
        bench._bench_obs_overhead(
            details, histories=24, base_n=8, n_ops=40, chunk=8, repeats=1
        )
        oo = details["obs_overhead"]
        for key in (
            "tracing_off_wall_s",
            "tracing_on_wall_s",
            "overhead_frac",
            "within_2pct",
            "spans_recorded",
            "check_batch_p50_ms",
            "check_batch_p99_ms",
            "e2e_histories_per_sec_traced",
            "histories",
            "devices",
            "lanes",
            "backend",
        ):
            assert key in oo, f"obs_overhead schema lost key {key!r}"
        assert oo["histories"] == 24
        assert oo["tracing_off_wall_s"] > 0 and oo["tracing_on_wall_s"] > 0
        assert oo["spans_recorded"] > 0
        assert oo["check_batch_p99_ms"] >= oo["check_batch_p50_ms"] > 0
        assert oo["overhead_frac"] == oo["overhead_frac"]  # finite
        # the traced run really went through the lanes executor
        assert oo["lanes"] >= 1

    def test_elastic_overhead_section_schema(self, bench):
        """Offline gate for the ISSUE-13 ``elastic_overhead`` bench
        schema: a tiny real elastic-vs-fail-fast pair plus real
        kill-0 / kill-1 launcher rows must carry the ≤2% no-fault bar
        key, prove the no-fault elastic arm quarantined NOTHING, and
        pin the honesty rule that a zero-kill row can't claim recovery
        (no deaths, no requeues, no recovery keys) while the kill row
        must show a real requeue.  The fraction itself is asserted only
        as finite here — a 24-history smoke is noise; the ≤2% claim
        belongs to the committed full-config log."""
        details = {}
        bench._bench_elastic_overhead(
            details, histories=24, base_n=8, n_ops=40, chunk=8,
            repeats=1, kill_histories=10, kill_base_n=5, kill_ops=25,
            kill_procs=2, kills=(0, 1), timeout_s=300.0,
        )
        eo = details["elastic_overhead"]
        for key in (
            "fail_fast_wall_s",
            "elastic_wall_s",
            "overhead_frac",
            "within_2pct",
            "quarantined_no_fault",
            "unit_retries_no_fault",
            "kill_recovery",
            "histories",
            "devices",
            "lanes",
            "backend",
        ):
            assert key in eo, f"elastic_overhead schema lost key {key!r}"
        assert eo["histories"] == 24
        assert eo["fail_fast_wall_s"] > 0 and eo["elastic_wall_s"] > 0
        assert eo["overhead_frac"] == eo["overhead_frac"]  # finite
        # the no-fault elastic arm must be genuinely no-fault
        assert eo["quarantined_no_fault"] == 0
        assert len(eo["kill_recovery"]) == 2
        zero, one = eo["kill_recovery"]
        # a zero-kill row can NEVER claim recovery
        assert zero["kills"] == 0
        assert zero["dead_workers"] == 0
        assert zero["requeued_stripes"] == 0
        assert zero["quarantined_histories"] == 0
        assert "recovery_p50_s" not in zero
        assert "recovery_count" not in zero
        # the kill row really exercised the requeue path
        assert one["kills"] == 1
        assert one["dead_workers"] >= 1
        assert one["requeued_stripes"] >= 1
        assert one["recovery_count"] >= 1
        assert one["recovery_p50_s"] > 0
        assert one["verdicts_match_no_kill"] is True

    def test_cluster_obs_overhead_section_schema(self, bench):
        """Offline gate for the ISSUE-12 ``cluster_obs_overhead`` bench
        schema: a tiny REAL off-vs-on pair over a live 3-node
        replicated cluster must carry the throughput keys, the
        overhead fraction, and proof the telemetry poller actually
        sampled (cluster.json polls/samples/events).  The fraction
        itself is asserted only as finite here — a 4-second smoke is
        noise; the ≤2% claim belongs to the committed full-recipe
        log."""
        details = {}
        bench._bench_cluster_obs_overhead(
            details, seconds=4.0, nodes=3, rate=120.0, repeats=1
        )
        co = details["cluster_obs_overhead"]
        for key in (
            "config",
            "nodes",
            "seconds",
            "rate",
            "repeats",
            "telemetry_off_ops_per_s",
            "telemetry_on_ops_per_s",
            "overhead_frac",
            "within_2pct",
            "polls",
            "samples",
            "node_events",
            "backend",
        ):
            assert key in co, f"cluster_obs_overhead schema lost {key!r}"
        assert co["nodes"] == 3
        assert co["telemetry_off_ops_per_s"] > 0
        assert co["telemetry_on_ops_per_s"] > 0
        assert co["overhead_frac"] == co["overhead_frac"]  # finite
        # the ON arm really sampled the cluster (no silent no-op)
        assert co["polls"] >= 2 and co["samples"] >= co["polls"]

    def test_report_section_schema(self, bench):
        """Offline gate for the ISSUE-11 ``report`` bench schema: a
        tiny REAL run of the windowed-stats kernel over packed ``.jtc``
        rows must carry the throughput keys, the ≤2% percentile
        differential (the PR-9 sketch bar — real even at smoke scale:
        it is a geometry bound, not noise), and proof that the report
        artifacts were actually emitted and XML-parsed."""
        details = {}
        bench._bench_report(
            details, histories=48, base_n=12, n_ops=60, chunk=16
        )
        r = details["report"]
        for key in (
            "histories",
            "n_ops",
            "windows",
            "buckets",
            "record_pack_s",
            "wall_s",
            "windowed_stats_histories_per_sec",
            "quantiles_checked",
            "max_quantile_rel_err",
            "within_2pct",
            "artifact_files",
            "artifact_xml_ok",
            "devices",
            "backend",
        ):
            assert key in r, f"report schema lost key {key!r}"
        assert r["histories"] == 48
        assert r["windowed_stats_histories_per_sec"] > 0
        assert r["quantiles_checked"] > 0
        assert r["within_2pct"] is True, r["max_quantile_rel_err"]
        assert r["artifact_xml_ok"] is True
        for name in ("report.html", "report.json", "timeline.html"):
            assert name in r["artifact_files"]

    def test_jtc_format_version_roundtrip(self, tmp_path):
        """Offline ``.jtc`` round trip under JAX_PLATFORMS=cpu: write →
        structural read → version-bump rejection (the stale-format-
        version corruption class)."""
        import numpy as np

        from jepsen_tpu.history.columnar import (
            ColumnarFormatError,
            VERSION,
            jtc_path_for,
            read_jtc,
            write_jtc,
        )

        src = tmp_path / "history.jsonl"
        src.write_text('{"type": "invoke", "f": "enqueue", "value": 1}\n')
        rows = np.arange(16, dtype=np.int32).reshape(2, 8)
        write_jtc(src, "queue", rows=rows)
        jtc, stamp = read_jtc(jtc_path_for(src))
        assert stamp["src_name"] == "history.jsonl"
        np.testing.assert_array_equal(jtc.rows(), rows)
        raw = bytearray(jtc_path_for(src).read_bytes())
        raw[4] = VERSION + 1
        jtc_path_for(src).write_bytes(raw)
        with pytest.raises(ColumnarFormatError, match="format version"):
            read_jtc(jtc_path_for(src))


class TestDistributedSpawnSmoke:
    """2-process spawn smoke of the distributed checker under
    JAX_PLATFORMS=cpu: the jax.distributed join, the deterministic
    stripe assignment, the per-process pipelines, and the KV-store
    verdict merge must all work without a chip — scale-out regressions
    fail the suite here."""

    def test_two_process_stream_check(self, tmp_path):
        from jepsen_tpu.history.store import write_history_jsonl
        from jepsen_tpu.history.synth import (
            StreamSynthSpec,
            synth_stream_batch,
        )
        from jepsen_tpu.parallel.distributed import run_multiprocess_check

        base = synth_stream_batch(4, StreamSynthSpec(n_ops=20, seed=2),
                                  lost=1)
        files = []
        for i, sh in enumerate(base):
            p = tmp_path / f"h{i}.jsonl"
            write_history_jsonl(p, sh.ops)
            files.append(p)
        results, info = run_multiprocess_check(
            "stream", files, 2, devices_per_proc=1, chunk=2,
            timeout_s=300,
        )
        assert info["n_procs"] == 2
        assert sum(p["checked"] for p in info["per_process"]) == 4
        assert len(results) == 4
        from jepsen_tpu.checkers.stream_lin import check_stream_lin_cpu

        for r, sh in zip(results, base):
            assert (
                r["stream"]["valid?"]
                == check_stream_lin_cpu(sh.ops)["valid?"]
            )
        assert any(r["stream"]["valid?"] is not True for r in results)


class TestChaosHarnessSmoke:
    """The checker-chaos harness (``tools/chaos_check.py``, ROADMAP
    direction 5(d)) must stay runnable offline: a 2-proc spawn with one
    deterministic mid-claim death (the die-env hook — CI must not bet
    on wall-clock kill timing) over a tiny corpus has to complete on
    the survivor and PASS every built-in assertion (verdicts ≡ serial
    oracle, provenance accuracy).  The full SIGKILL/SIGSTOP modes and
    the north-star-sized differential proof are committed capture runs
    (``store/chaos_r13_*``), not suite work."""

    def test_two_proc_kill_one_die_env_green(self, tmp_path):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "chaos_check_under_test",
            str(REPO / "tools" / "chaos_check.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(
            [
                "--procs", "2",
                "--kill", "1",
                "--mode", "die-env",
                "--histories", "8",
                "--base", "4",
                "--ops", "25",
                "--poison", "1",
                "--chunk", "4",
                "--timeout", "300",
                "--out", str(tmp_path / "chaos_smoke"),
            ]
        )
        assert rc == 0
        doc = json.loads(
            (tmp_path / "chaos_smoke" / "results.json").read_text()
        )
        assert doc["pass"] is True
        assert doc["degraded"]["dead_workers"]
        assert (tmp_path / "chaos_smoke" / "chaos_check.log").exists()


class TestSegmentedSectionSchema:
    """Offline gate for the ISSUE-15 ``segmented`` bench schema: a
    tiny REAL run (RSS-metered CPU subprocesses) must carry the
    bounded-memory keys, the verdict-equivalence flag, and pin the
    honesty rule that a NO-KILL run can never claim a resume."""

    @pytest.fixture()
    def bench(self):
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        import bench as bench_mod

        return bench_mod

    def test_segmented_section_schema(self, bench):
        details = {}
        bench._bench_segmented(
            details, n_ops=4000, segment_ops=512, small_ops=1200
        )
        sg = details["segmented"]
        for key in (
            "n_ops",
            "segment_ops",
            "segments",
            "seg_wall_s",
            "seg_peak_rss_mb",
            "seg_quarter_rss_mb",
            "rss_flat_ratio",
            "rss_bounded",  # THE bounded-memory key
            "segment_p50_ms",
            "segment_p99_ms",
            "resumed",
            "verdicts_match",
            "mono_small_rss_mb",
            "mono_refused_under_seg_budget",
            "backend",
        ):
            assert key in sg, f"segmented schema lost key {key!r}"
        assert sg["segments"] >= 2
        assert sg["seg_peak_rss_mb"] > 0
        assert sg["rss_flat_ratio"] == sg["rss_flat_ratio"]  # finite
        # the DIFFERENTIAL half: segmented == monolithic on the twin
        # both engines can run
        assert sg["verdicts_match"] is True
        # honesty rule: a no-kill run can NEVER claim a resume
        assert sg["resumed"] is False
        assert "resumed_from" not in sg


class TestSegmentedChaosSmoke:
    """The segmented kill/resume proof harness (``tools/chaos_check.py
    --segmented``) must stay runnable offline: the DETERMINISTIC
    die-after-segment hook (no wall-clock kill races in CI), tiny
    sizes, every built-in assertion green — uninterrupted oracle,
    mid-check death leaves a durable checkpoint, resume reaches the
    identical verdict, a torn checkpoint is refused and recovered.
    The real-SIGKILL run at scale is a committed capture
    (``store/chaos_r15_seg``), not suite work."""

    def test_die_env_resume_green(self, tmp_path):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "chaos_check_seg_under_test",
            str(REPO / "tools" / "chaos_check.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(
            [
                "--segmented",
                "--mode", "die-env",
                "--seg-ops", "250",
                "--seg-history-ops", "1500",
                "--out", str(tmp_path / "seg_chaos"),
            ]
        )
        assert rc == 0
        doc = json.loads(
            (tmp_path / "seg_chaos" / "results.json").read_text()
        )
        assert doc["pass"] is True
        assert doc["tool"] == "chaos_check --segmented"
        assert not doc["failures"]


class TestCampaignChaosSmoke:
    """The campaign-supervisor proof harness (``tools/chaos_check.py
    --campaign``, ISSUE 17) must stay runnable offline: the
    DETERMINISTIC die-after-trial hook (no wall-clock kill races in
    CI), in-process faults only (no serve-checker subprocess spawns —
    the service-restart arm belongs to the committed capture,
    ``store/campaign_r17``), every built-in assertion green —
    uninterrupted oracle campaign, mid-campaign death leaves a durable
    ledger, resume lands on the identical fingerprint set, verdict
    windows PUSHED, record→verdict p50/p99 measured."""

    def test_die_env_resume_green(self, tmp_path):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "chaos_check_campaign_under_test",
            str(REPO / "tools" / "chaos_check.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(
            [
                "--campaign",
                "--mode", "die-env",
                "--seed", "17",
                "--campaign-trials", "3",
                "--campaign-ops", "120",
                "--campaign-faults",
                "none,kill-worker,torn-subscription",
                "--timeout", "300",
                "--out", str(tmp_path / "camp_chaos"),
            ]
        )
        assert rc == 0
        doc = json.loads(
            (tmp_path / "camp_chaos" / "results.json").read_text()
        )
        assert doc["pass"] is True
        assert doc["tool"] == "chaos_check --campaign"
        assert not doc["failures"]
        camp = doc["campaign"]
        assert camp["oracle"]["windows_pushed"] >= 3
        assert camp["oracle"]["record_to_verdict_ms"]["p50"] is not None
        assert 0 < camp["journaled_at_kill"] < 3
        assert camp["resumed"]["resumed_from"] == camp["journaled_at_kill"]
        assert len(camp["fingerprints"]) == 3


class TestServeSectionSchema:
    """Offline gate for the ISSUE-16 ``serve`` bench schema: a tiny
    REAL in-process run of the streaming-service arms must carry the
    admission/latency keys, the honest-saturation accounting, and pin
    the honesty rule that a ZERO-KILL run can never claim recovery."""

    @pytest.fixture()
    def serve_bench(self):
        import importlib.util
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        spec = importlib.util.spec_from_file_location(
            "bench_serve_under_test",
            str(REPO / "tools" / "bench_serve.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @staticmethod
    def _ns(**over):
        import argparse as _ap

        base = dict(
            histories=400, base=8, ops=40, workers=2, seed=16,
            min_rate=0.0, cache_ops=600, cache_reps=40,
            chaos_streams=4, chaos_ops=600, chaos_blocks=6,
            kill_block=2, sat_submits=24, sat_block_delay=0.02,
            timeout=120.0, device=False,
        )
        base.update(over)
        return _ap.Namespace(**base)

    def test_throughput_schema(self, serve_bench):
        out = serve_bench.arm_throughput(self._ns(), lambda m: None)
        for key in (
            "histories",
            "ops_per_history",
            "workers",
            "admit_wall_s",
            "admitted_per_s",  # THE acceptance-floor key
            "wall_s",
            "completed_per_s",
            "submit_rejects_retried",
            "p50_ms",
            "p99_ms",
            "verdicts",
        ):
            assert key in out, f"serve throughput schema lost {key!r}"
        assert out["admitted_per_s"] > 0
        assert out["p99_ms"] >= out["p50_ms"]
        # no silent drops hiding behind the admission rate
        assert out["verdicts"] == out["histories"]

    def test_saturation_books_balance(self, serve_bench):
        failures = []

        def check(cond, msg):
            if not cond:
                failures.append(msg)

        out = serve_bench.arm_saturation(
            self._ns(), lambda m: None, check
        )
        for key in (
            "submitted",
            "accepted",
            "rejected_saturated",
            "verdicts",
            "quarantines",
            "gapped_carries",
            "silent_drops",
            "admission_rejects",
        ):
            assert key in out, f"serve saturation schema lost {key!r}"
        assert not failures, failures
        # honest saturation: loud rejects, exact books, no fabricated
        # gapped carries and no quarantines from mere overload
        assert out["rejected_saturated"] > 0
        assert out["silent_drops"] == 0
        assert out["gapped_carries"] == 0
        assert out["quarantines"] == 0
        assert (
            out["submitted"]
            == out["verdicts"] + out["rejected_saturated"]
        )

    def test_zero_kill_cannot_claim_recovery(self, serve_bench):
        failures = []

        def check(cond, msg):
            if not cond:
                failures.append(msg)

        out = serve_bench.arm_chaos(self._ns(), lambda m: None, check)
        assert not failures, failures
        zk = out["zero_kill"]
        # honesty rule: an unkilled run may never wear the recovery
        # story — no deaths, no degraded provenance, oracle-identical
        assert zk["worker_deaths"] == 0
        assert zk["claims_recovery"] is False
        assert zk["verdicts_match"] is True
        kill = out["kill"]
        assert kill["worker_deaths"] >= 1
        assert kill["oracle_mismatches"] == 0
        assert kill["degraded_streams"] >= 1


class TestServeBatchingSchema:
    """Offline gate for the ISSUE-20 ``serve_batching`` bench schema:
    a tiny REAL coalescing run under the CPU backend must carry the
    ON/OFF level schema, actually batch (ON's mean blocks-per-launch
    beats OFF's degenerate one-per-dispatch), hit the warmed bucket on
    first dispatch, and serve every verdict identical to the serial
    oracle.  Perf gates (≥2x, fill ≥ 0.8, p99 ≤ budget) arm only at
    the standalone evidence scale — never in a tiny CI run."""

    @pytest.fixture()
    def serve_bench(self):
        import importlib.util
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        spec = importlib.util.spec_from_file_location(
            "bench_serve_under_test",
            str(REPO / "tools" / "bench_serve.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @staticmethod
    def _ns(**over):
        import argparse as _ap

        base = dict(
            base=4, workers=2, seed=16, timeout=120.0,
            bat_streams=8, bat_blocks=12, bat_block_rows=64,
            target_batch=8, max_batch_wait_ms=25.0,
            bat_min_speedup=2.0, bat_probe_load=0.6,
            bat_gate_streams=10**9,
        )
        base.update(over)
        return _ap.Namespace(**base)

    def test_batching_schema_and_correctness(self, serve_bench):
        failures = []

        def check(cond, msg):
            if not cond:
                failures.append(msg)

        doc = serve_bench.run_batching(
            self._ns(), lambda m: None, check
        )
        assert not failures, failures
        for key in (
            "target_batch", "max_batch_wait_ms", "block_rows", "levels",
        ):
            assert key in doc, f"serve_batching schema lost {key!r}"
        assert [lv["streams"] for lv in doc["levels"]] == [1, 8]
        for lv in doc["levels"]:
            for arm in ("off", "on"):
                for key in (
                    "blocks", "wall_s", "blocks_per_s",
                    "oracle_mismatches", "quarantines",
                ):
                    assert key in lv[arm], (
                        f"serve_batching {arm} schema lost {key!r}"
                    )
                # the differential core: zero verdict divergence
                assert lv[arm]["oracle_mismatches"] == 0
            on = lv["on"]
            for key in (
                "launches", "batched_blocks", "salvages",
                "warmup_hits", "warmup_misses", "fill_fraction",
                "added_p50_ms", "added_p99_ms",
            ):
                assert key in on, f"serve_batching ON schema lost {key!r}"
            # every block went through the coalesced path, warmed
            assert on["batched_blocks"] == on["blocks"]
            assert on["salvages"] == 0
            assert on["warmup_hits"] >= 1
        # coalescing-ON fill beats OFF's degenerate one-block-per-
        # dispatch: mean entries per launch strictly above 1
        top = doc["levels"][-1]["on"]
        batch_w = 1
        while batch_w < 8:
            batch_w *= 2
        assert top["fill_fraction"] * batch_w > 1.0, (
            f"coalescing never actually batched: {top}"
        )


class TestServeChaosSmoke:
    """The streaming-service chaos harness (``tools/chaos_check.py
    --serve``) must stay runnable offline: deterministic die-hook
    (worker 0 dies mid-feed of its Nth block), tiny sizes, every
    built-in assertion green — zero-kill honesty row, surviving
    verdicts ≡ the serial oracle, degraded provenance names the dead
    worker, saturation books balance.  The at-scale capture is a
    committed artifact (``store/chaos_r16_serve``), not suite work."""

    def test_serve_chaos_green(self, tmp_path):
        import importlib.util

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip("offline CPU gate")
        spec = importlib.util.spec_from_file_location(
            "chaos_check_serve_under_test",
            str(REPO / "tools" / "chaos_check.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        rc = mod.main(
            [
                "--serve",
                "--procs", "2",
                "--histories", "4",
                "--serve-ops", "600",
                "--serve-kill-block", "2",
                "--out", str(tmp_path / "serve_chaos"),
            ]
        )
        assert rc == 0
        doc = json.loads(
            (tmp_path / "serve_chaos" / "results.json").read_text()
        )
        assert doc["pass"] is True
        assert doc["tool"] == "chaos_check --serve"
        assert not doc["failures"]


class TestFuzzMatrixSmoke:
    """Offline deterministic fuzzer smoke (sim harness, fixed seed,
    tiny budget): the run/triage/minimize plumbing must round-trip —
    a seeded-bug config is found, confirmed, shrunk to a nonempty
    minimal window, emitted as a repro driver whose schema gates here,
    and the emitted spec reproduces its red standalone."""

    @pytest.fixture(scope="class")
    def fuzz_run(self, tmp_path_factory):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "fuzz_matrix", REPO / "tools" / "fuzz_matrix.py"
        )
        fm = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fm)
        emit_dir = tmp_path_factory.mktemp("fuzz_emit")
        store = tmp_path_factory.mktemp("fuzz_store")
        rc = fm.main([
            "--seed", "3", "--budget", "2", "--db", "sim",
            "--workload", "queue", "--time-limit", "1.5",
            "--rate", "60", "--max-events", "2",
            "--sim-fault", "drop_acked_every=5",
            "--expect-red", "--stop-after-red",
            "--confirm", "1", "--attempts", "2",
            "--emit-dir", str(emit_dir), "--store", str(store),
            "--quiet-cluster",
        ])
        return rc, emit_dir

    def test_seeded_bug_found_and_minimized(self, fuzz_run):
        rc, emit_dir = fuzz_run
        assert rc == 0, "--expect-red exited non-zero: the seeded sim " \
            "fault went uncaught (fuzzer liveness broken)"
        repros = sorted(emit_dir.glob("fuzz_repro_*.py"))
        assert len(repros) == 1, repros

    def test_emitted_repro_schema_gates(self, fuzz_run):
        from jepsen_tpu.fuzz.emit import load_spec, validate_spec
        from jepsen_tpu.fuzz.space import SPEC_KEYS

        _rc, emit_dir = fuzz_run
        (path,) = sorted(emit_dir.glob("fuzz_repro_*.py"))
        spec = load_spec(str(path))
        assert set(SPEC_KEYS) <= set(spec), (
            sorted(set(SPEC_KEYS) - set(spec))
        )
        cfg = validate_spec(spec)  # round-trips into a config
        # the minimal failing window is nonempty and the sim fault that
        # caused the red rode along into the spec
        assert float(cfg.opts["time-limit"]) > 0.0
        assert cfg.sim_faults.get("drop_acked_every") == 5
        assert cfg.opts["nemesis-schedule"] == [
            [e.at_s, e.dur_s] for e in cfg.events
        ]
        # the driver is executable text that calls back into the repro
        # runtime (never a pickled blob)
        text = path.read_text()
        assert "jepsen_tpu.fuzz.repro" in text
        assert "SPEC = json.loads(" in text

    def test_emitted_spec_reproduces_red_and_green_twin(self, fuzz_run):
        from jepsen_tpu.fuzz.emit import load_spec
        from jepsen_tpu.fuzz.repro import green_twin_spec, run_spec

        _rc, emit_dir = fuzz_run
        (path,) = sorted(emit_dir.glob("fuzz_repro_*.py"))
        spec = load_spec(str(path))
        out = run_spec(spec, attempts=2)
        assert out.status == "red", (out.status, out.notes)
        twin = green_twin_spec(spec)
        assert twin["sim_faults"] == {}
        out2 = run_spec(twin, attempts=2)
        assert out2.status == "green", (out2.status, out2.notes)


class TestFleetMemorySectionSchema:
    """Offline gate for the ISSUE-19 ``fleet_memory`` bench schema: a
    tiny REAL shrink replay on CPU must carry the end-to-end speedup
    keys, the verdict-equivalence flag, the honest CAS dedup figures —
    and pin the honesty rule that a cache-cold probe row can never
    claim the >=5x bar (its ``speedup`` is None, always)."""

    @pytest.fixture()
    def bench(self):
        import sys as _sys

        import jax

        if jax.default_backend() != "cpu":
            pytest.skip(
                "the smoke gates the offline CPU path; chip windows "
                "measure through bench.py itself"
            )
        _sys.path.insert(0, str(REPO))
        import bench as bench_mod

        return bench_mod

    def test_fleet_memory_section_schema(self, bench):
        details = {}
        # sized so the FIRST bisection probe lands short of one full
        # segment (~no published anchor covers it): at least one row
        # must be cache-cold and prove the no-cold-claims rule on a
        # real run, not a mock
        bench._bench_fleet_memory(
            details, n_txns=150, segment_ops=256, seed=7
        )
        fm = details["fleet_memory"]
        for key in (
            "backend",
            "n_ops",
            "segment_ops",
            "min_red_ops",
            "probes",
            "resumed_probes",
            "wall_off_s",
            "wall_on_s",
            "speedup_e2e",  # THE fleet-memory headline
            "target_speedup",
            "speedup_met",
            "verdicts_identical",
            "rows",
            "dedup_ratio",
            "dedup_logical_bytes",
            "dedup_addressed_bytes",
            "regression_flagged",
        ):
            assert key in fm, f"fleet_memory schema lost key {key!r}"
        assert fm["backend"] == "cpu"
        assert fm["target_speedup"] == 5.0
        assert isinstance(fm["speedup_met"], bool)
        # the DIFFERENTIAL half: fleet memory may only be fast, never
        # change a single probe's verdict
        assert fm["verdicts_identical"] is True
        assert fm["probes"] == len(fm["rows"])
        # honesty rule: a cache-cold row carries NO speedup claim —
        # only resumed rows may put a number against the bar
        for row in fm["rows"]:
            if not row["resumed"]:
                assert row["speedup"] is None, row
            else:
                assert row["resume_offset"] > 0, row
        assert any(not r["resumed"] for r in fm["rows"]), (
            "gate needs at least one cold probe to pin the rule on"
        )
        # the regression-flag demo proved the machinery end to end
        assert fm["regression_flagged"] is True
        # NOT asserted: speedup_met — the tiny CI corpus is far below
        # the committed campaign's working set and must not pretend
        # to the 5x evidence (store/bench_pr19_cpu_fleet_memory.log)
