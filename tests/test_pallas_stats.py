"""Fused Pallas stats kernel ≡ scatter path (interpret mode on the CPU mesh)."""

import jax.numpy as jnp
import numpy as np
import pytest

from jepsen_tpu.checkers.fused import fused_tensor_check
from jepsen_tpu.checkers.queue_lin import queue_lin_tensor_check
from jepsen_tpu.checkers.total_queue import total_queue_tensor_check
from jepsen_tpu.history.encode import pack_histories
from jepsen_tpu.history.synth import SynthSpec, synth_batch
from jepsen_tpu.ops.pallas_stats import fused_queue_stats


def _packed(**overrides):
    shs = synth_batch(4, SynthSpec(n_ops=200), **overrides)
    return pack_histories([sh.ops for sh in shs])


def assert_tree_equal(x, y):
    for k in x.__dataclass_fields__:
        a, b = np.asarray(getattr(x, k)), np.asarray(getattr(y, k))
        np.testing.assert_array_equal(a, b, err_msg=k)


@pytest.mark.parametrize(
    "anomalies",
    [
        {},
        {"lost": 2},
        {"duplicated": 1},
        {"unexpected": 1},
        {"phantom_fail": 1},
        {"causality": 1},
    ],
)
def test_fused_equals_scatter_path(anomalies):
    packed = _packed(**anomalies)
    tq_f, ql_f = fused_tensor_check(packed, interpret=True)
    tq_s = total_queue_tensor_check(packed)
    ql_s = queue_lin_tensor_check(packed)
    assert_tree_equal(tq_f, tq_s)
    assert_tree_equal(ql_f, ql_s)


def test_fused_stats_shapes_and_padding():
    packed = _packed()
    st = fused_queue_stats(packed, interpret=True)
    V = packed.value_space
    assert st.a.shape == (packed.batch, V)
    # padded rows (mask=0) must contribute nothing: total attempts equal
    # the per-history live enqueue-invoke rows
    f = np.asarray(packed.f)
    t = np.asarray(packed.type)
    m = np.asarray(packed.mask)
    v = np.asarray(packed.value)
    want = ((f == 0) & (t == 0) & m & (v >= 0)).sum(axis=1)
    np.testing.assert_array_equal(np.asarray(st.a).sum(axis=1), want)


def test_fused_non_default_tile():
    # a small non-default L still packs into whole 128-row chunks
    shs = synth_batch(2, SynthSpec(n_ops=40))
    packed = pack_histories([sh.ops for sh in shs], length=128)
    tq_f, ql_f = fused_tensor_check(packed, interpret=True)
    assert_tree_equal(tq_f, total_queue_tensor_check(packed))
    assert_tree_equal(ql_f, queue_lin_tensor_check(packed))


def test_combined_single_program_equals_separate_checks():
    from jepsen_tpu.checkers.fused import combined_tensor_check

    packed = _packed(lost=1, duplicated=1, causality=1)
    tq_c, ql_c = combined_tensor_check(packed)
    assert_tree_equal(tq_c, total_queue_tensor_check(packed))
    assert_tree_equal(ql_c, queue_lin_tensor_check(packed))
