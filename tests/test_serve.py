"""Results web server: index, artifact access, traversal guard."""

import json
import urllib.request

import pytest

from jepsen_tpu.cli.serve import _index_page, start_background
from jepsen_tpu.history.store import Store
from jepsen_tpu.history.synth import SynthSpec, synth_history


@pytest.fixture()
def populated_store(tmp_path):
    st = Store(tmp_path / "store")
    sh = synth_history(SynthSpec(n_ops=40))
    d = st.run_dir("demo-test", "20260729T000000")
    st.save_history(d, sh.ops)
    st.save_results(d, {"valid?": True, "queue": {"ok-count": 3}})
    (d / "jepsen.log").write_text("Everything looks good!\n")
    bad = st.run_dir("demo-test", "20260729T000100")
    st.save_history(bad, sh.ops)
    st.save_results(bad, {"valid?": False})
    return st


@pytest.fixture()
def server(populated_store):
    srv, port = start_background(populated_store.root)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    srv.server_close()


def get(url):
    with urllib.request.urlopen(url) as r:
        return r.status, r.read().decode()


def test_index_lists_runs_with_verdicts(server):
    status, body = get(server + "/")
    assert status == 200
    assert "demo-test" in body
    assert "INVALID" in body  # the bad run
    assert ">valid<" in body  # the good run


def test_run_dir_listing_and_artifacts(server):
    status, body = get(server + "/files/demo-test/20260729T000000/")
    assert status == 200
    assert "history.jsonl" in body and "results.json" in body

    status, body = get(
        server + "/files/demo-test/20260729T000000/results.json"
    )
    assert status == 200
    assert json.loads(body)["valid?"] is True

    status, body = get(server + "/files/demo-test/20260729T000000/jepsen.log")
    assert "Everything looks good" in body


def test_traversal_guarded(server):
    req = urllib.request.Request(server + "/files/../../etc/passwd")
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(req)
    assert exc_info.value.code == 404


def test_unknown_path_404(server):
    with pytest.raises(urllib.error.HTTPError) as exc_info:
        urllib.request.urlopen(server + "/files/nope/nothing")
    assert exc_info.value.code == 404


def test_run_test_writes_jepsen_log(tmp_path):
    """run_test captures the framework log (with the verdict banner line
    the reference CI greps) into <run_dir>/jepsen.log."""
    from jepsen_tpu.control.runner import run_test
    from jepsen_tpu.suite import build_sim_test

    test, _cluster = build_sim_test(
        opts={
            "time-limit": 0.5,
            "time-before-partition": 0.1,
            "partition-duration": 0.1,
            "recovery-sleep": 0.1,
            "rate": 200.0,
        },
        checker_backend="cpu",
        store_root=str(tmp_path / "store"),
    )
    run = run_test(test)
    log = (run.run_dir / "jepsen.log").read_text()
    assert "analysis:" in log
    assert ("Everything looks good!" in log) or ("Analysis invalid!" in log)


def test_unknown_verdict_renders_as_unknown(tmp_path):
    """A tri-state "unknown" results.json must not render green."""
    run = tmp_path / "t" / "r1"
    run.mkdir(parents=True)
    (run / "results.json").write_text('{"valid?": "unknown"}')
    page = _index_page(tmp_path)
    assert 'class="unknown">unknown' in page
    assert 'class="valid"' not in page


def test_index_shows_live_monitor_column(tmp_path):
    import json

    from jepsen_tpu.cli.serve import _index_page

    d = tmp_path / "t" / "20260730T000000"
    d.mkdir(parents=True)
    (d / "results.json").write_text('{"valid?": true}')
    (d / "live.json").write_text(
        json.dumps({"monitor": "live-total-queue", "violation-so-far": True})
    )
    page = _index_page(tmp_path)
    assert "live monitor" in page and "flagged mid-run" in page
    (d / "live.json").write_text(
        json.dumps({"monitor": "live-total-queue", "violation-so-far": False})
    )
    assert "clean" in _index_page(tmp_path)
