"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

The environment pins ``JAX_PLATFORMS=axon`` (a single tunneled TPU chip) via
``sitecustomize``, which imports jax at interpreter start.  The backend is
not *initialized* until first use, so flipping ``jax_platforms`` to ``cpu``
and appending ``--xla_force_host_platform_device_count=8`` here — before any
test touches jax — gives every test the 8-device virtual CPU mesh that the
sharding tests (and the driver's ``dryrun_multichip``) expect.
"""

import os

import jax
import pytest

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert jax.default_backend() == "cpu" and len(devs) == 8, (
        "tests expect the 8-device virtual CPU mesh"
    )
    return devs


@pytest.fixture(scope="session")
def native_lib():
    """The C++ AMQP driver, loaded once and quieted — shared by every
    live local-cluster test file (the native-driver suites that also
    BUILD the library define their own richer fixture, which shadows
    this one)."""
    from jepsen_tpu.client import native

    native.load_library().amqp_set_logging(0)
    return native


@pytest.fixture()
def _reset(native_lib):
    """Fresh driver registry around each live test: the drain once-latch
    and client list are process-global in the native layer."""
    native_lib.reset(drain_wait_ms=100)
    yield
    native_lib.reset(drain_wait_ms=100)
