"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

The environment pins ``JAX_PLATFORMS=axon`` (a single tunneled TPU chip) via
``sitecustomize``, which imports jax at interpreter start.  The backend is
not *initialized* until first use, so flipping ``jax_platforms`` to ``cpu``
and appending ``--xla_force_host_platform_device_count=8`` here — before any
test touches jax — gives every test the 8-device virtual CPU mesh that the
sharding tests (and the driver's ``dryrun_multichip``) expect.
"""

import os

import jax
import pytest

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
jax.config.update("jax_platforms", "cpu")


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert jax.default_backend() == "cpu" and len(devs) == 8, (
        "tests expect the 8-device virtual CPU mesh"
    )
    return devs
