"""Continuous batching for the verification service (ISSUE 20): the
cross-stream coalescing scheduler differential against the per-stream
serial oracle — verdicts identical on every stream, carry isolation
preserved (batching crosses streams only on the history axis), a
stream dying mid-coalesce quarantined with evidence while its
batch-mates are untouched, parked segments bounded and evicted loudly,
warmup AOT counted honestly, and the verdict cache's ``report_ref``
surviving re-puts (the ``GET /report/<run>`` satellite)."""

import json
import time

import numpy as np
import pytest

from jepsen_tpu.checkers.segmented import (
    SegmentedChecker,
    queue_prepare_rows,
)
from jepsen_tpu.history.columnar import iter_row_blocks
from jepsen_tpu.history.rows import _rows_for
from jepsen_tpu.history.synth import SynthSpec, synth_history
from jepsen_tpu.obs.metrics import Registry
from jepsen_tpu.service.cache import VerdictCache
from jepsen_tpu.service.stream import IngestService, _wire_safe


def _history(n_ops=400, seed=3, **anoms):
    sh = synth_history(SynthSpec(n_ops=n_ops, seed=seed, **anoms))
    return _rows_for(sh.ops), len(sh.ops)


def _oracle(rows, n_ops):
    eng = SegmentedChecker("queue", device=False)
    eng.feed_rows(rows, n_ops)
    return eng.finish()


def _families_equal(served, oracle):
    o = _wire_safe(oracle)
    keys = set(o) - {"segmented"}
    s = _wire_safe({k: served.get(k) for k in keys})
    return s == {k: o[k] for k in keys}


def _svc(**kw):
    kw.setdefault("workers", 2)
    kw.setdefault("device", False)
    kw.setdefault("registry", Registry())
    kw.setdefault("batch", True)
    kw.setdefault("target_batch", 8)
    kw.setdefault("max_batch_wait_ms", 25.0)
    return IngestService(**kw)


def _open_stream(svc, deadline_s=60.0):
    r = svc.open("queue", None, kind="stream", deadline_s=deadline_s)
    assert r["op"] == "opened", r
    return r["stream"]


def _feed_interleaved(svc, streams, block_rows=96):
    """Round-robin blocks across streams so the coalescer genuinely
    sees cross-stream material in every bucket."""
    plans = []
    for sid, (rows, n_ops) in streams:
        plans.append((sid, list(iter_row_blocks(rows, block_rows)), [0]))
    fed = True
    while fed:
        fed = False
        for sid, blocks, cur in plans:
            if cur[0] >= len(blocks):
                continue
            blk, b_ops = blocks[cur[0]]
            rep = svc.feed(sid, cur[0], "rows", blk, b_ops)
            assert rep["op"] == "accepted", rep
            cur[0] += 1
            fed = True


class TestCoalescedDifferential:
    def test_cross_stream_batching_equals_serial_oracle(self):
        """The core differential: six concurrent streams with varied
        sizes and anomalies, fed round-robin through the coalescer —
        every verdict must be identical to that stream's serial
        oracle, with real batching (fewer launches than blocks)."""
        corpus = [
            _history(n_ops=160 + 40 * i, seed=i,
                     lost=i % 2, duplicated=(i + 1) % 2)
            for i in range(6)
        ]
        reg = Registry()
        svc = _svc(registry=reg)
        try:
            streams = [(_open_stream(svc), hv) for hv in corpus]
            _feed_interleaved(svc, streams)
            verdicts = [
                (svc.finish(sid, timeout=30), rows, n_ops)
                for sid, (rows, n_ops) in streams
            ]
            stats = svc.stats()
        finally:
            svc.close()
        for v, rows, n_ops in verdicts:
            assert _families_equal(v, _oracle(rows, n_ops)), v
            assert "degraded" not in v
        bat = stats["batcher"]
        assert bat["batched_blocks"] > 0
        assert bat["salvages"] == 0
        # coalescing happened: strictly fewer launches than blocks
        assert 0 < bat["launches"] < bat["batched_blocks"]

    def test_mixed_bucket_stream_merges_in_seq_order(self):
        """One stream whose blocks alternate between two shape buckets
        (single vs concatenated-pair blocks): super-batches land out
        of order across buckets, and the per-stream reorder buffer
        must still merge in seq order — the carry is NOT
        order-independent, so any reordering shows up as a verdict
        diff against the oracle."""
        rows, n_ops = _history(n_ops=900, seed=11, lost=2, duplicated=2)
        small = list(iter_row_blocks(rows, 64))
        blocks, i = [], 0
        while i < len(small):
            if i % 3 == 2 or i + 1 >= len(small):
                blocks.append(small[i])
                i += 1
            else:  # a double-width block: a different (L, V) bucket
                (b1, n1), (b2, n2) = small[i], small[i + 1]
                blocks.append((np.concatenate([b1, b2]), n1 + n2))
                i += 2
        svc = _svc(target_batch=4, max_batch_wait_ms=10.0)
        try:
            sid = _open_stream(svc)
            for seq, (blk, b_ops) in enumerate(blocks):
                rep = svc.feed(sid, seq, "rows", blk, b_ops)
                assert rep["op"] == "accepted", rep
            v = svc.finish(sid, timeout=30)
        finally:
            svc.close()
        assert _families_equal(v, _oracle(rows, n_ops)), v

    def test_ops_json_blocks_interleave_with_coalesced_rows(self):
        """Ops-JSON blocks on a queue stream can't join a rows
        super-batch; they ride the pass-through bucket and must still
        merge at their seq turn, between coalesced rows blocks."""
        sh = synth_history(SynthSpec(n_ops=240, seed=7, lost=1))
        rows = _rows_for(sh.ops)
        n_ops = len(sh.ops)
        row_blocks = list(iter_row_blocks(rows, 96))
        mid = len(sh.ops) // 2
        svc = _svc(target_batch=4)
        try:
            # stream A: rows / ops-json / rows interleaved by seq
            sid = _open_stream(svc)
            ops_payload = [op.to_json() for op in sh.ops[:mid]]
            rest = _rows_for(sh.ops[mid:])
            rep = svc.feed(sid, 0, "ops", ops_payload, mid)
            assert rep["op"] == "accepted", rep
            rep = svc.feed(sid, 1, "rows", rest, n_ops - mid)
            assert rep["op"] == "accepted", rep
            # stream B: plain rows, the coalescing batch-mate
            sid_b = _open_stream(svc)
            for seq, (blk, b_ops) in enumerate(row_blocks):
                svc.feed(sid_b, seq, "rows", blk, b_ops)
            v = svc.finish(sid, timeout=30)
            v_b = svc.finish(sid_b, timeout=30)
        finally:
            svc.close()
        oracle = _oracle(rows, n_ops)
        assert _families_equal(v, oracle), v
        assert _families_equal(v_b, oracle), v_b


class TestMidCoalesceDeath:
    def test_abort_mid_coalesce_leaves_batch_mates_unaffected(self):
        """A stream aborted while its segments sit parked in the
        coalescing queue: its entries are evicted (counted on
        ``service.batcher_evictions``), accounting is released, and
        the surviving batch-mates' verdicts are oracle-identical."""
        corpus = [_history(n_ops=200, seed=20 + i) for i in range(3)]
        reg = Registry()
        # a target far above supply + a long budget: everything parks
        svc = _svc(registry=reg, target_batch=64,
                   max_batch_wait_ms=30_000.0, park_max_s=60.0)
        try:
            streams = [(_open_stream(svc), hv) for hv in corpus]
            _feed_interleaved(svc, streams, block_rows=96)
            victim = streams[1][0]
            assert svc.abort(victim)["op"] == "aborted"
            evicted = reg.value(
                "service.batcher_evictions", reason="aborted"
            )
            survivors = [
                (svc.finish(sid, timeout=30), rows, n_ops)
                for sid, (rows, n_ops) in streams
                if sid != victim
            ]
        finally:
            svc.close()
        assert evicted > 0, "parked entries of the aborted stream " \
            "were not evicted"
        for v, rows, n_ops in survivors:
            assert _families_equal(v, _oracle(rows, n_ops)), v

    def test_gap_quarantine_mid_coalesce_keeps_evidence(self):
        """A sequence gap quarantines the stream while earlier blocks
        are still parked: the verdict is unknown WITH the gap as
        evidence, the parked entries are evicted, and the batch-mate
        stream is untouched."""
        rows, n_ops = _history(n_ops=300, seed=31)
        mate_rows, mate_ops = _history(n_ops=300, seed=32, lost=1)
        reg = Registry()
        svc = _svc(registry=reg, target_batch=64,
                   max_batch_wait_ms=30_000.0, park_max_s=60.0)
        try:
            sid = _open_stream(svc)
            mate = _open_stream(svc)
            blocks = list(iter_row_blocks(rows, 96))
            for seq, (blk, b_ops) in enumerate(
                iter_row_blocks(mate_rows, 96)
            ):
                svc.feed(mate, seq, "rows", blk, b_ops)
            svc.feed(sid, 0, "rows", *blocks[0])
            rep = svc.feed(sid, 2, "rows", *blocks[2])  # hole at seq 1
            assert rep["op"] == "quarantined"
            v = svc.finish(sid, timeout=30)
            v_mate = svc.finish(mate, timeout=30)
            evicted = reg.value(
                "service.batcher_evictions", reason="quarantined"
            )
        finally:
            svc.close()
        assert v["valid?"] == "unknown"
        assert "gap in block sequence" in json.dumps(v)
        assert evicted > 0
        assert _families_equal(v_mate, _oracle(mate_rows, mate_ops))


class TestParkingBounds:
    def test_park_age_bound_dispatches_undersized_bucket(self):
        """The stranded-segment backstop (ISSUE 20 satellite): a
        bucket that never reaches target and whose deadline is far
        away still dispatches once its oldest entry exceeds
        ``park_max_s`` — no finish() required, nothing parked
        forever."""
        rows, n_ops = _history(n_ops=160, seed=40)
        svc = _svc(target_batch=64, max_batch_wait_ms=600_000.0,
                   park_max_s=0.3)
        try:
            sid = _open_stream(svc)
            for seq, (blk, b_ops) in enumerate(iter_row_blocks(rows, 96)):
                svc.feed(sid, seq, "rows", blk, b_ops)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                stats = svc.stats()
                bat = stats["batcher"]
                if bat["parked"] == 0 and bat["launches"] >= 1:
                    break
                time.sleep(0.05)
            v = svc.finish(sid, timeout=30)
        finally:
            svc.close()
        assert bat["parked"] == 0 and bat["launches"] >= 1, bat
        assert _families_equal(v, _oracle(rows, n_ops))

    def test_parked_entries_count_against_admission(self):
        """Backpressure composes: blocks parked in the coalescing
        queue stay counted in the ingress bound, so a full coalescing
        queue rejects new feeds loudly (SATURATED) instead of
        buffering without bound — and finish() drains the parked
        entries so the stream still completes."""
        rows, n_ops = _history(n_ops=400, seed=41)
        svc = _svc(ingress_cap=4, target_batch=64,
                   max_batch_wait_ms=30_000.0, park_max_s=60.0)
        try:
            sid = _open_stream(svc)
            blocks = list(iter_row_blocks(rows, 64))
            assert len(blocks) > 5
            rejected = None
            fed = 0
            for seq, (blk, b_ops) in enumerate(blocks):
                rep = svc.feed(sid, seq, "rows", blk, b_ops)
                if rep["op"] == "rejected":
                    rejected = rep
                    break
                fed += 1
            assert rejected is not None, (
                "parked blocks never saturated the ingress bound"
            )
            assert rejected["reason"]  # loud, named reject
            # the finish-drain: parked entries dispatch immediately
            v = svc.finish(sid, timeout=30)
        finally:
            svc.close()
        oracle_rows = np.concatenate([b for b, _n in blocks[:fed]])
        oracle_ops = sum(n for _b, n in blocks[:fed])
        assert _families_equal(v, _oracle(oracle_rows, oracle_ops)), v


class TestWarmup:
    def test_warmup_hit_and_cold_miss_counters(self):
        rows, n_ops = _history(n_ops=200, seed=50)
        blk, b_ops = next(iter_row_blocks(rows, 96))
        prep = queue_prepare_rows(blk, blk[:, 0].astype(np.int64))
        bucket = (int(prep["L"]), int(prep["V"]))

        def run(**kw):
            reg = Registry()
            svc = _svc(registry=reg, target_batch=4, **kw)
            try:
                sid = _open_stream(svc)
                for seq, b in enumerate(iter_row_blocks(rows, 96)):
                    svc.feed(sid, seq, "rows", *b)
                v = svc.finish(sid, timeout=30)
                stats = svc.stats()
            finally:
                svc.close()
            assert _families_equal(v, _oracle(rows, n_ops))
            return stats["batcher"]

        warm = run(warmup=True, warmup_buckets=(bucket,))
        assert warm["warmup_hits"] >= 1
        assert warm["warmup_misses"] == 0
        assert bucket in [tuple(b) for b in warm["warmed_buckets"]]
        cold = run(warmup=False)
        assert cold["warmup_hits"] == 0
        assert cold["warmup_misses"] >= 1


class TestReportRefSurvival:
    def test_reput_without_ref_preserves_recorded_run(self):
        """The ``GET /report/<run>`` satellite: a live-stream
        re-verification of a seeded history re-puts the verdict
        without a ``report_ref`` — the recorded-run pointer must
        survive, or cache hits lose their report route."""
        cache = VerdictCache(capacity=8, registry=Registry())
        cache.put("k1", {"valid?": True}, report_ref="runs/r0001")
        cache.put("k1", {"valid?": True})  # live re-verification
        got = cache.get("k1")
        assert got["report_ref"] == "runs/r0001"
        # an explicit new ref still wins
        cache.put("k1", {"valid?": True}, report_ref="runs/r0002")
        assert cache.get("k1")["report_ref"] == "runs/r0002"
        # and a fresh key without any ref stays ref-less
        cache.put("k2", {"valid?": True})
        assert "report_ref" not in cache.get("k2")
