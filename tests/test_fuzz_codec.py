"""Differential fuzz of the AMQP codec, rabbitmq-c as the oracle.

The reference trusts a battle-tested client library for its wire layer
(``com.rabbitmq:amqp-client 5.34.0``, ``project.clj:12``); the in-tree
C++ codec (``native/amqp_wire.hpp``) earns equivalent trust by fuzzing:
random header tables — every field kind in RabbitMQ's grammar, nested
tables/arrays, boundary-length strings — flow through the mini broker
(which replays publisher properties verbatim) in three directions:

- ours → ours, with the broker's TCP writes fragmented into 1–5-byte
  chunks (frame reassembly under arbitrarily split reads);
- rabbitmq-c encodes → our decoder must skip every fuzzed field to find
  the planted ``x-stream-offset``;
- our encoder → rabbitmq-c decodes the whole table (a table it cannot
  parse, or a wrong planted value, is our encoder's bug).

``FUZZ_N`` scales the case count (default 250 per direction here;
``make -C native fuzz`` runs 1000).
"""

import ctypes
import os
import subprocess
from pathlib import Path

import pytest

from jepsen_tpu.harness.broker import MiniAmqpBroker

NATIVE = Path(__file__).resolve().parent.parent / "native"
FUZZ_N = int(os.environ.get("FUZZ_N", "250"))


@pytest.fixture(scope="module")
def lib():
    r = subprocess.run(
        ["make", "-C", str(NATIVE)], capture_output=True, text=True
    )
    if r.returncode != 0:
        pytest.skip(f"native build failed:\n{r.stderr}")
    from jepsen_tpu.client.native import load_library

    lib = load_library()
    lib.amqp_set_logging(0)
    lib.amqp_fuzz_publish_tables.restype = ctypes.c_longlong
    lib.amqp_fuzz_publish_tables.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int,
    ]
    lib.amqp_fuzz_consume_offsets.restype = ctypes.c_long
    lib.amqp_fuzz_consume_offsets.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
    ]
    return lib


@pytest.fixture(scope="module")
def probe():
    r = subprocess.run(
        ["make", "-C", str(NATIVE), "interop_probe"],
        capture_output=True, text=True,
    )
    if r.returncode != 0:
        pytest.skip(f"probe build failed:\n{r.stderr}")
    return NATIVE / "interop_probe"


def _consume_ours(lib, port, n, timeout_ms=5000):
    offs = (ctypes.c_longlong * n)()
    bodies = (ctypes.c_int * n)()
    got = lib.amqp_fuzz_consume_offsets(
        b"127.0.0.1", port, b"fuzz.queue", n, offs, bodies, timeout_ms
    )
    assert got == n, f"consumed {got}/{n}"
    return [(int(offs[i]), int(bodies[i])) for i in range(n)]


def test_ours_to_ours_fragmented(lib):
    """Our encoder → fragmented broker replay → our decoder: every
    planted offset found behind the random fields, every body intact,
    under 1–5-byte TCP chunks."""
    b = MiniAmqpBroker(fragment_max=5).start()
    try:
        seed, base = 42, 7_000_000
        rc = lib.amqp_fuzz_publish_tables(
            b"127.0.0.1", b.port, b"fuzz.queue", seed, base, FUZZ_N
        )
        assert rc == FUZZ_N, f"publish failed at case {-rc - 1}"
        pairs = _consume_ours(lib, b.port, FUZZ_N)
        assert pairs == [(base + i, i) for i in range(FUZZ_N)]
    finally:
        b.stop()


def test_duplicate_injection_preserves_props(lib):
    """The at-least-once duplicate fault re-delivers the SAME message:
    the duplicated copy must carry the original's properties (a dup with
    stripped headers would be a harness artifact, not broker behavior)."""
    b = MiniAmqpBroker(duplicate_every=2).start()
    try:
        base, n = 5_000_000, 4
        rc = lib.amqp_fuzz_publish_tables(
            b"127.0.0.1", b.port, b"fuzz.queue", 3, base, n
        )
        assert rc == n
        offs = (ctypes.c_longlong * 8)()
        bodies = (ctypes.c_int * 8)()
        got = lib.amqp_fuzz_consume_offsets(
            b"127.0.0.1", b.port, b"fuzz.queue", 8, offs, bodies, 2000
        )
        assert got > n  # at least one duplicate was injected
        for i in range(got):
            assert offs[i] == base + bodies[i], (offs[i], bodies[i])
    finally:
        b.stop()


def test_rabbitmq_c_encodes_ours_decodes(lib, probe):
    """librabbitmq builds the tables (oracle encoder); our codec must
    skip every field kind it chose to reach the planted offset."""
    b = MiniAmqpBroker().start()
    try:
        seed, base = 99, 9_000_000
        r = subprocess.run(
            [str(probe), "127.0.0.1", str(b.port), "fuzzpub",
             str(FUZZ_N), str(seed), str(base)],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert f"FUZZPUB OK {FUZZ_N}" in r.stdout
        pairs = _consume_ours(lib, b.port, FUZZ_N)
        assert pairs == [(base + i, i) for i in range(FUZZ_N)]
    finally:
        b.stop()


def test_ours_encodes_rabbitmq_c_decodes(lib, probe):
    """Our encoder's output parsed by librabbitmq (oracle decoder): a
    table it cannot parse — or a wrong planted value — fails the probe."""
    b = MiniAmqpBroker().start()
    try:
        seed, base = 7, 3_000_000
        rc = lib.amqp_fuzz_publish_tables(
            b"127.0.0.1", b.port, b"fuzz.queue", seed, base, FUZZ_N
        )
        assert rc == FUZZ_N, f"publish failed at case {-rc - 1}"
        r = subprocess.run(
            [str(probe), "127.0.0.1", str(b.port), "fuzzget",
             str(FUZZ_N), str(base)],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr + r.stdout
        assert f"FUZZGET OK {FUZZ_N}" in r.stdout
    finally:
        b.stop()


def test_fragmented_broker_survives_standard_probe(probe):
    """The full rabbitmq-c conformance pass still holds when every broker
    write is split into 1–3-byte TCP chunks."""
    b = MiniAmqpBroker(fragment_max=3).start()
    try:
        r = subprocess.run(
            [str(probe), "127.0.0.1", str(b.port), "tx", "stream"],
            capture_output=True, text=True, timeout=120,
        )
        assert r.returncode == 0, r.stderr
        assert "PROBE OK" in r.stdout
    finally:
        b.stop()
