"""Per-value queue linearizability: anomaly detection + CPU≡TPU."""

import pytest

from jepsen_tpu.checkers.queue_lin import (
    check_queue_lin_batch,
    check_queue_lin_cpu,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import SynthSpec, synth_history


def both(history):
    cpu = check_queue_lin_cpu(history)
    tpu = check_queue_lin_batch([history])[0]
    assert cpu == tpu, f"cpu/tpu divergence:\n{cpu}\n{tpu}"
    return cpu


def test_clean_history_linearizable():
    sh = synth_history(SynthSpec(n_ops=300, seed=11))
    assert both(sh.ops)["valid?"]


def test_lost_values_still_linearizable():
    # loss is total-queue's concern; the value just never came out
    sh = synth_history(SynthSpec(n_ops=300, seed=12, lost=2))
    assert both(sh.ops)["valid?"]


def test_duplicate_delivery_not_linearizable():
    sh = synth_history(SynthSpec(n_ops=300, seed=13, duplicated=2))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["duplicate"] == sh.duplicated


def test_phantom_from_nowhere():
    sh = synth_history(SynthSpec(n_ops=300, seed=14, unexpected=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.unexpected <= r["phantom"]


def test_phantom_from_failed_enqueue():
    sh = synth_history(SynthSpec(n_ops=300, seed=15, phantom_fail=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.phantom_fail <= r["phantom"]


def test_causality_violation():
    sh = synth_history(SynthSpec(n_ops=200, seed=16, causality=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["causality"] == sh.causality


def test_indeterminate_enqueue_read_is_linearizable():
    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 3, time=0),
            Op(OpType.INFO, OpF.ENQUEUE, 0, 3, time=1_000_000, error="timeout"),
            Op.invoke(OpF.DEQUEUE, 1, time=5_000_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 3, time=6_000_000),
        ]
    )
    assert both(ops)["valid?"]


def test_overlapping_enqueue_dequeue_is_linearizable():
    # dequeue completes after enqueue *starts* but before it completes:
    # points p_enq < p_deq exist inside both intervals
    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 5, time=0),
            Op.invoke(OpF.DEQUEUE, 1, time=1_000_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 5, time=2_000_000),
            Op(OpType.OK, OpF.ENQUEUE, 0, 5, time=3_000_000),
        ]
    )
    assert both(ops)["valid?"]


@pytest.mark.parametrize("seed", range(4))
def test_differential_random(seed):
    sh = synth_history(
        SynthSpec(
            n_ops=400,
            seed=200 + seed,
            duplicated=seed % 2,
            unexpected=(seed + 1) % 2,
        )
    )
    r = both(sh.ops)
    assert r["valid?"] == (not sh.duplicated and not sh.unexpected)


def test_sub_ms_causality_detected():
    # read completes 300us before the enqueue is invoked: both land in the
    # same millisecond, so ordering must come from history order, not
    # truncated timestamps
    ops = reindex(
        [
            Op.invoke(OpF.DEQUEUE, 1, time=100_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 0, time=200_000),
            Op.invoke(OpF.ENQUEUE, 0, 0, time=500_000),
            Op(OpType.OK, OpF.ENQUEUE, 0, 0, time=600_000),
        ]
    )
    r = both(ops)
    assert not r["valid?"]
    assert r["causality"] == {0}
