"""Per-value queue linearizability: anomaly detection + CPU≡TPU."""

import pytest

from jepsen_tpu.checkers.queue_lin import (
    check_queue_lin_batch,
    check_queue_lin_cpu,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import SynthSpec, synth_history


def both(history):
    cpu = check_queue_lin_cpu(history)
    tpu = check_queue_lin_batch([history])[0]
    assert cpu == tpu, f"cpu/tpu divergence:\n{cpu}\n{tpu}"
    return cpu


def test_clean_history_linearizable():
    sh = synth_history(SynthSpec(n_ops=300, seed=11))
    assert both(sh.ops)["valid?"]


def test_lost_values_still_linearizable():
    # loss is total-queue's concern; the value just never came out
    sh = synth_history(SynthSpec(n_ops=300, seed=12, lost=2))
    assert both(sh.ops)["valid?"]


def test_duplicate_delivery_not_linearizable():
    sh = synth_history(SynthSpec(n_ops=300, seed=13, duplicated=2))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["duplicate"] == sh.duplicated


def test_phantom_from_nowhere():
    sh = synth_history(SynthSpec(n_ops=300, seed=14, unexpected=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.unexpected <= r["phantom"]


def test_phantom_from_failed_enqueue():
    sh = synth_history(SynthSpec(n_ops=300, seed=15, phantom_fail=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.phantom_fail <= r["phantom"]


def test_failed_enqueue_read_is_recovered_under_at_least_once():
    """Regression (round-4 live matrix, config pause-random-node +
    dead-letter): an enqueue completed ``fail`` with a connection error
    — the publish had committed broker-side before the connection died —
    and the value drained normally.  Under the live at-least-once
    contract this is jepsen total-queue's ``recovered`` bucket (the
    reference's driver maps connection errors to ``:fail`` identically,
    ``rabbitmq.clj:210-213``), NOT a phantom; flagging it failed a valid
    run.  Under exactly-once (sim: in-process ``fail`` is authoritative)
    it stays a phantom."""
    sh = synth_history(SynthSpec(n_ops=300, seed=15, phantom_fail=1))

    cpu = check_queue_lin_cpu(sh.ops, delivery="at-least-once")
    tpu = check_queue_lin_batch([sh.ops], delivery="at-least-once")[0]
    assert cpu == tpu, f"cpu/tpu divergence:\n{cpu}\n{tpu}"
    assert cpu["valid?"]
    assert cpu["phantom-count"] == 0
    assert sh.phantom_fail <= cpu["recovered"]

    # the strict contract still invalidates the same history
    strict = check_queue_lin_cpu(sh.ops, delivery="exactly-once")
    assert not strict["valid?"]
    assert strict["recovered-count"] == 0


def test_never_attempted_read_is_phantom_under_both_contracts():
    sh = synth_history(SynthSpec(n_ops=300, seed=14, unexpected=1))
    for delivery in ("exactly-once", "at-least-once"):
        cpu = check_queue_lin_cpu(sh.ops, delivery=delivery)
        tpu = check_queue_lin_batch([sh.ops], delivery=delivery)[0]
        assert cpu == tpu
        assert not cpu["valid?"]
        assert cpu["phantom-count"] >= 1


def test_fail_read_before_any_attempt_is_causal_under_at_least_once():
    # a recovered candidate whose read COMPLETED before any attempt was
    # even invoked came from nowhere — still invalid under at-least-once
    ops = reindex(
        [
            Op(OpType.INVOKE, OpF.DEQUEUE, 1, None, 100),
            Op(OpType.OK, OpF.DEQUEUE, 1, 7, 200),  # reads 7 first
            Op(OpType.INVOKE, OpF.ENQUEUE, 0, 7, 300),
            Op(OpType.FAIL, OpF.ENQUEUE, 0, 7, 400),
        ]
    )
    cpu = check_queue_lin_cpu(ops, delivery="at-least-once")
    tpu = check_queue_lin_batch([ops], delivery="at-least-once")[0]
    assert cpu == tpu
    assert not cpu["valid?"]
    assert 7 in cpu["causality"]
    assert cpu["recovered-count"] == 0


def test_causality_violation():
    sh = synth_history(SynthSpec(n_ops=200, seed=16, causality=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["causality"] == sh.causality


def test_indeterminate_enqueue_read_is_linearizable():
    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 3, time=0),
            Op(OpType.INFO, OpF.ENQUEUE, 0, 3, time=1_000_000, error="timeout"),
            Op.invoke(OpF.DEQUEUE, 1, time=5_000_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 3, time=6_000_000),
        ]
    )
    assert both(ops)["valid?"]


def test_overlapping_enqueue_dequeue_is_linearizable():
    # dequeue completes after enqueue *starts* but before it completes:
    # points p_enq < p_deq exist inside both intervals
    ops = reindex(
        [
            Op.invoke(OpF.ENQUEUE, 0, 5, time=0),
            Op.invoke(OpF.DEQUEUE, 1, time=1_000_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 5, time=2_000_000),
            Op(OpType.OK, OpF.ENQUEUE, 0, 5, time=3_000_000),
        ]
    )
    assert both(ops)["valid?"]


@pytest.mark.parametrize("seed", range(4))
def test_differential_random(seed):
    sh = synth_history(
        SynthSpec(
            n_ops=400,
            seed=200 + seed,
            duplicated=seed % 2,
            unexpected=(seed + 1) % 2,
        )
    )
    r = both(sh.ops)
    assert r["valid?"] == (not sh.duplicated and not sh.unexpected)


def test_sub_ms_causality_detected():
    # read completes 300us before the enqueue is invoked: both land in the
    # same millisecond, so ordering must come from history order, not
    # truncated timestamps
    ops = reindex(
        [
            Op.invoke(OpF.DEQUEUE, 1, time=100_000),
            Op(OpType.OK, OpF.DEQUEUE, 1, 0, time=200_000),
            Op.invoke(OpF.ENQUEUE, 0, 0, time=500_000),
            Op(OpType.OK, OpF.ENQUEUE, 0, 0, time=600_000),
        ]
    )
    r = both(ops)
    assert not r["valid?"]
    assert r["causality"] == {0}
