"""Elle list-append serializability: anomaly detection + CPU≡TPU.

BASELINE.json config #5.  The CPU reference (Tarjan SCC) and the TPU
backend (MXU transitive closure) must report identical result maps on
every history; fabricated anomalies must be detected exactly.
"""

from jepsen_tpu.checkers.elle import (
    APPEND,
    READ,
    check_elle_batch,
    check_elle_cpu,
    infer_txn_graph,
)
from jepsen_tpu.history.ops import Op, OpF, OpType, reindex
from jepsen_tpu.history.synth import (
    ElleSynthSpec,
    synth_elle_batch,
    synth_elle_history,
)


def both(history):
    cpu = check_elle_cpu(history)
    tpu = check_elle_batch([history])[0]
    assert cpu == tpu, f"cpu/tpu divergence:\n{cpu}\n{tpu}"
    return cpu


def txn(p, mops, typ=OpType.OK):
    return [
        Op.invoke(OpF.TXN, p, mops),
        Op(typ, OpF.TXN, p, mops),
    ]


def test_clean_serial_history_serializable():
    sh = synth_elle_history(ElleSynthSpec(n_txns=200, seed=41))
    assert sh.clean
    r = both(sh.ops)
    assert r["valid?"], r
    assert r["txn-count"] > 150


def test_g1a_aborted_read():
    sh = synth_elle_history(ElleSynthSpec(n_txns=100, seed=42, g1a=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["G1a"] == sh.g1a


def test_g1b_intermediate_read():
    sh = synth_elle_history(ElleSynthSpec(n_txns=100, seed=43, g1b=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["G1b"] == sh.g1b


def test_g0_write_cycle():
    sh = synth_elle_history(ElleSynthSpec(n_txns=100, seed=44, g0_cycle=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["G0"] == sh.g0
    # Adya classes are disjoint: a pure ww cycle is G0 only, not also
    # reported as the weaker G1c/G2
    assert r["G1c"] == set() and r["G2"] == set()


def test_g1c_information_cycle():
    sh = synth_elle_history(ElleSynthSpec(n_txns=100, seed=45, g1c_cycle=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["G0"] == set()  # no pure write cycle
    assert r["G1c"] == sh.g1c
    assert r["G2"] == set()  # the wr cycle is G1c, not also G2


def test_g2_write_skew():
    sh = synth_elle_history(ElleSynthSpec(n_txns=100, seed=46, g2_cycle=1))
    r = both(sh.ops)
    assert not r["valid?"]
    assert r["G0"] == set() and r["G1c"] == set()  # needs the rw edges
    assert r["G2"] == sh.g2


def test_incompatible_order():
    ops = reindex(
        [
            *txn(0, [[APPEND, 0, 1]]),
            *txn(0, [[APPEND, 0, 2]]),
            *txn(1, [[READ, 0, [1, 2]]]),
            *txn(2, [[READ, 0, [2]]]),  # contradicts [1, 2]
        ]
    )
    r = both(ops)
    assert not r["valid?"]
    assert r["incompatible-order"] == {0}
    # the contradicting read's content is unreliable — it must not
    # fabricate dependency cycles
    assert r["G1c"] == set() and r["G2"] == set()


def test_tensor_valid_folds_host_anomalies():
    from jepsen_tpu.checkers.elle import (
        elle_tensor_check,
        infer_txn_graph,
        pack_txn_graphs,
    )

    sh = synth_elle_history(ElleSynthSpec(n_txns=60, seed=48, g1a=1))
    t = elle_tensor_check(pack_txn_graphs([infer_txn_graph(sh.ops)]))
    assert not bool(t.valid[0])  # no cycle, but G1a must invalidate


def test_own_intermediate_read_is_legal():
    ops = reindex(
        [
            *txn(0, [[APPEND, 0, 1], [READ, 0, [1]], [APPEND, 0, 2]]),
            *txn(1, [[READ, 0, [1, 2]]]),
        ]
    )
    r = both(ops)
    assert r["valid?"], r
    assert r["G1b-count"] == 0


def test_read_of_indeterminate_append_imposes_nothing():
    ops = reindex(
        [
            Op.invoke(OpF.TXN, 0, [[APPEND, 0, 1]]),
            Op(OpType.INFO, OpF.TXN, 0, [[APPEND, 0, 1]], error="timeout"),
            *txn(1, [[READ, 0, [1]]]),
        ]
    )
    r = both(ops)
    assert r["valid?"], r  # info append may have happened — not G1a


def test_wr_edge_inference():
    ops = reindex(
        [
            *txn(0, [[APPEND, 0, 1]]),
            *txn(1, [[READ, 0, [1]]]),
        ]
    )
    g = infer_txn_graph(ops)
    assert g.wr == {(0, 1)}
    assert g.ww == set() and g.rw == set()


def test_rw_edge_inference():
    ops = reindex(
        [
            *txn(0, [[READ, 0, []]]),
            *txn(1, [[APPEND, 0, 1]]),
            *txn(2, [[READ, 0, [1]]]),
        ]
    )
    g = infer_txn_graph(ops)
    assert (0, 1) in g.rw  # the empty read missed txn 1's append


def test_batch_of_mixed_histories():
    shs = synth_elle_batch(4, ElleSynthSpec(n_txns=80))
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=80, seed=60), g2_cycle=1)
    rs = check_elle_batch([sh.ops for sh in shs])
    for sh, r in zip(shs, rs):
        assert r["valid?"] == sh.clean
        assert r == check_elle_cpu(sh.ops)


def test_device_and_host_inference_report_identically():
    """The two inference backends of check_elle_batch (device micro-op
    kernel vs per-history infer_txn_graph) are interchangeable down to
    the full result maps."""
    shs = synth_elle_batch(3, ElleSynthSpec(n_txns=60), g1c_cycle=1)
    shs += synth_elle_batch(2, ElleSynthSpec(n_txns=60, seed=80), g1b=1)
    hs = [sh.ops for sh in shs]
    assert check_elle_batch(hs, inference="device") == check_elle_batch(
        hs, inference="host"
    )


def test_device_inferred_edge_counts_match_host_sets():
    """ElleInferred's on-device edge counters equal the host twin's edge
    set sizes (the counts feed the result maps without any [T, T]
    device->host transfer)."""
    import numpy as np

    from jepsen_tpu.checkers.elle import elle_mops_check, pack_elle_mops

    shs = synth_elle_batch(4, ElleSynthSpec(n_txns=50), g2_cycle=1)
    mops, metas = pack_elle_mops([sh.ops for sh in shs])
    assert not any(g.degenerate for g in metas)
    _, inf = elle_mops_check(mops)
    for b, sh in enumerate(shs):
        g = infer_txn_graph(sh.ops)
        assert int(np.asarray(inf.ww_edges)[b]) == len(g.ww)
        assert int(np.asarray(inf.wr_edges)[b]) == len(g.wr)
        assert int(np.asarray(inf.rw_edges)[b]) == len(g.rw)


def test_large_history_many_txns():
    # cycle search at a scale where the closure is real MXU work
    sh = synth_elle_history(
        ElleSynthSpec(n_txns=600, seed=47, g1c_cycle=1, g2_cycle=1)
    )
    r = both(sh.ops)
    assert not r["valid?"]
    assert sh.g1c <= r["G1c"]
    assert sh.g2 <= r["G2"]


def test_consistency_model_levels():
    """read-committed admits G2 (the AMQP-tx contract: atomic commit
    visibility without read isolation) but still proscribes G0/G1;
    serializable proscribes everything.  Every class is reported at
    every level."""
    import pytest
    from jepsen_tpu.checkers.elle import check_elle_batch

    g2h = synth_elle_history(ElleSynthSpec(n_txns=60, seed=46, g2_cycle=1))
    g1h = synth_elle_history(ElleSynthSpec(n_txns=60, seed=47, g1c_cycle=1))

    strict = check_elle_cpu(g2h.ops)  # default serializable
    assert strict["valid?"] is False and strict["G2-count"] > 0
    rc = check_elle_cpu(g2h.ops, model="read-committed")
    assert rc["valid?"] is True
    assert rc["G2-count"] == strict["G2-count"]  # reported, not hidden
    assert rc["consistency-model"] == "read-committed"

    # G1c invalidates at BOTH levels
    for model in ("serializable", "read-committed"):
        r = check_elle_cpu(g1h.ops, model=model)
        assert r["valid?"] is False and r["G1c-count"] > 0, model

    # the tensor path agrees
    t = check_elle_batch([g2h.ops, g1h.ops], model="read-committed")
    assert t[0]["valid?"] is True and t[1]["valid?"] is False

    with pytest.raises(ValueError):
        check_elle_cpu(g2h.ops, model="snapshot-isolation")


def test_own_staged_append_in_intermediate_read_is_not_incompatible():
    """Read-your-writes normalization: a txn's intermediate read merges
    its own staged (uncommitted) appends after the committed prefix —
    client/native.py's txn driver and the sim driver both do this.  An
    interloper committing between that read and the txn's own commit
    makes the merged list contradict the final order ([2] vs [1, 2]);
    the checker must strip the txn's own values before order inference
    instead of flagging incompatible-order (found live: the measured-G2
    runs were red at read-committed for exactly this)."""
    from jepsen_tpu.checkers.elle import check_elle_batch, check_elle_cpu
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

    k = 0
    h = []
    # T1 stages append(k,2), reads k -> sees committed [] + own [2]
    t1 = Op.invoke(OpF.TXN, 0, [["append", k, 2], ["r", k, [2]]])
    h.append(t1)
    # T0 commits append(k,1) while T1 is still open
    t0 = Op.invoke(OpF.TXN, 1, [["append", k, 1]])
    h.append(t0)
    h.append(t0.complete(OpType.OK, value=[["append", k, 1]]))
    # T1 commits after T0: the real order is [1, 2]
    h.append(t1.complete(OpType.OK, value=[["append", k, 2], ["r", k, [2]]]))
    # T2 reads the final committed list
    t2 = Op.invoke(OpF.TXN, 2, [["r", k, None]])
    h.append(t2)
    h.append(t2.complete(OpType.OK, value=[["r", k, [1, 2]]]))
    hh = reindex(h)
    r = check_elle_cpu(hh, model="read-committed")
    assert r["incompatible-order-count"] == 0, r
    assert r["valid?"], r
    # the tensor path shares the host inference
    assert check_elle_batch([hh], model="read-committed")[0]["valid?"]


def test_genuinely_incompatible_committed_reads_still_flagged():
    """The normalization must not swallow real divergence: two COMMITTED
    reads that disagree on other txns' values remain incompatible."""
    from jepsen_tpu.checkers.elle import check_elle_cpu
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

    k = 0
    h = []
    for t_id, (vals, read) in enumerate(
        [([1], None), ([2], None)]
    ):
        t = Op.invoke(OpF.TXN, t_id, [["append", k, vals[0]]])
        h.append(t)
        h.append(t.complete(OpType.OK, value=[["append", k, vals[0]]]))
    # reader A saw [1, 2]; reader B saw [2, 1] — not prefix-compatible
    for t_id, seen in ((2, [1, 2]), (3, [2, 1])):
        t = Op.invoke(OpF.TXN, t_id, [["r", k, None]])
        h.append(t)
        h.append(t.complete(OpType.OK, value=[["r", k, seen]]))
    r = check_elle_cpu(reindex(h), model="read-committed")
    assert r["incompatible-order-count"] == 1
    assert not r["valid?"]


def test_own_value_mid_list_is_still_a_misorder():
    """The own-append normalization strips the trailing own-suffix ONLY:
    the read-your-writes merge appends own staged values after the
    committed prefix, so an own value observed MID-list cannot come from
    the merge — it is a genuine broker misorder and must stay flagged."""
    from jepsen_tpu.checkers.elle import check_elle_cpu
    from jepsen_tpu.history.ops import Op, OpF, OpType, reindex

    k = 0
    h = []
    t0 = Op.invoke(OpF.TXN, 0, [["append", k, 3]])
    h.append(t0)
    h.append(t0.complete(OpType.OK, value=[["append", k, 3]]))
    t1 = Op.invoke(OpF.TXN, 1, [["append", k, 4]])
    h.append(t1)
    h.append(t1.complete(OpType.OK, value=[["append", k, 4]]))
    # T2's own append 5 observed BETWEEN other txns' committed values —
    # not the trailing merge position
    t2 = Op.invoke(OpF.TXN, 2, [["append", k, 5], ["r", k, [3, 5, 4]]])
    h.append(t2)
    h.append(
        t2.complete(OpType.OK, value=[["append", k, 5], ["r", k, [3, 5, 4]]])
    )
    t3 = Op.invoke(OpF.TXN, 3, [["r", k, None]])
    h.append(t3)
    h.append(t3.complete(OpType.OK, value=[["r", k, [3, 4, 5]]]))
    r = check_elle_cpu(reindex(h), model="read-committed")
    assert r["incompatible-order-count"] == 1, r
    assert not r["valid?"]
