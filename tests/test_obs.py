"""Flight recorder (jepsen_tpu/obs): tracer, metrics, export, endpoint.

The ISSUE-10 test contract: trace round-trip across concurrent lanes
(well-formed JSON, tracks don't interleave, nesting preserved),
quantile-sketch merge correctness vs numpy percentiles, the service
``/metrics`` scrape smoke, and the disabled tracer's zero-allocation
off-path."""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from jepsen_tpu.obs import export as obs_export
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace


@pytest.fixture(autouse=True)
def _tracer_off_after():
    yield
    obs_trace.disable()


class TestTracerRoundTrip:
    def _record_lanes(self, n_lanes=4, spans_per_lane=8):
        obs_trace.enable(capacity=4096)

        def lane(i: int):
            track = f"lane{i}"
            for k in range(spans_per_lane):
                with obs_trace.span(
                    "outer", track=track, args={"k": k}
                ):
                    with obs_trace.span("mid", track=track):
                        with obs_trace.span("inner", track=track):
                            pass
                obs_trace.event("tick", track=track)

        threads = [
            threading.Thread(target=lane, args=(i,)) for i in range(n_lanes)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return n_lanes, spans_per_lane

    def test_export_well_formed_tracks_and_nesting(self, tmp_path):
        n_lanes, per = self._record_lanes()
        out = tmp_path / "trace.json"
        summary = obs_export.write_trace(out)
        doc = json.loads(out.read_text())  # well-formed by parse
        events = doc["traceEvents"]
        assert summary["events"] == len(events)
        assert summary["dropped"] == 0

        # track metadata: one thread_name row per lane track
        names = {
            ev["tid"]: ev["args"]["name"]
            for ev in events
            if ev["ph"] == "M" and ev["name"] == "thread_name"
        }
        assert sorted(names.values()) == sorted(
            f"lane{i}" for i in range(n_lanes)
        )

        # tracks don't interleave: every span carries its own lane's
        # tid only (a lane's records never land on another track)...
        by_tid: dict[int, list] = {}
        for ev in events:
            if ev["ph"] == "X":
                by_tid.setdefault(ev["tid"], []).append(ev)
        assert len(by_tid) == n_lanes
        for tid, spans in by_tid.items():
            assert len(spans) == 3 * per
            ks = [
                ev["args"]["k"] for ev in spans if ev["name"] == "outer"
            ]
            assert ks == sorted(ks)  # one thread per track: in order
            # ...and nesting is preserved: on each track the
            # inner/mid intervals lie within their outer span
            outers = sorted(
                (ev for ev in spans if ev["name"] == "outer"),
                key=lambda e: e["ts"],
            )
            for name in ("mid", "inner"):
                for ev in (e for e in spans if e["name"] == name):
                    assert any(
                        o["ts"] - 1e-3 <= ev["ts"]
                        and ev["ts"] + ev["dur"] <= o["ts"] + o["dur"] + 1e-3
                        for o in outers
                    ), (name, ev)

        # instant events present, thread-scoped
        ticks = [ev for ev in events if ev["ph"] == "i"]
        assert len(ticks) == n_lanes * per
        assert all(ev["s"] == "t" for ev in ticks)

    def test_snapshot_survives_disable(self):
        self._record_lanes(n_lanes=1, spans_per_lane=2)
        n_live = len(obs_trace.snapshot())
        obs_trace.disable()
        assert len(obs_trace.snapshot()) == n_live > 0

    def test_ring_wrap_drops_oldest_and_reports(self):
        obs_trace.enable(capacity=256)
        for k in range(600):
            obs_trace.event("e", track="t", args={"k": k})
        recs = obs_trace.snapshot()
        assert len(recs) == 256
        assert obs_trace.dropped() == 600 - 256
        # the TAIL survived (flight-recorder semantics)
        ks = [r[5]["k"] for r in recs]
        assert ks == list(range(600 - 256, 600))

    def test_complete_records_from_perf_counter_seconds(self):
        import time

        obs_trace.enable()
        t0 = time.perf_counter()
        t1 = t0 + 0.25
        obs_trace.complete("win", t0, t1, track="nemesis")
        ((kind, name, track, t_ns, dur_ns, _args),) = obs_trace.snapshot()
        assert (kind, name, track) == ("X", "win", "nemesis")
        assert abs(dur_ns - 0.25e9) < 1e6


class TestDisabledOverhead:
    def test_disabled_span_is_shared_noop(self):
        obs_trace.disable()
        assert obs_trace.span("a") is obs_trace.span("b")
        with obs_trace.span("a"):
            with obs_trace.span("a"):  # reentrant-safe
                pass
        obs_trace.event("nothing")  # no-op, no error

    def test_disabled_span_costs_zero_allocations(self):
        """The off-path contract: a disabled span() call allocates
        NOTHING (the shared no-op comes back by reference), so leaving
        instrumentation in hot loops is free when the recorder is off."""
        import gc
        import sys

        obs_trace.disable()

        def loop(n):
            for _ in range(n):
                with obs_trace.span("hot"):
                    pass
                obs_trace.event("hot")

        loop(1000)  # warm (method caches, code objects)
        gc.collect()
        gc.disable()
        try:
            before = sys.getallocatedblocks()
            loop(10_000)
            after = sys.getallocatedblocks()
        finally:
            gc.enable()
        # zero per-span cost: the delta must not scale with the 10k
        # iterations (a handful of blocks of interpreter noise allowed)
        assert after - before < 50, f"{after - before} blocks for 10k spans"


class TestQuantileSketch:
    @pytest.mark.parametrize("dist", ["lognormal", "uniform", "exp"])
    def test_merge_matches_numpy_percentiles(self, dist):
        rng = np.random.default_rng(7)
        xs = {
            "lognormal": rng.lognormal(0.0, 1.0, 20_000),
            "uniform": rng.uniform(0.001, 5.0, 20_000),
            "exp": rng.exponential(0.05, 20_000),
        }[dist]
        shards = [obs_metrics.QuantileSketch() for _ in range(5)]
        for i, x in enumerate(xs):
            shards[i % 5].add(float(x))
        merged = obs_metrics.QuantileSketch()
        for s in shards:
            merged.merge(s)
        assert merged.count == len(xs)
        assert merged.sum == pytest.approx(float(xs.sum()), rel=1e-9)
        for q in (0.5, 0.9, 0.99):
            got = merged.quantile(q)
            ref = float(np.percentile(xs, q * 100))
            # the sketch's own bound is alpha=1% relative error; allow
            # 2% for the rank interpolation numpy applies and we don't
            assert abs(got - ref) / ref < 0.02, (q, got, ref)

    def test_merge_refuses_mismatched_alpha(self):
        a = obs_metrics.QuantileSketch(alpha=0.01)
        b = obs_metrics.QuantileSketch(alpha=0.05)
        with pytest.raises(ValueError, match="alpha"):
            a.merge(b)

    def test_empty_and_zero_handling(self):
        sk = obs_metrics.QuantileSketch()
        assert sk.quantile(0.5) != sk.quantile(0.5)  # NaN
        sk.add(0.0)
        sk.add(-1.0)
        sk.add(2.0)
        assert sk.quantile(0.0) == 0.0
        assert sk.quantile(1.0) == pytest.approx(2.0, rel=0.02)


class TestRegistry:
    def test_counters_gauges_and_labels(self):
        reg = obs_metrics.Registry()
        reg.counter("a.b").inc()
        reg.counter("a.b").inc(2)
        reg.counter("a.b", reason="x").inc()
        reg.gauge("g").set(3.5)
        assert reg.value("a.b") == 3
        assert reg.value("a.b", reason="x") == 1
        assert reg.value("g") == 3.5
        assert reg.value("never.touched") == 0.0

    def test_kind_collision_is_loud(self):
        reg = obs_metrics.Registry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.sketch("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_prometheus_rendering(self):
        reg = obs_metrics.Registry()
        reg.counter("pipeline.files_dropped", reason="zero-length").inc(2)
        sk = reg.sketch("service.check_latency_s", op="check")
        for v in (0.01, 0.02, 0.03):
            sk.add(v)
        text = obs_metrics.render_prometheus(reg)
        assert (
            'jepsen_tpu_pipeline_files_dropped{reason="zero-length"} 2'
            in text
        )
        assert "# TYPE jepsen_tpu_service_check_latency_s summary" in text
        assert 'quantile="0.5"' in text and 'quantile="0.99"' in text
        assert "jepsen_tpu_service_check_latency_s_count" in text


class TestPipelineStatsView:
    """The PipelineStats refactor contract: same fields, registry-backed."""

    def test_fields_are_registry_views(self):
        from jepsen_tpu.parallel.pipeline import PipelineStats

        stats = PipelineStats(lanes=2, dropped=1)
        stats.histories = 5
        stats.batches = 2
        stats.add_busy("produce", 0.0, 0.5)
        stats.add_busy("check", 0.0, 0.25)
        assert stats.histories == 5 and isinstance(stats.histories, int)
        assert stats.dropped == 1
        assert stats.produce_busy_s == pytest.approx(0.5)
        assert stats.check_busy_s == pytest.approx(0.25)
        # the registry IS the storage
        assert stats.metrics.value(
            "pipeline.stage_busy_s", stage="produce"
        ) == pytest.approx(0.5)
        assert stats.metrics.value("pipeline.histories") == 5
        # per-batch check latency sketch feeds p50/p99
        assert stats.check_batch_quantile(0.5) == pytest.approx(
            0.25, rel=0.02
        )
        stats.wall_s = 0.5
        stats.finalize()
        assert 0.0 <= stats.stage_overlap_frac <= 1.0
        assert 0.0 <= stats.device_idle_frac <= 1.0

    def test_add_busy_mirrors_global_registry(self):
        from jepsen_tpu.parallel.pipeline import PipelineStats

        before = obs_metrics.REGISTRY.value(
            "pipeline.stage_busy_s", stage="place"
        )
        PipelineStats().add_busy("place", 0.0, 0.125)
        assert obs_metrics.REGISTRY.value(
            "pipeline.stage_busy_s", stage="place"
        ) == pytest.approx(before + 0.125)


class TestMetricsEndpoint:
    def test_scrape_smoke(self):
        """GET /metrics serves the registry as Prometheus text."""
        reg = obs_metrics.Registry()
        reg.sketch("service.check_latency_s", op="check").add(0.004)
        reg.counter("service.requests", op="check").inc()
        srv = obs_metrics.serve_metrics("127.0.0.1", 0, reg)
        srv.start_background()
        try:
            port = srv.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert 'jepsen_tpu_service_requests{op="check"} 1' in body
            assert (
                'jepsen_tpu_service_check_latency_s{op="check",'
                'quantile="0.99"}' in body
            )
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/else", timeout=10
                )
        finally:
            srv.shutdown()
            srv.server_close()

    def test_checker_server_records_and_serves_latency(self):
        """The acceptance bar: after a real check request the sidecar's
        /metrics answers p50/p99 check latency from the SHARED registry."""
        from jepsen_tpu.history.synth import SynthSpec, synth_batch
        from jepsen_tpu.service import CheckerClient, CheckerServer

        reg = obs_metrics.Registry()
        srv = CheckerServer(
            host="127.0.0.1", port=0, metrics_registry=reg
        )
        srv.start_background()
        msrv = srv.start_metrics("127.0.0.1", 0)
        try:
            shs = synth_batch(2, SynthSpec(n_ops=40))
            with CheckerClient(port=srv.port) as client:
                results = client.check_histories([s.ops for s in shs])
            assert all(r["valid?"] for r in results)
            assert reg.value("service.requests", op="check") == 1
            assert reg.value("service.histories", op="check") == 2
            sk = reg.sketch("service.check_latency_s", op="check")
            assert sk.count == 1 and sk.quantile(0.99) > 0
            port = msrv.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
            assert (
                'jepsen_tpu_service_check_latency_s{op="check",'
                'quantile="0.5"}' in body
            )
            assert (
                'jepsen_tpu_service_check_latency_s{op="check",'
                'quantile="0.99"}' in body
            )
        finally:
            srv.shutdown()
            srv.server_close()


class TestNemesisWindowSpans:
    def test_fault_windows_become_spans(self, tmp_path):
        """A traced sim run records one span per nemesis START/STOP
        window on the `nemesis` track, alongside the run-phase spans —
        the two timelines red triage needs side by side."""
        from jepsen_tpu.control.runner import run_test
        from jepsen_tpu.suite import build_sim_test

        obs_trace.enable()
        opts = {
            "rate": 400.0,
            "time-limit": 1.5,
            "time-before-partition": 0.3,
            "partition-duration": 0.4,
            "recovery-sleep": 0.2,
        }
        test, _cluster = build_sim_test(
            opts=opts, store_root=str(tmp_path / "store")
        )
        run = run_test(test)
        obs_trace.disable()
        assert run.results.get("valid?") is True
        recs = obs_trace.snapshot()
        nemesis = [
            r for r in recs
            if r[0] == "X" and str(r[1]).startswith("nemesis:")
        ]
        assert nemesis, "no fault-window spans recorded"
        assert all(r[2] == "nemesis" for r in nemesis)
        phases = {r[1] for r in recs if r[2] == "run"}
        assert {"run.setup", "run.load", "run.analysis"} <= phases
