"""Execute ``ci/jepsen-tpu-test.sh`` end-to-end against a fake cloud.

VERDICT r4 missing #3: the bash CI driver — the script a real CI run
would actually execute (reference: ``ci/jepsen-test.sh``) — had zero
execution evidence; only its Python twin (``harness/matrix.py``) was
tested.  These tests run the real script under a PATH shim that replays
scripted ``terraform``/``ssh``/``scp``/``aws``/``ssh-keygen`` outputs
(the ``SshTransport`` fake-transport pattern, lifted to the process
boundary), covering:

- leftover-teardown tolerance (a failing ``aws ec2 terminate-instances``
  must not kill the run — the reference wraps it in ``set +e``)
- terraform bring-up + state preservation for the workflow's always()
  destroy step
- controller/worker provisioning choreography (hosts entries, binary
  fan-out via controller-side scp, apt refresh)
- the matrix invocation (all workers in --nodes, the file:// archive
  URL the workers install from)
- verdict propagation: the matrix's exit code is the script's exit
  code, while the store archive is tarred and shipped to S3 either way
  (red runs must still deliver their evidence).
"""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

BINARY_URL = (
    "https://builds.example.com/server-packages/"
    "rabbitmq-server-generic-unix-4.1.0-alpha.047cc5a0.tar.xz"
)
ARCHIVE = "rabbitmq-server-generic-unix-4.1.0-alpha.047cc5a0.tar.xz"

WORKERS = ["w1", "w2", "w3", "w4", "w5"]
WORKER_IPS = ["10.0.0.11", "10.0.0.12", "10.0.0.13", "10.0.0.14",
              "10.0.0.15"]
HOSTS_ENTRIES = r"\n".join(
    f"{ip} {w}" for ip, w in zip(WORKER_IPS, WORKERS)
)

SSH_FAKE = """#!/bin/bash
# fake ssh: log the full invocation, answer scripted commands.
log="$SHIM_LOG/ssh.log"
printf '%s\\n' "$*" >> "$log"
last="${@: -1}"
case "$last" in
  *"python -m jepsen_tpu matrix"*)
    printf '{"configs": 14, "failed": %s}\\n' "${FAKE_MATRIX_FAILED:-0}"
    exit "${FAKE_MATRIX_RC:-0}"
    ;;
  *"tar -zcf -"*)
    printf 'FAKETAR'
    ;;
  "bash -s")
    cat > /dev/null   # provisioning script arrives on stdin
    ;;
esac
exit 0
"""

TERRAFORM_FAKE = f"""#!/bin/bash
log="$SHIM_LOG/terraform.log"
printf '%s\\n' "$*" >> "$log"
case "$1" in
  init)  mkdir -p .terraform ;;
  apply) echo 'fake-state' > terraform.tfstate ;;
  output)
    case "$3" in
      controller_ip)         echo 10.0.0.1 ;;
      workers_hostname)      echo '{" ".join(WORKERS)}' ;;
      workers_ip)            echo '{" ".join(WORKER_IPS)}' ;;
      workers_hosts_entries) printf '{HOSTS_ENTRIES}\\n' ;;
      *) echo "unknown output $3" >&2; exit 1 ;;
    esac ;;
esac
exit 0
"""

AWS_FAKE = """#!/bin/bash
log="$SHIM_LOG/aws.log"
printf '%s\\n' "$*" >> "$log"
case "$*" in
  *describe-instances*) echo "i-0aaa i-0bbb" ;;
  *terminate-instances*) exit 1 ;;  # leftovers may not exist: tolerated
  *delete-key-pair*) exit 1 ;;
esac
exit 0
"""

SSH_KEYGEN_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/ssh-keygen.log"
while [ $# -gt 0 ]; do
  if [ "$1" = "-f" ]; then keyfile=$2; shift; fi
  shift
done
: "${keyfile:?fake ssh-keygen needs -f}"
echo fake-private-key > "$keyfile"
echo fake-public-key > "$keyfile.pub"
"""

SCP_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/scp.log"
exit 0
"""


@pytest.fixture
def cloud(tmp_path):
    """A workdir with the repo's ci/ scripts, a PATH shim of fake cloud
    binaries, and an isolated HOME."""
    work = tmp_path / "work"
    shutil.copytree(REPO / "ci", work / "ci")
    bins = tmp_path / "bin"
    bins.mkdir()
    for name, body in (
        ("ssh", SSH_FAKE),
        ("terraform", TERRAFORM_FAKE),
        ("aws", AWS_FAKE),
        ("ssh-keygen", SSH_KEYGEN_FAKE),
        ("scp", SCP_FAKE),
    ):
        p = bins / name
        p.write_text(body)
        p.chmod(0o755)
    logs = tmp_path / "logs"
    logs.mkdir()
    home = tmp_path / "home"
    home.mkdir()
    return {"work": work, "bins": bins, "logs": logs, "home": home}


def _run_script(cloud, script, env_over=None, timeout=60):
    """Execute one ci/ script under the shim PATH + isolated HOME — the
    single copy of the environment every shim test runs in."""
    env = {
        **os.environ,
        "PATH": f"{cloud['bins']}:{os.environ['PATH']}",
        "HOME": str(cloud["home"]),
        "SHIM_LOG": str(cloud["logs"]),
        "BINARY_URL": BINARY_URL,
        **(env_over or {}),
    }
    return subprocess.run(
        ["bash", str(cloud["work"] / "ci" / script)],
        cwd=cloud["work"],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def _run(cloud, **env_over):
    return _run_script(
        cloud,
        "jepsen-tpu-test.sh",
        env_over={
            "AWS_CONFIG": "[default]\nregion = eu-west-1",
            "AWS_CREDENTIALS": "[default]\naws_access_key_id = AKIAFAKE",
            **env_over,
        },
        timeout=120,
    )


def _log(cloud, name):
    p = cloud["logs"] / f"{name}.log"
    return p.read_text() if p.exists() else ""


class TestGreenRun:
    def test_full_choreography(self, cloud):
        r = _run(cloud)
        assert r.returncode == 0, r.stderr[-2000:]
        work, home = cloud["work"], cloud["home"]

        # aws credentials materialized outside the xtrace window
        assert "AKIAFAKE" in (home / ".aws" / "credentials").read_text()
        assert "eu-west-1" in (home / ".aws" / "config").read_text()

        # leftover teardown attempted (and its failure tolerated)
        aws_log = _log(cloud, "aws")
        assert "terminate-instances" in aws_log
        assert "delete-key-pair" in aws_log and "JepsenTpuQq41" in aws_log

        # terraform bring-up, branch tag threaded through
        tf_log = _log(cloud, "terraform")
        assert "init" in tf_log
        assert "apply -auto-approve -var=rabbitmq_branch=41" in tf_log

        # state preserved for the workflow's always() destroy step
        state = work / "terraform-state"
        for needed in ("jepsen-bot", "jepsen-bot.pub", ".terraform",
                       "terraform.tfstate", "jepsen-tpu-aws.tf"):
            assert (state / needed).exists(), needed

        ssh_log = _log(cloud, "ssh")
        # controller provisioned via stdin script + hosts entries
        assert "admin@10.0.0.1 bash -s" in ssh_log
        assert ssh_log.count("sudo tee --append /etc/hosts") == 1 + len(
            WORKERS
        )
        # binary under test fetched once, fanned out to every worker
        assert f"wget -q '{BINARY_URL}'" in ssh_log
        for w in WORKERS:
            assert f"admin@{w}:/tmp/{ARCHIVE}" in ssh_log
        for ip in WORKER_IPS:
            assert f"admin@{ip} sudo apt-get update -q" in ssh_log

        # the matrix: every worker in --nodes, file:// archive URL
        matrix_lines = [
            l for l in ssh_log.splitlines() if "jepsen_tpu matrix" in l
        ]
        assert len(matrix_lines) == 1
        m = matrix_lines[0]
        assert "--db rabbitmq" in m
        assert f"--nodes '{','.join(WORKERS)}'" in m
        assert f"--archive-url 'file:///tmp/{ARCHIVE}'" in m
        assert "--ssh-private-key ~/jepsen-bot" in m

        # store archived and shipped
        tars = list(work.glob("qq-jepsen-tpu-41-*-logs.tar.gz"))
        assert len(tars) == 1
        assert tars[0].read_bytes() == b"FAKETAR"
        assert f"s3 cp {tars[0].name} s3://jepsen-tests-logs/" in _log(
            cloud, "aws"
        )
        assert "Download logs:" in r.stdout

    def test_keypair_is_fresh_per_run(self, cloud):
        _run(cloud)
        kg = _log(cloud, "ssh-keygen")
        assert "-t ed25519" in kg and "-N " in kg
        assert (cloud["work"] / "jepsen-bot").exists()


class TestRedRun:
    def test_matrix_failure_propagates_but_still_archives(self, cloud):
        """A red matrix (Analysis invalid after retries) exits nonzero —
        and the evidence archive ships to S3 anyway, exactly like the
        reference's always-archive behavior."""
        r = _run(cloud, FAKE_MATRIX_RC="3", FAKE_MATRIX_FAILED="2")
        assert r.returncode == 3, r.stderr[-2000:]
        aws_log = _log(cloud, "aws")
        assert "s3 cp" in aws_log and "-logs.tar.gz" in aws_log
        tars = list(cloud["work"].glob("qq-jepsen-tpu-41-*-logs.tar.gz"))
        assert len(tars) == 1

    def test_missing_binary_url_fails_fast(self, cloud):
        import os

        env = {
            **os.environ,
            "PATH": f"{cloud['bins']}:{os.environ['PATH']}",
            "HOME": str(cloud["home"]),
            "SHIM_LOG": str(cloud["logs"]),
        }
        env.pop("BINARY_URL", None)
        r = subprocess.run(
            ["bash", str(cloud["work"] / "ci" / "jepsen-tpu-test.sh")],
            cwd=cloud["work"], env=env, capture_output=True, text=True,
            timeout=30,
        )
        assert r.returncode != 0
        assert "BINARY_URL" in r.stderr
        # nothing provisioned: the guard fired before any cloud call
        assert not _log(cloud, "terraform")


# ---------------------------------------------------------------------------
# destroy-cluster.sh — the always() teardown
# ---------------------------------------------------------------------------

DESTROY_TERRAFORM_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/terraform.log"
[ "${FAKE_TF_DESTROY_RC:-0}" != 0 ] && [ "$1" = destroy ] && exit "$FAKE_TF_DESTROY_RC"
exit 0
"""


class TestDestroyCluster:
    def _run(self, cloud, env_over=None, make_state=True):
        if make_state:
            (cloud["work"] / "terraform-state").mkdir(exist_ok=True)
            (cloud["work"] / "terraform-state" / "terraform.tfstate"
             ).write_text("fake")
        aws_home = cloud["home"] / ".aws"
        aws_home.mkdir(exist_ok=True)
        (aws_home / "credentials").write_text("secret")
        # destroy's terraform fake must not fail on `destroy` by default
        p = cloud["bins"] / "terraform"
        p.write_text(DESTROY_TERRAFORM_FAKE)
        p.chmod(0o755)
        return _run_script(cloud, "destroy-cluster.sh", env_over)

    def test_destroys_and_scrubs(self, cloud):
        r = self._run(cloud)
        assert r.returncode == 0, r.stderr
        tf = _log(cloud, "terraform")
        assert "init" in tf
        assert "destroy -auto-approve -var=rabbitmq_branch=41" in tf
        assert "delete-key-pair" in _log(cloud, "aws")
        assert "jepsen-tpu-qq-41-key" in _log(cloud, "aws")
        # credentials and state scrubbed even on success
        assert not (cloud["home"] / ".aws").exists()
        assert not (cloud["work"] / "terraform-state").exists()

    def test_failed_destroy_scrubs_credentials_but_keeps_state(self, cloud):
        """The always() contract: a failed terraform destroy must not
        leave AWS credentials on the runner — but it must KEEP the
        terraform state, which is the only handle the advertised manual
        cleanup has on the orphaned instances (review r5 find)."""
        r = self._run(cloud, env_over={"FAKE_TF_DESTROY_RC": "1"})
        assert r.returncode == 0, r.stderr
        assert "manual cleanup" in r.stdout
        assert not (cloud["home"] / ".aws").exists()
        assert (cloud["work"] / "terraform-state" / "terraform.tfstate"
                ).exists()
        assert "keeping terraform-state/" in r.stdout

    def test_no_state_dir_skips_terraform_but_scrubs(self, cloud):
        r = self._run(cloud, make_state=False)
        assert r.returncode == 0, r.stderr
        assert "destroy" not in _log(cloud, "terraform")
        assert not (cloud["home"] / ".aws").exists()


# ---------------------------------------------------------------------------
# verify-binary-signature.sh — the GPG gate
# ---------------------------------------------------------------------------

CURL_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/curl.log"
# last arg is the url; -o NAME names the output, -O basenames the url
out=""
args=("$@")
for ((i=0; i<${#args[@]}; i++)); do
  case "${args[$i]}" in
    -o) out="${args[$((i+1))]}" ;;
    -O) ;;
    http*) url="${args[$i]}" ;;
  esac
done
[ -z "$out" ] && out=$(basename "$url")
echo "fake-content-of $url" > "$out"
"""

GPG_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/gpg.log"
case "$1" in
  --import) exit 0 ;;
  --verify) exit "${FAKE_GPG_RC:-0}" ;;
esac
exit 0
"""


class TestVerifyBinarySignature:
    def _run(self, cloud, env_over=None):
        for name, body in (("curl", CURL_FAKE), ("gpg", GPG_FAKE)):
            p = cloud["bins"] / name
            p.write_text(body)
            p.chmod(0o755)
        return _run_script(cloud, "verify-binary-signature.sh", env_over)

    def test_verifies_tarball_against_release_key(self, cloud):
        r = self._run(cloud)
        assert r.returncode == 0, r.stderr
        curl = _log(cloud, "curl")
        assert "rabbitmq-release-signing-key.asc" in curl
        assert BINARY_URL in curl and f"{BINARY_URL}.asc" in curl
        gpg = _log(cloud, "gpg")
        assert "--import signing-key.asc" in gpg
        assert f"--verify {ARCHIVE}.asc {ARCHIVE}" in gpg
        assert "signature OK" in r.stdout

    def test_bad_signature_fails_the_gate(self, cloud):
        r = self._run(cloud, env_over={"FAKE_GPG_RC": "2"})
        assert r.returncode != 0
        assert "signature OK" not in r.stdout
        # the failure came from the verify step itself, not some earlier
        # breakage that would leave the bad-signature path untested
        assert f"--verify {ARCHIVE}.asc {ARCHIVE}" in _log(cloud, "gpg")


# ---------------------------------------------------------------------------
# provision-jepsen-tpu-controller.sh — controller bring-up
# ---------------------------------------------------------------------------

SUDO_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/sudo.log"
exit 0
"""

GIT_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/git.log"
if [ "$1" = clone ]; then mkdir -p "${@: -1}"; fi
exit 0
"""

PYTHON3_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/python3.log"
if [ "$1" = -m ] && [ "$2" = venv ]; then
  mkdir -p "$3/bin"
  printf 'export JEPSEN_FAKE_VENV=1\\n' > "$3/bin/activate"
fi
exit 0
"""

PIP_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/pip.log"
exit 0
"""

MAKE_FAKE = """#!/bin/bash
printf '%s\\n' "$*" >> "$SHIM_LOG/make.log"
exit 0
"""

PYTHON_FAKE = """#!/bin/bash
printf '%s %s\\n' "$PWD" "$*" >> "$SHIM_LOG/python.log"
exit 0
"""


class TestProvisionController:
    def test_full_bring_up(self, cloud):
        for name, body in (
            ("sudo", SUDO_FAKE), ("git", GIT_FAKE),
            ("python3", PYTHON3_FAKE), ("pip", PIP_FAKE),
            ("make", MAKE_FAKE), ("python", PYTHON_FAKE),
        ):
            p = cloud["bins"] / name
            p.write_text(body)
            p.chmod(0o755)
        env_over = {"JAX_EXTRA": "jax"}  # CPU-controller variant
        r = _run_script(
            cloud, "provision-jepsen-tpu-controller.sh", env_over
        )
        assert r.returncode == 0, r.stderr
        assert "controller provisioned" in r.stdout
        sudo = _log(cloud, "sudo")
        assert "apt-get update" in sudo
        assert "g++" in sudo and "python3-venv" in sudo
        assert "clone" in _log(cloud, "git")
        pip = _log(cloud, "pip")
        assert "install jax numpy matplotlib" in pip
        assert "install -e" in pip
        assert "-C" in _log(cloud, "make")  # native driver built
        # venv activation persisted for later ssh commands
        profile = (cloud["home"] / ".profile").read_text()
        assert "jepsen-tpu-venv/bin/activate" in profile
        # the smoke check ran inside the repo checkout (the fake logs
        # $PWD ahead of argv)
        py = _log(cloud, "python")
        assert (
            f"{cloud['home']}/jepsen-tpu -m jepsen_tpu test --help" in py
        )
        # idempotence: a second run must not duplicate the profile line
        r2 = _run_script(
            cloud, "provision-jepsen-tpu-controller.sh", env_over
        )
        assert r2.returncode == 0, r2.stderr
        profile2 = (cloud["home"] / ".profile").read_text()
        assert profile2.count("jepsen-tpu-venv/bin/activate") == 1
