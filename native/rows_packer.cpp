// Native history packer: history.jsonl -> [n, 8] int32 row matrix.
//
// C++ twin of jepsen_tpu/history/rows.py::_rows_for composed with the
// JSONL reader (store.py::read_history_jsonl) and the workload
// classifier (ops.py::workload_of), fused into one streaming pass so a
// fresh pack never materializes Python op objects at all.  The hot cost
// of the batched-replay north star's fresh path is JSON parsing on the
// host (the reference's analogue is jepsen's EDN history read before
// checker/check runs); this parser reads ~2 GB of JSONL at native
// speed where Python's json module is the 1-core bottleneck.
//
// Semantics contract (differential-tested in tests/test_fastpack.py
// against the Python packer on every workload family):
//   - row schema: index, process, type, f, value, time_ms, latency_ms,
//     first  (int32 each)
//   - completion latency: against the immediately preceding op of the
//     same process iff that op is an INVOKE and both timestamps are
//     valid; floor division to ms (matches numpy int64 //)
//   - value explosion: scalar int -> one row; bool -> 1/0; null/absent/
//     float/string/object -> NO_VALUE; list -> one row per element
//     (elements: int or bool kept, anything else NO_VALUE); empty
//     list -> a single NO_VALUE row; `first` flags the first row of
//     each op, and latency_ms is -1 on non-first rows
//   - any value or time_ms outside int32 -> OVERFLOW error (the Python
//     packer raises OverflowError; the binding falls back so the
//     Python error path stays the single source of truth)
//   - any parse irregularity (unknown type/f string, non-object line,
//     malformed JSON, non-int process) -> PARSE error; the binding
//     falls back to the Python packer, which raises its own exception
//   - workload: first op whose f is append/read -> stream, txn ->
//     elle, acquire/release -> mutex; else queue
//
// Reference tie-in (same as rows.py): the op schema mirrors jepsen op
// maps (rabbitmq.clj:191-215,245-248); dense-int values are what make
// histories tensorizable (Utils.java:443,496,532,584).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int32_t NO_VALUE = -1;

// OpType / OpF integer codes (ops.py enums)
constexpr int T_INVOKE = 0;

enum Err : int32_t { OK = 0, ERR_IO = 1, ERR_PARSE = 2, ERR_OVERFLOW = 3 };

enum class VKind { NONE, INT, OTHER, LIST };

struct JVal {
  VKind kind = VKind::NONE;
  long long i = 0;
  // list elements: (is_int, value) pairs; non-int elements carry NO_VALUE
  std::vector<long long> elems;
  std::vector<uint8_t> elem_is_int;
};

struct Cursor {
  const char* p;
  const char* end;
  bool fail = false;
  bool overflow = false;
};

inline void skip_ws(Cursor& c) {
  while (c.p < c.end &&
         (*c.p == ' ' || *c.p == '\t' || *c.p == '\r' || *c.p == '\n'))
    ++c.p;
}

inline bool is_hex(char ch) {
  return (ch >= '0' && ch <= '9') || (ch >= 'a' && ch <= 'f') ||
         (ch >= 'A' && ch <= 'F');
}

// Scan a JSON string (cursor on the opening quote); returns the raw
// (still-escaped) span in [*s, *e) excluding quotes.  Validates what
// Python's json module validates — legal escapes only, no raw control
// characters — so a file the canonical parser rejects is never
// silently accepted here.
bool scan_string(Cursor& c, const char** s, const char** e) {
  if (c.p >= c.end || *c.p != '"') return false;
  ++c.p;
  *s = c.p;
  while (c.p < c.end) {
    char ch = *c.p;
    if (ch == '\\') {
      if (c.p + 1 >= c.end) return false;
      char esc = c.p[1];
      if (esc == 'u') {
        if (c.p + 5 >= c.end || !is_hex(c.p[2]) || !is_hex(c.p[3]) ||
            !is_hex(c.p[4]) || !is_hex(c.p[5]))
          return false;
        c.p += 6;
        continue;
      }
      if (esc != '"' && esc != '\\' && esc != '/' && esc != 'b' &&
          esc != 'f' && esc != 'n' && esc != 'r' && esc != 't')
        return false;
      c.p += 2;
      continue;
    }
    if (ch == '"') {
      *e = c.p;
      ++c.p;
      return true;
    }
    if (static_cast<unsigned char>(ch) < 0x20) return false;  // raw control
    ++c.p;
  }
  return false;
}

// Parse a number with the exact JSON grammar (RFC 8259: '-'? int frac?
// exp?, no leading zeros, no leading '+') — anything the canonical
// Python parser rejects must set c.fail so the binding falls back.
// int_ok=false when it is a float (or out of int64 range -> overflow).
long long scan_number(Cursor& c, bool* int_ok) {
  const char* start = c.p;
  *int_ok = false;
  if (c.p < c.end && *c.p == '-') ++c.p;
  // int part: '0' | [1-9][0-9]*
  if (c.p >= c.end || *c.p < '0' || *c.p > '9') {
    c.fail = true;
    return 0;
  }
  if (*c.p == '0') {
    ++c.p;
    if (c.p < c.end && *c.p >= '0' && *c.p <= '9') {
      c.fail = true;  // leading zero: json.loads rejects "01"
      return 0;
    }
  } else {
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
  }
  const char* int_end = c.p;
  bool is_float = false;
  if (c.p < c.end && *c.p == '.') {
    is_float = true;
    ++c.p;
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') {
      c.fail = true;  // "1." is not JSON
      return 0;
    }
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
  }
  if (c.p < c.end && (*c.p == 'e' || *c.p == 'E')) {
    is_float = true;
    ++c.p;
    if (c.p < c.end && (*c.p == '-' || *c.p == '+')) ++c.p;
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') {
      c.fail = true;  // "1e" / "1e+" are not JSON
      return 0;
    }
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
  }
  if (is_float) return 0;
  errno = 0;
  char* endp = nullptr;
  std::string tmp(start, int_end - start);  // bounded copy for strtoll
  long long v = std::strtoll(tmp.c_str(), &endp, 10);
  if (errno == ERANGE) {
    c.overflow = true;  // int beyond int64: Python raises OverflowError
    return 0;           // at np.asarray — binding falls back to raise
  }
  if (endp == nullptr || *endp != '\0') {
    c.fail = true;
    return 0;
  }
  *int_ok = true;
  return v;
}

void skip_value(Cursor& c);

// Parse (and discard) a JSON object with full structural validation —
// a malformed nested object must fall back to the canonical parser,
// never be skipped over.
void parse_object(Cursor& c) {
  ++c.p;  // cursor was on '{'
  skip_ws(c);
  if (c.p < c.end && *c.p == '}') {
    ++c.p;
    return;
  }
  while (c.p < c.end && !c.fail) {
    skip_ws(c);
    const char *ks, *ke;
    if (!scan_string(c, &ks, &ke)) {
      c.fail = true;
      return;
    }
    skip_ws(c);
    if (c.p >= c.end || *c.p != ':') {
      c.fail = true;
      return;
    }
    ++c.p;
    skip_value(c);
    if (c.fail) return;
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') {
      ++c.p;
      continue;
    }
    if (c.p < c.end && *c.p == '}') {
      ++c.p;
      return;
    }
    c.fail = true;
    return;
  }
  c.fail = true;
}

// Parse one JSON value into a JVal (only as much structure as the
// packer needs: scalar int/bool vs list-of-scalars vs everything-else).
void parse_value(Cursor& c, JVal& out) {
  skip_ws(c);
  if (c.p >= c.end) {
    c.fail = true;
    return;
  }
  char ch = *c.p;
  if (ch == 'n') {  // null
    if (c.end - c.p >= 4 && std::memcmp(c.p, "null", 4) == 0) {
      c.p += 4;
      out.kind = VKind::NONE;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == 't') {
    if (c.end - c.p >= 4 && std::memcmp(c.p, "true", 4) == 0) {
      c.p += 4;
      out.kind = VKind::INT;  // isinstance(True, int) in the Python twin
      out.i = 1;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == 'f') {
    if (c.end - c.p >= 5 && std::memcmp(c.p, "false", 5) == 0) {
      c.p += 5;
      out.kind = VKind::INT;
      out.i = 0;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == '"') {
    const char *s, *e;
    if (!scan_string(c, &s, &e)) {
      c.fail = true;
      return;
    }
    out.kind = VKind::OTHER;
    return;
  }
  if (ch == '{') {
    parse_object(c);
    out.kind = VKind::OTHER;
    return;
  }
  if (ch == '[') {
    ++c.p;
    out.kind = VKind::LIST;
    skip_ws(c);
    if (c.p < c.end && *c.p == ']') {
      ++c.p;
      return;  // empty list
    }
    while (c.p < c.end && !c.fail) {
      JVal elem;
      parse_value(c, elem);
      if (c.fail) return;
      if (elem.kind == VKind::INT) {
        out.elems.push_back(elem.i);
        out.elem_is_int.push_back(1);
      } else {
        out.elems.push_back(NO_VALUE);
        out.elem_is_int.push_back(0);
      }
      skip_ws(c);
      if (c.p < c.end && *c.p == ',') {
        ++c.p;
        continue;
      }
      if (c.p < c.end && *c.p == ']') {
        ++c.p;
        return;
      }
      c.fail = true;
      return;
    }
    c.fail = true;
    return;
  }
  // number
  bool int_ok = false;
  long long v = scan_number(c, &int_ok);
  if (c.fail || c.overflow) return;
  if (int_ok) {
    out.kind = VKind::INT;
    out.i = v;
  } else {
    out.kind = VKind::OTHER;  // float: not isinstance(v, int) -> NO_VALUE
  }
}

void skip_value(Cursor& c) {
  JVal scratch;
  parse_value(c, scratch);
}

int type_code(const char* s, size_t n) {
  if (n == 6 && std::memcmp(s, "invoke", 6) == 0) return 0;
  if (n == 2 && std::memcmp(s, "ok", 2) == 0) return 1;
  if (n == 4 && std::memcmp(s, "fail", 4) == 0) return 2;
  if (n == 4 && std::memcmp(s, "info", 4) == 0) return 3;
  return -1;
}

int f_code(const char* s, size_t n) {
  switch (n) {
    case 7:
      if (std::memcmp(s, "enqueue", 7) == 0) return 0;
      if (std::memcmp(s, "dequeue", 7) == 0) return 1;
      if (std::memcmp(s, "acquire", 7) == 0) return 9;
      if (std::memcmp(s, "release", 7) == 0) return 10;
      break;
    case 5:
      if (std::memcmp(s, "drain", 5) == 0) return 2;
      if (std::memcmp(s, "start", 5) == 0) return 3;
      break;
    case 4:
      if (std::memcmp(s, "stop", 4) == 0) return 4;
      if (std::memcmp(s, "read", 4) == 0) return 7;
      break;
    case 3:
      if (std::memcmp(s, "log", 3) == 0) return 5;
      if (std::memcmp(s, "txn", 3) == 0) return 8;
      break;
    case 6:
      if (std::memcmp(s, "append", 6) == 0) return 6;
      break;
  }
  return -1;
}

inline long long floordiv_ms(long long ns) {
  long long q = ns / 1000000;
  if (ns % 1000000 != 0 && ns < 0) --q;  // numpy // floors; C trunc's
  return q;
}

struct PerProc {
  int last_type = -1;
  long long last_time = -1;
};

}  // namespace

extern "C" {

typedef struct {
  int32_t* rows;     // n_rows * 8, row-major; owned by the result
  int64_t n_rows;
  int32_t workload;  // 0 queue, 1 stream, 2 elle, 3 mutex
  int32_t err;       // Err enum; non-zero => rows is NULL
  int64_t err_line;  // 1-based line of the first error (0 if n/a)
} JtPackResult;

// Pack one history.jsonl into rows.  Caller frees with jt_pack_free.
JtPackResult* jt_pack_file(const char* path) {
  auto* res = static_cast<JtPackResult*>(std::calloc(1, sizeof(JtPackResult)));
  if (!res) return nullptr;

  FILE* fh = std::fopen(path, "rb");
  if (!fh) {
    res->err = ERR_IO;
    return res;
  }

  std::vector<int32_t> rows;
  rows.reserve(1 << 14);
  std::unordered_map<long long, PerProc> last;
  int workload = 0;

  std::string buf;
  buf.reserve(1 << 20);
  char chunk[1 << 16];
  size_t got;
  int64_t line_no = 0;
  bool done_reading = false;
  size_t pos = 0;  // consumed prefix of buf — lines are read in place and
                   // the buffer compacted once per refill, not per line

  auto fail = [&](int32_t err) {
    std::fclose(fh);
    res->err = err;
    res->err_line = line_no;
    return res;
  };

  while (true) {
    // refill until we hold at least one full line past `pos` (or EOF)
    size_t nl = buf.find('\n', pos);
    while (nl == std::string::npos && !done_reading) {
      if (pos > 0) {  // compact once per refill, not per line
        buf.erase(0, pos);
        pos = 0;
      }
      size_t scan_from = buf.size();
      got = std::fread(chunk, 1, sizeof(chunk), fh);
      if (got == 0) {
        if (std::ferror(fh)) return fail(ERR_IO);
        done_reading = true;
        break;
      }
      buf.append(chunk, got);
      nl = buf.find('\n', scan_from);
    }
    size_t line_end = (nl == std::string::npos) ? buf.size() : nl;
    if (line_end <= pos && done_reading) break;

    // one line in buf[pos, line_end)
    ++line_no;
    const char* ls = buf.data() + pos;
    const char* le = buf.data() + line_end;
    // strip()
    while (ls < le && (*ls == ' ' || *ls == '\t' || *ls == '\r')) ++ls;
    while (le > ls &&
           (le[-1] == ' ' || le[-1] == '\t' || le[-1] == '\r'))
      --le;
    if (ls < le) {
      Cursor c{ls, le};
      skip_ws(c);
      if (c.p >= c.end || *c.p != '{') return fail(ERR_PARSE);
      ++c.p;

      long long op_index = -1, op_process = -1, op_time = -1;
      int op_type = -1, op_f = -1;
      JVal value;
      bool saw_type = false, saw_f = false;

      skip_ws(c);
      if (c.p < c.end && *c.p == '}') {
        ++c.p;  // empty object: missing "type" -> Python KeyError
        return fail(ERR_PARSE);
      }
      while (c.p < c.end && !c.fail) {
        skip_ws(c);
        const char *ks, *ke;
        if (!scan_string(c, &ks, &ke)) return fail(ERR_PARSE);
        skip_ws(c);
        if (c.p >= c.end || *c.p != ':') return fail(ERR_PARSE);
        ++c.p;
        size_t klen = static_cast<size_t>(ke - ks);
        // keys are matched on their RAW span; a \u-escaped spelling of
        // "value"/"process"/… would dodge the match and yield a wrong
        // matrix — any escaped key falls back to the canonical parser
        if (std::memchr(ks, '\\', klen) != nullptr) return fail(ERR_PARSE);
        skip_ws(c);
        if (klen == 4 && std::memcmp(ks, "type", 4) == 0) {
          const char *vs, *ve;
          if (!scan_string(c, &vs, &ve)) return fail(ERR_PARSE);
          op_type = type_code(vs, static_cast<size_t>(ve - vs));
          if (op_type < 0) return fail(ERR_PARSE);
          saw_type = true;
        } else if (klen == 1 && *ks == 'f') {
          const char *vs, *ve;
          if (!scan_string(c, &vs, &ve)) return fail(ERR_PARSE);
          op_f = f_code(vs, static_cast<size_t>(ve - vs));
          if (op_f < 0) return fail(ERR_PARSE);
          saw_f = true;
        } else if (klen == 7 && std::memcmp(ks, "process", 7) == 0) {
          JVal v;
          parse_value(c, v);
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail || v.kind != VKind::INT) return fail(ERR_PARSE);
          op_process = v.i;
        } else if (klen == 4 && std::memcmp(ks, "time", 4) == 0) {
          JVal v;
          parse_value(c, v);
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail || v.kind != VKind::INT) return fail(ERR_PARSE);
          op_time = v.i;
        } else if (klen == 5 && std::memcmp(ks, "index", 5) == 0) {
          JVal v;
          parse_value(c, v);
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail || v.kind != VKind::INT) return fail(ERR_PARSE);
          op_index = v.i;
        } else if (klen == 5 && std::memcmp(ks, "value", 5) == 0) {
          value = JVal{};  // duplicate "value" keys: last wins, like
          parse_value(c, value);  // json.loads — never accumulate
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail) return fail(ERR_PARSE);
        } else {
          skip_value(c);  // e.g. "error"
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail) return fail(ERR_PARSE);
        }
        skip_ws(c);
        if (c.p < c.end && *c.p == ',') {
          ++c.p;
          continue;
        }
        if (c.p < c.end && *c.p == '}') {
          ++c.p;
          break;
        }
        return fail(ERR_PARSE);
      }
      if (c.fail) return fail(ERR_PARSE);
      skip_ws(c);
      if (c.p != c.end) return fail(ERR_PARSE);  // trailing junk
      if (!saw_type || !saw_f) return fail(ERR_PARSE);  // Python KeyError

      // ---- the op is parsed; now the _rows_for semantics ------------
      if (workload == 0) {
        if (op_f == 6 || op_f == 7)
          workload = 1;  // stream
        else if (op_f == 8)
          workload = 2;  // elle
        else if (op_f == 9 || op_f == 10)
          workload = 3;  // mutex
      }

      long long t_ms = op_time >= 0 ? op_time / 1000000 : -1;
      if (t_ms > INT32_MAX) return fail(ERR_OVERFLOW);
      if (op_index > INT32_MAX || op_index < INT32_MIN ||
          op_process > INT32_MAX || op_process < INT32_MIN)
        return fail(ERR_PARSE);  // Python: np.asarray(..., np.int32) raises

      long long lat = -1;
      auto it = last.find(op_process);
      if (op_type != T_INVOKE && it != last.end() &&
          it->second.last_type == T_INVOKE && it->second.last_time >= 0 &&
          op_time >= 0)
        lat = floordiv_ms(op_time - it->second.last_time);
      last[op_process] = PerProc{op_type, op_time};

      auto push_row = [&](long long v, int first) {
        if (v > INT32_MAX || v < INT32_MIN) {
          return false;  // value outside int32: OverflowError in Python
        }
        rows.push_back(static_cast<int32_t>(op_index));
        rows.push_back(static_cast<int32_t>(op_process));
        rows.push_back(static_cast<int32_t>(op_type));
        rows.push_back(static_cast<int32_t>(op_f));
        rows.push_back(static_cast<int32_t>(v));
        rows.push_back(static_cast<int32_t>(t_ms));
        // latency is int64 in the Python packer and narrowed with
        // .astype(np.int32), which wraps — static_cast matches
        rows.push_back(first ? static_cast<int32_t>(lat) : -1);
        rows.push_back(first);
        return true;
      };
      bool ok;
      if (value.kind == VKind::LIST) {
        if (value.elems.empty()) {
          ok = push_row(NO_VALUE, 1);
        } else {
          ok = true;
          for (size_t k = 0; ok && k < value.elems.size(); ++k)
            ok = push_row(value.elems[k], k == 0 ? 1 : 0);
        }
      } else if (value.kind == VKind::INT) {
        ok = push_row(value.i, 1);
      } else {  // NONE / OTHER
        ok = push_row(NO_VALUE, 1);
      }
      if (!ok) return fail(ERR_OVERFLOW);
    }

    if (nl == std::string::npos) break;  // consumed the final line
    pos = nl + 1;
  }
  std::fclose(fh);

  res->n_rows = static_cast<int64_t>(rows.size() / 8);
  if (res->n_rows > 0) {
    res->rows = static_cast<int32_t*>(
        std::malloc(rows.size() * sizeof(int32_t)));
    if (!res->rows) {
      res->err = ERR_IO;
      return res;
    }
    std::memcpy(res->rows, rows.data(), rows.size() * sizeof(int32_t));
  }
  res->workload = workload;
  return res;
}

void jt_pack_free(JtPackResult* r) {
  if (!r) return;
  std::free(r->rows);
  std::free(r);
}

}  // extern "C"
