// Native history packer: history.jsonl -> [n, 8] int32 row matrix.
//
// C++ twin of jepsen_tpu/history/rows.py::_rows_for composed with the
// JSONL reader (store.py::read_history_jsonl) and the workload
// classifier (ops.py::workload_of), fused into one streaming pass so a
// fresh pack never materializes Python op objects at all.  The hot cost
// of the batched-replay north star's fresh path is JSON parsing on the
// host (the reference's analogue is jepsen's EDN history read before
// checker/check runs); this parser reads ~2 GB of JSONL at native
// speed where Python's json module is the 1-core bottleneck.
//
// Semantics contract (differential-tested in tests/test_fastpack.py
// against the Python packer on every workload family):
//   - row schema: index, process, type, f, value, time_ms, latency_ms,
//     first  (int32 each)
//   - completion latency: against the immediately preceding op of the
//     same process iff that op is an INVOKE and both timestamps are
//     valid; floor division to ms (matches numpy int64 //)
//   - value explosion: scalar int -> one row; bool -> 1/0; null/absent/
//     float/string/object -> NO_VALUE; list -> one row per element
//     (elements: int or bool kept, anything else NO_VALUE); empty
//     list -> a single NO_VALUE row; `first` flags the first row of
//     each op, and latency_ms is -1 on non-first rows
//   - any value or time_ms outside int32 -> OVERFLOW error (the Python
//     packer raises OverflowError; the binding falls back so the
//     Python error path stays the single source of truth)
//   - any parse irregularity (unknown type/f string, non-object line,
//     malformed JSON, non-int process) -> PARSE error; the binding
//     falls back to the Python packer, which raises its own exception
//   - workload: first op whose f is append/read -> stream, txn ->
//     elle, acquire/release -> mutex; else queue
//
// Reference tie-in (same as rows.py): the op schema mirrors jepsen op
// maps (rabbitmq.clj:191-215,245-248); dense-int values are what make
// histories tensorizable (Utils.java:443,496,532,584).

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <utility>
#include <vector>

namespace {

constexpr int32_t NO_VALUE = -1;

// OpType / OpF integer codes (ops.py enums)
constexpr int T_INVOKE = 0;

enum Err : int32_t {
  OK = 0,
  ERR_IO = 1,
  ERR_PARSE = 2,
  ERR_OVERFLOW = 3,
  // a sibling .jtc columnar substrate exists and is stat-fresh but fails
  // its structural/CRC validation: the binding returns None and the
  // Python loader (history/columnar.py) re-detects the corruption and
  // LOGS it before any legacy re-parse — never a silent fallback
  ERR_JTC = 4,
};

enum class VKind { NONE, INT, OTHER, LIST };

struct JVal {
  VKind kind = VKind::NONE;
  long long i = 0;
  // list elements: (is_int, value) pairs; non-int elements carry NO_VALUE
  std::vector<long long> elems;
  std::vector<uint8_t> elem_is_int;
};

struct Cursor {
  const char* p;
  const char* end;
  bool fail = false;
  bool overflow = false;
};

inline void skip_ws(Cursor& c) {
  while (c.p < c.end &&
         (*c.p == ' ' || *c.p == '\t' || *c.p == '\r' || *c.p == '\n'))
    ++c.p;
}

inline bool is_hex(char ch) {
  return (ch >= '0' && ch <= '9') || (ch >= 'a' && ch <= 'f') ||
         (ch >= 'A' && ch <= 'F');
}

// Scan a JSON string (cursor on the opening quote); returns the raw
// (still-escaped) span in [*s, *e) excluding quotes.  Validates what
// Python's json module validates — legal escapes only, no raw control
// characters — so a file the canonical parser rejects is never
// silently accepted here.
bool scan_string(Cursor& c, const char** s, const char** e) {
  if (c.p >= c.end || *c.p != '"') return false;
  ++c.p;
  *s = c.p;
  while (c.p < c.end) {
    char ch = *c.p;
    if (ch == '\\') {
      if (c.p + 1 >= c.end) return false;
      char esc = c.p[1];
      if (esc == 'u') {
        if (c.p + 5 >= c.end || !is_hex(c.p[2]) || !is_hex(c.p[3]) ||
            !is_hex(c.p[4]) || !is_hex(c.p[5]))
          return false;
        c.p += 6;
        continue;
      }
      if (esc != '"' && esc != '\\' && esc != '/' && esc != 'b' &&
          esc != 'f' && esc != 'n' && esc != 'r' && esc != 't')
        return false;
      c.p += 2;
      continue;
    }
    if (ch == '"') {
      *e = c.p;
      ++c.p;
      return true;
    }
    if (static_cast<unsigned char>(ch) < 0x20) return false;  // raw control
    ++c.p;
  }
  return false;
}

// Parse a number with the exact JSON grammar (RFC 8259: '-'? int frac?
// exp?, no leading zeros, no leading '+') — anything the canonical
// Python parser rejects must set c.fail so the binding falls back.
// int_ok=false when it is a float (or out of int64 range -> overflow).
long long scan_number(Cursor& c, bool* int_ok) {
  const char* start = c.p;
  *int_ok = false;
  if (c.p < c.end && *c.p == '-') ++c.p;
  // int part: '0' | [1-9][0-9]*
  if (c.p >= c.end || *c.p < '0' || *c.p > '9') {
    c.fail = true;
    return 0;
  }
  if (*c.p == '0') {
    ++c.p;
    if (c.p < c.end && *c.p >= '0' && *c.p <= '9') {
      c.fail = true;  // leading zero: json.loads rejects "01"
      return 0;
    }
  } else {
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
  }
  const char* int_end = c.p;
  bool is_float = false;
  if (c.p < c.end && *c.p == '.') {
    is_float = true;
    ++c.p;
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') {
      c.fail = true;  // "1." is not JSON
      return 0;
    }
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
  }
  if (c.p < c.end && (*c.p == 'e' || *c.p == 'E')) {
    is_float = true;
    ++c.p;
    if (c.p < c.end && (*c.p == '-' || *c.p == '+')) ++c.p;
    if (c.p >= c.end || *c.p < '0' || *c.p > '9') {
      c.fail = true;  // "1e" / "1e+" are not JSON
      return 0;
    }
    while (c.p < c.end && *c.p >= '0' && *c.p <= '9') ++c.p;
  }
  if (is_float) return 0;
  errno = 0;
  char* endp = nullptr;
  std::string tmp(start, int_end - start);  // bounded copy for strtoll
  long long v = std::strtoll(tmp.c_str(), &endp, 10);
  if (errno == ERANGE) {
    c.overflow = true;  // int beyond int64: Python raises OverflowError
    return 0;           // at np.asarray — binding falls back to raise
  }
  if (endp == nullptr || *endp != '\0') {
    c.fail = true;
    return 0;
  }
  *int_ok = true;
  return v;
}

void skip_value(Cursor& c);

// Parse (and discard) a JSON object with full structural validation —
// a malformed nested object must fall back to the canonical parser,
// never be skipped over.
void parse_object(Cursor& c) {
  ++c.p;  // cursor was on '{'
  skip_ws(c);
  if (c.p < c.end && *c.p == '}') {
    ++c.p;
    return;
  }
  while (c.p < c.end && !c.fail) {
    skip_ws(c);
    const char *ks, *ke;
    if (!scan_string(c, &ks, &ke)) {
      c.fail = true;
      return;
    }
    skip_ws(c);
    if (c.p >= c.end || *c.p != ':') {
      c.fail = true;
      return;
    }
    ++c.p;
    skip_value(c);
    if (c.fail) return;
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') {
      ++c.p;
      continue;
    }
    if (c.p < c.end && *c.p == '}') {
      ++c.p;
      return;
    }
    c.fail = true;
    return;
  }
  c.fail = true;
}

// Parse one JSON value into a JVal (only as much structure as the
// packer needs: scalar int/bool vs list-of-scalars vs everything-else).
void parse_value(Cursor& c, JVal& out) {
  skip_ws(c);
  if (c.p >= c.end) {
    c.fail = true;
    return;
  }
  char ch = *c.p;
  if (ch == 'n') {  // null
    if (c.end - c.p >= 4 && std::memcmp(c.p, "null", 4) == 0) {
      c.p += 4;
      out.kind = VKind::NONE;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == 't') {
    if (c.end - c.p >= 4 && std::memcmp(c.p, "true", 4) == 0) {
      c.p += 4;
      out.kind = VKind::INT;  // isinstance(True, int) in the Python twin
      out.i = 1;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == 'f') {
    if (c.end - c.p >= 5 && std::memcmp(c.p, "false", 5) == 0) {
      c.p += 5;
      out.kind = VKind::INT;
      out.i = 0;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == '"') {
    const char *s, *e;
    if (!scan_string(c, &s, &e)) {
      c.fail = true;
      return;
    }
    out.kind = VKind::OTHER;
    return;
  }
  if (ch == '{') {
    parse_object(c);
    out.kind = VKind::OTHER;
    return;
  }
  if (ch == '[') {
    ++c.p;
    out.kind = VKind::LIST;
    skip_ws(c);
    if (c.p < c.end && *c.p == ']') {
      ++c.p;
      return;  // empty list
    }
    while (c.p < c.end && !c.fail) {
      JVal elem;
      parse_value(c, elem);
      if (c.fail) return;
      if (elem.kind == VKind::INT) {
        out.elems.push_back(elem.i);
        out.elem_is_int.push_back(1);
      } else {
        out.elems.push_back(NO_VALUE);
        out.elem_is_int.push_back(0);
      }
      skip_ws(c);
      if (c.p < c.end && *c.p == ',') {
        ++c.p;
        continue;
      }
      if (c.p < c.end && *c.p == ']') {
        ++c.p;
        return;
      }
      c.fail = true;
      return;
    }
    c.fail = true;
    return;
  }
  // number
  bool int_ok = false;
  long long v = scan_number(c, &int_ok);
  if (c.fail || c.overflow) return;
  if (int_ok) {
    out.kind = VKind::INT;
    out.i = v;
  } else {
    out.kind = VKind::OTHER;  // float: not isinstance(v, int) -> NO_VALUE
  }
}

void skip_value(Cursor& c) {
  JVal scratch;
  parse_value(c, scratch);
}

int type_code(const char* s, size_t n) {
  if (n == 6 && std::memcmp(s, "invoke", 6) == 0) return 0;
  if (n == 2 && std::memcmp(s, "ok", 2) == 0) return 1;
  if (n == 4 && std::memcmp(s, "fail", 4) == 0) return 2;
  if (n == 4 && std::memcmp(s, "info", 4) == 0) return 3;
  return -1;
}

int f_code(const char* s, size_t n) {
  switch (n) {
    case 7:
      if (std::memcmp(s, "enqueue", 7) == 0) return 0;
      if (std::memcmp(s, "dequeue", 7) == 0) return 1;
      if (std::memcmp(s, "acquire", 7) == 0) return 9;
      if (std::memcmp(s, "release", 7) == 0) return 10;
      break;
    case 5:
      if (std::memcmp(s, "drain", 5) == 0) return 2;
      if (std::memcmp(s, "start", 5) == 0) return 3;
      break;
    case 4:
      if (std::memcmp(s, "stop", 4) == 0) return 4;
      if (std::memcmp(s, "read", 4) == 0) return 7;
      break;
    case 3:
      if (std::memcmp(s, "log", 3) == 0) return 5;
      if (std::memcmp(s, "txn", 3) == 0) return 8;
      break;
    case 6:
      if (std::memcmp(s, "append", 6) == 0) return 6;
      break;
  }
  return -1;
}

inline long long floordiv_ms(long long ns) {
  long long q = ns / 1000000;
  if (ns % 1000000 != 0 && ns < 0) --q;  // numpy // floors; C trunc's
  return q;
}

struct PerProc {
  int last_type = -1;
  long long last_time = -1;
};

// ---------------------------------------------------------------------------
// Deep JSON tree (for the elle txn micro-op lists and stream read pairs,
// whose nesting the flat JVal deliberately collapses).  Structure is kept
// exactly as deep as the checkers inspect; strings are raw spans into the
// line buffer — an ESCAPED string sets c.fail so the binding falls back
// to the canonical parser (the only strings the checkers compare are
// "append"/"r"/"full", none of which are ever escaped by the writer).
// ---------------------------------------------------------------------------

struct JNode {
  enum K { NUL, INT, STR, LIST, OTHER } k = NUL;
  long long i = 0;
  const char* s = nullptr;  // STR: raw span (escape-free by construction)
  size_t slen = 0;
  std::vector<JNode> items;  // LIST

  bool is_str(const char* lit, size_t n) const {
    return k == STR && slen == n && std::memcmp(s, lit, n) == 0;
  }
};

void parse_node(Cursor& c, JNode& out, int depth = 0) {
  if (depth > 24) {  // micro-op nesting is ≤ 3; anything deeper is not ours
    c.fail = true;
    return;
  }
  skip_ws(c);
  if (c.p >= c.end) {
    c.fail = true;
    return;
  }
  char ch = *c.p;
  if (ch == 'n') {
    if (c.end - c.p >= 4 && std::memcmp(c.p, "null", 4) == 0) {
      c.p += 4;
      out.k = JNode::NUL;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == 't') {
    if (c.end - c.p >= 4 && std::memcmp(c.p, "true", 4) == 0) {
      c.p += 4;
      out.k = JNode::INT;  // isinstance(True, int) in the Python twin
      out.i = 1;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == 'f') {
    if (c.end - c.p >= 5 && std::memcmp(c.p, "false", 5) == 0) {
      c.p += 5;
      out.k = JNode::INT;
      out.i = 0;
      return;
    }
    c.fail = true;
    return;
  }
  if (ch == '"') {
    const char *s, *e;
    if (!scan_string(c, &s, &e)) {
      c.fail = true;
      return;
    }
    if (std::memchr(s, '\\', static_cast<size_t>(e - s)) != nullptr) {
      c.fail = true;  // escaped string: fall back (see header comment)
      return;
    }
    out.k = JNode::STR;
    out.s = s;
    out.slen = static_cast<size_t>(e - s);
    return;
  }
  if (ch == '{') {
    parse_object(c);
    out.k = JNode::OTHER;
    return;
  }
  if (ch == '[') {
    ++c.p;
    out.k = JNode::LIST;
    skip_ws(c);
    if (c.p < c.end && *c.p == ']') {
      ++c.p;
      return;
    }
    while (c.p < c.end && !c.fail) {
      out.items.emplace_back();
      parse_node(c, out.items.back(), depth + 1);
      if (c.fail) return;
      skip_ws(c);
      if (c.p < c.end && *c.p == ',') {
        ++c.p;
        continue;
      }
      if (c.p < c.end && *c.p == ']') {
        ++c.p;
        return;
      }
      c.fail = true;
      return;
    }
    c.fail = true;
    return;
  }
  bool int_ok = false;
  long long v = scan_number(c, &int_ok);
  if (c.fail || c.overflow) return;
  if (int_ok) {
    out.k = JNode::INT;
    out.i = v;
  } else {
    out.k = JNode::OTHER;  // float
  }
}

// One parsed op line for the deep-value entry points.
struct OpView {
  int type = -1;
  int f = -1;
  long long process = -1;  // from_json's NEMESIS_PROCESS default
  JNode value;             // NUL when absent
  bool ok = false;
};

// Parse one op JSON object (deep value).  Mirrors the key handling of
// jt_pack_file: escaped keys and unknown type/f names fail (the binding
// falls back to the canonical Python parser).
bool parse_op_deep(Cursor& c, OpView& op) {
  skip_ws(c);
  if (c.p >= c.end || *c.p != '{') return false;
  ++c.p;
  bool saw_type = false, saw_f = false;
  skip_ws(c);
  if (c.p < c.end && *c.p == '}') return false;  // missing "type"
  while (c.p < c.end && !c.fail) {
    skip_ws(c);
    const char *ks, *ke;
    if (!scan_string(c, &ks, &ke)) return false;
    skip_ws(c);
    if (c.p >= c.end || *c.p != ':') return false;
    ++c.p;
    size_t klen = static_cast<size_t>(ke - ks);
    if (std::memchr(ks, '\\', klen) != nullptr) return false;
    skip_ws(c);
    if (klen == 4 && std::memcmp(ks, "type", 4) == 0) {
      const char *vs, *ve;
      if (!scan_string(c, &vs, &ve)) return false;
      op.type = type_code(vs, static_cast<size_t>(ve - vs));
      if (op.type < 0) return false;
      saw_type = true;
    } else if (klen == 1 && *ks == 'f') {
      const char *vs, *ve;
      if (!scan_string(c, &vs, &ve)) return false;
      op.f = f_code(vs, static_cast<size_t>(ve - vs));
      if (op.f < 0) return false;
      saw_f = true;
    } else if (klen == 7 && std::memcmp(ks, "process", 7) == 0) {
      JVal v;
      parse_value(c, v);
      if (c.fail || c.overflow || v.kind != VKind::INT) return false;
      op.process = v.i;
    } else if (klen == 5 && std::memcmp(ks, "value", 5) == 0) {
      op.value = JNode{};  // duplicate keys: last wins, like json.loads
      parse_node(c, op.value);
      if (c.fail || c.overflow) return false;
    } else {
      skip_value(c);  // index / time / error — unused by these checkers
      if (c.fail || c.overflow) return false;
    }
    skip_ws(c);
    if (c.p < c.end && *c.p == ',') {
      ++c.p;
      continue;
    }
    if (c.p < c.end && *c.p == '}') {
      ++c.p;
      skip_ws(c);
      if (c.p != c.end) return false;  // trailing junk
      if (!saw_type || !saw_f) return false;
      op.ok = true;
      return true;
    }
    return false;
  }
  return false;
}

// Streaming line iterator over a JSONL file; calls cb(op, pos) per
// non-blank line (pos = 0-based op position, matching enumerate() over
// read_history_jsonl).  Returns OK / ERR_* with the failing line in
// *err_line.
template <typename CB>
int for_each_op(const char* path, CB&& cb, int64_t* err_line) {
  FILE* fh = std::fopen(path, "rb");
  if (!fh) return ERR_IO;
  std::string buf;
  buf.reserve(1 << 20);
  char chunk[1 << 16];
  size_t got;
  int64_t line_no = 0;
  long long pos = 0;
  bool done_reading = false;
  size_t cons = 0;
  int err = OK;
  while (true) {
    size_t nl = buf.find('\n', cons);
    while (nl == std::string::npos && !done_reading) {
      if (cons > 0) {
        buf.erase(0, cons);
        cons = 0;
      }
      size_t scan_from = buf.size();
      got = std::fread(chunk, 1, sizeof(chunk), fh);
      if (got == 0) {
        if (std::ferror(fh)) {
          std::fclose(fh);
          *err_line = line_no;
          return ERR_IO;
        }
        done_reading = true;
        break;
      }
      buf.append(chunk, got);
      nl = buf.find('\n', scan_from);
    }
    size_t line_end = (nl == std::string::npos) ? buf.size() : nl;
    if (line_end <= cons && done_reading) break;
    ++line_no;
    const char* ls = buf.data() + cons;
    const char* le = buf.data() + line_end;
    while (ls < le && (*ls == ' ' || *ls == '\t' || *ls == '\r')) ++ls;
    while (le > ls && (le[-1] == ' ' || le[-1] == '\t' || le[-1] == '\r'))
      --le;
    if (ls < le) {
      Cursor c{ls, le};
      OpView op;
      if (!parse_op_deep(c, op)) {
        err = c.overflow ? ERR_OVERFLOW : ERR_PARSE;
        *err_line = line_no;
        break;
      }
      if (!cb(op, pos)) {
        err = ERR_PARSE;  // structure the checker twin cannot map
        *err_line = line_no;
        break;
      }
      ++pos;
    }
    if (nl == std::string::npos) break;
    cons = nl + 1;
  }
  std::fclose(fh);
  return err;
}

// test hook: JT_PACK_FAKE_OOM=1 makes every result-array allocation fail,
// so the malloc-failure path (err set, Python binding falls back to the
// pure-Python packer) is exercisable without exhausting real memory
bool fake_oom() {
  const char* e = std::getenv("JT_PACK_FAKE_OOM");
  return e && *e && *e != '0';
}

void* checked_malloc(size_t n) {
  if (fake_oom()) return nullptr;
  return std::malloc(n);
}

int32_t* copy_i32(const std::vector<int32_t>& v) {
  if (v.empty()) return nullptr;
  auto* p = static_cast<int32_t*>(checked_malloc(v.size() * sizeof(int32_t)));
  if (p) std::memcpy(p, v.data(), v.size() * sizeof(int32_t));
  return p;
}

int64_t* copy_i64(const std::vector<long long>& v) {
  if (v.empty()) return nullptr;
  auto* p = static_cast<int64_t*>(checked_malloc(v.size() * sizeof(int64_t)));
  if (p) {
    for (size_t i = 0; i < v.size(); ++i) p[i] = v[i];
  }
  return p;
}

}  // namespace

// ---------------------------------------------------------------------------
// .jtc columnar substrate fast path (history/columnar.py is the format
// owner — layout documented there).  When a history source has a
// stat-fresh sibling .jtc, the packers below serve its CRC-verified
// column blocks straight into the result arena instead of parsing JSONL
// — this is what makes the multi-file thread-pool entry points
// (jt_*_files / jt_*_files_part) a bytes-to-staging-buffers pipe with
// zero parse in the loop.  Freshness here is the stat fast path ONLY
// (.jtc newer than the source AND the header (size, mtime_ns) stamp
// matches); anything the fast path cannot prove fresh falls through to
// the normal parse.  A fresh-but-invalid file returns ERR_JTC (loud —
// see the Err enum).
// ---------------------------------------------------------------------------

#include <sys/stat.h>

#include <array>

namespace {

uint32_t jtc_crc32(const uint8_t* p, size_t n) {
  static const std::array<uint32_t, 256> table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
    return t;
  }();
  uint32_t crc = 0xFFFFFFFFu;
  while (n--) crc = table[(crc ^ *p++) & 0xFFu] ^ (crc >> 8);
  return ~crc;
}

constexpr size_t kJtcHeader = 96;
constexpr size_t kJtcSection = 48;
constexpr uint32_t kJtcVersion = 1;

template <typename T>
T jtc_read_le(const uint8_t* p) {
  T v;
  std::memcpy(&v, p, sizeof(T));  // x86/arm64 linux: little-endian
  return v;
}

struct JtcSec {
  uint32_t kind, dtype;
  uint64_t rows, cols, off, len;
  uint32_t crc, flags;
};

struct JtcView {
  std::vector<uint8_t> buf;
  int32_t workload = -1;
  std::vector<JtcSec> secs;
  const JtcSec* find(uint32_t kind) const {
    for (const auto& s : secs)
      if (s.kind == kind) return &s;
    return nullptr;
  }
  const uint8_t* data(const JtcSec& s) const { return buf.data() + s.off; }
};

long long stat_mtime_ns(const struct stat& st) {
  return static_cast<long long>(st.st_mtim.tv_sec) * 1000000000LL +
         st.st_mtim.tv_nsec;
}

// per-process substrate toggle (jt_jtc_disable): the Python side sets
// it around native batch calls whose caller asked for a genuine parse
// (check_sources(use_cache=False)) — the env var alone is process-wide
// and cannot express a per-call intent
std::atomic<int32_t> g_jtc_disabled{0};

// 0 = no fresh .jtc (fall through to parse), 1 = loaded + verified,
// 2 = stat-fresh but corrupt/incompatible (caller returns ERR_JTC)
int jtc_load(const char* src_path, JtcView* out) {
  if (g_jtc_disabled.load(std::memory_order_relaxed)) return 0;
  const char* no = std::getenv("JEPSEN_TPU_NO_JTC");
  if (no && *no && *no != '0') return 0;
  std::string src(src_path);
  size_t slash = src.find_last_of('/');
  size_t dot = src.find_last_of('.');
  if (dot == std::string::npos ||
      (slash != std::string::npos && dot < slash))
    dot = src.size();
  std::string jtc = src.substr(0, dot) + ".jtc";

  struct stat st_src, st_jtc;
  if (stat(src.c_str(), &st_src) != 0) return 0;
  if (stat(jtc.c_str(), &st_jtc) != 0) return 0;
  if (stat_mtime_ns(st_jtc) <= stat_mtime_ns(st_src)) return 0;  // stale

  FILE* fh = std::fopen(jtc.c_str(), "rb");
  if (!fh) return 0;
  std::vector<uint8_t>& buf = out->buf;
  buf.clear();
  buf.reserve(static_cast<size_t>(st_jtc.st_size));
  uint8_t chunk[1 << 16];
  size_t got;
  while ((got = std::fread(chunk, 1, sizeof(chunk), fh)) > 0)
    buf.insert(buf.end(), chunk, chunk + got);
  bool rerr = std::ferror(fh) != 0;
  std::fclose(fh);
  if (rerr) return 2;

  if (buf.size() < kJtcHeader + 4) return 2;  // truncated header
  if (std::memcmp(buf.data(), "JTCF", 4) != 0) return 2;
  if (jtc_read_le<uint32_t>(buf.data() + 4) != kJtcVersion) return 2;
  out->workload = jtc_read_le<int32_t>(buf.data() + 8);
  uint32_t n_sections = jtc_read_le<uint32_t>(buf.data() + 12);
  size_t table_end = kJtcHeader + kJtcSection * n_sections;
  if (buf.size() < table_end + 4) return 2;  // truncated table
  if (jtc_crc32(buf.data(), table_end) !=
      jtc_read_le<uint32_t>(buf.data() + table_end))
    return 2;  // header checksum mismatch

  // source-identity stamp: size + mtime_ns must match the live source
  // (a mismatch is staleness, not corruption — re-parse), and the
  // basename must be the one this .jtc was packed from (jsonl vs edn
  // twins share the sibling slot)
  if (jtc_read_le<uint64_t>(buf.data() + 48) !=
          static_cast<uint64_t>(st_src.st_size) ||
      jtc_read_le<int64_t>(buf.data() + 56) != stat_mtime_ns(st_src))
    return 0;
  const char* base = src.c_str() + (slash == std::string::npos ? 0 : slash + 1);
  size_t base_len = std::strlen(base);
  if (base_len > 32) return 0;
  char name[33] = {0};
  std::memcpy(name, buf.data() + 16, 32);
  if (std::strncmp(name, base, 32) != 0 ||
      (base_len < 32 && name[base_len] != '\0'))
    return 0;

  out->secs.clear();
  size_t data_end = table_end + 4;
  for (uint32_t i = 0; i < n_sections; ++i) {
    const uint8_t* p = buf.data() + kJtcHeader + i * kJtcSection;
    JtcSec s;
    s.kind = jtc_read_le<uint32_t>(p);
    s.dtype = jtc_read_le<uint32_t>(p + 4);
    s.rows = jtc_read_le<uint64_t>(p + 8);
    s.cols = jtc_read_le<uint64_t>(p + 16);
    s.off = jtc_read_le<uint64_t>(p + 24);
    s.len = jtc_read_le<uint64_t>(p + 32);
    s.crc = jtc_read_le<uint32_t>(p + 40);
    s.flags = jtc_read_le<uint32_t>(p + 44);
    if (s.dtype > 1) return 2;
    // overflow-proof bounds/shape validation: a hostile or buggy table
    // (valid CRC, wild offsets/counts) must yield ERR_JTC, never a
    // wrapped uint64 that defeats the check and dereferences wild
    // memory (the Python reader is immune — arbitrary-precision ints)
    uint64_t item = s.dtype == 0 ? 4 : 8;
    uint64_t cols = s.cols > 1 ? s.cols : 1;
    if (s.off > buf.size() || s.len > buf.size() - s.off) return 2;
    // caps keep every product below 2^63: rows/cols are bounded by the
    // byte length they claim to describe, which is bounded by the file
    if (s.rows > (uint64_t{1} << 40) || cols > (uint64_t{1} << 20) ||
        s.rows * cols * item != s.len)
      return 2;  // truncated tail / shape mismatch
    if (jtc_crc32(buf.data() + s.off, s.len) != s.crc)
      return 2;  // payload bit flip
    size_t sec_end = static_cast<size_t>(s.off + s.len);
    if (sec_end > data_end) data_end = sec_end;
    out->secs.push_back(s);
  }
  // trailing bytes after the last payload must be exactly the digest
  // footer ("JTCD" + count + 32-byte sha256 per section + CRC); a flip
  // or tear in the footer region is corruption, never padding (legacy
  // pre-footer packs end at the last payload and skip this)
  if (buf.size() > data_end) {
    size_t foot_len = 8 + 32 * static_cast<size_t>(n_sections) + 4;
    if (buf.size() - data_end != foot_len) return 2;
    const uint8_t* f = buf.data() + data_end;
    if (std::memcmp(f, "JTCD", 4) != 0) return 2;
    if (jtc_read_le<uint32_t>(f + 4) != n_sections) return 2;
    if (jtc_crc32(f, foot_len - 4) !=
        jtc_read_le<uint32_t>(f + foot_len - 4))
      return 2;  // digest footer bit flip
  }
  return 1;
}

// copy one int32 section into a malloc'd array (the result arena's
// staging copy); false on allocation failure
bool jtc_copy_i32(const JtcView& v, const JtcSec& s, int32_t** dst) {
  *dst = nullptr;
  if (s.len == 0) return true;
  *dst = static_cast<int32_t*>(checked_malloc(s.len));
  if (!*dst) return false;
  std::memcpy(*dst, v.data(s), s.len);
  return true;
}

bool jtc_copy_i64(const JtcView& v, const JtcSec& s, int64_t** dst) {
  *dst = nullptr;
  if (s.len == 0) return true;
  *dst = static_cast<int64_t*>(checked_malloc(s.len));
  if (!*dst) return false;
  std::memcpy(*dst, v.data(s), s.len);
  return true;
}

}  // namespace

extern "C" {

typedef struct {
  int32_t* rows;     // n_rows * 8, row-major; owned by the result
  int64_t n_rows;
  int32_t workload;  // 0 queue, 1 stream, 2 elle, 3 mutex
  int32_t err;       // Err enum; non-zero => rows is NULL
  int64_t err_line;  // 1-based line of the first error (0 if n/a)
} JtPackResult;

// Pack one history.jsonl into rows.  Caller frees with jt_pack_free.
// A stat-fresh sibling .jtc serves the rows with no parse at all.
JtPackResult* jt_pack_file(const char* path) {
  auto* res = static_cast<JtPackResult*>(std::calloc(1, sizeof(JtPackResult)));
  if (!res) return nullptr;

  {
    JtcView v;
    int r = jtc_load(path, &v);
    if (r == 2) {
      res->err = ERR_JTC;
      return res;
    }
    if (r == 1) {
      const JtcSec* s = v.find(1 /* SEC_QROWS */);
      if (s && s->dtype == 0 && s->cols == 8 && v.workload >= 0 &&
          v.workload <= 3) {
        if (!jtc_copy_i32(v, *s, &res->rows)) {
          res->err = ERR_IO;  // allocation failure
          return res;
        }
        res->n_rows = static_cast<int64_t>(s->rows);
        res->workload = v.workload;
        return res;
      }
      // rows section absent (or unknown workload): parse normally
    }
  }

  FILE* fh = std::fopen(path, "rb");
  if (!fh) {
    res->err = ERR_IO;
    return res;
  }

  std::vector<int32_t> rows;
  rows.reserve(1 << 14);
  std::unordered_map<long long, PerProc> last;
  int workload = 0;

  std::string buf;
  buf.reserve(1 << 20);
  char chunk[1 << 16];
  size_t got;
  int64_t line_no = 0;
  bool done_reading = false;
  size_t pos = 0;  // consumed prefix of buf — lines are read in place and
                   // the buffer compacted once per refill, not per line

  auto fail = [&](int32_t err) {
    std::fclose(fh);
    res->err = err;
    res->err_line = line_no;
    return res;
  };

  while (true) {
    // refill until we hold at least one full line past `pos` (or EOF)
    size_t nl = buf.find('\n', pos);
    while (nl == std::string::npos && !done_reading) {
      if (pos > 0) {  // compact once per refill, not per line
        buf.erase(0, pos);
        pos = 0;
      }
      size_t scan_from = buf.size();
      got = std::fread(chunk, 1, sizeof(chunk), fh);
      if (got == 0) {
        if (std::ferror(fh)) return fail(ERR_IO);
        done_reading = true;
        break;
      }
      buf.append(chunk, got);
      nl = buf.find('\n', scan_from);
    }
    size_t line_end = (nl == std::string::npos) ? buf.size() : nl;
    if (line_end <= pos && done_reading) break;

    // one line in buf[pos, line_end)
    ++line_no;
    const char* ls = buf.data() + pos;
    const char* le = buf.data() + line_end;
    // strip()
    while (ls < le && (*ls == ' ' || *ls == '\t' || *ls == '\r')) ++ls;
    while (le > ls &&
           (le[-1] == ' ' || le[-1] == '\t' || le[-1] == '\r'))
      --le;
    if (ls < le) {
      Cursor c{ls, le};
      skip_ws(c);
      if (c.p >= c.end || *c.p != '{') return fail(ERR_PARSE);
      ++c.p;

      long long op_index = -1, op_process = -1, op_time = -1;
      int op_type = -1, op_f = -1;
      JVal value;
      bool saw_type = false, saw_f = false;

      skip_ws(c);
      if (c.p < c.end && *c.p == '}') {
        ++c.p;  // empty object: missing "type" -> Python KeyError
        return fail(ERR_PARSE);
      }
      while (c.p < c.end && !c.fail) {
        skip_ws(c);
        const char *ks, *ke;
        if (!scan_string(c, &ks, &ke)) return fail(ERR_PARSE);
        skip_ws(c);
        if (c.p >= c.end || *c.p != ':') return fail(ERR_PARSE);
        ++c.p;
        size_t klen = static_cast<size_t>(ke - ks);
        // keys are matched on their RAW span; a \u-escaped spelling of
        // "value"/"process"/… would dodge the match and yield a wrong
        // matrix — any escaped key falls back to the canonical parser
        if (std::memchr(ks, '\\', klen) != nullptr) return fail(ERR_PARSE);
        skip_ws(c);
        if (klen == 4 && std::memcmp(ks, "type", 4) == 0) {
          const char *vs, *ve;
          if (!scan_string(c, &vs, &ve)) return fail(ERR_PARSE);
          op_type = type_code(vs, static_cast<size_t>(ve - vs));
          if (op_type < 0) return fail(ERR_PARSE);
          saw_type = true;
        } else if (klen == 1 && *ks == 'f') {
          const char *vs, *ve;
          if (!scan_string(c, &vs, &ve)) return fail(ERR_PARSE);
          op_f = f_code(vs, static_cast<size_t>(ve - vs));
          if (op_f < 0) return fail(ERR_PARSE);
          saw_f = true;
        } else if (klen == 7 && std::memcmp(ks, "process", 7) == 0) {
          JVal v;
          parse_value(c, v);
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail || v.kind != VKind::INT) return fail(ERR_PARSE);
          op_process = v.i;
        } else if (klen == 4 && std::memcmp(ks, "time", 4) == 0) {
          JVal v;
          parse_value(c, v);
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail || v.kind != VKind::INT) return fail(ERR_PARSE);
          op_time = v.i;
        } else if (klen == 5 && std::memcmp(ks, "index", 5) == 0) {
          JVal v;
          parse_value(c, v);
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail || v.kind != VKind::INT) return fail(ERR_PARSE);
          op_index = v.i;
        } else if (klen == 5 && std::memcmp(ks, "value", 5) == 0) {
          value = JVal{};  // duplicate "value" keys: last wins, like
          parse_value(c, value);  // json.loads — never accumulate
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail) return fail(ERR_PARSE);
        } else {
          skip_value(c);  // e.g. "error"
          if (c.overflow) return fail(ERR_OVERFLOW);
          if (c.fail) return fail(ERR_PARSE);
        }
        skip_ws(c);
        if (c.p < c.end && *c.p == ',') {
          ++c.p;
          continue;
        }
        if (c.p < c.end && *c.p == '}') {
          ++c.p;
          break;
        }
        return fail(ERR_PARSE);
      }
      if (c.fail) return fail(ERR_PARSE);
      skip_ws(c);
      if (c.p != c.end) return fail(ERR_PARSE);  // trailing junk
      if (!saw_type || !saw_f) return fail(ERR_PARSE);  // Python KeyError

      // ---- the op is parsed; now the _rows_for semantics ------------
      if (workload == 0) {
        if (op_f == 6 || op_f == 7)
          workload = 1;  // stream
        else if (op_f == 8)
          workload = 2;  // elle
        else if (op_f == 9 || op_f == 10)
          workload = 3;  // mutex
      }

      long long t_ms = op_time >= 0 ? op_time / 1000000 : -1;
      if (t_ms > INT32_MAX) return fail(ERR_OVERFLOW);
      if (op_index > INT32_MAX || op_index < INT32_MIN ||
          op_process > INT32_MAX || op_process < INT32_MIN)
        return fail(ERR_PARSE);  // Python: np.asarray(..., np.int32) raises

      long long lat = -1;
      auto it = last.find(op_process);
      if (op_type != T_INVOKE && it != last.end() &&
          it->second.last_type == T_INVOKE && it->second.last_time >= 0 &&
          op_time >= 0)
        lat = floordiv_ms(op_time - it->second.last_time);
      last[op_process] = PerProc{op_type, op_time};

      auto push_row = [&](long long v, int first) {
        if (v > INT32_MAX || v < INT32_MIN) {
          return false;  // value outside int32: OverflowError in Python
        }
        rows.push_back(static_cast<int32_t>(op_index));
        rows.push_back(static_cast<int32_t>(op_process));
        rows.push_back(static_cast<int32_t>(op_type));
        rows.push_back(static_cast<int32_t>(op_f));
        rows.push_back(static_cast<int32_t>(v));
        rows.push_back(static_cast<int32_t>(t_ms));
        // latency is int64 in the Python packer and narrowed with
        // .astype(np.int32), which wraps — static_cast matches
        rows.push_back(first ? static_cast<int32_t>(lat) : -1);
        rows.push_back(first);
        return true;
      };
      bool ok;
      if (value.kind == VKind::LIST) {
        if (value.elems.empty()) {
          ok = push_row(NO_VALUE, 1);
        } else {
          ok = true;
          for (size_t k = 0; ok && k < value.elems.size(); ++k)
            ok = push_row(value.elems[k], k == 0 ? 1 : 0);
        }
      } else if (value.kind == VKind::INT) {
        ok = push_row(value.i, 1);
      } else {  // NONE / OTHER
        ok = push_row(NO_VALUE, 1);
      }
      if (!ok) return fail(ERR_OVERFLOW);
    }

    if (nl == std::string::npos) break;  // consumed the final line
    pos = nl + 1;
  }
  std::fclose(fh);

  res->n_rows = static_cast<int64_t>(rows.size() / 8);
  if (res->n_rows > 0) {
    res->rows = static_cast<int32_t*>(
        checked_malloc(rows.size() * sizeof(int32_t)));
    if (!res->rows) {
      res->err = ERR_IO;
      res->n_rows = 0;
      return res;
    }
    std::memcpy(res->rows, rows.data(), rows.size() * sizeof(int32_t));
  }
  res->workload = workload;
  return res;
}

void jt_pack_free(JtPackResult* r) {
  if (!r) return;
  std::free(r->rows);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Elle: history.jsonl -> inferred txn dependency graph.
//
// C++ twin of checkers/elle.py::infer_txn_graph composed with the JSONL
// reader — the host-side cost that bounded the elle family's fresh-pack
// end-to-end rate (VERDICT r4 weak #3: the device number measured
// cycle-search-only while a fresh history still paid Python parse +
// inference).  Differential contract in tests/test_fastpack.py: for any
// parseable file the edge sets, anomaly sets, and txn index must equal
// the Python twin's exactly; anything unmappable returns ERR_PARSE and
// the binding falls back.
// ---------------------------------------------------------------------------

typedef struct {
  int32_t* edges;      // n_edges * 3: (etype 0=ww 1=wr 2=rw, from, to)
  int64_t n_edges;
  int64_t* txn_index;  // history position per committed txn
  int32_t n_txns;
  int32_t* g1a;        // txn ids reading failed writes
  int32_t n_g1a;
  int32_t* g1b;        // txn ids reading intermediate versions
  int32_t n_g1b;
  int64_t* bad_keys;   // keys with prefix-incompatible observed orders
  int32_t n_bad_keys;
  int32_t err;         // Err enum; non-zero => arrays are NULL
  int64_t err_line;
} JtElleResult;

JtElleResult* jt_elle_infer_file(const char* path) {
  auto* res =
      static_cast<JtElleResult*>(std::calloc(1, sizeof(JtElleResult)));
  if (!res) return nullptr;

  // micro-op view of one committed txn
  struct Mop {
    int kind;  // 0 append(int v), 1 read(list)
    long long key;
    long long v;
    std::vector<long long> vs;  // read: int elements only (Python drops
                                // non-ints via the isinstance filter)
  };
  std::vector<std::vector<Mop>> committed;
  std::vector<long long> txn_index;
  std::unordered_set<long long> failed_values;

  // ["append", k, v] / ["r", k, [..]] with any other shape skipped —
  // exactly the len==3 + isinstance guards of the Python twin.  A txn
  // value that is not a list contributes no micro-ops.  Non-int keys
  // cannot map onto this implementation's tables: signal fallback.
  auto collect = [&](const JNode& value, std::vector<Mop>* out,
                     bool fail_txn) -> bool {
    if (value.k != JNode::LIST) return true;
    for (const JNode& m : value.items) {
      if (m.k != JNode::LIST || m.items.size() != 3) continue;
      const JNode& f = m.items[0];
      const JNode& key = m.items[1];
      const JNode& val = m.items[2];
      bool is_append = f.is_str("append", 6);
      bool is_read = f.is_str("r", 1);
      if (!is_append && !is_read) continue;
      if (key.k != JNode::INT)
        return false;  // non-int key (string/null/…): the Python twin
                       // handles — or canonically rejects — it; either
                       // way this table-based twin cannot, so fall back
      if (is_append && val.k == JNode::INT) {
        if (fail_txn) {
          failed_values.insert(val.i);
        } else if (out) {
          out->push_back(Mop{0, key.i, val.i, {}});
        }
      } else if (is_read && val.k == JNode::LIST && !fail_txn && out) {
        Mop r{1, key.i, 0, {}};
        for (const JNode& e : val.items)
          if (e.k == JNode::INT) r.vs.push_back(e.i);
        out->push_back(std::move(r));
      }
    }
    return true;
  };

  int64_t err_line = 0;
  int err = for_each_op(
      path,
      [&](const OpView& op, long long pos) -> bool {
        if (op.f != 8 /* txn */ || op.type == 0 /* invoke */) return true;
        if (op.type == 1 /* ok */) {
          committed.emplace_back();
          txn_index.push_back(pos);
          return collect(op.value, &committed.back(), false);
        }
        if (op.type == 2 /* fail */)
          return collect(op.value, nullptr, true);
        return true;  // info: indeterminate, no entries (elle's rule)
      },
      &err_line);
  if (err != OK) {
    res->err = err;
    res->err_line = err_line;
    return res;
  }

  const int n = static_cast<int>(committed.size());
  std::unordered_map<long long, int> writer_of;  // value -> txn (last wins)
  // appends_of[(t, k)] — per-txn key map
  std::vector<std::unordered_map<long long, std::vector<long long>>>
      appends(n);
  for (int t = 0; t < n; ++t)
    for (const Mop& m : committed[t])
      if (m.kind == 0) {
        writer_of[m.v] = t;
        appends[t][m.key].push_back(m.v);
      }

  // normalized reads + per-key inferred order (longest observed list,
  // first-seen wins ties — Python's strict `>` replacement)
  struct Read {
    int t;
    long long key;
    std::vector<long long> vs;
  };
  std::vector<Read> reads;
  std::unordered_map<long long, std::vector<long long>> order;
  for (int t = 0; t < n; ++t)
    for (const Mop& m : committed[t]) {
      if (m.kind != 1) continue;
      std::unordered_set<long long> own;
      auto it = appends[t].find(m.key);
      if (it != appends[t].end())
        own.insert(it->second.begin(), it->second.end());
      std::vector<long long> vs = m.vs;
      while (!vs.empty() && own.count(vs.back())) vs.pop_back();
      auto& cur = order[m.key];
      if (vs.size() > cur.size()) cur = vs;
      reads.push_back(Read{t, m.key, std::move(vs)});
    }

  std::set<std::pair<int, int>> ww, wr, rw;
  std::set<int> g1a, g1b;
  std::set<long long> bad_keys;
  std::vector<uint8_t> compatible(reads.size(), 0);
  for (size_t i = 0; i < reads.size(); ++i) {
    const Read& r = reads[i];
    const auto& ref = order[r.key];
    bool ok_prefix = r.vs.size() <= ref.size() &&
                     std::equal(r.vs.begin(), r.vs.end(), ref.begin());
    compatible[i] = ok_prefix;
    if (!ok_prefix) bad_keys.insert(r.key);
    for (long long v : r.vs)
      if (failed_values.count(v)) g1a.insert(r.t);
    if (!r.vs.empty() && ok_prefix) {
      auto w = writer_of.find(r.vs.back());
      if (w != writer_of.end() && w->second != r.t) {
        auto wk = appends[w->second].find(r.key);
        if (wk != appends[w->second].end()) {
          const auto& lst = wk->second;
          bool present =
              std::find(lst.begin(), lst.end(), r.vs.back()) != lst.end();
          if (present && r.vs.back() != lst.back()) g1b.insert(r.t);
        }
      }
    }
  }
  for (const auto& kv : order) {
    const auto& vs = kv.second;
    for (size_t i = 0; i + 1 < vs.size(); ++i) {
      auto wa = writer_of.find(vs[i]);
      auto wb = writer_of.find(vs[i + 1]);
      if (wa != writer_of.end() && wb != writer_of.end() &&
          wa->second != wb->second)
        ww.insert({wa->second, wb->second});
    }
  }
  for (size_t i = 0; i < reads.size(); ++i) {
    if (!compatible[i]) continue;
    const Read& r = reads[i];
    const auto& ref = order[r.key];
    if (!r.vs.empty()) {
      auto w = writer_of.find(r.vs.back());
      if (w != writer_of.end() && w->second != r.t)
        wr.insert({w->second, r.t});
    }
    if (r.vs.size() < ref.size()) {
      auto w = writer_of.find(ref[r.vs.size()]);
      if (w != writer_of.end() && w->second != r.t)
        rw.insert({r.t, w->second});
    }
  }

  std::vector<int32_t> edges;
  edges.reserve((ww.size() + wr.size() + rw.size()) * 3);
  auto emit = [&](const std::set<std::pair<int, int>>& es, int32_t et) {
    for (const auto& e : es) {
      edges.push_back(et);
      edges.push_back(e.first);
      edges.push_back(e.second);
    }
  };
  emit(ww, 0);
  emit(wr, 1);
  emit(rw, 2);

  res->edges = copy_i32(edges);
  res->n_edges = static_cast<int64_t>(edges.size() / 3);
  res->txn_index = copy_i64(txn_index);
  res->n_txns = n;
  std::vector<int32_t> va(g1a.begin(), g1a.end());
  std::vector<int32_t> vb(g1b.begin(), g1b.end());
  std::vector<long long> vk(bad_keys.begin(), bad_keys.end());
  res->g1a = copy_i32(va);
  res->n_g1a = static_cast<int32_t>(va.size());
  res->g1b = copy_i32(vb);
  res->n_g1b = static_cast<int32_t>(vb.size());
  res->bad_keys = copy_i64(vk);
  res->n_bad_keys = static_cast<int32_t>(vk.size());
  // allocation failure: a nullptr array with a positive count would make
  // the Python binding walk a NULL pointer (segfault) instead of taking
  // its None-fallback; flag the result as errored so the binding falls
  // back to the pure-Python path (advisor r5)
  if ((res->n_edges && !res->edges) || (res->n_txns && !res->txn_index) ||
      (res->n_g1a && !res->g1a) || (res->n_g1b && !res->g1b) ||
      (res->n_bad_keys && !res->bad_keys)) {
    res->err = ERR_IO;
    res->n_edges = res->n_txns = 0;
    res->n_g1a = res->n_g1b = res->n_bad_keys = 0;
  }
  return res;
}

void jt_elle_free(JtElleResult* r) {
  if (!r) return;
  std::free(r->edges);
  std::free(r->txn_index);
  std::free(r->g1a);
  std::free(r->g1b);
  std::free(r->bad_keys);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Elle micro-op cells: history.jsonl -> the [M, 8] int32 cell matrix of
// checkers/elle.py::elle_mops_for — the packed substrate of the DEVICE-
// side edge inference (the inference itself no longer runs on the host;
// this pass only parses, filters, and densifies).  Bit-identical to the
// Python twin (differential contract in tests/test_fastpack.py): cells
// emit in history order, key/value ids assign in first-encounter order,
// and the same degeneracy conditions are flagged.  Non-int keys cannot
// map onto this twin's tables -> ERR_PARSE, binding falls back.
// ---------------------------------------------------------------------------

typedef struct {
  int32_t* cells;      // n_cells * 8: txn kind key val rpos rid alast process
  int64_t n_cells;
  int64_t* txn_index;  // history position per committed txn
  int32_t n_txns;
  int64_t* keys;       // dense key id -> original key
  int32_t n_keys;
  int32_t degenerate;  // history needs host inference (see elle_mops_for)
  int32_t err;         // Err enum; non-zero => arrays are NULL
  int64_t err_line;
} JtElleMopsResult;

JtElleMopsResult* jt_elle_mops_file(const char* path) {
  auto* res = static_cast<JtElleMopsResult*>(
      std::calloc(1, sizeof(JtElleMopsResult)));
  if (!res) return nullptr;

  {
    JtcView v;
    int r = jtc_load(path, &v);
    if (r == 2) {
      res->err = ERR_JTC;
      return res;
    }
    if (r == 1) {
      const JtcSec* cells = v.find(3 /* SEC_EMOPS */);
      const JtcSec* txn = v.find(4 /* SEC_EMOPS_TXN */);
      const JtcSec* keys = v.find(5 /* SEC_EMOPS_KEYS */);
      if (cells && txn && keys && cells->dtype == 0 && cells->cols == 8 &&
          txn->dtype == 1 && keys->dtype == 1 &&
          txn->flags == txn->rows /* binding walks n_txns entries */) {
        if (!jtc_copy_i32(v, *cells, &res->cells) ||
            !jtc_copy_i64(v, *txn, &res->txn_index) ||
            !jtc_copy_i64(v, *keys, &res->keys)) {
          std::free(res->cells);
          std::free(res->txn_index);
          std::free(res->keys);
          res->cells = nullptr;
          res->txn_index = nullptr;
          res->keys = nullptr;
          res->err = ERR_IO;
          return res;
        }
        res->n_cells = static_cast<int64_t>(cells->rows);
        res->n_txns = static_cast<int32_t>(txn->flags);  // true n_txns
        res->n_keys = static_cast<int32_t>(keys->rows);
        res->degenerate = (cells->flags & 1) ? 1 : 0;
        return res;
      }
      // elle sections absent (e.g. a queue-family .jtc): parse normally
    }
  }

  constexpr long long kMaxCells = 46000;  // _MOPS_MAX_CELLS (sort-key cap)
  std::vector<int32_t> cells;
  cells.reserve(1 << 14);
  std::vector<long long> txn_index;
  std::vector<long long> keys;
  std::unordered_map<long long, int> key_id;
  std::unordered_map<long long, int> val_id;
  std::unordered_set<long long> writer_seen;
  std::unordered_map<long long, long long> read_key_of;
  bool degenerate = false;
  int rid = 0;
  int t = 0;

  auto kid = [&](long long k) -> int {
    auto it = key_id.find(k);
    if (it != key_id.end()) return it->second;
    int i = static_cast<int>(keys.size());
    key_id.emplace(k, i);
    keys.push_back(k);
    return i;
  };
  auto vid = [&](long long v) -> int {
    auto it = val_id.find(v);
    if (it != val_id.end()) return it->second;
    int i = static_cast<int>(val_id.size());
    val_id.emplace(v, i);
    return i;
  };
  auto clamp32 = [](long long v) -> int32_t {
    if (v > INT32_MAX) return INT32_MAX;
    if (v < INT32_MIN) return INT32_MIN;
    return static_cast<int32_t>(v);
  };
  auto emit = [&](int32_t txn, int32_t kind, int32_t key, int32_t val,
                  int32_t rpos, int32_t rd, int32_t alast, int32_t proc) {
    cells.push_back(txn);
    cells.push_back(kind);
    cells.push_back(key);
    cells.push_back(val);
    cells.push_back(rpos);
    cells.push_back(rd);
    cells.push_back(alast);
    cells.push_back(proc);
  };

  // micro-op validity mirrors _txn_micro_ops + the len==3/isinstance
  // guards: non-list elements and wrong-arity entries are skipped
  auto valid_append = [](const JNode& m) {
    return m.k == JNode::LIST && m.items.size() == 3 &&
           m.items[0].is_str("append", 6) && m.items[2].k == JNode::INT;
  };
  auto valid_read = [](const JNode& m) {
    return m.k == JNode::LIST && m.items.size() == 3 &&
           m.items[0].is_str("r", 1) && m.items[2].k == JNode::LIST;
  };

  int64_t err_line = 0;
  int err = for_each_op(
      path,
      [&](const OpView& op, long long pos) -> bool {
        if (op.f != 8 /* txn */ || op.type == 0 /* invoke */) return true;
        int32_t proc = clamp32(op.process);
        if (op.type == 2 /* fail */) {
          if (op.value.k != JNode::LIST) return true;
          for (const JNode& m : op.value.items)
            if (valid_append(m)) {
              // key deliberately NOT interned (the Python twin never
              // hashes a failed append's key); column holds 0
              emit(-1, 3, 0, vid(m.items[2].i), -1, -1, 0, proc);
            }
          return true;
        }
        if (op.type != 1 /* ok */) return true;  // info: nothing
        txn_index.push_back(pos);
        if (op.value.k == JNode::LIST) {
          // last-append micro-op index per key within this txn
          std::unordered_map<long long, size_t> last_app;
          for (size_t i = 0; i < op.value.items.size(); ++i) {
            const JNode& m = op.value.items[i];
            if (valid_append(m)) {
              if (m.items[1].k != JNode::INT) return false;  // non-int key
              last_app[m.items[1].i] = i;
            }
          }
          for (size_t i = 0; i < op.value.items.size(); ++i) {
            const JNode& m = op.value.items[i];
            if (valid_append(m)) {
              long long v = m.items[2].i;
              if (!writer_seen.insert(v).second) degenerate = true;
              emit(t, 0, kid(m.items[1].i), vid(v), -1, -1,
                   last_app[m.items[1].i] == i ? 1 : 0, proc);
            } else if (valid_read(m)) {
              if (m.items[1].k != JNode::INT) return false;  // non-int key
              long long k = m.items[1].i;
              int kd = kid(k);
              std::vector<long long> vs;
              for (const JNode& e : m.items[2].items)
                if (e.k == JNode::INT) vs.push_back(e.i);
              if (vs.empty()) {
                emit(t, 2, kd, -1, -1, rid, 0, proc);
              } else {
                std::unordered_set<long long> in_read;
                for (size_t j = 0; j < vs.size(); ++j) {
                  if (!in_read.insert(vs[j]).second) degenerate = true;
                  auto ins = read_key_of.emplace(vs[j], k);
                  if (!ins.second && ins.first->second != k)
                    degenerate = true;
                  emit(t, 1, kd, vid(vs[j]), static_cast<int32_t>(j), rid,
                       0, proc);
                }
              }
              ++rid;
            }
          }
        }
        ++t;
        return true;
      },
      &err_line);
  if (err != OK) {
    res->err = err;
    res->err_line = err_line;
    return res;
  }
  if (static_cast<long long>(cells.size() / 8) > kMaxCells)
    degenerate = true;

  res->cells = copy_i32(cells);
  res->n_cells = static_cast<int64_t>(cells.size() / 8);
  res->txn_index = copy_i64(txn_index);
  res->n_txns = t;
  res->keys = copy_i64(keys);
  res->n_keys = static_cast<int32_t>(keys.size());
  res->degenerate = degenerate ? 1 : 0;
  if ((res->n_cells && !res->cells) || (res->n_txns && !res->txn_index) ||
      (res->n_keys && !res->keys)) {  // malloc failure: see jt_elle note
    res->err = ERR_IO;
    res->n_cells = 0;
    res->n_txns = res->n_keys = 0;
  }
  return res;
}

void jt_elle_mops_free(JtElleMopsResult* r) {
  if (!r) return;
  std::free(r->cells);
  std::free(r->txn_index);
  std::free(r->keys);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Stream: history.jsonl -> the [n, 6] column matrix + full-read flag of
// checkers/stream_lin.py::_stream_rows (type, f, value, offset, pos,
// first) — the host explosion ahead of pack_stream_histories.  Same
// differential/fallback contract as the elle path.
// ---------------------------------------------------------------------------

typedef struct {
  int32_t* cols;  // n_rows * 6
  int64_t n_rows;
  int32_t full_read;
  int32_t err;
  int64_t err_line;
} JtStreamResult;

JtStreamResult* jt_stream_rows_file(const char* path) {
  auto* res =
      static_cast<JtStreamResult*>(std::calloc(1, sizeof(JtStreamResult)));
  if (!res) return nullptr;

  {
    JtcView v;
    int r = jtc_load(path, &v);
    if (r == 2) {
      res->err = ERR_JTC;
      return res;
    }
    if (r == 1) {
      const JtcSec* s = v.find(2 /* SEC_STREAM */);
      if (s && s->dtype == 0 && s->cols == 6) {
        if (!jtc_copy_i32(v, *s, &res->cols)) {
          res->err = ERR_IO;
          return res;
        }
        res->n_rows = static_cast<int64_t>(s->rows);
        res->full_read = (s->flags & 1) ? 1 : 0;
        return res;
      }
      // stream section absent (non-stream .jtc): parse normally
    }
  }

  std::vector<int32_t> cols;
  cols.reserve(1 << 14);
  bool full = false;
  bool range_bad = false;
  std::unordered_set<long long> full_pending;

  auto push = [&](int type, int f, long long v, long long o, long long pos,
                  int first) {
    // the Python twin materializes np.int32 — out-of-range values would
    // wrap there only via astype, but _stream_rows builds from raw ints
    // and np.asarray(np.int32) raises: treat as unmappable -> fallback
    if (v > INT32_MAX || v < INT32_MIN || o > INT32_MAX || o < INT32_MIN ||
        pos > INT32_MAX) {
      range_bad = true;
      return;
    }
    cols.push_back(type);
    cols.push_back(f);
    cols.push_back(static_cast<int32_t>(v));
    cols.push_back(static_cast<int32_t>(o));
    cols.push_back(static_cast<int32_t>(pos));
    cols.push_back(first);
  };

  auto is_pair = [](const JNode& x) {
    return x.k == JNode::LIST && x.items.size() == 2 &&
           x.items[0].k == JNode::INT && x.items[1].k == JNode::INT;
  };

  int64_t err_line = 0;
  int err = for_each_op(
      path,
      [&](const OpView& op, long long pos) -> bool {
        if (op.f == 6 /* append */) {
          long long v =
              op.value.k == JNode::INT ? op.value.i : NO_VALUE;
          push(op.type, op.f, v, -1, pos, 1);
        } else if (op.f == 7 /* read */) {
          if (op.type == 0 /* invoke */) {
            full_pending.erase(op.process);
            if (op.value.is_str("full", 4)) full_pending.insert(op.process);
            push(op.type, op.f, NO_VALUE, -1, pos, 1);
          } else {
            if (op.type == 1 /* ok */ && full_pending.count(op.process))
              full = true;
            full_pending.erase(op.process);
            // read_pairs: a single [o, v] pair, or a list of pairs
            // (non-pair elements skipped), or nothing
            std::vector<std::pair<long long, long long>> pairs;
            if (is_pair(op.value)) {
              pairs.push_back({op.value.items[0].i, op.value.items[1].i});
            } else if (op.value.k == JNode::LIST) {
              for (const JNode& p : op.value.items)
                if (is_pair(p))
                  pairs.push_back({p.items[0].i, p.items[1].i});
            }
            if (pairs.empty()) push(op.type, op.f, NO_VALUE, -1, pos, 1);
            int first = 1;
            for (const auto& p : pairs) {
              push(op.type, op.f, p.second, p.first, pos, first);
              first = 0;
            }
          }
        }
        return !range_bad;
      },
      &err_line);
  if (err != OK) {
    res->err = err;
    res->err_line = err_line;
    return res;
  }
  if (cols.empty()) {
    // sentinel row: (INVOKE, LOG, NO_VALUE, -1, 0, 1)
    push(0, 5, NO_VALUE, -1, 0, 1);
  }
  res->cols = copy_i32(cols);
  res->n_rows = static_cast<int64_t>(cols.size() / 6);
  res->full_read = full ? 1 : 0;
  if (res->n_rows && !res->cols) {  // malloc failure: see jt_elle note
    res->err = ERR_IO;
    res->n_rows = 0;
  }
  return res;
}

void jt_stream_free(JtStreamResult* r) {
  if (!r) return;
  std::free(r->cols);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Mutex WGL cells: history.jsonl -> the [n, 8] cell matrix of
// checkers/wgl_pcomp.py::wgl_cells_for (f01, process, token, type, inv,
// ret, key, 0) — one row per OK/INFO acquire/release completion with
// its interval, fencing token, and lock key.  The host substrate of the
// P-compositional mutex search; served zero-parse from a stat-fresh
// .jtc SEC_WGL block (kind 6) like the other families.  Same
// differential/fallback contract as the elle/stream paths.
// ---------------------------------------------------------------------------

typedef struct {
  int32_t* cells;  // n_rows * 8
  int64_t n_rows;
  int32_t err;
  int64_t err_line;
} JtWglResult;

JtWglResult* jt_wgl_cells_file(const char* path) {
  auto* res = static_cast<JtWglResult*>(std::calloc(1, sizeof(JtWglResult)));
  if (!res) return nullptr;

  {
    JtcView v;
    int r = jtc_load(path, &v);
    if (r == 2) {
      res->err = ERR_JTC;
      return res;
    }
    if (r == 1) {
      const JtcSec* s = v.find(6 /* SEC_WGL */);
      if (s && s->dtype == 0 && s->cols == 8) {
        if (!jtc_copy_i32(v, *s, &res->cells)) {
          res->err = ERR_IO;
          return res;
        }
        res->n_rows = static_cast<int64_t>(s->rows);
        return res;
      }
      // wgl section absent (non-mutex .jtc, or one written before this
      // section existed): parse normally
    }
  }

  std::vector<int32_t> cells;
  cells.reserve(1 << 12);
  std::unordered_map<long long, long long> open_inv;
  bool range_bad = false;

  auto push = [&](long long f01, long long proc, long long token,
                  long long typ, long long inv, long long ret,
                  long long key) {
    const long long vals[8] = {f01, proc, token, typ, inv, ret, key, 0};
    for (long long v : vals)
      if (v > INT32_MAX || v < INT32_MIN) {
        range_bad = true;  // Python twin returns None (unrepresentable)
        return;
      }
    for (long long v : vals) cells.push_back(static_cast<int32_t>(v));
  };

  int64_t err_line = 0;
  int err = for_each_op(
      path,
      [&](const OpView& op, long long pos) -> bool {
        if (op.f != 9 /* acquire */ && op.f != 10 /* release */)
          return true;
        if (op.type == 0 /* invoke */) {
          open_inv[op.process] = pos;
          return true;
        }
        long long inv = -1;
        auto it = open_inv.find(op.process);
        if (it != open_inv.end()) {
          inv = it->second;
          open_inv.erase(it);
        }
        if (op.type != 1 /* ok */ && op.type != 3 /* info */) return true;
        // mutex_key_token twin: int -> token; [key] -> key; [key, token]
        long long key = 0, token = -1;
        const JNode& v = op.value;
        if (v.k == JNode::INT) {
          token = v.i;
        } else if (v.k == JNode::LIST && v.items.size() == 1 &&
                   v.items[0].k == JNode::INT) {
          key = v.items[0].i;
        } else if (v.k == JNode::LIST && v.items.size() == 2 &&
                   v.items[0].k == JNode::INT &&
                   v.items[1].k == JNode::INT) {
          key = v.items[0].i;
          token = v.items[1].i;
        }
        push(op.f == 9 ? 0 : 1, op.process, token, op.type, inv, pos, key);
        return !range_bad;
      },
      &err_line);
  if (err != OK) {
    res->err = err;
    res->err_line = err_line;
    return res;
  }
  if (range_bad) {
    res->err = ERR_OVERFLOW;  // binding -> None -> Python twin decides
    return res;
  }
  res->cells = copy_i32(cells);
  res->n_rows = static_cast<int64_t>(cells.size() / 8);
  if (res->n_rows && !res->cells) {  // malloc failure: see jt_elle note
    res->err = ERR_IO;
    res->n_rows = 0;
  }
  return res;
}

void jt_wgl_cells_free(JtWglResult* r) {
  if (!r) return;
  std::free(r->cells);
  std::free(r);
}

// ---------------------------------------------------------------------------
// Thread-pool multi-file packing (the pipeline executor's host stage):
// K history shards packed concurrently, one result slot per input path in
// a preallocated arena (the returned pointer array).  Workers claim paths
// off an atomic cursor and run the existing single-file entry points, so
// the per-file semantics (and their differential contracts) are shared
// byte-for-byte with the serial path.  The ctypes caller holds the GIL
// released for the whole batch, which is what buys real host/device
// overlap on the Python side.  Elements are freed with the per-kind
// jt_*_free; the arena itself with jt_files_free.  A slot is NULL only
// when its result allocation itself failed (caller falls back per-file).
// ---------------------------------------------------------------------------

}  // extern "C"

namespace {

// Cursor partitioning (the scale-out input lanes): a caller owning lane
// `part` of `n_parts` claims only indices i with i % n_parts == part, so
// N concurrent lane/process callers can stride ONE shared path array
// with no shared atomic cursor between them — each call's cursor walks
// its own residue class.  part=0/n_parts=1 is the classic full scan.
template <typename R, R* (*ONE)(const char*)>
void** pack_files_pool(const char* const* paths, int32_t n,
                       int32_t threads, int32_t part, int32_t n_parts) {
  if (n < 0 || n_parts <= 0 || part < 0 || part >= n_parts) return nullptr;
  auto** out = static_cast<void**>(std::calloc(
      static_cast<size_t>(n) + 1, sizeof(void*)));
  if (!out) return nullptr;
  // stripe size: indices part, part+n_parts, ... below n
  int32_t n_mine = n > part ? (n - part + n_parts - 1) / n_parts : 0;
  if (n_mine == 0) return out;
  int32_t hw = static_cast<int32_t>(std::thread::hardware_concurrency());
  if (threads <= 0) threads = hw > 0 ? hw : 2;
  if (threads > n_mine) threads = n_mine;
  if (threads <= 1) {
    for (int32_t k = 0; k < n_mine; ++k)
      out[part + k * n_parts] = ONE(paths[part + k * n_parts]);
    return out;
  }
  std::atomic<int32_t> cursor{0};
  auto worker = [&]() {
    while (true) {
      int32_t k = cursor.fetch_add(1, std::memory_order_relaxed);
      if (k >= n_mine) return;
      int32_t i = part + k * n_parts;
      out[i] = ONE(paths[i]);
    }
  };
  std::vector<std::thread> pool;
  pool.reserve(static_cast<size_t>(threads));
  for (int32_t t = 0; t < threads; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  return out;
}

}  // namespace

extern "C" {

JtPackResult** jt_pack_files(const char* const* paths, int32_t n,
                             int32_t threads) {
  return reinterpret_cast<JtPackResult**>(
      pack_files_pool<JtPackResult, jt_pack_file>(paths, n, threads, 0, 1));
}

JtStreamResult** jt_stream_rows_files(const char* const* paths, int32_t n,
                                      int32_t threads) {
  return reinterpret_cast<JtStreamResult**>(
      pack_files_pool<JtStreamResult, jt_stream_rows_file>(
          paths, n, threads, 0, 1));
}

JtElleMopsResult** jt_elle_mops_files(const char* const* paths, int32_t n,
                                      int32_t threads) {
  return reinterpret_cast<JtElleMopsResult**>(
      pack_files_pool<JtElleMopsResult, jt_elle_mops_file>(
          paths, n, threads, 0, 1));
}

JtWglResult** jt_wgl_cells_files(const char* const* paths, int32_t n,
                                 int32_t threads) {
  return reinterpret_cast<JtWglResult**>(
      pack_files_pool<JtWglResult, jt_wgl_cells_file>(paths, n, threads,
                                                      0, 1));
}

// Striped variants (per-device input lanes / per-process file ranges):
// pack only indices i ≡ part (mod n_parts) of the SHARED path array;
// slots outside the stripe stay NULL in the returned arena.
JtPackResult** jt_pack_files_part(const char* const* paths, int32_t n,
                                  int32_t threads, int32_t part,
                                  int32_t n_parts) {
  return reinterpret_cast<JtPackResult**>(
      pack_files_pool<JtPackResult, jt_pack_file>(
          paths, n, threads, part, n_parts));
}

JtStreamResult** jt_stream_rows_files_part(const char* const* paths,
                                           int32_t n, int32_t threads,
                                           int32_t part, int32_t n_parts) {
  return reinterpret_cast<JtStreamResult**>(
      pack_files_pool<JtStreamResult, jt_stream_rows_file>(
          paths, n, threads, part, n_parts));
}

JtElleMopsResult** jt_elle_mops_files_part(const char* const* paths,
                                           int32_t n, int32_t threads,
                                           int32_t part, int32_t n_parts) {
  return reinterpret_cast<JtElleMopsResult**>(
      pack_files_pool<JtElleMopsResult, jt_elle_mops_file>(
          paths, n, threads, part, n_parts));
}

// frees only the pointer arena — elements are freed by jt_*_free
void jt_files_free(void** arr) { std::free(arr); }

// process-wide .jtc fast-path toggle (see g_jtc_disabled): non-zero
// disables substrate serving so the next calls genuinely parse.  The
// Python binding sets it around no-cache batch calls and restores it.
void jt_jtc_disable(int32_t disabled) {
  g_jtc_disabled.store(disabled ? 1 : 0, std::memory_order_relaxed);
}

}  // extern "C"
