// AMQP 0-9-1 wire codec: frames, methods, field tables.
//
// TPU-native twin of the reference's Java driver layer
// (/root/reference/rabbitmq/src/main/java/com/rabbitmq/jepsen/Utils.java,
// which delegates framing to com.rabbitmq:amqp-client 5.34.0).  Here the
// protocol subset the jepsen workload needs is implemented directly:
// connection/channel handshake, queue declare/purge with argument tables
// (x-queue-type=quorum etc.), publisher confirms, basic publish/get/consume/
// ack/reject/nack, mandatory-return, and heartbeats.

#pragma once

#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <vector>

namespace amqp {

// ---- frame types ----------------------------------------------------------
constexpr uint8_t FRAME_METHOD = 1;
constexpr uint8_t FRAME_HEADER = 2;
constexpr uint8_t FRAME_BODY = 3;
constexpr uint8_t FRAME_HEARTBEAT = 8;
constexpr uint8_t FRAME_END = 0xCE;

// ---- class / method ids ---------------------------------------------------
constexpr uint16_t CLS_CONNECTION = 10;
constexpr uint16_t M_CONN_START = 10, M_CONN_START_OK = 11, M_CONN_TUNE = 30,
                   M_CONN_TUNE_OK = 31, M_CONN_OPEN = 40, M_CONN_OPEN_OK = 41,
                   M_CONN_CLOSE = 50, M_CONN_CLOSE_OK = 51;
constexpr uint16_t CLS_CHANNEL = 20;
constexpr uint16_t M_CH_OPEN = 10, M_CH_OPEN_OK = 11, M_CH_CLOSE = 40,
                   M_CH_CLOSE_OK = 41;
constexpr uint16_t CLS_QUEUE = 50;
constexpr uint16_t M_Q_DECLARE = 10, M_Q_DECLARE_OK = 11, M_Q_PURGE = 30,
                   M_Q_PURGE_OK = 31, M_Q_DELETE = 40, M_Q_DELETE_OK = 41;
constexpr uint16_t CLS_BASIC = 60;
constexpr uint16_t M_B_QOS = 10, M_B_QOS_OK = 11, M_B_CONSUME = 20,
                   M_B_CONSUME_OK = 21, M_B_CANCEL = 30, M_B_CANCEL_OK = 31,
                   M_B_PUBLISH = 40, M_B_RETURN = 50,
                   M_B_DELIVER = 60, M_B_GET = 70, M_B_GET_OK = 71,
                   M_B_GET_EMPTY = 72, M_B_ACK = 80, M_B_REJECT = 90,
                   M_B_NACK = 120;
constexpr uint16_t CLS_CONFIRM = 85;
constexpr uint16_t M_CF_SELECT = 10, M_CF_SELECT_OK = 11;
constexpr uint16_t CLS_TX = 90;
constexpr uint16_t M_TX_SELECT = 10, M_TX_SELECT_OK = 11, M_TX_COMMIT = 20,
                   M_TX_COMMIT_OK = 21, M_TX_ROLLBACK = 30,
                   M_TX_ROLLBACK_OK = 31;

// ---- buffer writer --------------------------------------------------------
struct Writer {
  std::vector<uint8_t> buf;
  void u8(uint8_t v) { buf.push_back(v); }
  void u16(uint16_t v) {
    buf.push_back(v >> 8);
    buf.push_back(v & 0xFF);
  }
  void u32(uint32_t v) {
    for (int i = 3; i >= 0; --i) buf.push_back((v >> (8 * i)) & 0xFF);
  }
  void u64(uint64_t v) {
    for (int i = 7; i >= 0; --i) buf.push_back((v >> (8 * i)) & 0xFF);
  }
  void bytes(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), b, b + n);
  }
  void shortstr(const std::string& s) {
    if (s.size() > 255) throw std::runtime_error("shortstr too long");
    u8(static_cast<uint8_t>(s.size()));
    bytes(s.data(), s.size());
  }
  void longstr(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    bytes(s.data(), s.size());
  }
};

// ---- field table ----------------------------------------------------------
struct Table {
  Writer w;  // entries only; serialized with a length prefix
  Table& put_str(const std::string& k, const std::string& v) {
    w.shortstr(k);
    w.u8('S');
    w.longstr(v);
    return *this;
  }
  Table& put_int(const std::string& k, int32_t v) {
    w.shortstr(k);
    w.u8('I');
    w.u32(static_cast<uint32_t>(v));
    return *this;
  }
  Table& put_bool(const std::string& k, bool v) {
    w.shortstr(k);
    w.u8('t');
    w.u8(v ? 1 : 0);
    return *this;
  }
  Table& put_long(const std::string& k, int64_t v) {
    w.shortstr(k);
    w.u8('l');
    w.u64(static_cast<uint64_t>(v));
    return *this;
  }
  void serialize(Writer& out) const {
    out.u32(static_cast<uint32_t>(w.buf.size()));
    out.bytes(w.buf.data(), w.buf.size());
  }
};

// ---- buffer reader --------------------------------------------------------
struct Reader {
  const uint8_t* p;
  size_t n;
  size_t off = 0;
  Reader(const uint8_t* p_, size_t n_) : p(p_), n(n_) {}
  void need(size_t k) const {
    if (off + k > n) throw std::runtime_error("frame underflow");
  }
  uint8_t u8() {
    need(1);
    return p[off++];
  }
  uint16_t u16() {
    need(2);
    uint16_t v = (uint16_t(p[off]) << 8) | p[off + 1];
    off += 2;
    return v;
  }
  uint32_t u32() {
    need(4);
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) v = (v << 8) | p[off + i];
    off += 4;
    return v;
  }
  uint64_t u64() {
    need(8);
    uint64_t v = 0;
    for (int i = 0; i < 8; ++i) v = (v << 8) | p[off + i];
    off += 8;
    return v;
  }
  std::string shortstr() {
    uint8_t k = u8();
    need(k);
    std::string s(reinterpret_cast<const char*>(p + off), k);
    off += k;
    return s;
  }
  std::string longstr() {
    uint32_t k = u32();
    need(k);
    std::string s(reinterpret_cast<const char*>(p + off), k);
    off += k;
    return s;
  }
  void skip_table() {
    uint32_t k = u32();
    need(k);
    off += k;
  }
};

// ---- frame ----------------------------------------------------------------
struct Frame {
  uint8_t type = 0;
  uint16_t channel = 0;
  std::vector<uint8_t> payload;
};

inline void serialize_frame(Writer& w, uint8_t type, uint16_t channel,
                            const std::vector<uint8_t>& payload) {
  w.u8(type);
  w.u16(channel);
  w.u32(static_cast<uint32_t>(payload.size()));
  w.bytes(payload.data(), payload.size());
  w.u8(FRAME_END);
}

// method payload prefix
inline Writer method_writer(uint16_t cls, uint16_t mth) {
  Writer w;
  w.u16(cls);
  w.u16(mth);
  return w;
}

// content header for basic publish: persistent delivery mode
inline std::vector<uint8_t> content_header(uint64_t body_size) {
  Writer w;
  w.u16(CLS_BASIC);
  w.u16(0);           // weight
  w.u64(body_size);   // body size
  w.u16(0x1000);      // property flags: delivery-mode present
  w.u8(2);            // delivery-mode = persistent
  return w.buf;
}

// skip one field-table value by its type octet (RabbitMQ's field grammar)
inline void skip_field_value(Reader& r, uint8_t type) {
  switch (type) {
    case 't': case 'b': case 'B': r.u8(); break;
    case 's': case 'u': r.u16(); break;
    case 'I': case 'i': case 'f': r.u32(); break;
    case 'l': case 'd': case 'T': r.u64(); break;
    case 'D': r.u8(); r.u32(); break;
    case 'S': case 'x': r.longstr(); break;
    case 'F': case 'A': r.skip_table(); break;
    case 'V': break;
    default: throw std::runtime_error("unknown table field type");
  }
}

// Parse a basic content header and return the integer value of the named
// message header, or -1 when absent/unparseable.
inline int64_t header_i64(const std::vector<uint8_t>& payload,
                          const char* name) {
  try {
    Reader r(payload.data(), payload.size());
    r.u16();  // class
    r.u16();  // weight
    r.u64();  // body size
    uint16_t flags = r.u16();
    if (flags & 0x8000) r.shortstr();  // content-type
    if (flags & 0x4000) r.shortstr();  // content-encoding
    if (!(flags & 0x2000)) return -1;  // no headers table
    uint32_t len = r.u32();
    size_t end = r.off + len;
    while (r.off < end) {
      std::string key = r.shortstr();
      uint8_t type = r.u8();
      if (key == name && (type == 'l' || type == 'T'))
        return static_cast<int64_t>(r.u64());
      if (key == name && (type == 'I' || type == 'i'))
        return static_cast<int64_t>(static_cast<int32_t>(r.u32()));
      skip_field_value(r, type);
    }
  } catch (const std::exception&) {
    return -1;
  }
  return -1;
}

// The `x-stream-offset` message header (RabbitMQ streams deliver each
// record's log offset this way over AMQP 0-9-1), or -1 when absent.
inline int64_t header_stream_offset(const std::vector<uint8_t>& payload) {
  return header_i64(payload, "x-stream-offset");
}

}  // namespace amqp
